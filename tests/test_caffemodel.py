"""Binary .caffemodel import/export — parity with reference
`libs/CaffeNet.scala:152-165` (CopyTrainedLayersFrom / saveWeightsToFile)
and the save->load roundtrip test `CaffeNetSpec.scala:72-82`."""
import numpy as np
import pytest

from sparknet_tpu.model.caffemodel import (load_caffemodel,
                                           load_caffemodel_file,
                                           save_caffemodel, _len_delim,
                                           _varint, _tag)
from sparknet_tpu.model.weights import WeightCollection
from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.zoo import cifar10_quick

BATCH = 4


def test_roundtrip_bit_identical(tmp_path):
    """save -> load preserves every blob exactly (CaffeNetSpec.scala:72-82)."""
    net = JaxNet(cifar10_quick(batch=BATCH), seed=3)
    p = str(tmp_path / "w.caffemodel")
    net.save_weights(p)
    loaded = load_caffemodel_file(p)
    assert WeightCollection.check_equal(net.get_weights(), loaded, tol=0.0)


def test_import_into_net_and_forward(tmp_path, rng):
    """A .caffemodel written elsewhere imports into cifar10_quick and the
    net forwards with those exact weights (copyTrainedLayersFrom parity)."""
    donor = JaxNet(cifar10_quick(batch=BATCH), seed=7)
    p = str(tmp_path / "donor.caffemodel")
    donor.save_weights(p)

    net = JaxNet(cifar10_quick(batch=BATCH), seed=0)
    assert not WeightCollection.check_equal(net.get_weights(),
                                            donor.get_weights())
    net.load_weights(p)
    assert WeightCollection.check_equal(net.get_weights(),
                                        donor.get_weights(), tol=0.0)
    batch = {"data": rng.standard_normal((BATCH, 3, 32, 32)).astype(np.float32),
             "label": rng.integers(0, 10, (BATCH, 1)).astype(np.int32)}
    a = donor.forward(batch)["prob"]
    b = net.forward(batch)["prob"]
    np.testing.assert_array_equal(a, b)


def _legacy_blob(arr: np.ndarray, dims4) -> bytes:
    """BlobProto with LEGACY num/channels/height/width fields (old Caffe)."""
    out = b""
    for field_no, d in zip((1, 2, 3, 4), dims4):
        out += _tag(field_no, 0) + _varint(int(d))
    out += _len_delim(5, arr.astype("<f4").tobytes())
    return out


def test_legacy_v1_layers_and_shapes():
    """Old-style NetParameter: `layers` field 2 (V1LayerParameter, name=4)
    with legacy 4-D blob dims — e.g. the original bvlc reference nets."""
    w = np.arange(24, dtype=np.float32).reshape(2, 3, 2, 2)
    b = np.array([0.5, -0.5], np.float32)
    layer = (_len_delim(4, b"conv1") + _tag(5, 0) + _varint(4) +
             _len_delim(6, _legacy_blob(w, (2, 3, 2, 2))) +
             _len_delim(6, _legacy_blob(b, (1, 1, 1, 2))))
    net_param = _len_delim(1, b"legacy") + _len_delim(2, layer)
    coll = load_caffemodel(net_param)
    np.testing.assert_array_equal(coll["conv1"][0], w)
    # legacy (1,1,1,2) bias canonicalizes to (2,) like Caffe's shape()
    np.testing.assert_array_equal(coll["conv1"][1], b)
    assert coll["conv1"][1].shape == (2,)


def test_not_a_caffemodel_fails_loudly():
    with pytest.raises(ValueError, match="caffemodel"):
        load_caffemodel(_len_delim(1, b"empty-net"))


def test_shape_value_mismatch_fails_loudly():
    bad_blob = (_len_delim(5, np.zeros(3, "<f4").tobytes()) +
                _len_delim(7, _len_delim(1, _varint(4))))  # claims 4
    layer = _len_delim(1, b"ip") + _len_delim(2, b"InnerProduct") + \
        _len_delim(7, bad_blob)
    with pytest.raises(ValueError, match="shape"):
        load_caffemodel(_len_delim(100, layer))


def test_legacy_num_output_one_blobs():
    """Legacy 4-D blobs with num=1 keep their shape (a (1,C,H,W) conv head
    must NOT collapse to 3-D); only pure vectors (1,1,1,N) canonicalize
    (r2 review finding)."""
    w = np.arange(12, dtype=np.float32).reshape(1, 3, 2, 2)
    layer = (_len_delim(4, b"head") + _tag(5, 0) + _varint(4) +
             _len_delim(6, _legacy_blob(w, (1, 3, 2, 2))))
    coll = load_caffemodel(_len_delim(1, b"n") + _len_delim(2, layer))
    assert coll["head"][0].shape == (1, 3, 2, 2)
    # legacy IP weight (1,1,out,in) feeds collection_to_params as 4-D
    from sparknet_tpu.model.caffe_compat import collection_to_params
    from sparknet_tpu.model.net import CompiledNet
    from sparknet_tpu.model.spec import (InnerProductParam, InputSpec,
                                         LayerSpec, NetSpec)
    spec = NetSpec(name="t", inputs=(InputSpec("data", (2, 5)),), layers=(
        LayerSpec(name="ip", type="InnerProduct", bottoms=("data",),
                  tops=("ip",),
                  inner_product=InnerProductParam(num_output=3)),))
    net = CompiledNet.compile(spec)
    wip = np.arange(15, dtype=np.float32).reshape(1, 1, 3, 5)
    params = collection_to_params(net, WeightCollection(
        {"ip": [wip, np.zeros(3, np.float32)]}, ["ip"]))
    assert params["ip"]["w"].shape == (5, 3)  # (out,in) -> (in,out)
    np.testing.assert_array_equal(np.asarray(params["ip"]["w"]),
                                  wip.reshape(3, 5).T)
