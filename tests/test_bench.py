"""Benchmark harness pieces: analytic FLOPs, MFU peak lookup, the shared
round-timing core, and the jax.profiler capture hook (SURVEY §5.1)."""
import glob
import json
import os

import numpy as np
import pytest

from sparknet_tpu import CompiledNet
from sparknet_tpu.utils import flops
from sparknet_tpu.zoo import caffenet, cifar10_quick


def test_caffenet_forward_flops_match_alexnet_ballpark():
    """CaffeNet == AlexNet: published conv+fc forward cost is ~1.4-1.5
    GFLOP/image (2x ~720M MACs). The analytic count must land there —
    a wrong blob-shape or group factor would be off by 2x or more."""
    net = CompiledNet.compile(caffenet(batch=1, crop=227, n_classes=1000))
    f = flops.forward_flops_per_image(net)
    assert 1.3e9 < f < 1.6e9, f
    assert flops.train_flops_per_image(net) == pytest.approx(3 * f)


def test_conv_flops_shape_math():
    """cifar10_quick conv1: 32x32 out, 5x5 kernel, 3->32 channels."""
    net = CompiledNet.compile(cifar10_quick(batch=1))
    f = flops.forward_flops_per_image(net)
    conv1 = 2 * 32 * 32 * 5 * 5 * 3 * 32
    assert f > conv1  # contains at least conv1 + the rest
    # recompute by hand over all conv/ip layers and compare exactly
    total = 0.0
    for layer in net.spec.layers:
        if layer.type == "Convolution":
            _, h, w, co = net.blob_shapes[layer.tops[0]]
            ci = net.blob_shapes[layer.bottoms[0]][-1]
            k, g = layer.conv.kernel_size, layer.conv.group
            total += 2 * h * w * k * k * (ci // g) * co
        elif layer.type == "InnerProduct":
            of = net.blob_shapes[layer.tops[0]][-1]
            inf = int(np.prod(net.blob_shapes[layer.bottoms[0]][1:]))
            total += 2 * inf * of
    assert f == pytest.approx(total)


def test_peak_lookup():
    assert flops.peak_bf16_flops("TPU v5 lite") == pytest.approx(197e12)
    assert flops.peak_bf16_flops("TPU v4") == pytest.approx(275e12)
    assert flops.peak_bf16_flops("cpu") == 0.0  # unknown -> omit MFU


def test_bench_round_timing_core():
    """bench._build/_device_batches/_time_rounds run the real trainer round
    on the test mesh and return a positive time."""
    import bench
    net, trainer, state = bench._build(2, 2, crop=35, n_classes=8,
                                       n_devices=2)
    batches = bench._device_batches(trainer, 2, 2, 35, 8)
    t = bench._time_rounds(trainer, state, batches, trials=1)
    assert t > 0


def test_checkpoint_stall_bench_core(tmp_path):
    """bench.checkpoint_stall runs the real two-stage pipeline against all
    three stores at a tiny state size and reports a sane shape: async
    blocking must come in UNDER sync for every store (the whole point),
    and the artifact rows cover the full store x mode matrix."""
    import bench
    rows = bench.checkpoint_stall(
        mb=2, saves=2, out_path=str(tmp_path / "BENCH_CKPT.json"))
    assert {(r["store"], r["mode"]) for r in rows} == {
        (s, m) for s in ("local", "gs", "s3") for m in ("sync", "async")}
    by = {(r["store"], r["mode"]): r["blocking_ms_per_save"] for r in rows}
    for store in ("local", "gs", "s3"):
        assert by[(store, "async")] < by[(store, "sync")], (store, by)
    assert json.load(open(tmp_path / "BENCH_CKPT.json"))["headline"][
        "metric"] == "checkpoint_blocking_stall_async_over_sync"


def test_serve_bench_smoke(tmp_path):
    """bench.serve_bench drives the REAL server through every load
    regime — in-process trickle/open/saturate, the OPEN-LOOP HTTP rows
    through the real data plane, and the hot-swap + replica-drain chaos
    arm — and writes a complete BENCH_SERVE artifact. The committed
    BENCH_SERVE.json pins the acceptance numbers; this smoke asserts the
    harness itself — rows present, counters sane, zero dropped/hung HTTP
    clients, jit cache steady — at CI-noise-tolerant thresholds."""
    import bench
    out = bench.serve_bench(out_path=str(tmp_path / "BENCH_SERVE.json"),
                            duration_s=0.4, max_batch=4,
                            http_rps=(200.0,),
                            keep=str(tmp_path / "keep"))
    rows = out["rows"]
    assert [r["load"] for r in rows] == [
        "trickle", "open_50rps", "open_200rps", "saturate",
        "http_open_200rps", "binary_open_200rps", "ab_small_http",
        "ab_small_binary", "transport_parity", "binary_stream_blob",
        "http_chaos_swap_drain"]
    for r in rows[:4]:
        assert r["requests_failed"] == 0
        assert r["requests_ok"] > 0
        assert r["p99_ms"] is not None
    assert rows[0]["batch_fill_ratio"] == 1.0  # closed-loop single client
    assert rows[3]["batch_fill_ratio"] > 0.5   # saturation batches up
    # trickle carries the wake-on-submit stamp (the pin itself is
    # test_serve's lone-request bound; here: the artifact records it)
    assert rows[0]["old_poll_quantum_ms"] == 50.0
    assert "p99_below_old_quantum" in rows[0]
    # the open-loop rows, both transports: every request answered, none
    # dropped, silently timed out, or hung
    for row in (rows[4], rows[5]):
        assert row["ok"] > 0
        assert row["dropped"] == 0 and row["hung_clients"] == 0
        assert row["timed_out"] == 0
        assert row["answered"] == row["ok"] + row["shed_429"] + \
            row["shed_503"] + row["errors_other"]
        assert row["errors_other"] == 0
    # the driver-cost A/B rows carry the accounting the headline gates on
    for row in (rows[6], rows[7]):
        assert row["requests"] > 0
        assert row["dropped"] == 0 and row["hung_clients"] == 0
        assert row["errors_other"] == 0
        assert row["cpu_s_per_1k"] is not None
    # identical tensors through both wires (same replica, same bucket)
    assert rows[8]["bitwise_equal"] is True
    # the streaming row: multi-MB blob, bounded per-connection buffering
    stream = rows[9]
    assert stream["blob_mb"] >= 2.0
    assert stream["buffer_bounded_by_chunk"] is True
    assert stream["first_byte_decoupled"] is True
    assert stream["bitwise_equal_stream_vs_full"] is True
    # chaos: mid-traffic swap + drain with zero dropped/corrupted
    chaos = rows[10]
    assert chaos["zero_dropped"] and chaos["swap_ok"]
    assert chaos["bad"] == 0
    art = json.load(open(tmp_path / "BENCH_SERVE.json"))
    assert art["headline"]["metric"] == "serve_saturated_batch_fill_ratio"
    assert art["headline"]["jit_cache_ok"] is True
    assert art["headline"]["http_zero_dropped"] is True
    assert art["headline"]["binary_zero_dropped"] is True
    assert art["headline"]["transport_parity_bitwise"] is True
    assert art["headline"]["transport_ab"]["ab_zero_dropped"] is True
    assert art["headline"]["stream"]["buffer_bounded_by_chunk"] is True
    assert art["headline"]["chaos_zero_dropped"] is True
    # the serve JSONL artifact landed for CI upload-on-failure
    assert (tmp_path / "keep" / "serve_bench.jsonl").exists()


def test_econ_bench_smoke(tmp_path):
    """bench.econ_bench runs the three r9 inference-economics levers
    through the REAL serving stack: quant-vs-f32 saturate + parity, the
    cold/warm subprocess replica against a shared persistent compile
    cache, and the pow2-vs-derived bucket-ladder A/B on a skewed trace.
    The committed BENCH_ECON.json pins the acceptance numbers; this
    smoke asserts the harness and its gates hold at CI scale."""
    import bench
    out = bench.econ_bench(out_path=str(tmp_path / "BENCH_ECON.json"),
                           duration_s=0.4, max_batch=8,
                           keep=str(tmp_path / "keep"))
    head = out["headline"]
    # quant parity: drift within the calibrated tolerance
    assert head["quant_parity_ok"] is True
    # the cold-start acceptance: a warm replica compiles NOTHING fresh
    assert head["coldstart_warm_zero_miss"] is True
    cold_s, warm_s = head["coldstart_cold_vs_warm_s"]
    assert cold_s > 0 and warm_s > 0
    # the ladder acceptance: derived beats pow2 on fill, jit cache pinned
    assert head["ladder_fill_improved"] is True
    assert head["jit_cache_ok"] is True
    assert head["ok"] is True
    rows = {r.get("arm", r.get("load")): r for r in out["rows"]}
    warm_stats = rows["coldstart"]["warm_compile_stats"]
    for what in ("net", "serve_bucket"):
        assert warm_stats.get(what, {}).get("cache_misses", 1) == 0, what
    lad = rows["ladder_ab"]
    # the deterministic half: optimal-by-construction on the observed
    # histogram, never worse than pow2
    assert lad["derived_fill_on_observed"] >= lad["pow2_fill_on_observed"]
    art = json.load(open(tmp_path / "BENCH_ECON.json"))
    assert art["headline"]["metric"] == "serve_econ_levers"
    assert (tmp_path / "keep" / "econ_bench.log").exists()


def test_obs_bench_smoke(tmp_path, monkeypatch):
    """bench.obs_bench runs the REAL train loop in both arms (telemetry
    on with status server + trace + scraper, and off) and writes a
    complete BENCH_OBS artifact. The committed BENCH_OBS.json pins the
    acceptance number (<= 2% overhead); this smoke asserts the harness —
    both arms ran, the artifact is stamped — without asserting the
    noise-sensitive ratio on a contended CI host."""
    import bench
    monkeypatch.setenv("SPARKNET_TPU_HOME", str(tmp_path))
    out_path = str(tmp_path / "BENCH_OBS.json")
    out = bench.obs_bench(out_path=out_path, rounds=6, warmup=2, reps=1)
    assert out["metric"] == "obs_full_telemetry_per_round_overhead"
    assert out["per_mode"]["off_ms"] > 0 and out["per_mode"]["on_ms"] > 0
    art = json.load(open(out_path))
    assert {r["telemetry"] for r in art["rows"]} == {"on", "off"}
    assert art["meta"]["jax_version"]  # run_metadata stamp


def test_mfu_bench_smoke(tmp_path):
    """bench.mfu_bench runs the REAL host-fed round through all four
    lever arms (dispatch-H2D baseline, prefetch placement, +donation,
    +Pallas layer path) and writes a complete BENCH_r06-style artifact.
    The acceptance number (MFU >= 0.55) is stamped by running this on the
    TPU pod; this smoke asserts the harness — arms present and ordered,
    the breakdown recorded, prefetch arms placing off the dispatch path,
    jit cache steady across arms, run_metadata stamped — on the CPU
    config."""
    import bench
    out_path = str(tmp_path / "BENCH_r06.json")
    out = bench.mfu_bench(out_path=out_path, small=True)
    rows = out["rows"]
    assert [r["arm"] for r in rows] == [
        "r5_baseline", "prefetch", "prefetch_donate",
        "prefetch_donate_pallas"]
    for r in rows:
        assert r["images_per_sec_per_chip"] > 0
        assert set(r["breakdown_ms"]) == {"data", "h2d", "dispatch"}
        # steady cache: pre-placement/donation caused no churn past the
        # two fast-path keys of the one executable (see
        # test_round_pipeline.test_overlapped_round_holds_steady_jit_cache)
        assert r["compiled_variants"] <= rows[0]["compiled_variants"]
    # prefetch arms place on the prep thread: the dispatch-side h2d phase
    # sees the passthrough, the baseline pays the real copy there
    assert rows[1]["breakdown_ms"]["h2d"] <= \
        rows[0]["breakdown_ms"]["h2d"] + 1.0
    # off-TPU the Pallas arm must actually run the kernels (interpreter,
    # lrn forced) — 'auto' would silently rerun the XLA arm
    import jax
    if jax.default_backend() != "tpu":
        assert rows[3]["ops_interpret"] and rows[3]["lrn_impl"] == "pallas"
    art = json.load(open(out_path))
    assert art["headline"]["metric"] == "caffenet_train_mfu_host_fed_round"
    assert set(art["headline"]["levers"]) == {r["arm"] for r in rows}
    assert art["meta"]["jax_version"]  # run_metadata stamp


def test_sharding_bench_smoke(tmp_path):
    """bench.sharding_bench runs the three r7 trainer arms and writes a
    complete BENCH_r07-style artifact. The deterministic claims are
    asserted here too (they do not depend on CPU timing): the ZeRO-1 arm
    cuts the per-device at-rest momentum bytes by >= (n_data-1)/n_data of
    the replicated arm's, params stay replicated in the momentum mode,
    and the stage-1 collect number is recorded per arm. The 2%-img/s
    acceptance is a committed-BENCH_r07 claim (timing on a shared-core
    CPU mesh is noise), not a tier-1 assertion."""
    import bench
    out_path = str(tmp_path / "BENCH_r07.json")
    out = bench.sharding_bench(out_path=out_path, trials=2, small=True)
    rows = out["rows"]
    assert [r["arm"] for r in rows] == [
        "r6_prefetch_donate", "named_replicated", "named_fused",
        "named_momentum"]
    by = {r["arm"]: r for r in rows}
    for r in rows:
        assert r["images_per_sec"] > 0
        assert r["collect_stage1_ms"] >= 0
    base = by["r6_prefetch_donate"]["per_device_state_bytes"]
    rep = by["named_replicated"]["per_device_state_bytes"]
    zm = by["named_momentum"]["per_device_state_bytes"]
    assert rep == base  # logical replicated == replica layout, byte for byte
    assert zm["params"] == base["params"]
    n = out["headline"]["n_data"]
    # >= (n_data-1)/n_data of the momentum bytes stays the conservative
    # floor even counting indivisible leaves (CaffeNet's momentum mass is
    # in divisible fc/conv weights)
    assert base["momentum"] - zm["momentum"] >= \
        base["momentum"] * (n - 1) / n * 0.95, (base, zm)
    art = json.load(open(out_path))
    assert art["headline"]["metric"] == \
        "per_device_momentum_bytes_sharded_over_replicated"
    assert art["meta"]["jax_version"]
    assert "fetch_async_ms" in art["headline"]
    # r8 arms: the fused-boundary round ratio and the collect A/B (the
    # async-collect main-thread cost must be far below the sync fetch's
    # lower bound of an actual D2H materialization... on CPU both are
    # small; assert presence + sanity, not timing)
    assert art["headline"]["fused_round_ms_vs_unfused"] > 0
    for k in ("collect_sync_ms", "collect_async_blocking_ms",
              "fetch_shards_ms"):
        assert k in art["headline"], k


def test_ckpt_shard_bench_smoke(tmp_path):
    """bench.ckpt_shard_bench writes the r8 BENCH_CKPT_SHARD artifact;
    the DETERMINISTIC claims — restored maps bitwise equal across
    layouts, logical bytes identical (no replicated leaf written twice)
    — are asserted inside the bench per worker count and re-checked on
    the artifact here. The wall-time-decreases claim is the committed
    pod number (CPU rows stamp structure_proof)."""
    import bench
    out_path = str(tmp_path / "BENCH_CKPT_SHARD.json")
    out = bench.ckpt_shard_bench(out_path=out_path, trials=1, mb=2,
                                 workers=(2, 4))
    art = json.load(open(out_path))
    assert art["headline"]["bytes_equal"] is True
    assert [r["workers"] for r in art["rows"]] == [2, 4]
    for r in art["rows"]:
        for layout in ("monolithic", "sharded"):
            assert r[layout]["save_restore_ms"] > 0
    assert art["headline"]["structure_proof"] is True  # CPU build
    assert art["meta"]["jax_version"]


def test_profiler_trace_capture(tmp_path):
    """maybe_trace writes a TensorBoard-loadable capture; None is a no-op."""
    import jax
    import jax.numpy as jnp
    from sparknet_tpu.utils.profiling import maybe_trace
    with maybe_trace(None):
        pass
    d = str(tmp_path / "trace")
    with maybe_trace(d):
        float(jax.jit(lambda x: x * 2)(jnp.ones(8)).sum())
    files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
    assert any(os.path.isfile(f) for f in files), "no trace files written"
