"""Pallas LRN kernel vs XLA/torch oracles (interpreter mode on the CPU mesh;
the same kernel compiles for real on TPU — exercised by bench.py)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

from sparknet_tpu.ops.lrn import _lrn_xla
from sparknet_tpu.ops.pallas_lrn import lrn_pallas


@pytest.mark.parametrize("shape", [(2, 7, 7, 96), (1, 3, 3, 5), (300, 256)])
def test_pallas_lrn_forward_matches_xla(rng, shape):
    x = rng.standard_normal(shape, dtype=np.float32)
    want = np.asarray(_lrn_xla(jnp.asarray(x), 5, alpha=1e-4, beta=0.75, k=1.0))
    got = np.asarray(lrn_pallas(jnp.asarray(x), 5, 1e-4, 0.75, 1.0, True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pallas_lrn_forward_matches_torch(rng):
    x = rng.standard_normal((2, 5, 5, 16), dtype=np.float32)
    got = np.asarray(lrn_pallas(jnp.asarray(x), 5, 1e-4, 0.75, 1.0, True))
    want = F.local_response_norm(
        torch.from_numpy(np.transpose(x, (0, 3, 1, 2))), size=5, alpha=1e-4,
        beta=0.75, k=1.0).numpy()
    np.testing.assert_allclose(got, np.transpose(want, (0, 2, 3, 1)),
                               rtol=1e-5, atol=1e-6)


def test_pallas_lrn_gradient_matches_autodiff_of_xla(rng):
    """Custom VJP (Caffe's closed-form backward) vs autodiff of the XLA
    forward — must agree."""
    x = rng.standard_normal((3, 4, 4, 32), dtype=np.float32)
    dy = rng.standard_normal((3, 4, 4, 32), dtype=np.float32)

    def f_xla(x_):
        return jnp.vdot(_lrn_xla(x_, 5, alpha=2e-4, beta=0.75, k=1.0),
                        jnp.asarray(dy))

    def f_pal(x_):
        return jnp.vdot(lrn_pallas(x_, 5, 2e-4, 0.75, 1.0, True),
                        jnp.asarray(dy))

    g_want = np.asarray(jax.grad(f_xla)(jnp.asarray(x)))
    g_got = np.asarray(jax.grad(f_pal)(jnp.asarray(x)))
    np.testing.assert_allclose(g_got, g_want, rtol=1e-4, atol=1e-6)


def test_pallas_lrn_nmin_path_matches_xla(rng):
    """4-D inputs with lane-aligned batch take the N-minor sublane-window
    kernel (layout-bitcast path) — must match the XLA oracle fwd + bwd."""
    from sparknet_tpu.ops.pallas_lrn import _lrn_nmin
    x = rng.standard_normal((128, 3, 3, 8), dtype=np.float32)
    dy = rng.standard_normal((128, 3, 3, 8), dtype=np.float32)
    want = np.asarray(_lrn_xla(jnp.asarray(x), 5, alpha=1e-4, beta=0.75,
                               k=1.0))
    got = np.asarray(_lrn_nmin(jnp.asarray(x), 5, 1e-4, 0.75, 1.0, True))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def f_xla(x_):
        return jnp.vdot(_lrn_xla(x_, 5, alpha=1e-4, beta=0.75, k=1.0),
                        jnp.asarray(dy))

    def f_nmin(x_):
        return jnp.vdot(_lrn_nmin(x_, 5, 1e-4, 0.75, 1.0, True),
                        jnp.asarray(dy))

    g_want = np.asarray(jax.grad(f_xla)(jnp.asarray(x)))
    g_got = np.asarray(jax.grad(f_nmin)(jnp.asarray(x)))
    np.testing.assert_allclose(g_got, g_want, rtol=1e-4, atol=1e-6)


def test_lrn_pallas_dispatch():
    """Routing predicate: lane-aligned 4-D spatial inputs take the N-minor
    kernel; everything else takes the 2-D rows kernel."""
    from unittest import mock
    from sparknet_tpu.ops import pallas_lrn as m

    def routed(shape):
        x = jnp.zeros(shape, jnp.float32)
        with mock.patch.object(m, "_lrn_nmin") as nmin, \
                mock.patch.object(m, "_lrn_rows") as rows:
            m.lrn_pallas(x, 5, 1e-4, 0.75, 1.0, True)
            assert nmin.called != rows.called
            return "nmin" if nmin.called else "rows"

    assert routed((128, 3, 3, 8)) == "nmin"
    assert routed((256, 7, 7, 96)) == "nmin"
    assert routed((2, 7, 7, 96)) == "rows"     # batch not lane-aligned
    assert routed((128, 1, 1, 96)) == "rows"   # no spatial extent
    assert routed((300, 256)) == "rows"        # 2-D


def test_row_block_divides():
    from sparknet_tpu.ops.pallas_lrn import _row_block
    for r in (3025, 729, 169, 36, 7, 1):
        b = _row_block(r)
        assert r % b == 0 and 1 <= b <= 64


def test_pallas_lrn_row_padding(rng):
    """Row counts not divisible by BLOCK_ROWS must round-trip unchanged."""
    x = rng.standard_normal((7, 96), dtype=np.float32)  # 7 rows << 256
    got = np.asarray(lrn_pallas(jnp.asarray(x), 5, 1e-4, 0.75, 1.0, True))
    want = np.asarray(_lrn_xla(jnp.asarray(x), 5))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
