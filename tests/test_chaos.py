"""Chaos test: the COMPOSED preemption story (r3 review item 5).

Cursor, checkpoint, and launcher pieces are individually tested; this test
exercises the whole promise at once: a streaming training run (parallel
multi-reader ingest + per-round checkpoints) is SIGKILLed mid-flight three
times and relaunched, and the final state must be bit-identical to an
uninterrupted run — which requires that every resume restored params +
momentum + round counter + per-reader stream cursors exactly, and that the
replayed/continued rounds fed byte-identical batches (no example skipped,
none consumed twice in the effective history). The reference had nothing
here: its loop was `while(true)` with `task.maxFailures=1` (SURVEY §5.3).

Mechanism: the child process logs a hash of every round's batch; the parent
kills it with SIGKILL after observing fresh progress, relaunches, and at the
end asserts (a) every occurrence of round R across all launches hashed
identically to the uninterrupted run's round R — the stream never skews,
replays always reproduce; (b) the final checkpoint's params equal the
uninterrupted run's bit for bit.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

CHILD = r"""
import hashlib, json, os, sys
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=8'
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
from sparknet_tpu.apps.train_loop import train
from sparknet_tpu.data import imagenet
from sparknet_tpu.data.streaming import make_parallel_source
from sparknet_tpu.utils.config import RunConfig
from sparknet_tpu.utils.logger import Logger
from sparknet_tpu.zoo import lenet

root, ckdir, hashlog, max_rounds = sys.argv[1:5]

class HashingSource:
    '''Wraps the round source; appends {round, hash} per produced round.'''
    def __init__(self, inner, path):
        self.inner, self.path = inner, path
    def next_round(self, round_index=None):
        b = self.inner.next_round(round_index)
        h = hashlib.sha256(b['data'].tobytes() +
                           b['label'].tobytes()).hexdigest()[:16]
        with open(self.path, 'a') as f:
            f.write(json.dumps({'round': round_index, 'hash': h}) + '\n')
            f.flush()
        return b
    def cursor_at(self, r):
        return self.inner.cursor_at(r)
    def seek_rows(self, rows):
        return self.inner.seek_rows(rows)
    def close(self):
        self.inner.close()

class GrayTo28:
    def convert_batch(self, batch, train=True, rng=None):
        x = batch['data'].astype(np.float32).mean(axis=1)  # CHW -> HW
        return {'data': x[..., None], 'label': batch['label']}

n_local = jax.local_device_count()
src = HashingSource(make_parallel_source(
    imagenet.list_shards(root), imagenet.load_label_map(root + '/train.txt'),
    n_local, 2, 2, n_sources=2, height=28, width=28), hashlog)
# health off: the fixture net diverges on purpose (raw 0-255 pixels) and a
# supervisor rollback would advance the retried rounds' data order —
# breaking this test's round->hash bit-exactness invariant, which is about
# PREEMPTION resume, not anomaly recovery (test_health.py covers that)
from sparknet_tpu.utils.health import HealthConfig
cfg = RunConfig(model='lenet', tau=2, local_batch=2,
                max_rounds=int(max_rounds), eval_every=0, seed=0,
                checkpoint_dir=ckdir, checkpoint_every=1,
                workdir=os.path.dirname(hashlog),
                health=HealthConfig(enabled=False))
train(cfg, lenet(batch=2), src, None,
      logger=Logger(os.path.join(os.path.dirname(hashlog), 'train.txt'),
                    echo=False),
      batch_transform=GrayTo28())
print('CHILD DONE')
"""

MAX_ROUNDS = 10


def _launch(root, ckdir, hashlog, env=None):
    return subprocess.Popen(
        [sys.executable, "-c", CHILD, root, ckdir, hashlog,
         str(MAX_ROUNDS)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        env=env)


def _hashes(path):
    out = []
    if os.path.exists(path):
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if ln:
                    try:
                        out.append(json.loads(ln))
                    except json.JSONDecodeError:
                        pass  # torn final line from a SIGKILL mid-write
    return out


CHILD_BUCKET_CKPT = r"""
import hashlib, json, os, sys
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=4'
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
from sparknet_tpu.apps.train_loop import train
from sparknet_tpu.data import mnist
from sparknet_tpu.data.dataset import ArrayDataset
from sparknet_tpu.utils.config import RunConfig
from sparknet_tpu.utils.health import HealthConfig
from sparknet_tpu.utils.logger import Logger
from sparknet_tpu.zoo import lenet

root, ckdir, proglog, max_rounds = sys.argv[1:5]

tr = mnist.MnistLoader(root).train_batch_dict()


def hook(rnd, state):
    with open(proglog, 'a') as f:
        f.write(json.dumps({'round': rnd}) + '\n')
        f.flush()


cfg = RunConfig(model='lenet', tau=2, local_batch=2,
                max_rounds=int(max_rounds), eval_every=0, seed=0,
                checkpoint_dir=ckdir, checkpoint_every=1,
                workdir=os.path.dirname(proglog),
                health=HealthConfig(enabled=False))
train(cfg, lenet(batch=2), ArrayDataset(tr), None,
      logger=Logger(os.path.join(os.path.dirname(proglog), 'train.txt'),
                    echo=False), round_hook=hook)
print('CHILD DONE')
"""

BUCKET_ROUNDS = 5


@pytest.mark.chaos
def test_kill9_mid_upload_resumes_bitexact_from_bucket(tmp_path,
                                                       monkeypatch):
    """The r6 bucket-checkpoint chaos story (NOT slow-marked: runs in the
    tier-1 workflow): a training child writes per-round checkpoints
    natively to gs:// through the ASYNC two-stage pipeline; the parent —
    which hosts the fake bucket and can SEE the store's live resumable
    sessions — SIGKILLs the child exactly while a state.npz upload is in
    flight. The torn save must be invisible (meta.json never landed), the
    relaunch must resume from the newest committed bucket checkpoint, and
    the finished run's final state must be bit-identical to an
    uninterrupted local-checkpoint run."""
    from sparknet_tpu.data import mnist
    from sparknet_tpu.utils import checkpoint as ckpt
    from fake_stores import serve_gcs, stop_serving

    root = str(tmp_path / "mnist")
    mnist.write_synthetic(root, n_train=64, n_test=8)

    srv, endpoint = serve_gcs()
    handler = srv.handler
    handler.upload_delay_s = 0.05  # widen the mid-upload kill window
    # parent env too: the final restore_flat("gs://...") below runs here
    monkeypatch.setenv("STORAGE_EMULATOR_HOST", endpoint)
    monkeypatch.setenv("no_proxy", "*")

    def launch(ckdir, workdir):
        os.makedirs(workdir, exist_ok=True)
        return subprocess.Popen(
            [sys.executable, "-c", CHILD_BUCKET_CKPT, root, ckdir,
             os.path.join(workdir, "prog.jsonl"), str(BUCKET_ROUNDS)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            cwd=os.path.join(os.path.dirname(__file__), ".."),
            env=dict(os.environ))

    try:
        # uninterrupted reference run, local checkpoint dir
        ck_a = str(tmp_path / "ck_a")
        p = launch(ck_a, str(tmp_path / "run_a"))
        out, _ = p.communicate(timeout=420)
        assert p.returncode == 0 and "CHILD DONE" in out, out

        # chaos run against the bucket: kill WHILE an upload session for
        # the checkpoint prefix is live AND at least one step committed
        ck_b = "gs://bkt/ck_b"
        p = launch(ck_b, str(tmp_path / "run_b"))
        deadline = time.time() + 300
        killed = False
        while time.time() < deadline and p.poll() is None:
            committed = any(k.startswith("ck_b/") and
                            k.endswith("meta.json")
                            for k in list(handler.objects))  # server
            # threads mutate the dict concurrently; list() snapshots it
            live = [s for s in list(handler.sessions.values())
                    if s["name"].startswith("ck_b/")]
            if committed and live:
                os.kill(p.pid, signal.SIGKILL)
                p.wait(timeout=60)
                killed = True
                break
            time.sleep(0.002)
        assert killed, "never observed a live mid-upload window to kill"

        # relaunch: must resume from the newest COMMITTED bucket step and
        # finish; the torn upload is swept/ignored
        p = launch(ck_b, str(tmp_path / "run_b2"))
        out, _ = p.communicate(timeout=420)
        assert p.returncode == 0 and "CHILD DONE" in out, out
        text = open(str(tmp_path / "run_b2" / "train.txt")).read()
        assert "resumed from checkpoint round" in text

        fa, sa, _ = ckpt.restore_flat(ck_a)
        fb, sb, _ = ckpt.restore_flat(ck_b)
        assert sa == sb == BUCKET_ROUNDS
        assert sorted(fa) == sorted(fb)
        for k in fa:
            np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)
        # r8: the loop saves the SHARDED layout by default, so the kill
        # window above lands mid-SHARD upload — assert the manifest
        # layout really is in play, and that the relaunch's own saves
        # swept every orphan: no meta-less step (stray shard files of
        # the torn save), no stray .part- components, no commit residue
        meta = ckpt._load_meta(f"{ck_b}/step-{BUCKET_ROUNDS}")
        assert meta is not None and "shards" in meta, meta
        for s, files in ckpt._bucket_step_files(ck_b).items():
            assert "meta.json" in files, (
                f"orphan shard files survived at step-{s}: {files}")
            stray = [f for f in files
                     if ".part-" in f or f.startswith("commit-")]
            assert not stray, (s, stray)
    finally:
        stop_serving(srv)


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.parametrize("store", ["local", "gs"])
def test_kill9_resume_matches_uninterrupted(tmp_path, store):
    """`store='gs'` runs the SAME kill -9 chaos over a fake-GCS bucket —
    the path a real pod streams (r5, VERDICT weak #5): children resume
    their per-reader cursors against ranged HTTP tar streams (and the
    member-carve fast path after each child's first full shard pass)
    instead of local files."""
    from sparknet_tpu.data import imagenet
    from sparknet_tpu.utils import checkpoint as ckpt

    root = str(tmp_path / "shards")
    imagenet.write_synthetic_shards(root, n_shards=4, per_shard=12,
                                    size=28, n_classes=10)
    env = None
    srv = None
    if store == "gs":
        from fake_stores import serve_dir_as_gcs
        srv, endpoint = serve_dir_as_gcs(root)
        env = dict(os.environ, STORAGE_EMULATOR_HOST=endpoint,
                   no_proxy="*")
        root = "gs://bkt/imagenet"

    # uninterrupted reference run
    ck_a = str(tmp_path / "ck_a")
    hl_a = str(tmp_path / "hash_a.jsonl")
    p = _launch(root, ck_a, hl_a, env)
    out, _ = p.communicate(timeout=300)
    assert p.returncode == 0 and "CHILD DONE" in out, out

    # chaos run: SIGKILL after fresh progress, three times, then finish
    ck_b = str(tmp_path / "ck_b")
    hl_b = str(tmp_path / "hash_b.jsonl")
    rng = np.random.default_rng(7)
    kills = 0
    for attempt in range(12):  # hard cap on relaunches
        before = len(_hashes(hl_b))
        p = _launch(root, ck_b, hl_b, env)
        if kills < 3:
            # wait for >= 1-2 fresh rounds to be produced, then kill -9
            want = before + int(rng.integers(1, 3))
            deadline = time.time() + 120
            while len(_hashes(hl_b)) < want and p.poll() is None and \
                    time.time() < deadline:
                time.sleep(0.1)
            if p.poll() is None:
                os.kill(p.pid, signal.SIGKILL)
                p.wait(timeout=60)
                kills += 1
                continue
            out, _ = p.communicate(timeout=10)  # finished before the kill
        out, _ = p.communicate(timeout=300)
        if p.returncode == 0 and "CHILD DONE" in out:
            break
        pytest.fail(f"relaunch failed (rc={p.returncode}):\n{out}")
    else:
        pytest.fail("never completed after repeated kills")
    assert kills == 3, f"only {kills} kills landed"

    # (a) round -> hash must be a FUNCTION across every launch, equal to
    # the uninterrupted run's: replays reproduce bytes exactly, nothing
    # skipped, nothing skewed
    ref = {}
    for rec in _hashes(hl_a):
        ref.setdefault(rec["round"], set()).add(rec["hash"])
    assert all(len(v) == 1 for v in ref.values())
    assert set(ref) == set(range(MAX_ROUNDS))
    chaos = {}
    for rec in _hashes(hl_b):
        chaos.setdefault(rec["round"], set()).add(rec["hash"])
    for r, hs in chaos.items():
        assert hs == ref[r], (
            f"round {r}: chaos produced {hs}, uninterrupted {ref[r]}")
    assert set(range(MAX_ROUNDS)) <= set(chaos)

    # (b) final checkpoints bit-identical (params AND momentum AND counter
    # AND stream cursors): the whole composed resume story
    fa, sa, ea = ckpt.restore_flat(ck_a)
    fb, sb, eb = ckpt.restore_flat(ck_b)
    assert sa == sb == MAX_ROUNDS
    assert ea["stream"] == eb["stream"]
    assert sorted(fa) == sorted(fb)
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)
    if srv is not None:
        srv.shutdown()
