"""Distributed per-request tracing (this PR) — the `obs.reqtrace`
layer and its transport satellites:

  - trace-context mint / encode / parse / child semantics;
  - propagation pins across BOTH wires (X-Trace-Id on HTTP, the
    REQUEST-meta trace field on the binary wire) and through the
    router's remote proxy hop;
  - hedge legs carry leg=primary / leg=hedge tags exactly once;
  - the batch driver mints one trace per work unit, rows as children;
  - journal rows carry trace_id + request_id on both front doors;
  - the tail-sampling policy (typed sheds always, beyond-live-p95
    always, head-sample as minted) and the bounded-buffer drop
    accounting under a span flood;
  - clock-offset normalization + Chrome-trace assembly on synthetic
    skewed shards, and the LIVE two-process acceptance run (a
    deliberately slowed request router -> remote replica over the
    binary wire assembles into one trace with the cross-process hop).

Tier-1: CPU backend, pure-python nets (ModelManager tolerates a
paramless net when checkpoint_dir/quant are off), ephemeral ports.
"""
import http.client
import json
import time

import numpy as np
import pytest

from sparknet_tpu.obs import reqtrace
from sparknet_tpu.serve.batcher import QueueFullError
from sparknet_tpu.serve.binary_frontend import BinaryFrontend, binary_infer
from sparknet_tpu.serve.http_frontend import (NPZ_CONTENT_TYPE,
                                              HttpFrontend, _encode_npz,
                                              http_infer)
from sparknet_tpu.serve.router import ModelRouter, RouterConfig
from sparknet_tpu.serve.server import InferenceServer, ServeConfig
from sparknet_tpu.utils.logger import Logger


class SleepyNet:
    """Pure-python net: y = 2x after an optional sleep — slow enough to
    shape queues, no jax compile in the loop."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s

    def input_shapes(self):
        return {"x": (1, 4)}

    def input_dtypes(self):
        return {"x": np.float32}

    def forward(self, batch, blob_names=None):
        if self.delay_s:
            time.sleep(self.delay_s)
        return {"y": np.asarray(batch["x"], dtype=np.float32) * 2.0}


def _cfg(**kw):
    base = dict(max_batch=2, max_wait_ms=1.0, buckets=(1, 2),
                outputs=("y",), metrics_every_batches=0)
    base.update(kw)
    return ServeConfig(**base)


_X = {"x": np.ones((4,), np.float32)}


@pytest.fixture
def tracer():
    """A live tracer for the duration of one test, head-sampling
    everything (capture decisions under test get their own tracers)."""
    with reqtrace.request_tracing(None, head_sample=1.0,
                                  proc="test") as tr:
        yield tr


def _rows_until(tr, pred, timeout=10.0):
    """Poll the tracer's buffered rows until `pred(rows)` (completion
    callbacks may land after the client's future resolves)."""
    deadline = time.monotonic() + timeout
    rows = []
    while time.monotonic() < deadline:
        rows = rows + tr.drain_rows()
        if pred(rows):
            return rows
        time.sleep(0.02)
    raise AssertionError(f"rows never satisfied predicate: {rows}")


# -- context ------------------------------------------------------------------

def test_context_mint_encode_parse_child():
    ctx = reqtrace.mint_context(sampled=True)
    assert len(ctx.trace_id) == 16 and len(ctx.span_id) == 8
    back = reqtrace.parse_context(ctx.encoded())
    assert back == ctx
    # child: fresh span id, same identity; leg inherited unless overridden
    kid = ctx.child()
    assert kid.trace_id == ctx.trace_id and kid.span_id != ctx.span_id
    assert kid.sampled is True
    hedge = ctx.child(leg="hedge")
    assert hedge.leg == "hedge"
    assert hedge.child().leg == "hedge"  # a hedge's proxy hop stays hedge
    assert "-hedge" in hedge.encoded()
    assert reqtrace.parse_context(hedge.encoded()).leg == "hedge"
    # tolerant decode: garbage is None, never an exception
    for junk in (None, "", "xyz", "nothex!-00-1", "aa-bb", "aa-bb-7", 42):
        assert reqtrace.parse_context(junk) is None


# -- propagation over both wires ---------------------------------------------

def test_trace_propagates_over_binary_wire(tracer):
    with InferenceServer(SleepyNet(), _cfg()) as srv:
        fe = BinaryFrontend(srv, port=0)
        try:
            ctx = tracer.mint(sampled=True)
            # a client-side record owns the wire span, as the router
            # does on a proxy hop (finishing it drains whatever the
            # server-side finish didn't — same-tracer test, two procs
            # in production)
            cli_rec = tracer.begin(ctx, transport="cli")
            out = binary_infer(fe.address, "default", _X, trace=ctx)
            tracer.finish(cli_rec, "ok")
            np.testing.assert_allclose(out["y"], 2.0)
            rows = _rows_until(
                tracer,
                lambda rs: any(r["k"] == "r" and
                               r["transport"] == "binary" for r in rs))
        finally:
            fe.stop()
    req = [r for r in rows
           if r["k"] == "r" and r["transport"] == "binary"]
    assert len(req) == 1
    # the server's request row carries the trace identity AND the exact
    # span id the client sent — the cross-process join key
    assert req[0]["trace"] == ctx.trace_id
    assert req[0]["span"] == ctx.span_id
    assert req[0]["transport"] == "binary"
    assert req[0]["outcome"] == "ok"
    # the server-side stage spans are captured under the same trace
    # (asserted on the span rows: with BOTH wire ends sharing one
    # tracer in-process, which request row's finish() drains a given
    # span is timing-dependent — in production they are two processes)
    names = {r["name"] for r in rows
             if r["k"] == "s" and r.get("kind") == "server"}
    for st in ("queue", "forward", "reply"):
        assert st in names, names
    # the client-side wire span matches by the same span id
    wire_spans = [r for r in rows
                  if r["k"] == "s" and r.get("kind") == "client"]
    assert [s["span"] for s in wire_spans] == [ctx.span_id]
    assert wire_spans[0]["name"] == "wire:binary"
    # exemplars feed /status
    assert srv.status().get("slow_requests")


def test_trace_propagates_over_http_wire_and_echoes_header(tracer):
    with InferenceServer(SleepyNet(), _cfg()) as srv:
        fe = HttpFrontend(srv, port=0)
        try:
            ctx = tracer.mint(sampled=True)
            conn = http.client.HTTPConnection(*fe.address, timeout=30)
            conn.request("POST", "/v1/models/default/infer",
                         body=_encode_npz(_X),
                         headers={"Content-Type": NPZ_CONTENT_TYPE,
                                  "Accept": NPZ_CONTENT_TYPE,
                                  "X-Trace-Id": ctx.encoded()})
            resp = conn.getresponse()
            echoed = resp.getheader("X-Trace-Id")
            resp.read()
            conn.close()
            assert resp.status == 200
            # the reply names the trace so a slow client can go straight
            # to sparknet-trace
            assert echoed == ctx.encoded()
            rows = _rows_until(
                tracer, lambda rs: any(r["k"] == "r" for r in rs))
        finally:
            fe.stop()
    req = [r for r in rows if r["k"] == "r"]
    assert len(req) == 1
    assert req[0]["trace"] == ctx.trace_id
    assert req[0]["span"] == ctx.span_id
    assert req[0]["transport"] == "http"
    for st in ("admission", "decode", "queue", "forward"):
        assert st in req[0]["stages"], req[0]["stages"]


def test_journal_rows_carry_trace_and_request_id(tracer, tmp_path):
    jpath = tmp_path / "journal.jsonl"
    journal = Logger(jsonl_path=str(jpath), echo=False)
    with InferenceServer(SleepyNet(), _cfg()) as srv:
        bfe = BinaryFrontend(srv, port=0, journal=journal)
        hfe = HttpFrontend(srv, port=0, journal=journal)
        try:
            ctx_b = tracer.mint(sampled=True)
            ctx_h = tracer.mint(sampled=True)
            binary_infer(bfe.address, "default", _X, trace=ctx_b)
            http_infer(f"http://{hfe.address[0]}:{hfe.address[1]}",
                       "default", _X, trace=ctx_h)
        finally:
            bfe.stop()
            hfe.stop()
            journal.close()
    rows = [json.loads(l) for l in
            jpath.read_text().strip().splitlines()]
    by_transport = {r["transport"]: r for r in rows}
    assert set(by_transport) == {"binary", "http"}
    assert by_transport["binary"]["trace_id"] == ctx_b.trace_id
    assert by_transport["http"]["trace_id"] == ctx_h.trace_id
    for r in by_transport.values():
        # the Logger numeric-casts jsonl values; identity, not type
        assert r["request_id"] == int(r["request_id"]) >= 1


# -- router: proxy hop + hedge legs ------------------------------------------

def test_router_proxy_hop_propagates_and_mints(tracer):
    """A router fronted directly MINTS the context; the remote proxy
    hop carries a child of it over the binary wire, so the server-side
    request row joins the same trace by span-id equality."""
    with InferenceServer(SleepyNet(), _cfg()) as srv:
        fe = BinaryFrontend(srv, port=0)
        router = ModelRouter(RouterConfig(workers=2, hedge=False))
        router.add_remote_replica(
            "default", f"spkn://{fe.address[0]}:{fe.address[1]}")
        try:
            with router:
                out = router.infer("default", _X, timeout=30.0)
            np.testing.assert_allclose(out["y"], 2.0)
            rows = _rows_until(
                tracer,
                lambda rs: sum(r["k"] == "r" for r in rs) >= 2)
        finally:
            fe.stop()
    req = [r for r in rows if r["k"] == "r"]
    tids = {r["trace"] for r in req}
    assert len(tids) == 1  # one trace end to end
    by_transport = {r["transport"]: r for r in req}
    assert set(by_transport) == {"router", "binary"}
    assert by_transport["router"]["root"] is True
    # the frontend's row is keyed by the LEG's span id (a child), which
    # the client wire span shares
    wire = [r for r in rows if r["k"] == "s" and r.get("kind") == "client"]
    assert by_transport["binary"]["span"] in {s["span"] for s in wire}
    assert by_transport["binary"]["span"] != by_transport["router"]["span"]


def test_hedge_legs_tagged_exactly_once(tracer):
    """With hedging forced (2 slow replicas, no delay floor, full
    budget) a traced request's server-side rows carry leg=primary and
    leg=hedge EXACTLY once each — the trace shows both copies of the
    work and which leg is which."""
    srv1 = InferenceServer(SleepyNet(0.15), _cfg())
    srv2 = InferenceServer(SleepyNet(0.15), _cfg())
    srv1.start()
    srv2.start()
    fe1 = BinaryFrontend(srv1, port=0)
    fe2 = BinaryFrontend(srv2, port=0)
    router = ModelRouter(RouterConfig(workers=4, hedge=True,
                                      hedge_budget=1.0,
                                      hedge_min_delay_ms=1.0))
    for fe in (fe1, fe2):
        router.add_remote_replica(
            "default", f"spkn://{fe.address[0]}:{fe.address[1]}")
    try:
        with router:
            out = router.infer("default", _X, timeout=30.0)
        np.testing.assert_allclose(out["y"], 2.0)

        def both_legs(rs):
            legs = [r.get("leg") for r in rs if r["k"] == "r"
                    and r["transport"] == "binary"]
            return "primary" in legs and "hedge" in legs
        rows = _rows_until(tracer, both_legs, timeout=15.0)
    finally:
        fe1.stop()
        fe2.stop()
        srv1.stop()
        srv2.stop()
    legs = [r.get("leg") for r in rows
            if r["k"] == "r" and r["transport"] == "binary"]
    assert legs.count("primary") == 1, legs
    assert legs.count("hedge") == 1, legs
    # both legs belong to ONE trace
    assert len({r["trace"] for r in rows if r["k"] == "r"}) == 1


# -- batch driver -------------------------------------------------------------

def test_batch_driver_unit_spans(tracer, tmp_path):
    from sparknet_tpu.batch import BatchConfig, BatchDriver
    r = np.random.default_rng(3)
    np.savez(str(tmp_path / "in.npz"),
             x=r.standard_normal((8, 4)).astype(np.float32))
    with InferenceServer(SleepyNet(), _cfg()) as srv:
        fe = BinaryFrontend(srv, port=0)
        try:
            res = BatchDriver(BatchConfig(
                input=str(tmp_path / "in.npz"),
                output=str(tmp_path / "out"),
                replicas=[f"{fe.address[0]}:{fe.address[1]}"],
                outputs=("y",), unit_rows=4, window=2, concurrency=1,
                deadline_s=30.0, request_timeout_s=60.0)).run()
            assert res["done"]
        finally:
            fe.stop()
    rows = tracer.drain_rows()
    units = [r for r in rows
             if r["k"] == "r" and r["transport"] == "batch"]
    assert len(units) == 2  # one trace per work unit
    for u in units:
        assert u["outcome"] == "ok"
        assert "unit" in u["stages"]
        # the unit's row requests are children on the SAME trace: each
        # produced a server-side binary request row under this trace_id
        kids = [r for r in rows if r["k"] == "r"
                and r["transport"] == "binary"
                and r["trace"] == u["trace"]]
        assert len(kids) == 4
        assert all(k["span"] != u["span"] for k in kids)


# -- sampling policy + bounded buffers ---------------------------------------

def test_tail_sampling_policy():
    tr = reqtrace.RequestTracer(head_sample=0.0, slow_min_n=4)
    ctx = reqtrace.mint_context(sampled=False)
    # 1) healthy + unsampled: forgotten
    assert tr.finish(tr.begin(ctx, model="m"), "ok") is False
    # 2) typed shed: ALWAYS captured
    rec = tr.begin(reqtrace.mint_context(), model="m")
    assert tr.finish_exc(rec, QueueFullError("full")) is True
    row = [r for r in tr.drain_rows() if r["k"] == "r"][0]
    assert row["outcome"] == "queue_full" and row["why"] == ["outcome"]
    # 3) beyond the live windowed p95: captured, with the threshold read
    #    BEFORE this observation joins the window
    for _ in range(16):
        tr.finish(tr.begin(reqtrace.mint_context(), model="m"), "ok")
    slow = tr.begin(reqtrace.mint_context(), model="m")
    slow["ts"] -= 2e6  # backdate 2 s: far past any live p95
    assert tr.finish(slow, "ok") is True
    srow = [r for r in tr.drain_rows() if r["k"] == "r"][0]
    assert "slow" in srow["why"]
    # 4) head-sample flag minted into the context is honored
    rec = tr.begin(reqtrace.mint_context(sampled=True), model="m")
    assert tr.finish(rec, "ok") is True
    assert "sampled" in [r for r in tr.drain_rows()
                         if r["k"] == "r"][0]["why"]


def test_outcome_mapping_walks_mro():
    class SubQueueFull(QueueFullError):
        pass
    assert reqtrace.outcome_of(SubQueueFull("x")) == "queue_full"
    assert reqtrace.outcome_of(TimeoutError()) == "timeout"
    assert reqtrace.outcome_of(ValueError("?")) == "error"


def test_bounded_buffers_account_drops_under_flood():
    tr = reqtrace.RequestTracer(head_sample=1.0, max_pending=64,
                                max_rows=128, flush_every=10 ** 9)
    # span flood across many traces that never finish: the pending
    # bound evicts oldest traces wholesale, with accounting
    for i in range(300):
        ctx = reqtrace.mint_context()
        tr.stage(ctx, "queue", 0.0, 1.0)
    st = tr.stats()
    assert st["pending_spans"] <= 64
    assert st["dropped_spans"] >= 300 - 64
    # captured-row flood: the shard bound drops whole requests, counted
    for i in range(300):
        tr.finish(tr.begin(reqtrace.mint_context(sampled=True),
                           model="m"), "ok")
    st = tr.stats()
    assert st["buffered_rows"] <= 128
    assert st["dropped_rows"] > 0
    assert st["finished"] == 300
    # the tracer never threw and still works
    rec = tr.begin(reqtrace.mint_context(sampled=True), model="m")
    tr.drain_rows()
    assert tr.finish(rec, "ok") is True


# -- assembly -----------------------------------------------------------------

def _row(k, proc, span, ts_us, dur_us, **kw):
    base = {"k": k, "trace": "t" * 16, "span": span, "ts": ts_us,
            "dur": dur_us, "pid": 1, "proc": proc}
    if k == "r":
        base.update(root=False, model="m", transport="binary",
                    outcome="ok", why=["sampled"], stages={})
    else:
        base.update(name="wire:binary", kind="client")
    base.update(kw)
    return base


def test_clock_offsets_recover_synthetic_skew():
    """Server clock skewed +500 ms: the matched wire hop's midpoint
    alignment recovers the offset, and the assembled Chrome trace nests
    the server row inside the client span on one normalized timeline."""
    skew = 500_000.0
    client_req = _row("r", "router", "aaaa", 1_000.0, 60_000.0,
                      root=True, transport="router",
                      stages={"queue": 1.0})
    wire = _row("s", "router", "bbbb", 5_000.0, 50_000.0)
    server_req = _row("r", "replica", "bbbb", 10_000.0 + skew, 40_000.0,
                      stages={"forward": 35.0, "queue": 2.0})
    rows = [client_req, wire, server_req]
    offs = reqtrace.clock_offsets(rows)
    assert offs["router"] == 0.0
    # off[replica] = mid(client span) - mid(server row) = 30000 - 530000
    assert offs["replica"] == pytest.approx(-skew, abs=1.0)
    ch = reqtrace.chrome_trace("t" * 16, rows, offs)
    evs = {(e["pid"], e["tid"]): e for e in ch["traceEvents"]
           if e["ph"] == "X"}
    assert len({pid for pid, _ in evs}) == 2
    # normalized: the server row starts AFTER the wire span starts and
    # ends before it ends, despite the raw +500 ms skew
    srv_ev = [e for e in ch["traceEvents"] if e["ph"] == "X"
              and e["args"].get("transport") == "binary"][0]
    wire_ev = [e for e in ch["traceEvents"] if e["ph"] == "X"
               and e["name"] == "wire:binary"][0]
    assert wire_ev["ts"] <= srv_ev["ts"]
    assert (srv_ev["ts"] + srv_ev["dur"]
            <= wire_ev["ts"] + wire_ev["dur"] + 1.0)
    s = reqtrace.trace_summary("t" * 16, rows, offs)
    assert s["procs"] == 2 and s["hops"] == 1
    assert s["forward_ms"] == pytest.approx(35.0)
    assert s["queue_ms"] == pytest.approx(3.0)
    # wire = client wait minus the server's own time
    assert s["wire_ms"] == pytest.approx(10.0)
    assert s["total_ms"] == pytest.approx(60.0)
    assert s["dominant"] == "forward"


def test_shard_roundtrip_and_tolerant_loader(tmp_path):
    tr = reqtrace.RequestTracer(out_dir=str(tmp_path), head_sample=1.0,
                                proc="p/1")  # sanitized in filename
    ctx = tr.mint(sampled=True)
    rec = tr.begin(ctx, transport="http", model="m")
    tr.stage(ctx, "queue", rec["ts"], 10.0)
    tr.finish(rec, "ok")
    path = tr.flush()
    assert path and path.endswith(".jsonl") and "/" not in path.split(
        "trace-")[1]
    with open(path, "a") as f:
        f.write("not json\n{\"k\": \"junk\"}\n")
    rows = reqtrace.load_shards([str(tmp_path)])
    assert {r["k"] for r in rows} == {"r", "s"}
    asm = reqtrace.assemble(rows)
    assert ctx.trace_id in asm
    assert asm[ctx.trace_id]["summary"]["queue_ms"] == pytest.approx(
        0.01)
    # the console table renders without a live tracer
    table = reqtrace.format_slowest(
        [t["summary"] for t in asm.values()])
    assert ctx.trace_id in table


# -- the live two-process acceptance run -------------------------------------

def test_two_process_slow_request_assembles_one_trace(tmp_path):
    """The PR's acceptance path, live: a router here proxies a
    deliberately slowed request over the binary wire to a replica
    subprocess; both processes shard spans; `sparknet-trace` assembly
    must produce ONE trace crossing both processes with a matched wire
    hop and the queue/formation/forward breakdown. (This is exactly
    what `sparknet-trace --selfcheck` runs in CI.)"""
    keep = str(tmp_path / "selfcheck")
    assert reqtrace._selfcheck(keep=keep, delay_ms=40.0) == 0
    rows = reqtrace.load_shards([keep + "/shards"])
    traces = reqtrace.assemble(rows)
    crossing = [t for t in traces.values()
                if t["summary"]["procs"] >= 2]
    assert crossing
    s = max(crossing, key=lambda t: t["summary"]["total_ms"])["summary"]
    assert s["hops"] >= 1
    assert s["forward_ms"] >= 20.0  # the planted 40 ms delay dominates
    assert s["dominant"] == "forward"
