"""`sparknet_tpu.serve` — dynamic batching, hot-reload, parity, chaos.

Tier-1 (CPU mesh, local/fake stores, small nets). The contracts pinned:

  - batching policy: max-batch flush, oldest-request deadline flush,
    queue-capacity backpressure, batches never exceed their bucket.
  - concurrency: N client threads, every request answered exactly once
    with ITS OWN answer (responses keyed to request content).
  - parity: padded rows are BITWISE-identical to an unpadded forward at
    the same compiled bucket (padding is lossless); across different
    buckets outputs are allclose (XLA may re-associate per-shape — the
    same contract training accepts, pinned empirically here).
  - chaos: a checkpoint hot-swap lands mid-traffic without dropping or
    corrupting a single response; a corrupt snapshot is rejected
    (digest verify) with traffic unharmed; a poisoned-but-valid
    snapshot is rolled back by the canary.
"""
import json
import os
import threading
import time
import urllib.request
from concurrent.futures import Future

import numpy as np
import pytest

from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.serve import (DeadlineExpiredError, DynamicBatcher,
                                InferenceServer, ModelManager,
                                QueueFullError, ServeConfig,
                                ServeModelError, zeros_batch)
from sparknet_tpu.serve.model_manager import params_from_checkpoint_flat
from sparknet_tpu.utils import checkpoint as ckpt
from sparknet_tpu.utils.heartbeat import read_heartbeat
from sparknet_tpu.zoo import lenet


def _example(i: int) -> dict:
    """Deterministic per-request input keyed on i — responses can be
    matched back to the request that produced them."""
    r = np.random.default_rng(1000 + i)
    return {"data": r.standard_normal((28, 28, 1)).astype(np.float32)}


@pytest.fixture(scope="module")
def net():
    return JaxNet(lenet(batch=4))


@pytest.fixture()
def server(net):
    cfg = ServeConfig(max_batch=4, max_wait_ms=10.0,
                      outputs=("fc2", "prob"), metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        yield srv


# -- batcher policy ----------------------------------------------------------

def test_batcher_flushes_at_max_batch():
    b = DynamicBatcher(max_batch=4, max_wait_s=60.0)  # deadline far away
    for i in range(9):
        b.submit({"x": np.float32(i)})
    got = b.next_batch()
    assert [r.payload["x"] for r in got] == [0, 1, 2, 3]  # FIFO, full
    assert len(b.next_batch()) == 4
    # 1 leftover: the deadline (not size) must flush it
    b.max_wait_s = 0.01
    t0 = time.perf_counter()
    got = b.next_batch()
    assert len(got) == 1 and got[0].payload["x"] == 8
    assert time.perf_counter() - t0 < 5.0


def test_batcher_deadline_keyed_on_oldest():
    """A steady trickle must not reset the timer: the batch closes at
    oldest.t_enqueue + max_wait even while new requests keep arriving."""
    b = DynamicBatcher(max_batch=64, max_wait_s=0.08)
    stop = threading.Event()

    def trickle():
        while not stop.is_set():
            b.submit({"x": np.float32(0)})
            time.sleep(0.005)

    t = threading.Thread(target=trickle, daemon=True)
    b.submit({"x": np.float32(-1)})
    t0 = time.perf_counter()
    t.start()
    try:
        got = b.next_batch()
    finally:
        stop.set()
        t.join()
    dt = time.perf_counter() - t0
    assert got[0].payload["x"] == -1
    assert dt < 1.0, f"trickle starved the head of the queue for {dt:.2f}s"
    b.close()


def test_batcher_wake_on_submit_no_poll_quantum():
    """Wake-on-submit: a consumer parked with a FAR wake_at alarm is
    woken by submit immediately — a lone request's wait is bounded by
    max_wait_s + scheduling jitter, with no poll-interval quantum."""
    b = DynamicBatcher(max_batch=8, max_wait_s=0.005)
    got, lat = [], []

    def consume():
        t0 = time.perf_counter()
        got.append(b.next_batch(wake_at=t0 + 30.0))  # alarm way out
        lat.append(time.perf_counter() - t0)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.1)  # consumer is parked in the condition wait
    t0 = time.perf_counter()
    b.submit({"x": np.float32(7)})
    t.join(timeout=5.0)
    assert not t.is_alive()
    dt = time.perf_counter() - t0
    assert got[0][0].payload["x"] == 7
    # bound: max_wait (5 ms) + generous scheduling jitter, FAR below the
    # old 50 ms poll quantum this replaced
    assert dt < 0.045, f"lone request waited {dt * 1e3:.1f} ms"
    b.close()


def test_batcher_sheds_expired_deadlines_before_forming():
    """A queued request whose client deadline passed is shed at batch
    formation (DeadlineExpiredError + shed counter), never returned in
    a batch; requests without deadlines are unaffected."""
    from sparknet_tpu.obs import MetricsRegistry

    reg = MetricsRegistry()
    b = DynamicBatcher(max_batch=8, max_wait_s=0.01, registry=reg,
                       model="m")
    doomed = b.submit({"x": np.float32(1)}, deadline_s=0.005)
    alive = b.submit({"x": np.float32(2)})
    time.sleep(0.05)  # doomed expires while queued
    got = b.next_batch()
    assert [r.payload["x"] for r in got] == [2]
    with pytest.raises(DeadlineExpiredError):
        doomed.result(timeout=1.0)
    assert b.shed == 1
    c = reg.counter("sparknet_serve_shed_total",
                    labels=("model", "reason"))
    assert c.value(model="m", reason="deadline") == 1
    # an ALREADY-expired deadline never touches the queue
    pre = b.submit({"x": np.float32(3)}, deadline_s=0.0)
    with pytest.raises(DeadlineExpiredError):
        pre.result(timeout=1.0)
    assert b.depth() == 0 and b.shed == 2
    # sanity: the un-deadlined request was actually served
    assert alive  # future returned; group serving is the server's job
    b.close()


def test_batcher_closes_batch_early_for_client_deadline():
    """Deadline-aware formation: a request whose client deadline lands
    BEFORE the oldest-request max_wait close resolves at ~its deadline —
    served early (the formation loop closes 1 ms ahead of the deadline),
    or, if a contended host loses that scheduling margin, shed AT it.
    Either way the client is answered around its deadline, never held
    to the 0.5 s batch deadline."""
    b = DynamicBatcher(max_batch=64, max_wait_s=0.5)
    t0 = time.perf_counter()
    f = b.submit({"x": np.float32(1)}, deadline_s=0.05)
    got = b.next_batch()
    dt = time.perf_counter() - t0
    assert dt < 0.3, (f"batch held {dt:.2f}s past the client deadline "
                      f"instead of closing early")
    if got:  # the common, uncontended outcome: served before expiry
        assert got[0].payload["x"] == 1
    else:    # margin lost to scheduling: shed AT the deadline, answered
        with pytest.raises(DeadlineExpiredError):
            f.result(timeout=1.0)
    b.close()


def test_server_infer_timeout_is_a_deadline(net):
    """infer(timeout=) threads the deadline into batch formation: an
    expired request is shed with DeadlineExpiredError instead of riding
    a bucket slot (and instead of a bare concurrent.futures timeout)."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)

    class SlowNet:
        """Facade: forwards take long enough that a queued request's
        deadline expires while an earlier batch is still running."""

        def __init__(self, inner, delay_s):
            self._inner, self._delay = inner, delay_s

        def __getattr__(self, k):
            return getattr(self._inner, k)

        def forward(self, *a, **kw):
            time.sleep(self._delay)
            return self._inner.forward(*a, **kw)

    slow = SlowNet(net, 0.25)
    with InferenceServer(slow, cfg) as srv:
        srv.infer(_example(0))  # compile + warm
        # first request occupies the worker; the second's 100 ms deadline
        # expires during that forward -> shed at ITS batch formation
        first = srv.submit(_example(1))
        time.sleep(0.05)  # first's batch is IN the slow forward now
        t0 = time.perf_counter()
        with pytest.raises(DeadlineExpiredError):
            srv.infer(_example(2), timeout=0.1)
        dt = time.perf_counter() - t0
        assert dt < 2.0, f"shed took {dt:.2f}s (shed-not-hang violated)"
        first.result(timeout=30.0)
        assert srv.batcher.shed >= 1
        assert srv.status()["requests_shed"] >= 1


def test_server_lone_request_latency_bounded(net):
    """The wake-on-submit pin at server level: a warmed, idle server
    answers a lone request within max_wait + a few forwards — the old
    50 ms idle-poll quantum is gone from the path."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=5.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        srv.infer(_example(0))  # compile bucket 1
        # estimate one forward
        t0 = time.perf_counter()
        srv.infer(_example(1))
        fwd_s = max(time.perf_counter() - t0 - 0.005, 0.002)
        time.sleep(0.3)  # worker fully parked (mid-poll, in the old code)
        lats = []
        for i in range(15):
            t0 = time.perf_counter()
            srv.infer(_example(2 + i))
            lats.append(time.perf_counter() - t0)
            time.sleep(0.01)
        lats.sort()
        p99 = lats[-1]
        bound = 0.005 + 6 * fwd_s + 0.015  # deadline + forwards + jitter
        assert p99 < max(bound, 0.045), (
            f"lone p99 {p99 * 1e3:.1f} ms vs bound "
            f"{max(bound, 0.045) * 1e3:.1f} ms — is an idle-poll quantum "
            f"back in the path?")


def test_batcher_backpressure_and_close():
    b = DynamicBatcher(max_batch=2, max_wait_s=60.0, max_queue=3)
    futs = [b.submit({"x": np.float32(i)}) for i in range(3)]
    with pytest.raises(QueueFullError):
        b.submit({"x": np.float32(9)})
    b.close()
    with pytest.raises(RuntimeError):
        b.submit({"x": np.float32(9)})
    for f in futs:  # queued-but-unserved requests must not hang clients
        with pytest.raises(RuntimeError, match="shut down"):
            f.result(timeout=1.0)


# -- serving: concurrency + bucket discipline --------------------------------

def test_concurrent_clients_every_request_answered_exactly_once(server,
                                                                net):
    """8 client threads x 12 requests: every future resolves exactly once,
    with the answer belonging to ITS request (matched against a direct
    forward of the same example), and every formed batch fits a bucket."""
    n_clients, per = 8, 12
    results: dict = {}
    errs = []

    def client(c):
        try:
            futs = [(i, server.submit(_example(c * per + i)))
                    for i in range(per)]
            for i, f in futs:
                results[(c, i)] = f.result(timeout=30.0)
        except Exception as e:  # pragma: no cover - failure detail
            errs.append(e)

    ts = [threading.Thread(target=client, args=(c,))
          for c in range(n_clients)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    assert len(results) == n_clients * per  # exactly once, none dropped
    st = server.status()
    assert st["requests_ok"] == n_clients * per
    assert st["requests_failed"] == 0
    # responses match their own request: direct forward of example k
    # (cross-bucket tolerance — the response may have run in any bucket;
    # see test_cross_bucket_outputs_allclose for why not bitwise)
    for (c, i), resp in results.items():
        k = c * per + i
        direct = net.forward({**zeros_batch(net, 1), **{
            "data": _example(k)["data"][None]}}, blob_names=["fc2"])
        np.testing.assert_allclose(resp["fc2"], direct["fc2"][0],
                                   rtol=1e-4, atol=1e-4)
    # bucket discipline: n <= bucket, bucket is a configured bucket
    assert server.batch_log, "no batches recorded"
    for n, bucket in server.batch_log:
        assert bucket in server.buckets
        assert 1 <= n <= bucket <= server.cfg.max_batch


def test_mis_shaped_request_rejected_at_the_door(server):
    """A mis-shaped request is a TYPED ValueError at submit() — the
    frontends' 400 ladder — never a batch-mate poisoner. It used to
    survive to the pre-sized pad path, where `np.stack(rows,
    out=buf[:n])` blew up the WHOLE signature group with an opaque
    "Output array is the wrong shape" server-side 500."""
    good = [server.submit(_example(i)) for i in range(2)]
    with pytest.raises(ValueError, match=r"\(7, 7, 1\)"):
        server.submit({"data": np.zeros((7, 7, 1), np.float32)})
    with pytest.raises(ValueError, match="not a net input"):
        server.submit({"dta": _example(0)["data"]})
    # co-batched good requests are untouched, and the bad one never
    # entered the pipeline: no server-side failure is recorded
    for f in good:
        assert np.isfinite(f.result(timeout=30.0)["prob"]).all()
    assert server.status()["requests_failed"] == 0


# -- parity ------------------------------------------------------------------

def test_padded_batch_bitwise_matches_unpadded_rows(net):
    """Padding is lossless WITHIN a compiled bucket: rows of a 2-real/
    2-pad forward are bitwise-identical to the same rows of a full-4
    forward (every layer is row-independent across the batch)."""
    data = np.stack([_example(i)["data"] for i in range(4)])
    full = net.forward({**zeros_batch(net, 4), "data": data},
                       blob_names=["fc2", "prob"])
    padded_in = np.concatenate([data[:2], np.zeros_like(data[:2])])
    padded = net.forward({**zeros_batch(net, 4), "data": padded_in},
                         blob_names=["fc2", "prob"])
    for k in ("fc2", "prob"):
        np.testing.assert_array_equal(padded[k][:2], full[k][:2])


def test_server_single_bucket_bitwise_parity(net):
    """With ONE bucket, a lone request and a full concurrent batch run
    the SAME compiled forward — server answers are bitwise-identical to
    direct single-request forwards padded to that bucket."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=5.0, buckets=(4,),
                      outputs=("fc2",))
    with InferenceServer(net, cfg) as srv:
        lone = srv.infer(_example(0))  # padded 1 -> 4 by the server
        futs = [srv.submit(_example(i)) for i in range(4)]
        batched = [f.result(timeout=30.0) for f in futs]
        assert all(b == 4 for _, b in srv.batch_log)
    direct_in = np.stack([_example(i)["data"] for i in range(4)])
    direct = net.forward({**zeros_batch(net, 4), "data": direct_in},
                         blob_names=["fc2"])
    # the lone request and its batched twin took different-fill batches
    # of the SAME bucket: bitwise equal, and equal to the direct forward
    np.testing.assert_array_equal(lone["fc2"], batched[0]["fc2"])
    for i in range(4):
        np.testing.assert_array_equal(batched[i]["fc2"], direct["fc2"][i])


def test_cross_bucket_outputs_allclose(server, net):
    """Across DIFFERENT compiled buckets XLA may re-associate reductions:
    the contract is allclose, not bitwise (measured ~3e-5 max drift on
    f32 lenet logits) — pinned so a real numerical regression (layout
    bug, wrong padding) still fails loudly."""
    lone = server.infer(_example(3))  # bucket 1
    futs = [server.submit(_example(i)) for i in range(3, 7)]  # bucket 4
    batched = futs[0].result(timeout=30.0)
    for f in futs[1:]:
        f.result(timeout=30.0)
    np.testing.assert_allclose(lone["fc2"], batched["fc2"],
                               rtol=1e-4, atol=1e-4)


# -- checkpoint hot-reload ---------------------------------------------------

def _save_trainstate_like(net, d, step, scale=1.0, anomalous=False):
    """A TrainState-shaped checkpoint (params/<l>/<p> with a leading
    replica axis) holding this net's weights scaled by `scale`."""
    flat = {}
    for lname, lp in net.params.items():
        for pname, w in lp.items():
            flat[f"params/{lname}/{pname}"] = np.asarray(w)[None] * scale
    extra = {"anomalous": True} if anomalous else None
    return ckpt.save(str(d), flat, step=step, extra=extra)


def test_manager_initial_load_and_flat_extraction(net, tmp_path):
    d = tmp_path / "ck"
    _save_trainstate_like(net, d, step=3, scale=0.5)
    m = ModelManager(net, checkpoint_dir=str(d))
    assert m.load_initial() == 3
    assert m.step == 3
    # and the extraction helper round-trips shapes exactly
    flat, _, _ = ckpt.restore_flat(str(d))
    params = params_from_checkpoint_flat(flat, net.params)
    for lname, lp in net.params.items():
        for pname, w in lp.items():
            assert params[lname][pname].shape == w.shape


def test_manager_rejects_missing_leaves(net, tmp_path):
    d = tmp_path / "ck"
    _save_trainstate_like(net, d, step=1)
    flat, _, _ = ckpt.restore_flat(str(d))
    with pytest.raises(ServeModelError, match="conv1"):
        params_from_checkpoint_flat(
            {k: v for k, v in flat.items() if "conv1" not in k},
            net.params)
    # a claimed-tp checkpoint whose shards do NOT reassemble to the net's
    # shapes still fails loudly with the leaf path
    bad = dict(flat)
    bad["params/fc1/w"] = bad["params/fc1/w"][:, :, :100]
    with pytest.raises(ServeModelError, match="fc1"):
        params_from_checkpoint_flat(bad, net.params, tp=2)


def _tp2_trainer_checkpoint(cls, d, step):
    """A REAL tp=2 training checkpoint of the serve net's architecture,
    written exactly as the train loop persists it (fetch_global ->
    flatten, topology in extra)."""
    import jax

    from sparknet_tpu import CompiledNet
    from sparknet_tpu.parallel import make_mesh
    from sparknet_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS,
                                            fetch_global)
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.zoo import lenet as lenet_spec

    cnet = CompiledNet.compile(lenet_spec(batch=4))
    mesh = make_mesh(4, axis_names=(DATA_AXIS, MODEL_AXIS), shape=(2, 2))
    t = cls(cnet, SolverConfig(base_lr=0.01, momentum=0.9,
                               lr_policy="fixed"), mesh, tau=1)
    state = t.init_state(jax.random.PRNGKey(5))
    flat = ckpt._flatten(fetch_global(state))
    extra = {"n_devices": 4, "tp": 2}
    if getattr(t, "state_layout", "replica") != "replica":
        extra["layout"] = t.state_layout
        extra["state_sharding"] = t.state_sharding
    ckpt.save(str(d), flat, step=step, extra=extra)
    return {l: {p: np.asarray(x) for p, x in lp.items()}
            for l, lp in t.averaged_params(state).items()}


def test_manager_serves_tp2_checkpoints_both_layouts(net, tmp_path):
    """r7: tp=2 checkpoints are servable. The replica layout's per-device
    column shards reassemble inside params_from_checkpoint_flat; the
    NamedSharding layout stores full logical weights and needs no
    reassembly. Either way the installed params equal the trainer's own
    averaged_params BITWISE and the manager reports a healthy swap."""
    from sparknet_tpu.parallel import ParallelTrainer, ShardedTrainer

    for sub, cls in (("replica", ParallelTrainer),
                     ("logical", ShardedTrainer)):
        d = tmp_path / f"ck_{sub}"
        want = _tp2_trainer_checkpoint(cls, d, step=2)
        m = ModelManager(net, checkpoint_dir=str(d), poll_interval_s=0.0)
        assert m.load_initial() == 2, sub
        assert m.swap_failures == 0, sub
        for lname, lp in want.items():
            for pname, w in lp.items():
                got = np.asarray(net.params[lname][pname])
                assert got.shape == w.shape, (sub, lname, pname)
                assert np.array_equal(got, w), (sub, lname, pname)
        # and the served net actually answers from the TP weights
        out = net.forward(zeros_batch(net, 4), blob_names=["prob"])
        assert np.all(np.isfinite(np.asarray(out["prob"])))


@pytest.mark.chaos
def test_hot_swap_mid_traffic_chaos(net, tmp_path):
    """The acceptance chaos: continuous client traffic while (1) a GOOD
    new checkpoint hot-swaps in, (2) a CORRUPT newer one is rejected,
    (3) a NONFINITE-but-digest-valid one is rolled back by the canary.
    Zero dropped responses, zero corrupted (all finite, right shape),
    and the swap/rejection counters tell the story."""
    d = tmp_path / "ck"
    _save_trainstate_like(net, d, step=1)
    hb_path = str(tmp_path / "hb.json")
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      checkpoint_dir=str(d), poll_interval_s=0.05,
                      heartbeat_path=hb_path, heartbeat_every_s=0.01)
    answered, bad = [], []
    stop = threading.Event()

    def client():
        i = 0
        while not stop.is_set():
            try:
                out = srv.infer(_example(i), timeout=30.0)
                p = out["prob"]
                if p.shape != (10,) or not np.isfinite(p).all() or \
                        abs(float(p.sum()) - 1.0) > 1e-3:
                    bad.append((i, p))
                answered.append(i)
            except Exception as e:
                bad.append((i, e))
            i += 1

    with InferenceServer(net, cfg) as srv:
        assert srv.manager.step == 1
        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            # (1) good swap lands without a hiccup
            _save_trainstate_like(net, d, step=2, scale=0.9)
            _wait(lambda: srv.manager.step == 2)
            # (2) corrupt snapshot: digest verify must reject it. Stage
            # the save OUTSIDE the watched dir and corrupt it there —
            # corrupting in place races the 50 ms poll, which can install
            # the still-clean step 3 before the byte flips (observed
            # flake). The rename publishes step 3 already-corrupt.
            stage = tmp_path / "stage"
            path = _save_trainstate_like(net, stage, step=3)
            npz = os.path.join(path, "state.npz")
            raw = bytearray(open(npz, "rb").read())
            raw[-32] ^= 0x01
            open(npz, "wb").write(bytes(raw))
            os.rename(path, os.path.join(str(d), os.path.basename(path)))
            fails = srv.manager.swap_failures
            _wait(lambda: srv.manager.swap_failures > fails)
            assert srv.manager.step == 2  # still on the good one
            assert "corrupt" in srv.manager.last_error
            # (3) digest-valid but poisoned weights: canary rolls back
            _save_trainstate_like(net, d, step=4, scale=np.nan)
            fails = srv.manager.swap_failures
            _wait(lambda: srv.manager.swap_failures > fails)
            assert srv.manager.step == 2
            assert "canary" in srv.manager.last_error
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not bad, bad[:3]
        assert len(answered) > 20  # real traffic flowed throughout
        assert srv.manager.swaps == 1
        assert srv.manager.swap_failures == 2
        st = srv.status()
        assert st["requests_failed"] == 0
        assert st["requests_ok"] >= len(answered)
    hb = read_heartbeat(hb_path)
    assert hb is not None and hb["role"] == "serve"
    assert hb["step"] == 2 and hb["rollbacks"] == 2


def _wait(cond, timeout=20.0):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < timeout, "condition never held"
        time.sleep(0.02)


# -- status surfaces ---------------------------------------------------------

def test_healthz_and_metrics_http(net):
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      status_port=0)  # ephemeral port
    with InferenceServer(net, cfg) as srv:
        srv.infer(_example(0))
        host, port = srv.status_address
        h = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read())
        assert h["status"] == "ok"
        # /metrics is now the Prometheus text exposition rendered from
        # the shared obs registry (same name schema as the train side);
        # the JSON vitals moved to /status
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10)
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = resp.read().decode()
        # serve families carry the model label (multi-model routers share
        # one registry; a single-model server labels its sole lane)
        assert ('sparknet_serve_requests_total{model="default",'
                'outcome="ok"} 1') in text
        assert 'sparknet_serve_batch_fill_ratio{model="default"} 1' in text
        assert "sparknet_build_info{" in text
        s = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status", timeout=10).read())
        assert s["requests_ok"] == 1
        assert s["batch_fill_ratio"] == 1.0  # one request, bucket 1
        assert s["p50_ms"] is not None
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope",
                                   timeout=10)


def test_serve_cli_demo(tmp_path, capsys):
    """The `sparknet-serve` entry point end to end in --demo mode."""
    from sparknet_tpu.serve.app import main
    main(["--model", "lenet", "--outputs", "prob", "--max-batch", "4",
          "--demo", "12", "--workdir", str(tmp_path),
          "--heartbeat", str(tmp_path / "hb.json")])
    status = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert status["requests_ok"] == 12 and status["requests_failed"] == 0
    assert read_heartbeat(str(tmp_path / "hb.json"))["status"] == "done"


def test_future_type(server):
    assert isinstance(server.submit(_example(0)), Future)


def test_status_and_jsonl_carry_batch_size_hist(net, tmp_path):
    """The formed-batch size histogram (the bucket-ladder derivation
    input) lands in /status and — cumulative, with the model name — in
    the metrics JSONL at the metrics cadence."""
    from sparknet_tpu.serve import size_hist_from_jsonl
    from sparknet_tpu.utils.logger import Logger

    jsonl = str(tmp_path / "serve.jsonl")
    log = Logger(str(tmp_path / "l.txt"), echo=False, jsonl_path=jsonl)
    cfg = ServeConfig(max_batch=4, max_wait_ms=5.0, buckets=(1, 4),
                      outputs=("prob",), metrics_every_batches=1)
    with InferenceServer(net, cfg, logger=log) as srv:
        srv.infer(_example(0))                    # one size-1 batch
        for f in [srv.submit(_example(i)) for i in range(4)]:
            f.result(timeout=30.0)                # one size-4 batch
        st = srv.status()
        hist = st["batch_size_hist"]
        assert hist.get("1", 0) >= 1          # the lone first request
        # every real row is accounted for (burst formation may split)
        assert sum(int(k) * v for k, v in hist.items()) == 5
        assert sum(int(v) for v in hist.values()) == st["batches"]
        # the live meter agrees with the status copy
        assert srv.fill.size_hist() == {int(k): v
                                        for k, v in hist.items()}
    log.close()
    hists = size_hist_from_jsonl([jsonl])
    assert hists["default"] == {int(k): v for k, v in hist.items()}


def test_manager_loads_sharded_manifest_checkpoints(net, tmp_path):
    """r8: serve hot-swap reads SHARD-MANIFEST checkpoints — the layout
    training writes by default now — through the same restore_flat path,
    installing params bitwise equal to a monolithic save of the same
    state. (The manager never sees the layout: restore reassembles the
    exact flat map.)"""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from sparknet_tpu.parallel.mesh import (fetch_state_shards, make_mesh)

    mesh = make_mesh(4)
    want = {lname: {pname: np.asarray(w) * 0.5 for pname, w in lp.items()}
            for lname, lp in net.params.items()}
    tree = {"params": {
        lname: {pname: jax.device_put(w[None],
                                      NamedSharding(mesh, P()))
                for pname, w in lp.items()}
        for lname, lp in want.items()}}
    d = tmp_path / "ck"
    ckpt.save_sharded(str(d), fetch_state_shards(tree, mesh), step=7)
    meta = json.load(open(d / "step-7" / "meta.json"))
    assert "shards" in meta  # really the manifest layout
    m = ModelManager(net, checkpoint_dir=str(d))
    assert m.load_initial() == 7
    for lname, lp in want.items():
        for pname, w in lp.items():
            np.testing.assert_array_equal(
                np.asarray(m.net.params[lname][pname]), w,
                err_msg=f"{lname}/{pname}")


# -- r12 freshness-era poll behavior -----------------------------------------

def test_manager_store_outage_is_store_error_not_corrupt(net, tmp_path):
    """A store that stops answering mid-poll is TRANSIENT trouble: it
    lands under swaps_total{outcome="store_error"}, cools down NO step
    (the checkpoint is probably fine), raises no swap_failures (a fleet
    rollout must not read an outage as a rejection), and reschedules the
    poll with full-jitter backoff inside one interval."""
    from fake_stores import bucket_store, stop_serving

    from sparknet_tpu.obs import MetricsRegistry
    reg = MetricsRegistry()
    with bucket_store("gs") as (url, srv):
        d = f"{url}/ck"
        _save_trainstate_like(net, d, step=1)
        m = ModelManager(net, checkpoint_dir=d, poll_interval_s=5.0,
                         registry=reg)
        assert m.load_initial() == 1
        _save_trainstate_like(net, d, step=2)
        stop_serving(srv)
        t0 = time.monotonic()
        assert m.poll(now=t0) is False
    assert m.step == 1
    assert m.swap_failures == 0          # an outage is NOT a rejection
    assert m._bad == {}                  # and NO step went on cooldown
    assert 'outcome="store_error"} 1' in reg.render_prometheus()
    assert 'outcome="rejected"' not in reg.render_prometheus()
    # full-jitter: retry lands uniformly within ONE poll interval, not at
    # the bad_step_retry_s corruption cadence
    assert t0 <= m._next_poll <= t0 + 5.0


def test_manager_transient_load_error_then_same_step_installs(
        net, tmp_path, monkeypatch):
    """Store trouble during the checkpoint FETCH (listing worked) is
    classified the same way — and once the store answers again the very
    same step installs, because it was never cooled down."""
    d = tmp_path / "ck"
    _save_trainstate_like(net, d, step=1)
    m = ModelManager(net, checkpoint_dir=str(d), poll_interval_s=2.0)
    assert m.load_initial() == 1
    _save_trainstate_like(net, d, step=2)
    real, tries = ckpt.restore_flat, []

    def flaky(*a, **kw):
        if not tries:
            tries.append(1)
            raise TimeoutError("store busy")
        return real(*a, **kw)

    monkeypatch.setattr(ckpt, "restore_flat", flaky)
    t0 = time.monotonic()
    assert m.poll(now=t0) is False
    assert m.step == 1 and m.swap_failures == 0 and m._bad == {}
    assert "store" in m.last_error or "busy" in m.last_error
    assert t0 <= m._next_poll <= t0 + 2.0
    assert m.poll(now=m._next_poll + 1e-3) is True
    assert m.step == 2                   # no cooldown stood in the way


def test_poll_jitter_desynchronizes_replicas(net, tmp_path):
    """N replicas watching one store must not list it in lockstep: with
    poll_jitter set, one shared poll instant schedules N DISTINCT next
    polls, all within ±jitter of the interval. jitter=0 keeps the exact
    legacy cadence (back-compat default for ModelManager)."""
    d = tmp_path / "ck"
    _save_trainstate_like(net, d, step=1)
    mgrs = [ModelManager(net, checkpoint_dir=str(d), poll_interval_s=10.0,
                         poll_jitter=0.4) for _ in range(8)]
    for m in mgrs:
        m.poll(now=100.0)
    nexts = [m._next_poll for m in mgrs]
    assert all(106.0 <= t <= 114.0 for t in nexts)
    assert len(set(nexts)) >= 7          # spread, not lockstep
    legacy = ModelManager(net, checkpoint_dir=str(d), poll_interval_s=10.0)
    legacy.poll(now=100.0)
    assert legacy._next_poll == 110.0
    with pytest.raises(ValueError, match="poll_jitter"):
        ModelManager(net, checkpoint_dir=str(d), poll_jitter=1.0)


def test_poll_skips_torn_sharded_write_until_meta_commits(net, tmp_path):
    """Serve-side torn-checkpoint safety: a poll landing in the middle of
    a SHARDED save (array shards on disk, meta.json not yet) must treat
    the step as not-a-checkpoint — no install, no rejection, no cooldown.
    The moment the meta.json commit marker lands, the same poll path
    installs it whole."""
    import shutil

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from sparknet_tpu.parallel.mesh import fetch_state_shards, make_mesh

    d = tmp_path / "ck"
    _save_trainstate_like(net, d, step=1)
    m = ModelManager(net, checkpoint_dir=str(d), poll_interval_s=0.0)
    assert m.load_initial() == 1
    want = {ln: {pn: np.asarray(w) * 0.25 for pn, w in lp.items()}
            for ln, lp in net.params.items()}
    mesh = make_mesh(4)
    tree = {"params": {
        ln: {pn: jax.device_put(w[None], NamedSharding(mesh, P()))
             for pn, w in lp.items()}
        for ln, lp in want.items()}}
    stage = tmp_path / "stage"
    ckpt.save_sharded(str(stage), fetch_state_shards(tree, mesh), step=9)
    src, dst = stage / "step-9", d / "step-9"
    os.makedirs(dst)
    for f in os.listdir(src):
        if f != "meta.json":             # the commit marker stays out
            shutil.copy(src / f, dst / f)
    with pytest.warns(RuntimeWarning, match="meta.json"):
        assert m.poll() is False
    assert m.step == 1 and m.swap_failures == 0 and m._bad == {}
    shutil.copy(src / "meta.json", dst / "meta.json")
    assert m.poll() is True and m.step == 9
    for ln, lp in want.items():
        for pn, w in lp.items():
            np.testing.assert_array_equal(
                np.asarray(m.net.params[ln][pn]), w,
                err_msg=f"{ln}/{pn}")
