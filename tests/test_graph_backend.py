"""Second-backend tests — mirrors the reference's TensorFlowNetSpec
(`src/test/scala/libs/TensorFlowNetSpec.scala`): graph load, construction,
forward shapes, probabilities summing to 1, get/set weights roundtrip,
forward purity, step smoke test — plus serialization roundtrip and protocol
validation the reference never tested."""
import numpy as np
import pytest

from sparknet_tpu.backend import GraphBuilder, GraphDef, GraphNet, \
    build_mnist_graph
from sparknet_tpu.backend.graphdef import NodeDef, TRAIN_STEP, UPDATE_SUFFIX
from sparknet_tpu.model.weights import WeightCollection
from sparknet_tpu.schema import Field, Schema

BATCH = 8


@pytest.fixture(scope="module")
def mnist_graph():
    return build_mnist_graph(batch=BATCH)


@pytest.fixture(scope="module")
def batch(rng):
    return {"data": rng.standard_normal((BATCH, 28, 28, 1)).astype(np.float32),
            "label": rng.integers(0, 10, (BATCH, 1)).astype(np.int32)}


def test_serialize_roundtrip(mnist_graph, tmp_path):
    p = str(tmp_path / "g.json")
    mnist_graph.save(p)
    g2 = GraphDef.load(p)
    assert [n.name for n in g2.nodes] == [n.name for n in mnist_graph.nodes]
    np.testing.assert_array_equal(g2.node("conv1_w").attrs["init"],
                                  mnist_graph.node("conv1_w").attrs["init"])


def test_introspection(mnist_graph):
    net = GraphNet(mnist_graph)
    # inputs exclude //update_placeholder (TensorFlowNet.scala:24)
    assert set(net.input_names) == {"data", "label"}
    assert "conv1_w" in net.variable_names
    assert net._train_node is not None


def test_schema_validation_mismatch(mnist_graph):
    bad = Schema(Field("data", "float32", (28, 28, 1)))
    with pytest.raises(ValueError, match="graph inputs"):
        GraphNet(mnist_graph, schema=bad)


def test_forward_shapes_and_prob(mnist_graph, batch):
    net = GraphNet(mnist_graph)
    shapes = net.forward_shapes(["prob", "loss"])
    assert shapes["prob"] == (BATCH, 10)
    out = net.forward(batch, ["prob", "accuracy", "loss"])
    np.testing.assert_allclose(out["prob"].sum(-1), 1.0, rtol=1e-5)
    assert 0.0 <= out["accuracy"] <= 1.0


def test_forward_accepts_nchw(mnist_graph, batch):
    net = GraphNet(mnist_graph)
    nchw = {"data": np.transpose(batch["data"], (0, 3, 1, 2)),
            "label": batch["label"]}
    a = net.forward(batch, ["prob"])["prob"]
    b = net.forward(nchw, ["prob"])["prob"]
    np.testing.assert_allclose(a, b, rtol=1e-5)


def test_weights_roundtrip(mnist_graph):
    net = GraphNet(mnist_graph)
    w = net.get_weights()
    assert "conv1_w" in w and w["conv1_w"][0].shape == (5, 5, 1, 32)
    net2 = GraphNet(build_mnist_graph(batch=BATCH, seed=1))
    assert not WeightCollection.check_equal(w, net2.get_weights())
    net2.set_weights(w)
    assert WeightCollection.check_equal(w, net2.get_weights(), tol=0.0)


def test_forward_purity(mnist_graph, batch):
    """forward must not change weights (TensorFlowNetSpec.scala:104-118)."""
    net = GraphNet(mnist_graph)
    before = net.get_weights()
    net.forward(batch)
    assert WeightCollection.check_equal(before, net.get_weights(), tol=0.0)


def test_step_reduces_loss(mnist_graph, batch):
    net = GraphNet(mnist_graph)
    losses = [net.step(batch) for _ in range(10)]
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_unsupported_op_fails_loudly():
    g = GraphDef(name="bad", nodes=[
        __import__("sparknet_tpu.backend.graphdef",
                   fromlist=["NodeDef"]).NodeDef(
            name="x", op="Placeholder", attrs={"shape": [1], "dtype": "float32"}),
        __import__("sparknet_tpu.backend.graphdef",
                   fromlist=["NodeDef"]).NodeDef(
            name="y", op="FancyOp", inputs=["x"]),
    ])
    net = GraphNet(g)
    with pytest.raises(ValueError, match="FancyOp"):
        net.forward({"x": np.zeros((1,), np.float32)}, ["y"])


def test_incomplete_assign_pair_rejected(mnist_graph):
    nodes = [n for n in mnist_graph.nodes
             if n.name != "conv1_w" + UPDATE_SUFFIX]
    with pytest.raises(ValueError, match="incomplete"):
        GraphNet(GraphDef(name="m", nodes=nodes))


def test_output_schema(mnist_graph):
    net = GraphNet(mnist_graph)
    schema = net.output_schema()
    assert schema["prob"].shape == (10,)


def test_featurize_graph_backend(rng):
    """FeaturizerApp's hidden-blob extraction works against the serialized
    graph backend through the same NetInterface spelling (blob_names=)."""
    from sparknet_tpu.apps.featurizer_app import featurize
    from sparknet_tpu.backend import GraphNet, build_mnist_graph
    net = GraphNet(build_mnist_graph(batch=4))
    batch = {"data": rng.standard_normal((12, 28, 28, 1)).astype(np.float32),
             "label": rng.integers(0, 10, (12, 1)).astype(np.int32)}
    feats = featurize(net, batch, "flat", 4)
    assert feats.shape == (12, 7 * 7 * 64)
    probs = featurize(net, batch, "prob", 4)
    assert probs.shape == (12, 10)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-5)


def test_featurizer_app_graph_validation(tmp_path, rng):
    """The graph featurizer CLI fails fast on a dataset/graph size
    mismatch (CIFAR data into an MNIST-shaped graph), names missing
    inputs, and loads --weights into graph variables."""
    from sparknet_tpu.apps import featurizer_app
    from sparknet_tpu.backend import build_mnist_graph
    from sparknet_tpu.data import cifar

    d = str(tmp_path / "cifar")
    cifar.write_synthetic(d, n_per_file=10)
    gp = str(tmp_path / "mnist.json")
    build_mnist_graph(batch=5).save(gp)
    with pytest.raises(ValueError, match="per-example shape"):
        featurizer_app.main(["--data-dir", d, "--graph", gp,
                             "--blob", "flat", "--batch", "5"])


def test_deep_chain_graph_no_recursion_limit():
    """A 10k-node chain (an imported graph's depth is not ours to choose)
    must execute: the traversals are explicit-stack, not host-recursive —
    sys.getrecursionlimit() would kill a recursive visit at ~1k
    (r3 review item 7)."""
    import sys
    depth = 10_000
    assert depth > sys.getrecursionlimit()
    nodes = [NodeDef(name="data", op="Placeholder",
                     attrs={"shape": (2, 4), "dtype": "float32"}),
             NodeDef(name="c", op="Const",
                     attrs={"value": np.float32(1.0)})]
    prev = "data"
    for i in range(depth):
        nodes.append(NodeDef(name=f"n{i}", op="Add", inputs=[prev, "c"]))
        prev = f"n{i}"
    net = GraphNet(GraphDef(name="chain", nodes=nodes))
    # output discovery walks the whole chain (_evaluable) ...
    assert net.output_names() == [prev]
    # ... and execution topo-sorts it (_topo_order)
    out = net.forward({"data": np.zeros((2, 4), np.float32)}, [prev])
    np.testing.assert_allclose(np.asarray(out[prev]), float(depth))
