"""spkn-shm, the shared-memory local transport (serve/shm.py + the
FLAG_SHM wire surface):

  - `ShmRing` mechanics: slot reuse, resize-with-fresh-generation-name,
    full-ring -> None (inline fallback, never blocks), payload cap,
    close-unlinks.
  - the same-host proof: nonce file grants, wrong/missing/oversized
    nonce degrades to inline — a remote peer can never be granted shm.
  - orphan reclamation: a kill -9'd creator's segments are swept at the
    next frontend startup; live creators' segments are left alone.
  - end to end over a real frontend: ZERO tensor payload bytes cross
    the socket in either direction (pinned by byte counters on BOTH
    ends), results bitwise-identical to the inline wire, ring slots
    fully recycled after the burst.
  - capability fallback: a client denied shm (server disabled, or
    client opted out) serves inline transparently — same results, the
    payload bytes back on the socket.
  - wire-v2 peers still get the typed bad_version frame with shm
    enabled — capability negotiation never misparses an old peer.

Tier-1: CPU backend, lenet shapes, ephemeral ports.
"""
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.serve import (BinaryClient, BinaryFrontend,
                                InferenceServer, ServeConfig)
from sparknet_tpu.serve import shm, wire
from sparknet_tpu.zoo import lenet

pytestmark = pytest.mark.skipif(not shm.shm_available(),
                                reason="no POSIX shared memory")


def _example(i: int) -> dict:
    r = np.random.default_rng(7000 + i)
    return {"data": r.standard_normal((28, 28, 1)).astype(np.float32)}


@pytest.fixture(scope="module")
def net():
    return JaxNet(lenet(batch=4))


@pytest.fixture()
def srv(net):
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as s:
        yield s


# -- ring mechanics -----------------------------------------------------------

def test_ring_reuse_resize_full_and_cap():
    ring = shm.ShmRing(n_slots=2, slot_bytes=4096, max_bytes=1 << 20)
    try:
        # acquire-write-release-reacquire reuses the SAME segment
        name1, view1 = ring.acquire(100)
        view1[:3] = b"abc"
        assert ring.in_flight() == 1
        assert ring.release(name1)
        name2, _ = ring.acquire(100)
        assert name2 == name1  # recycled, not re-created
        ring.release(name2)

        # a payload over the slot size resizes: FRESH generation name,
        # old name becomes unknown to release (the resize race rule)
        name3, view3 = ring.acquire(8192)
        assert name3 != name1
        assert len(view3) == 8192
        assert not ring.release(name1)  # old name: quiet miss
        # both slots in flight -> None, the caller sends inline
        name4, _ = ring.acquire(10)
        assert ring.acquire(10) is None
        ring.release(name3)
        ring.release(name4)

        # payload over max_bytes never touches the ring
        assert ring.acquire((1 << 20) + 1) is None
    finally:
        ring.close()
    # closed ring: every acquire is an inline fallback
    assert ring.acquire(10) is None


def test_ring_close_unlinks_segments():
    ring = shm.ShmRing(n_slots=1, slot_bytes=4096)
    name, _ = ring.acquire(16)
    assert os.path.exists(f"/dev/shm/{name}")
    ring.release(name)
    ring.close()
    assert not os.path.exists(f"/dev/shm/{name}")


# -- the same-host proof ------------------------------------------------------

def test_nonce_grants_only_matching_bytes(tmp_path):
    path, nonce = shm.write_nonce(dir=str(tmp_path))
    assert shm.check_nonce(path, nonce)
    assert not shm.check_nonce(path, "not-the-nonce")
    assert not shm.check_nonce(path + ".gone", nonce)
    assert not shm.check_nonce(path, "")        # empty is never proof
    assert not shm.check_nonce(path, "x" * 300)  # oversized claim
    shm.cleanup_nonce(path)
    assert not os.path.exists(path)
    assert not shm.check_nonce(path, nonce)  # a swept proof is no proof


# -- orphan reclamation -------------------------------------------------------

def test_sweep_reclaims_kill9_orphan_spares_live(tmp_path):
    """A creator killed -9 (tracker cleanup simulated away, as when the
    whole process group dies) leaks its segment in /dev/shm; the startup
    sweep reclaims exactly that — a LIVE creator's segment survives."""
    child = subprocess.Popen(
        [sys.executable, "-c",
         "import os, sys, time\n"
         "from sparknet_tpu.serve import shm\n"
         "seg = shm._Segment(\n"
         "    name=f'{shm.SEG_PREFIX}_{os.getpid()}_dead_0g1',\n"
         "    create=True, size=4096)\n"
         "shm._untrack(seg.name)  # a kill -9 takes the tracker too\n"
         "print(seg.name, flush=True)\n"
         "time.sleep(120)\n"],
        stdout=subprocess.PIPE, text=True,
        env={**os.environ,
             "PYTHONPATH": os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))})
    try:
        orphan = child.stdout.readline().strip()
        assert orphan.startswith(shm.SEG_PREFIX)
        assert os.path.exists(f"/dev/shm/{orphan}")
    finally:
        child.kill()  # SIGKILL: no atexit, no unlink
        child.wait(timeout=10)
        child.stdout.close()

    live = shm.ShmRing(n_slots=1)
    live_name, _ = live.acquire(16)
    try:
        swept = shm.sweep_orphans()
        assert orphan in swept
        assert not os.path.exists(f"/dev/shm/{orphan}")
        assert live_name not in swept
        assert os.path.exists(f"/dev/shm/{live_name}")
    finally:
        live.release(live_name)
        live.close()


# -- end to end: zero payload bytes on the socket -----------------------------

def test_shm_transport_zero_socket_payload_both_directions(net, srv):
    bfe = BinaryFrontend(srv, port=0)
    assert bfe.enable_shm
    cli = BinaryClient(*bfe.address, use_shm=True)
    try:
        assert cli._shm_granted is True
        xs = [_example(i) for i in range(8)]
        outs = [cli.infer(x, model="default", deadline_s=30.0)
                for x in xs]
        # the pin: zero tensor payload bytes crossed the shm
        # connection's socket, measured on BOTH ends (snapshot the
        # frontend counters BEFORE the inline reference client below
        # shares them)
        assert cli.payload_tx_bytes == 0
        assert cli.payload_rx_bytes == 0
        assert bfe.payload_rx_bytes == 0
        assert bfe.payload_tx_bytes == 0
        # results match the inline wire bitwise
        ref = BinaryClient(*bfe.address, use_shm=False)
        try:
            for x, out in zip(xs, outs):
                inline = ref.infer(x, model="default", deadline_s=30.0)
                np.testing.assert_array_equal(out["prob"],
                                              inline["prob"])
        finally:
            ref.close()
        # queue-wait rides the response meta
        qw = cli.last_timing["queue_wait_ms"]
        assert qw is not None and qw >= 0.0
        # every ring slot recycled once the burst drained
        assert cli._ring.in_flight() == 0
    finally:
        cli.close()
        bfe.stop()


def test_shm_denied_by_server_falls_back_inline(net, srv):
    """`enable_shm=False` on the frontend: the client's SHM_HELLO is
    answered with a denial, and every request serves inline — same
    results, payload bytes back on the socket."""
    bfe = BinaryFrontend(srv, port=0, enable_shm=False)
    cli = BinaryClient(*bfe.address, use_shm=True)
    try:
        assert cli._shm_granted is False
        assert cli._ring is None
        out = cli.infer(_example(0), model="default", deadline_s=30.0)
        assert out["prob"].shape == (10,)
        nbytes = 28 * 28 * 4
        assert cli.payload_tx_bytes == nbytes
        assert bfe.payload_rx_bytes == nbytes
        assert cli.payload_rx_bytes > 0  # reply payload came inline too
    finally:
        cli.close()
        bfe.stop()


def test_shm_client_optout_never_handshakes(net, srv):
    bfe = BinaryFrontend(srv, port=0)
    cli = BinaryClient(*bfe.address, use_shm=False)
    try:
        assert cli._shm_granted is None  # no HELLO ever sent
        out = cli.infer(_example(1), model="default", deadline_s=30.0)
        assert out["prob"].shape == (10,)
        assert cli.payload_tx_bytes == 28 * 28 * 4
    finally:
        cli.close()
        bfe.stop()


def test_frontend_startup_sweeps_orphans(net, srv):
    """The frontend's constructor runs the orphan sweep before serving:
    a dead-pid segment planted in /dev/shm is gone once the frontend is
    up, and its name is reported in `swept_segments`."""
    # plant an orphan under a pid that cannot be alive (pid 1 is init,
    # alive - use a dead child's pid)
    child = subprocess.Popen([sys.executable, "-c", "pass"])
    child.wait(timeout=30)
    name = f"{shm.SEG_PREFIX}_{child.pid}_plant_0g1"
    seg = shm._Segment(name=name, create=True, size=4096)
    shm._untrack(name)
    seg.close()
    assert os.path.exists(f"/dev/shm/{name}")
    bfe = BinaryFrontend(srv, port=0)
    try:
        assert name in bfe.swept_segments
        assert not os.path.exists(f"/dev/shm/{name}")
    finally:
        bfe.stop()


# -- old peers ----------------------------------------------------------------

def test_v2_frame_gets_typed_bad_version_with_shm_enabled(net, srv):
    """A wire-v2 peer (pre-shm protocol) against an shm-enabled
    frontend: typed bad_version error frame, connection closed, server
    keeps serving — never a misparse into the shm surface."""
    bfe = BinaryFrontend(srv, port=0)
    assert bfe.enable_shm
    try:
        head, _ = wire.pack_request(1, "default", {})
        s = socket.create_connection(bfe.address, timeout=10)
        s.sendall(head[:4] + bytes([2]) + head[5:])
        s.settimeout(10.0)
        buf = b""
        while len(buf) < wire.HEADER_LEN:
            d = s.recv(4096)
            assert d, "server closed without the typed frame"
            buf += d
        ftype, flags, rid, meta_len, plen = wire.parse_header(buf)
        while len(buf) < wire.HEADER_LEN + meta_len + plen:
            buf += s.recv(4096)
        code, kind, _ = wire.unpack_error_meta(
            buf[wire.HEADER_LEN:wire.HEADER_LEN + meta_len])
        assert ftype == wire.T_ERROR and (code, kind) == \
            (400, "bad_version")
        assert s.recv(4096) == b""
        s.close()
        out = BinaryClient(*bfe.address, use_shm=True)
        try:
            assert out.infer(_example(2), model="default",
                             deadline_s=30.0)["prob"].shape == (10,)
        finally:
            out.close()
    finally:
        bfe.stop()
