"""CompiledNet / JaxNet tests — mirrors the reference's CaffeNetSpec
(`src/test/scala/libs/CaffeNetSpec.scala`): construction, forward output
schema/shapes, forward purity (weights unchanged), save->load roundtrip —
plus gradient checks the reference never had.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu import CompiledNet, net_from_prototxt
from sparknet_tpu.model.caffe_compat import (collection_to_params,
                                             params_to_collection)
from sparknet_tpu.model.weights import WeightCollection
from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.solver import SolverConfig
from tests.test_prototxt import ADULT

CIFARISH = """
name: "tiny_cifar"
input: "data"
input_shape { dim: 4 dim: 3 dim: 16 dim: 16 }
input: "label"
input_shape { dim: 4 dim: 1 }
layer {
  name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  param { lr_mult: 1 } param { lr_mult: 2 }
  convolution_param {
    num_output: 8 pad: 2 kernel_size: 5 stride: 1
    weight_filler { type: "gaussian" std: 0.01 }
    bias_filler { type: "constant" }
  }
}
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
        pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
layer { name: "relu1" type: "ReLU" bottom: "pool1" top: "pool1" }
layer { name: "ip1" type: "InnerProduct" bottom: "pool1" top: "ip1"
        inner_product_param { num_output: 10
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "prob" type: "Softmax" bottom: "ip1" top: "prob" }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip1" bottom: "label" top: "loss" }
layer { name: "acc" type: "Accuracy" bottom: "ip1" bottom: "label" top: "acc" }
"""


@pytest.fixture(scope="module")
def tiny_net():
    return CompiledNet.compile(net_from_prototxt(CIFARISH))


def test_shapes_and_outputs(tiny_net):
    assert tiny_net.input_shapes["data"] == (4, 16, 16, 3)
    assert tiny_net.blob_shapes["conv1"] == (4, 16, 16, 8)
    assert tiny_net.blob_shapes["pool1"] == (4, 8, 8, 8)
    assert tiny_net.blob_shapes["prob"] == (4, 10)
    assert set(tiny_net.output_names) == {"prob", "loss", "acc"}


def test_forward_probabilities_sum_to_one(tiny_net):
    params = tiny_net.init_params(jax.random.PRNGKey(0))
    blobs = tiny_net.apply(params, tiny_net.example_batch())
    probs = np.asarray(blobs["prob"])
    np.testing.assert_allclose(probs.sum(-1), 1.0, rtol=1e-5)
    assert (probs >= 0).all()


def test_forward_purity(tiny_net):
    """forward/forwardBackward must not mutate weights
    (CaffeNetSpec.scala:48-70)."""
    net = JaxNet(net_from_prototxt(CIFARISH), solver=SolverConfig(base_lr=0.1))
    before = net.get_weights()
    batch = {k: np.asarray(v) for k, v in net.net.example_batch().items()}
    net.forward(batch)
    net.forward_backward(batch)
    after = net.get_weights()
    assert WeightCollection.check_equal(before, after, tol=0.0)
    net.step(batch)
    stepped = net.get_weights()
    assert not WeightCollection.check_equal(before, stepped, tol=1e-9)


def test_weight_roundtrip(tiny_net, tmp_path):
    """save -> load roundtrip preserves weights exactly
    (CaffeNetSpec.scala:72-82)."""
    net = JaxNet(net_from_prototxt(CIFARISH), seed=3)
    path = str(tmp_path / "w.npz")
    net.save_weights(path)
    net2 = JaxNet(net_from_prototxt(CIFARISH), seed=7)
    assert not WeightCollection.check_equal(net.get_weights(),
                                            net2.get_weights())
    net2.load_weights(path)
    assert WeightCollection.check_equal(net.get_weights(), net2.get_weights(),
                                        tol=0.0)


def test_caffe_layout_roundtrip(tiny_net):
    params = tiny_net.init_params(jax.random.PRNGKey(1))
    coll = params_to_collection(tiny_net, params)
    # Caffe layouts: conv OIHW, ip (out, in)
    assert coll["conv1"][0].shape == (8, 3, 5, 5)
    assert coll["ip1"][0].shape == (10, 8 * 8 * 8)
    back = collection_to_params(tiny_net, coll)
    for lname, lp in params.items():
        for pname, w in lp.items():
            np.testing.assert_array_equal(np.asarray(w),
                                          np.asarray(back[lname][pname]))


def test_adult_net_forward():
    net = JaxNet(net_from_prototxt(ADULT))
    batch = {"C0": np.random.default_rng(0).standard_normal(
        (64, 1), dtype=np.float32)}
    out = net.forward(batch)
    assert out["prob"].shape == (64, 10)
    np.testing.assert_allclose(out["prob"].sum(-1), 1.0, rtol=1e-5)


def test_output_schema(tiny_net):
    net = JaxNet(net_from_prototxt(CIFARISH))
    schema = net.output_schema()
    assert schema["prob"].shape == (10,)
    assert schema["loss"].shape == ()


def test_gradients_flow(tiny_net):
    params = tiny_net.init_params(jax.random.PRNGKey(0))
    batch = tiny_net.example_batch()
    grads = jax.grad(lambda p: tiny_net.apply(p, batch, train=True,
                                              rng=jax.random.PRNGKey(1))["loss"]
                     )(params)
    norms = [float(jnp.linalg.norm(g)) for lp in grads.values()
             for g in lp.values()]
    assert all(np.isfinite(norms)) and sum(norms) > 0


def test_hidden_blob_extraction(tiny_net):
    """FeaturizerApp parity: request a hidden blob by name
    (apps/FeaturizerApp.scala:91-94)."""
    net = JaxNet(net_from_prototxt(CIFARISH))
    batch = {k: np.asarray(v) for k, v in net.net.example_batch().items()}
    out = net.forward(batch, blob_names=["ip1"])
    assert out["ip1"].shape == (4, 10)


def test_space_to_depth_conv_exact(rng):
    """The stride-s space-to-depth conv rewrite (image-stem convs like
    CaffeNet conv1) computes the same contraction as the direct
    convolution — same products, channel-grouped summation order — so
    forward values and weight gradients agree to f32 accumulation noise,
    odd and even geometries."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from sparknet_tpu.model.layers import apply_convolution, ApplyCtx
    from sparknet_tpu.model.spec import ConvolutionParam, LayerSpec

    for h, k, s in [(227, 11, 4), (224, 7, 2), (65, 5, 3)]:
        layer = LayerSpec(name="c", type="Convolution", bottoms=("x",),
                          tops=("y",),
                          conv=ConvolutionParam(num_output=32, kernel_size=k,
                                                stride=s, pad=0))
        x = rng.standard_normal((2, h, h, 3)).astype(np.float32)
        w = (0.1 * rng.standard_normal((k, k, 3, 32))).astype(np.float32)

        def direct(w, x):
            return lax.conv_general_dilated(
                x, w, (s, s), "VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                precision=lax.Precision.HIGHEST)

        def rewritten(w, x):
            (y,) = apply_convolution(layer, {"w": jnp.asarray(w)},
                                     (jnp.asarray(x),), ApplyCtx())
            return y

        y_d = direct(jnp.asarray(w), jnp.asarray(x))
        y_r = rewritten(w, x)
        assert y_r.shape == y_d.shape, (h, k, s)
        np.testing.assert_allclose(np.asarray(y_r), np.asarray(y_d),
                                   rtol=1e-4, atol=1e-4)
        g_d = jax.grad(lambda w: (direct(w, jnp.asarray(x)) ** 2).sum())(
            jnp.asarray(w))
        g_r = jax.grad(lambda w: (rewritten(w, x) ** 2).sum())(
            jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(g_r), np.asarray(g_d),
                                   rtol=1e-4, atol=1e-2)


def test_space_to_depth_gate():
    """Padded / grouped / stride-1 / wide-channel convs keep the direct
    form."""
    from sparknet_tpu.model.layers import _s2d_eligible
    from sparknet_tpu.model.spec import ConvolutionParam
    ok = ConvolutionParam(num_output=96, kernel_size=11, stride=4, pad=0)
    assert _s2d_eligible(ok, 3)
    import dataclasses
    assert not _s2d_eligible(dataclasses.replace(ok, pad=1), 3)
    assert not _s2d_eligible(dataclasses.replace(ok, group=2), 3)
    assert not _s2d_eligible(dataclasses.replace(ok, stride=1), 3)
    assert not _s2d_eligible(ok, 64)  # 64*16 channels: already MXU-friendly
