"""The fleet control plane (sparknet_tpu/fleet/) + its admission and
router substrate:

  - priority-aware admission: classes, weighted tenant budgets,
    pressure-driven tightening; the tenant-table churn hygiene
    (bounded under a tenant-id sweep, fresh burst after eviction).
  - policy units: SLO burn, hot/cold verdicts, the pressure curve,
    construction-time validation.
  - router fairness: a drained-then-undrained replica resumes its
    round-robin share, and a FLAPPING replica is never parity-starved
    (the rotation-index fix); live pool resizing.
  - heartbeat-health demotion END TO END: a remote replica over the
    binary transport whose beat goes stale mid-traffic is routed
    around within stale_after_s and rejoins when beats resume.
  - FleetController: grow on SLO burn (audit-named), shrink via drain
    with zero dropped, dead-replica eviction + replacement, min-bound
    enforcement, admission pressure threading, /fleet/status.
  - both frontends shed low-priority traffic TYPED under pressure
    (X-Priority header / binary priority field, reason="priority").

Tier-1: CPU backend, lenet shapes, ephemeral ports, no subprocess
spawns (the subprocess provider runs in bench.py --fleet; here an
in-process provider keeps the suite fast).
"""
import json
import threading
import time
import urllib.request
from dataclasses import replace

import numpy as np
import pytest

from sparknet_tpu.fleet import (FleetConfig, FleetController, FleetPolicy,
                                ModelSignals, PodReplicaProvider,
                                ReplicaHandle, ReplicaProvider, slo_burn)
from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.serve import (BinaryFrontend, HttpFrontend,
                                InferenceServer, ModelRouter,
                                PriorityAdmission, PriorityShedError,
                                Replica, RouterConfig, ServeConfig,
                                TenantAdmission, binary_infer,
                                http_infer, parse_priority)
from sparknet_tpu.utils.heartbeat import HeartbeatWriter
from sparknet_tpu.zoo import lenet

SLO_MS = 50.0


def _example(i: int) -> dict:
    r = np.random.default_rng(7000 + i)
    return {"data": r.standard_normal((28, 28, 1)).astype(np.float32)}


def _lane_cfg(name: str = "m") -> ServeConfig:
    return ServeConfig(model_name=name, max_batch=4, max_wait_ms=2.0,
                       outputs=("prob",), slo_p99_ms=SLO_MS,
                       metrics_every_batches=0)


class InProcessProvider(ReplicaProvider):
    """Grow = a fresh InferenceServer + BinaryFrontend in THIS process
    (the subprocess provider's spawn cost without the subprocess)."""

    def __init__(self):
        self.spawned = []          # (server, frontend, handle)
        self.retired = []
        self._dead = set()

    def grow(self, model: str) -> ReplicaHandle:
        srv = InferenceServer(JaxNet(lenet(batch=4)),
                              _lane_cfg(model)).start()
        fe = BinaryFrontend(srv, port=0)
        h = ReplicaHandle(model,
                          f"spkn://{fe.address[0]}:{fe.address[1]}",
                          meta={"i": len(self.spawned)})
        self.spawned.append((srv, fe, h))
        return h

    def kill(self, handle: ReplicaHandle) -> None:
        """The in-process kill -9: the frontend stops answering and
        alive() flips false."""
        self._dead.add(handle.meta["i"])
        srv, fe, _ = self.spawned[handle.meta["i"]]
        fe.stop()

    def retire(self, handle: ReplicaHandle) -> None:
        self.retired.append(handle.meta["i"])

    def alive(self, handle: ReplicaHandle) -> bool:
        return handle.meta["i"] not in self._dead

    def stop(self) -> None:
        for srv, fe, h in self.spawned:
            if h.meta["i"] not in self._dead:
                fe.stop()
            srv.stop()


def _controller(router, provider=None, admission=None, logger=None,
                **over) -> FleetController:
    kw = dict(interval_s=0.05, window_s=30.0, min_replicas=1,
              max_replicas=2, up_cooldown_s=0.0, down_cooldown_s=0.0,
              drain_grace_s=0.0, dead_ticks=2,
              policy=FleetPolicy(up_ticks=2, down_ticks=3,
                                 min_window_n=8))
    kw.update(over)
    return FleetController(router, provider=provider,
                           cfg=FleetConfig(**kw), admission=admission,
                           logger=logger)


def _burn(router, model: str, n: int = 32, seconds: float = 0.2):
    """Inject a burning tail into the router-vantage latency window."""
    for _ in range(n):
        router.latency[model].add(seconds)


# -- admission: priority classes + weighted budgets ---------------------------

def test_parse_priority_degrades_unknown_to_normal():
    assert parse_priority("high") == "high"
    assert parse_priority(" LOW ") == "low"
    assert parse_priority(None) == "normal"
    assert parse_priority("argh") == "normal"


def test_tenant_churn_table_bounded_and_fresh_burst_after_eviction():
    """The admission-hygiene satellite: thousands of distinct tenants
    sweeping through must not grow the table past max_tenants, and an
    evicted-then-returning tenant gets a FRESH full burst — never a
    stale empty bucket left from its previous life."""
    a = TenantAdmission(rate_rps=0.001, burst=3.0, max_tenants=128)
    # drain tenant t0 to empty (burst 3, negligible refill)
    for _ in range(3):
        assert a.allow("t0")
    assert not a.allow("t0")  # bucket empty now
    # a 5000-tenant sweep churns t0 out
    for i in range(5000):
        a.allow(f"sweep-{i}")
        assert a.tracked_tenants() <= 128
    assert "t0" not in a.snapshot()
    # the returning tenant starts from a FULL burst: 3 admits, then shed
    for _ in range(3):
        assert a.allow("t0"), "evicted tenant did not get a fresh burst"
    assert not a.allow("t0")
    assert abs(a.snapshot()["t0"]) < 0.01


def test_weighted_tenant_gets_scaled_rate_and_burst():
    a = PriorityAdmission(rate_rps=10.0, burst=2.0,
                          weights={"vip": 2.0, "cheap": 0.5})
    assert a._rate_for("vip") == 20.0   # 10 * weight 2.0, no pressure
    assert a._rate_for("cheap") == 5.0
    assert a._burst_for("vip") == 4.0
    assert a._burst_for("cheap") == 1.0
    assert a._burst_for("unknown") == 2.0
    # a fresh weighted bucket opens at ITS burst: vip admits 4 straight
    for _ in range(4):
        assert a.admit("vip") is None
    assert a.admit("vip") == "tenant_limit"
    # the churn rule survives the weighting: evict + return = full burst
    a2 = PriorityAdmission(rate_rps=0.001, burst=2.0,
                           weights={"vip": 2.0}, max_tenants=8)
    for _ in range(4):
        assert a2.admit("vip") is None
    assert a2.admit("vip") == "tenant_limit"
    for i in range(64):
        a2.admit(f"sweep-{i}")
    for _ in range(4):
        assert a2.admit("vip") is None, "stale bucket after eviction"


def test_priority_sheds_low_first_under_pressure():
    a = PriorityAdmission()  # no tenant buckets: pure priority door
    for cls in ("high", "normal", "low"):
        assert a.admit("t", cls) is None  # no pressure: all admitted
    a.set_pressure(0.6)
    assert a.admit("t", "low") == "priority"
    assert a.admit("t", "normal") is None
    assert a.admit("t", "high") is None
    a.set_pressure(0.95)
    assert a.admit("t", "low") == "priority"
    assert a.admit("t", "normal") == "priority"
    assert a.admit("t", "high") is None  # high never pressure-shed
    assert a.shed_priority == 3


def test_pressure_tightens_refill_toward_floor():
    a = PriorityAdmission(rate_rps=10.0, tighten=0.8, rate_floor=0.1)
    assert a._rate_for("t") == 10.0
    a.set_pressure(1.0)
    assert abs(a._rate_for("t") - 2.0) < 1e-9   # 10 * (1 - 0.8)
    b = PriorityAdmission(rate_rps=10.0, tighten=1.0, rate_floor=0.25)
    b.set_pressure(1.0)
    assert abs(b._rate_for("t") - 2.5) < 1e-9   # clamped at the floor


def test_admission_validation_fails_at_construction():
    with pytest.raises(ValueError, match="weights"):
        PriorityAdmission(rate_rps=1.0, weights={"t": -1.0})
    with pytest.raises(ValueError, match="priority class"):
        PriorityAdmission(shed_at={"urgent": 0.5})
    with pytest.raises(ValueError, match="tighten"):
        PriorityAdmission(tighten=1.5)
    with pytest.raises(ValueError, match="rate must be > 0"):
        TenantAdmission(rate_rps=0.0)


# -- policy units -------------------------------------------------------------

def test_slo_burn_edges():
    assert slo_burn(None, 50.0) == 0.0
    assert slo_burn(100.0, None) == 0.0
    assert slo_burn(100.0, 50.0) == 2.0


def _sig(**over) -> ModelSignals:
    kw = dict(model="m", p99_ms=None, slo_p99_ms=SLO_MS, n_window=100,
              queue_frac=0.0, shed_per_s=0.0, replicas=1, routable=1)
    kw.update(over)
    return ModelSignals(**kw)


def test_policy_hot_reasons_and_window_gate():
    p = FleetPolicy()
    assert p.hot_reason(_sig(p99_ms=2 * SLO_MS)) == "slo_burn"
    # a near-empty window's p99 is noise, not a scale-up signal
    assert p.hot_reason(_sig(p99_ms=2 * SLO_MS, n_window=3)) is None
    assert p.hot_reason(_sig(queue_frac=0.9)) == "queue"
    assert p.hot_reason(_sig(shed_per_s=5.0)) == "shed"
    assert p.hot_reason(_sig(p99_ms=0.5 * SLO_MS)) is None


def test_policy_cold_requires_every_margin():
    p = FleetPolicy()
    assert p.is_cold(_sig(p99_ms=0.2 * SLO_MS))
    assert p.is_cold(_sig())  # idle model (no p99) IS cold
    assert not p.is_cold(_sig(queue_frac=0.3))
    assert not p.is_cold(_sig(p99_ms=0.9 * SLO_MS))


def test_policy_pressure_curve():
    p = FleetPolicy(pressure_start=1.0, pressure_full=2.0)
    assert p.pressure_from_burn(0.5) == 0.0
    assert abs(p.pressure_from_burn(1.5) - 0.5) < 1e-9
    assert p.pressure_from_burn(3.0) == 1.0


def test_policy_and_config_validate_at_construction():
    with pytest.raises(ValueError, match="burn_down"):
        FleetPolicy(burn_up=1.0, burn_down=1.0)
    with pytest.raises(ValueError, match="pressure_full"):
        FleetPolicy(pressure_start=1.0, pressure_full=1.0)
    with pytest.raises(ValueError, match="min_replicas"):
        FleetConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="interval_s"):
        FleetConfig(interval_s=0.0)


# -- router fairness (the rotation-index satellite) ---------------------------

def _bare_router_with_remotes(n: int = 3):
    router = ModelRouter(RouterConfig(workers=1))
    reps = [Replica(f"r{i}", url=f"http://h{i}:1") for i in range(n)]
    router.replicas["m"] = reps
    router._rr["m"] = -1
    return router, reps


def test_drained_then_undrained_replica_resumes_round_robin_share():
    router, (r0, r1, r2) = _bare_router_with_remotes(3)
    picks = [router._pick("m").name for _ in range(30)]
    assert all(picks.count(r.name) == 10 for r in (r0, r1, r2))
    r1.drain()
    picks = [router._pick("m").name for _ in range(20)]
    assert picks.count("r1") == 0
    assert picks.count("r0") == picks.count("r2") == 10
    r1.undrain()
    picks = [router._pick("m").name for _ in range(30)]
    # the returning replica resumes its FULL share — no permanent skew
    assert all(picks.count(r.name) == 10 for r in (r0, r1, r2)), picks


def test_flapping_replica_is_never_parity_starved():
    """The regression the rotation-index fix exists for: with the old
    count-modulo over the FILTERED healthy list, a replica whose
    health flaps in step with the pick parity is starved FOREVER
    (len alternates 2/1, the counter advances 2 between len-2 picks,
    the modulo parity never reaches it)."""
    router, (r0, r1) = _bare_router_with_remotes(2)
    got_r1 = 0
    undrained_picks = 0
    for i in range(20):
        if i % 2:
            r1.drain()
        else:
            r1.undrain()
            undrained_picks += 1
        if router._pick("m").name == "r1":
            got_r1 += 1
    assert undrained_picks == 10
    assert got_r1 >= 8, (f"flapping replica starved: picked {got_r1} "
                         f"of {undrained_picks} available turns")


def test_pool_resize_live():
    router = ModelRouter(RouterConfig(workers=1))
    router.add_model("m", JaxNet(lenet(batch=4)), cfg=_lane_cfg())
    with router:
        router.infer("m", _example(0), timeout=30.0)
        assert router.pool_size() == 1
        router.set_pool_size(3)
        deadline = time.monotonic() + 5
        while router.pool_size() < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert router.pool_size() == 3
        router.infer("m", _example(1), timeout=30.0)
        router.set_pool_size(1)
        deadline = time.monotonic() + 5
        while router.pool_size() > 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.pool_size() == 1
        # a shrunk pool still serves
        out = router.infer("m", _example(2), timeout=30.0)
        assert out["prob"].shape == (10,)


# -- heartbeat-health demotion, end to end over the binary wire ---------------

def test_stale_heartbeat_routes_around_then_rejoins(tmp_path):
    """The two-replica e2e satellite: mid-traffic, the remote replica's
    heartbeat goes stale -> the router demotes it within stale_after_s
    (NEW requests all land on the local lane); beats resume -> the
    replica rejoins the rotation. All through the real binary
    transport."""
    hb_path = str(tmp_path / "replica.heartbeat.json")
    hb = HeartbeatWriter(hb_path, role="serve", interval_s=0.0)
    hb.beat(1, force=True)
    rb = ModelRouter(RouterConfig(workers=1))
    rb.add_model("m", JaxNet(lenet(batch=4)), cfg=_lane_cfg())
    ra = ModelRouter(RouterConfig(workers=1, stale_after_s=0.6,
                                  health_refresh_s=0.05))
    ra.add_model("m", JaxNet(lenet(batch=4)), cfg=_lane_cfg())
    with rb:
        fe_b = BinaryFrontend(rb, port=0)
        try:
            with ra:
                rep = ra.add_remote_replica(
                    "m", f"spkn://{fe_b.address[0]}:{fe_b.address[1]}",
                    heartbeat_path=hb_path)
                routed = ra.registry.counter(
                    "sparknet_serve_routed_total",
                    labels=("model", "replica"))

                def remote_count():
                    return routed.value(model="m",
                                        replica=rep.name) or 0

                for i in range(6):
                    hb.beat(1, force=True)
                    ra.infer("m", _example(i), timeout=30.0)
                assert remote_count() >= 2  # rotation includes remote
                # beats STOP: within stale_after_s (+ probe refresh) the
                # replica must become unroutable
                t0 = time.monotonic()
                while ra._replica_routable(rep) and \
                        time.monotonic() - t0 < 3.0:
                    time.sleep(0.05)
                detect_s = time.monotonic() - t0
                assert not ra._replica_routable(rep), \
                    "stale heartbeat never demoted the replica"
                assert detect_s <= 1.5, f"demotion took {detect_s:.2f}s"
                before = remote_count()
                for i in range(6):
                    out = ra.infer("m", _example(10 + i), timeout=30.0)
                    assert out["prob"].shape == (10,)
                assert remote_count() == before, \
                    "stale replica still received new routing"
                # beats RESUME: the replica rejoins
                hb.beat(2, force=True)
                t0 = time.monotonic()
                while not ra._replica_routable(rep) and \
                        time.monotonic() - t0 < 3.0:
                    hb.beat(2, force=True)
                    time.sleep(0.05)
                assert ra._replica_routable(rep)
                for i in range(6):
                    hb.beat(2, force=True)
                    ra.infer("m", _example(20 + i), timeout=30.0)
                assert remote_count() > before, \
                    "recovered replica never rejoined the rotation"
        finally:
            fe_b.stop()


# -- the controller -----------------------------------------------------------

@pytest.fixture()
def fleet_router():
    router = ModelRouter(RouterConfig(workers=1, stale_after_s=0.6,
                                      health_refresh_s=0.02,
                                      conn_fail_cooldown_s=0.2))
    router.add_model("m", JaxNet(lenet(batch=4)), cfg=_lane_cfg())
    provider = InProcessProvider()
    with router:
        router.infer("m", _example(0), timeout=30.0)
        yield router, provider
    provider.stop()


def test_controller_grows_on_slo_burn_with_named_audit(fleet_router):
    router, provider = fleet_router
    fc = _controller(router, provider)
    fc.tick()
    assert len(router.replicas["m"]) == 1  # quiet: nothing to do
    _burn(router, "m")
    fc.tick()
    assert len(router.replicas["m"]) == 1  # hysteresis: 1 hot tick
    fc.tick()
    assert len(router.replicas["m"]) == 2  # up_ticks=2 satisfied
    ev = fc.audit[-1]
    assert (ev["direction"], ev["reason"]) == ("up", "slo_burn")
    assert ev["replica"].startswith("remote:spkn://")
    g = router.registry.gauge("sparknet_fleet_replicas",
                              labels=("model",))
    assert g.value(model="m") == 2
    c = router.registry.counter(
        "sparknet_fleet_scale_events_total",
        labels=("model", "direction", "reason"))
    assert c.value(model="m", direction="up", reason="slo_burn") == 1
    # bounded: still-burning traffic cannot exceed max_replicas
    _burn(router, "m")
    for _ in range(4):
        fc.tick()
    assert len(router.replicas["m"]) == 2
    # the grown replica actually serves
    for i in range(4):
        out = router.infer("m", _example(i), timeout=30.0)
        assert out["prob"].shape == (10,)
    fc.stop()


def test_controller_shrinks_via_drain_zero_dropped(fleet_router):
    router, provider = fleet_router
    fc = _controller(router, provider)
    _burn(router, "m")
    fc.tick()
    fc.tick()
    assert len(router.replicas["m"]) == 2
    router.latency["m"].reset()  # traffic goes quiet
    # keep a trickle flowing THROUGH the shrink: zero dropped required
    errors, answered = [], []

    def trickle():
        for i in range(12):
            try:
                answered.append(router.infer("m", _example(i),
                                             timeout=30.0))
            except Exception as e:
                errors.append(e)
            time.sleep(0.02)
    tt = threading.Thread(target=trickle)
    tt.start()
    deadline = time.monotonic() + 10
    while len(router.replicas["m"]) > 1 and \
            time.monotonic() < deadline:
        fc.tick()
        time.sleep(0.05)
    tt.join(timeout=30.0)
    assert len(router.replicas["m"]) == 1
    assert provider.retired, "provider never retired the drained child"
    assert not errors, f"shrink dropped requests: {errors[:3]}"
    assert len(answered) == 12
    downs = [a for a in fc.audit if a["direction"] == "down"]
    assert downs and downs[-1]["reason"] == "quiet"
    fc.stop()


def test_controller_replaces_dead_replica_and_names_it(fleet_router):
    router, provider = fleet_router
    fc = _controller(router, provider, max_replicas=3)
    _burn(router, "m")
    fc.tick()
    fc.tick()
    assert len(router.replicas["m"]) == 2
    victim_rep, victim_handle = fc._owned["m"][0]
    provider.kill(victim_handle)          # the in-process kill -9
    fc.tick()                             # proc-dead: evict + replace
    assert victim_rep.name not in [r.name for r in
                                   router.replicas["m"]]
    reasons = [(a["direction"], a["reason"]) for a in fc.audit]
    assert ("down", "dead") in reasons
    assert ("up", "replace") in reasons
    dead_ev = next(a for a in fc.audit if a["reason"] == "dead")
    assert dead_ev["replica"] == victim_rep.name  # eviction is NAMED
    assert len(router.replicas["m"]) == 2  # replacement restored size
    for i in range(4):
        out = router.infer("m", _example(i), timeout=30.0)
        assert out["prob"].shape == (10,)
    fc.stop()


def test_controller_enforces_min_replicas(fleet_router):
    router, provider = fleet_router
    fc = _controller(router, provider, min_replicas=2, max_replicas=3)
    fc.tick()  # no burn needed: the floor is not a load decision
    assert len(router.replicas["m"]) == 2
    assert fc.audit[-1]["reason"] == "min_bound"
    fc.stop()


def test_controller_pool_lever_from_queue_pressure(fleet_router):
    router, provider = fleet_router
    fc = _controller(router, None, pool_min=1, pool_max=3)
    hot = _sig(queue_frac=0.9)
    fc._signals = lambda model, dt: hot  # craft signal, keep the loop
    fc.tick()
    fc.tick()
    assert router._pool_target == 2
    assert fc.audit[-1] == {**fc.audit[-1], "model": "_pool",
                            "direction": "up", "reason": "queue"}
    quiet = _sig(queue_frac=0.0)
    fc._signals = lambda model, dt: quiet
    for _ in range(4):
        fc.tick()
    assert router._pool_target == 1
    fc.stop()


def test_controller_pressure_threads_to_admission_door(fleet_router):
    router, provider = fleet_router
    admission = PriorityAdmission()
    fc = _controller(router, None, admission=admission,
                     policy=FleetPolicy(up_ticks=2, down_ticks=3,
                                        min_window_n=8,
                                        pressure_start=0.5,
                                        pressure_full=1.0))
    fc.tick()
    assert admission.pressure == 0.0
    _burn(router, "m", seconds=0.2)       # burn 4.0 -> pressure 1.0
    fc.tick()
    assert admission.pressure == 1.0
    assert admission.admit("t", "low") == "priority"
    assert admission.admit("t", "high") is None
    router.latency["m"].reset()
    fc.tick()
    assert admission.pressure == 0.0       # instantly reversible
    fc.stop()


def test_fleet_status_route(fleet_router):
    router, provider = fleet_router
    # the route exists without a controller and says so
    from sparknet_tpu.obs import StatusServer
    assert router._fleet_status() == {"enabled": False}
    fc = _controller(router, provider)
    _burn(router, "m")
    fc.tick()
    fc.tick()
    st = router._fleet_status()
    assert st["enabled"] is True
    assert st["models"]["m"]["replicas"] == 2
    assert st["models"]["m"]["slo_p99_ms"] == SLO_MS
    assert st["models"]["m"]["burn"] > 1.0
    assert st["audit"][-1]["reason"] == "slo_burn"
    assert st["pool"]["size"] == 1
    # and over real HTTP via the router's StatusServer route table
    http = StatusServer(0, router.registry,
                        routes={"/fleet/status": router._fleet_status})
    try:
        host, port = http.address
        with urllib.request.urlopen(
                f"http://{host}:{port}/fleet/status", timeout=10) as r:
            body = json.loads(r.read())
        assert body["enabled"] is True
        assert body["models"]["m"]["replicas"] == 2
    finally:
        http.stop()
    fc.stop()


# -- frontends: priority shed, typed on both wires ----------------------------

def test_http_x_priority_sheds_typed_under_pressure():
    admission = PriorityAdmission()
    admission.set_pressure(0.6)
    srv = InferenceServer(JaxNet(lenet(batch=4)),
                          _lane_cfg("default")).start()
    fe = HttpFrontend(srv, port=0, tenants=admission)
    try:
        url = f"http://{fe.address[0]}:{fe.address[1]}"
        with pytest.raises(PriorityShedError):
            http_infer(url, "default", _example(0), deadline_s=30.0,
                       priority="low")
        out = http_infer(url, "default", _example(0), deadline_s=30.0,
                         priority="high")
        assert out["prob"].shape == (10,)
        c = srv.registry.counter("sparknet_serve_shed_total",
                                 labels=("model", "reason"))
        assert c.value(model="default", reason="priority") == 1
    finally:
        fe.stop()
        srv.stop()


def test_binary_priority_field_sheds_typed_under_pressure():
    admission = PriorityAdmission()
    admission.set_pressure(0.95)
    srv = InferenceServer(JaxNet(lenet(batch=4)),
                          _lane_cfg("default")).start()
    fe = BinaryFrontend(srv, port=0, tenants=admission)
    try:
        with pytest.raises(PriorityShedError):
            binary_infer(fe.address, "default", _example(0),
                         deadline_s=30.0, priority="normal")
        out = binary_infer(fe.address, "default", _example(0),
                           deadline_s=30.0, priority="high")
        assert out["prob"].shape == (10,)
        # the typed shed rode the SAME keep-alive connection
        assert fe.connections == 1
    finally:
        fe.stop()
        srv.stop()


# -- providers + CLI ----------------------------------------------------------

def test_pod_provider_stub_assembles_launcher_protocol():
    calls = []
    prov = PodReplicaProvider({"m": "lenet"}, zone="us-east5-b",
                              accel_type="v5e-8",
                              launcher="scripts/tpu_pod_launch.sh",
                              runner=calls.append)
    h = prov.grow("m")
    assert h.url == "spkn://sparknet-fleet-m-1:8470"
    assert [c[1] for c in calls] == ["create", "setup", "run"]
    assert calls[0][2:] == ["sparknet-fleet-m-1", "us-east5-b", "v5e-8"]
    assert "sparknet-serve" in calls[2][4]
    assert "--binary-port 8470" in calls[2][4]
    prov.retire(h)
    assert calls[-1][1] == "delete"
    with pytest.raises(KeyError):
        prov.grow("unknown")


def test_serve_cli_autoscale_demo(tmp_path, capsys):
    """`sparknet-serve --models ... --autoscale --fleet-provider none
    --demo`: the control plane starts, the demo serves, the status
    carries autoscale=true, and shutdown is clean."""
    from sparknet_tpu.serve.app import main
    main(["--models", "m=lenet", "--autoscale",
          "--fleet-provider", "none", "--binary-port", "0",
          "--slo-p99-ms", "50", "--demo", "4",
          "--workdir", str(tmp_path)])
    status = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert status["autoscale"] is True
    assert status["models"]["m"]["requests_ok"] == 4


def test_serve_cli_autoscale_requires_models(tmp_path):
    from sparknet_tpu.serve.app import main
    with pytest.raises(SystemExit):
        main(["--model", "lenet", "--autoscale",
              "--workdir", str(tmp_path)])
