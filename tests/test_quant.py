"""Inference economics (r9): quantized serving, persistent compile cache,
traffic-derived bucket ladders.

Tier-1 (CPU). The contracts pinned:

  - quantization math: per-channel symmetric int8 round-trips within
    scale/2 per weight; the quantized pytree is self-describing.
  - per-bucket parity: the int8-weight/bf16-activation forward stays
    allclose to the f32 forward within the calibrated QuantConfig
    tolerance on every zoo serve model, at every bucket size — the PR 7
    Pallas-pin pattern applied to the quant lever.
  - the load-time parity gate: a corrupted-scale quantization NEVER
    serves — canary-rejected mid-traffic with zero corrupted responses
    (the chaos acceptance), and the f32 path stays bitwise untouched.
  - bucket-ladder derivation: derive_buckets is optimal on the observed
    histogram (checked against exhaustive search) and the ladder rides
    config validation (ServeConfig.__post_init__ fails bad ladders at
    construction).
  - compile-cache verdicts: a fresh XLA compile region records a MISS,
    a no-fresh-work region (memoized spec compile, cached executable)
    records a HIT, and the exposition carries the label.
"""
import itertools
import json
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.model.quant import (QuantConfig, dequantize_params,
                                      is_quantized, quantize_leaf,
                                      quantize_params)
from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.serve import (InferenceServer, ModelManager, ServeConfig,
                                ServeModelError, derive_buckets,
                                fill_ratio, parity_batch,
                                size_hist_from_jsonl, zeros_batch)
from sparknet_tpu.utils import checkpoint as ckpt
from sparknet_tpu.utils.metrics import FillMeter
from sparknet_tpu.zoo import adult_mlp, caffenet, cifar10_quick, lenet


# -- quantization math -------------------------------------------------------

def test_quantize_leaf_roundtrip_error_bounded():
    r = np.random.default_rng(0)
    w = (r.standard_normal((5, 5, 3, 16)) * r.uniform(0.01, 3.0, 16))
    w = w.astype(np.float32)
    q = quantize_leaf(w)
    assert np.asarray(q["w_q"]).dtype == np.int8
    assert q["w_scale"].shape == (16,)
    deq = np.asarray(q["w_q"], np.float32) * np.asarray(q["w_scale"])
    # symmetric rounding: error <= scale/2 per element, per channel
    assert np.all(np.abs(deq - w) <= np.asarray(q["w_scale"]) / 2 + 1e-7)
    # an all-zero channel must not divide by zero
    w[..., 3] = 0.0
    q0 = quantize_leaf(w)
    assert np.all(np.asarray(q0["w_q"])[..., 3] == 0)
    assert np.isfinite(np.asarray(q0["w_scale"])).all()


def test_quantize_params_structure_and_dequant():
    net = JaxNet(lenet(batch=2))
    qp = quantize_params(net.params, QuantConfig())
    assert is_quantized(qp) and not is_quantized(net.params)
    for lname, lp in net.params.items():
        if "w" in lp and np.ndim(lp["w"]) >= 2:
            assert "w_q" in qp[lname] and "w_scale" in qp[lname]
            assert "w" not in qp[lname]
        if "b" in lp:  # biases ride along in f32
            np.testing.assert_array_equal(np.asarray(qp[lname]["b"]),
                                          np.asarray(lp["b"]))
    deq = dequantize_params(qp)
    for lname, lp in net.params.items():
        for pname, w in lp.items():
            assert deq[lname][pname].shape == np.shape(w)


def test_quant_config_validates_at_construction():
    with pytest.raises(ValueError, match="quant mode"):
        QuantConfig(mode="int4")
    with pytest.raises(ValueError, match="act dtype"):
        QuantConfig(act="float16")
    assert QuantConfig.coerce("int8").mode == "int8"
    assert QuantConfig.coerce(None) is None
    assert QuantConfig.coerce({"atol": 0.2}).atol == 0.2
    with pytest.raises(ValueError, match="quant"):
        QuantConfig.coerce(3.14)


# -- per-bucket parity on the zoo serve models -------------------------------

def _zoo_serve_models():
    # every zoo model the serve path can carry, at serve-size shapes
    # (caffenet at the e2e-smoke crop: tier-1 budget, same layer set)
    return [("lenet", lenet(batch=4)),
            ("cifar10_quick", cifar10_quick(batch=4)),
            ("adult_mlp", adult_mlp(batch=4, n_features=10)),
            ("caffenet", caffenet(batch=4, crop=67, n_classes=16))]


@pytest.mark.parametrize("name,spec", _zoo_serve_models(),
                         ids=[n for n, _ in _zoo_serve_models()])
def test_quant_parity_per_bucket_vs_f32(name, spec):
    """The acceptance pin: quantized forward allclose to f32 within the
    calibrated tolerance on EVERY zoo serve model, per bucket (1 and a
    full bucket — the two compiled shapes a 2-rung ladder serves)."""
    net = JaxNet(spec)
    qc = QuantConfig()
    f32p = net.params
    qp = quantize_params(f32p, qc)
    for bucket in (1, 4):
        batch = parity_batch(net, bucket, seed=11)
        net.params = f32p
        net.set_quant(None)
        ref = net.forward(batch)
        net.params = qp
        net.set_quant(qc)
        out = net.forward(batch)
        # per-row blobs — the responses clients consume; batch-aggregate
        # scalars (accuracy) are argmax-discontinuous, the gate's
        # documented exclusion
        for k, rv in ref.items():
            if np.ndim(rv) < 1:
                continue
            qv = np.asarray(out[k], np.float32)
            rv = np.asarray(rv, np.float32)
            assert np.isfinite(qv).all(), (name, bucket, k)
            np.testing.assert_allclose(
                qv, rv, rtol=qc.rtol, atol=qc.atol,
                err_msg=f"{name} bucket {bucket} blob {k}")
        net.params = f32p
        net.set_quant(None)


def test_f32_path_bitwise_untouched_by_quant_plumbing():
    """The quant lever must not perturb the f32 path: a forward through
    the same net before and after a quantized install/rollback cycle is
    BITWISE identical."""
    net = JaxNet(lenet(batch=4))
    batch = parity_batch(net, 4, seed=3)
    ref = net.forward(batch, blob_names=["prob"])
    f32p = net.params
    net.params = quantize_params(f32p, QuantConfig())
    net.set_quant(QuantConfig())
    net.forward(batch, blob_names=["prob"])  # quantized trace exercised
    net.params = f32p
    net.set_quant(None)
    again = net.forward(batch, blob_names=["prob"])
    np.testing.assert_array_equal(ref["prob"], again["prob"])


# -- quantized serving end to end --------------------------------------------

def _example(i):
    r = np.random.default_rng(1000 + i)
    return {"data": r.standard_normal((28, 28, 1)).astype(np.float32)}


def test_quantized_server_serves_f32_wire_within_tol():
    """End to end: a quantized server answers f32 arrays (npz/JSON
    clients never see bf16), within tolerance of an f32 server over the
    same weights, with the jit cache pinned at len(buckets) and the pad
    buffers keyed by the bf16 activation dtype (satellite: no aliasing
    with f32 buffers)."""
    spec = lenet(batch=4)
    net_f = JaxNet(spec)
    net_q = JaxNet(spec)
    net_q.set_weights(net_f.get_weights())  # identical weights
    cfg_f = ServeConfig(max_batch=4, max_wait_ms=2.0, buckets=(1, 4),
                        outputs=("prob",), metrics_every_batches=0)
    cfg_q = ServeConfig(max_batch=4, max_wait_ms=2.0, buckets=(1, 4),
                        outputs=("prob",), metrics_every_batches=0,
                        quant="int8")
    with InferenceServer(net_f, cfg_f) as sf:
        refs = [sf.infer(_example(i)) for i in range(3)]
    with InferenceServer(net_q, cfg_q) as sq:
        outs = [sq.infer(_example(i)) for i in range(3)]
        futs = [sq.submit(_example(i)) for i in range(4)]
        for f in futs:
            f.result(timeout=30.0)
        st = sq.status()
        assert st["quant"] == "int8"
        assert st["bucket_compiles"] == 2 == len(sq.buckets)
        assert all(k[1] == "bfloat16" for k in sq._bucket_buf)
        hist = st["batch_size_hist"]
        assert sum(int(v) for v in hist.values()) == st["batches"]
    qc = QuantConfig()
    for ref, out in zip(refs, outs):
        assert out["prob"].dtype == np.float32
        np.testing.assert_allclose(out["prob"], ref["prob"],
                                   rtol=qc.rtol, atol=qc.atol)


def test_quant_rejects_graph_backend():
    class FakeGraphNet:  # no .params / .set_quant
        pass
    with pytest.raises(ServeModelError, match="quantized serving"):
        ModelManager(FakeGraphNet(), quant=QuantConfig())


def test_manager_quantizes_initial_weights_without_checkpoint():
    net = JaxNet(lenet(batch=4))
    m = ModelManager(net, quant=QuantConfig(),
                     parity_batch=parity_batch(net, 1))
    assert m.load_initial() is None
    assert is_quantized(net.params) and net.quant is not None
    assert m.last_parity_drift is not None
    assert m.last_parity_drift <= QuantConfig().atol


def _save_trainstate_like(net_params, d, step, scale=1.0):
    flat = {}
    for lname, lp in net_params.items():
        for pname, w in lp.items():
            flat[f"params/{lname}/{pname}"] = np.asarray(w)[None] * scale
    return ckpt.save(str(d), flat, step=step)


def test_manager_hot_swap_installs_quantized(tmp_path):
    net = JaxNet(lenet(batch=4))
    f32p = {l: {p: np.asarray(w) for p, w in lp.items()}
            for l, lp in net.params.items()}
    d = tmp_path / "ck"
    _save_trainstate_like(f32p, d, step=5, scale=0.5)
    m = ModelManager(net, checkpoint_dir=str(d), quant=QuantConfig(),
                     parity_batch=parity_batch(net, 1),
                     canary_batch=zeros_batch(net, 1))
    assert m.load_initial() == 5
    assert is_quantized(net.params)
    # the installed quantization dequantizes to the checkpoint's weights
    deq = dequantize_params(net.params)
    w_ref = f32p["conv1"]["w"] * 0.5
    got = np.asarray(deq["conv1"]["w"])
    assert np.max(np.abs(got - w_ref)) <= \
        float(np.max(np.asarray(net.params["conv1"]["w_scale"]))) / 2 + 1e-6


@pytest.mark.chaos
def test_corrupted_scale_checkpoint_canary_rejected_mid_traffic(
        tmp_path, monkeypatch):
    """The quant chaos acceptance: mid-traffic, (1) a good checkpoint
    hot-swaps into the QUANTIZED path, (2) a checkpoint whose
    quantization comes out corrupted (scale blown up 16x on one layer —
    digest-valid bytes, poisoned math) is canary-rejected by the parity
    gate with the server still answering from the previous weights.
    Zero dropped, zero corrupted responses."""
    import sparknet_tpu.serve.model_manager as mm

    net = JaxNet(lenet(batch=4))
    f32p = {l: {p: np.asarray(w) for p, w in lp.items()}
            for l, lp in net.params.items()}
    d = tmp_path / "ck"
    _save_trainstate_like(f32p, d, step=1)
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      checkpoint_dir=str(d), poll_interval_s=0.05,
                      metrics_every_batches=0, quant="int8")
    answered, bad = [], []
    stop = threading.Event()

    def client():
        i = 0
        while not stop.is_set():
            try:
                out = srv.infer(_example(i), timeout=30.0)
                p = out["prob"]
                if p.shape != (10,) or p.dtype != np.float32 or \
                        not np.isfinite(p).all() or \
                        abs(float(p.sum()) - 1.0) > 5e-2:
                    bad.append((i, p))
                answered.append(i)
            except Exception as e:
                bad.append((i, e))
            i += 1

    real_quantize = mm.quantize_params

    def corrupted_quantize(params, cfg_):
        qp = real_quantize(params, cfg_)
        qp["fc1"]["w_scale"] = qp["fc1"]["w_scale"] * 16.0
        return qp

    with InferenceServer(net, cfg) as srv:
        assert srv.manager.step == 1 and is_quantized(net.params)
        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            # (1) a good swap lands, still quantized
            _save_trainstate_like(f32p, d, step=2, scale=0.9)
            _wait(lambda: srv.manager.step == 2)
            assert is_quantized(net.params)
            # (2) corrupted scales: the parity gate must reject
            monkeypatch.setattr(
                "sparknet_tpu.serve.model_manager.quantize_params",
                corrupted_quantize)
            _save_trainstate_like(f32p, d, step=3, scale=0.8)
            fails = srv.manager.swap_failures
            _wait(lambda: srv.manager.swap_failures > fails)
            assert srv.manager.step == 2  # still the good one
            assert "quantization rejected" in srv.manager.last_error
            # (3) with honest quantization back, the NEXT step serves
            monkeypatch.setattr(
                "sparknet_tpu.serve.model_manager.quantize_params",
                real_quantize)
            _save_trainstate_like(f32p, d, step=4, scale=0.8)
            _wait(lambda: srv.manager.step == 4)
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not bad, bad[:3]
        assert len(answered) > 10
        assert srv.manager.swaps == 2
        assert srv.manager.swap_failures == 1
        assert srv.status()["requests_failed"] == 0


def _wait(cond, timeout=30.0):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < timeout, "condition never held"
        time.sleep(0.02)


def test_serve_cli_quant_and_buckets_from(tmp_path, capsys):
    """The sparknet-serve wiring end to end: a --quant int8 demo records
    a serve JSONL; a second launch derives its bucket ladder from that
    JSONL via --buckets-from and serves on it."""
    from sparknet_tpu.serve.app import main

    main(["--model", "lenet", "--outputs", "prob", "--max-batch", "4",
          "--quant", "int8", "--demo", "6", "--workdir", str(tmp_path)])
    status = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert status["requests_ok"] == 6 and status["requests_failed"] == 0
    assert status["quant"] == "int8"
    jsonls = list(tmp_path.glob("serving_metrics_*.jsonl"))
    assert jsonls, "demo wrote no serve JSONL"
    # hand the recorded traffic back as the ladder source
    main(["--model", "lenet", "--outputs", "prob", "--max-batch", "4",
          "--buckets-from"] + [str(p) for p in jsonls] +
         ["--buckets-k", "2", "--demo", "4", "--workdir", str(tmp_path)])
    status2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert status2["requests_ok"] == 4
    b = status2["buckets"]
    assert b[-1] == 4 and len(b) <= 2  # a derived <=2-rung ladder


# -- bucket-ladder derivation ------------------------------------------------

def test_derive_buckets_optimal_vs_exhaustive():
    """The DP matches exhaustive search over all <=k ladders on skewed
    histograms (the padded-slots objective, top rung pinned)."""
    r = np.random.default_rng(5)
    for trial in range(6):
        sizes = {int(s): int(r.integers(1, 60))
                 for s in r.choice(np.arange(1, 17), size=6,
                                   replace=False)}
        for k in (1, 2, 3, 4):
            got = derive_buckets(sizes, 16, k=k)
            assert len(got) <= k and got[-1] == 16
            cand = sorted(set(sizes) - {16})
            best = min(
                padded(sizes, tuple(sorted(set(c) | {16})))
                for n in range(0, k)           # n lower rungs + the top
                for c in itertools.combinations(cand, n))
            assert padded(sizes, got) == best, (trial, k, sizes, got)


def padded(sizes, buckets):
    return sum(next(b for b in buckets if b >= s) * n
               for s, n in sizes.items())


def test_derive_buckets_edges():
    assert derive_buckets({}, 8, k=4) == (8,)
    assert derive_buckets({16: 5}, 8, k=4) == (8,)    # clipped to max
    assert derive_buckets({"2": "7"}, 8, k=2) == (2, 8)
    assert derive_buckets({1: 100, 8: 1}, 8, k=2) == (1, 8)
    with pytest.raises(ValueError):
        derive_buckets({1: 1}, 0)
    with pytest.raises(ValueError):
        derive_buckets({1: 1}, 8, k=0)
    # fill_ratio agrees with hand math: 50x1 on rung 1 + 1x8 on rung 8
    assert fill_ratio({1: 50, 8: 1}, (1, 8)) == pytest.approx(58 / 58)
    assert fill_ratio({1: 50, 8: 1}, (8,)) == pytest.approx(58 / 408)


def test_size_hist_from_jsonl_last_row_wins(tmp_path):
    p = tmp_path / "serve.jsonl"
    rows = [
        {"step": 1, "model": "m", "batch_size_hist": {"1": 2}},
        {"step": 2, "model": "m", "batch_size_hist": {"1": 5, "4": 1}},
        {"step": 1, "model": "n", "batch_size_hist": {"2": 3}},
    ]
    import json
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    hists = size_hist_from_jsonl([str(p)])
    assert hists["m"] == {1: 5, 4: 1}  # cumulative: last row per model
    assert hists["n"] == {2: 3}
    assert size_hist_from_jsonl([str(p)], model="m") == {
        "m": {1: 5, 4: 1}}


def test_fillmeter_size_hist():
    fm = FillMeter()
    fm.add(3, 4)
    fm.add(3, 4)
    fm.add(1, 1)
    assert fm.size_hist() == {3: 2, 1: 1}
    fm.reset()
    assert fm.size_hist() == {}


# -- ServeConfig validation (satellite) --------------------------------------

def test_serve_config_validates_buckets_at_construction():
    ServeConfig(max_batch=8, buckets=(1, 4, 8))  # fine
    with pytest.raises(ValueError, match="strictly increasing"):
        ServeConfig(max_batch=8, buckets=(4, 1, 8))
    with pytest.raises(ValueError, match="strictly increasing"):
        ServeConfig(max_batch=8, buckets=(1, 4, 4, 8))
    with pytest.raises(ValueError, match="positive"):
        ServeConfig(max_batch=8, buckets=(0, 8))
    with pytest.raises(ValueError, match="largest bucket"):
        ServeConfig(max_batch=8, buckets=(1, 4))
    with pytest.raises(ValueError, match="non-empty"):
        ServeConfig(max_batch=8, buckets=())
    with pytest.raises(ValueError, match="max_batch"):
        ServeConfig(max_batch=0)
    # quant coercion rides the same construction-time gate
    with pytest.raises(ValueError, match="quant mode"):
        ServeConfig(quant="int4")


# -- compile-cache verdicts --------------------------------------------------

def test_track_compiles_verdicts():
    """A region with a FRESH XLA compile reads as a miss (no cache, or
    first sight with one); a region with no fresh XLA work reads as a
    hit. The thread-local counting attributes compiles to the region
    that ran them."""
    import jax
    import jax.numpy as jnp

    from sparknet_tpu.utils.compile_cache import track_compiles

    salt = time.time_ns()  # a jit signature no other test compiled
    f = jax.jit(lambda x: x * 2 + (salt % 97))
    with track_compiles() as cold:
        f(jnp.ones((3,)))
    assert cold.xla_compiles >= 1
    assert cold.cache_hit is False  # fresh XLA work, nothing served it
    with track_compiles() as warm:
        f(jnp.ones((3,)))          # same executable: no compile at all
    assert warm.xla_compiles == 0
    assert warm.cache_hit is True


def test_spec_compile_memo_records_cache_hit():
    """Identical NetSpecs compile once: the second CompiledNet.compile
    is a memo hit recorded as cache_hit=true, and returns the SAME
    object."""
    from sparknet_tpu.model.net import CompiledNet
    from sparknet_tpu.obs.device import compile_stats

    spec = lenet(batch=3)
    a = CompiledNet.compile(spec)
    before = compile_stats()["net"]
    b = CompiledNet.compile(lenet(batch=3))
    after = compile_stats()["net"]
    assert b is a
    assert after["events"] == before["events"] + 1
    assert after["cache_hits"] == before["cache_hits"] + 1
