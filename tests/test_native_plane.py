"""C++ data plane tests (skipped if the native lib can't build)."""
import io

import numpy as np
import pytest

from sparknet_tpu.data import jpeg_plane

pytestmark = pytest.mark.skipif(not jpeg_plane.available(),
                                reason="native plane unavailable")


def make_jpeg(arr):
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG", quality=95)
    return buf.getvalue()


def test_decode_resize_matches_pil_on_smooth_image():
    y, x = np.mgrid[0:61, 0:83]
    arr = np.stack([(y * 2) % 256, (x * 3) % 256, (x + y) % 256],
                   -1).astype(np.uint8)
    data = make_jpeg(arr)
    got = jpeg_plane.decode_resize_chw(data, 48, 48)
    from sparknet_tpu.data.imagenet import _decode_pil
    ref = _decode_pil(data, 48, 48)
    assert got.shape == (3, 48, 48)
    assert np.abs(got.astype(int) - ref.astype(int)).mean() < 2.0


def test_decode_corrupt_raises():
    with pytest.raises(ValueError, match="decode failed"):
        jpeg_plane.decode_resize_chw(b"not a jpeg", 32, 32)


def test_batch_decode_flags_corrupt_entries():
    arr = np.zeros((40, 40, 3), np.uint8)
    good = make_jpeg(arr)
    imgs, ok = jpeg_plane.decode_resize_chw_batch(
        [good, good[: len(good) // 2], good, b""], 32, 32)
    assert ok.tolist() == [True, False, True, False]
    assert imgs.shape == (4, 3, 32, 32)
    np.testing.assert_array_equal(imgs[0], imgs[2])


def test_fused_crop_mean_nhwc_matches_numpy(rng):
    imgs = rng.integers(0, 256, (5, 3, 20, 24), dtype=np.uint8)
    mean = rng.standard_normal((3, 20, 24)).astype(np.float32)
    ys = np.array([0, 1, 2, 3, 4], np.int32)
    xs = np.array([4, 3, 2, 1, 0], np.int32)
    got = jpeg_plane.crop_mean_nhwc(imgs, mean, ys, xs, 16)
    for i in range(5):
        want = (imgs[i].astype(np.float32) - mean)[
            :, ys[i]:ys[i] + 16, xs[i]:xs[i] + 16].transpose(1, 2, 0)
        np.testing.assert_allclose(got[i], want, rtol=1e-6)


def test_fused_no_mean(rng):
    imgs = rng.integers(0, 256, (2, 3, 8, 8), dtype=np.uint8)
    got = jpeg_plane.crop_mean_nhwc(imgs, None, np.zeros(2, np.int32),
                                    np.zeros(2, np.int32), 8)
    np.testing.assert_array_equal(got[0],
                                  imgs[0].astype(np.float32).transpose(1, 2, 0))


def test_preprocessor_uses_fused_path(rng):
    """ImagePreprocessor with uint8 CHW input routes through the native
    kernel and matches the pure-numpy float path."""
    from sparknet_tpu.data.preprocess import ImagePreprocessor
    from sparknet_tpu.schema import Field, Schema
    schema = Schema(Field("data", "float32", (3, 10, 10)),
                    Field("label", "int32", (1,)))
    imgs = rng.integers(0, 256, (6, 3, 14, 14), dtype=np.uint8)
    mean = rng.standard_normal((3, 14, 14)).astype(np.float32)
    a = ImagePreprocessor(schema, mean_image=mean, crop=10, seed=7)
    b = ImagePreprocessor(schema, mean_image=mean, crop=10, seed=7)
    lab = np.zeros((6, 1))
    fused = a.convert_batch({"data": imgs, "label": lab}, train=True)
    plain = b.convert_batch({"data": imgs.astype(np.float32), "label": lab},
                            train=True)
    np.testing.assert_allclose(fused["data"], plain["data"], atol=1e-5)


def test_bf16_out_bit_identical_to_ml_dtypes(rng):
    """The bf16 emit path must match ml_dtypes' round-to-nearest-even cast
    BIT-for-bit — including NaN (a low-payload NaN must stay NaN, not carry
    into +/-Inf through the RNE add), Inf, and values that round up to Inf."""
    import ml_dtypes

    if not jpeg_plane.supports_bf16_out():
        pytest.skip("libjpeg_plane.so predates bf16 output")
    imgs = rng.integers(0, 256, (1, 1, 16, 16), dtype=np.uint8)
    mean = rng.standard_normal((1, 16, 16)).astype(np.float32) * 300
    # plant specials: out = u8 - mean, so mean=NaN -> NaN, mean=-Inf -> Inf,
    # mean near -f32max -> rounds to Inf, exact-tie mantissas for RNE
    mean.reshape(-1)[:6] = [np.nan, -np.inf, np.inf, -3.4e38, 3.4e38,
                            -2.00390625]
    got = jpeg_plane.crop_mean_nhwc(imgs, mean, np.zeros(1, np.int32),
                                    np.zeros(1, np.int32), 16,
                                    out_dtype="bfloat16")
    want = (imgs[0].astype(np.float32) - mean).transpose(1, 2, 0) \
        .astype(ml_dtypes.bfloat16)
    g16 = got[0].view(np.uint16)
    w16 = want.view(np.uint16)
    nan_g = np.isnan(got[0].astype(np.float32))
    nan_w = np.isnan(want.astype(np.float32))
    np.testing.assert_array_equal(nan_g, nan_w)  # NaN stays NaN
    # non-NaN lanes: exact bit identity (NaN payload bits may differ)
    np.testing.assert_array_equal(g16[~nan_g], w16[~nan_w])
