"""r12 continuous learning — the train->serve loop's control surfaces.

Three contracts pinned here:

  - FRESHNESS: training stamps `commit_ts` into meta.json at
    manifest-commit time; ModelManager carries it through install so
    `freshness_s` (now - commit of the serving step) and `step_lag`
    (newest committed step - serving step) are measurable per replica.
    Pre-r12 checkpoints (no stamp) degrade to freshness=None, never an
    error.
  - STAGGERED ADOPTION: RolloutManager sequences a new committed step
    through canary -> waves -> done against its ROLLOUT.json gate, and
    HALTS (deny fleet-wide, revert approvals) on a rejection, an SLO
    burn breach, or an adoption timeout. A denied step is never
    re-targeted; a newer step still rolls out.
  - BLAST RADIUS (the acceptance pin): a poisoned-but-digest-valid
    checkpoint is rejected by the CANARY replica's forward gate, the
    rollout halts, the canary sheds to its peers via swap-cooldown —
    and the bad step NEVER installs on a second replica.
"""
import json
import time

import numpy as np
import pytest

from sparknet_tpu.fleet import ReplicaView, RolloutManager
from sparknet_tpu.fleet.rollout import read_gate, write_gate
from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.obs import MetricsRegistry
from sparknet_tpu.serve import ModelManager, zeros_batch
from sparknet_tpu.utils import checkpoint as ckpt
from sparknet_tpu.zoo import lenet


@pytest.fixture(scope="module")
def net():
    return JaxNet(lenet(batch=4))


def _save(net, d, step, scale=1.0):
    """A TrainState-shaped checkpoint of this net's weights * scale."""
    flat = {f"params/{ln}/{pn}": np.asarray(w)[None] * scale
            for ln, lp in net.params.items() for pn, w in lp.items()}
    return ckpt.save(str(d), flat, step=step)


def _views(*mgrs):
    return [ReplicaView(m.replica, m.step, m.swap_failures) for m in mgrs]


# -- the gate file ------------------------------------------------------------

def test_gate_roundtrip_and_degraded_reads(tmp_path):
    p = str(tmp_path / "ROLLOUT.json")
    assert read_gate(p) is None                      # missing -> ungated
    gate = {"v": 1, "state": "wave", "wave": 2, "approved": {"r1": 7},
            "denied": [6], "all": 5, "target": 7}
    write_gate(p, gate)
    assert read_gate(p) == gate
    # torn/garbage content degrades to None, never raises
    open(p, "w").write('{"v": 1, "appro')
    assert read_gate(p) is None
    open(p, "w").write("[1, 2]")                     # valid JSON, non-dict
    assert read_gate(p) is None


# -- freshness plumbing -------------------------------------------------------

def test_commit_ts_stamped_and_freshness_accessors(net, tmp_path):
    d = tmp_path / "ck"
    t0 = time.time()
    path = _save(net, d, step=3)
    meta = json.load(open(f"{path}/meta.json"))
    assert t0 - 1.0 <= meta["commit_ts"] <= time.time() + 1.0
    m = ModelManager(net, checkpoint_dir=str(d))
    assert m.load_initial() == 3
    assert m.commit_ts == meta["commit_ts"]
    assert m.freshness_s(now=m.commit_ts + 5.0) == 5.0
    assert m.freshness_s(now=m.commit_ts - 9.0) == 0.0  # clock skew clamps
    assert m.step_lag() == 0
    # no checkpoint dir at all: freshness is undefined, not an error
    bare = ModelManager(net)
    assert bare.freshness_s() is None and bare.step_lag() is None


def test_pre_r12_checkpoint_without_stamp_degrades(net, tmp_path):
    d = tmp_path / "ck"
    path = _save(net, d, step=1)
    meta = json.load(open(f"{path}/meta.json"))
    del meta["commit_ts"]                 # a checkpoint from an old writer
    json.dump(meta, open(f"{path}/meta.json", "w"))
    m = ModelManager(net, checkpoint_dir=str(d))
    assert m.load_initial() == 1          # serves fine
    assert m.commit_ts is None and m.freshness_s() is None


def test_step_lag_counts_held_back_steps(net, tmp_path):
    """A gated replica that is HELD while newer steps commit reports the
    lag — the staleness signal podview/metrics surface per replica."""
    d = tmp_path / "ck"
    gate = str(tmp_path / "ROLLOUT.json")
    _save(net, d, step=1)
    m = ModelManager(net, checkpoint_dir=str(d), poll_interval_s=0.0,
                     rollout_gate=gate)
    assert m.load_initial() == 1
    write_gate(gate, {"v": 1, "state": "canary", "approved": {"other": 4},
                      "denied": []})
    _save(net, d, step=4)
    assert m.poll() is False              # held: nothing approved for us
    assert m.step == 1 and m.latest_seen == 4 and m.step_lag() == 3


def test_vanished_step_is_not_a_rejection(net, tmp_path):
    """A step that retention pruned between listing and fetch must not
    count as a REJECTED swap: a rising swap_failures reads as "this
    replica refused the checkpoint" and would halt a fleet rollout over
    a step that is simply gone."""
    import shutil

    d = tmp_path / "ck"
    _save(net, d, step=1)
    path2 = _save(net, d, step=2)
    reg = MetricsRegistry()
    m = ModelManager(net, checkpoint_dir=str(d), poll_interval_s=0.0,
                     registry=reg)
    assert m.load_initial() == 2
    shutil.rmtree(path2)                  # retention prunes step 2
    with pytest.raises(ckpt.CheckpointVanishedError):
        ckpt.restore_flat(str(d), step=2)
    assert m._try_swap(2) is False        # a slow rollout still wants it
    assert m.step == 2 and m.swap_failures == 0 and m._bad == {}
    assert "vanished" in m.last_error
    assert 'outcome="vanished"} 1' in reg.render_prometheus()
    assert 'outcome="rejected"' not in reg.render_prometheus()


# -- the rollout state machine ------------------------------------------------

def test_rollout_staggers_canary_then_waves_then_all(tmp_path):
    gate = str(tmp_path / "ROLLOUT.json")
    events = []
    ro = RolloutManager(gate, wave_size=2, timeout_s=30.0,
                        event=lambda _d, r, **ex: events.append((r, ex)))
    keys = ["local", "r1", "r2", "r3", "r4"]
    at = {k: 1 for k in keys}
    view = lambda: [ReplicaView(k, at[k]) for k in keys]
    # nothing new committed: stays idle
    assert ro.tick(view(), newest_step=None, burn=0.0, now=0.0) == "idle"
    # step 2 commits: canary (first view = the local lane) only
    assert ro.tick(view(), 2, 0.0, now=1.0) == "canary"
    g = read_gate(gate)
    assert g["approved"] == {"local": 2} and g.get("all") is None
    # canary not adopted yet: no wave opens
    assert ro.tick(view(), 2, 0.0, now=2.0) == "canary"
    at["local"] = 2
    assert ro.tick(view(), 2, 0.0, now=3.0) == "wave"
    g = read_gate(gate)
    assert g["wave"] == 1 and set(g["approved"]) == {"local", "r1", "r2"}
    at["r1"] = at["r2"] = 2
    assert ro.tick(view(), 2, 0.0, now=4.0) == "wave"
    assert set(read_gate(gate)["approved"]) == set(keys)
    at["r3"] = at["r4"] = 2
    assert ro.tick(view(), 2, 0.0, now=5.0) == "idle"  # done
    g = read_gate(gate)
    # the finished rollout opens the step to EVERYONE — including a
    # replica grown later that never appeared in any wave
    assert g["all"] == 2 and g["approved"] == {} and g["denied"] == []
    st = ro.status()
    assert st["rollouts"] == 1 and st["waves_done"] == 2
    assert st["halts"] == 0
    assert [r for r, _ in events] == ["canary", "wave", "wave", "done"]
    # the same step never re-opens; a NEWER one does
    assert ro.tick(view(), 2, 0.0, now=6.0) == "idle"
    assert ro.tick(view(), 3, 0.0, now=7.0) == "canary"


def test_rollout_halt_on_burn_and_on_adoption_timeout(tmp_path):
    gate = str(tmp_path / "ROLLOUT.json")
    ro = RolloutManager(gate, wave_size=1, halt_burn=1.5, timeout_s=10.0)
    views = [ReplicaView("local", 5), ReplicaView("r1", 5)]
    assert ro.tick(views, 6, 0.0, now=0.0) == "canary"
    views[0].step = 6                     # canary adopted, but burn is hot
    assert ro.tick(views, 6, burn=2.0, now=1.0) == "idle"
    assert ro.status()["denied"] == [6] and ro.status()["halts"] == 1
    assert read_gate(gate)["all"] == 5    # fleet reverts to pre-rollout
    # adoption timeout: a canary that never installs (wedged replica)
    views[0].step = 5
    assert ro.tick(views, 7, 0.0, now=2.0) == "canary"
    assert ro.tick(views, 7, 0.0, now=5.0) == "canary"   # within budget
    assert ro.tick(views, 7, 0.0, now=13.0) == "idle"    # 11s > 10s
    assert ro.status()["denied"] == [6, 7]


def test_gate_target_resolution(net, tmp_path):
    gate = str(tmp_path / "ROLLOUT.json")
    m = ModelManager(net, checkpoint_dir=str(tmp_path / "ck"),
                     replica="r1", rollout_gate=gate)
    assert m._gate_target() == (False, None)        # no gate: ungated
    write_gate(gate, {"approved": {"r1": 5}})
    assert m._gate_target() == (False, 5)           # named approval wins
    write_gate(gate, {"approved": {"other": 5}})
    assert m._gate_target() == (True, None)         # someone else's wave
    write_gate(gate, {"approved": {}, "all": 4})
    assert m._gate_target() == (False, 4)           # completed rollout
    write_gate(gate, {"approved": {"r1": 6}, "denied": [6]})
    assert m._gate_target() == (True, None)         # approval raced a deny


# -- the acceptance pin -------------------------------------------------------

@pytest.mark.chaos
def test_rejected_canary_halts_wave_and_never_reaches_peers(tmp_path):
    """A digest-valid but POISONED step (NaN weights) reaches the canary
    replica, fails its canary forward, and the rollout halts: the step is
    denied fleet-wide, the canary sheds to peers through swap-cooldown,
    and no second replica ever installs it."""
    d = tmp_path / "ck"
    gate = str(tmp_path / "ROLLOUT.json")
    nets = [JaxNet(lenet(batch=4)) for _ in range(3)]
    _save(nets[0], d, step=1)
    regs = [MetricsRegistry() for _ in range(3)]
    mgrs = [ModelManager(nets[i], checkpoint_dir=str(d),
                         poll_interval_s=0.0, bad_step_retry_s=0.01,
                         canary_batch=zeros_batch(nets[i], 1),
                         canary_outputs=("prob",), replica=rk,
                         rollout_gate=gate, registry=regs[i])
            for i, rk in enumerate(("local", "r1", "r2"))]
    for m in mgrs:
        assert m.load_initial() == 1
    ro = RolloutManager(gate, wave_size=1, timeout_s=30.0)
    _save(nets[0], d, step=2, scale=np.nan)   # poisoned, digests valid
    assert ro.tick(_views(*mgrs), 2, 0.0, now=0.0) == "canary"
    # the canary tries it and ROLLS BACK; peers are held by the gate
    assert mgrs[0].poll() is False
    assert mgrs[0].step == 1 and mgrs[0].swap_failures == 1
    assert "canary" in mgrs[0].last_error
    assert mgrs[0].swap_cooldown_active(30.0)   # router sheds to peers
    assert 'outcome="rejected"} 1' in regs[0].render_prometheus()
    for m in mgrs[1:]:
        assert m.poll() is False and m.step == 1
    # the controller sees the canary's rollback count rise -> HALT
    assert ro.tick(_views(*mgrs), 2, 0.0, now=1.0) == "idle"
    st = ro.status()
    assert st["denied"] == [2] and st["halts"] == 1
    assert read_gate(gate)["all"] == 1
    # even past the canary's bad-step cooldown, the denied step installs
    # NOWHERE — and peers took zero swap attempts at it
    time.sleep(0.02)
    for m in mgrs:
        assert m.poll() is False and m.step == 1
    for m in mgrs[1:]:
        assert m.swaps == 0 and m.swap_failures == 0
        assert 'outcome="rejected"' not in \
            regs[mgrs.index(m)].render_prometheus()
    # a FIXED newer step then rolls out to the whole fleet, staggered
    _save(nets[0], d, step=3, scale=0.5)
    assert ro.tick(_views(*mgrs), 3, 0.0, now=2.0) == "canary"
    assert mgrs[0].poll() is True and mgrs[0].step == 3
    assert mgrs[1].poll() is False              # still only the canary
    assert ro.tick(_views(*mgrs), 3, 0.0, now=3.0) == "wave"
    moved = [m for m in mgrs[1:] if m.poll()]
    assert len(moved) == 1                      # wave_size=1
    assert ro.tick(_views(*mgrs), 3, 0.0, now=4.0) == "wave"
    assert [m for m in mgrs if m.step != 3 and m.poll()]
    assert ro.tick(_views(*mgrs), 3, 0.0, now=5.0) == "idle"
    assert [m.step for m in mgrs] == [3, 3, 3]
    assert ro.status()["rollouts"] == 1 and read_gate(gate)["all"] == 3
