"""Round-pipeline overlap & fuse (r6): bit-exactness pins for the three
MFU levers — double-buffered H2D pre-placement, batch-buffer donation, and
the Pallas LRN/pool wiring in the layer path — plus the jit-cache-churn
gauge check. The levers may only move WHERE work happens (prefetch thread
vs dispatch, donated vs fresh buffers, kernel vs XLA lowering), never WHAT
is computed: pre-placement and donation pin bitwise, the kernels pin to
parity tolerances under the bf16 policy.
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu import CompiledNet, net_from_prototxt, precision
from sparknet_tpu.model.layers import OpsImpl
from sparknet_tpu.parallel import ParallelTrainer, make_mesh
from sparknet_tpu.solver import SolverConfig

N_DEV = 4
TAU = 3
LOCAL_B = 8

TINY_MLP = """
name: "tiny_mlp"
input: "data"
input_shape { dim: 8 dim: 6 }
input: "label"
input_shape { dim: 8 dim: 1 }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 16
          weight_filler { type: "gaussian" std: 0.3 } } }
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
        inner_product_param { num_output: 4
          weight_filler { type: "gaussian" std: 0.3 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" bottom: "label"
        top: "loss" }
"""

# conv -> LRN -> MAX pool -> ip -> loss at Pallas-gate-friendly shapes:
# batch 128 (the pool kernel's N-lane and the LRN N-minor kernel's lane
# alignment), pool 3x3/2 pad 0 (the CaffeNet pool geometry), C=16 (the
# bf16 sublane tile)
CONV_LRN_POOL = """
name: "conv_lrn_pool"
input: "data"
input_shape { dim: 128 dim: 3 dim: 9 dim: 9 }
input: "label"
input_shape { dim: 128 dim: 1 }
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
        convolution_param { num_output: 16 kernel_size: 3
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "norm1" type: "LRN" bottom: "conv1" top: "norm1"
        lrn_param { local_size: 5 alpha: 0.0001 beta: 0.75 } }
layer { name: "pool1" type: "Pooling" bottom: "norm1" top: "pool1"
        pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
layer { name: "ip1" type: "InnerProduct" bottom: "pool1" top: "ip1"
        inner_product_param { num_output: 4
          weight_filler { type: "gaussian" std: 0.1 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip1" bottom: "label"
        top: "loss" }
"""


@pytest.fixture(scope="module")
def net():
    return CompiledNet.compile(net_from_prototxt(TINY_MLP))


@pytest.fixture(scope="module")
def solver_cfg():
    return SolverConfig(base_lr=0.05, momentum=0.9, weight_decay=0.001,
                        lr_policy="fixed")


def make_round_batches(seed):
    r = np.random.default_rng(seed)
    data = r.standard_normal((TAU, N_DEV * LOCAL_B, 6)).astype(np.float32)
    label = (data.sum(-1, keepdims=True) > 0).astype(np.int32)
    return {"data": data, "label": label}


def params_np(state):
    return jax.tree.map(np.asarray, state.params)


def assert_trees_bitwise(a, b, msg=""):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree_util.tree_leaves_with_path(b)
    assert len(fa) == len(fb)
    for (ka, xa), (_, xb) in zip(fa, fb):
        assert np.array_equal(np.asarray(xa), np.asarray(xb)), (msg, ka)


# -- pin (a): pre-placed device batches == host batches ----------------------


def test_preplaced_batches_bitwise_equal_host_batches(net, solver_cfg,
                                                      trainer_cls):
    """place_batches on the 'prefetch side' then train_round must produce
    the SAME post-round params as handing train_round the host arrays —
    pre-placement is the same cast + put_device_axis, just earlier."""
    mesh = make_mesh(N_DEV)
    t_host = trainer_cls(net, solver_cfg, mesh, tau=TAU)
    t_pre = trainer_cls(net, solver_cfg, mesh, tau=TAU)
    s_host = t_host.init_state(jax.random.PRNGKey(3))
    s_pre = t_pre.init_state(jax.random.PRNGKey(3))
    for rnd in range(3):
        rng = jax.random.PRNGKey(50 + rnd)
        s_host, l_host = t_host.train_round(s_host, make_round_batches(rnd),
                                            rng)
        placed = t_pre.place_batches(make_round_batches(rnd))
        assert all(isinstance(v, jax.Array) for v in placed.values())
        s_pre, l_pre = t_pre.train_round(s_pre, placed, rng)
        assert float(l_host) == float(l_pre)
    assert_trees_bitwise(params_np(s_host), params_np(s_pre), "preplaced")


def test_preplaced_batches_thread_cast_matches_main_thread(net, solver_cfg,
                                                           trainer_cls):
    """The prefetch thread passes compute_dt explicitly (the precision
    policy is thread-local): placement on a worker thread under the bf16
    policy must equal main-thread placement bit for bit."""
    from concurrent.futures import ThreadPoolExecutor

    mesh = make_mesh(N_DEV)
    t = trainer_cls(net, solver_cfg, mesh, tau=TAU)
    with precision.policy("bfloat16"):
        dt = precision.compute_dtype()
        main = t.place_batches(make_round_batches(0), dt)
        with ThreadPoolExecutor(1) as exe:
            # the worker thread sees the DEFAULT (f32) policy; compute_dt
            # must carry the main thread's choice across
            threaded = exe.submit(
                t.place_batches, make_round_batches(0), dt).result()
    for k in main:
        assert main[k].dtype == threaded[k].dtype
        assert np.array_equal(np.asarray(main[k]), np.asarray(threaded[k]))
    assert main["data"].dtype == jnp.bfloat16


# -- pin (b): donated-batch rotation never aliases a live buffer -------------


def test_donating_trainer_bitwise_equals_non_donating(net, solver_cfg,
                                                      trainer_cls):
    """Hammer τ rounds through a donate_batches trainer fed freshly placed
    batches each round (the train loop's two-slot rotation) and through
    the legacy non-donating trainer: every round's loss and the final
    params must match BITWISE — donation may recycle buffers, never
    values."""
    mesh = make_mesh(N_DEV)
    t_ref = trainer_cls(net, solver_cfg, mesh, tau=TAU)
    t_don = trainer_cls(net, solver_cfg, mesh, tau=TAU,
                        donate_batches=True)
    assert t_don.donate_batches and not t_ref.donate_batches
    s_ref = t_ref.init_state(jax.random.PRNGKey(9))
    s_don = t_don.init_state(jax.random.PRNGKey(9))
    placed_prev = None
    for rnd in range(8):
        rng = jax.random.PRNGKey(70 + rnd)
        s_ref, l_ref = t_ref.train_round(s_ref, make_round_batches(rnd), rng)
        # two-slot rotation: place round R+1's buffers while round R's
        # (donated) are still owned by the executable, as the loop does
        placed = t_don.place_batches(make_round_batches(rnd))
        if placed_prev is not None:
            # the previous round's donated buffers are dead; the fresh
            # placement must not have resurrected them
            for k in placed:
                assert placed[k] is not placed_prev[k]
        s_don, l_don = t_don.train_round(s_don, placed, rng)
        placed_prev = placed
        assert float(l_ref) == float(l_don), rnd
    assert_trees_bitwise(params_np(s_ref), params_np(s_don), "donate")


def test_donated_batches_are_consumed(net, solver_cfg, trainer_cls):
    """The donation contract: train_round CONSUMES the batch buffers — a
    caller re-feeding the same placed dict must fail loudly (deleted
    arrays), not silently compute on recycled memory."""
    mesh = make_mesh(N_DEV)
    t = trainer_cls(net, solver_cfg, mesh, tau=TAU, donate_batches=True)
    s = t.init_state(jax.random.PRNGKey(0))
    placed = t.place_batches(make_round_batches(0))
    s, loss = t.train_round(s, placed, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    if not any(getattr(v, "is_deleted", lambda: False)()
               for v in placed.values()):
        # XLA:CPU declines batch donation ("donated buffers were not
        # usable") and leaves the arrays alive — the consumed contract is
        # only observable where donation really happens (TPU)
        pytest.skip("backend did not honor batch donation")
    with pytest.raises(Exception):  # RuntimeError: Array has been deleted
        _ = [np.asarray(v) for v in placed.values()]
        t.train_round(s, placed, jax.random.PRNGKey(2))


# -- satellite: jit-cache churn gauge ----------------------------------------


def test_overlapped_round_holds_steady_jit_cache(net, solver_cfg,
                                                 trainer_cls):
    """The overlapped/donating round must hold a STEADY executable cache:
    pre-placement and donation may not introduce shape/layout churn. The
    vanilla trainer's cache plateaus after round 1 (the round-0 entry is
    keyed on the freshly device_put state, round 1 on the round's own
    donated output — same ONE executable, two fast-path keys on this
    jax); the levered trainer must plateau at the SAME count and never
    grow past it."""
    mesh = make_mesh(N_DEV)
    t_ref = trainer_cls(net, solver_cfg, mesh, tau=TAU)
    t_lev = trainer_cls(net, solver_cfg, mesh, tau=TAU,
                        donate_batches=True)
    s_ref = t_ref.init_state(jax.random.PRNGKey(0))
    s_lev = t_lev.init_state(jax.random.PRNGKey(0))
    for rnd in range(2):  # reach steady state (round-0 key + output key)
        rng = jax.random.PRNGKey(rnd)
        s_ref, _ = t_ref.train_round(s_ref, make_round_batches(rnd), rng)
        s_lev, _ = t_lev.train_round(
            s_lev, t_lev.place_batches(make_round_batches(rnd)), rng)
    steady_ref = t_ref.compiled_variants()
    steady_lev = t_lev.compiled_variants()
    assert steady_lev == steady_ref  # no churn introduced by the levers
    for rnd in range(2, 8):
        rng = jax.random.PRNGKey(rnd)
        s_ref, _ = t_ref.train_round(s_ref, make_round_batches(rnd), rng)
        s_lev, _ = t_lev.train_round(
            s_lev, t_lev.place_batches(make_round_batches(rnd)), rng)
        assert t_lev.compiled_variants() == steady_lev, rnd
        assert t_ref.compiled_variants() == steady_ref, rnd


def test_preplaced_wrong_dtype_fails_loudly(net, solver_cfg, trainer_cls):
    """The dtype half of the placement contract is ENFORCED, not just
    documented: a float32 jax.Array fed under the bf16 policy (a caller
    that placed without the compute-dtype cast — cast_host_inputs skips
    device arrays) must fail at first sight, not silently train an f32
    second executable."""
    t = trainer_cls(net, solver_cfg, make_mesh(N_DEV), tau=TAU)
    bad = {k: jnp.asarray(v) for k, v in make_round_batches(0).items()}
    with precision.policy("bfloat16"):
        with pytest.raises(AssertionError, match="compute dtype"):
            t.place_batches(bad)


def test_preplaced_wrong_sharding_fails_loudly(net, solver_cfg,
                                               trainer_cls):
    """The SHARDING half of the placement contract: a jax.Array placed
    without the P(None, data) spec (e.g. a plain single-device
    device_put) must fail at first sight — passing it through would make
    jit reshard it inside every dispatch, a real per-round copy hidden
    behind the passthrough's t_h2d_ms ~ 0."""
    t = trainer_cls(net, solver_cfg, make_mesh(N_DEV), tau=TAU)
    bad = {k: jax.device_put(jnp.asarray(v), jax.devices()[0])
           for k, v in make_round_batches(0).items()}
    with pytest.raises(AssertionError, match="sharding"):
        t.place_batches(bad)


def test_batch_invariants_still_enforced_on_first_call(net, solver_cfg,
                                                       trainer_cls):
    """Hoisting the shape checks to first sight must not lose them: a
    wrong tau or an indivisible batch still fails loudly."""
    t = trainer_cls(net, solver_cfg, make_mesh(N_DEV), tau=TAU)
    good = make_round_batches(0)
    with pytest.raises(AssertionError, match="tau"):
        t.place_batches({k: v[:1] for k, v in good.items()})
    with pytest.raises(AssertionError, match="divisible"):
        t.place_batches({k: v[:, :N_DEV * LOCAL_B - 1]
                         for k, v in good.items()})


def test_pallas_lrn_inside_sharded_round(solver_cfg):
    """The kernel must trace inside the shard_map'd ROUND, not just in a
    bare loss_fn: pallas_call has no shard_map replication rule, so the
    trainer switches replication checking off when the ops config routes
    to a kernel (the net-level parity tests below bypass shard_map and
    cannot catch a trace-time crash here)."""
    net = CompiledNet.compile(net_from_prototxt(CONV_LRN_POOL))
    r = np.random.default_rng(11)
    batches = {
        "data": r.standard_normal((2, 32, 9, 9, 3)).astype(np.float32),
        "label": r.integers(0, 4, (2, 32, 1)).astype(np.int32)}
    t_pal = ParallelTrainer(
        net, solver_cfg, make_mesh(N_DEV), tau=2,
        ops=OpsImpl(lrn="pallas", pool="xla", interpret=True))
    t_xla = ParallelTrainer(
        net, solver_cfg, make_mesh(N_DEV), tau=2,
        ops=OpsImpl(lrn="window", pool="xla"))
    rng = jax.random.PRNGKey(1)
    _, l_pal = t_pal.train_round(
        t_pal.init_state(jax.random.PRNGKey(0)), dict(batches), rng)
    _, l_xla = t_xla.train_round(
        t_xla.init_state(jax.random.PRNGKey(0)), dict(batches), rng)
    assert np.isfinite(float(l_pal))
    assert float(l_pal) == pytest.approx(float(l_xla), rel=1e-3)


# -- pin (c): net-level Pallas-vs-XLA parity under the bf16 policy -----------


def _loss_and_grads(net, ops, batch, params):
    loss_fn = net.loss_fn("loss", ops=ops)
    (loss, _), grads = jax.value_and_grad(
        lambda p: loss_fn(p, batch, jax.random.PRNGKey(0)),
        has_aux=True)(params)
    return float(loss), jax.tree.map(np.asarray, grads)


def _parity_net_and_batch():
    net = CompiledNet.compile(net_from_prototxt(CONV_LRN_POOL))
    r = np.random.default_rng(4)
    batch = {
        "data": jnp.asarray(
            r.standard_normal((128, 9, 9, 3)).astype(np.float32)),
        "label": jnp.asarray(r.integers(0, 4, (128, 1)).astype(np.int32))}
    params = net.init_params(jax.random.PRNGKey(2))
    return net, batch, params


def test_net_level_pallas_lrn_parity_bf16():
    """The LAYER-PATH wiring pin (kernel-level parity lives in
    tests/test_pallas_lrn.py): the same net through ops=(lrn=pallas,
    interpret) vs the explicit XLA fallback, loss + all grads, under the
    bf16 precision policy the TPU headline runs."""
    net, batch, params = _parity_net_and_batch()
    with precision.policy("bfloat16"):
        l_pal, g_pal = _loss_and_grads(
            net, OpsImpl(lrn="pallas", pool="xla", interpret=True),
            batch, params)
        l_xla, g_xla = _loss_and_grads(
            net, OpsImpl(lrn="window", pool="xla"), batch, params)
    # both paths quantize the LRN output to bf16 once; differences are
    # accumulation-order ulps inside the f32 normalizer
    assert l_pal == pytest.approx(l_xla, rel=2e-2)
    for (kp, gp), (_, gx) in zip(
            jax.tree_util.tree_leaves_with_path(g_pal),
            jax.tree_util.tree_leaves_with_path(g_xla)):
        np.testing.assert_allclose(
            np.asarray(gp, np.float32), np.asarray(gx, np.float32),
            rtol=5e-2, atol=5e-3, err_msg=str(kp))


def test_net_level_pallas_pool_parity_bf16():
    """Same wiring pin for the MAX-pool backward kernel. Needs the
    Element/BoundedSlice Pallas API (jax >= 0.5); on older jax the gate
    makes 'auto'/explicit-pallas unavailable and the arm is skipped —
    the XLA fallback is then the ONLY path, which the gate test below
    still pins."""
    from sparknet_tpu.ops.pallas_pool import kernel_api_available
    if not kernel_api_available():
        pytest.skip("pallas pool kernel needs pl.Element (newer jax)")
    net, batch, params = _parity_net_and_batch()
    with precision.policy("bfloat16"):
        l_pal, g_pal = _loss_and_grads(
            net, OpsImpl(lrn="window", pool="pallas", interpret=True),
            batch, params)
        l_xla, g_xla = _loss_and_grads(
            net, OpsImpl(lrn="window", pool="xla"), batch, params)
    # pool forward is reduce_window in BOTH arms; the backward routes every
    # window's dy to the same first-max element — grads match to bf16 ulps
    assert l_pal == pytest.approx(l_xla, rel=1e-2)
    for (kp, gp), (_, gx) in zip(
            jax.tree_util.tree_leaves_with_path(g_pal),
            jax.tree_util.tree_leaves_with_path(g_xla)):
        np.testing.assert_allclose(
            np.asarray(gp, np.float32), np.asarray(gx, np.float32),
            rtol=5e-2, atol=5e-3, err_msg=str(kp))


def test_pool_auto_gate_degrades_to_xla_not_crash():
    """'auto' must NEVER die on a backend where the kernel API is absent
    or the shape gate fails — it silently takes the XLA lowering (the
    explicit fallback); only impl='pallas' is allowed to raise."""
    from sparknet_tpu.ops.pooling import pool2d
    x = jnp.asarray(np.random.default_rng(0).standard_normal(
        (2, 7, 7, 16)).astype(np.float32))  # N=2: fails the 128-lane gate
    y_auto = pool2d(x, "MAX", 3, 2, 0, impl="auto", interpret=True)
    y_xla = pool2d(x, "MAX", 3, 2, 0, impl="xla")
    assert np.array_equal(np.asarray(y_auto), np.asarray(y_xla))
    with pytest.raises(ValueError, match="unsupported"):
        pool2d(x, "MAX", 3, 2, 0, impl="pallas", interpret=True)


def test_ops_impl_validates_at_construction():
    """A typo'd knob fails at config/trainer BUILD, not at the first
    round's trace deep inside jit (the ElasticConfig rule from PR 6)."""
    with pytest.raises(ValueError, match="unknown lrn impl"):
        OpsImpl(lrn="palas")
    with pytest.raises(ValueError, match="unknown pool impl"):
        OpsImpl(pool="window")


def test_ops_knobs_thread_through_trainer(net, solver_cfg):
    """RunConfig-style OpsImpl reaches the compiled round AND survives an
    elastic resize (resized() carries donate_batches + ops)."""
    t = ParallelTrainer(net, solver_cfg, make_mesh(N_DEV), tau=TAU,
                        donate_batches=True,
                        ops=OpsImpl(lrn="window", pool="xla"))
    assert t.ops.lrn == "window"
    s = t.init_state(jax.random.PRNGKey(0))
    s, loss = t.train_round(s, make_round_batches(0), jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    t2 = t.resized(2)
    assert t2.ops == t.ops and t2.donate_batches


# -- loop-level wiring: the knobs through train() ----------------------------


def _run_tiny_train(tmp_path, tag, **overrides):
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    r = np.random.default_rng(0)
    ds = ArrayDataset({
        "data": r.standard_normal((256, 1, 28, 28)).astype(np.float32),
        "label": r.integers(0, 10, (256, 1)).astype(np.int32)})
    root = os.path.join(str(tmp_path), tag)
    os.makedirs(root)
    cfg = RunConfig(model="lenet", n_devices=2, local_batch=8, tau=2,
                    max_rounds=4, eval_every=0, workdir=root,
                    **overrides)
    jsonl = os.path.join(root, "m.jsonl")
    log = Logger(os.path.join(root, "l.txt"), echo=False, jsonl_path=jsonl)
    try:
        train(cfg, lenet(batch=8), ds, None, logger=log)
    finally:
        log.close()
    return [json.loads(l) for l in open(jsonl) if "loss" in l]


def test_train_loop_levers_do_not_change_the_trajectory(tmp_path):
    """train() with every r6 lever ON (the defaults: h2d prefetch on the
    round-prep thread, donated batches) must reproduce the lever-less
    loop's losses BITWISE, and the breakdown rows must show the prefetch
    h2d residual at ~0."""
    on = _run_tiny_train(tmp_path, "on")  # defaults: levers on
    off = _run_tiny_train(tmp_path, "off", h2d_prefetch=False,
                          donate_batches=False)
    assert [rec["step"] for rec in on] == [rec["step"] for rec in off]
    for a, b in zip(on, off):
        assert a["loss"] == b["loss"], (a, b)
    # the placement happened on the prefetch thread: the dispatch-side h2d
    # phase sees only the passthrough (pre-placed contract), not the copy
    assert all("t_h2d_ms" in rec for rec in on)
    on_h2d = [rec["t_h2d_ms"] for rec in on[1:]]   # round 0 places inline
    assert max(on_h2d) < 50.0, on_h2d  # passthrough, not a batch copy


# -- r8: fused τ-boundary + async collect ------------------------------------


def test_fused_boundary_bitwise_multi_round(net, solver_cfg, trainer_cls):
    """The r8 fused τ-boundary (final scan step peeled so the boundary
    pmean — and the ZeRO re-shard under the named trainer — traces in the
    same region as the last optimizer update) must be a pure
    RESTRUCTURING: the same ops on the same values in the same order.
    Pinned bitwise against the unfused two-step round over a multi-round
    trajectory — losses, params, momentum, AND the health scalars —
    under BOTH trainer impls (the conftest trainer_cls matrix)."""
    mesh = make_mesh(N_DEV)
    ref = trainer_cls(net, solver_cfg, mesh, tau=TAU)
    fused = trainer_cls(net, solver_cfg, mesh, tau=TAU,
                        fused_boundary=True)
    assert ref.fused_boundary is False and fused.fused_boundary is True
    s_ref = ref.init_state(jax.random.PRNGKey(0))
    s_fus = fused.init_state(jax.random.PRNGKey(0))
    for rnd in range(4):
        batches = make_round_batches(rnd)
        key = jax.random.PRNGKey(rnd)
        s_ref, l_ref = ref.train_round(s_ref, batches, key)
        s_fus, l_fus = fused.train_round(s_fus, batches, key)
        assert float(l_ref) == float(l_fus), rnd
        for (ka, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(s_ref),
                jax.tree_util.tree_leaves_with_path(s_fus)):
            assert np.array_equal(np.asarray(a), np.asarray(b)), (rnd, ka)
        for k in ("grad_norm", "nonfinite", "nonfinite_by_worker"):
            assert np.array_equal(np.asarray(ref.last_health[k]),
                                  np.asarray(fused.last_health[k])), \
                (rnd, k)


def test_fused_boundary_tau1_and_elastic_masked(net, solver_cfg,
                                                trainer_cls):
    """Edge geometry: τ=1 compiles the fused round scan-free, and an
    elastic_tau-masked round (per-worker budgets, the peeled final step
    masked off for short-budget workers) still pins bitwise against the
    unfused trainer fed the same tau vector."""
    mesh = make_mesh(N_DEV)
    for kw, tau, tbw in (({}, 1, None),
                         ({"elastic_tau": True}, TAU, [1, TAU, 2, TAU])):
        ref = trainer_cls(net, solver_cfg, mesh, tau=tau, **kw)
        fused = trainer_cls(net, solver_cfg, mesh, tau=tau,
                            fused_boundary=True, **kw)
        s_ref = ref.init_state(jax.random.PRNGKey(1))
        s_fus = fused.init_state(jax.random.PRNGKey(1))
        r = np.random.default_rng(5)
        batches = {
            "data": r.standard_normal(
                (tau, N_DEV * LOCAL_B, 6)).astype(np.float32)}
        batches["label"] = (batches["data"].sum(-1, keepdims=True)
                            > 0).astype(np.int32)
        extra = {"tau_by_worker": tbw} if tbw is not None else {}
        s_ref, l_ref = ref.train_round(s_ref, batches,
                                       jax.random.PRNGKey(2), **extra)
        s_fus, l_fus = fused.train_round(s_fus, batches,
                                         jax.random.PRNGKey(2), **extra)
        assert float(l_ref) == float(l_fus), (tau, tbw)
        for (ka, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(s_ref),
                jax.tree_util.tree_leaves_with_path(s_fus)):
            assert np.array_equal(np.asarray(a), np.asarray(b)), \
                (tau, tbw, ka)


def test_fused_boundary_resize_carries_knob(net, solver_cfg, trainer_cls):
    t = trainer_cls(net, solver_cfg, make_mesh(N_DEV), tau=TAU,
                    fused_boundary=True)
    assert t.resized(2).fused_boundary is True


def test_async_collect_loop_bitwise_and_t_collect_zero(tmp_path):
    """The r8 loop levers through the REAL train(). Async collect only
    moves WHERE the deferred fetch blocks (the collector thread, not the
    round loop), so collect on/off must reproduce the same losses
    BITWISE — and with it on, the breakdown's t_collect_ms (the round
    loop's blocking share) must read ~0 with the off-thread fetch
    attributed as t_collect_bg_ms. The fused boundary changes the traced
    program shape (peeled final step), which on conv nets shifts XLA's
    fusion tiling at the last ulp — same caveat the elastic_tau masking
    documents — so fused on/off pins at ulp tolerance here; the BITWISE
    fused pin is the TINY_MLP trainer matrix above."""
    on = _run_tiny_train(tmp_path, "r8_on")  # defaults: fused + async
    sync = _run_tiny_train(tmp_path, "r8_sync", collect_async=False)
    unfused = _run_tiny_train(tmp_path, "r8_unf", fused_boundary=False,
                              collect_async=False)
    assert [rec["step"] for rec in on] == [rec["step"] for rec in sync]
    for a, b in zip(on, sync):
        assert a["loss"] == b["loss"], (a, b)  # collect: bitwise
    for a, b in zip(on, unfused):  # fused: same math, ulp-level conv
        assert abs(a["loss"] - b["loss"]) <= 1e-5 * abs(b["loss"]), (a, b)
    on_rows = [rec for rec in on if "t_collect_ms" in rec]
    assert on_rows, "breakdown rows missing under async collect"
    assert all(rec["t_collect_ms"] == 0.0 for rec in on_rows), on_rows
    assert all("t_collect_bg_ms" in rec for rec in on_rows)
    sync_rows = [rec for rec in sync if "t_collect_ms" in rec]
    assert all("t_collect_bg_ms" not in rec for rec in sync_rows)
