"""Distributed trainer tests on the 8-virtual-device CPU mesh.

The reference had NO tests of its distributed sync loop (SURVEY.md §4); here
the τ-local-step parameter-averaging semantics are verified exactly against a
sequential per-worker oracle built from the same single-device solver.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu import CompiledNet, net_from_prototxt
from sparknet_tpu.parallel import ParallelTrainer, make_mesh
from sparknet_tpu.solver import SgdSolver, SolverConfig, SolverState

TINY_MLP = """
name: "tiny_mlp"
input: "data"
input_shape { dim: 8 dim: 6 }
input: "label"
input_shape { dim: 8 dim: 1 }
layer { name: "ip1" type: "InnerProduct" bottom: "data" top: "ip1"
        inner_product_param { num_output: 16
          weight_filler { type: "gaussian" std: 0.3 } } }
layer { name: "relu1" type: "ReLU" bottom: "ip1" top: "ip1" }
layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
        inner_product_param { num_output: 4
          weight_filler { type: "gaussian" std: 0.3 } } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" bottom: "label" top: "loss" }
layer { name: "acc" type: "Accuracy" bottom: "ip2" bottom: "label" top: "acc" }
"""

N_DEV = 8
TAU = 3
LOCAL_B = 8


@pytest.fixture(scope="module")
def net():
    return CompiledNet.compile(net_from_prototxt(TINY_MLP))


@pytest.fixture(scope="module")
def cfg():
    return SolverConfig(base_lr=0.05, momentum=0.9, weight_decay=0.001,
                        lr_policy="fixed")


def make_round_batches(seed):
    r = np.random.default_rng(seed)
    data = r.standard_normal((TAU, N_DEV * LOCAL_B, 6)).astype(np.float32)
    label = (data.sum(-1, keepdims=True) > 0).astype(np.int32) + \
        (data[..., :1] > 0.5).astype(np.int32)
    return {"data": data, "label": label}


def test_mesh_has_8_devices():
    assert len(jax.devices()) == N_DEV


def test_tau_averaging_matches_sequential_oracle(net, cfg):
    """One full round on the mesh == per-worker sequential simulation."""
    mesh = make_mesh()
    trainer = ParallelTrainer(net, cfg, mesh, tau=TAU)
    state = trainer.init_state(jax.random.PRNGKey(0))
    init_params = trainer.averaged_params(state)
    batches = make_round_batches(1)
    rng = jax.random.PRNGKey(42)
    new_state, loss = trainer.train_round(state, batches, rng)

    # oracle: run each worker's τ steps sequentially with the single-device
    # solver, then average weights (momentum NOT averaged).
    solver = SgdSolver(net, cfg)
    rngs = jax.random.split(rng, N_DEV)
    worker_params = []
    for w in range(N_DEV):
        p = init_params
        s = solver.init_state(p)
        step_rngs = jax.random.split(rngs[w], TAU)
        for t in range(TAU):
            batch = {
                k: jnp.asarray(v[t, w * LOCAL_B:(w + 1) * LOCAL_B])
                for k, v in batches.items()}
            (l, _), grads = jax.value_and_grad(
                lambda p_: net.loss_fn()(p_, batch, step_rngs[t]),
                has_aux=True)(p)
            p, s = solver.update(p, s, grads)
        worker_params.append(p)
    avg = jax.tree.map(lambda *xs: sum(xs) / N_DEV, *worker_params)

    got = trainer.averaged_params(new_state)
    for lname in avg:
        for pname in avg[lname]:
            np.testing.assert_allclose(
                np.asarray(got[lname][pname]), np.asarray(avg[lname][pname]),
                rtol=2e-5, atol=1e-6, err_msg=f"{lname}/{pname}")


def test_round_synchronizes_replicas(net, cfg):
    """After a round every device holds identical params (broadcast is free)."""
    mesh = make_mesh()
    trainer = ParallelTrainer(net, cfg, mesh, tau=TAU)
    state = trainer.init_state(jax.random.PRNGKey(1))
    state, _ = trainer.train_round(state, make_round_batches(2),
                                   jax.random.PRNGKey(7))
    params = np.asarray(state.params["ip1"]["w"])
    for d in range(1, N_DEV):
        np.testing.assert_array_equal(params[0], params[d])
    # momentum stays worker-local => replicas differ (reference parity)
    mom = np.asarray(state.momentum["ip1"]["w"])
    assert not np.array_equal(mom[0], mom[1])


def test_sync_sgd_mode_matches_large_batch(net, cfg):
    """τ=1 gradient-pmean == single-device step on the concatenated batch
    (valid because SoftmaxWithLoss is a per-example mean and all shards are
    equal size)."""
    mesh = make_mesh()
    trainer = ParallelTrainer(net, cfg, mesh, tau=1, mode="sync_sgd")
    state = trainer.init_state(jax.random.PRNGKey(3))
    init_params = trainer.averaged_params(state)
    batches = {k: v[:1] for k, v in make_round_batches(5).items()}
    state, loss = trainer.train_round(state, batches, jax.random.PRNGKey(9))

    solver = SgdSolver(net, cfg)
    big = {k: jnp.asarray(v[0]) for k, v in batches.items()}
    (l, _), grads = jax.value_and_grad(
        lambda p: net.loss_fn()(p, big, None), has_aux=True)(init_params)
    p1, _ = solver.update(init_params, solver.init_state(init_params), grads)

    got = trainer.averaged_params(state)
    np.testing.assert_allclose(np.asarray(got["ip2"]["w"]),
                               np.asarray(p1["ip2"]["w"]), rtol=2e-5, atol=1e-6)
    assert abs(float(loss) - float(l)) < 1e-4


def test_distributed_eval(net, cfg):
    mesh = make_mesh()
    trainer = ParallelTrainer(net, cfg, mesh, tau=TAU)
    state = trainer.init_state(jax.random.PRNGKey(0))
    r = np.random.default_rng(3)
    batch = {
        "data": r.standard_normal((N_DEV * 16, 6)).astype(np.float32),
        "label": r.integers(0, 4, (N_DEV * 16, 1)).astype(np.int32),
    }
    acc = trainer.evaluate(state, batch)
    assert 0.0 <= acc <= 1.0


def test_training_learns(net, cfg):
    """End-to-end: τ-averaged training on 8 devices fits a separable task."""
    mesh = make_mesh()
    trainer = ParallelTrainer(net, cfg, mesh, tau=TAU)
    state = trainer.init_state(jax.random.PRNGKey(0))
    losses = []
    for i in range(25):
        state, loss = trainer.train_round(state, make_round_batches(100 + i),
                                          jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses[::5]


# -- Tensor parallelism (DPxTP hybrid; beyond reference parity) --------------

def test_tp_trajectory_matches_dp_exactly(rng):
    """TP is an exact parallelization: the (data=2, model=2) trainer must
    reproduce the (data=2) trainer's trajectory — same losses, and the
    reassembled full params equal across 3 rounds. Column-parallel
    InnerProduct + all_gather changes only WHERE the math runs.

    Tolerance, not bitwise: splitting the OUTPUT dim leaves every
    contraction whole, so the math is identical — but XLA compiles the
    (in, out) and (in, out/2) dots as different programs and may tile
    their reduction loops differently (observed: in-process compiler
    state from unrelated earlier compilations shifts the choice). A
    1-ulp drift can then flip a ReLU/maxpool decision, and 3 rounds of
    momentum SGD amplify the flip locally — so per-element closeness
    after a trajectory is NOT a stable property to assert tightly. The
    split: losses (each round) and eval stay tight; params get a bound
    loose enough for fp-flip noise but far below what any real TP bug
    (wrong shard, missing gather, skipped averaging) produces."""
    import jax
    from sparknet_tpu import CompiledNet
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh
    from sparknet_tpu.zoo import cifar10_quick

    net = CompiledNet.compile(cifar10_quick(batch=2))
    cfg = SolverConfig(base_lr=0.05, momentum=0.9, weight_decay=0.001,
                       lr_policy="fixed")
    tau, local_b, n_data = 2, 2, 2
    dp = ParallelTrainer(net, cfg, make_mesh(n_data), tau=tau)
    tp = ParallelTrainer(
        net, cfg,
        make_mesh(4, axis_names=("data", "model"), shape=(n_data, 2)),
        tau=tau)
    assert tp.tp == 2
    # ip1 (64) and ip2 (10) both divide 2 -> both column-sharded
    assert {"ip1", "ip2"} <= tp._tp_sharded_layers()

    params0 = net.init_params(jax.random.PRNGKey(3))
    s_dp = dp.state_from_params(params0)
    s_tp = tp.state_from_params(params0)
    for r in range(3):
        batches = {
            "data": rng.standard_normal(
                (tau, n_data * local_b, 32, 32, 3)).astype(np.float32),
            "label": rng.integers(0, 10, (tau, n_data * local_b, 1))
            .astype(np.int32),
        }
        key = jax.random.PRNGKey(100 + r)
        s_dp, l_dp = dp.train_round(s_dp, dict(batches), key)
        s_tp, l_tp = tp.train_round(s_tp, dict(batches), key)
        assert float(l_dp) == pytest.approx(float(l_tp), rel=1e-5)
    full_dp = dp.averaged_params(s_dp)
    full_tp = tp.averaged_params(s_tp)
    for lname in full_dp:
        for pname in full_dp[lname]:
            np.testing.assert_allclose(
                np.asarray(full_tp[lname][pname]),
                np.asarray(full_dp[lname][pname]), rtol=1e-3, atol=5e-4,
                err_msg=f"{lname}/{pname}")
    # eval agrees too
    ev = {"data": batches["data"][0], "label": batches["label"][0]}
    assert dp.evaluate(s_dp, ev) == pytest.approx(tp.evaluate(s_tp, ev),
                                                  abs=1e-6)


# -- velocity_dtype across resume (r3 advisor) -------------------------------

def test_resume_casts_momentum_to_configured_velocity_dtype(net, cfg, tmp_path):
    """A checkpoint carries the momentum dtype it was trained with; resuming
    under a different SolverConfig.velocity_dtype must apply the CONFIGURED
    dtype, not silently inherit the checkpoint's (r3 advisor). Both resume
    paths funnel through ParallelTrainer.place, so each is checked."""
    from dataclasses import replace
    from sparknet_tpu.parallel.mesh import fetch_global
    from sparknet_tpu.utils import checkpoint as ckpt

    f32 = ParallelTrainer(net, cfg, make_mesh(), tau=TAU)
    state, _ = f32.train_round(f32.init_state(jax.random.PRNGKey(0)),
                               make_round_batches(0), jax.random.PRNGKey(1))
    ckpt.save(str(tmp_path), fetch_global(state), step=1,
              extra={"n_devices": N_DEV, "tp": 1})
    flat, _, _ = ckpt.restore_flat(str(tmp_path))

    bf16 = ParallelTrainer(net, replace(cfg, velocity_dtype="bfloat16"),
                           make_mesh(), tau=TAU)
    # same-topology path (train_loop: place(unflatten_like(...)))
    restored = bf16.place(ckpt.unflatten_like(
        bf16.init_state(jax.random.PRNGKey(0)), flat))
    for leaf in jax.tree.leaves(restored.momentum):
        assert leaf.dtype == jnp.bfloat16
    for leaf in jax.tree.leaves(restored.params):
        assert leaf.dtype == jnp.float32  # params untouched
    # elastic path (adapt_state -> state_from_params -> place): use a
    # DIFFERENT device count, or the r5 same-topology shortcut bypasses
    # the reassembly this is meant to pin
    bf16_half = ParallelTrainer(net, replace(cfg, velocity_dtype="bfloat16"),
                                make_mesh(N_DEV // 2), tau=TAU)
    adapted = bf16_half.adapt_state(flat)
    for leaf in jax.tree.leaves(adapted.momentum):
        assert leaf.dtype == jnp.bfloat16
    # and the same-topology shortcut path casts too
    adapted_same = bf16.adapt_state(flat)
    for leaf in jax.tree.leaves(adapted_same.momentum):
        assert leaf.dtype == jnp.bfloat16
    # the restored state trains (dtype layout matches the jitted round)
    restored, loss = bf16.train_round(restored, make_round_batches(1),
                                      jax.random.PRNGKey(2))
    assert np.isfinite(float(loss))
    # and the reverse direction: bf16 checkpoint into an f32 run
    ckpt.save(str(tmp_path), fetch_global(restored), step=2,
              extra={"n_devices": N_DEV, "tp": 1})
    flat2, _, _ = ckpt.restore_flat(str(tmp_path))
    back = f32.place(ckpt.unflatten_like(
        f32.init_state(jax.random.PRNGKey(0)), flat2))
    for leaf in jax.tree.leaves(back.momentum):
        assert leaf.dtype == jnp.float32
