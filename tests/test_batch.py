"""Bulk inference at fleet scale (r14) — the `sparknet_tpu.batch`
subsystem and its serve/fleet satellites:

  - work-unit planning + the resumable manifest (manifest-LAST commit
    semantics, resume-identity pins);
  - the batch object-store surface (atomic local writes, temp files
    invisible to listings);
  - the per-request named-output route on BOTH frontends (and through
    the router's proxy hop), unknown blobs rejected TYPED;
  - journal rows carry priority + deadline_ms on both frontends;
  - hedging skips the low class (hedged_total flat under a low flood);
  - admission's batch-starvation clock + the policy's scavenger
    signals (low backlog is not online demand; relief bounds
    starvation);
  - the driver end-to-end: resume exactly-once, a dead replica is a
    retry (not a job failure), kill -9 chaos against local and
    fake-gs:// output stores.

Tier-1: CPU backend, lenet shapes, ephemeral ports.
"""
import http.client
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from sparknet_tpu.batch import (BatchConfig, BatchDriver, load_manifest,
                                manifest as mf, store)
from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.serve import (BinaryClient, BinaryFrontend,
                                HttpFrontend, InferenceServer,
                                ModelRouter, PriorityAdmission,
                                RouterConfig, ServeConfig, binary_infer,
                                http_infer)
from sparknet_tpu.serve.http_frontend import (NPZ_CONTENT_TYPE,
                                              _encode_npz)
from sparknet_tpu.utils.logger import Logger
from sparknet_tpu.zoo import lenet

from fake_stores import bucket_store


def _example(i: int) -> dict:
    r = np.random.default_rng(9000 + i)
    return {"data": r.standard_normal((28, 28, 1)).astype(np.float32)}


def _input_npz(path, n: int) -> str:
    r = np.random.default_rng(7)
    np.savez(str(path),
             data=r.standard_normal((n, 28, 28, 1)).astype(np.float32))
    return str(path)


@pytest.fixture(scope="module")
def served():
    """One lenet replica behind both front doors, shared per module."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, buckets=(1, 4),
                      outputs=("prob",), metrics_every_batches=0)
    srv = InferenceServer(JaxNet(lenet(batch=4)), cfg)
    srv.start()
    bfe = BinaryFrontend(srv, port=0)
    hfe = HttpFrontend(srv, port=0)
    yield srv, bfe, hfe
    bfe.stop()
    hfe.stop()
    srv.stop()


# -- manifest -----------------------------------------------------------------

def test_plan_units_disjoint_cover_ragged():
    units = mf.plan_units(20, 6)
    assert units == [(0, 6), (6, 12), (12, 18), (18, 20)]
    assert units[-1][1] - units[-1][0] == 2  # ragged tail kept
    with pytest.raises(ValueError):
        mf.plan_units(0, 6)
    with pytest.raises(ValueError):
        mf.plan_units(6, 0)


def test_manifest_roundtrip_pending_and_done(tmp_path):
    m = mf.new_manifest("j1", "in.npz", 20, 6, "m", ("fc1",))
    assert m["n_units"] == 4 and not m["done"]
    assert [(u, lo, hi) for u, lo, hi in mf.pending_units(m)] == \
        [(0, 0, 6), (1, 6, 12), (2, 12, 18), (3, 18, 20)]
    mf.record_unit(m, 1, 6, 12, 123, "r1", 1)
    assert not m["done"]
    assert [u for u, _, _ in mf.pending_units(m)] == [0, 2, 3]
    for uid, lo, hi in mf.pending_units(m):
        mf.record_unit(m, uid, lo, hi, 1, "r1", 1)
    assert m["done"]
    mf.save_manifest(str(tmp_path), m)
    m2 = mf.load_manifest(str(tmp_path))
    assert m2 == m
    assert mf.load_manifest(str(tmp_path / "nowhere")) is None


def test_manifest_resume_identity_pinned(tmp_path):
    """A resume against a different input/plan/model/outputs must fail
    loudly — silently interleaving two jobs' rows under one manifest is
    exactly the bug the identity fields exist to stop."""
    m = mf.new_manifest("j1", "in.npz", 20, 6, "m", ("fc1",))
    mf.check_resume(m, "in.npz", 20, 6, "m", ("fc1",))  # same job: fine
    for bad in (("OTHER.npz", 20, 6, "m", ("fc1",)),
                ("in.npz", 21, 6, "m", ("fc1",)),
                ("in.npz", 20, 7, "m", ("fc1",)),
                ("in.npz", 20, 6, "m2", ("fc1",)),
                ("in.npz", 20, 6, "m", ("fc2",))):
        with pytest.raises(ValueError, match="resume"):
            mf.check_resume(m, *bad)


def test_manifest_version_pinned(tmp_path):
    store.write_bytes(str(tmp_path / mf.MANIFEST_NAME),
                      json.dumps({"version": 999}).encode())
    with pytest.raises(ValueError, match="version"):
        mf.load_manifest(str(tmp_path))


# -- store --------------------------------------------------------------------

def test_store_local_roundtrip_and_tmp_invisible(tmp_path):
    url = str(tmp_path / "a" / "b.bin")
    assert not store.exists(url)
    store.write_bytes(url, b"xyz")
    assert store.exists(url) and store.read_bytes(url) == b"xyz"
    # an interrupted writer's temp file never appears in listings
    (tmp_path / "a" / ".tmp-torn").write_bytes(b"partial")
    assert store.list_names(str(tmp_path / "a")) == ["b.bin"]
    store.delete(url)
    store.delete(url)  # idempotent
    assert not store.exists(url)
    assert store.list_names(str(tmp_path / "missing")) == []


def test_store_gs_roundtrip():
    with bucket_store("gs") as (root, _srv):
        url = store.join(root, "job", "part-00000.npz")
        assert store.is_bucket(url)
        assert not store.exists(url)
        store.write_bytes(url, b"npzbytes")
        assert store.exists(url)
        assert store.read_bytes(url) == b"npzbytes"
        assert store.list_names(store.join(root, "job")) == \
            ["part-00000.npz"]


# -- the named-output route ---------------------------------------------------

def test_outputs_route_parity_both_frontends(served):
    """Request fc1 by name over BOTH wires: each returns exactly that
    blob, bitwise equal (same replica, same bucket, raw f32 both
    ways); no outputs = the lane's configured default."""
    srv, bfe, hfe = served
    x = _example(0)
    hurl = f"http://{hfe.address[0]}:{hfe.address[1]}"
    out_b = binary_infer(bfe.address, "default", x, deadline_s=30.0,
                         outputs=("fc1",))
    out_h = http_infer(hurl, "default", x, deadline_s=30.0,
                       outputs=("fc1",))
    assert set(out_b) == set(out_h) == {"fc1"}
    np.testing.assert_array_equal(out_b["fc1"], out_h["fc1"])
    assert set(binary_infer(bfe.address, "default", x,
                            deadline_s=30.0)) == {"prob"}


def test_outputs_route_json_body(served):
    """The JSON data plane names blobs via an `outputs` list."""
    _, _, hfe = served
    x = _example(1)
    conn = http.client.HTTPConnection(*hfe.address, timeout=30)
    conn.request("POST", "/v1/models/default/infer",
                 body=json.dumps({
                     "inputs": {"data": x["data"].tolist()},
                     "outputs": ["fc2", "prob"]}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 200, body
    assert set(body["outputs"]) == {"fc2", "prob"}


def test_unknown_output_blob_rejected_typed(served):
    """An unknown blob name must be a TYPED 400 at submit, not rows
    silently missing from the reply (net.forward drops unknown names)."""
    srv, bfe, hfe = served
    x = _example(2)
    with pytest.raises(ValueError, match="unknown output blob"):
        binary_infer(bfe.address, "default", x, deadline_s=30.0,
                     outputs=("not_a_blob",))
    conn = http.client.HTTPConnection(*hfe.address, timeout=30)
    conn.request("POST", "/v1/models/default/infer",
                 body=json.dumps({"inputs": {"data": x["data"].tolist()},
                                  "outputs": ["not_a_blob"]}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 400
    assert body["error_kind"] == "bad_request"
    assert "not_a_blob" in body["error"]


def test_outputs_route_through_router_proxy_hop(served):
    """The outputs selection rides the payload through the router's
    proxy hop untouched and is honored by the TERMINAL lane."""
    _, bfe, _ = served
    router = ModelRouter(RouterConfig(workers=2))
    router.add_remote_replica("default",
                              f"spkn://127.0.0.1:{bfe.address[1]}")
    router.start()
    rfe = BinaryFrontend(router, port=0)
    try:
        out = binary_infer(rfe.address, "default", _example(3),
                           deadline_s=30.0, outputs=("fc1", "prob"))
        assert set(out) == {"fc1", "prob"}
    finally:
        rfe.stop()
        router.stop()


# -- journal rows carry the admission identity (satellite pin) ----------------

def test_journal_rows_pin_priority_and_deadline(served, tmp_path):
    srv, _, _ = served
    jpath = tmp_path / "journal.jsonl"
    journal = Logger(jsonl_path=str(jpath), echo=False)
    bfe = BinaryFrontend(srv, port=0, journal=journal)
    hfe = HttpFrontend(srv, port=0, journal=journal)
    try:
        binary_infer(bfe.address, "default", _example(4),
                     deadline_s=2.5, tenant="batch", priority="low")
        conn = http.client.HTTPConnection(*hfe.address, timeout=30)
        conn.request("POST", "/v1/models/default/infer",
                     body=_encode_npz(_example(5)),
                     headers={"Content-Type": NPZ_CONTENT_TYPE,
                              "Accept": NPZ_CONTENT_TYPE,
                              "X-Priority": "low",
                              "X-Deadline-Ms": "2500"})
        conn.getresponse().read()
        conn.close()
    finally:
        bfe.stop()
        hfe.stop()
        journal.close()
    rows = [json.loads(l) for l in
            jpath.read_text().strip().splitlines()]
    by_transport = {r["transport"]: r for r in rows}
    assert set(by_transport) == {"binary", "http"}
    for r in by_transport.values():
        assert r["priority"] == "low"
        assert r["deadline_ms"] == pytest.approx(2500.0)


# -- hedging skips the scavenger class (satellite pin) ------------------------

def test_hedge_skips_low_priority():
    """Under a config where NORMAL traffic hedges on nearly every
    request (min-delay 0, budget 1.0), a low-priority flood must leave
    hedged_total flat: a scavenger's latency is not worth a second
    replica's cycles."""
    reps = []
    for _ in range(2):
        cfg = ServeConfig(model_name="m", max_batch=4, max_wait_ms=2.0,
                          outputs=("prob",), metrics_every_batches=0)
        s = InferenceServer(JaxNet(lenet(batch=4)), cfg)
        s.start()
        reps.append((s, BinaryFrontend(s, port=0)))
    router = ModelRouter(RouterConfig(workers=4, hedge=True,
                                      hedge_min_delay_ms=0.0,
                                      hedge_budget=1.0))
    for _, fe in reps:
        router.add_remote_replica(
            "m", f"spkn://127.0.0.1:{fe.address[1]}")
    router.start()
    try:
        # positive control first, on the FRESH router (empty latency
        # window -> hedge delay 0): normal traffic hedges, so a flat
        # counter below means the skip, not broken hedging
        futs = [router.submit("m", _example(i), deadline_s=30.0)
                for i in range(16)]
        for f in futs:
            f.result(timeout=30.0)
        hedged_before = router.status()["hedging"]["m"]["hedged"]
        assert hedged_before > 0
        # now the scavenger flood: hedged_total stays flat
        futs = [router.submit("m", _example(i), deadline_s=30.0,
                              priority="low") for i in range(16)]
        for f in futs:
            f.result(timeout=30.0)
        assert router.status()["hedging"]["m"]["hedged"] == \
            hedged_before
    finally:
        router.stop()
        for s, fe in reps:
            fe.stop()
            s.stop()


# -- admission starvation clock + policy scavenger signals --------------------

def test_admission_batch_starvation_clock():
    adm = PriorityAdmission()
    assert adm.starvation_s() == 0.0
    adm.set_pressure(0.9)
    assert adm.admit(None, "low") == "priority"
    time.sleep(0.05)
    s1 = adm.starvation_s()
    assert s1 >= 0.05
    assert adm.admit(None, "low") == "priority"
    assert adm.starvation_s() >= s1  # one clock, not reset per shed
    assert adm.status()["batch_starvation_s"] >= 0.05
    adm.set_pressure(0.0)
    assert adm.admit(None, "low") is None  # admitted: clock resets
    assert adm.starvation_s() == 0.0


def test_admission_high_sheds_do_not_start_the_clock():
    adm = PriorityAdmission()
    adm.set_pressure(1.0)  # everything below 'high' sheds
    assert adm.admit(None, "normal") == "priority"
    assert adm.starvation_s() == 0.0  # the clock is the LOW class's


def test_policy_low_queue_is_not_online_demand():
    from sparknet_tpu.fleet import FleetPolicy
    from sparknet_tpu.fleet.policy import ModelSignals

    pol = FleetPolicy()

    def sig(queue_frac, low_frac):
        return ModelSignals(model="m", p99_ms=None, slo_p99_ms=None,
                            n_window=0, queue_frac=queue_frac,
                            shed_per_s=0.0, replicas=1, routable=1,
                            low_queue_frac=low_frac)
    # a queue FULL of scavenger units: not hot, still cold
    assert pol.hot_reason(sig(0.9, 0.9)) is None
    assert pol.is_cold(sig(0.9, 0.9))
    # the same depth of online work: hot, not cold
    assert pol.hot_reason(sig(0.9, 0.0)) == "queue"
    assert not pol.is_cold(sig(0.9, 0.0))


def test_policy_batch_relief_bounds_starvation():
    from sparknet_tpu.fleet import FleetPolicy

    pol = FleetPolicy(batch_max_starvation_s=5.0,
                      batch_relief_pressure=0.45)
    assert not pol.batch_relief(4.9, 0.9)    # not starved long enough
    assert not pol.batch_relief(60.0, 0.45)  # pressure already at/below
    assert pol.batch_relief(5.0, 0.9)        # starved + door shut
    with pytest.raises(ValueError):
        FleetPolicy(batch_max_starvation_s=0.0)
    with pytest.raises(ValueError):
        FleetPolicy(batch_relief_pressure=1.0)


# -- the driver ---------------------------------------------------------------

def _job_cfg(inp, out, addrs, **kw):
    base = dict(input=str(inp), output=str(out),
                replicas=list(addrs), outputs=("fc1",), unit_rows=6,
                window=4, concurrency=2, deadline_s=30.0,
                request_timeout_s=60.0)
    base.update(kw)
    return BatchConfig(**base)


def _assert_exactly_once(out_dir, n_rows, unit_rows, blob="fc1"):
    """The committed artifacts ARE the exactly-once proof: manifest
    ranges equal the plan (disjoint, covering), each part holds exactly
    its unit's rows."""
    m = load_manifest(str(out_dir))
    assert m is not None and m["done"]
    plan = mf.plan_units(n_rows, unit_rows)
    got = sorted((u["start"], u["stop"]) for u in m["units"].values())
    assert got == sorted(plan)
    total = 0
    for uid_s, u in m["units"].items():
        with np.load(os.path.join(str(out_dir),
                                  mf.part_name(int(uid_s)))) as z:
            assert z[blob].shape[0] == u["rows"]
            total += z[blob].shape[0]
    assert total == n_rows


def test_driver_end_to_end_and_resume(served, tmp_path):
    _, bfe, _ = served
    addr = f"{bfe.address[0]}:{bfe.address[1]}"
    inp = _input_npz(tmp_path / "in.npz", 20)
    out = tmp_path / "out"
    res = BatchDriver(_job_cfg(inp, out, [addr])).run()
    assert res["done"] and res["units_this_run"] == 4
    assert res["rows_this_run"] == 20 and res["rows_per_s"] > 0
    _assert_exactly_once(out, 20, 6)
    # rerun on a done job: nothing recomputed
    res2 = BatchDriver(_job_cfg(inp, out, [addr])).run()
    assert res2["units_this_run"] == 0
    assert res2["units_skipped_resume"] == 4
    # an orphan part (crash between part write and manifest row) is
    # redone: drop a unit from the manifest but leave its part behind
    m = load_manifest(str(out))
    del m["units"]["2"]
    m["done"] = False
    mf.save_manifest(str(out), m)
    res3 = BatchDriver(_job_cfg(inp, out, [addr])).run()
    assert res3["units_this_run"] == 1 and res3["done"]
    _assert_exactly_once(out, 20, 6)


def test_driver_resume_identity_mismatch_fails_loudly(served, tmp_path):
    _, bfe, _ = served
    addr = f"{bfe.address[0]}:{bfe.address[1]}"
    inp = _input_npz(tmp_path / "in.npz", 12)
    out = tmp_path / "out"
    BatchDriver(_job_cfg(inp, out, [addr], unit_rows=6)).run()
    with pytest.raises(ValueError, match="resume"):
        BatchDriver(_job_cfg(inp, out, [addr], unit_rows=4)).run()


def test_driver_cost_and_metrics_accounting(served, tmp_path):
    _, bfe, _ = served
    addr = f"{bfe.address[0]}:{bfe.address[1]}"
    inp = _input_npz(tmp_path / "in.npz", 12)
    drv = BatchDriver(_job_cfg(inp, tmp_path / "out", [addr],
                               cost_per_replica_hour=3.6))
    res = drv.run()
    # summary fields are rounded independently; pin consistency, not
    # the exact float
    assert res["cost_usd"] > 0
    assert res["cost_per_million_embeddings"] == pytest.approx(
        res["cost_usd"] / (12 / 1e6), rel=2e-2)
    reg = drv.registry
    assert reg.counter("sparknet_batch_units_done_total").value() == 2
    assert reg.counter("sparknet_batch_rows_total").value() == 12
    assert reg.counter(
        "sparknet_batch_output_bytes_total").value() == \
        res["output_bytes"] > 0


def test_driver_dead_replica_is_a_retry_not_a_job_failure(
        served, tmp_path):
    """One of the two 'replicas' is a dead port: every unit that
    rotates onto it takes a typed hard retry and completes on the
    living one — the fleet contract, without a subprocess."""
    _, bfe, _ = served
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()  # nothing listens here now
    addr = f"{bfe.address[0]}:{bfe.address[1]}"
    inp = _input_npz(tmp_path / "in.npz", 24)
    drv = BatchDriver(_job_cfg(inp, tmp_path / "out", [dead, addr],
                               backoff_cap_s=0.05))
    res = drv.run()
    assert res["done"]
    assert res["retries"] > 0
    assert int(drv._c_retries.value(kind="error") or 0) > 0
    _assert_exactly_once(tmp_path / "out", 24, 6)


def test_driver_all_replicas_dead_fails_named(tmp_path):
    from sparknet_tpu.batch.driver import UnitFailedError
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    inp = _input_npz(tmp_path / "in.npz", 6)
    with pytest.raises(UnitFailedError, match="hard failures"):
        BatchDriver(_job_cfg(inp, tmp_path / "out", [dead],
                             max_attempts=2, backoff_cap_s=0.01)).run()


def test_driver_rejects_bad_config():
    with pytest.raises(ValueError):
        BatchConfig(input="x", output="y", replicas=[])
    with pytest.raises(ValueError):
        BatchConfig(input="x", output="y", replicas=["a:1"],
                    unit_rows=0)
    with pytest.raises(ValueError):
        BatchConfig(input="x", output="y", replicas=["a:1"],
                    max_attempts=0)


# -- kill -9 chaos ------------------------------------------------------------

def _spawn_driver(inp, out, addrs, extra=()):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "sparknet_tpu.batch.driver",
         "--input", str(inp), "--out", str(out),
         "--replicas", ",".join(addrs), "--outputs", "fc1",
         "--unit-rows", "6", "--window", "4", "--concurrency", "1",
         "--pace-s", "0.25", "--timeout-s", "60",
         "--deadline-ms", "30000", *extra],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)


def _kill_mid_job(proc, out_dir, min_units=1):
    """Wait for >= min_units committed units, then SIGKILL."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < 120.0:
        if proc.poll() is not None:
            pytest.fail("driver exited before the kill window")
        m = load_manifest(str(out_dir))
        if m is not None and len(m["units"]) >= min_units:
            break
        time.sleep(0.05)
    else:
        pytest.fail("driver never committed a unit to kill against")
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30.0)


@pytest.mark.chaos
def test_driver_kill9_resumes_exactly_once_local(served, tmp_path):
    _, bfe, _ = served
    addr = f"{bfe.address[0]}:{bfe.address[1]}"
    inp = _input_npz(tmp_path / "in.npz", 48)
    out = tmp_path / "out"
    proc = _spawn_driver(inp, out, [addr])
    _kill_mid_job(proc, out)
    partial = load_manifest(str(out))
    assert partial is not None and not partial["done"]
    done_before = len(partial["units"])
    assert 0 < done_before < partial["n_units"]
    res = BatchDriver(_job_cfg(inp, out, [addr])).run()
    assert res["done"]
    assert res["units_skipped_resume"] == done_before
    assert res["units_this_run"] == partial["n_units"] - done_before
    _assert_exactly_once(out, 48, 6)


@pytest.mark.chaos
def test_driver_kill9_resumes_exactly_once_fake_gs(served, tmp_path):
    """Same kill -9 contract with the output shards and manifest living
    in a (fake) gs:// bucket: bucket objects finalize atomically, so
    manifest-last holds there too. The killed subprocess inherits the
    emulator env; the resuming in-process driver shares it."""
    _, bfe, _ = served
    addr = f"{bfe.address[0]}:{bfe.address[1]}"
    inp = _input_npz(tmp_path / "in.npz", 48)
    with bucket_store("gs") as (root, _srv):
        out = store.join(root, "job-kill")
        proc = _spawn_driver(inp, out, [addr])
        _kill_mid_job(proc, out)
        partial = load_manifest(out)
        assert partial is not None and not partial["done"]
        done_before = len(partial["units"])
        assert 0 < done_before < partial["n_units"]
        res = BatchDriver(_job_cfg(inp, out, [addr])).run()
        assert res["done"]
        assert res["units_skipped_resume"] == done_before
        m = load_manifest(out)
        got = sorted((u["start"], u["stop"])
                     for u in m["units"].values())
        assert got == sorted(mf.plan_units(48, 6))
        names = store.list_names(out)
        assert set(names) == {mf.MANIFEST_NAME} | {
            mf.part_name(u) for u in range(m["n_units"])}


# -- the metrics summary's batch view -----------------------------------------

def test_summary_batch_view():
    from sparknet_tpu.obs.summary import format_text, summarize

    recs = [
        {"step": 0, "event": "batch_unit", "unit": 0, "rows": 6,
         "replica": "a:1", "attempts": 1, "bytes": 100, "dt_s": 0.1},
        {"step": 1, "event": "batch_unit", "unit": 1, "rows": 6,
         "replica": "b:2", "attempts": 2, "bytes": 100, "dt_s": 0.2},
        {"step": 1, "event": "batch_retry", "unit": 1, "kind": "shed",
         "replica": "a:1", "attempt": 1, "error": "PriorityShedError"},
        {"step": 2, "event": "batch_done", "job_id": "j", "done": True,
         "units_total": 2, "units_done": 2, "rows_total": 12,
         "elapsed_s": 0.3, "rows_per_s": 40.0, "retries": 1,
         "cost_per_million_embeddings": 1.5},
    ]
    s = summarize(recs)
    b = s["batch"]
    assert b["units"] == 2 and b["rows"] == 12
    assert b["retries_by_kind"] == {"shed": 1}
    assert b["units_by_replica"] == {"a:1": 1, "b:2": 1}
    assert b["attempts_max"] == 2
    assert b["jobs"]["j"]["done"] and \
        b["jobs"]["j"]["cost_per_million_embeddings"] == 1.5
    text = format_text(s)
    assert "batch view" in text and "$1.5/M embeddings" in text
    assert "batch" not in summarize([{"step": 0, "loss": 1.0}])
