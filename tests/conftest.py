"""Test fixtures. Must run before jax initializes: force CPU platform with 8
virtual devices so multi-chip sharding is tested without TPU hardware (the
reference had no distributed tests at all — see SURVEY.md §4)."""
import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The TPU plugin pins jax_platforms at interpreter boot (sitecustomize), so a
# plain env var is not enough — override via jax.config before backend init.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _logs_to_tmp(tmp_path, monkeypatch):
    """Any code path that falls back to the default log location
    (RunConfig.workdir=None -> $SPARKNET_TPU_HOME) writes under tmp, never
    the repo root."""
    monkeypatch.setenv("SPARKNET_TPU_HOME", str(tmp_path))


@pytest.fixture(autouse=True)
def _precision_policy_isolated():
    """Restore the (thread-local) precision policy after every test: the
    bench arms set bfloat16 on the main thread and a leaked policy turns
    later f32-exactness tests red — a latent cross-file coupling that only
    shows when the whole suite runs in one process past test_bench."""
    import jax.numpy as jnp

    from sparknet_tpu import precision
    prev = ("bfloat16" if precision.compute_dtype() == jnp.bfloat16
            else "float32")
    yield
    precision.set_policy(prev)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session", params=["shard_map", "named"])
def trainer_cls(request):
    """Both layer-IR trainer implementations (r7): the shard_map replica-
    layout ParallelTrainer and the NamedSharding logical-state
    ShardedTrainer. Trainer-facing tests take this fixture so the parity
    pin is the test MATRIX itself — every round-pipeline, elastic, and
    health-layout behavior must hold under either implementation."""
    from sparknet_tpu.parallel import ParallelTrainer, ShardedTrainer
    return (ParallelTrainer if request.param == "shard_map"
            else ShardedTrainer)
