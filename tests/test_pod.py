"""Pod-scope observability (sparknet_tpu.obs.pod + obs.device): exposition
parse/merge (counter sums, gauge max/min, histogram pod sums), straggler
attribution over fake workers (http and heartbeat-file modes), the
/pod/status endpoint, the train loop's pod wiring, device telemetry, and
the compile counters (CompiledNet + serve bucket forwards)."""
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from sparknet_tpu.obs import MetricsRegistry, StatusServer
from sparknet_tpu.obs.pod import (PodAggregator, flag_stragglers,
                                  format_pod_table, merge_expositions,
                                  parse_exposition, render_exposition,
                                  worker_heartbeat_path)
from sparknet_tpu.utils.health import mad_classify
from sparknet_tpu.utils.heartbeat import HeartbeatWriter


# -- exposition parse / merge / render ---------------------------------------

def _registry(rounds: int, round_s: float, lat=(0.05,)) -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter("sparknet_train_rounds_total", "rounds").inc(rounds)
    reg.gauge("sparknet_train_round_seconds", "round").set(round_s)
    h = reg.histogram("sparknet_serve_request_latency_seconds", "lat",
                      buckets=(0.1, 1.0))
    for v in lat:
        h.observe(v)
    reg.counter("sparknet_health_rounds_total", "cls",
                labels=("cls",)).inc(rounds, cls="ok")
    return reg


def test_parse_roundtrip_scalars_and_histograms():
    reg = _registry(7, 0.25, lat=(0.05, 0.5, 5.0))
    fams = parse_exposition(reg.render_prometheus())
    assert fams["sparknet_train_rounds_total"].kind == "counter"
    assert fams["sparknet_train_rounds_total"].samples[()] == 7
    assert fams["sparknet_health_rounds_total"].samples[
        (("cls", "ok"),)] == 7
    h = fams["sparknet_serve_request_latency_seconds"].hists[()]
    assert h["count"] == 3 and h["sum"] == pytest.approx(5.55)
    assert h["le"]["0.1"] == 1 and h["le"]["1"] == 2 and h["le"]["+Inf"] == 3


def test_parse_escaped_labels():
    reg = MetricsRegistry()
    reg.gauge("g", labels=("path",)).set(1, path='a"b\\c\nd')
    fams = parse_exposition(reg.render_prometheus())
    assert fams["g"].samples[(("path", 'a"b\\c\nd'),)] == 1


def test_merge_counter_sums_gauge_minmax_hist_podsum():
    per = {"0": parse_exposition(_registry(10, 0.1).render_prometheus()),
           "1": parse_exposition(_registry(6, 0.4).render_prometheus())}
    merged = merge_expositions(per)
    text = render_exposition(merged)
    # counters: per-worker children + worker="pod" sum
    assert 'sparknet_train_rounds_total{worker="0"} 10' in text
    assert 'sparknet_train_rounds_total{worker="1"} 6' in text
    assert 'sparknet_train_rounds_total{worker="pod"} 16' in text
    assert 'sparknet_health_rounds_total{cls="ok",worker="pod"} 16' in text
    # gauges: max/min envelope labels
    assert 'sparknet_train_round_seconds{worker="max"} 0.4' in text
    assert 'sparknet_train_round_seconds{worker="min"} 0.1' in text
    # histograms: pod-summed cumulative buckets
    assert ('sparknet_serve_request_latency_seconds_count{worker="pod"} 2'
            in text)
    # the merged text is itself parseable (round trip)
    again = parse_exposition(text)
    assert again["sparknet_train_rounds_total"].samples[
        (("worker", "pod"),)] == 16


def test_merge_kind_conflict_degrades_family_not_scrape():
    a = MetricsRegistry()
    a.counter("m").inc(3)
    b = MetricsRegistry()
    b.gauge("m").set(9)
    merged = merge_expositions(
        {"0": parse_exposition(a.render_prometheus()),
         "1": parse_exposition(b.render_prometheus())})
    # first-seen kind (worker 0's counter) wins; worker 1's sample skipped
    assert merged["m"].kind == "counter"
    assert merged["m"].samples[(("worker", "pod"),)] == 3
    assert (("worker", "1"),) not in merged["m"].samples


# -- straggler classification ------------------------------------------------

def test_mad_classify_flags_and_floor():
    med, sigma, flags = mad_classify([1.0, 1.0, 1.0, 10.0])
    assert flags == [False, False, False, True]
    assert med == 1.0 and sigma > 0  # floored despite MAD == 0
    # equal values: nothing flagged, ever
    assert mad_classify([2.0] * 8)[2] == [False] * 8
    # n < 3 never flags (MAD is degenerate)
    assert mad_classify([1.0, 100.0])[2] == [False, False]


def test_flag_stragglers_two_worker_ratio_rule():
    # 2 workers: MAD cannot fire; the ratio rule names the slower one
    med, skew, flagged = flag_stragglers({"0": 0.1, "1": 1.0})
    assert flagged == {"1"}
    assert skew == pytest.approx(1.0 - med)
    # clean 2-worker pod: nothing flagged
    assert flag_stragglers({"0": 0.1, "1": 0.11})[2] == set()
    # 3+ workers use median+MAD
    assert flag_stragglers({"0": 1.0, "1": 1.0, "2": 10.0})[2] == {"2"}
    assert flag_stragglers({"0": 1.0, "1": 1.0, "2": 1.0})[2] == set()


# -- the aggregator: http mode -----------------------------------------------

@pytest.fixture
def two_workers():
    """Two in-process fake workers behind real StatusServers; worker 1 is
    a 10x straggler. Yields (urls, vitals) with servers torn down after."""
    vitals = [{"role": "train", "round": 10, "status": "ok", "loss": 1.0,
               "round_s": 0.1, "data_wait_s": 0.001, "rollbacks": 0},
              {"role": "train", "round": 9, "status": "ok", "loss": 1.2,
               "round_s": 1.0, "data_wait_s": 0.6, "rollbacks": 0}]
    regs = [_registry(10, 0.1), _registry(9, 1.0)]
    servers = [StatusServer(0, reg, status=(lambda v=v: dict(v)))
               for reg, v in zip(regs, vitals)]
    urls = {str(i): f"http://{s.address[0]}:{s.address[1]}"
            for i, s in enumerate(servers)}
    try:
        yield urls, vitals
    finally:
        for s in servers:
            s.stop()


def test_aggregator_http_merge_and_straggler(two_workers):
    urls, vitals = two_workers
    agg = PodAggregator(workers=urls, min_refresh_s=0.0)
    status = agg.pod_status()
    assert status["n_workers"] == 2 and status["n_alive"] == 2
    assert status["stragglers"] == ["1"]
    assert status["straggler_rounds"] == {"1": 1}
    assert status["max_round"] == 10 and status["min_round"] == 9
    assert status["round_skew_s"] == pytest.approx(1.0 - 0.55)
    text = agg.render()
    assert 'sparknet_train_rounds_total{worker="pod"} 19' in text
    assert 'sparknet_train_round_seconds{worker="max"} 1' in text
    assert "sparknet_pod_round_skew_seconds" in text
    assert 'sparknet_pod_straggler_rounds_total{worker="1"} 1' in text
    assert 'sparknet_pod_worker_up{worker="1"} 1' in text
    # same reported round again -> no double count
    agg.collect(force=True)
    assert agg.registry.counter(
        "sparknet_pod_straggler_rounds_total",
        labels=("worker",)).value(worker="1") == 1
    # round advances, still slow -> counts again
    vitals[1]["round"] = 10
    agg.collect(force=True)
    assert agg.registry.counter(
        "sparknet_pod_straggler_rounds_total",
        labels=("worker",)).value(worker="1") == 2
    # the audit trail names the worker and the magnitude
    log = agg.pod_status()["straggler_log"]
    assert log and log[-1]["worker"] == "1"
    assert "STRAGGLER" in format_pod_table(agg.pod_status())


def test_aggregator_clean_two_worker_run_reports_zero(two_workers):
    urls, vitals = two_workers
    vitals[1]["round_s"] = 0.1  # same speed
    agg = PodAggregator(workers=urls, min_refresh_s=0.0)
    status = agg.pod_status()
    assert status["stragglers"] == []
    assert status["straggler_rounds"] == {}
    assert status["straggler_log"] == []
    assert agg.registry.counter(
        "sparknet_pod_straggler_rounds_total",
        labels=("worker",)).value(worker="1") is None


def test_aggregator_dead_worker_degrades(two_workers):
    urls, _ = two_workers
    urls = dict(urls, **{"2": "http://127.0.0.1:1/"})  # nothing listening
    agg = PodAggregator(workers=urls, min_refresh_s=0.0, timeout_s=0.5)
    status = agg.pod_status()
    assert status["n_workers"] == 3 and status["n_alive"] == 2
    dead = [w for w in status["workers"] if w["worker"] == "2"][0]
    assert not dead["alive"] and dead["error"]
    assert 'sparknet_pod_worker_up{worker="2"} 0' in agg.render()


def test_aggregator_http_hung_loop_reads_stale(two_workers):
    """http mode freshness comes from the worker LOOP's beat_ts stamp:
    a hung round loop whose HTTP daemon thread still answers must be
    reported stale, not alive (the file mode already had this via the
    heartbeat's t)."""
    urls, vitals = two_workers
    vitals[1]["beat_ts"] = time.time() - 3600  # loop last flushed 1h ago
    vitals[0]["beat_ts"] = time.time()
    agg = PodAggregator(workers=urls, stale_after_s=60.0,
                        min_refresh_s=0.0)
    status = agg.pod_status()
    assert status["n_alive"] == 1
    hung = [w for w in status["workers"] if w["worker"] == "1"][0]
    assert not hung["alive"] and "stale" in hung["error"]
    # and a stale worker's round time is excluded from attribution
    assert status["stragglers"] == []


def test_heartbeat_bucket_roundtrip_and_flush(monkeypatch):
    """gs:// heartbeats: the beat is a non-blocking handoff to a writer
    thread; flush() bounds the wait and the aggregator reads the record
    back through the same native store client."""
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from fake_stores import serve_gcs, stop_serving
    from sparknet_tpu.utils.heartbeat import read_heartbeat

    srv, endpoint = serve_gcs()
    monkeypatch.setenv("STORAGE_EMULATOR_HOST", endpoint)
    monkeypatch.setenv("no_proxy", "*")
    try:
        path = worker_heartbeat_path("gs://bkt/pod", 1)
        hb = HeartbeatWriter(path, interval_s=0.0)
        assert hb.beat(4, status="ok", worker=1, round_s=0.2)
        hb.flush()
        rec = read_heartbeat(path)
        assert rec and rec["step"] == 4 and rec["round_s"] == 0.2
        agg = PodAggregator(pod_dir="gs://bkt/pod", min_refresh_s=0.0)
        status = agg.pod_status()
        assert status["n_workers"] == 1
        assert status["workers"][0]["worker"] == "1"
        assert status["workers"][0]["round_s"] == 0.2
    finally:
        stop_serving(srv)


def test_pod_status_server_endpoints(two_workers):
    urls, _ = two_workers
    agg = PodAggregator(workers=urls, min_refresh_s=0.0)
    srv = agg.serve(0)
    try:
        host, port = srv.address
        s = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/pod/status", timeout=10).read())
        assert s["role"] == "pod" and s["stragglers"] == ["1"]
        m = urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=10)
        assert m.headers["Content-Type"].startswith("text/plain")
        text = m.read().decode()
        assert 'sparknet_train_rounds_total{worker="pod"} 19' in text
        hz = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/healthz", timeout=10).read())
        assert hz["status"] == "ok" and hz["stragglers"] == ["1"]
    finally:
        agg.stop()


# -- the aggregator: heartbeat-file mode -------------------------------------

def test_aggregator_file_mode_flags_injected_straggler(tmp_path):
    pod_dir = str(tmp_path / "pod")
    times = [0.1, 0.1, 1.5]  # worker 2 injected slow
    for i, round_s in enumerate(times):
        hb = HeartbeatWriter(worker_heartbeat_path(pod_dir, i))
        hb.beat(5, status="ok", worker=i, round_s=round_s,
                data_wait_s=0.001, last_loss=1.0)
    agg = PodAggregator(pod_dir=pod_dir, min_refresh_s=0.0)
    status = agg.pod_status()
    assert status["n_workers"] == 3 and status["n_alive"] == 3
    assert status["stragglers"] == ["2"]
    assert status["straggler_rounds"] == {"2": 1}
    assert [w["round"] for w in status["workers"]] == [5, 5, 5]
    # file mode still renders a pod exposition (aggregator registry)
    text = agg.render()
    assert "sparknet_pod_workers 3" in text
    assert 'sparknet_pod_worker_round_seconds{worker="2"} 1.5' in text


def test_aggregator_surfaces_per_model_serve_rows(tmp_path):
    """A serve-role heartbeat's per-model vitals rows ride through the
    aggregator into /pod/status worker rows and the podview table —
    multi-model straggler attribution reads per model, not just per
    process."""
    pod_dir = str(tmp_path / "pod")
    HeartbeatWriter(worker_heartbeat_path(pod_dir, 0)).beat(
        7, status="ok", round_s=0.1)
    HeartbeatWriter(worker_heartbeat_path(pod_dir, 1), role="serve").beat(
        42, status="ok",
        models={"mnist": {"step": 42, "freshness_s": 3.25, "step_lag": 1,
                          "queue_depth": 3, "p99_ms": 8.5,
                          "requests_ok": 100, "requests_shed": 2,
                          "swaps": 1},
                "cifar": {"step": 9, "queue_depth": 0, "p99_ms": 30.1,
                          "requests_ok": 10}})
    agg = PodAggregator(pod_dir=pod_dir, min_refresh_s=0.0)
    status = agg.pod_status()
    serve = [w for w in status["workers"] if w["worker"] == "1"][0]
    assert serve["role"] == "serve"
    assert set(serve["models"]) == {"mnist", "cifar"}
    assert serve["models"]["mnist"]["p99_ms"] == 8.5
    # r12: checkpoint freshness and step lag ride the heartbeat row, so
    # podview shows per-replica staleness WITHOUT scraping /metrics
    assert serve["models"]["mnist"]["freshness_s"] == 3.25
    assert serve["models"]["mnist"]["step_lag"] == 1
    train = [w for w in status["workers"] if w["worker"] == "0"][0]
    assert "models" not in train  # train rows stay exactly as before
    table = format_pod_table(status)
    assert "model=mnist" in table and "p99=8.5ms" in table
    assert "fresh=3.25s" in table and "lag=1" in table
    assert "model=cifar" in table and "shed=2" in table
    cifar = [ln for ln in table.splitlines() if "model=cifar" in ln][0]
    assert "fresh=" not in cifar      # no freshness reported = omitted


def test_aggregator_file_mode_stale_worker_named(tmp_path):
    pod_dir = str(tmp_path / "pod")
    for i in range(2):
        HeartbeatWriter(worker_heartbeat_path(pod_dir, i)).beat(
            3, status="ok", round_s=0.1)
    # age worker 1's beat far past the staleness bound
    p1 = worker_heartbeat_path(pod_dir, 1)
    rec = json.load(open(p1))
    rec["t"] = time.time() - 3600
    json.dump(rec, open(p1, "w"))
    agg = PodAggregator(pod_dir=pod_dir, stale_after_s=60.0,
                        min_refresh_s=0.0)
    status = agg.pod_status()
    assert status["n_alive"] == 1
    stale = [w for w in status["workers"] if w["worker"] == "1"][0]
    assert not stale["alive"] and "stale" in stale["error"]


# -- train-loop wiring (single process = 1-worker pod) -----------------------

@pytest.fixture(scope="module")
def pod_trained(tmp_path_factory):
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    root = str(tmp_path_factory.mktemp("pod_train"))
    r = np.random.default_rng(0)
    ds = ArrayDataset({
        "data": r.standard_normal((128, 1, 28, 28)).astype(np.float32),
        "label": r.integers(0, 10, (128, 1)).astype(np.int32)})
    cfg = RunConfig(model="lenet", n_devices=1, local_batch=16, tau=2,
                    max_rounds=3, eval_every=0, workdir=root,
                    status_port=0, pod_dir=os.path.join(root, "pod"),
                    pod_port=0, heartbeat_every_s=0.0)
    scraped = {}

    def hook(rnd, state):
        if rnd == 2:
            host, port = cfg.status_address
            scraped["metrics"] = urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=10).read().decode()
            host, port = cfg.pod_address
            scraped["pod"] = json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/pod/status", timeout=10).read())
            scraped["pod_metrics"] = urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=10).read().decode()

    log = Logger(os.path.join(root, "l.txt"), echo=False,
                 jsonl_path=os.path.join(root, "m.jsonl"))
    train(cfg, lenet(batch=16), ds, None, logger=log, round_hook=hook)
    log.close()
    return {"cfg": cfg, "root": root, "scraped": scraped}


def test_train_worker_exports_straggler_inputs(pod_trained):
    text = pod_trained["scraped"]["metrics"]
    for name in ("sparknet_train_round_seconds",
                 "sparknet_train_data_wait_seconds",
                 "sparknet_train_round_compiled_variants",
                 "sparknet_device_live_arrays",
                 # the r9 cache_hit label rides every compile event
                 'sparknet_compile_events_total{what="net",cache_hit='):
        assert name in text, f"missing {name} in worker /metrics"


def test_train_pod_endpoint_sees_worker(pod_trained):
    pod = pod_trained["scraped"]["pod"]
    assert pod["n_workers"] == 1 and pod["n_alive"] == 1
    w = pod["workers"][0]
    assert w["worker"] == "0" and w["round_s"] is not None
    assert w["data_wait_s"] is not None
    assert pod["stragglers"] == []  # 1 worker: nothing to attribute
    assert "sparknet_pod_workers 1" in pod_trained["scraped"]["pod_metrics"]


def test_train_pod_heartbeat_file_schema(pod_trained):
    hb = json.load(open(worker_heartbeat_path(
        pod_trained["cfg"].pod_dir, 0)))
    assert hb["role"] == "train" and hb["worker"] == 0
    assert hb["status"] == "done"  # final forced beat
    assert hb["round_s"] is not None and hb["data_wait_s"] is not None


# -- device telemetry + compile counters -------------------------------------

def test_device_telemetry_samples_without_accelerator_stats():
    from sparknet_tpu.obs.device import DeviceTelemetry

    reg = MetricsRegistry()
    tel = DeviceTelemetry(reg)
    tel.sample()  # CPU: memory_stats() is None -> only live arrays
    assert reg.gauge("sparknet_device_live_arrays").value() is not None
    # a device whose memory_stats raises must not break the sample
    class Boom:
        platform, id = "boom", 0

        def memory_stats(self):
            raise RuntimeError("no stats")
    DeviceTelemetry(reg, devices=[Boom()]).sample()


def test_device_telemetry_memory_gauges_from_stats():
    from sparknet_tpu.obs.device import DeviceTelemetry

    class Fake:
        platform, id = "tpu", 3

        def memory_stats(self):
            return {"bytes_in_use": 1024, "peak_bytes_in_use": 4096,
                    "bytes_limit": 1 << 30}
    reg = MetricsRegistry()
    DeviceTelemetry(reg, devices=[Fake()]).sample()
    text = reg.render_prometheus()
    assert 'sparknet_device_hbm_bytes_in_use{device="tpu:3"} 1024' in text
    assert 'sparknet_device_hbm_peak_bytes{device="tpu:3"} 4096' in text


def _compile_event_count(reg, what):
    snap = reg.snapshot()["sparknet_compile_events_total"]
    return sum(v for key, v in snap["values"].items() if key[0] == what)


def test_compile_events_replayed_into_late_registry():
    from sparknet_tpu.model.net import CompiledNet
    from sparknet_tpu.obs.device import (attach_compile_metrics,
                                         compile_stats)
    from sparknet_tpu.zoo import lenet

    CompiledNet.compile(lenet(batch=2))  # happens BEFORE the registry
    reg = MetricsRegistry()
    attach_compile_metrics(reg)
    before = _compile_event_count(reg, "net")
    assert before >= 1  # the history replayed
    CompiledNet.compile(lenet(batch=2))  # and live events keep flowing
    assert _compile_event_count(reg, "net") == before + 1
    # the seconds histogram carries REAL compile cost only: memo/cache
    # hits count events but never dilute the duration percentiles
    snap = reg.snapshot()["sparknet_compile_seconds"]
    stats = compile_stats()["net"]
    assert snap["values"][("net",)]["count"] == \
        stats["events"] - stats["cache_hits"]


def test_compile_events_cache_hit_labeling():
    """The r9 cache_hit label end to end: a region doing FRESH XLA work
    records cache_hit="false" (a cold compile — with no persistent cache
    there is nothing to hit), an identical spec recompile records
    cache_hit="true" (the CompiledNet memo: zero fresh work), and the
    Prometheus exposition carries both label values."""
    import jax
    import jax.numpy as jnp

    from sparknet_tpu.model.net import CompiledNet
    from sparknet_tpu.obs.device import (attach_compile_metrics,
                                         compile_stats, timed_compile)
    from sparknet_tpu.zoo import lenet

    what = f"test_site_{time.time_ns()}"  # unique event site
    salt = time.time_ns() % 89
    f = jax.jit(lambda x: x * 3 + salt)   # a jit nobody compiled before
    with timed_compile(what):
        f(jnp.ones((2,)))                 # cold: fresh XLA compile
    assert compile_stats()[what]["cache_misses"] == 1
    with timed_compile(what):
        f(jnp.ones((2,)))                 # cached executable: no work
    assert compile_stats()[what]["cache_hits"] == 1
    # identical spec recompile -> memo hit recorded as a hit
    CompiledNet.compile(lenet(batch=2))
    before = compile_stats()["net"]["cache_hits"]
    CompiledNet.compile(lenet(batch=2))
    assert compile_stats()["net"]["cache_hits"] == before + 1
    # the exposition carries the label, both values
    reg = MetricsRegistry()
    attach_compile_metrics(reg)
    text = reg.render_prometheus()
    assert (f'sparknet_compile_events_total{{what="{what}",'
            f'cache_hit="false"}} 1') in text
    assert (f'sparknet_compile_events_total{{what="{what}",'
            f'cache_hit="true"}} 1') in text


def test_serve_bucket_recompile_counter_steady_state():
    """The serve recompile counter equals len(buckets) once every bucket
    has been exercised, and STAYS there — steady state means zero compile
    churn, and churn past len(buckets) is the metric's alarm condition."""
    from sparknet_tpu.net_api import JaxNet
    from sparknet_tpu.serve import InferenceServer, ServeConfig
    from sparknet_tpu.zoo import lenet

    net = JaxNet(lenet(batch=4))
    cfg = ServeConfig(max_batch=4, max_wait_ms=1.0, buckets=(1, 2, 4),
                      outputs=("prob",), metrics_every_batches=0)
    x = {"data": np.zeros((28, 28, 1), np.float32)}
    with InferenceServer(net, cfg) as srv:
        c = srv.registry.counter("sparknet_serve_bucket_compiles_total",
                                 labels=("model",))
        srv.infer(x)                       # bucket 1
        futs = [srv.submit(x) for _ in range(4)]
        for f in futs:
            f.result(timeout=30)           # bucket 4 (and maybe others)
        futs = [srv.submit(x) for _ in range(2)]
        for f in futs:
            f.result(timeout=30)
        # drive until all three buckets have been seen at least once
        deadline = time.monotonic() + 30
        while len(srv._compiled_buckets) < 3 and \
                time.monotonic() < deadline:
            n = min(b for b in (1, 2, 4)
                    if b not in srv._compiled_buckets)
            for f in [srv.submit(x) for _ in range(n)]:
                f.result(timeout=30)
        assert srv._compiled_buckets == {1, 2, 4}
        assert c.value(model="default") == 3  # == len(buckets)
        # steady state: more traffic adds NO compile events
        for f in [srv.submit(x) for _ in range(4)]:
            f.result(timeout=30)
        srv.infer(x)
        assert c.value(model="default") == 3
        assert srv.status()["bucket_compiles"] == 3


# -- podview CLI -------------------------------------------------------------

def test_podview_selfcheck():
    from sparknet_tpu.obs.pod import main
    assert main(["--selfcheck"]) == 0


def test_podview_file_mode_cli(tmp_path, capsys):
    pod_dir = str(tmp_path / "pod")
    for i, rs in enumerate((0.1, 0.1, 2.0)):
        HeartbeatWriter(worker_heartbeat_path(pod_dir, i)).beat(
            7, status="ok", round_s=rs, last_loss=0.5)
    from sparknet_tpu.obs.pod import main
    assert main(["--pod-dir", pod_dir, "--json"]) == 0
    s = json.loads(capsys.readouterr().out)
    assert s["n_workers"] == 3 and s["stragglers"] == ["2"]


def test_aggregator_file_mode_vanished_worker_surfaced(tmp_path):
    """Mid-run membership change: a worker whose heartbeat FILE vanishes
    between scrapes must surface as worker_up=0 / candidate-dead, not
    silently drop out of the pod view and the straggler population."""
    pod_dir = str(tmp_path / "pod")
    for i in range(3):
        HeartbeatWriter(worker_heartbeat_path(pod_dir, i)).beat(
            4, status="ok", round_s=0.1)
    agg = PodAggregator(pod_dir=pod_dir, min_refresh_s=0.0)
    assert agg.pod_status()["n_alive"] == 3
    os.remove(worker_heartbeat_path(pod_dir, 1))  # vanishes, not stale
    status = agg.pod_status()
    assert status["n_workers"] == 3  # sticky: still in the population
    assert status["n_alive"] == 2
    assert status["candidate_dead"] == ["1"]
    gone = [w for w in status["workers"] if w["worker"] == "1"][0]
    assert not gone["alive"] and "unreadable" in gone["error"]
    assert 'sparknet_pod_worker_up{worker="1"} 0' in agg.render()
    # the survivors' straggler stats still work over the live population
    assert status["stragglers"] == []


def test_aggregator_surfaces_membership_epoch(tmp_path):
    """Elastic runs stamp membership_epoch on their beats; /pod/status
    reports the newest epoch any worker saw (resizes visible on a
    scrape, no JSONL required)."""
    pod_dir = str(tmp_path / "pod")
    HeartbeatWriter(worker_heartbeat_path(pod_dir, 0)).beat(
        7, status="ok", round_s=0.1, membership_epoch=2, n_members=3)
    HeartbeatWriter(worker_heartbeat_path(pod_dir, 1)).beat(
        6, status="ok", round_s=0.1, membership_epoch=1, n_members=4)
    status = PodAggregator(pod_dir=pod_dir,
                           min_refresh_s=0.0).pod_status()
    assert status["membership_epoch"] == 2
    by_id = {w["worker"]: w for w in status["workers"]}
    assert by_id["0"]["membership_epoch"] == 2
    assert by_id["1"]["membership_epoch"] == 1
