"""Data layer tests with synthetic fixtures in the exact on-disk formats
(the offline analogue of the reference's loader specs + PreprocessorSpec)."""
import numpy as np
import pytest

from sparknet_tpu.data import cifar, mnist, adult, imagenet
from sparknet_tpu.data.dataset import ArrayDataset, RoundSampler
from sparknet_tpu.data.preprocess import (ImagePreprocessor,
                                          compute_mean_image, to_nhwc,
                                          random_crop_nchw, center_crop_nchw)
from sparknet_tpu.schema import Field, Schema


# -- CIFAR -------------------------------------------------------------------

def test_cifar_loader(tmp_path):
    d = str(tmp_path / "cifar")
    cifar.write_synthetic(d, n_per_file=50)
    loader = cifar.CifarLoader(d, seed=1)
    assert loader.train_images.shape == (250, 3, 32, 32)
    assert loader.test_images.shape == (50, 3, 32, 32)
    assert loader.mean_image.shape == (3, 32, 32)
    assert loader.train_labels.min() >= 0 and loader.train_labels.max() <= 9
    batch = loader.train_batch_dict()
    # mean-subtracted data has ~zero mean
    assert abs(batch["data"].mean()) < 1.0
    assert batch["label"].shape == (250, 1)


def test_cifar_missing_file(tmp_path):
    with pytest.raises(FileNotFoundError, match="data_batch_1.bin"):
        cifar.CifarLoader(str(tmp_path))


def test_cifar_shuffle_deterministic(tmp_path):
    d = str(tmp_path / "c")
    cifar.write_synthetic(d, n_per_file=20)
    a = cifar.CifarLoader(d, seed=5)
    b = cifar.CifarLoader(d, seed=5)
    np.testing.assert_array_equal(a.train_labels, b.train_labels)


# -- MNIST -------------------------------------------------------------------

def test_mnist_loader(tmp_path):
    d = str(tmp_path / "mnist")
    mnist.write_synthetic(d, n_train=64, n_test=16)
    loader = mnist.MnistLoader(d)
    assert loader.train_images.shape == (64, 1, 28, 28)
    # normalized to [-0.5, 0.5] (reference MnistLoader.scala:35)
    assert loader.train_images.min() >= -0.5
    assert loader.train_images.max() <= 0.5
    assert loader.test_labels.dtype == np.int32


def test_mnist_bad_magic(tmp_path):
    p = tmp_path / "bad"
    p.write_bytes(b"\x00\x00\x00\x07" + b"\x00" * 16)
    with pytest.raises(ValueError, match="bad magic"):
        mnist.read_idx_images(str(p))


# -- Adult -------------------------------------------------------------------

def test_adult_loader(tmp_path):
    p = str(tmp_path / "adult.data")
    adult.write_synthetic(p, n=100)
    loader = adult.AdultLoader(p)
    batch = loader.batch_dict()
    assert batch["C0"].shape == (100, 14)
    assert set(np.unique(batch["label"])) <= {0, 1}
    # normalized features
    assert abs(batch["C0"].mean()) < 0.2


# -- ImageNet sharded tar ----------------------------------------------------

def test_sharded_tar_loader(tmp_path):
    root = str(tmp_path / "shards")
    label_path = imagenet.write_synthetic_shards(root, n_shards=2, per_shard=6,
                                                 size=48)
    labels = imagenet.load_label_map(label_path)
    shards = imagenet.list_shards(root, prefix="train.")
    assert len(shards) == 2
    loader = imagenet.ShardedTarLoader(shards, labels, height=32, width=32)
    images, lbls = loader.load_all()
    assert images.shape == (12, 3, 32, 32)  # decoded + force-resized, CHW
    assert images.dtype == np.uint8
    assert loader.skipped == 0


def test_sharded_tar_corrupt_images_skipped_not_looped(tmp_path):
    """The reference looped forever on a corrupt image
    (ImageNetLoader.scala:82-85); we must skip and count."""
    root = str(tmp_path / "shards")
    label_path = imagenet.write_synthetic_shards(root, n_shards=1,
                                                 per_shard=9, size=48,
                                                 corrupt_every=3)
    loader = imagenet.ShardedTarLoader(
        imagenet.list_shards(root), imagenet.load_label_map(label_path),
        height=32, width=32)
    images, _ = loader.load_all()   # terminates — that's the test
    assert len(images) == 6
    assert loader.skipped == 3


def test_host_shard_assignment():
    shards = [f"s{i}" for i in range(10)]
    a = imagenet.host_shards(shards, 0, 4)
    b = imagenet.host_shards(shards, 1, 4)
    assert a == ["s0", "s4", "s8"] and b == ["s1", "s5", "s9"]
    allsets = [imagenet.host_shards(shards, i, 4) for i in range(4)]
    assert sorted(sum(allsets, [])) == sorted(shards)


def test_streaming_batches(tmp_path):
    root = str(tmp_path / "shards")
    label_path = imagenet.write_synthetic_shards(root, n_shards=1, per_shard=7,
                                                 size=48)
    loader = imagenet.ShardedTarLoader(
        imagenet.list_shards(root), imagenet.load_label_map(label_path),
        height=32, width=32)
    batches = list(loader.batches(3))
    assert len(batches) == 2  # 7 images, drop_last
    assert batches[0]["data"].shape == (3, 3, 32, 32)
    assert batches[0]["label"].shape == (3, 1)


# -- Preprocessing -----------------------------------------------------------

def test_random_crop_values_come_from_source(rng):
    imgs = rng.standard_normal((4, 3, 16, 16)).astype(np.float32)
    crop = random_crop_nchw(imgs, 8, np.random.default_rng(0))
    assert crop.shape == (4, 3, 8, 8)
    # every cropped pixel must exist in the source image (set membership,
    # the reference's own crop test strategy, PreprocessorSpec.scala:95-114)
    for i in range(4):
        assert np.isin(crop[i], imgs[i]).all()


def test_center_crop():
    imgs = np.arange(1 * 1 * 6 * 6, dtype=np.float32).reshape(1, 1, 6, 6)
    c = center_crop_nchw(imgs, 4)
    np.testing.assert_array_equal(c[0, 0, 0], imgs[0, 0, 1, 1:5])


def test_image_preprocessor_mean_and_crop(rng):
    schema = Schema(Field("data", "float32", (3, 8, 8)),
                    Field("label", "int32", (1,)))
    imgs = rng.standard_normal((10, 3, 12, 12)).astype(np.float32)
    mean = compute_mean_image(imgs)
    pp = ImagePreprocessor(schema, mean_image=mean, crop=8, seed=3)
    out = pp.convert_batch({"data": imgs,
                            "label": np.zeros((10, 1), np.int64)},
                           train=True)
    assert out["data"].shape == (10, 8, 8, 3)  # cropped + NHWC
    assert out["label"].dtype == np.int32
    # deterministic center crop in eval mode
    e1 = pp.convert_batch({"data": imgs, "label": np.zeros((10, 1))},
                          train=False)
    e2 = pp.convert_batch({"data": imgs, "label": np.zeros((10, 1))},
                          train=False)
    np.testing.assert_array_equal(e1["data"], e2["data"])


def test_preprocessor_throughput_floor():
    """Perf budget the reference CI asserted: 256 images (crop+mean+layout)
    in <= 1.0 s (PreprocessorSpec.scala:75,136)."""
    import time
    schema = Schema(Field("data", "float32", (3, 227, 227)),
                    Field("label", "int32", (1,)))
    imgs = np.random.default_rng(0).integers(
        0, 256, (256, 3, 256, 256)).astype(np.float32)
    pp = ImagePreprocessor(schema, mean_image=imgs.mean(0), crop=227)
    t0 = time.perf_counter()
    out = pp.convert_batch({"data": imgs, "label": np.zeros((256, 1))})
    dt = time.perf_counter() - t0
    assert out["data"].shape == (256, 227, 227, 3)
    assert dt <= 1.0, f"preprocessing 256 images took {dt:.3f}s (budget 1.0s)"


# -- Sampler -----------------------------------------------------------------

def test_round_sampler_windows_stay_in_partition():
    n_workers, local_b, tau = 4, 2, 3
    ds = ArrayDataset({"x": np.arange(80, dtype=np.int64)})
    s = RoundSampler(ds, n_workers, local_b, tau, seed=1)
    for _ in range(5):
        r = s.next_round()
        assert r["x"].shape == (tau, n_workers * local_b)
        for w in range(n_workers):
            block = r["x"][:, w * local_b:(w + 1) * local_b]
            lo, hi = w * 20, (w + 1) * 20
            assert (block >= lo).all() and (block < hi).all()
            # sequential window (reference it.drop(startIdx) semantics)
            flat = block.reshape(-1)
            assert (np.diff(flat) == 1).all()


def test_round_sampler_rejects_oversized_window():
    ds = ArrayDataset({"x": np.arange(16)})
    with pytest.raises(ValueError, match="exceeds partition"):
        RoundSampler(ds, n_workers=4, local_batch=2, tau=3)


def test_eval_batches_cover():
    ds = ArrayDataset({"x": np.arange(17)})
    s = RoundSampler(ds, 1, 1, 1)
    batches = list(s.eval_batches(4))
    assert len(batches) == 4
    assert sum(len(b["x"]) for b in batches) == 16


def test_shard_imagenet_val_split(tmp_path):
    """scripts/shard_imagenet.py val path (reference process_val_files,
    put_imagenet_on_s3.py:64-77): flat val tar + ground-truth labels ->
    val.NNNN.tar shards + val.txt, loadable by ShardedTarLoader."""
    import io
    import os
    import sys
    import tarfile
    from PIL import Image
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import shard_imagenet

    r = np.random.default_rng(0)
    val_tar = str(tmp_path / "ILSVRC2012_img_val.tar")
    truth = str(tmp_path / "truth.txt")
    names = [f"ILSVRC2012_val_{i:08d}.JPEG" for i in range(12)]
    with tarfile.open(val_tar, "w") as tar:
        for name in names:
            arr = r.integers(0, 256, (48, 48, 3), dtype=np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="JPEG")
            info = tarfile.TarInfo(name=name)
            info.size = len(buf.getvalue())
            tar.addfile(info, io.BytesIO(buf.getvalue()))
    with open(truth, "w") as f:
        f.write("\n".join(f"{n} {i % 5}" for i, n in enumerate(names)) + "\n")

    out = str(tmp_path / "out")
    os.makedirs(out)
    shard_imagenet.shard_val(val_tar, truth, out, shards=3, size=32, seed=0)

    shards = imagenet.list_shards(out, prefix="val.")
    assert len(shards) == 3
    labels = imagenet.load_label_map(os.path.join(out, "val.txt"))
    assert len(labels) == 12
    loader = imagenet.ShardedTarLoader(shards, labels, height=32, width=32)
    images, lbls = loader.load_all()
    assert images.shape == (12, 3, 32, 32)
    # labels survive the reshard: every (name, label) pair intact
    assert sorted(lbls.tolist()) == sorted(int(v) for v in labels.values())


# -- Streaming round source --------------------------------------------------

def _stream_fixture(tmp_path, n_shards=2, per_shard=8):
    root = str(tmp_path / "shards")
    label_path = imagenet.write_synthetic_shards(
        root, n_shards=n_shards, per_shard=per_shard, size=48)
    return imagenet.ShardedTarLoader(
        imagenet.list_shards(root), imagenet.load_label_map(label_path),
        height=32, width=32)


def test_streaming_round_source_layout(tmp_path):
    """Rounds have the RoundSampler layout ([tau, W*B, ...], batch axis
    blocked by worker) and each worker block is a consecutive stream run —
    verified against the materialized loader order."""
    from sparknet_tpu.data.streaming import StreamingRoundSource
    loader = _stream_fixture(tmp_path)  # 16 images
    ref_images, ref_labels = _stream_fixture(tmp_path).load_all()
    w, b, tau = 2, 2, 2  # round = 8 examples
    with StreamingRoundSource(loader, w, b, tau) as src:
        r = src.next_round(round_index=0)
        assert r["data"].shape == (tau, w * b, 3, 32, 32)
        assert r["data"].dtype == np.uint8
        assert r["label"].shape == (tau, w * b, 1)
        # worker 0's block = stream[0:4], worker 1's = stream[4:8]
        for wk in range(w):
            block = np.concatenate(
                [r["data"][t, wk * b:(wk + 1) * b] for t in range(tau)])
            np.testing.assert_array_equal(
                block, ref_images[wk * tau * b:(wk + 1) * tau * b])
            lbl = np.concatenate(
                [r["label"][t, wk * b:(wk + 1) * b, 0] for t in range(tau)])
            np.testing.assert_array_equal(
                lbl, ref_labels[wk * tau * b:(wk + 1) * tau * b])


def test_streaming_round_source_cycles_epochs(tmp_path):
    """16 images / 8 per round: round 3 requires a second pass over the
    shards (the reference requeued tars; no StopIteration mid-training)."""
    from sparknet_tpu.data.streaming import StreamingRoundSource
    loader = _stream_fixture(tmp_path)
    with StreamingRoundSource(loader, 2, 2, 2) as src:
        first = src.next_round()
        src.next_round()          # round 2 finishes epoch 1 (16 = 2 rounds)
        again = src.next_round()  # round 3 re-streams the shards
        np.testing.assert_array_equal(first["data"], again["data"])
    assert src.epochs >= 1


def test_streaming_cursor_resume_continues_stream(tmp_path):
    """THE elastic-stream property: a fresh source seeked to the cursor
    recorded after round R produces exactly the rounds an uninterrupted
    stream would have produced from R+1 on — no re-stream from shard 0,
    no skipped window (fixes the r2 data/streaming.py:16-19 limitation)."""
    from sparknet_tpu.data.streaming import StreamingRoundSource
    w, b, tau = 2, 2, 2  # 8 examples per round, 16 per epoch
    with StreamingRoundSource(_stream_fixture(tmp_path), w, b, tau) as src:
        uninterrupted = [src.next_round(round_index=i) for i in range(4)]
        cursor_after_r0 = src.cursor_at(0)
    assert cursor_after_r0 is not None
    (shard, entry), epochs = cursor_after_r0
    assert (shard, entry) != (0, 0)

    resumed = StreamingRoundSource(_stream_fixture(tmp_path), w, b, tau)
    resumed.seek((shard, entry), epochs)
    with resumed:
        for want in uninterrupted[1:]:
            got = resumed.next_round()
            np.testing.assert_array_equal(got["data"], want["data"])
            np.testing.assert_array_equal(got["label"], want["label"])


def test_streaming_cursor_at_retention_and_epochs(tmp_path):
    """cursor_at keys by round index (the loop's one-deep prefetch runs one
    round ahead of training); old entries are pruned; epoch counter rides
    the cursor. Seeking after the stream started fails loudly."""
    from sparknet_tpu.data.streaming import StreamingRoundSource
    with StreamingRoundSource(_stream_fixture(tmp_path), 2, 2, 2) as src:
        for i in range(8):  # 4 epochs of 2 rounds
            src.next_round(round_index=i)
        assert src.cursor_at(0) is None  # pruned (keeps a small window)
        assert src.cursor_at(7) is not None
        (_, _), ep = src.cursor_at(7)
        assert ep == 3  # 8 rounds of 8 = rounds 7 starts in pass 4
        with pytest.raises(RuntimeError, match="seek"):
            src.seek((0, 0))


def test_iter_with_pos_seek_skips_without_decoding(tmp_path):
    """Seeking skips raw tar entries: the positions reported for the
    continuation match the unseeked stream's, and a cursor past the end
    yields nothing (no false 'no decodable images' error on wrap)."""
    loader = _stream_fixture(tmp_path)
    all_pos = [(lbl, pos) for _, lbl, pos in loader.iter_with_pos()]
    mid = all_pos[5][1]
    cont = [(lbl, pos) for _, lbl, pos
            in _stream_fixture(tmp_path).iter_with_pos(mid)]
    assert cont == all_pos[6:]
    last = all_pos[-1][1]
    assert list(_stream_fixture(tmp_path).iter_with_pos(last)) == []


def test_run_loop_checkpoint_carries_stream_cursor(tmp_path):
    """End to end through run_loop: a streaming training run checkpoints
    its stream cursor, and the resumed run seeks (log line) instead of
    restarting at shard 0."""
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data.streaming import StreamingRoundSource
    from sparknet_tpu.utils import checkpoint as ckpt
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet
    import jax

    root = str(tmp_path / "shards")
    label_path = imagenet.write_synthetic_shards(
        root, n_shards=4, per_shard=16, size=28, n_classes=10)
    n_local = jax.local_device_count()

    def make_source():
        loader = imagenet.ShardedTarLoader(
            imagenet.list_shards(root), imagenet.load_label_map(label_path),
            height=28, width=28)
        return StreamingRoundSource(loader, n_local, 2, 2)

    def make_cfg(rounds):
        # health off: this trains a throwaway lenet on RAW 0-255 pixels (a
        # cursor-bookkeeping fixture, not a convergence run) — it diverges
        # violently by design, and the supervisor would (correctly) step in
        from sparknet_tpu.utils.health import HealthConfig
        return RunConfig(model="lenet", tau=2, local_batch=2,
                         max_rounds=rounds, workdir=str(tmp_path), seed=0,
                         eval_every=0, checkpoint_dir=str(tmp_path / "ck"),
                         checkpoint_every=2,
                         health=HealthConfig(enabled=False))

    class GrayTo28:
        def convert_batch(self, batch, train=True, rng=None):
            x = batch["data"].astype(np.float32).mean(axis=1)  # CHW->HW
            return {"data": x[..., None], "label": batch["label"]}

    spec = lenet(batch=2)
    train(make_cfg(2), spec, make_source(), None,
          logger=Logger(str(tmp_path / "l1.txt"), echo=False),
          batch_transform=GrayTo28())
    _, _, extra = ckpt.restore_flat(str(tmp_path / "ck"))
    # one host, one reader: [[ [shard, entry, epochs] ]]
    assert "stream" in extra and len(extra["stream"]) == 1
    (host_rows,) = extra["stream"]
    assert len(host_rows) == 1
    shard, entry, epochs = host_rows[0]
    assert (shard, entry) != (0, 0)

    train(make_cfg(4), spec, make_source(), None,
          logger=Logger(str(tmp_path / "l2.txt"), echo=False),
          batch_transform=GrayTo28())
    text = open(str(tmp_path / "l2.txt")).read()
    assert f"stream resumed at shard {shard} entry {entry}" in text

    # relaunching the COMPLETED run must not overwrite the final
    # checkpoint with a cursor-less one (the loop runs zero rounds and
    # has no cursor to record — r3 review finding)
    _, _, extra2 = ckpt.restore_flat(str(tmp_path / "ck"))
    assert "stream" in extra2
    train(make_cfg(4), spec, make_source(), None,
          logger=Logger(str(tmp_path / "l3.txt"), echo=False),
          batch_transform=GrayTo28())
    _, _, extra3 = ckpt.restore_flat(str(tmp_path / "ck"))
    assert extra3.get("stream") == extra2.get("stream")


def test_mean_image_sidecar_skips_second_pass(tmp_path, monkeypatch):
    """Streaming mean image is computed once and persisted next to the
    checkpoints; later launches load it WITHOUT another decode pass over
    the corpus (fixes the r2 apps/imagenet_app.py:164-168 re-pass)."""
    from sparknet_tpu.apps import imagenet_app
    from sparknet_tpu.utils.config import RunConfig

    loader = _stream_fixture(tmp_path)
    cfg = RunConfig(checkpoint_dir=str(tmp_path / "ck"),
                    data_dir=str(tmp_path / "shards"))
    first = imagenet_app._load_or_compute_mean(cfg, loader, 0, 1, "t")
    assert (tmp_path / "ck" / "mean_image.npz").exists()

    def boom(*_a, **_k):
        raise AssertionError("second launch re-streamed the corpus")

    monkeypatch.setattr(imagenet_app, "streaming_sum_count", boom)
    second = imagenet_app._load_or_compute_mean(cfg, loader, 0, 1, "t")
    np.testing.assert_allclose(second, first, atol=1e-6)
    # no checkpoint_dir -> no sidecar, compute every launch
    with pytest.raises(AssertionError, match="re-streamed"):
        imagenet_app._load_or_compute_mean(
            RunConfig(checkpoint_dir=None,
                      data_dir=str(tmp_path / "shards")), loader, 0, 1, "t")
    # a CHANGED corpus must not silently reuse the sidecar: growing a
    # shard changes the corpus id, so the loader recomputes (r3 review)
    with open(loader.shard_paths[0], "ab") as f:
        f.write(b"\0" * 1024)
    with pytest.raises(AssertionError, match="re-streamed"):
        imagenet_app._load_or_compute_mean(cfg, loader, 0, 1, "t")
    # legacy un-id'd mean_image.npy migrates to the stamped .npz without
    # a decode pass (r3 review: no silent repay of the corpus pass)
    import os
    os.remove(tmp_path / "ck" / "mean_image.npz")
    with open(tmp_path / "ck" / "mean_image.npy", "wb") as f:
        np.save(f, first)
    migrated = imagenet_app._load_or_compute_mean(cfg, loader, 0, 1, "t")
    np.testing.assert_allclose(migrated, first, atol=1e-6)
    assert (tmp_path / "ck" / "mean_image.npz").exists()


def test_streaming_round_source_error_propagates(tmp_path):
    """A decode-thread failure must fail the training loop, not hang it."""
    from sparknet_tpu.data.streaming import StreamingRoundSource
    loader = _stream_fixture(tmp_path)
    loader.shard_paths = [str(tmp_path / "missing.tar")]
    src = StreamingRoundSource(loader, 2, 2, 2)
    with pytest.raises(RuntimeError, match="streaming decode thread"):
        src.next_round()
    src.close()


def test_streaming_sum_count_matches_materialized(tmp_path):
    from sparknet_tpu.data.streaming import streaming_sum_count
    loader = _stream_fixture(tmp_path)
    images, _ = _stream_fixture(tmp_path).load_all()
    s, n = streaming_sum_count(loader)
    assert n == len(images)
    np.testing.assert_allclose(s / n, compute_mean_image(images), atol=1e-5)


def test_shard_val_rejects_label_only_file(tmp_path):
    """A devkit-style ground-truth file (labels only, no filenames) must
    fail with a clear message, not an unpack traceback (r2 review)."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import shard_imagenet
    bad = str(tmp_path / "truth.txt")
    with open(bad, "w") as f:
        f.write("490\n361\n171\n")
    with pytest.raises(SystemExit, match="filename label"):
        shard_imagenet.shard_val("unused.tar", bad, str(tmp_path), 2, 32, 0)


def test_load_all_limit_caps_decoding(tmp_path):
    """load_all(limit=n) stops DECODING at n examples (a real RAM cap, not
    a slice of a fully materialized corpus — r2 review)."""
    root = str(tmp_path / "shards")
    label_path = imagenet.write_synthetic_shards(root, n_shards=2,
                                                 per_shard=8, size=48)
    loader = imagenet.ShardedTarLoader(
        imagenet.list_shards(root), imagenet.load_label_map(label_path),
        height=32, width=32)
    images, labels = loader.load_all(5)
    assert len(images) == 5 and len(labels) == 5


# -- Parallel multi-reader streaming (r4: per-source ceiling killer) ---------

def _parallel_fixture(tmp_path, n_sources, n_shards=4, per_shard=8,
                      w=2, b=2, tau=2):
    from sparknet_tpu.data.streaming import make_parallel_source
    root = str(tmp_path / "shards")
    label_path = imagenet.write_synthetic_shards(
        root, n_shards=n_shards, per_shard=per_shard, size=48)
    return make_parallel_source(
        imagenet.list_shards(root), imagenet.load_label_map(label_path),
        w, b, tau, n_sources, height=32, width=32)


def test_parallel_source_layout_blocks_by_reader(tmp_path):
    """Round layout matches StreamingRoundSource ([tau, W*B, ...], batch
    axis blocked by worker); with N == n_workers each worker's window is
    exactly one reader's consecutive stream run over shards j::N."""
    w, b, tau = 2, 2, 2  # round = 8, block = 4 per reader
    src = _parallel_fixture(tmp_path, n_sources=2, w=w, b=b, tau=tau)
    per_reader = [ld.__class__(ld.shard_paths, ld.label_map,
                               height=32, width=32).load_all()
                  for ld in src.loaders]
    with src:
        r = src.next_round(round_index=0)
    assert r["data"].shape == (tau, w * b, 3, 32, 32)
    assert r["label"].shape == (tau, w * b, 1)
    for wk in range(w):  # worker wk's window = reader wk's stream[0:4]
        block = np.concatenate(
            [r["data"][t, wk * b:(wk + 1) * b] for t in range(tau)])
        np.testing.assert_array_equal(block, per_reader[wk][0][:tau * b])
        lbl = np.concatenate(
            [r["label"][t, wk * b:(wk + 1) * b, 0] for t in range(tau)])
        np.testing.assert_array_equal(lbl, per_reader[wk][1][:tau * b])


def test_parallel_source_n1_matches_single_source(tmp_path):
    """make_parallel_source(n=1) reproduces StreamingRoundSource's rounds
    exactly — the parallel layout is a strict generalization."""
    from sparknet_tpu.data.streaming import StreamingRoundSource
    w, b, tau = 2, 2, 2
    psrc = _parallel_fixture(tmp_path, n_sources=1, w=w, b=b, tau=tau)
    loader = imagenet.ShardedTarLoader(
        list(psrc.loaders[0].shard_paths), psrc.loaders[0].label_map,
        height=32, width=32)
    with psrc, StreamingRoundSource(loader, w, b, tau) as ssrc:
        for _ in range(3):
            pr, sr = psrc.next_round(), ssrc.next_round()
            np.testing.assert_array_equal(pr["data"], sr["data"])
            np.testing.assert_array_equal(pr["label"], sr["label"])


def test_parallel_source_exactly_once_per_epoch(tmp_path):
    """Every example is consumed exactly once per reader-epoch: 4 shards x
    8 images, 2 readers of 16 each, 8-example rounds -> 4 rounds cover the
    corpus exactly once (labels compared as multisets per reader)."""
    src = _parallel_fixture(tmp_path, n_sources=2)  # block = 4
    per_reader = [ld.__class__(ld.shard_paths, ld.label_map,
                               height=32, width=32).load_all()
                  for ld in src.loaders]
    seen = [[] for _ in range(2)]
    with src:
        for i in range(4):
            r = src.next_round(round_index=i)
            for wk in range(2):
                seen[wk].extend(np.concatenate(
                    [r["label"][t, wk * 2:(wk + 1) * 2, 0]
                     for t in range(2)]).tolist())
        cursors = src.cursor_at(3)
    for j in range(2):
        assert sorted(seen[j]) == sorted(per_reader[j][1].tolist())
    # end-of-pass cursor: position at the subset's last entry, epoch count
    # still 0 until the wrap is observed (same semantics as the single
    # source's cursor_at)
    assert all(ep == 0 for (_, _), ep in cursors)


def test_parallel_source_resume_continues_stream(tmp_path):
    """The elastic-stream property with N readers: a fresh source
    seek_rows'd to the cursors recorded after round R reproduces the
    uninterrupted rounds R+1.. exactly — per-reader cursors, no re-stream,
    no replay."""
    src = _parallel_fixture(tmp_path, n_sources=2)
    with src:
        uninterrupted = [src.next_round(round_index=i) for i in range(5)]
        cur = src.cursor_at(1)
    assert cur is not None and len(cur) == 2
    rows = [[s, e, ep] for (s, e), ep in cur]

    resumed = _parallel_fixture(tmp_path, n_sources=2)
    assert resumed.seek_rows(rows)
    with resumed:
        for want in uninterrupted[2:]:
            got = resumed.next_round()
            np.testing.assert_array_equal(got["data"], want["data"])
            np.testing.assert_array_equal(got["label"], want["label"])


def test_parallel_source_reader_count_change_refuses_cursors(tmp_path):
    """A checkpoint from a different reader count reassigned the shards:
    seek_rows must refuse (False) so the caller restarts cleanly."""
    src = _parallel_fixture(tmp_path, n_sources=2)
    assert not src.seek_rows([[0, 0, 0]])          # 1 row into 2 readers
    assert not src.seek_rows([[0, 0, 0]] * 3)      # 3 rows into 2 readers
    assert src.seek_rows([[0, 0, 0], [0, 0, 0]])   # matching count is fine
    src.close()


def test_parallel_source_invalid_construction(tmp_path):
    """More sources than shards clamps (make_parallel_source); a round not
    divisible by N fails loudly; an empty reader fails loudly."""
    from sparknet_tpu.data.streaming import ParallelStreamingSource
    src = _parallel_fixture(tmp_path, n_sources=99, n_shards=4)
    assert src.n_sources == 4
    src.close()
    loaders = _parallel_fixture(tmp_path, n_sources=2).loaders
    with pytest.raises(ValueError, match="not divisible"):
        ParallelStreamingSource(loaders + [loaders[0]], 2, 2, 2)  # 8 % 3
    empty = imagenet.ShardedTarLoader([], loaders[0].label_map)
    with pytest.raises(ValueError, match="no shards"):
        ParallelStreamingSource([loaders[0], empty], 2, 2, 2)


def test_parallel_source_error_propagates(tmp_path):
    """One reader failing must fail the consumer, not hang the round
    barrier."""
    src = _parallel_fixture(tmp_path, n_sources=2)
    src.loaders[1].shard_paths = [str(tmp_path / "missing.tar")]
    with pytest.raises(RuntimeError, match="streaming decode thread"):
        for i in range(8):  # reader 0 alone can never complete a round
            src.next_round(round_index=i)
    src.close()


def test_run_loop_checkpoint_carries_parallel_cursors(tmp_path):
    """End to end through run_loop with 2 readers: the checkpoint carries
    one cursor row PER READER, and the resumed run seeks all of them; a
    resume with a different reader count restarts at shard 0 (logged)."""
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data.streaming import make_parallel_source
    from sparknet_tpu.utils import checkpoint as ckpt
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet
    import jax

    root = str(tmp_path / "shards")
    label_path = imagenet.write_synthetic_shards(
        root, n_shards=4, per_shard=16, size=28, n_classes=10)
    n_local = jax.local_device_count()

    def make_source(n):
        return make_parallel_source(
            imagenet.list_shards(root), imagenet.load_label_map(label_path),
            n_local, 2, 2, n, height=28, width=28)

    def make_cfg(rounds):
        # health off: this trains a throwaway lenet on RAW 0-255 pixels (a
        # cursor-bookkeeping fixture, not a convergence run) — it diverges
        # violently by design, and the supervisor would (correctly) step in
        from sparknet_tpu.utils.health import HealthConfig
        return RunConfig(model="lenet", tau=2, local_batch=2,
                         max_rounds=rounds, workdir=str(tmp_path), seed=0,
                         eval_every=0, checkpoint_dir=str(tmp_path / "ck"),
                         checkpoint_every=2,
                         health=HealthConfig(enabled=False))

    class GrayTo28:
        def convert_batch(self, batch, train=True, rng=None):
            x = batch["data"].astype(np.float32).mean(axis=1)  # CHW->HW
            return {"data": x[..., None], "label": batch["label"]}

    spec = lenet(batch=2)
    train(make_cfg(2), spec, make_source(2), None,
          logger=Logger(str(tmp_path / "l1.txt"), echo=False),
          batch_transform=GrayTo28())
    _, _, extra = ckpt.restore_flat(str(tmp_path / "ck"))
    (host_rows,) = extra["stream"]
    assert len(host_rows) == 2  # one cursor row per reader

    train(make_cfg(4), spec, make_source(2), None,
          logger=Logger(str(tmp_path / "l2.txt"), echo=False),
          batch_transform=GrayTo28())
    text = open(str(tmp_path / "l2.txt")).read()
    assert "stream resumed at" in text
    for s, e, ep in host_rows:
        assert f"shard {s} entry {e}" in text

    # reader-count change: cursors refused, stream restarts at zero
    train(make_cfg(6), spec, make_source(4), None,
          logger=Logger(str(tmp_path / "l3.txt"), echo=False),
          batch_transform=GrayTo28())
    text = open(str(tmp_path / "l3.txt")).read()
    assert "restarting" in text and "stream resumed at" not in text


# -- C tar member index (r4: GIL-free local shard walk) ----------------------

def test_tar_index_matches_tarfile_path_exactly(tmp_path):
    """The C member index must reproduce the tarfile path bit for bit:
    same bytes, same labels, same cursor numbering (resume depends on it),
    including unlabeled-entry skips and mid-shard seeks."""
    from sparknet_tpu.data import jpeg_plane
    if not jpeg_plane.supports_tar_index():
        pytest.skip("native plane unavailable")
    loader_idx = _stream_fixture(tmp_path, n_shards=2, per_shard=8)
    loader_tar = _stream_fixture(tmp_path, n_shards=2, per_shard=8)
    # drop one label so the unlabeled-skip path is exercised
    victim = sorted(loader_idx.label_map)[3]
    del loader_idx.label_map[victim]
    del loader_tar.label_map[victim]
    for p in loader_tar.shard_paths:
        loader_tar._tar_indices[p] = None  # force the tarfile path
    a = [(img.tobytes(), lbl, pos)
         for img, lbl, pos in loader_idx.iter_with_pos()]
    b = [(img.tobytes(), lbl, pos)
         for img, lbl, pos in loader_tar.iter_with_pos()]
    assert a == b and len(a) == 15
    assert loader_idx.skipped == loader_tar.skipped == 1
    mid = a[5][2]
    c = [(img.tobytes(), lbl, pos) for img, lbl, pos
         in _stream_fixture(tmp_path, n_shards=2,
                            per_shard=8).iter_with_pos(mid)]
    # fixture labels differ (fresh loader keeps victim's label): compare
    # positions only for the seek check
    assert [x[2] for x in c][:5] == [x[2] for x in a[6:11]]


def test_tar_index_extension_headers_fall_back(tmp_path):
    """A GNU long-name member desynchronizes C-vs-tarfile numbering, so
    the indexer must refuse (None) and the loader silently use tarfile."""
    import io as _io
    import tarfile as _tarfile
    from PIL import Image
    from sparknet_tpu.data import jpeg_plane
    if not jpeg_plane.supports_tar_index():
        pytest.skip("native plane unavailable")
    root = tmp_path / "ln"
    root.mkdir()
    long_name = "x" * 120 + ".JPEG"  # > 100 chars: GNU 'L' header
    tar_path = str(root / "train.0000.tar")
    buf = _io.BytesIO()
    Image.fromarray(np.zeros((32, 32, 3), np.uint8)).save(buf, format="JPEG")
    data = buf.getvalue()
    with _tarfile.open(tar_path, "w", format=_tarfile.GNU_FORMAT) as tar:
        info = _tarfile.TarInfo(name=long_name)
        info.size = len(data)
        tar.addfile(info, _io.BytesIO(data))
    assert jpeg_plane.tar_index(tar_path) is None
    loader = imagenet.ShardedTarLoader(
        [tar_path], {long_name: 3}, height=32, width=32)
    images, labels = loader.load_all()
    assert len(images) == 1 and labels[0] == 3


def test_truncated_shard_fails_loudly(tmp_path):
    """A shard truncated mid-member (interrupted copy) must raise, not
    silently drop the tail: the C index refuses (last member extends past
    EOF) and the tarfile fallback then reports the corruption."""
    from sparknet_tpu.data import jpeg_plane
    if not jpeg_plane.supports_tar_index():
        pytest.skip("native plane unavailable")
    loader = _stream_fixture(tmp_path, n_shards=1, per_shard=8)
    path = loader.shard_paths[0]
    offsets, sizes, _, _ = jpeg_plane.tar_index(path)
    with open(path, "r+b") as f:
        # cut INTO the last member's data (tar pads archives with ~10KB of
        # trailing zero blocks, so an end-relative truncate misses)
        f.truncate(int(offsets[-1] + sizes[-1] // 2))
    with pytest.raises(jpeg_plane.TruncatedTarError):
        jpeg_plane.tar_index(path)
    with pytest.raises(Exception):  # surfaced, not swallowed
        loader.load_all()

    # truncation exactly AT a member boundary is the sneaky case: the
    # archive looks complete to a naive walk (and to Python's tarfile,
    # which iterates the partial archive silently) — the missing zero
    # end-of-archive block is the tell, and it must NOT fall back
    loader2 = _stream_fixture(tmp_path.joinpath("b"), n_shards=1,
                              per_shard=8)
    path2 = loader2.shard_paths[0]
    o2, s2, _, _ = jpeg_plane.tar_index(path2)
    with open(path2, "r+b") as f:
        f.truncate(int(o2[-1] + ((s2[-1] + 511) & ~511)))
    with pytest.raises(jpeg_plane.TruncatedTarError):
        jpeg_plane.tar_index(path2)
    with pytest.raises(jpeg_plane.TruncatedTarError):
        loader2.load_all()  # no silent tarfile fallback
    # the PURE-tarfile path (no native plane / extension archives) has its
    # own terminator check and must also refuse
    loader3 = imagenet.ShardedTarLoader([path2], loader2.label_map, 32, 32)
    loader3._tar_indices[path2] = None  # force the tarfile branch
    with pytest.raises(jpeg_plane.TruncatedTarError):
        loader3.load_all()


def test_streaming_sum_count_parallel_matches_serial(tmp_path):
    """The fanned-out mean pass is float64 partial sums over shard subsets
    — identical to the serial pass, any worker count."""
    from sparknet_tpu.data.streaming import streaming_sum_count
    serial = streaming_sum_count(_stream_fixture(tmp_path, n_shards=4))
    for w in (2, 3, 99):
        par = streaming_sum_count(_stream_fixture(tmp_path, n_shards=4),
                                  workers=w)
        assert par[1] == serial[1]
        np.testing.assert_array_equal(par[0], serial[0])
