"""Prototxt importer tests (tiny fixtures inline; reference files only read
if the read-only mount is present)."""
import os

import pytest

from sparknet_tpu.model.prototxt import (
    net_from_prototxt,
    net_from_prototxt_file,
    parse_message,
    solver_from_prototxt,
)

ADULT = """
name: "adult"
input: "C0"
input_shape { dim: 64 dim: 1 }
layer {
  name: "ip"
  type: "InnerProduct"
  bottom: "C0"
  top: "ip"
  param { lr_mult: 1 }
  param { lr_mult: 2 }
  inner_product_param {
    num_output: 10
    weight_filler { type: "xavier" }
    bias_filler { type: "constant" }
  }
}
layer { name: "prob" type: "Softmax" bottom: "ip" top: "prob" }
"""

SOLVER = """
# a comment
net: "whatever.prototxt"
base_lr: 0.001
momentum: 0.9
weight_decay: 0.004
lr_policy: "fixed"
max_iter: 4000
"""


def test_parse_message_generic():
    msg = parse_message('a: 1 b { c: "x" c: "y" } a: 2')
    assert msg["a"] == [1, 2]
    assert msg["b"][0]["c"] == ["x", "y"]


def test_adult_net():
    spec = net_from_prototxt(ADULT)
    assert spec.name == "adult"
    assert [i.name for i in spec.inputs] == ["C0"]
    assert spec.inputs[0].shape == (64, 1)
    ip = spec.layer_by_name("ip")
    assert ip.inner_product.num_output == 10
    assert ip.inner_product.weight_filler.type == "xavier"
    assert ip.params[0].lr_mult == 1 and ip.params[1].lr_mult == 2
    assert spec.layers[-1].type == "Softmax"


def test_solver_parse():
    cfg = solver_from_prototxt(SOLVER)
    assert cfg["base_lr"] == 0.001
    assert cfg["momentum"] == 0.9
    assert cfg["weight_decay"] == 0.004
    assert cfg["lr_policy"] == "fixed"
    assert cfg["max_iter"] == 4000


REFERENCE_CIFAR = "/root/reference/models/cifar10/cifar10_quick_train_test.prototxt"


@pytest.mark.skipif(not os.path.exists(REFERENCE_CIFAR),
                    reason="reference mount absent")
def test_reference_cifar10_prototxt():
    spec = net_from_prototxt_file(REFERENCE_CIFAR)
    assert spec.name == "CIFAR10_quick"
    types = [l.type for l in spec.layers]
    assert types.count("Convolution") == 3
    assert types.count("Pooling") == 3
    assert types.count("InnerProduct") == 2
    conv1 = spec.layer_by_name("conv1")
    assert conv1.conv.num_output == 32
    assert conv1.conv.pad == 2 and conv1.conv.kernel_size == 5
    assert conv1.conv.weight_filler.type == "gaussian"
    assert conv1.conv.weight_filler.std == 0.0001
    pool1 = spec.layer_by_name("pool1")
    assert pool1.pool.pool == "MAX" and pool1.pool.kernel_size == 3


REFERENCE_ALEXNET = "/root/reference/models/bvlc_reference_caffenet/train_val.prototxt"


@pytest.mark.skipif(not os.path.exists(REFERENCE_ALEXNET),
                    reason="reference mount absent")
def test_reference_caffenet_prototxt():
    spec = net_from_prototxt_file(
        REFERENCE_ALEXNET,
        input_shapes={"data": (256, 3, 227, 227), "label": (256, 1)})
    types = [l.type for l in spec.layers]
    assert types.count("Convolution") == 5
    assert types.count("LRN") == 2
    assert types.count("Dropout") == 2
    conv2 = spec.layer_by_name("conv2")
    assert conv2.conv.group == 2
    norm1 = spec.layer_by_name("norm1")
    assert norm1.lrn.local_size == 5 and norm1.lrn.alpha == 0.0001


def test_unimplemented_geometry_fields_rejected():
    """Recognized-but-unimplemented Caffe fields must fail loudly, not
    import a structurally different net with defaults."""
    import pytest
    from sparknet_tpu.model.prototxt import net_from_prototxt
    base = """
    name: "g"
    input: "data"
    input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
    layer {
      name: "c" type: "Convolution" bottom: "data" top: "c"
      convolution_param { num_output: 4 %s }
    }
    """
    for bad in ("kernel_h: 3 kernel_w: 5", "stride_h: 2", "pad_w: 1",
                "dilation: 2"):
        with pytest.raises(ValueError, match="not implemented|dilation"):
            net_from_prototxt(base % bad)
    # square geometry still imports
    net_from_prototxt(base % "kernel_size: 3 pad: 1")

    pool_bad = """
    name: "g"
    input: "data"
    input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
    layer {
      name: "p" type: "Pooling" bottom: "data" top: "p"
      pooling_param { pool: MAX kernel_h: 2 }
    }
    """
    with pytest.raises(ValueError, match="not implemented"):
        net_from_prototxt(pool_bad)

    concat_bad = """
    name: "g"
    input: "a"
    input_shape { dim: 1 dim: 4 }
    input: "b"
    input_shape { dim: 1 dim: 4 }
    layer {
      name: "cat" type: "Concat" bottom: "a" bottom: "b" top: "cat"
      concat_param { axis: 2 }
    }
    """
    with pytest.raises(ValueError, match="Concat axis"):
        net_from_prototxt(concat_bad)


def test_square_h_w_geometry_accepted():
    """kernel_h==kernel_w (etc.) is the SAME square geometry as kernel_size
    and must import, not be rejected (r2 review finding); conflicting
    base-vs-h/w values still fail."""
    from sparknet_tpu.model.prototxt import net_from_prototxt
    base = """
    name: "g"
    input: "data"
    input_shape { dim: 1 dim: 3 dim: 8 dim: 8 }
    layer {
      name: "c" type: "Convolution" bottom: "data" top: "c"
      convolution_param { num_output: 4 %s }
    }
    """
    spec = net_from_prototxt(base % "kernel_h: 3 kernel_w: 3 pad_h: 1 pad_w: 1")
    conv = [l for l in spec.layers if l.name == "c"][0]
    assert conv.conv.kernel_size == 3 and conv.conv.pad == 1
    import pytest
    with pytest.raises(ValueError, match="conflicting"):
        net_from_prototxt(base % "kernel_size: 5 kernel_h: 3 kernel_w: 3")
