"""Distributed τ-averaging on the serialized-graph backend — the pairing the
reference proved with `apps/MnistApp.scala:98-138` (per-worker TF steps, then
TensorFlowWeightCollection averaging) and that round 1 lacked.

Covers: loss decrease + replica sync on BOTH a native builder graph and the
imported reference mnist_graph.pb; momentum-slot locality semantics;
set_weights never resetting optimizer slots; in-graph lr schedules.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sparknet_tpu.backend import GraphNet, build_mnist_graph
from sparknet_tpu.backend.tf_import import import_tf_graphdef_file
from sparknet_tpu.parallel import GraphTrainer, make_mesh

MNIST_PB = "/root/reference/models/tensorflow/mnist/mnist_graph.pb"
needs_pb = pytest.mark.skipif(not os.path.exists(MNIST_PB),
                              reason="reference mount absent")

N_DEV, LOCAL_B, TAU = 8, 4, 3


def _mnist_batches(rng, tau=TAU, global_b=N_DEV * LOCAL_B):
    return {
        "data": rng.standard_normal(
            (tau, global_b, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, (tau, global_b)).astype(np.int64),
    }


def _real_digit_batches(rng, tau=TAU, global_b=N_DEV * LOCAL_B):
    """Synthetic but learnable data: class-dependent mean patches."""
    labels = rng.integers(0, 10, (tau, global_b))
    data = 0.1 * rng.standard_normal((tau, global_b, 28, 28, 1))
    for t in range(tau):
        for i in range(global_b):
            c = labels[t, i]
            data[t, i, c:(c + 6), c:(c + 6), 0] += 1.0
    return {"data": data.astype(np.float32),
            "label": labels.astype(np.int64)}


def test_native_graph_distributed_round_syncs_and_learns(rng):
    net = GraphNet(build_mnist_graph(batch=LOCAL_B))
    trainer = GraphTrainer(net, make_mesh(N_DEV), tau=TAU)
    state = trainer.init_state()
    losses = []
    for r in range(4):
        state, loss = trainer.train_round(
            state, _real_digit_batches(np.random.default_rng(r)))
        losses.append(loss)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # replicas synchronized after the averaging collective
    for name, v in state["variables"].items():
        arr = np.asarray(v)
        np.testing.assert_allclose(arr, np.broadcast_to(arr[:1], arr.shape),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"variable {name} diverged")
    # native-graph momentum slots stay worker-local: they hold per-worker
    # gradient history and need NOT be identical across devices
    assert set(state["slots"]) == {
        v for v in net.variable_names}


@needs_pb
def test_imported_pb_distributed_round(rng):
    """The reference's own frozen mnist_graph.pb trains inside the τ-round:
    imported optimizer (ApplyMomentum + ExponentialDecay), autodiff grads,
    on-mesh averaging — `apps/MnistApp.scala:98-138` end to end."""
    net = GraphNet(import_tf_graphdef_file(MNIST_PB))
    trainer = GraphTrainer(net, make_mesh(N_DEV), tau=TAU)
    state = trainer.init_state()
    losses = []
    for r in range(4):
        state, loss = trainer.train_round(
            state, _real_digit_batches(np.random.default_rng(r)))
        losses.append(loss)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    # float variables (weights AND momentum slots — the reference averaged
    # every DT_FLOAT variable) are synced; the int counter advanced by τ
    # per round locally on every device
    vars_ = state["variables"]
    for name, v in vars_.items():
        arr = np.asarray(v)
        if np.issubdtype(arr.dtype, np.floating):
            np.testing.assert_allclose(
                arr, np.broadcast_to(arr[:1], arr.shape), rtol=1e-5,
                atol=1e-6, err_msg=f"float variable {name} diverged")
    assert np.asarray(vars_["Variable_7"]).tolist() == [4 * TAU] * N_DEV
    # eval path: distributed accuracy via psum
    ev = _real_digit_batches(np.random.default_rng(99), tau=1)
    acc = trainer.evaluate(state, {"data": ev["data"][0],
                                   "label": ev["label"][0]})
    assert 0.0 <= acc <= 1.0


def test_set_weights_preserves_optimizer_slots(rng):
    """Reference setWeights only runs //assign ops — momentum accumulators
    persist across syncs (TensorFlowNet.scala:110-121). Regression for the
    round-1 bug where set_weights zeroed velocity every call."""
    net = GraphNet(build_mnist_graph(batch=LOCAL_B))
    b = {"data": rng.standard_normal((LOCAL_B, 28, 28, 1)).astype(np.float32),
         "label": rng.integers(0, 10, (LOCAL_B, 1)).astype(np.int32)}
    net.step(b)
    net.step(b)
    slots_before = {k: np.asarray(v) for k, v in net._slots.items()}
    assert any(np.abs(v).sum() > 0 for v in slots_before.values())
    net.set_weights(net.get_weights())  # a sync round-trip
    for k, v in net._slots.items():
        np.testing.assert_array_equal(np.asarray(v), slots_before[k])
    net.step(b)  # and stepping again still works


def test_native_exp_decay_schedule():
    """Train-node lr_policy=exp_decay: lr(it) = base * rate^floor(it/steps),
    the reference mnist graph's tf.train.exponential_decay in Train attrs."""
    net = GraphNet(build_mnist_graph(batch=64, train_size=64 * 10))
    opt = net.discover_optimizer()
    for it, want in [(0, 0.01), (9, 0.01), (10, 0.0095), (25, 0.01 * 0.95**2)]:
        got = float(opt.lr_fn(net.variables, jnp.asarray(it, jnp.int32)))
        assert got == pytest.approx(want, rel=1e-6), (it, got, want)


def test_get_weights_skips_int_variables():
    """Reference getWeights DT_FLOAT filter (TensorFlowNet.scala:100-105)."""
    if not os.path.exists(MNIST_PB):
        pytest.skip("reference mount absent")
    net = GraphNet(import_tf_graphdef_file(MNIST_PB))
    w = net.get_weights()
    assert "Variable_7" not in w  # int32 global-step counter
    assert "conv1" in w and "conv1/Momentum" in w  # slots DO cross the wire


def test_build_alexnet_graph_shapes():
    """The native AlexNet generator reproduces the reference graph's
    geometry (alexnet_graph.pb variable shapes: conv1 11x11x3x64 /4 VALID
    -> ... -> flat 9216 -> fc 4096/4096/n)."""
    from sparknet_tpu.backend import GraphNet, build_alexnet_graph
    net = GraphNet(build_alexnet_graph(batch=1, n_classes=10))
    shapes = net.forward_shapes(["conv1", "pool1", "flat", "logits", "prob"])
    # conv1 SAME /4 -> 57 (the imported reference pb gives (128,57,57,64);
    # VALID's 55 also flattens to 9216, so check conv1 explicitly)
    assert shapes["conv1"] == (1, 57, 57, 64)
    assert shapes["pool1"] == (1, 28, 28, 64)
    assert shapes["flat"] == (1, 9216)
    assert shapes["logits"] == (1, 10)
    assert shapes["prob"] == (1, 10)
    assert tuple(net.variables["conv1_w"].shape) == (11, 11, 3, 64)
    opt = net.discover_optimizer()
    assert opt.momentum == 0.9


def test_graph_imagenet_app_streaming_loop(tmp_path):
    """TFImageNetApp parity end to end: a serialized graph (JSON, tiny
    AlexNet-shaped convnet) trained in the distributed tau-round from
    STREAMING tar shards with mean-subtract + random-crop preprocessing —
    the full apps/TFImageNetApp.scala shape on the 8-device mesh."""
    import glob
    import os
    import shutil
    from sparknet_tpu.apps import graph_imagenet_app
    from sparknet_tpu.backend.builder import GraphBuilder
    from sparknet_tpu.data import imagenet

    d = str(tmp_path / "data")
    imagenet.write_synthetic_shards(d, n_shards=2, per_shard=40, size=48)
    imagenet.write_synthetic_shards(d + "/v", n_shards=1, per_shard=16,
                                    size=48)
    for f in glob.glob(d + "/v/train.*.tar"):
        shutil.move(f, os.path.join(
            d, os.path.basename(f).replace("train.", "val.")))
    shutil.move(d + "/v/train.txt", d + "/val.txt")

    g = GraphBuilder("tiny")
    g.placeholder("data", (2, 32, 32, 3))
    g.placeholder("label", (2,), dtype="int32")
    g.variable("w", 0.01 * np.random.default_rng(0).standard_normal(
        (5, 5, 3, 8)))
    g.variable("b", np.zeros(8))
    x = g.relu("r", g.bias_add("cb", g.conv2d("c", "data", "w"), "b"))
    x = g.max_pool("p", x)
    f = g.flatten("flat", x)
    g.variable("fw", 0.01 * np.random.default_rng(1).standard_normal(
        (16 * 16 * 8, 10)))
    g.variable("fb", np.zeros(10))
    logits = g.add("logits", g.matmul("fc", f, "fw"), "fb")
    g.accuracy("accuracy", logits, "label")
    loss = g.sparse_softmax_ce("loss", logits, "label")
    graph = g.finalize(loss=loss, learning_rate=0.01, momentum=0.9)
    gpath = str(tmp_path / "tiny.json")
    graph.save(gpath)

    graph_imagenet_app.main([
        "--data-dir", d, "--graph", gpath, "--stream", "always",
        "--val-limit", "12",
        "crop=32", "local_batch=2", "tau=2", "max_rounds=3",
        "eval_every=2", "eval_batch=16", "n_classes=10",
        f'workdir="{tmp_path}"',
    ])


@pytest.mark.slow
def test_reference_alexnet_pb_trains_distributed(tmp_path):
    """The reference's own alexnet_graph.pb (the TFImageNetApp workload)
    trains through GraphTrainer: one tau-round on 2 devices via its
    imported in-graph ApplyMomentum optimizer, loss finite, replicas in
    sync after averaging."""
    import os
    pb = "/root/reference/models/tensorflow/alexnet/alexnet_graph.pb"
    if not os.path.exists(pb):
        pytest.skip("reference alexnet_graph.pb not available")
    from sparknet_tpu.backend import GraphNet
    from sparknet_tpu.backend.tf_import import import_tf_graphdef_file
    from sparknet_tpu.parallel import GraphTrainer, make_mesh

    net = GraphNet(import_tf_graphdef_file(pb), seed=0)
    r = np.random.default_rng(0)
    for v in net.variable_names:  # pb stores no weights (TruncatedNormal)
        if "Momentum" not in v and jnp.issubdtype(
                net.variables[v].dtype, jnp.floating):
            net.variables[v] = jnp.asarray(
                0.01 * r.standard_normal(net.variables[v].shape),
                jnp.float32)
    trainer = GraphTrainer(net, make_mesh(2), tau=1)
    state = trainer.init_state()
    local_b = 1
    batches = {
        "data": r.standard_normal(
            (1, 2 * local_b, 227, 227, 3)).astype(np.float32),
        "label": r.integers(0, 1000, (1, 2 * local_b)).astype(np.int64),
    }
    state, loss = trainer.train_round(state, batches)
    assert np.isfinite(loss)
    # replicas identical after the averaging collective
    w = np.asarray(state["variables"]["conv1/weights"])
    np.testing.assert_array_equal(w[0], w[1])


def test_graph_input_shape_validation():
    """A crop/graph mismatch fails fast naming the shapes, not as a bare
    XLA matmul error mid-round (r2 review)."""
    from sparknet_tpu.apps.graph_common import check_input_shape
    from sparknet_tpu.backend import GraphNet, build_mnist_graph
    net = GraphNet(build_mnist_graph(batch=2))
    check_input_shape(net, "data", (28, 28, 1))  # matches: no raise
    with pytest.raises(ValueError, match="data pipeline produces"):
        check_input_shape(net, "data", (32, 32, 1))


def test_graph_elastic_resume(tmp_path, rng):
    """A graph-backend checkpoint from 8 devices adapts onto 4: variables
    carry exactly (row 0 of the synced state), slots average, the counter
    continues, and a round runs on the adapted state."""
    from sparknet_tpu.parallel.mesh import fetch_global
    from sparknet_tpu.utils import checkpoint as ck

    net = GraphNet(build_mnist_graph(batch=LOCAL_B))
    t8 = GraphTrainer(net, make_mesh(8), tau=2)
    state = t8.init_state()
    state, _ = t8.train_round(state, _mnist_batches(rng, tau=2))
    it8 = int(np.asarray(state["it"])[0])
    vars8 = {k: np.asarray(v)[0] for k, v in state["variables"].items()}

    d = str(tmp_path / "ck")
    ck.save(d, fetch_global(state), step=1, extra={"n_devices": 8, "tp": 1})
    flat, _, extra = ck.restore_flat(d)

    t4 = GraphTrainer(GraphNet(build_mnist_graph(batch=LOCAL_B)),
                      make_mesh(4), tau=2)
    s4 = t4.adapt_state(flat, old_tp=extra["tp"])
    assert np.asarray(s4["it"]).shape == (4,)
    assert int(np.asarray(s4["it"])[0]) == it8
    for k, v in s4["variables"].items():
        np.testing.assert_array_equal(np.asarray(v)[0], vars8[k],
                                      err_msg=k)
    s4, loss = t4.train_round(s4, _mnist_batches(rng, tau=2, global_b=16))
    assert np.isfinite(float(loss))


def test_graph_elastic_resume_through_restore_state(tmp_path, rng):
    """The run_loop resume seam: _restore_state must route a graph
    checkpoint from a different device count through adapt_state WITHOUT
    layout kwargs (GraphTrainer predates state layouts — r7 regression:
    an unconditional old_layout= was a TypeError here), and must refuse
    a logical-layout checkpoint loudly rather than mis-parse it."""
    from sparknet_tpu.apps.train_loop import _restore_state
    from sparknet_tpu.parallel.mesh import fetch_global
    from sparknet_tpu.utils import checkpoint as ck

    t8 = GraphTrainer(GraphNet(build_mnist_graph(batch=LOCAL_B)),
                      make_mesh(8), tau=2)
    state = t8.init_state()
    state, _ = t8.train_round(state, _mnist_batches(rng, tau=2))
    d = str(tmp_path / "ck")
    ck.save(d, fetch_global(state), step=1, extra={"n_devices": 8, "tp": 1})
    flat, _, extra = ck.restore_flat(d)

    t4 = GraphTrainer(GraphNet(build_mnist_graph(batch=LOCAL_B)),
                      make_mesh(4), tau=2)
    s4, same = _restore_state(t4, t4.init_state(), flat, extra)
    assert not same
    assert np.asarray(s4["it"]).shape == (4,)
    _, loss = t4.train_round(s4, _mnist_batches(rng, tau=2, global_b=16))
    assert np.isfinite(float(loss))
    # a NamedSharding-layout checkpoint has no graph-backend reading
    with pytest.raises(ValueError, match="layer-IR"):
        _restore_state(t4, t4.init_state(), flat,
                       dict(extra, layout="logical"))


def test_graph_adapt_rejects_foreign_checkpoint(tmp_path):
    """A layer-backend (params/momentum) checkpoint must be rejected with a
    clear error, not adapted into an empty graph state."""
    from sparknet_tpu.utils import checkpoint as ck
    d = str(tmp_path / "ck")
    ck.save(d, {"params": {"conv1": {"w": np.zeros((8, 2, 2))}},
                "momentum": {"conv1": {"w": np.zeros((8, 2, 2))}},
                "it": np.zeros(8, np.int32)}, step=1,
            extra={"n_devices": 8, "tp": 1})
    flat, _, _ = ck.restore_flat(d)
    t = GraphTrainer(GraphNet(build_mnist_graph(batch=2)), make_mesh(4),
                     tau=1)
    with pytest.raises(ValueError, match="does not match"):
        t.adapt_state(flat)
    with pytest.raises(ValueError, match="no tensor parallelism"):
        t.adapt_state(flat, old_tp=2)
