"""Native gs:// ingest against a local fake-GCS server (r4: the r3 build
delegated cloud storage to a FUSE mount; now the loader streams the bucket
itself — listing, label fetch, ranged tar streams with reconnect-resume —
the reference's per-task S3 GetObject path, `ImageNetLoader.scala:62-63`)."""
import http.server
import json
import os
import threading
import urllib.parse

import numpy as np
import pytest

from sparknet_tpu.data import imagenet


class _FakeGcs(http.server.BaseHTTPRequestHandler):
    """JSON-API subset: paginated listing, alt=media with Range, ?fields=size.
    Knobs (class attrs set by the fixture):
      fail_once    — object names whose next media GET truncates mid-body
                     (Content-Length lies), exercising reconnect-resume
      ignore_range — serve 200-from-zero despite a Range header (a broken
                     middlebox); the client must fail loudly, not corrupt
    """
    objects = {}
    fail_once = set()
    ignore_range = False
    page_size = 2
    range_log = []

    def log_message(self, *a):
        pass

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        parts = parsed.path.split("/")
        # /storage/v1/b/<bucket>/o[/<name>]
        if len(parts) < 6 or parts[1:4] != ["storage", "v1", "b"] or \
                parts[5] != "o":
            self.send_error(404)
            return
        if len(parts) == 6:  # listing
            prefix = qs.get("prefix", [""])[0]
            names = sorted(n for n in self.objects if n.startswith(prefix))
            start = int(qs.get("pageToken", ["0"])[0])
            page = names[start:start + self.page_size]
            d = {"items": [{"name": n, "size": str(len(self.objects[n]))}
                           for n in page]}
            if start + self.page_size < len(names):
                d["nextPageToken"] = str(start + self.page_size)
            self._json(d)
            return
        name = urllib.parse.unquote(parts[6])
        if name not in self.objects:
            self.send_error(404)
            return
        data = self.objects[name]
        if qs.get("alt") == ["media"]:
            start = 0
            rng = self.headers.get("Range")
            if rng:
                type(self).range_log.append((name, rng))
            if rng and not self.ignore_range:
                start = int(rng.split("=")[1].split("-")[0])
                self.send_response(206)
            else:
                self.send_response(200)
            body = data[start:]
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if name in self.fail_once:  # truncate: client must resume
                self.fail_once.discard(name)
                self.wfile.write(body[: max(1, len(body) // 2)])
                self.wfile.flush()
                self.connection.close()
                return
            self.wfile.write(body)
            return
        self._json({"size": str(len(data))})  # metadata

    def _json(self, d):
        body = json.dumps(d).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # simple media upload
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        parts = parsed.path.split("/")
        # /upload/storage/v1/b/<bucket>/o?uploadType=media&name=...
        if len(parts) < 7 or parts[1] != "upload" or \
                qs.get("uploadType") != ["media"] or "name" not in qs:
            self.send_error(400)
            return
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        self.objects[qs["name"][0]] = body
        self._json({"name": qs["name"][0], "size": str(len(body))})


@pytest.fixture
def gcs(tmp_path, monkeypatch):
    """Fake bucket 'bkt' holding synthetic shards under imagenet/, with the
    client pointed at it via STORAGE_EMULATOR_HOST."""
    root = str(tmp_path / "local")
    imagenet.write_synthetic_shards(root, n_shards=3, per_shard=6, size=48)
    objects = {}
    for f in sorted(os.listdir(root)):
        with open(os.path.join(root, f), "rb") as fh:
            objects[f"imagenet/{f}"] = fh.read()
    _FakeGcs.objects = objects
    _FakeGcs.fail_once = set()
    _FakeGcs.ignore_range = False
    _FakeGcs.range_log = []
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _FakeGcs)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    monkeypatch.setenv("STORAGE_EMULATOR_HOST",
                       f"http://127.0.0.1:{srv.server_address[1]}")
    monkeypatch.setenv("no_proxy", "*")
    # retries back off 0.5*2^n seconds; keep the flaky-path test fast
    from sparknet_tpu.data import gcs as gcs_mod
    monkeypatch.setattr(gcs_mod, "BACKOFF_S", 0.01)
    gcs_mod._SIZE_CACHE.clear()
    yield "gs://bkt/imagenet", root
    srv.shutdown()


def test_list_and_labels_match_local(gcs):
    url, root = gcs
    remote = imagenet.list_shards(url, prefix="train.")
    local = imagenet.list_shards(root, prefix="train.")
    assert [os.path.basename(p) for p in remote] == \
        [os.path.basename(p) for p in local]
    assert len(remote) == 3  # > page_size: pagination exercised
    assert imagenet.load_label_map(f"{url}/train.txt") == \
        imagenet.load_label_map(os.path.join(root, "train.txt"))


def test_gs_loader_bit_identical_to_local(gcs):
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    l = imagenet.ShardedTarLoader(imagenet.list_shards(root), labels,
                                  height=32, width=32)
    gi, gl = g.load_all()
    li, ll = l.load_all()
    np.testing.assert_array_equal(gi, li)
    np.testing.assert_array_equal(gl, ll)
    assert g.skipped == 0


def test_gs_mid_shard_seek(gcs):
    """iter_with_pos from a mid-shard cursor continues exactly like the
    local loader — the streaming-resume path over the bucket."""
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))

    def fresh(src):
        return imagenet.ShardedTarLoader(imagenet.list_shards(src), labels,
                                         height=32, width=32)

    all_pos = [(lbl, pos) for _, lbl, pos in fresh(root).iter_with_pos()]
    mid = all_pos[7][1]
    assert mid[0] > 0  # genuinely mid-stream, second shard
    cont = [(lbl, pos) for _, lbl, pos in fresh(url).iter_with_pos(mid)]
    assert cont == all_pos[8:]


def test_gs_stream_resumes_after_disconnect(gcs):
    """A connection dropped mid-tar (Content-Length lies, body truncated)
    must reconnect with a nonzero Range offset and produce IDENTICAL data —
    the multi-hour-epoch survival property FUSE could not give."""
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    shard = sorted(_FakeGcs.objects)[0]
    _FakeGcs.fail_once = {shard}
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    l = imagenet.ShardedTarLoader(imagenet.list_shards(root), labels,
                                  height=32, width=32)
    gi, gl = g.load_all()
    li, ll = l.load_all()
    np.testing.assert_array_equal(gi, li)
    np.testing.assert_array_equal(gl, ll)
    resumes = [(n, r) for n, r in _FakeGcs.range_log
               if n == shard and not r.endswith("=0-")]
    assert resumes, f"no resumed Range request seen: {_FakeGcs.range_log}"


def test_gs_range_ignored_fails_loudly(gcs):
    """A server that ignores Range re-serves from byte 0; silently
    accepting that would corrupt the tar mid-resume."""
    url, _ = gcs
    from sparknet_tpu.data.gcs import gs_open_stream
    s = gs_open_stream(f"{url}/train.0000.tar", start=0)
    head = s.read(100)
    assert len(head) == 100
    s.close()
    _FakeGcs.ignore_range = True
    s = gs_open_stream(f"{url}/train.0000.tar", start=50)
    with pytest.raises(IOError, match="ignored Range"):
        s.read(10)


def test_gs_path_size_uses_listing_cache(gcs):
    url, root = gcs
    shards = imagenet.list_shards(url)
    local = imagenet.list_shards(root)
    for g, l in zip(shards, local):
        assert imagenet.path_size(g) == os.path.getsize(l)
    # cold-cache path: direct metadata GET
    from sparknet_tpu.data import gcs as gcs_mod
    gcs_mod._SIZE_CACHE.clear()
    assert imagenet.path_size(shards[0]) == os.path.getsize(local[0])


def test_gs_streaming_source_end_to_end(gcs):
    """StreamingRoundSource over gs:// shards: rounds equal the local
    stream's bit for bit (the full ingest path — ranged tar streams,
    decode, round assembly — against the bucket)."""
    from sparknet_tpu.data.streaming import StreamingRoundSource
    url, root = gcs
    labels = imagenet.load_label_map(f"{url}/train.txt")

    def source(src_root):
        loader = imagenet.ShardedTarLoader(
            imagenet.list_shards(src_root), labels, height=32, width=32)
        return StreamingRoundSource(loader, 2, 2, 2)

    with source(url) as g, source(root) as l:
        for i in range(3):
            gr, lr = g.next_round(round_index=i), l.next_round(round_index=i)
            np.testing.assert_array_equal(gr["data"], lr["data"])
            np.testing.assert_array_equal(gr["label"], lr["label"])
        assert g.cursor_at(2) == l.cursor_at(2)


def test_parse_gs_url_rejects_malformed():
    from sparknet_tpu.data.gcs import parse_gs_url
    assert parse_gs_url("gs://b/a/c.tar") == ("b", "a/c.tar")
    with pytest.raises(ValueError, match="not a gs"):
        parse_gs_url("/local/path")
    with pytest.raises(ValueError, match="missing bucket"):
        parse_gs_url("gs://")


def test_gs_write_roundtrip_and_sharder_push(gcs):
    """gs_write uploads; the sharder's --upload path pushes a shard dir
    to the bucket and the loader reads it back bit-identically."""
    import sys
    url, root = gcs
    from sparknet_tpu.data.gcs import gs_read, gs_write
    gs_write("gs://bkt/up/x.bin", b"hello-gcs")
    assert gs_read("gs://bkt/up/x.bin") == b"hello-gcs"

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import shard_imagenet
    n = shard_imagenet.upload_dir(root, "gs://bkt/pushed")
    assert n == 4  # 3 shards + train.txt
    labels = imagenet.load_label_map("gs://bkt/pushed/train.txt")
    up = imagenet.ShardedTarLoader(
        imagenet.list_shards("gs://bkt/pushed"), labels, 32, 32)
    local = imagenet.ShardedTarLoader(
        imagenet.list_shards(root), labels, 32, 32)
    np.testing.assert_array_equal(up.load_all()[0], local.load_all()[0])
