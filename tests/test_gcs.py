"""Native gs:// ingest against a local fake-GCS server (r4: the r3 build
delegated cloud storage to a FUSE mount; now the loader streams the bucket
itself — listing, label fetch, ranged tar streams with reconnect-resume —
the reference's per-task S3 GetObject path, `ImageNetLoader.scala:62-63`)."""
import os

import numpy as np
import pytest

from sparknet_tpu.data import imagenet

#: the LIVE handler class of the current fixture's server (state is
#: per-server since r6 — the fixture rebinds this module global so tests
#: keep their `_FakeGcs.objects`-style spelling)
_FakeGcs = None


@pytest.fixture
def gcs(tmp_path, monkeypatch):
    """Fake bucket 'bkt' holding synthetic shards under imagenet/, with the
    client pointed at it via STORAGE_EMULATOR_HOST."""
    global _FakeGcs
    from fake_stores import serve_dir_as_gcs, stop_serving
    root = str(tmp_path / "local")
    imagenet.write_synthetic_shards(root, n_shards=3, per_shard=6, size=48)
    srv, endpoint = serve_dir_as_gcs(root)
    _FakeGcs = srv.handler
    monkeypatch.setenv("STORAGE_EMULATOR_HOST", endpoint)
    monkeypatch.setenv("no_proxy", "*")
    # retries back off 0.5*2^n seconds; keep the flaky-path test fast
    from sparknet_tpu.data import gcs as gcs_mod
    monkeypatch.setattr(gcs_mod, "BACKOFF_S", 0.01)
    gcs_mod._SIZE_CACHE.clear()
    gcs_mod._STAT_CACHE.clear()
    yield "gs://bkt/imagenet", root
    stop_serving(srv)
    _FakeGcs = None


def test_list_and_labels_match_local(gcs):
    url, root = gcs
    remote = imagenet.list_shards(url, prefix="train.")
    local = imagenet.list_shards(root, prefix="train.")
    assert [os.path.basename(p) for p in remote] == \
        [os.path.basename(p) for p in local]
    assert len(remote) == 3  # > page_size: pagination exercised
    assert imagenet.load_label_map(f"{url}/train.txt") == \
        imagenet.load_label_map(os.path.join(root, "train.txt"))


def test_gs_loader_bit_identical_to_local(gcs):
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    l = imagenet.ShardedTarLoader(imagenet.list_shards(root), labels,
                                  height=32, width=32)
    gi, gl = g.load_all()
    li, ll = l.load_all()
    np.testing.assert_array_equal(gi, li)
    np.testing.assert_array_equal(gl, ll)
    assert g.skipped == 0


def test_gs_mid_shard_seek(gcs):
    """iter_with_pos from a mid-shard cursor continues exactly like the
    local loader — the streaming-resume path over the bucket."""
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))

    def fresh(src):
        return imagenet.ShardedTarLoader(imagenet.list_shards(src), labels,
                                         height=32, width=32)

    all_pos = [(lbl, pos) for _, lbl, pos in fresh(root).iter_with_pos()]
    mid = all_pos[7][1]
    assert mid[0] > 0  # genuinely mid-stream, second shard
    cont = [(lbl, pos) for _, lbl, pos in fresh(url).iter_with_pos(mid)]
    assert cont == all_pos[8:]


def test_gs_stream_resumes_after_disconnect(gcs):
    """A connection dropped mid-tar (Content-Length lies, body truncated)
    must reconnect with a nonzero Range offset and produce IDENTICAL data —
    the multi-hour-epoch survival property FUSE could not give."""
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    shard = sorted(_FakeGcs.objects)[0]
    _FakeGcs.fail_once = {shard}
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    l = imagenet.ShardedTarLoader(imagenet.list_shards(root), labels,
                                  height=32, width=32)
    gi, gl = g.load_all()
    li, ll = l.load_all()
    np.testing.assert_array_equal(gi, li)
    np.testing.assert_array_equal(gl, ll)
    resumes = [(n, r) for n, r in _FakeGcs.range_log
               if n == shard and not r.endswith("=0-")]
    assert resumes, f"no resumed Range request seen: {_FakeGcs.range_log}"


def test_gs_range_ignored_fails_loudly(gcs):
    """A server that ignores Range re-serves from byte 0; silently
    accepting that would corrupt the tar mid-resume."""
    url, _ = gcs
    from sparknet_tpu.data.gcs import gs_open_stream
    s = gs_open_stream(f"{url}/train.0000.tar", start=0)
    head = s.read(100)
    assert len(head) == 100
    s.close()
    _FakeGcs.ignore_range = True
    s = gs_open_stream(f"{url}/train.0000.tar", start=50)
    with pytest.raises(IOError, match="ignored Range"):
        s.read(10)


def test_gs_path_size_uses_listing_cache(gcs):
    url, root = gcs
    shards = imagenet.list_shards(url)
    local = imagenet.list_shards(root)
    for g, l in zip(shards, local):
        assert imagenet.path_size(g) == os.path.getsize(l)
    # cold-cache path: direct metadata GET
    from sparknet_tpu.data import gcs as gcs_mod
    gcs_mod._SIZE_CACHE.clear()
    assert imagenet.path_size(shards[0]) == os.path.getsize(local[0])


def test_gs_streaming_source_end_to_end(gcs):
    """StreamingRoundSource over gs:// shards: rounds equal the local
    stream's bit for bit (the full ingest path — ranged tar streams,
    decode, round assembly — against the bucket)."""
    from sparknet_tpu.data.streaming import StreamingRoundSource
    url, root = gcs
    labels = imagenet.load_label_map(f"{url}/train.txt")

    def source(src_root):
        loader = imagenet.ShardedTarLoader(
            imagenet.list_shards(src_root), labels, height=32, width=32)
        return StreamingRoundSource(loader, 2, 2, 2)

    with source(url) as g, source(root) as l:
        for i in range(3):
            gr, lr = g.next_round(round_index=i), l.next_round(round_index=i)
            np.testing.assert_array_equal(gr["data"], lr["data"])
            np.testing.assert_array_equal(gr["label"], lr["label"])
        assert g.cursor_at(2) == l.cursor_at(2)


def test_parse_gs_url_rejects_malformed():
    from sparknet_tpu.data.gcs import parse_gs_url
    assert parse_gs_url("gs://b/a/c.tar") == ("b", "a/c.tar")
    with pytest.raises(ValueError, match="not a gs"):
        parse_gs_url("/local/path")
    with pytest.raises(ValueError, match="missing bucket"):
        parse_gs_url("gs://")


def test_gs_write_roundtrip_and_sharder_push(gcs):
    """gs_write uploads; the sharder's --upload path pushes a shard dir
    to the bucket and the loader reads it back bit-identically."""
    import sys
    url, root = gcs
    from sparknet_tpu.data.gcs import gs_read, gs_write
    gs_write("gs://bkt/up/x.bin", b"hello-gcs")
    assert gs_read("gs://bkt/up/x.bin") == b"hello-gcs"

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import shard_imagenet
    n = shard_imagenet.upload_dir(root, "gs://bkt/pushed")
    assert n == 4  # 3 shards + train.txt
    labels = imagenet.load_label_map("gs://bkt/pushed/train.txt")
    up = imagenet.ShardedTarLoader(
        imagenet.list_shards("gs://bkt/pushed"), labels, 32, 32)
    local = imagenet.ShardedTarLoader(
        imagenet.list_shards(root), labels, 32, 32)
    np.testing.assert_array_equal(up.load_all()[0], local.load_all()[0])


def test_gs_second_epoch_carve_bit_identical(gcs):
    """Epoch 1 walks the bucket tar with tarfile and captures a member
    index; epoch 2 carves members from the ranged stream by (offset,
    size) — no tar header parsing (r5: the bucket path's answer to the
    local C member indexer). Bytes must be identical and the carve
    stream must OPEN at the first member's offset, not 0."""
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    e1 = g.load_all()
    assert len(g._bucket_indices) == 3  # every shard's walk completed
    _FakeGcs.range_log.clear()
    e2 = g.load_all()
    np.testing.assert_array_equal(e1[0], e2[0])
    np.testing.assert_array_equal(e1[1], e2[1])
    assert g.skipped == 0
    # every epoch-2 open was a carve open at a member offset (> 0)
    assert _FakeGcs.range_log, "carve path issued no ranged reads"
    for name, rng in _FakeGcs.range_log:
        assert int(rng.split("=")[1].split("-")[0]) > 0, (name, rng)


def test_gs_carve_resume_skips_prefix(gcs):
    """With a warm index, a mid-shard resume opens the stream AT the
    member offset instead of reading through the prefix — removing the
    partial-shard-download-per-restart cost the r4 docstring conceded."""
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    all_pos = [(img.tobytes(), lbl, pos)
               for img, lbl, pos in g.iter_with_pos()]
    mid = all_pos[7][2]
    _FakeGcs.range_log.clear()
    cont = [(img.tobytes(), lbl, pos)
            for img, lbl, pos in g.iter_with_pos(mid)]
    assert cont == all_pos[8:]
    starts = [int(rng.split("=")[1].split("-")[0])
              for _, rng in _FakeGcs.range_log]
    assert starts and min(starts) >= 512  # never re-read the tar prefix


def test_gs_mid_walk_replace_forces_rewalk_next_epoch(gcs):
    """The freshness token is captured BEFORE the walk: an object
    replaced WHILE epoch 1 streams it leaves an index paired with the
    PRE-replacement stat, so epoch 2's fresh stat differs and the shard
    is re-walked — a post-walk capture would pair old offsets with the
    new token and carve garbage forever."""
    from sparknet_tpu.data.gcs import gs_write
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    g.DECODE_CHUNK = 1  # yield per entry so the walk is genuinely
    # mid-flight at the replacement (the default buffers a whole chunk)
    shard0 = imagenet.list_shards(url)[0]
    it = g.iter_with_pos()
    next(it)  # shard 0's walk has started: its stat is already captured
    name = sorted(n for n in _FakeGcs.objects if n.endswith(".tar"))[0]
    gs_write(f"gs://bkt/{name}", _FakeGcs.objects[name])  # gen bump
    for _ in it:  # drain: index cached with the PRE-replacement stat
        pass
    cached_stat = g._bucket_indices[shard0][1]
    assert cached_stat != imagenet.path_stat(shard0, fresh=True)
    g.load_all()  # epoch 2 must re-walk shard 0 and refresh its stat
    assert g._bucket_indices[shard0][1] == \
        imagenet.path_stat(shard0, fresh=True)


def test_gs_resume_walk_captures_index(gcs):
    """A COLD resume (skip>0, no warm index) still iterates the tar stream
    from byte 0 and records every member — so reaching end-of-archive must
    cache the index (ADVICE r5 #4: the old `skip == 0` gate threw it away
    and the resumed shard paid one extra full header-parsing walk)."""
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    mid_shard_entry = 2  # resume mid-shard-0: skip>0 on its walk
    drained = list(g.iter_with_pos((0, mid_shard_entry)))
    assert drained
    assert len(g._bucket_indices) == 3  # resumed shard's index kept too
    # and the captured index carves the next epoch bit-identically
    full = imagenet.ShardedTarLoader(imagenet.list_shards(root), labels,
                                     height=32, width=32)
    np.testing.assert_array_equal(g.load_all()[0], full.load_all()[0])


def test_gs_carve_disconnect_resumes(gcs):
    """The carve path rides the same reconnect-resume transport: a body
    truncated mid-member on epoch 2 is retried from the break, bytes
    bit-identical."""
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    e1 = g.load_all()
    _FakeGcs.fail_once = {"imagenet/train.0001.tar"}
    e2 = g.load_all()
    np.testing.assert_array_equal(e1[0], e2[0])


def test_gs_carve_short_object_fails_loudly(gcs):
    """An object that SHRANK under a warm index (overwritten upload) must
    raise, not feed short members to the decoder as routine corruption.
    The per-epoch freshness check spots the size change, drops the index,
    and the tarfile re-walk then fails loudly on the truncated archive."""
    import tarfile
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    g.load_all()
    name = "imagenet/train.0002.tar"
    _FakeGcs.objects[name] = _FakeGcs.objects[name][:1024]
    from sparknet_tpu.data import gcs as gcs_mod
    gcs_mod._SIZE_CACHE.clear()
    with pytest.raises((IOError, ConnectionError, tarfile.ReadError)):
        g.load_all()
    assert not any(k.endswith("train.0002.tar")
                   for k in g._bucket_indices), \
        "stale index survived the size change"


def test_gs_equal_size_replace_invalidated_by_generation(gcs):
    """An EQUAL-size replacement is invisible to the size check — the
    generation token (bumped by every write, returned by the same
    metadata GET) must drop the warm index so the walk re-reads instead
    of carving at possibly-stale offsets (ADVICE r5 #3)."""
    from sparknet_tpu.data.gcs import gs_write
    url, root = gcs
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    e1 = g.load_all()
    assert len(g._bucket_indices) == 3
    # re-upload identical bytes: same size, NEW generation
    name = sorted(n for n in _FakeGcs.objects if n.endswith(".tar"))[0]
    gs_write(f"gs://bkt/{name}", _FakeGcs.objects[name])
    _FakeGcs.range_log.clear()
    e2 = g.load_all()
    np.testing.assert_array_equal(e1[0], e2[0])
    # the replaced shard was re-WALKED (a from-byte-0 stream), not carved
    # at warm offsets; un-replaced shards still carve (opens > 0)
    starts = [int(rng.split("=")[1].split("-")[0])
              for n, rng in _FakeGcs.range_log if n == name]
    assert (not starts) or min(starts) == 0, starts
    # ... and the walk re-captured a fresh index for it
    assert any(k.endswith(name.split("/")[-1]) for k in g._bucket_indices)


def test_gs_carve_index_invalidated_on_object_replace(gcs):
    """An object REPLACED under a warm index (different size) must not be
    carved at stale offsets: the per-epoch freshness check (one metadata
    GET per shard) drops the index and the tarfile walk re-reads the NEW
    content — parity with the pre-index behavior."""
    import io
    import tarfile
    url, root = gcs
    labels = dict(imagenet.load_label_map(os.path.join(root, "train.txt")))
    g = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    g.load_all()
    assert g._bucket_indices
    # replace shard 0 with a one-member tar of a fresh synthetic image
    other = str(os.path.dirname(root)) + "/other"
    label_path2 = imagenet.write_synthetic_shards(
        other, n_shards=1, per_shard=3, size=48)
    name = sorted(n for n in _FakeGcs.objects if n.endswith(".tar"))[0]
    with open(os.path.join(other, "train.0000.tar"), "rb") as fh:
        _FakeGcs.objects[name] = fh.read()
    labels.update(imagenet.load_label_map(label_path2))
    g.label_map.update(labels)
    from sparknet_tpu.data import gcs as gcs_mod
    gcs_mod._SIZE_CACHE.clear()
    imgs, lbls = g.load_all()  # must NOT raise or silently skip-all
    l = imagenet.ShardedTarLoader(
        [os.path.join(other, "train.0000.tar")]
        + imagenet.list_shards(root)[1:], labels, height=32, width=32)
    li, ll = l.load_all()
    np.testing.assert_array_equal(imgs, li)
    np.testing.assert_array_equal(lbls, ll)
