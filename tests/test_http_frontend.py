"""The serve network data plane (serve/http_frontend.py + serve/router.py):
keep-alive connection reuse, JSON/npz wire decode, 429-with-Retry-After
admission control, deadline shedding that answers instead of hanging,
multi-model routing over the shared worker pool, and a replica draining
mid-traffic with zero dropped responses (the chaos bar PR 3 set).

Tier-1: CPU backend, lenet shapes, ephemeral ports.
"""
import http.client
import json
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.serve import (DeadlineExpiredError, HttpFrontend,
                                InferenceServer, ModelRouter,
                                NoReplicaError, QueueFullError,
                                RouterConfig, ServeConfig, http_infer,
                                zeros_batch)
from sparknet_tpu.zoo import lenet


def _example(i: int) -> dict:
    r = np.random.default_rng(2000 + i)
    return {"data": r.standard_normal((28, 28, 1)).astype(np.float32)}


class SlowNet:
    """Facade that makes every forward take `delay_s` — the knob that
    turns a CPU lenet into an overloadable server for backpressure and
    shed tests."""

    def __init__(self, inner, delay_s: float):
        self._inner, self.delay_s = inner, delay_s

    def __getattr__(self, k):
        return getattr(self._inner, k)

    def forward(self, *a, **kw):
        time.sleep(self.delay_s)
        return self._inner.forward(*a, **kw)


@pytest.fixture(scope="module")
def net():
    return JaxNet(lenet(batch=4))


def _post(conn: http.client.HTTPConnection, path: str, body: bytes,
          ctype: str = "application/json", headers: dict = None):
    h = {"Content-Type": ctype, **(headers or {})}
    conn.request("POST", path, body=body, headers=h)
    resp = conn.getresponse()
    return resp, resp.read()


# -- wire format + keep-alive ------------------------------------------------

def test_json_roundtrip_on_one_keepalive_connection(net):
    """Five sequential requests over ONE HTTP/1.1 connection: all
    answered, outputs match a direct forward, and the server saw exactly
    one connection (keep-alive reuse asserted, not assumed)."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        fe = HttpFrontend(srv, port=0)
        try:
            host, port = fe.address
            conn = http.client.HTTPConnection(host, port, timeout=30)
            for i in range(5):
                x = _example(i)
                body = json.dumps(
                    {"inputs": {"data": x["data"].tolist()}}).encode()
                resp, data = _post(conn, "/v1/infer", body)
                assert resp.status == 200, data
                out = json.loads(data)
                assert out["model"] == "default"
                direct = net.forward(
                    {**zeros_batch(net, 1), "data": x["data"][None]},
                    blob_names=["prob"])
                np.testing.assert_allclose(
                    np.asarray(out["outputs"]["prob"]),
                    direct["prob"][0], rtol=1e-4, atol=1e-4)
            conn.close()
            assert fe.requests == 5
            assert fe.connections == 1, (
                f"{fe.connections} connections for 5 requests — "
                f"keep-alive reuse is broken")
        finally:
            fe.stop()


def test_npz_roundtrip_exact_dtype(net):
    """The raw-tensor wire format: npz in, npz out, float32 end to end,
    bitwise-equal to the in-process submit path at the same bucket."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, buckets=(4,),
                      outputs=("fc2",), metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        fe = HttpFrontend(srv, port=0)
        try:
            x = _example(0)
            inproc = srv.infer(x)
            out = http_infer(f"http://{fe.address[0]}:{fe.address[1]}",
                             "default", x, deadline_s=30.0)
            assert out["fc2"].dtype == np.float32
            np.testing.assert_array_equal(out["fc2"], inproc["fc2"])
        finally:
            fe.stop()


def test_bad_requests_answered_not_hung(net):
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        fe = HttpFrontend(srv, port=0)
        try:
            host, port = fe.address
            conn = http.client.HTTPConnection(host, port, timeout=30)
            # undecodable body -> 400
            resp, data = _post(conn, "/v1/infer", b"not json")
            assert resp.status == 400
            assert json.loads(data)["error_kind"] == "bad_request"
            # unknown model -> 404 (and the connection survived the 400)
            resp, data = _post(conn, "/v1/models/nope/infer",
                               json.dumps({"inputs": {}}).encode())
            assert resp.status == 404
            assert json.loads(data)["error_kind"] == "unknown_model"
            # not a net input -> 400 with the field named
            resp, data = _post(conn, "/v1/infer", json.dumps(
                {"inputs": {"bogus": [1.0]}}).encode())
            assert resp.status == 400
            assert "bogus" in json.loads(data)["error"]
            # GET surfaces
            conn.request("GET", "/v1/models")
            r = conn.getresponse()
            models = json.loads(r.read())["models"]
            assert "default" in models
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            assert r.status == 200
            r.read()
            assert fe.connections == 1  # all of it on one connection
        finally:
            fe.stop()


# -- admission control + shedding --------------------------------------------

def test_429_retry_after_under_full_queue(net):
    """Queue at capacity: excess requests are answered 429 with a
    Retry-After header (admission control wired to QueueFullError), the
    admitted ones still serve, nothing hangs."""
    cfg = ServeConfig(max_batch=2, max_wait_ms=1.0, max_queue=2,
                      outputs=("prob",), metrics_every_batches=0)
    slow = SlowNet(net, 0.15)
    with InferenceServer(slow, cfg) as srv:
        srv.submit(_example(0)).result(timeout=30)  # compile outside
        fe = HttpFrontend(srv, port=0)
        try:
            url = f"http://{fe.address[0]}:{fe.address[1]}"
            codes, retry_after = [], []
            lock = threading.Lock()

            def client(i):
                conn = http.client.HTTPConnection(*fe.address, timeout=30)
                body = json.dumps(
                    {"inputs": {"data": _example(i)["data"].tolist()}}
                ).encode()
                resp, data = _post(conn, "/v1/infer", body)
                with lock:
                    codes.append(resp.status)
                    if resp.status == 429:
                        retry_after.append(
                            resp.getheader("Retry-After"))
                        assert json.loads(data)["error_kind"] == \
                            "queue_full"
                conn.close()

            ts = [threading.Thread(target=client, args=(i,))
                  for i in range(12)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in ts), "a client hung"
            assert time.perf_counter() - t0 < 30
            assert codes.count(200) >= 2, codes   # admitted ones served
            assert 429 in codes, codes            # and overload was shed
            assert all(ra and int(ra) >= 1 for ra in retry_after)
        finally:
            fe.stop()


def test_deadline_shed_answers_503_not_hang(net):
    """Expired deadlines: requests whose deadline passes while queued
    behind a slow forward are answered 503 + Retry-After (error_kind
    deadline) within bounded time — never a hang, and the shed counter
    tells the story."""
    cfg = ServeConfig(max_batch=2, max_wait_ms=1.0, outputs=("prob",),
                      metrics_every_batches=0)
    slow = SlowNet(net, 0.3)
    with InferenceServer(slow, cfg) as srv:
        srv.submit(_example(0)).result(timeout=30)  # compile outside
        fe = HttpFrontend(srv, port=0)
        try:
            host, port = fe.address
            # occupy the worker, then pile deadlined requests behind it
            blocker = srv.submit(_example(1))
            time.sleep(0.05)  # blocker's batch is in its slow forward

            codes = []
            lock = threading.Lock()

            def client(i):
                conn = http.client.HTTPConnection(host, port, timeout=30)
                body = json.dumps({
                    "inputs": {"data": _example(i)["data"].tolist()},
                    "deadline_ms": 100.0}).encode()
                resp, data = _post(conn, "/v1/infer", body)
                with lock:
                    codes.append((resp.status,
                                  resp.getheader("Retry-After"),
                                  json.loads(data).get("error_kind")))
                conn.close()

            ts = [threading.Thread(target=client, args=(i,))
                  for i in range(2, 8)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            dt = time.perf_counter() - t0
            assert not any(t.is_alive() for t in ts), "a client hung"
            assert dt < 10, f"shed took {dt:.1f}s"
            blocker.result(timeout=30)
            shed = [c for c in codes if c[0] == 503]
            assert shed, codes  # the 100 ms deadlines could not all make it
            for status, ra, kind in shed:
                assert kind == "deadline" and ra is not None
            assert srv.batcher.shed >= len(shed)
        finally:
            fe.stop()


# -- multi-model routing ------------------------------------------------------

def test_router_serves_two_models_with_per_model_metrics(net):
    """Two models over one shared pool: requests route to the right
    net (weights differ between lanes), per-model buckets hold, and the
    shared registry carries model-labeled families for both."""
    r = ModelRouter(RouterConfig(workers=2))
    net_b = JaxNet(lenet(batch=4))
    # make b's weights visibly different from a's
    net_b.params = {ln: {pn: w * 0.5 for pn, w in lp.items()}
                    for ln, lp in net_b.params.items()}
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("fc2",),
                      metrics_every_batches=0)
    r.add_model("a", net, cfg=cfg)
    r.add_model("b", net_b, cfg=cfg)
    with r:
        fe = HttpFrontend(r, port=0)
        try:
            url = f"http://{fe.address[0]}:{fe.address[1]}"
            x = _example(0)
            out_a = http_infer(url, "a", x, deadline_s=30.0)
            out_b = http_infer(url, "b", x, deadline_s=30.0)
            da = net.forward({**zeros_batch(net, 1),
                              "data": x["data"][None]},
                             blob_names=["fc2"])
            db = net_b.forward({**zeros_batch(net_b, 1),
                                "data": x["data"][None]},
                               blob_names=["fc2"])
            np.testing.assert_allclose(out_a["fc2"], da["fc2"][0],
                                       rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(out_b["fc2"], db["fc2"][0],
                                       rtol=1e-4, atol=1e-4)
            assert not np.allclose(out_a["fc2"], out_b["fc2"])
            # /v1/infer is ambiguous with two models
            conn = http.client.HTTPConnection(*fe.address, timeout=30)
            resp, data = _post(conn, "/v1/infer", json.dumps(
                {"inputs": {"data": x["data"].tolist()}}).encode())
            assert resp.status == 404
            conn.close()
            text = r.registry.render_prometheus()
            assert ('sparknet_serve_requests_total{model="a",'
                    'outcome="ok"}') in text
            assert ('sparknet_serve_requests_total{model="b",'
                    'outcome="ok"}') in text
            assert 'sparknet_serve_routed_total{model="a",' in text
        finally:
            fe.stop()


@pytest.mark.chaos
def test_replica_drains_mid_traffic_zero_dropped(net):
    """The routing chaos bar: model m has a local replica (router A) and
    a remote replica (router B behind its HTTP frontend). Mid-traffic
    the local replica DRAINS: every in-flight and queued request still
    answers, new traffic routes to the remote replica, zero dropped or
    corrupted responses."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    rb = ModelRouter(RouterConfig(workers=1))
    rb.add_model("m", JaxNet(lenet(batch=4)), cfg=cfg)
    ra = ModelRouter(RouterConfig(workers=1))
    ra.add_model("m", net, cfg=cfg)
    with rb:
        fe_b = HttpFrontend(rb, port=0)
        with ra:
            ra.add_remote_replica(
                "m", f"http://{fe_b.address[0]}:{fe_b.address[1]}")
            answered, bad = [], []
            stop = threading.Event()

            def client(c):
                i = 0
                while not stop.is_set():
                    try:
                        out = ra.infer("m", _example(c * 10000 + i),
                                       timeout=30.0)
                        p = np.asarray(out["prob"])
                        if p.shape != (10,) or not np.isfinite(p).all():
                            bad.append((c, i, p))
                        answered.append((c, i))
                    except Exception as e:
                        bad.append((c, i, e))
                    i += 1

            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(4)]
            for t in threads:
                t.start()
            try:
                time.sleep(0.4)  # traffic flowing through both replicas
                before = len(answered)
                ra.drain("m", "local:m")  # in-flight must still answer
                time.sleep(0.6)  # all new traffic rides the remote
                assert len(answered) > before + 4, \
                    "traffic stalled after drain"
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=30)
            assert not any(t.is_alive() for t in threads)
            assert not bad, bad[:3]
            assert len(answered) > 20
            # the drain actually shifted routing to the remote replica
            routed = ra.registry.counter(
                "sparknet_serve_routed_total",
                labels=("model", "replica"))
            remote_name = ra.replicas["m"][1].name
            assert routed.value(model="m", replica=remote_name) > 0
        fe_b.stop()


def test_busy_router_still_runs_idle_lane_duties(net, tmp_path):
    """Sustained traffic to one lane must not starve the others'
    periodic duties (regression: the pool only ran duty_tick on idle
    sweeps): with a SINGLE pool worker hammered on model a, model b's
    checkpoint hot-reload poll still runs and lands a swap, the router
    heartbeat keeps beating, and /healthz stays ok throughout."""
    from sparknet_tpu.utils import checkpoint as ckpt
    from sparknet_tpu.utils.heartbeat import read_heartbeat

    net_b = JaxNet(lenet(batch=4))
    ckdir = tmp_path / "ck"
    flat = {f"params/{ln}/{pn}": np.asarray(w)[None] * 0.9
            for ln, lp in net_b.params.items() for pn, w in lp.items()}
    ckpt.save(str(ckdir), flat, step=1)
    hb_path = str(tmp_path / "hb.json")
    r = ModelRouter(RouterConfig(workers=1, heartbeat_path=hb_path,
                                 heartbeat_every_s=0.05))
    cfg_a = ServeConfig(max_batch=4, max_wait_ms=1.0, outputs=("prob",),
                        metrics_every_batches=0)
    cfg_b = ServeConfig(max_batch=4, max_wait_ms=1.0, outputs=("prob",),
                        checkpoint_dir=str(ckdir), poll_interval_s=0.05,
                        metrics_every_batches=0)
    r.add_model("a", net, cfg=cfg_a)
    r.add_model("b", net_b, cfg=cfg_b)
    with r:
        r.infer("a", _example(0))  # compile before the hammer
        assert r.lanes["b"].manager.step == 1
        stop = threading.Event()
        unhealthy = []

        def hammer():
            i = 0
            while not stop.is_set():
                r.infer("a", _example(i), timeout=30.0)
                i += 1

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            time.sleep(0.3)  # lane a saturates the single pool worker
            ckpt.save(str(ckdir), flat, step=2)  # b must still poll
            deadline = time.monotonic() + 10
            while r.lanes["b"].manager.step != 2 and \
                    time.monotonic() < deadline:
                if not r.healthy():
                    unhealthy.append(time.monotonic())
                time.sleep(0.02)
        finally:
            stop.set()
            t.join(timeout=30)
        assert r.lanes["b"].manager.step == 2, (
            "idle lane's hot-reload poll starved under sustained "
            "traffic to the other lane")
        assert not unhealthy, "router read unhealthy while serving fine"
        hb = read_heartbeat(hb_path)
        assert hb is not None and hb["age_s"] < 5.0, (
            "router heartbeat starved under sustained traffic")


def test_router_no_replica_is_503_shed(net):
    """Every replica draining -> NoReplicaError locally, 503 +
    Retry-After over HTTP (load shedding, never a hang)."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    r = ModelRouter(RouterConfig(workers=1))
    r.add_model("m", net, cfg=cfg)
    with r:
        fe = HttpFrontend(r, port=0)
        try:
            r.drain("m", "local:m")
            with pytest.raises(NoReplicaError):
                r.submit("m", _example(0))
            conn = http.client.HTTPConnection(*fe.address, timeout=30)
            resp, data = _post(conn, "/v1/models/m/infer", json.dumps(
                {"inputs": {"data": _example(0)["data"].tolist()}}
            ).encode())
            assert resp.status == 503
            assert resp.getheader("Retry-After") is not None
            assert json.loads(data)["error_kind"] == "no_replica"
            conn.close()
        finally:
            fe.stop()


# -- connection hygiene -------------------------------------------------------

def test_idle_keepalive_timeout_releases_connection(net):
    """Thread-per-connection means an idle keep-alive connection pins an
    OS thread: past idle_timeout_s the server must close it (and the
    active-connections gauge must drop back), instead of holding it
    forever."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        fe = HttpFrontend(srv, port=0, idle_timeout_s=0.3)
        try:
            conn = http.client.HTTPConnection(*fe.address, timeout=10)
            body = json.dumps(
                {"inputs": {"data": _example(0)["data"].tolist()}}
            ).encode()
            resp, data = _post(conn, "/v1/infer", body)
            assert resp.status == 200
            gauge = srv.registry.gauge(
                "sparknet_serve_http_connections_active",
                labels=("transport",))
            assert gauge.value(transport="http") == 1
            # idle past the timeout: the server hangs up
            deadline = time.monotonic() + 10
            while gauge.value(transport="http") != 0 and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            assert gauge.value(transport="http") == 0, (
                "idle connection still pinning its thread")
            conn.close()
        finally:
            fe.stop()


def test_max_connections_cap_answers_503(net):
    """Connections past the cap are ANSWERED 503 (error_kind
    over_capacity) + Connection: close — not silently refused, and the
    capped connections release immediately (the flood cannot pin
    threads)."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        fe = HttpFrontend(srv, port=0, max_connections=2,
                          idle_timeout_s=30.0)
        try:
            body = json.dumps(
                {"inputs": {"data": _example(0)["data"].tolist()}}
            ).encode()
            held = []
            for i in range(2):  # occupy the cap with keep-alive conns
                c = http.client.HTTPConnection(*fe.address, timeout=10)
                resp, _ = _post(c, "/v1/infer", body)
                assert resp.status == 200
                held.append(c)
            over = http.client.HTTPConnection(*fe.address, timeout=10)
            resp, data = _post(over, "/v1/infer", body)
            assert resp.status == 503
            assert json.loads(data)["error_kind"] == "over_capacity"
            assert resp.getheader("Connection") == "close"
            assert resp.getheader("Retry-After") is not None
            over.close()
            assert fe.rejected_over_cap == 1
            # the held connections still serve (cap != collapse)
            resp, _ = _post(held[0], "/v1/infer", body)
            assert resp.status == 200
            for c in held:
                c.close()
        finally:
            fe.stop()


def test_mid_body_read_timeout_answers_408_and_closes(net):
    """A client that stalls mid-body: the server's read times out, and
    the reply must be a typed 408 that CLOSES the connection — the
    unread body bytes have desynced the keep-alive stream, and leaving
    it open would parse them as the next request line."""
    import socket as socketlib

    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        fe = HttpFrontend(srv, port=0, idle_timeout_s=0.3)
        try:
            s = socketlib.create_connection(fe.address, timeout=10)
            s.sendall(b"POST /v1/infer HTTP/1.1\r\nHost: x\r\n"
                      b"Content-Type: application/json\r\n"
                      b"Content-Length: 1000\r\n\r\n"
                      b'{"inputs"')  # 991 bytes never arrive
            data = b""
            while True:  # server must answer then close (EOF)
                chunk = s.recv(4096)
                if not chunk:
                    break
                data += chunk
            s.close()
            assert b" 408 " in data.split(b"\r\n")[0], data[:80]
            assert b"request_timeout" in data
            assert b"Connection: close" in data
            # ...and the server is still serving new connections
            conn = http.client.HTTPConnection(*fe.address, timeout=10)
            body = json.dumps(
                {"inputs": {"data": _example(0)["data"].tolist()}}
            ).encode()
            resp, _ = _post(conn, "/v1/infer", body)
            assert resp.status == 200
            conn.close()
        finally:
            fe.stop()


# -- client cache hygiene -----------------------------------------------------

class _MidReplyCloser(threading.Thread):
    """A server that reads the request then closes MID-REPLY (announces
    100 body bytes, sends 5) — the poisoned-stream regression food."""

    def __init__(self):
        super().__init__(daemon=True)
        import socket
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.address = self.sock.getsockname()
        self.running = True

    def run(self):
        while self.running:
            try:
                c, _ = self.sock.accept()
            except OSError:
                return
            try:
                c.settimeout(5.0)
                buf = b""
                while b"\r\n\r\n" not in buf:
                    buf += c.recv(4096)
                c.sendall(b"HTTP/1.1 200 OK\r\n"
                          b"Content-Type: application/x-npz\r\n"
                          b"Content-Length: 100\r\n\r\nxxxxx")
            except OSError:
                pass
            finally:
                c.close()

    def stop(self):
        self.running = False
        self.sock.close()


def test_http_infer_evicts_cached_conn_on_mid_reply_close():
    """A server that dies mid-reply must not leave a poisoned connection
    in the thread cache: http_infer raises (after its one fresh-socket
    retry) AND the cache holds nothing for that address — the next call
    starts clean instead of desyncing on a half-read stream."""
    from sparknet_tpu.serve.http_frontend import _conn_cache

    srv = _MidReplyCloser()
    srv.start()
    try:
        host, port = srv.address
        with pytest.raises((ConnectionError, OSError)):
            http_infer(f"http://{host}:{port}", "m", _example(0),
                       timeout=5.0)
        cache = getattr(_conn_cache, "conns", {})
        assert (host, port) not in cache, (
            "half-read connection left in the thread cache")
    finally:
        srv.stop()


def test_http_infer_connection_cache_is_bounded():
    """The per-thread keep-alive cache is LRU-bounded: sweeping many
    addresses (a router proxying to a large fleet) must not accumulate
    one socket per address forever."""
    from sparknet_tpu.serve.http_frontend import (_conn_cache,
                                                  _connection,
                                                  MAX_CACHED_CONNECTIONS)

    for p in range(20000, 20040):  # never connected: construction only
        _connection("127.0.0.1", p, timeout=1.0)
    cache = getattr(_conn_cache, "conns", {})
    n = sum(1 for (h, p) in cache if 20000 <= p < 20040)
    assert n <= MAX_CACHED_CONNECTIONS
    # most-recently-used survives the sweep (LRU, not random)
    assert ("127.0.0.1", 20039) in cache


# -- per-tenant admission -----------------------------------------------------

@pytest.mark.chaos
def test_hot_tenant_cannot_starve_quiet_tenant(net):
    """Token buckets AHEAD of the 429 path: a hot tenant flooding far
    past its rate is shed typed (429 error_kind tenant_limit, counted
    reason="tenant_limit") while a quiet tenant's paced requests ALL
    serve — the hot flood never occupies the queue slots the quiet
    tenant needs."""
    from sparknet_tpu.serve import TenantAdmission, TenantLimitError

    cfg = ServeConfig(max_batch=2, max_wait_ms=1.0, max_queue=4,
                      outputs=("prob",), metrics_every_batches=0)
    slow = SlowNet(net, 0.02)
    with InferenceServer(slow, cfg) as srv:
        srv.submit(_example(0)).result(timeout=30)  # compile outside
        fe = HttpFrontend(srv, port=0,
                          tenants=TenantAdmission(rate_rps=5.0,
                                                  burst=2))
        try:
            url = f"http://{fe.address[0]}:{fe.address[1]}"
            hot = {"ok": 0, "tenant_limit": 0, "queue_full": 0,
                   "other": 0}
            stop = threading.Event()

            def hot_client():
                while not stop.is_set():
                    try:
                        http_infer(url, "default", _example(1),
                                   deadline_s=5.0, tenant="hot")
                        hot["ok"] += 1
                    except TenantLimitError:
                        hot["tenant_limit"] += 1
                    except QueueFullError:
                        hot["queue_full"] += 1
                    except Exception:
                        hot["other"] += 1

            ts = [threading.Thread(target=hot_client, daemon=True)
                  for _ in range(2)]
            for t in ts:
                t.start()
            try:
                time.sleep(0.1)  # the flood is flowing
                quiet_ok = 0
                for i in range(6):
                    out = http_infer(url, "default", _example(i),
                                     deadline_s=10.0, tenant="quiet")
                    assert np.asarray(out["prob"]).shape == (10,)
                    quiet_ok += 1
                    time.sleep(0.22)  # ~4 rps, under the 5 rps rate
            finally:
                stop.set()
                for t in ts:
                    t.join(timeout=30)
            assert quiet_ok == 6, "a hot tenant starved the quiet one"
            assert hot["tenant_limit"] > 0, (
                "the flood was never shed by the tenant bucket")
            c = srv.registry.counter("sparknet_serve_shed_total",
                                     labels=("model", "reason"))
            # registry count is exact; the client-side tally may lose
            # racing += updates across the two hot threads
            assert c.value(model="default",
                           reason="tenant_limit") >= hot["tenant_limit"]
        finally:
            fe.stop()


def test_serve_cli_router_demo(tmp_path, capsys):
    """`sparknet-serve --models a=lenet,b=lenet --demo` end to end: the
    router CLI self-drives requests across both lanes and prints the
    router status JSON."""
    from sparknet_tpu.serve.app import main
    main(["--models", "a=lenet,b=lenet", "--router-workers", "2",
          "--outputs", "prob", "--max-batch", "4", "--demo", "8",
          "--workdir", str(tmp_path)])
    status = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert status["router"] is True
    assert set(status["models"]) == {"a", "b"}
    lanes = status["lanes"]
    assert sum(lane["requests_ok"] for lane in lanes.values()) == 8
    assert all(lane["requests_failed"] == 0 for lane in lanes.values())
