"""The serve network data plane (serve/http_frontend.py + serve/router.py):
keep-alive connection reuse, JSON/npz wire decode, 429-with-Retry-After
admission control, deadline shedding that answers instead of hanging,
multi-model routing over the shared worker pool, and a replica draining
mid-traffic with zero dropped responses (the chaos bar PR 3 set).

Tier-1: CPU backend, lenet shapes, ephemeral ports.
"""
import http.client
import json
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.serve import (DeadlineExpiredError, HttpFrontend,
                                InferenceServer, ModelRouter,
                                NoReplicaError, QueueFullError,
                                RouterConfig, ServeConfig, http_infer,
                                zeros_batch)
from sparknet_tpu.zoo import lenet


def _example(i: int) -> dict:
    r = np.random.default_rng(2000 + i)
    return {"data": r.standard_normal((28, 28, 1)).astype(np.float32)}


class SlowNet:
    """Facade that makes every forward take `delay_s` — the knob that
    turns a CPU lenet into an overloadable server for backpressure and
    shed tests."""

    def __init__(self, inner, delay_s: float):
        self._inner, self.delay_s = inner, delay_s

    def __getattr__(self, k):
        return getattr(self._inner, k)

    def forward(self, *a, **kw):
        time.sleep(self.delay_s)
        return self._inner.forward(*a, **kw)


@pytest.fixture(scope="module")
def net():
    return JaxNet(lenet(batch=4))


def _post(conn: http.client.HTTPConnection, path: str, body: bytes,
          ctype: str = "application/json", headers: dict = None):
    h = {"Content-Type": ctype, **(headers or {})}
    conn.request("POST", path, body=body, headers=h)
    resp = conn.getresponse()
    return resp, resp.read()


# -- wire format + keep-alive ------------------------------------------------

def test_json_roundtrip_on_one_keepalive_connection(net):
    """Five sequential requests over ONE HTTP/1.1 connection: all
    answered, outputs match a direct forward, and the server saw exactly
    one connection (keep-alive reuse asserted, not assumed)."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        fe = HttpFrontend(srv, port=0)
        try:
            host, port = fe.address
            conn = http.client.HTTPConnection(host, port, timeout=30)
            for i in range(5):
                x = _example(i)
                body = json.dumps(
                    {"inputs": {"data": x["data"].tolist()}}).encode()
                resp, data = _post(conn, "/v1/infer", body)
                assert resp.status == 200, data
                out = json.loads(data)
                assert out["model"] == "default"
                direct = net.forward(
                    {**zeros_batch(net, 1), "data": x["data"][None]},
                    blob_names=["prob"])
                np.testing.assert_allclose(
                    np.asarray(out["outputs"]["prob"]),
                    direct["prob"][0], rtol=1e-4, atol=1e-4)
            conn.close()
            assert fe.requests == 5
            assert fe.connections == 1, (
                f"{fe.connections} connections for 5 requests — "
                f"keep-alive reuse is broken")
        finally:
            fe.stop()


def test_npz_roundtrip_exact_dtype(net):
    """The raw-tensor wire format: npz in, npz out, float32 end to end,
    bitwise-equal to the in-process submit path at the same bucket."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, buckets=(4,),
                      outputs=("fc2",), metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        fe = HttpFrontend(srv, port=0)
        try:
            x = _example(0)
            inproc = srv.infer(x)
            out = http_infer(f"http://{fe.address[0]}:{fe.address[1]}",
                             "default", x, deadline_s=30.0)
            assert out["fc2"].dtype == np.float32
            np.testing.assert_array_equal(out["fc2"], inproc["fc2"])
        finally:
            fe.stop()


def test_bad_requests_answered_not_hung(net):
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        fe = HttpFrontend(srv, port=0)
        try:
            host, port = fe.address
            conn = http.client.HTTPConnection(host, port, timeout=30)
            # undecodable body -> 400
            resp, data = _post(conn, "/v1/infer", b"not json")
            assert resp.status == 400
            assert json.loads(data)["error_kind"] == "bad_request"
            # unknown model -> 404 (and the connection survived the 400)
            resp, data = _post(conn, "/v1/models/nope/infer",
                               json.dumps({"inputs": {}}).encode())
            assert resp.status == 404
            assert json.loads(data)["error_kind"] == "unknown_model"
            # not a net input -> 400 with the field named
            resp, data = _post(conn, "/v1/infer", json.dumps(
                {"inputs": {"bogus": [1.0]}}).encode())
            assert resp.status == 400
            assert "bogus" in json.loads(data)["error"]
            # GET surfaces
            conn.request("GET", "/v1/models")
            r = conn.getresponse()
            models = json.loads(r.read())["models"]
            assert "default" in models
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            assert r.status == 200
            r.read()
            assert fe.connections == 1  # all of it on one connection
        finally:
            fe.stop()


# -- admission control + shedding --------------------------------------------

def test_429_retry_after_under_full_queue(net):
    """Queue at capacity: excess requests are answered 429 with a
    Retry-After header (admission control wired to QueueFullError), the
    admitted ones still serve, nothing hangs."""
    cfg = ServeConfig(max_batch=2, max_wait_ms=1.0, max_queue=2,
                      outputs=("prob",), metrics_every_batches=0)
    slow = SlowNet(net, 0.15)
    with InferenceServer(slow, cfg) as srv:
        srv.submit(_example(0)).result(timeout=30)  # compile outside
        fe = HttpFrontend(srv, port=0)
        try:
            url = f"http://{fe.address[0]}:{fe.address[1]}"
            codes, retry_after = [], []
            lock = threading.Lock()

            def client(i):
                conn = http.client.HTTPConnection(*fe.address, timeout=30)
                body = json.dumps(
                    {"inputs": {"data": _example(i)["data"].tolist()}}
                ).encode()
                resp, data = _post(conn, "/v1/infer", body)
                with lock:
                    codes.append(resp.status)
                    if resp.status == 429:
                        retry_after.append(
                            resp.getheader("Retry-After"))
                        assert json.loads(data)["error_kind"] == \
                            "queue_full"
                conn.close()

            ts = [threading.Thread(target=client, args=(i,))
                  for i in range(12)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in ts), "a client hung"
            assert time.perf_counter() - t0 < 30
            assert codes.count(200) >= 2, codes   # admitted ones served
            assert 429 in codes, codes            # and overload was shed
            assert all(ra and int(ra) >= 1 for ra in retry_after)
        finally:
            fe.stop()


def test_deadline_shed_answers_503_not_hang(net):
    """Expired deadlines: requests whose deadline passes while queued
    behind a slow forward are answered 503 + Retry-After (error_kind
    deadline) within bounded time — never a hang, and the shed counter
    tells the story."""
    cfg = ServeConfig(max_batch=2, max_wait_ms=1.0, outputs=("prob",),
                      metrics_every_batches=0)
    slow = SlowNet(net, 0.3)
    with InferenceServer(slow, cfg) as srv:
        srv.submit(_example(0)).result(timeout=30)  # compile outside
        fe = HttpFrontend(srv, port=0)
        try:
            host, port = fe.address
            # occupy the worker, then pile deadlined requests behind it
            blocker = srv.submit(_example(1))
            time.sleep(0.05)  # blocker's batch is in its slow forward

            codes = []
            lock = threading.Lock()

            def client(i):
                conn = http.client.HTTPConnection(host, port, timeout=30)
                body = json.dumps({
                    "inputs": {"data": _example(i)["data"].tolist()},
                    "deadline_ms": 100.0}).encode()
                resp, data = _post(conn, "/v1/infer", body)
                with lock:
                    codes.append((resp.status,
                                  resp.getheader("Retry-After"),
                                  json.loads(data).get("error_kind")))
                conn.close()

            ts = [threading.Thread(target=client, args=(i,))
                  for i in range(2, 8)]
            t0 = time.perf_counter()
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=30)
            dt = time.perf_counter() - t0
            assert not any(t.is_alive() for t in ts), "a client hung"
            assert dt < 10, f"shed took {dt:.1f}s"
            blocker.result(timeout=30)
            shed = [c for c in codes if c[0] == 503]
            assert shed, codes  # the 100 ms deadlines could not all make it
            for status, ra, kind in shed:
                assert kind == "deadline" and ra is not None
            assert srv.batcher.shed >= len(shed)
        finally:
            fe.stop()


# -- multi-model routing ------------------------------------------------------

def test_router_serves_two_models_with_per_model_metrics(net):
    """Two models over one shared pool: requests route to the right
    net (weights differ between lanes), per-model buckets hold, and the
    shared registry carries model-labeled families for both."""
    r = ModelRouter(RouterConfig(workers=2))
    net_b = JaxNet(lenet(batch=4))
    # make b's weights visibly different from a's
    net_b.params = {ln: {pn: w * 0.5 for pn, w in lp.items()}
                    for ln, lp in net_b.params.items()}
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("fc2",),
                      metrics_every_batches=0)
    r.add_model("a", net, cfg=cfg)
    r.add_model("b", net_b, cfg=cfg)
    with r:
        fe = HttpFrontend(r, port=0)
        try:
            url = f"http://{fe.address[0]}:{fe.address[1]}"
            x = _example(0)
            out_a = http_infer(url, "a", x, deadline_s=30.0)
            out_b = http_infer(url, "b", x, deadline_s=30.0)
            da = net.forward({**zeros_batch(net, 1),
                              "data": x["data"][None]},
                             blob_names=["fc2"])
            db = net_b.forward({**zeros_batch(net_b, 1),
                                "data": x["data"][None]},
                               blob_names=["fc2"])
            np.testing.assert_allclose(out_a["fc2"], da["fc2"][0],
                                       rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(out_b["fc2"], db["fc2"][0],
                                       rtol=1e-4, atol=1e-4)
            assert not np.allclose(out_a["fc2"], out_b["fc2"])
            # /v1/infer is ambiguous with two models
            conn = http.client.HTTPConnection(*fe.address, timeout=30)
            resp, data = _post(conn, "/v1/infer", json.dumps(
                {"inputs": {"data": x["data"].tolist()}}).encode())
            assert resp.status == 404
            conn.close()
            text = r.registry.render_prometheus()
            assert ('sparknet_serve_requests_total{model="a",'
                    'outcome="ok"}') in text
            assert ('sparknet_serve_requests_total{model="b",'
                    'outcome="ok"}') in text
            assert 'sparknet_serve_routed_total{model="a",' in text
        finally:
            fe.stop()


@pytest.mark.chaos
def test_replica_drains_mid_traffic_zero_dropped(net):
    """The routing chaos bar: model m has a local replica (router A) and
    a remote replica (router B behind its HTTP frontend). Mid-traffic
    the local replica DRAINS: every in-flight and queued request still
    answers, new traffic routes to the remote replica, zero dropped or
    corrupted responses."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    rb = ModelRouter(RouterConfig(workers=1))
    rb.add_model("m", JaxNet(lenet(batch=4)), cfg=cfg)
    ra = ModelRouter(RouterConfig(workers=1))
    ra.add_model("m", net, cfg=cfg)
    with rb:
        fe_b = HttpFrontend(rb, port=0)
        with ra:
            ra.add_remote_replica(
                "m", f"http://{fe_b.address[0]}:{fe_b.address[1]}")
            answered, bad = [], []
            stop = threading.Event()

            def client(c):
                i = 0
                while not stop.is_set():
                    try:
                        out = ra.infer("m", _example(c * 10000 + i),
                                       timeout=30.0)
                        p = np.asarray(out["prob"])
                        if p.shape != (10,) or not np.isfinite(p).all():
                            bad.append((c, i, p))
                        answered.append((c, i))
                    except Exception as e:
                        bad.append((c, i, e))
                    i += 1

            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(4)]
            for t in threads:
                t.start()
            try:
                time.sleep(0.4)  # traffic flowing through both replicas
                before = len(answered)
                ra.drain("m", "local:m")  # in-flight must still answer
                time.sleep(0.6)  # all new traffic rides the remote
                assert len(answered) > before + 4, \
                    "traffic stalled after drain"
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=30)
            assert not any(t.is_alive() for t in threads)
            assert not bad, bad[:3]
            assert len(answered) > 20
            # the drain actually shifted routing to the remote replica
            routed = ra.registry.counter(
                "sparknet_serve_routed_total",
                labels=("model", "replica"))
            remote_name = ra.replicas["m"][1].name
            assert routed.value(model="m", replica=remote_name) > 0
        fe_b.stop()


def test_busy_router_still_runs_idle_lane_duties(net, tmp_path):
    """Sustained traffic to one lane must not starve the others'
    periodic duties (regression: the pool only ran duty_tick on idle
    sweeps): with a SINGLE pool worker hammered on model a, model b's
    checkpoint hot-reload poll still runs and lands a swap, the router
    heartbeat keeps beating, and /healthz stays ok throughout."""
    from sparknet_tpu.utils import checkpoint as ckpt
    from sparknet_tpu.utils.heartbeat import read_heartbeat

    net_b = JaxNet(lenet(batch=4))
    ckdir = tmp_path / "ck"
    flat = {f"params/{ln}/{pn}": np.asarray(w)[None] * 0.9
            for ln, lp in net_b.params.items() for pn, w in lp.items()}
    ckpt.save(str(ckdir), flat, step=1)
    hb_path = str(tmp_path / "hb.json")
    r = ModelRouter(RouterConfig(workers=1, heartbeat_path=hb_path,
                                 heartbeat_every_s=0.05))
    cfg_a = ServeConfig(max_batch=4, max_wait_ms=1.0, outputs=("prob",),
                        metrics_every_batches=0)
    cfg_b = ServeConfig(max_batch=4, max_wait_ms=1.0, outputs=("prob",),
                        checkpoint_dir=str(ckdir), poll_interval_s=0.05,
                        metrics_every_batches=0)
    r.add_model("a", net, cfg=cfg_a)
    r.add_model("b", net_b, cfg=cfg_b)
    with r:
        r.infer("a", _example(0))  # compile before the hammer
        assert r.lanes["b"].manager.step == 1
        stop = threading.Event()
        unhealthy = []

        def hammer():
            i = 0
            while not stop.is_set():
                r.infer("a", _example(i), timeout=30.0)
                i += 1

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            time.sleep(0.3)  # lane a saturates the single pool worker
            ckpt.save(str(ckdir), flat, step=2)  # b must still poll
            deadline = time.monotonic() + 10
            while r.lanes["b"].manager.step != 2 and \
                    time.monotonic() < deadline:
                if not r.healthy():
                    unhealthy.append(time.monotonic())
                time.sleep(0.02)
        finally:
            stop.set()
            t.join(timeout=30)
        assert r.lanes["b"].manager.step == 2, (
            "idle lane's hot-reload poll starved under sustained "
            "traffic to the other lane")
        assert not unhealthy, "router read unhealthy while serving fine"
        hb = read_heartbeat(hb_path)
        assert hb is not None and hb["age_s"] < 5.0, (
            "router heartbeat starved under sustained traffic")


def test_router_no_replica_is_503_shed(net):
    """Every replica draining -> NoReplicaError locally, 503 +
    Retry-After over HTTP (load shedding, never a hang)."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    r = ModelRouter(RouterConfig(workers=1))
    r.add_model("m", net, cfg=cfg)
    with r:
        fe = HttpFrontend(r, port=0)
        try:
            r.drain("m", "local:m")
            with pytest.raises(NoReplicaError):
                r.submit("m", _example(0))
            conn = http.client.HTTPConnection(*fe.address, timeout=30)
            resp, data = _post(conn, "/v1/models/m/infer", json.dumps(
                {"inputs": {"data": _example(0)["data"].tolist()}}
            ).encode())
            assert resp.status == 503
            assert resp.getheader("Retry-After") is not None
            assert json.loads(data)["error_kind"] == "no_replica"
            conn.close()
        finally:
            fe.stop()


def test_serve_cli_router_demo(tmp_path, capsys):
    """`sparknet-serve --models a=lenet,b=lenet --demo` end to end: the
    router CLI self-drives requests across both lanes and prints the
    router status JSON."""
    from sparknet_tpu.serve.app import main
    main(["--models", "a=lenet,b=lenet", "--router-workers", "2",
          "--outputs", "prob", "--max-batch", "4", "--demo", "8",
          "--workdir", str(tmp_path)])
    status = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert status["router"] is True
    assert set(status["models"]) == {"a", "b"}
    lanes = status["lanes"]
    assert sum(lane["requests_ok"] for lane in lanes.values()) == 8
    assert all(lane["requests_failed"] == 0 for lane in lanes.values())
