"""Caffe-semantics op tests, cross-checked against torch (CPU) oracles.

torch's ceil_mode pooling, grouped conv2d, and local_response_norm implement
the same semantics as native Caffe (which the reference called through
JavaCPP, `libs/CaffeNet.scala:91`), so they serve as an independent oracle.
"""
import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax
import jax.numpy as jnp

from sparknet_tpu.ops.lrn import lrn
from sparknet_tpu.ops.pooling import caffe_pool_output_size, pool2d


def nchw(x_nhwc):
    return np.transpose(x_nhwc, (0, 3, 1, 2))


def nhwc(x_nchw):
    return np.transpose(x_nchw, (0, 2, 3, 1))


@pytest.mark.parametrize("h,k,s,p", [
    (32, 3, 2, 0),   # cifar10 pool1-3: 32->16 via ceil
    (16, 3, 2, 0),
    (55, 3, 2, 0),   # alexnet pool1: 55->27
    (13, 3, 2, 0),   # alexnet pool5: 13->6
    (10, 2, 2, 0),
    (7, 3, 2, 1),
])
def test_pool_output_size_matches_torch(h, k, s, p):
    x = torch.zeros(1, 1, h, h)
    out = F.max_pool2d(x, k, stride=s, padding=p, ceil_mode=True)
    assert caffe_pool_output_size(h, k, s, p) == out.shape[-1]


@pytest.mark.parametrize("mode", ["MAX", "AVE"])
@pytest.mark.parametrize("h,k,s,p", [(32, 3, 2, 0), (13, 3, 2, 0), (8, 3, 2, 1)])
def test_pool2d_matches_torch(rng, mode, h, k, s, p):
    x = rng.standard_normal((2, h, h, 5), dtype=np.float32)
    got = np.asarray(pool2d(jnp.asarray(x), mode, k, s, p))
    xt = torch.from_numpy(nchw(x))
    if mode == "MAX":
        want = F.max_pool2d(xt, k, stride=s, padding=p, ceil_mode=True)
    else:
        want = F.avg_pool2d(xt, k, stride=s, padding=p, ceil_mode=True,
                            count_include_pad=True)
    np.testing.assert_allclose(got, nhwc(want.numpy()), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("impl", ["fused", "window"])
def test_lrn_matches_torch(rng, impl):
    x = rng.standard_normal((2, 7, 7, 16), dtype=np.float32)
    got = np.asarray(lrn(jnp.asarray(x), 5, alpha=1e-4, beta=0.75, k=1.0,
                         impl=impl))
    want = F.local_response_norm(torch.from_numpy(nchw(x)), size=5,
                                 alpha=1e-4, beta=0.75, k=1.0)
    np.testing.assert_allclose(got, nhwc(want.numpy()), rtol=1e-5, atol=1e-6)


def test_lrn_fused_gradient_matches_autodiff_of_window(rng):
    """The fused impl's closed-form Caffe backward (recomputed normalizer)
    vs autodiff of the reduce_window reference — must agree."""
    x = rng.standard_normal((3, 4, 4, 32), dtype=np.float32)
    dy = rng.standard_normal((3, 4, 4, 32), dtype=np.float32)

    def f(impl):
        return lambda x_: jnp.vdot(
            lrn(x_, 5, alpha=2e-4, beta=0.75, k=1.0, impl=impl),
            jnp.asarray(dy))

    g_want = np.asarray(jax.grad(f("window"))(jnp.asarray(x)))
    g_got = np.asarray(jax.grad(f("fused"))(jnp.asarray(x)))
    np.testing.assert_allclose(g_got, g_want, rtol=1e-4, atol=1e-6)


def test_grouped_conv_matches_torch(rng):
    # AlexNet conv2 shape: group=2 (models/bvlc_reference_caffenet)
    x = rng.standard_normal((2, 9, 9, 8), dtype=np.float32)
    w_hwio = rng.standard_normal((3, 3, 4, 6), dtype=np.float32)  # group=2
    b = rng.standard_normal((6,), dtype=np.float32)
    y = jax.lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w_hwio), (1, 1), ((1, 1), (1, 1)),
        dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=2,
        precision=jax.lax.Precision.HIGHEST)
    got = np.asarray(y + b)
    w_oihw = np.transpose(w_hwio, (3, 2, 0, 1))
    want = F.conv2d(torch.from_numpy(nchw(x)), torch.from_numpy(w_oihw),
                    torch.from_numpy(b), stride=1, padding=1, groups=2)
    np.testing.assert_allclose(got, nhwc(want.numpy()), rtol=1e-4, atol=1e-4)


# NOTE: an argmax "k*k shift" maxpool formulation (fwd = max tree of
# strided views, bwd = argmax-routed scatter-adds, replacing XLA's
# select-and-scatter) was implemented and benchmarked at ~0.64x the
# reduce_window path's end-to-end throughput on v5e — the strided slices
# and scatters lower worse than select-and-scatter. Kept: the tie-routing
# semantics test below, which the reduce_window gradient must also satisfy.


def test_maxpool_tie_gradient_goes_to_first_max():
    """Caffe MaxPoolBackward routes the gradient to the FIRST max in
    row-major window order when values tie (select-and-scatter picks the
    same element)."""
    import jax
    import jax.numpy as jnp
    from sparknet_tpu.ops.pooling import pool2d
    x = np.zeros((1, 2, 2, 1), np.float32)  # one 2x2 window, all tied
    g = jax.grad(lambda v: pool2d(v, "MAX", 2, 2, 0).sum())(jnp.asarray(x))
    np.testing.assert_array_equal(
        np.asarray(g)[0, :, :, 0], [[1.0, 0.0], [0.0, 0.0]])


def test_grouped_conv_split_impl_matches_native(rng):
    """The CONV_GROUP_IMPL='split' A/B lever (PERF.md r4) is the same math
    as XLA's native feature_group_count: outputs and gradients must agree."""
    import jax
    import sparknet_tpu.model.layers as L
    from sparknet_tpu.model.spec import (ConvolutionParam, Filler,
                                         InputSpec, LayerSpec, NetSpec)
    from sparknet_tpu import CompiledNet

    spec = NetSpec(
        name="g", inputs=(InputSpec("data", (2, 6, 8, 8)),),
        layers=(LayerSpec(
            name="conv", type="Convolution", bottoms=("data",),
            tops=("conv",),
            conv=ConvolutionParam(
                num_output=8, kernel_size=3, pad=1, group=2,
                weight_filler=Filler(type="gaussian", std=0.1))),))
    net = CompiledNet.compile(spec)
    params = net.init_params(jax.random.PRNGKey(0))
    batch = {"data": rng.standard_normal((2, 8, 8, 6)).astype(np.float32)}

    def out_sum(p):
        return jnp.sum(net.apply(p, batch, train=False)["conv"] ** 2)

    try:
        y_nat = net.apply(params, batch, train=False)["conv"]
        g_nat = jax.grad(out_sum)(params)
        L.CONV_GROUP_IMPL = "split"
        y_spl = net.apply(params, batch, train=False)["conv"]
        g_spl = jax.grad(out_sum)(params)
    finally:
        L.CONV_GROUP_IMPL = "native"
    np.testing.assert_allclose(np.asarray(y_spl), np.asarray(y_nat),
                               rtol=1e-5, atol=1e-6)
    for pname in g_nat["conv"]:
        np.testing.assert_allclose(
            np.asarray(g_spl["conv"][pname]),
            np.asarray(g_nat["conv"][pname]), rtol=1e-5, atol=1e-6,
            err_msg=pname)
