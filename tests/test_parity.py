"""Accuracy-parity tests — the EXACT reference recipes (see PARITY.md),
gated on the datasets being present. This offline environment skips them
all; anyone with data runs

    scripts/get_datasets.sh all data
    python -m pytest tests/test_parity.py -m parity -v

and gets the reference's own validation: cifar10_quick to the Caffe-
documented accuracy band (reference models/cifar10/cifar10_quick_solver
.prototxt:12-20, apps/CifarApp.scala:20,127), MNIST on the serialized-
graph backend (apps/MnistApp.scala:18,118), Adult, and an ImageNet
preprocessing/label-sanity smoke run. Recipes run single-replica
(n_devices=1) so the band reproduces the serial Caffe baseline — the
tau-averaged multi-replica dynamics are pinned separately by the oracle
tests in test_parallel.py."""
import os

import numpy as np
import pytest

DATA = os.environ.get("SPARKNET_TPU_DATA", "data")

pytestmark = pytest.mark.parity


def _missing(*paths):
    return not all(os.path.exists(os.path.join(DATA, p)) for p in paths)


def _final_accuracy(cfg, spec, state, test_ds):
    """Distributed-eval the final state exactly as the loop does."""
    from sparknet_tpu import CompiledNet
    from sparknet_tpu.apps.train_loop import _evaluate, _to_device_layout
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh

    net = CompiledNet.compile(spec)
    trainer = ParallelTrainer(net, cfg.solver, make_mesh(cfg.n_devices),
                              tau=cfg.tau)
    ds = _to_device_layout(test_ds, net)
    return _evaluate(trainer, state, ds, cfg.eval_batch, trainer.n_devices)


@pytest.mark.skipif(
    _missing("cifar10/data_batch_1.bin", "cifar10/test_batch.bin"),
    reason="data/cifar10 absent (scripts/get_datasets.sh cifar10)")
def test_cifar10_quick_recipe(tmp_path):
    """The canonical recipe: lr 0.001 fixed / momentum 0.9 / wd 0.004 /
    batch 100 / tau 10 / 400 rounds = 4000 solver iterations (~8 epochs).
    Caffe's documented result for this phase is ~71-75% test accuracy;
    assert the 0.70 floor (PARITY.md section 1)."""
    from sparknet_tpu.apps import cifar_app
    from sparknet_tpu.apps.train_loop import resolve_spec, train
    from sparknet_tpu.utils.logger import Logger

    cfg = cifar_app.default_config()
    cfg.data_dir = os.path.join(DATA, "cifar10")
    cfg.n_devices, cfg.max_rounds = 1, 400
    cfg.eval_every = 50                       # progress visibility only
    cfg.workdir = str(tmp_path)
    train_ds, test_ds = cifar_app.build_datasets(cfg)
    spec = resolve_spec(cfg, data=(cfg.local_batch, 3, 32, 32),
                        label=(cfg.local_batch, 1))
    log_path = str(tmp_path / "cifar_parity.txt")
    state = train(cfg, spec, train_ds, test_ds,
                  logger=Logger(log_path, echo=True))
    acc = _final_accuracy(cfg, spec, state, test_ds)
    assert acc >= 0.70, (
        f"cifar10_quick @4000 iters: acc={acc:.4f}, expected >=0.70 "
        f"(reference band ~0.71-0.75); see {log_path}")


@pytest.mark.skipif(
    _missing("mnist/train-images-idx3-ubyte", "mnist/t10k-images-idx3-ubyte"),
    reason="data/mnist absent (scripts/get_datasets.sh mnist)")
def test_mnist_graph_recipe(tmp_path):
    """MnistApp pairing: the serialized-graph backend (in-graph Momentum +
    exp-decay lr, batch 64, tau 10) for 150 rounds = 1500 optimizer steps.
    LeNet-class band is >=98%; assert the 0.97 floor (PARITY.md section 2)."""
    from sparknet_tpu.apps import graph_mnist_app
    from sparknet_tpu.backend import GraphNet, build_mnist_graph
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.data.mnist import MnistLoader
    from sparknet_tpu.parallel import GraphTrainer, make_mesh
    from sparknet_tpu.apps.graph_common import train_graph
    from sparknet_tpu.apps.train_loop import _evaluate
    from sparknet_tpu.utils.logger import Logger

    cfg = graph_mnist_app.default_config()
    cfg.data_dir = os.path.join(DATA, "mnist")
    cfg.n_devices, cfg.max_rounds = 1, 150
    cfg.eval_every = 25
    cfg.workdir = str(tmp_path)
    loader = MnistLoader(cfg.data_dir)
    train_ds = ArrayDataset(graph_mnist_app._nhwc(loader.train_batch_dict()))
    test_ds = ArrayDataset(graph_mnist_app._nhwc(loader.test_batch_dict()))
    graph = build_mnist_graph(batch=cfg.local_batch,
                              train_size=len(train_ds))
    state = train_graph(cfg, graph, train_ds, test_ds,
                        logger=Logger(str(tmp_path / "mnist_parity.txt"),
                                      echo=True),
                        expect_data_shape=(28, 28, 1))
    trainer = GraphTrainer(GraphNet(graph, seed=cfg.seed),
                           make_mesh(cfg.n_devices), tau=cfg.tau)
    acc = _evaluate(trainer, state, test_ds, cfg.eval_batch, 1)
    assert acc >= 0.97, (
        f"mnist graph recipe @1500 steps: acc={acc:.4f}, expected >=0.97")


@pytest.mark.skipif(_missing("adult/adult.data"),
                    reason="data/adult absent "
                    "(scripts/get_datasets.sh adult)")
def test_adult_recipe(tmp_path):
    """Adult MLP: 200 rounds x tau 5 at batch 64; assert >=0.80 held-out
    accuracy (logistic-regression-class baseline ~0.85; PARITY.md sec 4)."""
    from sparknet_tpu.apps.adult_app import adult_net
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data.adult import AdultLoader
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger

    loader = AdultLoader(os.path.join(DATA, "adult", "adult.data"))
    full = loader.batch_dict()
    n = len(loader.labels)
    split = int(n * 0.8)
    train_ds = ArrayDataset({k: v[:split] for k, v in full.items()})
    test_ds = ArrayDataset({k: v[split:] for k, v in full.items()})
    cfg = RunConfig(
        model="adult",
        solver=SolverConfig(base_lr=0.01, momentum=0.9, lr_policy="fixed"),
        n_devices=1, tau=5, local_batch=64, eval_every=50, eval_batch=1024,
        max_rounds=200, workdir=str(tmp_path))
    spec = adult_net(cfg.local_batch, loader.features.shape[1])
    state = train(cfg, spec, train_ds, test_ds,
                  logger=Logger(str(tmp_path / "adult_parity.txt"),
                                echo=True))
    acc = _final_accuracy(cfg, spec, state, test_ds)
    assert acc >= 0.80, f"adult recipe: acc={acc:.4f}, expected >=0.80"


@pytest.mark.skipif(_missing("imagenet/train.txt"),
                    reason="data/imagenet absent "
                    "(scripts/shard_imagenet.py ingest)")
def test_imagenet_smoke(tmp_path):
    """Not the 450k-iteration headline run (PARITY.md section 3 documents
    that recipe) — a 50-round smoke at the real recipe's lr/crop/mean
    settings on the real shards: loss must drop clearly below the ln(1000)
    = 6.908 random floor, catching preprocessing or label skew in minutes
    instead of days."""
    import re

    from sparknet_tpu import zoo
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data import imagenet
    from sparknet_tpu.data.preprocess import ImagePreprocessor
    from sparknet_tpu.data.streaming import StreamingRoundSource
    from sparknet_tpu.schema import Field, Schema
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger

    root = os.path.join(DATA, "imagenet")
    shards = [s for s in imagenet.list_shards(root)
              if os.path.basename(s).startswith("train.")][:2]
    loader = imagenet.ShardedTarLoader(
        shards, imagenet.load_label_map(os.path.join(root, "train.txt")))
    crop, local_b, tau = 227, 32, 5
    cfg = RunConfig(
        model="caffenet",
        solver=SolverConfig(base_lr=0.01, momentum=0.9, weight_decay=5e-4,
                            lr_policy="step", gamma=0.1, stepsize=100000),
        n_devices=1, tau=tau, local_batch=local_b, eval_every=0,
        max_rounds=50, crop=crop, workdir=str(tmp_path))
    src = StreamingRoundSource(loader, 1, local_b, tau)
    schema = Schema(Field("data", "float32", (crop, crop, 3)),
                    Field("label", "int32", (1,)))
    pp = ImagePreprocessor(schema, mean_image=None, crop=crop, seed=0)
    log_path = str(tmp_path / "imagenet_smoke.txt")
    train(cfg, zoo.caffenet(batch=local_b, crop=crop), src,
          logger=Logger(log_path, echo=True), batch_transform=pp)
    losses = [float(m.group(1)) for m in re.finditer(
        r"round loss: ([0-9.]+)", open(log_path).read())]
    assert losses, "no round losses logged"
    tail = np.mean(losses[-5:])
    assert tail < 6.5, (
        f"imagenet smoke: tail loss {tail:.3f} never left the 6.908 "
        f"random floor — preprocessing/label pipeline suspect")


# -- Offline proxies (synthetic data; always run) ----------------------------

def test_numpy_oracle_recipe_trajectory(tmp_path):
    """VERDICT r3 item 4b: ~50 iterations of the cifar10_quick RECIPE
    (lr 0.001 fixed, momentum 0.9, wd 0.004, batch 100, lr_mult 1/2) through
    an INDEPENDENT numpy reimplementation of the net + Caffe SGD
    (tests/numpy_oracle.py: hand-written im2col/col2im, window-argmax max
    pool routing, clipped AVE divisors) must match the framework's jitted
    step end to end — extending the per-step unit oracles to recipe
    hyperparameters. The PER-STEP pins are the real oracle: the
    single-step grad comparison at <=1e-4 max-rel pins every layer's
    backward, and the first-10-iter losses pin the step at <=1e-4 rel
    (measured 3.1e-6). Beyond that horizon the trajectory is a sanity
    ENVELOPE, not a precision pin, because it is CHAOTIC through
    max-pool near-tie routing (a window whose top-2 conv outputs sit
    within 1 ulp routes its gradient differently under any rounding
    difference; conv1, under pool1, accumulates it — a property of f32
    trajectories, not of either implementation), and the per-iter LOSS
    inherits exactly that divergence once the params carry it.

    Re-measured r7 (this jax/XLA's conv tilings shifted the routing draw
    from the r3 measurement of 0.13%/8% params): framework-vs-oracle
    relative L2 per tensor is 2.1% at iter 10 and 11.2% at iter 50
    (worst tensor conv1/w both times), while the SAME framework
    implementation nudged by ONE ULP on a single conv1 weight
    self-deviates 2.6% / 11.9% at the same horizons — the oracle
    disagreement sits BELOW the trajectory's own one-ulp sensitivity at
    every horizon, so any tighter band would pin compiler tiling luck,
    not correctness. Per-iter loss deviation follows the same curve:
    <=0.14% through iter 39, max 6.2% at iter 49. Bands asserted ~2-4x
    above the measurements (params 0.08 @ iter 10 / 0.25 @ 50; losses
    1e-4 for iters 0-9 / 0.20 after), well under what a real bug (wrong
    routing rule, wrong divisor, wrong update) produces.

    The same chaos makes the 50-iter loss LEVEL a draw property, not a
    parity property (observed across CPU runs: one draw descends 2.30 ->
    ~1.5, another drifts to ~2.7 — with the oracle TRACKING both inside
    the bands): whether this lr/task combination descends by iter 50 is
    the recipe study's claim (PARITY_SYNTH_r04.json runs the full 4000
    iterations), so the closing assert here pins only that the two
    implementations AGREE about the trajectory they shared — the
    per-iter band over every iter plus a real parameter displacement
    from init (training happened; it was not a frozen no-op on both
    sides)."""
    import jax
    import numpy_oracle as orc
    from sparknet_tpu import CompiledNet
    from sparknet_tpu.data import synth
    from sparknet_tpu.solver import SgdSolver, SolverConfig
    from sparknet_tpu.zoo import cifar10_quick

    B, ITERS = 100, 50
    net = CompiledNet.compile(cifar10_quick(batch=B))
    cfg = SolverConfig(base_lr=0.001, momentum=0.9, weight_decay=0.004,
                       lr_policy="fixed")
    solver = SgdSolver(net, cfg)
    params = net.init_params(jax.random.PRNGKey(0))
    np_params = {l: {p: np.asarray(v, np.float32) for p, v in lp.items()}
                 for l, lp in params.items()}
    mean = synth.mean_image(seed=0)
    imgs, labels = synth.synthetic_cifar(B * ITERS, seed=0)
    nhwc = np.ascontiguousarray((imgs - mean).transpose(0, 2, 3, 1))

    # single-step gradient agreement (pins every layer's backward)
    batch0 = {"data": nhwc[:B], "label": labels[:B, None]}
    (fw_loss, _), fw_grads = jax.value_and_grad(
        lambda p: net.loss_fn("loss")(p, batch0, jax.random.PRNGKey(0)),
        has_aux=True)(params)
    np_loss, np_grads = orc.forward_backward(np_params, nhwc[:B], labels[:B])
    assert abs(float(fw_loss) - np_loss) / np_loss < 1e-5
    for l in np_grads:
        for p in np_grads[l]:
            a, b = np.asarray(fw_grads[l][p]), np_grads[l][p]
            rel = np.abs(a - b).max() / max(np.abs(b).max(), 1e-12)
            assert rel < 1e-4, (l, p, rel)

    # 50-iteration recipe trajectory (params checked at two horizons)
    def param_dev():
        worst = 0.0
        for l in np_params:
            for p in np_params[l]:
                a, b = np.asarray(params[l][p]), np_params[l][p]
                worst = max(worst, np.linalg.norm(a - b) /
                            max(np.linalg.norm(b), 1e-12))
        return worst

    state = solver.init_state(params)
    fw_losses = []
    velocity = {l: {p: np.zeros_like(v) for p, v in lp.items()}
                for l, lp in np_params.items()}

    # Chaotic-horizon envelope (r8, the PR 8 root cause made
    # actionable): beyond iter ~10 the trajectory is chaotic through
    # max-pool near-tie routing, and the measured bands are a property
    # of THIS build's XLA conv-tiling draw — a different jax/XLA can
    # legitimately land outside them while both implementations stay
    # correct (the oracle deviation sits BELOW the trajectory's own
    # one-ulp self-sensitivity at every horizon). Violations are
    # therefore COLLECTED and turned into xfail-with-reason at the END
    # — after every hard check (single-step grads, first-10-iter loss
    # pins, and the training-happened displacement below) has run, so a
    # bad draw can never mask a frozen run or a real oracle failure.
    chaos_violations: list = []

    def chaos_band(ok: bool, detail) -> None:
        if not ok:
            chaos_violations.append(detail)

    for i in range(ITERS):
        batch = {"data": nhwc[i * B:(i + 1) * B],
                 "label": labels[i * B:(i + 1) * B, None]}
        params, state, loss = solver.step(params, state, batch)
        fw_losses.append(float(loss))
        nl, grads = orc.forward_backward(np_params, nhwc[i * B:(i + 1) * B],
                                         labels[i * B:(i + 1) * B])
        orc.sgd_update(np_params, velocity, grads, cfg.base_lr,
                       cfg.momentum, cfg.weight_decay)
        # horizon-scaled loss band (docstring): a precision pin while the
        # trajectories are still coherent (hard), a chaos envelope after
        rel = abs(fw_losses[-1] - nl) / max(abs(nl), 1e-9)
        if i < 10:
            assert rel < 1e-4, (i, fw_losses[-1], nl)
        else:
            chaos_band(rel < 0.20, (i, fw_losses[-1], nl))
        if i + 1 == 10:
            chaos_band(param_dev() < 0.08, ("param_dev@10", param_dev()))
    chaos_band(param_dev() < 0.25, ("param_dev@50", param_dev()))
    # training happened (both sides — the oracle moved in lockstep above):
    # params displaced materially from init, not a frozen no-op. The
    # 50-iter loss LEVEL is a chaos-draw property (docstring) — the full
    # recipe's descent claim lives in the 4000-iter PARITY_SYNTH study.
    init = net.init_params(jax.random.PRNGKey(0))
    # weight tensors only: biases init to ZERO, so a relative-to-init
    # displacement over them is a divide-by-floor that any microscopic
    # twitch satisfies — the weights are where "frozen run" would show
    disp = max(
        np.linalg.norm(np.asarray(params[l][p]) - np.asarray(init[l][p]))
        / np.linalg.norm(np.asarray(init[l][p]))
        for l in np_params for p in np_params[l]
        if np.linalg.norm(np.asarray(init[l][p])) > 1e-6)
    assert disp > 0.05, disp
    if chaos_violations:
        pytest.xfail(
            f"chaotic-horizon envelope exceeded ({chaos_violations[:3]}; "
            f"{len(chaos_violations)} total): XLA conv-tiling draw "
            f"shifted the max-pool near-tie routing (PR 8 root cause) — "
            f"divergence below the trajectory's one-ulp "
            f"self-sensitivity, not an oracle failure (every hard pin "
            f"above passed)")


def test_parity_synth_round_matches_trainer():
    """The vmapped round in scripts/parity_synth.py claims to be
    ParallelTrainer._round_impl's math (tau SGD steps per worker, params
    worker-averaged, momentum local) with vmap in place of shard_map so the
    4000-iter study fits one chip. Pin that: one round on identical data
    must produce the same averaged params and loss as the real trainer on
    the CPU mesh (tolerance: different XLA programs, f32)."""
    import os
    import sys
    import jax
    import jax.numpy as jnp
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import parity_synth
    from sparknet_tpu import CompiledNet
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh
    from sparknet_tpu.solver import SgdSolver, SolverConfig
    from sparknet_tpu.zoo import cifar10_quick

    W, tau, b = 4, 3, 2
    net = CompiledNet.compile(cifar10_quick(batch=b))
    cfg = SolverConfig(base_lr=0.001, momentum=0.9, weight_decay=0.004,
                       lr_policy="fixed")
    solver = SgdSolver(net, cfg)
    r = np.random.default_rng(0)
    corpus = jnp.asarray(r.standard_normal((64, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(r.integers(0, 10, (64, 1)), jnp.int32)
    idx = jnp.asarray(r.integers(0, 64, (W, tau, b)), jnp.int32)

    params0 = net.init_params(jax.random.PRNGKey(0))
    stacked = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (W,) + x.shape), params0)
    momentum = jax.tree.map(jnp.zeros_like, stacked)
    round_fn = parity_synth.make_round_fn(net, solver, W, tau, b)
    ps_params, _, ps_it, ps_loss = round_fn(
        stacked, momentum, jnp.zeros((), jnp.int32), idx, corpus, labels)
    assert int(ps_it) == tau

    # the real trainer on the same per-worker batches. ParallelTrainer's
    # loss_fn threads an rng (dropout); cifar10_quick has none, so the rng
    # difference is irrelevant.
    trainer = ParallelTrainer(net, cfg, make_mesh(W), tau=tau)
    state = trainer.state_from_params(params0)
    # batches [tau, W*b, ...]: worker w's rows at batch columns w*b:(w+1)*b
    data = np.zeros((tau, W * b, 32, 32, 3), np.float32)
    lab = np.zeros((tau, W * b, 1), np.int32)
    idx_np = np.asarray(idx)
    for w in range(W):
        for t in range(tau):
            data[t, w * b:(w + 1) * b] = np.asarray(corpus)[idx_np[w, t]]
            lab[t, w * b:(w + 1) * b] = np.asarray(labels)[idx_np[w, t]]
    tr_state, tr_loss = trainer.train_round(
        state, {"data": data, "label": lab}, jax.random.PRNGKey(5))

    assert float(ps_loss) == pytest.approx(float(tr_loss), rel=1e-5)
    tr_params = trainer.averaged_params(tr_state)
    ps_avg = jax.tree.map(lambda x: x[0], ps_params)
    for l in tr_params:
        for p in tr_params[l]:
            np.testing.assert_allclose(
                np.asarray(ps_avg[l][p]), np.asarray(tr_params[l][p]),
                rtol=2e-4, atol=2e-6, err_msg=f"{l}/{p}")


def test_parity_caffenet_round_matches_trainer():
    """The scanned-worker round in scripts/parity_caffenet.py (r5: device
    uint8 corpus -> mean subtract -> random crop -> tau SGD steps with
    dropout rng -> worker param mean) claims ParallelTrainer._round_impl's
    math with the mesh axis scanned and the reference's ImageNet
    preprocessing fused on device. Pin both claims: one round on identical
    data (host-side preprocessing replicating the device math) and the
    SAME per-worker dropout keys must reproduce the trainer's averaged
    params and loss on the CPU mesh."""
    import os
    import sys
    import jax
    import jax.numpy as jnp
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import parity_caffenet
    from sparknet_tpu import CompiledNet
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh
    from sparknet_tpu.parallel.mesh import DATA_AXIS, place_global_state
    from sparknet_tpu.solver import SgdSolver
    from sparknet_tpu.zoo import caffenet
    from jax.sharding import PartitionSpec as P

    W, tau, b, size, crop = 2, 2, 2, 80, 67
    net = CompiledNet.compile(caffenet(batch=b, crop=crop, n_classes=16))
    cfg = parity_caffenet.solver_config()
    solver = SgdSolver(net, cfg)
    r = np.random.default_rng(0)
    corpus = r.integers(0, 256, (32, size, size, 3)).astype(np.uint8)
    labels = r.integers(0, 16, 32).astype(np.int32)
    mean_hwc = r.uniform(100, 156, (size, size, 3)).astype(np.float32)
    idx = r.integers(0, 32, (W, tau, b)).astype(np.int32)
    offs = r.integers(0, size - crop + 1, (W, tau, b, 2)).astype(np.int32)
    keys = jax.random.split(jax.random.PRNGKey(3), W)

    params0 = net.init_params(jax.random.PRNGKey(0))
    stacked = jax.tree.map(
        lambda x: jnp.asarray(jnp.broadcast_to(x[None], (W,) + x.shape)),
        params0)
    momentum = jax.tree.map(jnp.zeros_like, stacked)
    round_fn = parity_caffenet.make_round_fn(net, solver, tau, crop=crop)
    pc_params, _, pc_it, pc_loss = round_fn(
        stacked, momentum, jnp.zeros((), jnp.int32), jnp.asarray(idx),
        jnp.asarray(offs), keys, jnp.asarray(corpus), jnp.asarray(labels),
        jnp.asarray(mean_hwc))
    assert int(pc_it) == tau

    # the real trainer on HOST-preprocessed identical batches + the SAME
    # per-worker rng keys (trainer: rngs[d] -> split(tau) = our round's
    # split of keys[w], so dropout masks match bit-for-bit)
    trainer = ParallelTrainer(net, cfg, make_mesh(W), tau=tau)
    state = trainer.state_from_params(params0)
    data = np.zeros((tau, W * b, crop, crop, 3), np.float32)
    lab = np.zeros((tau, W * b, 1), np.int32)
    for w in range(W):
        for t in range(tau):
            for k in range(b):
                img = corpus[idx[w, t, k]].astype(np.float32) - mean_hwc
                y, x = offs[w, t, k]
                data[t, w * b + k] = img[y:y + crop, x:x + crop]
                lab[t, w * b + k] = labels[idx[w, t, k]]
    rngs = place_global_state(keys, trainer.mesh, P(DATA_AXIS))
    tr_state, tr_loss, _ = trainer._round(
        state, trainer._shard_batches({"data": data, "label": lab}), rngs,
        jnp.asarray(1.0, jnp.float32))

    assert float(pc_loss) == pytest.approx(float(tr_loss), rel=1e-5)
    tr_params = trainer.averaged_params(tr_state)
    pc_avg = jax.tree.map(lambda x: x[0], pc_params)
    for l in tr_params:
        for p in tr_params[l]:
            np.testing.assert_allclose(
                np.asarray(pc_avg[l][p]), np.asarray(tr_params[l][p]),
                rtol=2e-4, atol=2e-6, err_msg=f"{l}/{p}")
