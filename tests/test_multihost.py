"""REAL multi-host tests: two OS processes form a jax.distributed world
(2 hosts × 4 virtual CPU devices = 8-device global mesh, Gloo collectives)
and run the actual training loop on disjoint host data — the coverage the
reference validated only empirically on EC2 (SURVEY §4: "no multi-node
tests").

Plus single-process unit tests of the host-sharding math.
"""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from sparknet_tpu.data.dataset import ArrayDataset
from sparknet_tpu.data.imagenet import host_shards

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- host-sharding math (single-process) ------------------------------------

def test_host_shards_disjoint_cover():
    shards = [f"s{i}.tar" for i in range(10)]
    parts = [host_shards(shards, h, 3) for h in range(3)]
    flat = [s for p in parts for s in p]
    assert sorted(flat) == sorted(shards)          # cover
    assert len(set(flat)) == len(flat)             # disjoint
    assert parts[0] == ["s0.tar", "s3.tar", "s6.tar", "s9.tar"]


def test_array_dataset_host_shard():
    ds = ArrayDataset({"x": np.arange(10)[:, None]})
    a, b = ds.host_shard(0, 2), ds.host_shard(1, 2)
    np.testing.assert_array_equal(a.arrays["x"][:, 0], np.arange(5))
    np.testing.assert_array_equal(b.arrays["x"][:, 0], np.arange(5, 10))
    assert ds.host_shard(0, 1) is ds               # single-host no-op
    with pytest.raises(ValueError):
        ds.host_shard(2, 2)


# -- 2-process end-to-end ----------------------------------------------------

_WORKER = textwrap.dedent("""
    import os, sys
    pid, nproc, port, workdir = (int(sys.argv[1]), int(sys.argv[2]),
                                 sys.argv[3], sys.argv[4])
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from sparknet_tpu.parallel import initialize_multihost
    initialize_multihost(coordinator=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc and len(jax.devices()) == 4 * nproc

    import numpy as np
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.parallel.mesh import host_id_count
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.apps.train_loop import train, probe_value
    from sparknet_tpu.zoo import lenet
    from sparknet_tpu import CompiledNet

    # identical corpus on every host (seeded), then disjoint host shards
    r = np.random.default_rng(0)
    n = 256
    labels = r.integers(0, 10, (n, 1)).astype(np.int32)
    data = 0.1 * r.standard_normal((n, 1, 28, 28)).astype(np.float32)
    for i in range(n):
        c = int(labels[i, 0])
        data[i, 0, c:(c + 6), c:(c + 6)] += 1.0
    ds = ArrayDataset({"data": data, "label": labels})
    pi, pc = host_id_count()
    train_ds = ds.host_shard(pi, pc)

    cfg = RunConfig(model="lenet",
                    solver=SolverConfig(base_lr=0.01, momentum=0.9,
                                        lr_policy="fixed"),
                    tau=2, local_batch=4, eval_every=0, max_rounds=3,
                    workdir=workdir, seed=0,
                    checkpoint_dir=os.path.join(workdir, "ck"),
                    checkpoint_every=2)
    state = train(cfg, lenet(batch=cfg.local_batch), train_ds,
                  logger=Logger(os.path.join(workdir, f"log{pid}.txt"),
                                echo=False))
    probe = probe_value(state, CompiledNet.compile(lenet(batch=4)))
    print(f"RESULT pid={pid} probe={probe:.8f}", flush=True)
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_training_world(tmp_path):
    """Both hosts run the full app loop (disjoint data, τ-rounds, allreduce
    sync, multi-host checkpointing) and must agree bit-for-bit on the final
    averaged params (the probe)."""
    port = _free_port()
    script = str(tmp_path / "worker.py")
    with open(script, "w") as f:
        f.write(_WORKER)
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    for pid in range(2):
        os.makedirs(tmp_path / f"w{pid}", exist_ok=True)
    procs = [
        subprocess.Popen(
            [sys.executable, script, str(pid), "2", str(port),
             str(tmp_path / f"w{pid}")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for pid in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
    probes = sorted(
        ln.split("probe=")[1] for out in outs for ln in out.splitlines()
        if ln.startswith("RESULT"))
    assert len(probes) == 2
    assert probes[0] == probes[1], f"hosts diverged: {probes}"
    # process 0 (and only process 0) wrote the checkpoint
    assert os.path.isdir(tmp_path / "w0" / "ck" / "step-3")
    assert not os.path.isdir(tmp_path / "w1" / "ck")


_STREAM_WORKER = textwrap.dedent("""
    import os, sys
    pid, nproc, port, shards_dir, ckdir, workdir, rounds = (
        int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
        sys.argv[5], sys.argv[6], int(sys.argv[7]))
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from sparknet_tpu.parallel import initialize_multihost
    initialize_multihost(coordinator=f"127.0.0.1:{port}",
                         num_processes=nproc, process_id=pid)

    import numpy as np
    from sparknet_tpu.apps.train_loop import train, probe_value
    from sparknet_tpu.data import imagenet
    from sparknet_tpu.data.streaming import make_parallel_source
    from sparknet_tpu.parallel.mesh import host_id_count
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.zoo import lenet
    from sparknet_tpu import CompiledNet

    pi, pc = host_id_count()
    shards = imagenet.host_shards(imagenet.list_shards(shards_dir), pi, pc)
    labels = imagenet.load_label_map(os.path.join(shards_dir, "train.txt"))
    src = make_parallel_source(shards, labels, jax.local_device_count(),
                               2, 2, n_sources=2, height=28, width=28)
    assert src.n_sources == 2

    class GrayTo28:
        def convert_batch(self, batch, train=True, rng=None):
            x = batch["data"].astype(np.float32).mean(axis=1)
            return {"data": x[..., None], "label": batch["label"]}

    cfg = RunConfig(model="lenet",
                    solver=SolverConfig(base_lr=0.01, momentum=0.9,
                                        lr_policy="fixed"),
                    tau=2, local_batch=2, eval_every=0, max_rounds=rounds,
                    workdir=workdir, seed=0, checkpoint_dir=ckdir,
                    checkpoint_every=1)
    state = train(cfg, lenet(batch=2), src,
                  logger=Logger(os.path.join(workdir, f"slog{pid}.txt"),
                                echo=False),
                  batch_transform=GrayTo28())
    probe = probe_value(state, CompiledNet.compile(lenet(batch=2)))
    print(f"RESULT pid={pid} probe={probe:.8f}", flush=True)
""")


@pytest.mark.slow
def test_two_process_parallel_streaming_cursors(tmp_path):
    """The composed multihost ingest story: 2 hosts x 2 parallel shard
    readers each; the checkpoint's stream cursors allgather as a per-host
    [readers, 3] block (the shape that would die with ragged per-host
    reader counts), and a relaunch resumes ALL four readers."""
    from sparknet_tpu.data import imagenet
    from sparknet_tpu.utils import checkpoint as ckpt

    shards_dir = str(tmp_path / "shards")
    imagenet.write_synthetic_shards(shards_dir, n_shards=8, per_shard=12,
                                    size=28, n_classes=10)
    ckdir = str(tmp_path / "ck")
    script = str(tmp_path / "sworker.py")
    with open(script, "w") as f:
        f.write(_STREAM_WORKER)
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    for pid in range(2):
        os.makedirs(tmp_path / f"w{pid}", exist_ok=True)

    def launch(rounds):
        port = _free_port()
        procs = [subprocess.Popen(
            [sys.executable, script, str(pid), "2", str(port), shards_dir,
             ckdir, str(tmp_path / f"w{pid}"), str(rounds)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True) for pid in range(2)]
        outs = [p.communicate(timeout=300)[0] for p in procs]
        for pid, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        probes = sorted(ln.split("probe=")[1] for out in outs
                        for ln in out.splitlines()
                        if ln.startswith("RESULT"))
        assert len(probes) == 2 and probes[0] == probes[1], probes
        return probes[0]

    launch(rounds=3)
    _, step, extra = ckpt.restore_flat(ckdir)
    assert step == 3
    # 2 hosts x 2 readers x [shard, entry, epochs]
    assert len(extra["stream"]) == 2
    assert all(len(host_rows) == 2 for host_rows in extra["stream"])

    launch(rounds=5)  # resume
    for pid in range(2):
        text = open(tmp_path / f"w{pid}" / f"slog{pid}.txt").read()
        assert "resumed from checkpoint round 3" in text
        assert "stream resumed at" in text and text.count("shard") >= 2
