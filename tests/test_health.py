"""Training health supervisor: anomaly signals, rollback, checkpoint
integrity (the detect -> rollback -> recover story, plus the hardening
satellites). All deterministic — fault injection is config-keyed
(utils/health.py), never random."""
import json
import os

import numpy as np
import pytest

import jax

from sparknet_tpu.utils import checkpoint as ckpt
from sparknet_tpu.utils.config import RunConfig
from sparknet_tpu.utils.health import (HealthConfig, HealthMonitor,
                                       TrainingHealthError, poison_batch)
from sparknet_tpu.utils.logger import Logger


# -- HealthMonitor classification ------------------------------------------


def _warmed_monitor(cfg=None, n=12, base=2.0):
    mon = HealthMonitor(cfg or HealthConfig(min_history=4))
    for r in range(n):
        assert mon.observe(r, base + 0.01 * (r % 3)) == "ok"
    return mon


def test_monitor_classifies_spike_and_recovers():
    mon = _warmed_monitor()
    assert mon.observe(100, 50.0) == "spike"
    assert mon.rollback_needed is None  # isolated spike: skip-and-continue
    # the spike did NOT enter the window: the next normal loss is ok
    assert mon.observe(101, 2.01) == "ok"
    assert mon.counts["spike"] == 1


def test_monitor_repeated_spikes_latch_rollback():
    mon = _warmed_monitor(HealthConfig(min_history=4, spike_patience=3))
    for r in range(3):
        assert mon.observe(100 + r, 50.0) == "spike"
    assert mon.rollback_needed == "repeated spikes"


def test_monitor_nonfinite_latches_rollback():
    mon = _warmed_monitor()
    assert mon.observe(100, float("nan")) == "nonfinite"
    assert mon.rollback_needed == "nonfinite"
    mon2 = _warmed_monitor()
    assert mon2.observe(100, 2.0, nonfinite_count=3.0) == "nonfinite"
    assert mon2.rollback_needed == "nonfinite"
    # a nonfinite grad norm with FINITE loss/params is overflow in the
    # squared-norm telemetry, not poisoned state: spike, not nonfinite
    mon3 = _warmed_monitor()
    assert mon3.observe(100, 2.0, grad_norm=float("inf")) == "spike"
    assert mon3.rollback_needed is None


def test_monitor_needs_history_before_spike_classification():
    mon = HealthMonitor(HealthConfig(min_history=8))
    # an early wild loss is NOT a spike: no baseline yet (fresh nets start
    # anywhere)
    assert mon.observe(0, 1000.0) == "ok"
    assert mon.observe(1, 2.0) == "ok"


def test_monitor_loss_drop_is_not_a_spike():
    mon = _warmed_monitor()
    assert mon.observe(100, 0.001) == "ok"  # one-sided: improvement is fine


def test_monitor_rollback_budget_hard_fails():
    mon = _warmed_monitor(HealthConfig(min_history=4, max_rollbacks=1))
    mon.observe(100, float("nan"))
    assert mon.consume_rollback() == "nonfinite"  # 1st: within budget
    mon.observe(101, float("nan"))
    with pytest.raises(TrainingHealthError, match="budget"):
        mon.consume_rollback()


def test_monitor_anomaly_tags_checkpoint_window():
    mon = _warmed_monitor(HealthConfig(min_history=4, window=8))
    assert not mon.recently_anomalous(50)
    mon.observe(100, 50.0)
    assert mon.recently_anomalous(101)
    assert not mon.recently_anomalous(100 + 8)
    # consuming a rollback clears the taint: restored state predates it
    mon.observe(110, float("nan"))
    mon.consume_rollback()
    assert not mon.recently_anomalous(111)


def test_poison_batch_spares_integer_labels():
    b = {"data": np.ones((2, 3), np.float32), "label": np.ones((2,), np.int32)}
    p = poison_batch(b, "nan")
    assert np.isnan(p["data"]).all() and (p["label"] == 1).all()
    assert np.isfinite(b["data"]).all()  # original untouched
    s = poison_batch(b, "spike", scale=100.0)
    assert (s["data"] == 100.0).all()


def test_health_config_round_trips_through_run_config():
    cfg = RunConfig.from_dict({"health": {"spike_mad": 5.0,
                                          "inject_nan_rounds": [3]}})
    assert cfg.health.spike_mad == 5.0
    assert cfg.health.inject_nan_rounds == (3,)
    over = cfg.with_overrides('max_rounds=7')
    assert over.health.spike_mad == 5.0 and over.max_rounds == 7
    with pytest.raises(ValueError, match="unknown health config"):
        RunConfig.from_dict({"health": {"nope": 1}})


# -- on-device health scalars ----------------------------------------------


@pytest.fixture(scope="module")
def tiny_trainer(trainer_cls):
    """Parametrized over BOTH trainer implementations (conftest
    trainer_cls): the [n_data+1] health psum layout and its per-worker
    attribution must hold identically under the shard_map replica layout
    and the NamedSharding logical layout."""
    from sparknet_tpu import CompiledNet, net_from_prototxt
    from sparknet_tpu.parallel import make_mesh
    from sparknet_tpu.solver import SolverConfig
    from test_parallel import TINY_MLP
    net = CompiledNet.compile(net_from_prototxt(TINY_MLP))
    cfg = SolverConfig(base_lr=0.05, momentum=0.9, lr_policy="fixed")
    return trainer_cls(net, cfg, make_mesh(), tau=3)


def _mlp_batches(seed):
    from test_parallel import make_round_batches
    return make_round_batches(seed)


def test_round_health_scalars_clean(tiny_trainer):
    state = tiny_trainer.init_state(jax.random.PRNGKey(0))
    state, loss = tiny_trainer.train_round(state, _mlp_batches(1),
                                           jax.random.PRNGKey(42))
    h = tiny_trainer.last_health
    assert float(h["nonfinite"]) == 0.0
    gn = float(h["grad_norm"])
    assert np.isfinite(gn) and gn > 0.0
    assert np.isfinite(float(loss))


def test_round_health_scalars_flag_nan_poison(tiny_trainer):
    state = tiny_trainer.init_state(jax.random.PRNGKey(0))
    batches = poison_batch(_mlp_batches(2), "nan")
    state, loss = tiny_trainer.train_round(state, batches,
                                           jax.random.PRNGKey(43))
    # every data group saw poison: the psum'd flag counts all 8 workers
    assert float(tiny_trainer.last_health["nonfinite"]) == 8.0
    np.testing.assert_array_equal(
        np.asarray(tiny_trainer.last_health["nonfinite_by_worker"]),
        np.ones(8, np.float32))
    assert not np.isfinite(float(loss))


def test_round_health_attributes_single_bad_worker(tiny_trainer):
    """NaNs fed to ONE worker's shard light exactly that worker's slot in
    the [n_data] attribution vector: the per-worker flag reads the
    PRE-average local state, so the weight-averaging pmean (which smears
    the NaN onto every replica one sync later) cannot erase the origin.
    A consistently bad host/feed is argmax of this vector."""
    bad = 5
    state = tiny_trainer.init_state(jax.random.PRNGKey(0))
    batches = _mlp_batches(3)
    per = batches["data"].shape[1] // 8  # [tau, n_dev*local_b, ...] rows
    data = batches["data"].copy()
    data[:, bad * per:(bad + 1) * per] = np.nan
    state, loss = tiny_trainer.train_round(
        state, {"data": data, "label": batches["label"]},
        jax.random.PRNGKey(44))
    h = tiny_trainer.last_health
    vec = np.asarray(h["nonfinite_by_worker"])
    expect = np.zeros(8, np.float32)
    expect[bad] = 1.0
    np.testing.assert_array_equal(vec, expect)
    assert float(h["nonfinite"]) == 1.0
    assert int(np.argmax(vec)) == bad
    # and the averaged params ARE poisoned (the attribution beat the
    # smear, it didn't prevent it — rollback is still the remedy)
    avg = tiny_trainer.averaged_params(state)
    assert not np.isfinite(np.asarray(avg["ip1"]["w"])).all()


def test_lr_scale_shrinks_the_update(tiny_trainer):
    k = jax.random.PRNGKey(0)
    p0 = np.asarray(tiny_trainer.averaged_params(
        tiny_trainer.init_state(k))["ip1"]["w"]).copy()

    def delta(scale):
        s = tiny_trainer.init_state(k)
        s, _ = tiny_trainer.train_round(s, _mlp_batches(1),
                                        jax.random.PRNGKey(42),
                                        lr_scale=scale)
        p = np.asarray(tiny_trainer.averaged_params(s)["ip1"]["w"])
        return np.abs(p - p0).max()

    full, half = delta(1.0), delta(0.5)
    assert half < full * 0.75  # backed-off rounds take smaller steps
    assert half > 0.0


# -- checkpoint integrity ---------------------------------------------------


def _save_steps(d, n=3, seed=0):
    r = np.random.default_rng(seed)
    trees = {}
    for s in range(1, n + 1):
        trees[s] = {"a": {"w": r.standard_normal((4, 3)).astype(np.float32)},
                    "it": np.asarray([s] * 2)}
        ckpt.save(str(d), trees[s], step=s)
    return trees


def _silently_corrupt(npz_path):
    """Path wrapper over the one canonical digest-evading corruption
    helper (fake_stores.corrupt_npz_bytes): flip one value but rewrite a
    VALID archive, the silent at-rest corruption only the recorded
    sha256 digests can catch."""
    from fake_stores import corrupt_npz_bytes
    with open(npz_path, "rb") as f:
        raw = f.read()
    with open(npz_path, "wb") as f:
        f.write(corrupt_npz_bytes(raw))


def test_digest_verification_rejects_flipped_byte(tmp_path):
    trees = _save_steps(tmp_path / "ck", n=3)
    _silently_corrupt(tmp_path / "ck" / "step-3" / "state.npz")

    assert not ckpt.verify(str(tmp_path / "ck" / "step-3"))
    assert ckpt.verify(str(tmp_path / "ck" / "step-2"))
    # auto-latest restore falls back to step 2 BIT-exactly
    with pytest.warns(RuntimeWarning):
        flat, step, _ = ckpt.restore_flat(str(tmp_path / "ck"))
    assert step == 2
    np.testing.assert_array_equal(flat["a/w"], trees[2]["a"]["w"])
    # explicit-step restore of the corrupt one fails loudly
    with pytest.raises(ckpt.CheckpointCorruptError, match="digest"):
        ckpt.restore_flat(str(tmp_path / "ck"), step=3)
    assert ckpt.newest_verified_step(str(tmp_path / "ck")) == 2


def test_truncated_npz_rejected_and_falls_back(tmp_path):
    trees = _save_steps(tmp_path / "ck", n=2)
    npz = tmp_path / "ck" / "step-2" / "state.npz"
    npz.write_bytes(npz.read_bytes()[:40])  # torn copy
    with pytest.warns(RuntimeWarning):
        flat, step, _ = ckpt.restore_flat(str(tmp_path / "ck"))
    assert step == 1
    np.testing.assert_array_equal(flat["a/w"], trees[1]["a"]["w"])


def test_bad_meta_json_is_not_a_checkpoint(tmp_path):
    _save_steps(tmp_path / "ck", n=2)
    meta = tmp_path / "ck" / "step-2" / "meta.json"
    meta.write_text("{ torn json")
    with pytest.warns(RuntimeWarning):
        assert ckpt.latest_step(str(tmp_path / "ck")) == 1
    with pytest.warns(RuntimeWarning):
        _, step, _ = ckpt.restore_flat(str(tmp_path / "ck"))
    assert step == 1
    os.remove(meta)  # missing entirely: same story
    with pytest.warns(RuntimeWarning):
        assert ckpt.latest_step(str(tmp_path / "ck")) == 1


def test_digestless_legacy_checkpoint_still_restores(tmp_path):
    tree = {"a": np.arange(6, dtype=np.float32)}
    path = ckpt.save(str(tmp_path / "ck"), tree, step=1)
    meta = json.load(open(os.path.join(path, "meta.json")))
    del meta["digests"]  # simulate a pre-integrity-format checkpoint
    json.dump(meta, open(os.path.join(path, "meta.json"), "w"))
    assert ckpt.verify(path)  # vacuous digest check
    flat, step, _ = ckpt.restore_flat(str(tmp_path / "ck"))
    assert step == 1
    np.testing.assert_array_equal(flat["a"], tree["a"])


def test_retain_protects_newest_verified(tmp_path):
    _save_steps(tmp_path / "ck", n=5)
    for s in (4, 5):  # corrupt the two newest
        npz = tmp_path / "ck" / f"step-{s}" / "state.npz"
        raw = bytearray(npz.read_bytes())
        raw[-10] ^= 0x01
        npz.write_bytes(bytes(raw))
    ckpt.retain(str(tmp_path / "ck"), keep=2)
    # keep-window is {4, 5}, but step 3 is the newest VERIFIED one: kept
    assert sorted(os.listdir(tmp_path / "ck")) == \
        ["step-3", "step-4", "step-5"]


def test_save_sweeps_stale_tmp_dirs(tmp_path):
    d = tmp_path / "ck"
    os.makedirs(d / ".tmp-deadbeef")  # SIGKILL'd writer's leftovers
    (d / ".tmp-deadbeef" / "state.npz").write_bytes(b"partial")
    ckpt.save(str(d), {"a": np.zeros(2)}, step=1)
    assert sorted(os.listdir(d)) == ["step-1"]


def test_anomalous_checkpoints_skipped_by_rollback_selector(tmp_path):
    d = str(tmp_path / "ck")
    ckpt.save(d, {"a": np.zeros(2)}, step=1)
    ckpt.save(d, {"a": np.ones(2)}, step=2, extra={"anomalous": True})
    assert ckpt.newest_verified_step(d) == 2
    assert ckpt.newest_verified_step(d, skip_anomalous=True) == 1


# -- the composed story: injected fault -> detect -> rollback -> recover ----


def _train_with_injection(tmp_path, health, max_rounds=8, log_every=1,
                          checkpoint_every=1, **cfg_kw):
    from sparknet_tpu.data import cifar
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.zoo import cifar10_quick

    d = str(tmp_path / "cifar")
    if not os.path.isdir(d):
        cifar.write_synthetic(d, n_per_file=40)
    train_ds = ArrayDataset(cifar.CifarLoader(d).train_batch_dict())
    cfg = RunConfig(
        solver=SolverConfig(base_lr=0.01, momentum=0.9, lr_policy="fixed"),
        tau=2, local_batch=4, eval_every=0, max_rounds=max_rounds, seed=0,
        workdir=str(tmp_path), log_every=log_every,
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=checkpoint_every, health=health, **cfg_kw)
    jsonl = str(tmp_path / "metrics.jsonl")
    state = train(cfg, cifar10_quick(batch=4), train_ds,
                  logger=Logger(str(tmp_path / "log.txt"), echo=False,
                                jsonl_path=jsonl))
    recs = [json.loads(ln) for ln in open(jsonl)]
    return cfg, state, recs


@pytest.mark.chaos
def test_injected_nan_round_detected_rolled_back_and_recovered(tmp_path):
    """The acceptance path: a forced-NaN round at R is detected within one
    log_every window, the run rolls back to the last verified checkpoint,
    completes to max_rounds, and the final loss is finite."""
    R = 3
    cfg, state, recs = _train_with_injection(
        tmp_path, HealthConfig(inject_nan_rounds=(R,), min_history=2),
        max_rounds=8)

    events = [r for r in recs if r.get("event") == "rollback"]
    assert len(events) == 1
    ev = events[0]
    assert ev["reason"] == "nonfinite"
    assert ev["target_step"] <= R  # restored a pre-fault checkpoint
    assert ev["retry"] == 1

    # round-accounting: the poisoned pass over R logged a nonfinite loss
    # (serialized as null — NaN is not valid JSON), the retried pass a
    # finite one, and every round 0..max_rounds-1 has a finite FINAL
    # occurrence (the retry wins)
    by_round = {}
    for r in recs:
        if "loss" in r:
            by_round.setdefault(r["step"], []).append(r["loss"])
    assert any(x is None for x in by_round[R])
    assert by_round[R][-1] is not None and np.isfinite(by_round[R][-1])
    for rr in range(cfg.max_rounds):
        last = by_round[rr][-1]
        assert last is not None and np.isfinite(last), f"round {rr}"
    # detection within one log_every window of the fault
    nonf = [r["step"] for r in recs if r.get("health") == "nonfinite"]
    assert nonf and min(nonf) == R

    # the run completed: final checkpoint at max_rounds, fully finite
    flat, step, extra = ckpt.restore_flat(cfg.checkpoint_dir)
    assert step == cfg.max_rounds
    assert all(np.isfinite(np.asarray(a)).all() for a in flat.values())
    assert "anomalous" not in extra  # recovery cleared the taint
    # the supervisor's recovery state rides the checkpoint: a preemption-
    # resume must not silently revert the backoff / retried data order
    assert extra["health"] == {"retry": 1, "lr_scale": 0.5, "rollbacks": 1}


@pytest.mark.chaos
def test_heartbeat_and_worker_attribution_in_loop(tmp_path):
    """The loop-level surface of both satellites: with heartbeat_path
    set, the run leaves a fresh heartbeat whose status reflects the
    outcome ("done", rollbacks counted), and the poisoned round's JSONL
    row carries the worst-worker attribution."""
    from sparknet_tpu.utils.heartbeat import read_heartbeat, staleness_s
    hb_path = str(tmp_path / "hb.json")
    R = 3
    cfg, state, recs = _train_with_injection(
        tmp_path, HealthConfig(inject_nan_rounds=(R,), min_history=2),
        max_rounds=6, heartbeat_path=hb_path, heartbeat_every_s=0.0)
    hb = read_heartbeat(hb_path)
    assert hb is not None and hb["role"] == "train"
    assert hb["status"] == "done" and hb["step"] == cfg.max_rounds
    assert hb["rollbacks"] == 1
    assert staleness_s(hb) < 120
    # the nonfinite round's metrics row names the worst worker (the
    # injection poisons every worker's shard, so index 0 wins the argmax
    # and ALL workers are flagged)
    row = next(r for r in recs if r.get("health") == "nonfinite")
    assert row["step"] == R
    assert row["worst_worker"] == 0
    assert row["nonfinite_workers"] == 8  # every worker's shard poisoned


@pytest.mark.chaos
def test_two_separate_incidents_each_detected(tmp_path):
    """Injection keys on per-round first execution, not the global retry
    generation: a second configured fault AFTER an earlier rollback still
    fires and is recovered independently."""
    cfg, state, recs = _train_with_injection(
        tmp_path, HealthConfig(inject_nan_rounds=(2, 5), min_history=2),
        max_rounds=8)
    events = [r for r in recs if r.get("event") == "rollback"]
    assert len(events) == 2
    assert [e["retry"] for e in events] == [1, 2]
    flat, step, _ = ckpt.restore_flat(cfg.checkpoint_dir)
    assert step == cfg.max_rounds
    assert all(np.isfinite(np.asarray(a)).all() for a in flat.values())


@pytest.mark.chaos
def test_injected_fault_with_batched_log_every(tmp_path):
    """log_every > 1: health scalars stay on device between flushes, and
    detection still lands within one window (<= log_every rounds late)."""
    R = 2
    cfg, state, recs = _train_with_injection(
        tmp_path, HealthConfig(inject_nan_rounds=(R,), min_history=2),
        max_rounds=8, log_every=3)
    events = [r for r in recs if r.get("event") == "rollback"]
    assert len(events) == 1
    flat, step, _ = ckpt.restore_flat(cfg.checkpoint_dir)
    assert step == cfg.max_rounds
    assert all(np.isfinite(np.asarray(a)).all() for a in flat.values())


@pytest.mark.chaos
def test_injected_spikes_skip_then_rollback_and_tag_checkpoints(tmp_path):
    """Spike path: repeated injected spikes cross spike_patience and roll
    back; checkpoints taken in the unhealthy window carry the anomalous
    tag (and the anomalous_checkpoint event lands in the JSONL — the
    Logger.event/step collision regression)."""
    cfg, state, recs = _train_with_injection(
        tmp_path, HealthConfig(min_history=2, spike_mad=6.0,
                               spike_patience=2,
                               inject_spike_rounds=(4, 5),
                               # gentle: x30 inputs spike the loss but stay
                               # finite (x1000 would overflow to NaN and
                               # test the nonfinite path instead)
                               inject_spike_scale=30.0),
        max_rounds=8, checkpoint_every=2)
    assert any(r.get("health") == "spike" for r in recs)
    kinds = {r["event"] for r in recs if "event" in r}
    assert "rollback" in kinds
    rb = next(r for r in recs if r.get("event") == "rollback")
    assert rb["reason"] == "repeated spikes"
    for ev in (r for r in recs if r.get("event") == "anomalous_checkpoint"):
        assert ev["checkpoint_step"] > 0  # event carries the tagged step
    flat, step, _ = ckpt.restore_flat(cfg.checkpoint_dir)
    assert step == cfg.max_rounds
    assert all(np.isfinite(np.asarray(a)).all() for a in flat.values())


@pytest.mark.chaos
def test_unrecoverable_without_checkpoints_fails_loudly(tmp_path):
    from sparknet_tpu.data import cifar
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.zoo import cifar10_quick

    d = str(tmp_path / "cifar")
    cifar.write_synthetic(d, n_per_file=40)
    train_ds = ArrayDataset(cifar.CifarLoader(d).train_batch_dict())
    cfg = RunConfig(
        solver=SolverConfig(base_lr=0.01, momentum=0.9, lr_policy="fixed"),
        tau=2, local_batch=4, eval_every=0, max_rounds=6, seed=0,
        workdir=str(tmp_path),  # NO checkpoint_dir
        health=HealthConfig(inject_nan_rounds=(2,), min_history=2))
    with pytest.raises(TrainingHealthError, match="checkpoint"):
        train(cfg, cifar10_quick(batch=4), train_ds,
              logger=Logger(echo=False))


@pytest.mark.chaos
def test_corrupt_latest_checkpoint_resume_falls_back_bit_exactly(tmp_path):
    """Corrupt-checkpoint chaos: byte-flip the newest checkpoint of a real
    run; resume must reject it via digest verification and restore the
    previous step bit-exactly."""
    cfg, state, _ = _train_with_injection(
        tmp_path, HealthConfig(), max_rounds=4, checkpoint_every=2)
    ckdir = cfg.checkpoint_dir
    assert ckpt.latest_step(ckdir) == 4
    good, good_step, _ = ckpt.restore_flat(ckdir, step=2)

    # the loop writes the sharded layout by default since r8: corrupt
    # whichever state file the step holds (state.npz, or a shard file)
    step_dir = os.path.join(ckdir, "step-4")
    victims = sorted(f for f in os.listdir(step_dir)
                     if f == "state.npz" or f.startswith("shard-"))
    _silently_corrupt(os.path.join(step_dir, victims[0]))

    with pytest.warns(RuntimeWarning, match="digest mismatch"):
        flat, step, _ = ckpt.restore_flat(ckdir)
    assert step == 2
    assert sorted(flat) == sorted(good)
    for k in good:
        np.testing.assert_array_equal(flat[k], good[k], err_msg=k)


@pytest.mark.chaos
def test_injection_inert_when_supervisor_disabled(tmp_path):
    """enabled=False must disarm the injection hooks too: poisoning a run
    with nothing watching would recreate the silent-NaN failure mode this
    subsystem exists to prevent."""
    cfg, state, recs = _train_with_injection(
        tmp_path, HealthConfig(enabled=False, inject_nan_rounds=(2,)),
        max_rounds=4)
    losses = [r["loss"] for r in recs if "loss" in r]
    assert len(losses) == cfg.max_rounds
    assert all(x is not None and np.isfinite(x) for x in losses)
    assert not any("event" in r for r in recs)


def test_healthy_run_has_no_health_events(tmp_path):
    """Steady state: no spikes, no rollbacks, no extra sync — the metrics
    stream carries grad_norm but no health/event records."""
    cfg, state, recs = _train_with_injection(
        tmp_path, HealthConfig(), max_rounds=4)
    assert not any("event" in r for r in recs)
    assert not any("health" in r for r in recs)
    gnorms = [r["grad_norm"] for r in recs if "grad_norm" in r]
    assert len(gnorms) == cfg.max_rounds
    assert all(np.isfinite(g) and g > 0 for g in gnorms)
    # vanilla runs write pre-health-format checkpoint extras (no recovery
    # state key rides along when nothing was recovered)
    _, _, extra = ckpt.restore_flat(cfg.checkpoint_dir)
    assert "health" not in extra and "anomalous" not in extra


# -- gcs backoff satellites -------------------------------------------------


def test_retry_delay_full_jitter_not_synchronized(monkeypatch):
    from sparknet_tpu.data import gcs
    delays = {gcs.retry_delay(2) for _ in range(32)}
    assert len(delays) > 1  # jittered, not the old deterministic 2.0 s
    assert all(0.0 <= d <= gcs.BACKOFF_S * 4 for d in delays)


def test_retry_delay_honors_retry_after_floor():
    import email.message
    import urllib.error
    from sparknet_tpu.data import gcs

    hdrs = email.message.Message()
    hdrs["Retry-After"] = "7"
    err = urllib.error.HTTPError("http://x", 429, "too many", hdrs, None)
    for _ in range(8):
        assert gcs.retry_delay(0, err) >= 7.0
    # non-429s and date-form headers keep the jittered delay
    err500 = urllib.error.HTTPError("http://x", 500, "ise", hdrs, None)
    assert gcs.retry_delay(0, err500) <= gcs.BACKOFF_S
    bad = email.message.Message()
    bad["Retry-After"] = "Wed, 21 Oct 2026 07:28:00 GMT"
    err_bad = urllib.error.HTTPError("http://x", 429, "tm", bad, None)
    assert gcs.retry_delay(0, err_bad) <= gcs.BACKOFF_S
