"""The binary data plane (serve/wire.py + serve/binary_frontend.py):
length-prefixed frame roundtrips bitwise-identical to HTTP, keep-alive
pipelining over one connection, flag-gated chunked response streaming
with bounded per-connection buffering, typed error frames for every shed,
malformed-wire robustness (oversized / truncated / bad magic / mid-stream
disconnect each fail their OWN connection while the server keeps
serving), per-tenant admission on the frame tenant field, and the
router's remote replicas riding the binary transport.

Tier-1: CPU backend, lenet shapes, ephemeral ports.
"""
import socket
import struct
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.model.spec import (InputSpec, LayerSpec, NetSpec,
                                     PoolingParam)
from sparknet_tpu.serve import (BinaryClient, BinaryFrontend,
                                DeadlineExpiredError, HttpFrontend,
                                InferenceServer, ModelRouter,
                                NoReplicaError, RouterConfig,
                                ServeConfig, TenantAdmission,
                                TenantLimitError, UnknownModelError,
                                binary_infer, http_infer, zeros_batch)
from sparknet_tpu.serve import wire
from sparknet_tpu.zoo import lenet


def _example(i: int) -> dict:
    r = np.random.default_rng(3000 + i)
    return {"data": r.standard_normal((28, 28, 1)).astype(np.float32)}


def blob_net(batch: int = 1, c: int = 8, hw: int = 256) -> JaxNet:
    """A featurizer-shaped net whose per-example output is a multi-MB
    blob (1x1 max-pool = identity): the streaming tests' food."""
    spec = NetSpec(
        name="blobber",
        inputs=(InputSpec("data", (batch, c, hw, hw)),),
        layers=(LayerSpec(name="feat", type="Pooling",
                          bottoms=("data",), tops=("feat",),
                          pool=PoolingParam(pool="MAX", kernel_size=1,
                                            stride=1)),))
    return JaxNet(spec)


@pytest.fixture(scope="module")
def net():
    return JaxNet(lenet(batch=4))


@pytest.fixture()
def srv(net):
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as s:
        yield s


# -- wire unit ----------------------------------------------------------------

def test_wire_request_roundtrip():
    r = np.random.default_rng(0)
    payload = {"data": r.standard_normal((3, 4)).astype(np.float32),
               "label": np.arange(2, dtype=np.int32)}
    head, views = wire.pack_request(7, "m", payload, deadline_ms=125.0,
                                    tenant="t1", priority="low",
                                    stream=True,
                                    trace="00000000000000ab-000000cd-1")
    buf = head + b"".join(bytes(v) for v in views)
    ftype, flags, rid, meta_len, payload_len = wire.parse_header(buf)
    assert (ftype, rid) == (wire.T_REQUEST, 7)
    assert flags & wire.FLAG_STREAM
    meta = buf[wire.HEADER_LEN:wire.HEADER_LEN + meta_len]
    model, tenant, priority, deadline_ms, trace, descs, seg = \
        wire.unpack_request_meta(meta)
    assert seg is None  # inline payload: no trailing shm segment
    assert (model, tenant, priority, deadline_ms) == \
        ("m", "t1", "low", 125.0)
    assert trace == "00000000000000ab-000000cd-1"
    # an untraced request puts "" on the wire, surfaced as None
    h2, v2 = wire.pack_request(8, "m", payload)
    buf2 = h2 + b"".join(bytes(v) for v in v2)
    meta2_len = wire.parse_header(buf2)[3]
    assert wire.unpack_request_meta(
        buf2[wire.HEADER_LEN:wire.HEADER_LEN + meta2_len])[4] is None
    out = wire.tensors_from(descs,
                            buf[wire.HEADER_LEN + meta_len:])
    assert set(out) == {"data", "label"}
    np.testing.assert_array_equal(out["data"], payload["data"])
    np.testing.assert_array_equal(out["label"], payload["label"])
    assert out["data"].dtype == np.float32
    assert out["label"].dtype == np.int32


def test_wire_bad_magic_and_version_raise_typed():
    head, _ = wire.pack_request(1, "m", {})
    with pytest.raises(wire.WireError, match="magic"):
        wire.parse_header(b"XXXX" + head[4:])
    with pytest.raises(wire.WireError, match="version"):
        wire.parse_header(head[:4] + bytes([99]) + head[5:])


def test_wire_truncated_meta_raises_not_crashes():
    with pytest.raises(wire.WireError, match="truncated"):
        wire.unpack_request_meta(b"\x05ab")  # str8 claims 5, has 2


def test_wire_streamed_response_chunks_cover_payload():
    arrs = {"a": np.arange(1000, dtype=np.float32),
            "b": np.arange(17, dtype=np.int32)}
    items = wire.pack_response(9, "m", 3, arrs, stream=True,
                               chunk_bytes=512)
    head0, view0 = items[0]
    ftype, flags, rid, meta_len, total = wire.parse_header(head0)
    assert ftype == wire.T_RESPONSE and flags & wire.FLAG_STREAM
    assert view0 is None and total == 4000 + 68
    buf = bytearray(total)
    saw_last = False
    for head, view in items[1:]:
        ftype, flags, rid, meta_len, plen = wire.parse_header(head)
        assert ftype == wire.T_CHUNK and rid == 9
        assert plen <= 512  # the bound the server promises
        off = wire.unpack_chunk_meta(head[wire.HEADER_LEN:])
        buf[off:off + plen] = bytes(view)
        saw_last |= bool(flags & wire.FLAG_LAST)
    assert saw_last
    model, step, queue_wait_ms, descs, seg = wire.unpack_response_meta(
        head0[wire.HEADER_LEN:])
    assert queue_wait_ms is None and seg is None
    out = wire.tensors_from(descs, bytes(buf))
    np.testing.assert_array_equal(out["a"], arrs["a"])
    np.testing.assert_array_equal(out["b"], arrs["b"])


# -- transport roundtrip + parity --------------------------------------------

def test_binary_bitwise_identical_to_http_same_bucket(net, srv):
    """The parity pin: one request through BOTH wires hits the same
    replica and the same bucket — the tensors must be BITWISE equal
    (the transports carry raw f32 bytes; neither may perturb them)."""
    bfe = BinaryFrontend(srv, port=0)
    hfe = HttpFrontend(srv, port=0)
    try:
        x = _example(0)
        out_b = binary_infer(bfe.address, "default", x, deadline_s=30.0)
        out_h = http_infer(f"http://{hfe.address[0]}:{hfe.address[1]}",
                           "default", x, deadline_s=30.0)
        assert out_b["prob"].dtype == np.float32
        np.testing.assert_array_equal(out_b["prob"], out_h["prob"])
        # and against the direct forward at the same bucket
        direct = net.forward({**zeros_batch(net, 1),
                              "data": x["data"][None]},
                             blob_names=["prob"])
        np.testing.assert_array_equal(out_b["prob"],
                                      np.asarray(direct["prob"][0]))
    finally:
        bfe.stop()
        hfe.stop()


def test_pipelined_burst_one_connection(net, srv):
    """Eight requests submitted before any reply is read — all answered
    on ONE connection (keep-alive + pipelining asserted via the server's
    connection/request counters), every output correct."""
    bfe = BinaryFrontend(srv, port=0)
    cli = BinaryClient(*bfe.address)
    try:
        xs = [_example(i) for i in range(8)]
        rids = [cli.submit(x, model="default", deadline_s=30.0)
                for x in xs]
        outs = [cli.collect(rid) for rid in rids]
        direct = net.forward(
            {**zeros_batch(net, 8),
             "data": np.stack([x["data"] for x in xs])},
            blob_names=["prob"])
        for i, out in enumerate(outs):
            np.testing.assert_allclose(out["prob"],
                                       np.asarray(direct["prob"][i]),
                                       rtol=1e-4, atol=1e-5)
        assert bfe.connections == 1, "pipelining opened extra connections"
        assert bfe.requests == 8
    finally:
        cli.close()
        bfe.stop()


def test_streaming_blob_bounded_buffering():
    """A multi-MB featurizer-style response with FLAG_STREAM: the
    reassembled tensors equal the non-streamed ones, and the server's
    per-connection COPIED buffering stays bounded by the chunk size —
    never the blob size (the npz door would buffer the whole blob)."""
    net2 = blob_net(batch=1, c=8, hw=256)  # 2 MB/row
    cfg = ServeConfig(model_name="featurizer", max_batch=1, buckets=(1,),
                      max_wait_ms=1.0, outputs=("feat",),
                      metrics_every_batches=0)
    chunk = 128 << 10
    with InferenceServer(net2, cfg) as s2:
        bfe = BinaryFrontend(s2, port=0, chunk_bytes=chunk)
        cli = BinaryClient(*bfe.address, timeout=60.0)
        try:
            from sparknet_tpu.serve.server import net_input_specs
            shape, dt = net_input_specs(net2)["data"]
            r = np.random.default_rng(1)
            req = {"data": r.standard_normal(shape).astype(dt)}
            full = cli.infer(req, model="featurizer", deadline_s=60.0)
            streamed = cli.infer(req, model="featurizer",
                                 deadline_s=60.0, stream=True)
            assert streamed["feat"].nbytes > 1 << 20  # genuinely multi-MB
            np.testing.assert_array_equal(streamed["feat"], full["feat"])
            t = cli.last_timing
            assert t["t_first_byte_s"] <= t["t_complete_s"]
            # the bounded-buffer pin: only frame headers are ever copied
            assert bfe.peak_buffered_bytes < chunk, (
                f"per-connection buffering {bfe.peak_buffered_bytes} is "
                f"not bounded by the chunk size {chunk}")
        finally:
            cli.close()
            bfe.stop()


# -- typed error frames -------------------------------------------------------

def test_error_frames_map_to_typed_exceptions(srv):
    bfe = BinaryFrontend(srv, port=0)
    try:
        # unknown model -> 404 frame -> UnknownModelError
        with pytest.raises(UnknownModelError):
            binary_infer(bfe.address, "nope", _example(0),
                         deadline_s=30.0)
        # already-expired deadline -> 503 deadline frame
        with pytest.raises(DeadlineExpiredError):
            binary_infer(bfe.address, "default", _example(0),
                         deadline_s=-1.0)
        # not a net input -> 400 frame -> ValueError, field named
        with pytest.raises(ValueError, match="bogus"):
            binary_infer(bfe.address, "default",
                         {"bogus": np.zeros(3, np.float32)},
                         deadline_s=30.0)
        # the connection survived every typed shed (all on one socket)
        assert bfe.connections == 1
        out = binary_infer(bfe.address, "default", _example(1),
                           deadline_s=30.0)
        assert out["prob"].shape == (10,)
        assert bfe.connections == 1
    finally:
        bfe.stop()


# -- malformed-wire robustness ------------------------------------------------

def _recv_frame(sock, timeout=10.0):
    sock.settimeout(timeout)
    buf = b""
    while len(buf) < wire.HEADER_LEN:
        d = sock.recv(4096)
        if not d:
            return None
        buf += d
    ftype, flags, rid, meta_len, plen = wire.parse_header(buf)
    want = wire.HEADER_LEN + meta_len + plen
    while len(buf) < want:
        d = sock.recv(4096)
        if not d:
            return None
        buf += d
    return ftype, flags, rid, buf[wire.HEADER_LEN:
                                  wire.HEADER_LEN + meta_len]


def _serves_fine(bfe):
    out = binary_infer(bfe.address, "default", _example(9),
                       deadline_s=30.0)
    assert out["prob"].shape == (10,)


def test_bad_magic_answered_typed_then_closed(srv):
    bfe = BinaryFrontend(srv, port=0)
    try:
        s = socket.create_connection(bfe.address, timeout=10)
        s.sendall(b"JUNKJUNKJUNKJUNK" + b"\0" * 16)
        ftype, flags, rid, meta = _recv_frame(s)
        assert ftype == wire.T_ERROR and rid == 0
        code, kind, msg = wire.unpack_error_meta(meta)
        assert (code, kind) == (400, "bad_magic")
        assert s.recv(4096) == b""  # server closed THIS connection
        s.close()
        _serves_fine(bfe)  # ...and only this one
    finally:
        bfe.stop()


def test_bad_version_answered_typed(srv):
    bfe = BinaryFrontend(srv, port=0)
    try:
        head, _ = wire.pack_request(1, "default", {})
        # version 3 is the PRE-TRACE wire (no trace field in the REQUEST
        # meta, this PR's bump): an old peer must get the typed frame,
        # not a silent close or a garbled meta decode
        for bad in (42, wire.VERSION - 1):
            s = socket.create_connection(bfe.address, timeout=10)
            s.sendall(head[:4] + bytes([bad]) + head[5:])
            ftype, flags, rid, meta = _recv_frame(s)
            code, kind, _ = wire.unpack_error_meta(meta)
            assert ftype == wire.T_ERROR and (code, kind) == \
                (400, "bad_version")
            assert s.recv(4096) == b""
            s.close()
        _serves_fine(bfe)
    finally:
        bfe.stop()


def test_oversized_frame_is_the_413_analog(srv):
    """A frame whose announced size exceeds the cap: typed too_large
    error frame carrying the REQUEST id, that connection alone closed,
    server keeps serving."""
    bfe = BinaryFrontend(srv, port=0, max_frame_bytes=1 << 20)
    try:
        hdr = wire.HEADER.pack(wire.MAGIC, wire.VERSION, wire.T_REQUEST,
                               0, 77, 0, (1 << 20) + 1)
        s = socket.create_connection(bfe.address, timeout=10)
        s.sendall(hdr)
        ftype, flags, rid, meta = _recv_frame(s)
        assert ftype == wire.T_ERROR and rid == 77
        code, kind, _ = wire.unpack_error_meta(meta)
        assert (code, kind) == (413, "too_large")
        assert s.recv(4096) == b""
        s.close()
        _serves_fine(bfe)
    finally:
        bfe.stop()


def test_truncated_header_and_midstream_disconnect(srv):
    """A client that dies mid-frame (10 header bytes) or mid-streamed-
    reply costs the server nothing but that connection."""
    bfe = BinaryFrontend(srv, port=0)
    try:
        # truncated header, then vanish
        s = socket.create_connection(bfe.address, timeout=10)
        head, _ = wire.pack_request(1, "default", _example(0))
        s.sendall(head[:10])
        s.close()
        # full request submitted, client vanishes before reading the
        # reply (the write path eats the reset, not the io thread)
        s2 = socket.create_connection(bfe.address, timeout=10)
        head2, views2 = wire.pack_request(2, "default", _example(1),
                                          deadline_ms=30000.0,
                                          stream=True)
        s2.sendall(head2)
        for v in views2:
            s2.sendall(v)
        s2.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                      struct.pack("ii", 1, 0))  # RST on close
        s2.close()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                _serves_fine(bfe)
                break
            except ConnectionError:
                time.sleep(0.05)
        _serves_fine(bfe)
        # every io thread is still alive
        assert all(lp.is_alive() for lp in bfe._loops)
    finally:
        bfe.stop()


def test_over_capacity_is_typed_no_replica_not_a_reset(srv):
    """A connection past the cap gets the TYPED over_capacity frame —
    delivered reliably (the server drains instead of closing into the
    client's mid-send request, which would RST the answer away) and
    mapped to NoReplicaError exactly as HTTP's 503 would be. The
    under-cap connection keeps serving."""
    bfe = BinaryFrontend(srv, port=0, max_connections=1)
    try:
        cli = BinaryClient(*bfe.address)
        out = cli.infer(_example(0), model="default", deadline_s=30.0)
        assert out["prob"].shape == (10,)
        for i in range(3):  # reliably typed, not a coin-flip reset
            with pytest.raises(NoReplicaError, match="capacity"):
                binary_infer(bfe.address, "default", _example(i),
                             deadline_s=10.0)
        assert bfe.rejected_over_cap == 3
        # the under-cap connection still serves
        out = cli.infer(_example(1), model="default", deadline_s=30.0)
        assert out["prob"].shape == (10,)
        cli.close()
    finally:
        bfe.stop()


# -- per-tenant admission -----------------------------------------------------

def test_binary_tenant_field_shed_typed(srv):
    """The frame tenant field feeds the same token buckets the HTTP
    X-Tenant header does: a flood past the rate sheds typed
    (tenant_limit, a QueueFullError subclass) and the shed counter
    carries reason="tenant_limit"."""
    bfe = BinaryFrontend(srv, port=0,
                         tenants=TenantAdmission(rate_rps=5.0, burst=2))
    try:
        ok, shed = 0, 0
        for i in range(10):
            try:
                binary_infer(bfe.address, "default", _example(i),
                             deadline_s=30.0, tenant="hot")
                ok += 1
            except TenantLimitError:
                shed += 1
        assert ok >= 2 and shed > 0  # burst served, flood shed
        c = srv.registry.counter("sparknet_serve_shed_total",
                                 labels=("model", "reason"))
        assert c.value(model="default", reason="tenant_limit") == shed
    finally:
        bfe.stop()


# -- router integration -------------------------------------------------------

def test_router_remote_replica_over_binary_transport(net):
    """`add_remote_replica(..., "spkn://...")` proxies over the binary
    wire: drain the local replica and traffic keeps flowing through the
    remote router's BinaryFrontend, zero dropped."""
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    rb = ModelRouter(RouterConfig(workers=1))
    rb.add_model("m", JaxNet(lenet(batch=4)), cfg=cfg)
    ra = ModelRouter(RouterConfig(workers=1))
    ra.add_model("m", net, cfg=cfg)
    with rb:
        fe_b = BinaryFrontend(rb, port=0)
        with ra:
            rep = ra.add_remote_replica(
                "m", f"spkn://{fe_b.address[0]}:{fe_b.address[1]}")
            assert rep.transport == "binary"
            ra.infer("m", _example(0), timeout=30.0)  # local, compiles
            ra.drain("m", "local:m")
            outs = [ra.infer("m", _example(i), timeout=30.0)
                    for i in range(5)]
            for out in outs:
                p = np.asarray(out["prob"])
                assert p.shape == (10,) and np.isfinite(p).all()
            routed = ra.registry.counter(
                "sparknet_serve_routed_total",
                labels=("model", "replica"))
            assert routed.value(
                model="m", replica=rep.name) >= 5
            # the remote hop really rode the binary wire
            assert fe_b.requests >= 5
        fe_b.stop()


def test_serve_cli_binary_port_demo(tmp_path, capsys):
    """`sparknet-serve --binary-port 0 --demo`: the binary front door
    starts alongside the server and shuts down cleanly."""
    from sparknet_tpu.serve.app import main
    main(["--model", "lenet", "--outputs", "prob", "--max-batch", "4",
          "--binary-port", "0", "--tenant-rate", "1000",
          "--demo", "4", "--workdir", str(tmp_path)])
    import json
    status = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert status["requests_ok"] == 4
