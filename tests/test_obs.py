"""Unified telemetry (sparknet_tpu.obs): registry + Prometheus exposition
golden, Chrome-trace validity, registry concurrency under a live scraper,
the train-side /metrics status server, per-round breakdown rows, the
wall-clock ts field, bench metadata stamps, and the sparknet-metrics
summarizer."""
import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from sparknet_tpu.obs import (MetricsRegistry, StatusServer, run_metadata,
                              trace as obs_trace)
from sparknet_tpu.obs.summary import main as summary_main
from sparknet_tpu.utils.logger import Logger


# -- Prometheus exposition golden (the name/type/label schema is a
#    compatibility surface: scrapers and dashboards key on it) --------------

def test_prometheus_exposition_golden():
    reg = MetricsRegistry()
    c = reg.counter("sparknet_test_requests_total", "requests by outcome",
                    labels=("outcome",))
    c.inc(outcome="ok")
    c.inc(2, outcome="failed")
    g = reg.gauge("sparknet_test_queue_depth", "queued requests")
    g.set(3)
    h = reg.histogram("sparknet_test_latency_seconds", "latency",
                      buckets=(0.3, 1.0))
    for v in (0.25, 0.5, 4.0):
        h.observe(v)
    expected = (
        '# HELP sparknet_test_latency_seconds latency\n'
        '# TYPE sparknet_test_latency_seconds histogram\n'
        'sparknet_test_latency_seconds_bucket{le="0.3"} 1\n'
        'sparknet_test_latency_seconds_bucket{le="1"} 2\n'
        'sparknet_test_latency_seconds_bucket{le="+Inf"} 3\n'
        'sparknet_test_latency_seconds_sum 4.75\n'
        'sparknet_test_latency_seconds_count 3\n'
        '# HELP sparknet_test_queue_depth queued requests\n'
        '# TYPE sparknet_test_queue_depth gauge\n'
        'sparknet_test_queue_depth 3\n'
        '# HELP sparknet_test_requests_total requests by outcome\n'
        '# TYPE sparknet_test_requests_total counter\n'
        'sparknet_test_requests_total{outcome="failed"} 2\n'
        'sparknet_test_requests_total{outcome="ok"} 1\n')
    assert reg.render_prometheus() == expected


def test_registry_label_escaping_and_callback_gauge():
    reg = MetricsRegistry()
    g = reg.gauge("g", labels=("path",))
    g.set(1, path='a"b\\c\nd')
    reg.gauge("live").set_fn(lambda: 7)
    text = reg.render_prometheus()
    assert r'g{path="a\"b\\c\nd"} 1' in text
    assert "live 7" in text
    # a callback that raises drops its sample, never the scrape
    reg.gauge("broken").set_fn(lambda: 1 / 0)
    assert "live 7" in reg.render_prometheus()


def test_registry_kind_conflict_rejected():
    reg = MetricsRegistry()
    reg.counter("m", labels=("a",))
    with pytest.raises(ValueError):
        reg.gauge("m", labels=("a",))
    with pytest.raises(ValueError):
        reg.counter("m", labels=("b",))
    # idempotent get-or-create returns the same family
    assert reg.counter("m", labels=("a",)) is reg.counter("m",
                                                          labels=("a",))
    c = reg.counter("m", labels=("a",))
    c.inc(2, a="x")
    assert c.value(a="x") == 2 and c.value(a="y") is None
    # value() on a raising callback drops the sample, like snapshot()
    g = reg.gauge("cb")
    g.set_fn(lambda: 1 / 0)
    assert g.value() is None


# -- concurrency: N writers hammer the registry while a reader scrapes ------

def test_registry_concurrent_writers_vs_scraper():
    reg = MetricsRegistry()
    c = reg.counter("hammer_total", labels=("worker",))
    h = reg.histogram("hammer_seconds", buckets=(0.5,))
    g = reg.gauge("hammer_gauge")
    n_threads, per = 8, 2000
    stop = threading.Event()
    scrapes = []

    def scraper():
        while not stop.is_set():
            text = reg.render_prometheus()
            snap = reg.snapshot()
            # a scrape mid-hammer must be internally consistent:
            # histogram count == sum of its bucket counts (all
            # observations land in the 0.5 bucket here)
            v = snap["hammer_seconds"]["values"].get(())
            if v is not None:
                assert v["count"] == sum(v["buckets"])
            scrapes.append(len(text))

    def writer(i):
        for _ in range(per):
            c.inc(worker=str(i))
            h.observe(0.25)
            g.set(i)

    ts = [threading.Thread(target=writer, args=(i,))
          for i in range(n_threads)]
    sc = threading.Thread(target=scraper)
    sc.start()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    stop.set()
    sc.join()
    assert scrapes, "scraper never ran"
    # nothing lost: every inc/observe landed exactly once
    snap = reg.snapshot()
    totals = snap["hammer_total"]["values"]
    assert all(totals[(str(i),)] == per for i in range(n_threads))
    assert snap["hammer_seconds"]["values"][()]["count"] == n_threads * per


def test_latency_stats_concurrent_summary():
    """The old live-attribute read path could sort a deque mid-append
    (RuntimeError) or mix windows; the locked summary cannot."""
    from sparknet_tpu.utils.metrics import LatencyStats

    ls = LatencyStats(window=256)
    stop = threading.Event()
    errs = []

    def reader():
        try:
            while not stop.is_set():
                s = ls.summary()
                if s["n"]:
                    assert s["p50_ms"] is not None
        except Exception as e:  # pragma: no cover - the failure we pin
            errs.append(e)

    t = threading.Thread(target=reader)
    t.start()
    for i in range(20000):
        ls.add(i * 1e-6)
    stop.set()
    t.join()
    assert not errs


# -- StatusServer ------------------------------------------------------------

def test_status_server_endpoints():
    reg = MetricsRegistry()
    reg.counter("sparknet_x_total").inc(5)
    srv = StatusServer(0, reg, healthz=lambda: (False, {"why": "testing"}),
                       status=lambda: {"role": "test"})
    try:
        host, port = srv.address
        resp = urllib.request.urlopen(f"http://{host}:{port}/metrics",
                                      timeout=10)
        assert resp.headers["Content-Type"].startswith("text/plain")
        assert "sparknet_x_total 5" in resp.read().decode()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"http://{host}:{port}/healthz",
                                   timeout=10)
        assert ei.value.code == 503
        s = json.loads(urllib.request.urlopen(
            f"http://{host}:{port}/status", timeout=10).read())
        assert s == {"role": "test"}
    finally:
        srv.stop()


# -- tracer ------------------------------------------------------------------

def test_span_noop_when_off():
    assert obs_trace.active_tracer() is None
    with obs_trace.span("nothing"):
        pass  # must not raise, must not record anywhere


def test_tracer_events_and_lanes(tmp_path):
    out = tmp_path / "t.json"
    with obs_trace.tracing(str(out)) as tr:
        with obs_trace.span("outer", round=1):
            with obs_trace.span("inner"):
                pass

        def worker():
            with obs_trace.span("worker_side"):
                pass
        th = threading.Thread(target=worker, name="lane-two")
        th.start()
        th.join()
        tr.instant("mark", k="v")
    data = json.loads(out.read_text())
    evs = data["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"outer", "inner", "worker_side"}
    for e in xs:
        assert {"ph", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["dur"] >= 0
    # two distinct lanes, both named via thread_name metadata
    assert len({e["tid"] for e in xs}) == 2
    names = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "lane-two" in names
    assert any(e["ph"] == "i" and e["name"] == "mark" for e in evs)


# -- the full train-side loop: /metrics + trace + breakdown + ts ------------

@pytest.fixture(scope="module")
def trained(tmp_path_factory):
    """One tiny training run with full telemetry: checkpointing (async
    writer lane), status server, trace capture, metrics JSONL."""
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.zoo import lenet

    root = str(tmp_path_factory.mktemp("obs_train"))
    r = np.random.default_rng(0)
    n, b, tau = 256, 16, 2
    ds = ArrayDataset({
        "data": r.standard_normal((n, 1, 28, 28)).astype(np.float32),
        "label": r.integers(0, 10, (n, 1)).astype(np.int32)})
    jsonl = os.path.join(root, "m.jsonl")
    cfg = RunConfig(model="lenet", n_devices=1, local_batch=b, tau=tau,
                    max_rounds=4, eval_every=0, workdir=root,
                    checkpoint_dir=os.path.join(root, "ck"),
                    checkpoint_every=2, status_port=0,
                    trace_out=os.path.join(root, "trace.json"))
    scraped = {}

    def hook(rnd, state):
        if rnd == 2:
            host, port = cfg.status_address
            scraped["metrics"] = urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=10).read().decode()
            scraped["healthz"] = json.loads(urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=10).read())

    log = Logger(os.path.join(root, "l.txt"), echo=False, jsonl_path=jsonl)
    train(cfg, lenet(batch=b), ds, None, logger=log, round_hook=hook)
    log.close()
    return {"cfg": cfg, "jsonl": jsonl, "scraped": scraped, "root": root}


def test_train_metrics_endpoint_schema(trained):
    text = trained["scraped"]["metrics"]
    # shared-schema names the serve side also exports from ITS registry
    assert "sparknet_build_info{" in text
    for name in ("sparknet_train_rounds_total",
                 "sparknet_train_loss",
                 "sparknet_train_images_per_sec_per_chip",
                 'sparknet_train_phase_seconds_total{phase="sample"}',
                 'sparknet_train_phase_seconds_total{phase="h2d"}',
                 'sparknet_train_phase_seconds_total{phase="dispatch"}',
                 "sparknet_health_rounds_total",
                 "sparknet_checkpoint_writes_total"):
        assert name in text, f"missing {name} in train /metrics"
    assert trained["scraped"]["healthz"]["status"] == "ok"


def test_trace_file_valid_with_expected_lanes(trained):
    data = json.load(open(trained["cfg"].trace_out))
    evs = data["traceEvents"]
    assert evs, "empty trace"
    for e in evs:
        assert "ph" in e and "pid" in e
        if e["ph"] != "M":
            assert "ts" in e and "tid" in e
    lanes = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "thread_name"}
    # the three host threads of a checkpointing training run
    assert any(n == "MainThread" for n in lanes)
    assert any(n.startswith("round-prep") for n in lanes), lanes
    assert any(n.startswith("ckpt-write") for n in lanes), lanes
    spans = {e["name"] for e in evs if e["ph"] == "X"}
    assert {"sample", "train_round", "round_prep",
            "checkpoint_write"} <= spans


def test_jsonl_breakdown_and_ts(trained):
    rows = [json.loads(l) for l in open(trained["jsonl"])]
    loss_rows = [r for r in rows if "loss" in r]
    assert loss_rows
    import time as _time
    now = _time.time()
    for r in loss_rows:
        # wall-clock epoch ts on every record (cross-process merge key)
        assert now - 3600 < r["ts"] <= now
        for fld in ("t_data_ms", "t_h2d_ms", "t_round_ms",
                    "t_collect_ms", "t_ckpt_fetch_ms", "t_log_ms"):
            assert fld in r and r[fld] >= 0
    # the round after a checkpoint round carries its stage-1 fetch stall
    assert any(r["t_ckpt_fetch_ms"] > 0 for r in loss_rows)


def test_serve_trace_has_worker_lane(tmp_path):
    """The serve half of the cross-thread picture: forwards on the
    serve-worker lane."""
    from sparknet_tpu.net_api import JaxNet
    from sparknet_tpu.serve import InferenceServer, ServeConfig
    from sparknet_tpu.zoo import lenet

    out = tmp_path / "serve_trace.json"
    net = JaxNet(lenet(batch=4))
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with obs_trace.tracing(str(out)):
        with InferenceServer(net, cfg) as srv:
            srv.infer({"data": np.zeros((28, 28, 1), np.float32)})
    data = json.loads(out.read_text())
    evs = data["traceEvents"]
    lanes = {e["args"]["name"] for e in evs
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "serve-worker" in lanes
    assert any(e["ph"] == "X" and e["name"] == "forward" for e in evs)


def test_telemetry_off_is_clean(tmp_path):
    """cfg.telemetry=False: no breakdown fields, no registry, no status
    attr — the bench's control arm."""
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.zoo import lenet

    r = np.random.default_rng(0)
    ds = ArrayDataset({
        "data": r.standard_normal((128, 1, 28, 28)).astype(np.float32),
        "label": r.integers(0, 10, (128, 1)).astype(np.int32)})
    jsonl = str(tmp_path / "m.jsonl")
    cfg = RunConfig(model="lenet", n_devices=1, local_batch=16, tau=1,
                    max_rounds=2, eval_every=0, workdir=str(tmp_path),
                    telemetry=False)
    log = Logger(str(tmp_path / "l.txt"), echo=False, jsonl_path=jsonl)
    train(cfg, lenet(batch=16), ds, None, logger=log)
    log.close()
    rows = [json.loads(l) for l in open(jsonl)]
    assert rows and all("t_round_ms" not in r for r in rows)
    assert all("ts" in r for r in rows)  # the merge key stays


# -- run metadata + summary tool --------------------------------------------

def test_run_metadata_fields():
    m = run_metadata()
    for k in ("ts", "python", "git_rev", "jax_version", "backend",
              "device_kind", "n_devices"):
        assert k in m, m


def test_bench_obs_artifact_stamped():
    """BENCH artifacts carry the run_metadata stamp (attribution
    satellite). Checked against the committed BENCH_OBS.json."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_OBS.json")
    if not os.path.exists(path):
        pytest.skip("BENCH_OBS.json not generated yet")
    art = json.load(open(path))
    assert art["meta"]["jax_version"]
    assert art["meta"]["backend"]
    assert "git_rev" in art["meta"]
    assert art["headline"]["value"] <= 0.02  # the acceptance bound


def test_metrics_summary_cli(trained, capsys):
    rc = summary_main([trained["jsonl"]])
    assert rc == 0
    out = capsys.readouterr().out
    assert "loss tail:" in out
    assert "step-time breakdown" in out
    assert "round" in out


def test_metrics_summary_events_and_json(tmp_path, capsys):
    """Event audit trail + --json machine output + multi-file ts merge."""
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    la = Logger(None, echo=False, jsonl_path=a)
    lb = Logger(None, echo=False, jsonl_path=b)
    la.metrics(0, loss=2.0)
    lb.event(1, "rollback", reason="nonfinite", target_step=0)
    la.metrics(2, loss=1.0, t_data_ms=1.5, t_round_ms=20.0)
    la.close()
    lb.close()
    rc = summary_main(["--json", a, b])
    assert rc == 0
    s = json.loads(capsys.readouterr().out)
    assert s["rounds"] == 2 and s["events"] == 1
    assert s["event_trail"][0]["event"] == "rollback"
    assert s["loss_final"] == 1.0
    assert s["step_time_breakdown"]["t_round_ms"]["mean_ms"] == 20.0
