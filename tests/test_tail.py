"""Tail-latency engineering (router hedging + coalesced formation +
the observability satellites):

  - hedged requests: exactly-once delivery (N submits -> N results even
    when both legs answer), the hedge budget cap, the under-drain
    fallback (no second healthy replica -> the primary stands alone,
    zero dropped), and the admission-pressure gate.
  - coalesced batch formation: one focus replica per window, focus
    ROTATES across windows (fairness), inactive on high fill or no
    fill signal.
  - LatencyStats windowed memory is bounded (count window AND age
    horizon) with exact order statistics over what remains.
  - queue-wait surfaces on both wires: X-Queue-Wait-Ms on HTTP,
    `last_timing["queue_wait_ms"]` on the binary client.
  - the request journal: one JSONL row per request on both frontends,
    off by default, per-row overhead pinned.

Tier-1: CPU backend, lenet shapes, ephemeral ports.
"""
import http.client
import json
import time

import numpy as np
import pytest

from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.serve import (BinaryClient, BinaryFrontend,
                                HttpFrontend, InferenceServer,
                                ModelRouter, Replica, RouterConfig,
                                ServeConfig, UnknownModelError)
from sparknet_tpu.serve.http_frontend import (NPZ_CONTENT_TYPE,
                                              _encode_npz)
from sparknet_tpu.utils.logger import Logger
from sparknet_tpu.utils.metrics import LatencyStats
from sparknet_tpu.zoo import lenet


def _example(i: int) -> dict:
    r = np.random.default_rng(5000 + i)
    return {"data": r.standard_normal((28, 28, 1)).astype(np.float32)}


def _mk_replica(model: str = "m"):
    net = JaxNet(lenet(batch=4))
    cfg = ServeConfig(model_name=model, max_batch=4, max_wait_ms=2.0,
                      outputs=("prob",), metrics_every_batches=0)
    s = InferenceServer(net, cfg)
    s.start()
    fe = BinaryFrontend(s, port=0)
    return s, fe


@pytest.fixture()
def two_replicas():
    s1, fe1 = _mk_replica()
    s2, fe2 = _mk_replica()
    yield fe1, fe2
    fe1.stop()
    fe2.stop()
    s1.stop()
    s2.stop()


def _router_over(fes, **cfg_kw):
    router = ModelRouter(RouterConfig(workers=4, **cfg_kw))
    for fe in fes:
        router.add_remote_replica(
            "m", f"spkn://127.0.0.1:{fe.address[1]}")
    router.start()
    return router


# -- hedging ------------------------------------------------------------------

def test_hedge_exactly_once_under_max_pressure_to_hedge(two_replicas):
    """min-delay 0 fires the hedge decision immediately on every
    request: near-every request grows a second leg, yet every submit
    resolves EXACTLY one result (first-resolution-wins) and the hedged
    counter never exceeds routed."""
    router = _router_over(two_replicas, hedge=True,
                          hedge_min_delay_ms=0.0, hedge_budget=1.0)
    try:
        futs = [router.submit("m", _example(i), deadline_s=30.0)
                for i in range(24)]
        outs = [f.result(timeout=30.0) for f in futs]
        assert len(outs) == 24
        for out in outs:
            p = np.asarray(out["prob"])
            assert p.shape[-1] == 10 and np.isfinite(p).all()
        hg = router.status()["hedging"]["m"]
        assert hg["routed"] == 24
        assert 0 < hg["hedged"] <= hg["routed"]
        # the metered counter agrees with the status rollup
        c = router.registry.counter("sparknet_serve_hedged_total",
                                    labels=("model", "won"))
        won = ((c.value(model="m", won="primary") or 0.0)
               + (c.value(model="m", won="hedge") or 0.0))
        assert won == hg["hedged"]
    finally:
        router.stop()


def test_hedge_budget_caps_second_legs(two_replicas):
    router = _router_over(two_replicas, hedge=True,
                          hedge_min_delay_ms=0.0, hedge_budget=0.2)
    try:
        futs = [router.submit("m", _example(i), deadline_s=30.0)
                for i in range(40)]
        for f in futs:
            f.result(timeout=30.0)
        hg = router.status()["hedging"]["m"]
        assert hg["routed"] == 40
        assert hg["hedged"] <= 0.2 * hg["routed"]
    finally:
        router.stop()


def test_hedge_under_drain_primary_stands_alone(two_replicas):
    """With the only other replica draining, the hedge decision finds
    no second target: every request still completes on the primary,
    zero dropped, zero hedged."""
    router = _router_over(two_replicas, hedge=True,
                          hedge_min_delay_ms=0.0, hedge_budget=1.0)
    try:
        reps = router.replicas["m"]
        reps[1].drain()
        futs = [router.submit("m", _example(i), deadline_s=30.0)
                for i in range(10)]
        outs = [f.result(timeout=30.0) for f in futs]
        assert all(np.asarray(o["prob"]).shape[-1] == 10 for o in outs)
        hg = router.status()["hedging"]["m"]
        assert hg["routed"] == 10 and hg["hedged"] == 0
    finally:
        router.stop()


def test_hedge_disabled_under_admission_pressure(two_replicas):
    """A shedding fleet must not grow extra request copies: with the
    pressure signal up, the fire-time gate skips every hedge."""
    router = _router_over(two_replicas, hedge=True,
                          hedge_min_delay_ms=0.0, hedge_budget=1.0)
    try:
        router._pressure = lambda: 0.7  # the admission door's signal
        futs = [router.submit("m", _example(i), deadline_s=30.0)
                for i in range(10)]
        for f in futs:
            f.result(timeout=30.0)
        hg = router.status()["hedging"]["m"]
        assert hg["routed"] == 10 and hg["hedged"] == 0
    finally:
        router.stop()


# -- coalesced formation ------------------------------------------------------

def _stub_reps(n: int, fill):
    return [Replica(f"r{i}", url=f"spkn://h{i}:1", transport="binary",
                    health_fn=lambda: True, fill_fn=fill)
            for i in range(n)]


def test_coalesce_one_focus_per_window_rotating_fairly():
    router = ModelRouter(RouterConfig(
        workers=1, coalesce=True, coalesce_window_ms=10.0,
        coalesce_fill_threshold=0.5))
    reps = _stub_reps(3, lambda: 0.1)
    focus_seq = []
    for _ in range(6):
        picks = set()
        t_end = time.monotonic() + 0.008
        while time.monotonic() < t_end:
            rep = router._coalesce_pick("m", reps)
            assert rep is not None
            picks.add(rep.name)
        assert len(picks) == 1, picks  # ONE focus inside a window
        focus_seq.append(picks.pop())
        time.sleep(0.004)  # cross the window boundary
    # fairness: over 2n windows every replica led at least once, in
    # rotation order
    assert len(set(focus_seq)) == 3, focus_seq
    assert focus_seq[:3] != [focus_seq[0]] * 3


def test_coalesce_inactive_on_high_fill_or_no_signal():
    router = ModelRouter(RouterConfig(
        workers=1, coalesce=True, coalesce_window_ms=10.0,
        coalesce_fill_threshold=0.5))
    # well-filled replicas: round-robin stands
    assert router._coalesce_pick("m", _stub_reps(3, lambda: 0.9)) is None
    # no replica reports a signal: coalescing never triggers blind
    assert router._coalesce_pick("m2", _stub_reps(3, None)) is None


def test_coalesce_skips_unroutable_focus():
    """A drained replica is never chosen as focus; the rotation walks
    past it."""
    router = ModelRouter(RouterConfig(
        workers=1, coalesce=True, coalesce_window_ms=5.0,
        coalesce_fill_threshold=0.5))
    reps = _stub_reps(3, lambda: 0.1)
    reps[1].drain()
    leads = set()
    for _ in range(6):
        rep = router._coalesce_pick("m", reps)
        assert rep is not None and rep.name != "r1"
        leads.add(rep.name)
        time.sleep(0.007)
    assert leads == {"r0", "r2"}


# -- LatencyStats memory bound ------------------------------------------------

def test_latency_stats_bounded_at_window_exact_order_stats():
    st = LatencyStats(window=10_000)
    for i in range(25_000):
        st.add(float(i))
    assert len(st._obs) == 10_000  # bounded: only the last window
    assert st.count == 25_000      # ...but the lifetime count survives
    assert st.quantile(0.0) == 15_000.0
    assert st.quantile(1.0) == 24_999.0
    mid = st.quantile(0.5)
    assert 19_900.0 <= mid <= 20_100.0


def test_latency_stats_age_horizon_prunes_stale():
    st = LatencyStats(window=1000, max_age_s=0.05)
    for _ in range(50):
        st.add(1.0)
    time.sleep(0.12)
    st.add(5.0)  # the add prunes everything past the horizon
    assert len(st._obs) == 1
    assert st.quantile(0.5) == 5.0


# -- queue-wait on the wire ---------------------------------------------------

def test_queue_wait_surfaces_on_both_wires():
    net = JaxNet(lenet(batch=4))
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        bfe = BinaryFrontend(srv, port=0)
        hfe = HttpFrontend(srv, port=0)
        cli = BinaryClient(*bfe.address, use_shm=False)
        try:
            cli.infer(_example(0), model="default", deadline_s=30.0)
            qw = cli.last_timing["queue_wait_ms"]
            assert qw is not None and 0.0 <= qw < 60_000.0
            # HTTP: the X-Queue-Wait-Ms response header
            conn = http.client.HTTPConnection(*hfe.address, timeout=30)
            conn.request(
                "POST", "/v1/models/default/infer",
                body=_encode_npz(_example(1)),
                headers={"Content-Type": NPZ_CONTENT_TYPE,
                         "Accept": NPZ_CONTENT_TYPE,
                         "X-Deadline-Ms": "30000"})
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 200
            hdr = resp.getheader("X-Queue-Wait-Ms")
            assert hdr is not None and 0.0 <= float(hdr) < 60_000.0
            conn.close()
        finally:
            cli.close()
            bfe.stop()
            hfe.stop()


# -- the request journal ------------------------------------------------------

def test_request_journal_rows_both_frontends(tmp_path):
    net = JaxNet(lenet(batch=4))
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    jpath = tmp_path / "journal.jsonl"
    journal = Logger(jsonl_path=str(jpath), echo=False)
    with InferenceServer(net, cfg) as srv:
        bfe = BinaryFrontend(srv, port=0, journal=journal)
        hfe = HttpFrontend(srv, port=0, journal=journal)
        cli = BinaryClient(*bfe.address, use_shm=False)
        try:
            cli.infer(_example(0), model="default", deadline_s=30.0,
                      tenant="t1")
            with pytest.raises(UnknownModelError):
                cli.infer(_example(1), model="nope", deadline_s=30.0)
            conn = http.client.HTTPConnection(*hfe.address, timeout=30)
            conn.request(
                "POST", "/v1/models/default/infer",
                body=_encode_npz(_example(2)),
                headers={"Content-Type": NPZ_CONTENT_TYPE,
                         "Accept": NPZ_CONTENT_TYPE})
            conn.getresponse().read()
            conn.close()
        finally:
            cli.close()
            bfe.stop()
            hfe.stop()
    journal.close()
    rows = [json.loads(l) for l in
            jpath.read_text().strip().splitlines()]
    assert all(r["kind"] == "request" for r in rows)
    by_transport = {}
    for r in rows:
        by_transport.setdefault(r["transport"], []).append(r)
    ok_bin = [r for r in by_transport["binary"]
              if r["outcome"] == "ok"]
    assert len(ok_bin) == 1
    assert ok_bin[0]["model"] == "default"
    assert ok_bin[0]["tenant"] == "t1"
    assert ok_bin[0]["sizes"] == {"data": 28 * 28 * 4}
    assert ok_bin[0]["queue_wait_ms"] >= 0.0
    # the typed shed is journaled with its reason, not dropped
    assert any(r["outcome"] != "ok" for r in by_transport["binary"])
    assert len(by_transport["http"]) == 1
    assert by_transport["http"][0]["model"] == "default"


def test_request_journal_off_by_default_and_cheap(tmp_path):
    net = JaxNet(lenet(batch=4))
    cfg = ServeConfig(max_batch=4, max_wait_ms=2.0, outputs=("prob",),
                      metrics_every_batches=0)
    with InferenceServer(net, cfg) as srv:
        bfe = BinaryFrontend(srv, port=0)
        try:
            assert bfe.journal is None  # off unless asked for
            # journaling cost when ON: bounded per row (line-buffered
            # JSONL append — must stay far under a request's budget)
            journal = Logger(jsonl_path=str(tmp_path / "j.jsonl"),
                             echo=False)
            bfe.journal = journal
            jinfo = {"transport": "binary", "model": "default",
                     "tenant": None, "priority": None,
                     "deadline_ms": 1000.0,
                     "sizes": {"data": 3136}}
            n = 500
            t0 = time.perf_counter()
            for _ in range(n):
                bfe._journal_row(dict(jinfo), "ok", queue_wait_ms=1.0)
            per_row_ms = (time.perf_counter() - t0) / n * 1e3
            journal.close()
            assert per_row_ms < 2.0, per_row_ms
        finally:
            bfe.stop()
