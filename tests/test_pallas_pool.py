"""Pallas maxpool-backward kernel vs oracles (interpreter mode on the CPU
mesh; the real-TPU path was A/B'd on the chip — see PERF.md §pool-backward
for why `auto` dispatch deliberately does NOT select it).

The load-bearing property is TIE ROUTING: Caffe's MaxPoolingLayer and
XLA's select-and-scatter both send each window's gradient to the FIRST
maximum in row-major window order, and ties are common on real data
(post-ReLU zeros). Tests use heavily quantized inputs so nearly every
window has ties."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from sparknet_tpu.ops import pallas_pool as pp
from sparknet_tpu.ops.pooling import pool2d


def _tie_heavy(rng, shape, levels=4):
    return np.maximum(
        rng.integers(-2, levels, shape), 0).astype(np.float32)


def _xla_bwd(x, dy, k, s):
    f = lambda a: lax.reduce_window(a, -jnp.inf, lax.max, (1, k, k, 1),
                                    (1, s, s, 1), ((0, 0),) * 4)
    return np.asarray(jax.vjp(f, jnp.asarray(x))[1](jnp.asarray(dy))[0])


@pytest.mark.parametrize("H,C,k,s", [(13, 8, 3, 2), (12, 8, 2, 2),
                                     (9, 16, 3, 1)])
def test_kernel_matches_oracle_and_xla(rng, H, C, k, s):
    N = 128
    x = _tie_heavy(rng, (N, H, H, C))
    OH = (H - k) // s + 1
    dy = rng.standard_normal((N, OH, OH, C)).astype(np.float32)
    assert pp.pallas_maxpool_supported(x.shape, x.dtype, k, s, 0)

    f = lambda a: pp.maxpool_pallas(a, k, s, True)  # interpret mode
    y, vjp = jax.vjp(f, jnp.asarray(x))
    (dx,) = vjp(jnp.asarray(dy))

    want_y = lax.reduce_window(jnp.asarray(x), -jnp.inf, lax.max,
                               (1, k, k, 1), (1, s, s, 1), ((0, 0),) * 4)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(want_y))
    oracle = pp.maxpool_bwd_reference(x, dy, k, s)
    np.testing.assert_allclose(np.asarray(dx), oracle, atol=1e-5)
    np.testing.assert_allclose(_xla_bwd(x, dy, k, s), oracle, atol=1e-5)


def test_supported_gate():
    ok = pp.pallas_maxpool_supported
    assert ok((128, 13, 13, 8), np.float32, 3, 2, 0)
    assert not ok((100, 13, 13, 8), np.float32, 3, 2, 0)   # N % 128
    assert not ok((128, 13, 13, 5), np.float32, 3, 2, 0)   # C % sublanes
    assert not ok((128, 13, 13, 8), np.float32, 3, 2, 1)   # pad
    assert not ok((128, 32, 32, 8), np.float32, 3, 2, 0)   # ceil end-pad
    assert not ok((128, 2, 2, 8), np.float32, 3, 2, 0)     # tiny


def test_pool2d_impl_pallas_rejects_unsupported(rng):
    x = jnp.asarray(rng.standard_normal((4, 8, 8, 8)).astype(np.float32))
    with pytest.raises(ValueError, match="impl='pallas' unsupported"):
        pool2d(x, "MAX", 3, 2, 0, impl="pallas")  # CPU backend + N%128


def test_pool2d_auto_is_xla_everywhere():
    """`auto` must stay on reduce_window (the kernel measured -10% end to
    end, PERF.md); this pins the dispatch so a refactor doesn't silently
    flip it back on."""
    import sparknet_tpu.ops.pooling as pooling
    called = []
    orig = pooling._can_pallas_pool
    pooling._can_pallas_pool = lambda *a: called.append(a) or True
    try:
        x = jnp.zeros((128, 13, 13, 8), jnp.float32)
        pool2d(x, "MAX", 3, 2, 0)          # auto
        assert not called                   # never even consulted
    finally:
        pooling._can_pallas_pool = orig


def test_pool2d_impl_validation(rng):
    x = jnp.asarray(rng.standard_normal((4, 8, 8, 8)).astype(np.float32))
    with pytest.raises(ValueError, match="unknown pool impl"):
        pool2d(x, "MAX", 3, 2, 0, impl="palas")
    with pytest.raises(ValueError, match="MAX pooling only"):
        pool2d(x, "AVE", 3, 2, 0, impl="pallas")
