"""Pallas maxpool-backward kernel vs oracles (interpreter mode on the CPU
mesh; the real-TPU path was A/B'd on the chip — see PERF.md §pool-backward
for why `auto` dispatch deliberately does NOT select it).

The load-bearing property is TIE ROUTING: Caffe's MaxPoolingLayer and
XLA's select-and-scatter both send each window's gradient to the FIRST
maximum in row-major window order, and ties are common on real data
(post-ReLU zeros). Tests use heavily quantized inputs so nearly every
window has ties."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from sparknet_tpu.ops import pallas_pool as pp
from sparknet_tpu.ops.pooling import pool2d


def _tie_heavy(rng, shape, levels=4):
    return np.maximum(
        rng.integers(-2, levels, shape), 0).astype(np.float32)


def _xla_bwd(x, dy, k, s):
    f = lambda a: lax.reduce_window(a, -jnp.inf, lax.max, (1, k, k, 1),
                                    (1, s, s, 1), ((0, 0),) * 4)
    return np.asarray(jax.vjp(f, jnp.asarray(x))[1](jnp.asarray(dy))[0])


@pytest.mark.parametrize("H,C,k,s", [(13, 8, 3, 2), (12, 8, 2, 2),
                                     (9, 16, 3, 1)])
def test_kernel_matches_oracle_and_xla(rng, H, C, k, s):
    if not pp.kernel_api_available():
        pytest.skip("pallas pool kernel needs pl.Element (newer jax)")
    N = 128
    x = _tie_heavy(rng, (N, H, H, C))
    OH = (H - k) // s + 1
    dy = rng.standard_normal((N, OH, OH, C)).astype(np.float32)
    assert pp.pallas_maxpool_supported(x.shape, x.dtype, k, s, 0)

    f = lambda a: pp.maxpool_pallas(a, k, s, True)  # interpret mode
    y, vjp = jax.vjp(f, jnp.asarray(x))
    (dx,) = vjp(jnp.asarray(dy))

    want_y = lax.reduce_window(jnp.asarray(x), -jnp.inf, lax.max,
                               (1, k, k, 1), (1, s, s, 1), ((0, 0),) * 4)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(want_y))
    oracle = pp.maxpool_bwd_reference(x, dy, k, s)
    np.testing.assert_allclose(np.asarray(dx), oracle, atol=1e-5)
    np.testing.assert_allclose(_xla_bwd(x, dy, k, s), oracle, atol=1e-5)


def test_supported_gate():
    ok = pp.pallas_maxpool_supported
    assert ok((128, 13, 13, 8), np.float32, 3, 2, 0)
    assert not ok((100, 13, 13, 8), np.float32, 3, 2, 0)   # N % 128
    assert not ok((128, 13, 13, 5), np.float32, 3, 2, 0)   # C % sublanes
    assert not ok((128, 13, 13, 8), np.float32, 3, 2, 1)   # pad
    assert not ok((128, 32, 32, 8), np.float32, 3, 2, 0)   # ceil end-pad
    assert not ok((128, 2, 2, 8), np.float32, 3, 2, 0)     # tiny


def test_pool2d_impl_pallas_rejects_unsupported(rng):
    x = jnp.asarray(rng.standard_normal((4, 8, 8, 8)).astype(np.float32))
    with pytest.raises(ValueError, match="impl='pallas' unsupported"):
        pool2d(x, "MAX", 3, 2, 0, impl="pallas")  # CPU backend + N%128


def test_pool2d_auto_consults_the_gate_and_degrades_to_xla():
    """r6 made `auto` a real dispatch: it consults the full gate (backend/
    kernel-API/shape) and takes the Pallas kernel where it passes —
    `RunConfig.pool_impl="xla"` is the explicit opt-out. This pins both
    halves: the gate IS consulted, and a False answer lands on the XLA
    lowering (never a crash). The r3 'auto stays on select-and-scatter'
    pin this replaces is now the per-deployment config decision, with the
    bench.py --mfu A/B rows as the standing evidence (PERF.md §r6)."""
    import sparknet_tpu.ops.pooling as pooling
    called = []
    orig = pooling._can_pallas_pool
    pooling._can_pallas_pool = lambda *a, **kw: called.append(a) or False
    try:
        x = jnp.zeros((128, 13, 13, 8), jnp.float32)
        y = pool2d(x, "MAX", 3, 2, 0)      # auto
        assert called                       # the gate decides now
        assert y.shape == (128, 6, 6, 8)    # gate said no -> XLA lowering
    finally:
        pooling._can_pallas_pool = orig
    # on this backend/toolchain the real gate answers False (CPU without
    # interpret, or a Pallas too old for the kernel API): auto == xla
    if not pooling._can_pallas_pool(x, 3, 2, 0):
        y_auto = pool2d(x, "MAX", 3, 2, 0)
        y_xla = pool2d(x, "MAX", 3, 2, 0, impl="xla")
        np.testing.assert_array_equal(np.asarray(y_auto), np.asarray(y_xla))


def test_pool2d_impl_xla_never_consults_the_gate():
    """impl='xla' is the documented wholesale opt-out: it must not consult
    the Pallas gate at all (the gate imports the Pallas toolchain — the
    explicit fallback has to work on a jax whose pallas import is
    broken)."""
    import sparknet_tpu.ops.pooling as pooling
    orig = pooling._can_pallas_pool

    def boom(*a, **kw):
        raise AssertionError("gate consulted under impl='xla'")

    pooling._can_pallas_pool = boom
    try:
        x = jnp.zeros((128, 13, 13, 8), jnp.float32)
        y = pool2d(x, "MAX", 3, 2, 0, impl="xla")
        assert y.shape == (128, 6, 6, 8)
    finally:
        pooling._can_pallas_pool = orig


def test_pool2d_auto_off_tpu_never_imports_the_toolchain(monkeypatch):
    """The DEFAULT impl='auto' off-TPU (no interpret) must be as
    import-free as 'xla': the gate's backend check runs before the
    pallas_pool import, so the default path also works on a jax whose
    pallas import is broken."""
    if jax.default_backend() == "tpu":
        pytest.skip("off-TPU contract")
    import builtins
    real_import = builtins.__import__

    def guarded(name, *a, **kw):
        if "pallas_pool" in name:
            raise AssertionError("pallas_pool imported under auto off-TPU")
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", guarded)
    x = jnp.zeros((128, 13, 13, 8), jnp.float32)
    y = pool2d(x, "MAX", 3, 2, 0, impl="auto")
    assert y.shape == (128, 6, 6, 8)


def test_pool2d_impl_validation(rng):
    x = jnp.asarray(rng.standard_normal((4, 8, 8, 8)).astype(np.float32))
    with pytest.raises(ValueError, match="unknown pool impl"):
        pool2d(x, "MAX", 3, 2, 0, impl="palas")
    with pytest.raises(ValueError, match="MAX pooling only"):
        pool2d(x, "AVE", 3, 2, 0, impl="pallas")
