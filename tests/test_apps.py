"""End-to-end app tests on the 8-device CPU mesh with synthetic datasets —
the full-loop integration coverage the reference never had (SURVEY §4)."""
import glob
import json
import os

import numpy as np
import pytest

from sparknet_tpu.data import cifar, mnist, adult
from sparknet_tpu.data.dataset import ArrayDataset
from sparknet_tpu.solver import SolverConfig
from sparknet_tpu.utils import checkpoint as ckpt
from sparknet_tpu.utils.config import RunConfig
from sparknet_tpu.utils.logger import Logger
from sparknet_tpu.apps.train_loop import train, probe_value
from sparknet_tpu.apps.featurizer_app import featurize
from sparknet_tpu.net_api import JaxNet
from sparknet_tpu.zoo import cifar10_quick, lenet


def small_cfg(tmp_path, **kw):
    base = dict(
        solver=SolverConfig(base_lr=0.01, momentum=0.9, weight_decay=0.004,
                            lr_policy="fixed"),
        tau=2, local_batch=4, eval_every=2, eval_batch=32, max_rounds=4,
        workdir=str(tmp_path), seed=0)
    base.update(kw)
    return RunConfig(**base)


def test_cifar_app_loop(tmp_path):
    d = str(tmp_path / "cifar")
    cifar.write_synthetic(d, n_per_file=40)
    loader = cifar.CifarLoader(d)
    train_ds = ArrayDataset(loader.train_batch_dict())
    test_ds = ArrayDataset(loader.test_batch_dict())
    cfg = small_cfg(tmp_path, data_dir=d)
    log_path = str(tmp_path / "log.txt")
    jsonl = str(tmp_path / "m.jsonl")
    state = train(cfg, cifar10_quick(batch=cfg.local_batch), train_ds,
                  test_ds, logger=Logger(log_path, echo=False,
                                         jsonl_path=jsonl))
    # divergence probe is finite, log has the reference's phase messages
    assert np.isfinite(probe_value(
        state, __import__("sparknet_tpu").CompiledNet.compile(
            cifar10_quick(batch=cfg.local_batch))))
    text = open(log_path).read()
    assert "test accuracy" in text and "round loss" in text
    recs = [json.loads(l) for l in open(jsonl)]
    assert any("test_accuracy" in r for r in recs)
    assert any("images_per_sec_per_chip" in r for r in recs)


def test_checkpoint_resume_exact(tmp_path):
    """Stop at round 2, resume, compare against an uninterrupted run —
    states must match exactly (deterministic rng schedule)."""
    d = str(tmp_path / "c2")
    cifar.write_synthetic(d, n_per_file=40)
    loader = cifar.CifarLoader(d)
    train_ds = ArrayDataset(loader.train_batch_dict())

    def run(max_rounds, ckdir, resume):
        cfg = small_cfg(tmp_path, max_rounds=max_rounds, eval_every=0,
                        checkpoint_dir=str(tmp_path / ckdir),
                        checkpoint_every=2, resume=resume)
        return train(cfg, cifar10_quick(batch=cfg.local_batch), train_ds,
                     logger=Logger(echo=False))

    full = run(4, "ck_full", resume=False)
    part = run(2, "ck_part", resume=False)     # writes step-2
    resumed = run(4, "ck_part", resume=True)   # resumes at 2, runs 2 more
    for lname in full.params:
        for pname in full.params[lname]:
            np.testing.assert_allclose(
                np.asarray(resumed.params[lname][pname]),
                np.asarray(full.params[lname][pname]), rtol=1e-6, atol=1e-7,
                err_msg=f"{lname}/{pname}")


def test_mnist_app_learns(tmp_path):
    d = str(tmp_path / "mnist")
    mnist.write_synthetic(d, n_train=256, n_test=64)
    loader = mnist.MnistLoader(d)
    # learnable task: relabel by a simple pixel statistic
    tr = loader.train_batch_dict()
    tr["label"] = (tr["data"].mean((1, 2, 3), keepdims=False)[:, None]
                   > 0).astype(np.int32)
    cfg = small_cfg(tmp_path, max_rounds=3, eval_every=0, local_batch=4,
                    tau=2)
    state = train(cfg, lenet(batch=cfg.local_batch), ArrayDataset(tr),
                  logger=Logger(echo=False))
    assert state is not None


def test_featurizer(tmp_path):
    d = str(tmp_path / "c3")
    cifar.write_synthetic(d, n_per_file=10)
    loader = cifar.CifarLoader(d)
    net = JaxNet(cifar10_quick(batch=5))
    feats = featurize(net, loader.train_batch_dict(), "ip1", 5)
    assert feats.shape == (50, 64)


def test_featurizer_cross_backend_agreement():
    """The SAME weights through both NetInterface impls must produce the
    SAME hidden-blob features (the FeaturizerApp contract: a featurizer
    run can't care which backend served it). zoo.lenet and the reference
    mnist graph share one architecture; copy the graph's variables into
    the layer-IR params (fc1 rows permuted: the layer IR flattens
    Caffe-style C,H,W while the graph flattens H,W,C) and compare the
    post-relu fc features."""
    from sparknet_tpu.backend.builder import build_mnist_graph
    from sparknet_tpu.backend.graph_net import GraphNet

    B = 8
    gnet = GraphNet(build_mnist_graph(batch=B))
    jnet = JaxNet(lenet(batch=B))
    v = {k: np.asarray(a) for k, a in gnet.variables.items()}
    jnet.params["conv1"]["w"] = v["conv1_w"]
    jnet.params["conv1"]["b"] = v["conv1_b"]
    jnet.params["conv2"]["w"] = v["conv2_w"]
    jnet.params["conv2"]["b"] = v["conv2_b"]
    jnet.params["fc1"]["w"] = (
        v["fc1_w"].reshape(7, 7, 64, 512)
        .transpose(2, 0, 1, 3).reshape(7 * 7 * 64, 512))
    jnet.params["fc1"]["b"] = v["fc1_b"]
    jnet.params["fc2"]["w"] = v["fc2_w"]
    jnet.params["fc2"]["b"] = v["fc2_b"]

    r = np.random.default_rng(0)
    batch = {"data": r.standard_normal((B, 28, 28, 1)).astype(np.float32),
             "label": r.integers(0, 10, (B, 1)).astype(np.int32)}
    jf = jnet.forward(batch, blob_names=["fc1"])["fc1"]
    gf = gnet.forward(batch, blob_names=["relu3"])["relu3"]
    assert jf.shape == gf.shape == (B, 512)
    np.testing.assert_allclose(jf, gf, rtol=1e-5, atol=1e-5)
    # and the logits head agrees too (full-net equivalence, not just fc1)
    jl = jnet.forward(batch, blob_names=["fc2"])["fc2"]
    gl = gnet.forward(batch, blob_names=["logits"])["logits"]
    np.testing.assert_allclose(jl, gl, rtol=1e-5, atol=1e-5)


def test_checkpoint_shape_mismatch_fails_loudly(tmp_path):
    from sparknet_tpu.utils import checkpoint
    tree = {"a": {"w": np.zeros((2, 3))}}
    checkpoint.save(str(tmp_path / "ck"), tree, step=1)
    bad = {"a": {"w": np.zeros((2, 4))}}
    with pytest.raises(ValueError, match="a/w"):
        checkpoint.restore(str(tmp_path / "ck"), bad)


def test_checkpoint_retention(tmp_path):
    from sparknet_tpu.utils import checkpoint
    tree = {"x": np.arange(3)}
    for s in range(5):
        checkpoint.save(str(tmp_path / "ck"), tree, step=s)
    checkpoint.retain(str(tmp_path / "ck"), keep=2)
    assert checkpoint.latest_step(str(tmp_path / "ck")) == 4
    assert sorted(os.listdir(tmp_path / "ck")) == ["step-3", "step-4"]


def test_graph_mnist_app_loop(tmp_path):
    """MnistApp pairing: serialized-graph backend inside the distributed
    τ-round (the reference's apps/MnistApp.scala shape), incl. checkpoint
    round-trip of the graph train state."""
    from sparknet_tpu.apps.graph_mnist_app import _nhwc, train_graph
    from sparknet_tpu.backend import build_mnist_graph
    d = str(tmp_path / "gm")
    mnist.write_synthetic(d, n_train=256, n_test=64)
    loader = mnist.MnistLoader(d)
    train_ds = ArrayDataset(_nhwc(loader.train_batch_dict()))
    test_ds = ArrayDataset(_nhwc(loader.test_batch_dict()))
    cfg = RunConfig(tau=2, local_batch=4, eval_every=2, eval_batch=32,
                    max_rounds=4, workdir=str(tmp_path), seed=0,
                    checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    log_path = str(tmp_path / "glog.txt")
    graph = build_mnist_graph(batch=cfg.local_batch, train_size=256)
    state = train_graph(cfg, graph, train_ds, test_ds,
                        logger=Logger(log_path, echo=False))
    text = open(log_path).read()
    assert "test accuracy" in text and "round loss" in text
    assert ckpt.latest_step(str(tmp_path / "ck")) == 4
    # resume path restores into the same structure
    restored, step, _ = ckpt.restore(str(tmp_path / "ck"), state)
    assert step == 4
    np.testing.assert_array_equal(
        np.asarray(state["it"]), np.asarray(restored["it"]))


def test_evaluate_covers_tail(tmp_path):
    """_evaluate weights the non-multiple tail (ADVICE r1: full coverage was
    documented but tail examples were dropped)."""
    from sparknet_tpu.apps.train_loop import _evaluate

    class FakeTrainer:
        def __init__(self):
            self.calls = []

        def evaluate(self, state, batch):
            n = len(next(iter(batch.values())))
            self.calls.append(n)
            return 1.0 if n == 32 else 0.0

    # 50 examples, eval_batch 32, 2 devices: one full batch of 32 (acc 1.0)
    # + tail of 18 (acc 0.0) -> weighted 32/50
    ds = ArrayDataset({"x": np.zeros((50, 3), np.float32)})
    t = FakeTrainer()
    acc = _evaluate(t, None, ds, eval_batch=32, n_dev=2)
    assert t.calls == [32, 18]
    assert acc == pytest.approx(32 / 50)


def test_streaming_source_through_train_loop(tmp_path):
    """Train the layer-IR backend from a StreamingRoundSource end to end:
    the corpus is never materialized (decode thread feeds the loop's
    prefetcher), preprocessing runs per round, loss is finite, and the
    source is closed by the loop."""
    from sparknet_tpu.data import imagenet
    from sparknet_tpu.data.streaming import StreamingRoundSource
    from sparknet_tpu.data.preprocess import ImagePreprocessor
    from sparknet_tpu.schema import Field, Schema
    from sparknet_tpu.model.spec import NetSpec
    from sparknet_tpu import zoo
    import jax

    root = str(tmp_path / "shards")
    label_path = imagenet.write_synthetic_shards(root, n_shards=2,
                                                 per_shard=40, size=36)
    loader = imagenet.ShardedTarLoader(
        imagenet.list_shards(root), imagenet.load_label_map(label_path),
        height=36, width=36)
    n_local, local_b, tau = jax.local_device_count(), 1, 2
    src = StreamingRoundSource(loader, n_local, local_b, tau)
    crop = 32
    schema = Schema(Field("data", "float32", (crop, crop, 3)),
                    Field("label", "int32", (1,)))
    pp = ImagePreprocessor(schema, mean_image=None, crop=crop, seed=0)
    # health off: raw 0-255 pixels (no mean image) blow this throwaway net
    # up within a few rounds by design — the plumbing, not the dynamics,
    # is under test, and the supervisor would (correctly) intervene
    from sparknet_tpu.utils.health import HealthConfig
    cfg = small_cfg(tmp_path, local_batch=local_b, tau=tau, max_rounds=3,
                    eval_every=0, crop=crop,
                    health=HealthConfig(enabled=False))
    log_path = str(tmp_path / "slog.txt")
    state = train(cfg, cifar10_quick(batch=local_b), src,
                  logger=Logger(log_path, echo=False), batch_transform=pp)
    assert state is not None
    text = open(log_path).read()
    assert "streaming" in text and "round loss" in text
    assert src._stop.is_set()  # loop closed the source


def test_elastic_resume_different_device_count(tmp_path):
    """A checkpoint taken on 8 devices resumes on a 4-device trainer:
    params carry over exactly (replicas are identical post-round), the
    iteration counter continues, and the app-level loop takes the ELASTIC
    path and trains on — elasticity the reference could not express (its
    worker state lived in executor JVMs)."""
    from sparknet_tpu import CompiledNet
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh
    from sparknet_tpu.utils import checkpoint as ck

    d = str(tmp_path / "c")
    cifar.write_synthetic(d, n_per_file=40)
    loader = cifar.CifarLoader(d)
    train_ds = ArrayDataset(loader.train_batch_dict())

    def run(n_devices, ckdir, max_rounds, log_path=None):
        cfg = small_cfg(tmp_path, max_rounds=max_rounds, eval_every=0,
                        n_devices=n_devices, checkpoint_dir=str(ckdir),
                        checkpoint_every=2, resume=True)
        return cfg, train(cfg, cifar10_quick(batch=cfg.local_batch),
                          train_ds, logger=Logger(log_path, echo=False))

    ckdir = tmp_path / "ck"
    _, s8 = run(8, ckdir, max_rounds=2)          # writes step-2 on 8 dev
    net = CompiledNet.compile(cifar10_quick(batch=4))
    # layout-neutral: build trainers of the implementation the loop ran
    # (the CI matrix leg routes train() through the NamedSharding trainer
    # via $SPARKNET_TRAINER_IMPL)
    from sparknet_tpu.apps.train_loop import resolve_trainer_impl
    from sparknet_tpu.parallel import ShardedTrainer
    cls = (ShardedTrainer if resolve_trainer_impl(RunConfig()) == "named"
           else ParallelTrainer)
    t8 = cls(net, SolverConfig(base_lr=0.01, momentum=0.9),
             make_mesh(8), tau=2)
    full8 = {k: {p: np.asarray(v) for p, v in lp.items()}
             for k, lp in t8.averaged_params(s8).items()}
    it8 = int(np.asarray(s8.it).reshape(-1)[0])

    # adapt the 8-device checkpoint on a 4-device trainer BEFORE any
    # 4-device run overwrites it: params and counter must carry exactly
    t4 = cls(net, SolverConfig(base_lr=0.01, momentum=0.9),
             make_mesh(4), tau=2)
    flat, step, extra = ck.restore_flat(str(ckdir))
    assert step == 2 and extra["n_devices"] == 8 and extra["tp"] == 1
    state4 = t4.adapt_state(flat, old_tp=extra["tp"],
                            old_layout=extra.get("layout", "replica"))
    assert int(np.asarray(state4.it).reshape(-1)[0]) == it8
    full4 = t4.averaged_params(state4)
    for lname in full8:
        for pname in full8[lname]:
            np.testing.assert_array_equal(
                np.asarray(full4[lname][pname]), full8[lname][pname],
                err_msg=f"{lname}/{pname}")

    # app-level loop: resumes elastically and keeps training
    log_path = str(tmp_path / "elastic.txt")
    _, s4 = run(4, ckdir, max_rounds=3, log_path=log_path)
    # layout-neutral topology probe: momentum rows count the data groups
    # in both layouts at tp == 1
    assert s4.momentum[list(s4.momentum)[0]]["w"].shape[0] == 4
    text = open(log_path).read()
    assert "ELASTIC resume from round 2: 8 devices" in text
    assert "round loss" in text


def test_adapt_state_tp_to_dp_exact(rng, tmp_path):
    """adapt_state reassembles a DPxTP checkpoint into a pure-DP state:
    the full params from the TP shards equal averaged_params, and momentum
    is the mean over old data groups."""
    import jax
    from sparknet_tpu import CompiledNet
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh
    from sparknet_tpu.parallel.mesh import fetch_global
    from sparknet_tpu.utils import checkpoint as ck

    net = CompiledNet.compile(cifar10_quick(batch=2))
    cfg = SolverConfig(base_lr=0.05, momentum=0.9, weight_decay=0.001)
    tp = ParallelTrainer(
        net, cfg, make_mesh(4, axis_names=("data", "model"), shape=(2, 2)),
        tau=2)
    state = tp.init_state(jax.random.PRNGKey(0))
    batches = {
        "data": rng.standard_normal((2, 4, 32, 32, 3)).astype(np.float32),
        "label": rng.integers(0, 10, (2, 4, 1)).astype(np.int32)}
    state, _ = tp.train_round(state, batches, jax.random.PRNGKey(1))
    full_tp = tp.averaged_params(state)

    d = str(tmp_path / "ck")
    ck.save(d, fetch_global(state), step=1,
            extra={"n_devices": 4, "tp": 2})
    flat, _, extra = ck.restore_flat(d)

    dp = ParallelTrainer(net, cfg, make_mesh(2), tau=2)
    s_dp = dp.adapt_state(flat, old_tp=extra["tp"])
    full_dp = dp.averaged_params(s_dp)
    for lname in full_tp:
        for pname in full_tp[lname]:
            np.testing.assert_allclose(
                np.asarray(full_dp[lname][pname]),
                np.asarray(full_tp[lname][pname]), rtol=1e-6,
                err_msg=f"{lname}/{pname}")
    # and a round runs on the adapted state
    s_dp, loss = dp.train_round(
        s_dp, {"data": batches["data"][:, :4], "label":
               batches["label"][:, :4]}, jax.random.PRNGKey(2))
    assert np.isfinite(float(loss))


@pytest.mark.slow
def test_distributed_training_converges(tmp_path):
    """End-to-end learning check through the REAL loop (8 devices, tau
    rounds, averaging, eval): cifar10_quick on an easy synthetic task
    (class-dependent mean patch) must reach high train accuracy — loss
    going down is necessary but not sufficient; this pins that the
    solver + averaging dynamics actually learn."""
    r = np.random.default_rng(0)
    n, classes = 1600, 10
    labels = r.integers(0, classes, n).astype(np.int32)
    data = 0.1 * r.standard_normal((n, 3, 32, 32)).astype(np.float32)
    for i, c in enumerate(labels):
        data[i, :, 2 * c:2 * c + 8, 2 * c:2 * c + 8] += 1.0
    ds = ArrayDataset({"data": data, "label": labels[:, None]})
    cfg = small_cfg(tmp_path, max_rounds=40, eval_every=0, local_batch=8,
                    tau=2,
                    solver=SolverConfig(base_lr=0.02, momentum=0.9,
                                        weight_decay=0.0,
                                        lr_policy="fixed"))
    state = train(cfg, cifar10_quick(batch=cfg.local_batch), ds,
                  logger=Logger(echo=False))

    from sparknet_tpu import CompiledNet
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh
    net = CompiledNet.compile(cifar10_quick(batch=cfg.local_batch))
    trainer = ParallelTrainer(net, cfg.solver, make_mesh(None), tau=2)
    arrays = _to_nhwc_eval(ds.arrays)
    correct = total = 0
    for i in range(0, 1024, 64):
        batch = {k: v[i:i + 64] for k, v in arrays.items()}
        correct += trainer.evaluate(state, batch) * 64
        total += 64
    acc = correct / total
    assert acc > 0.9, f"distributed training failed to learn: acc={acc:.3f}"


def _to_nhwc_eval(arrays):
    return {"data": np.ascontiguousarray(
        np.transpose(arrays["data"], (0, 2, 3, 1))),
        "label": arrays["label"]}


def test_elastic_resume_momentum_trajectory_band(tmp_path):
    """Momentum handling across an elastic resume, validated on the
    TRAJECTORY (r3 review item 6): continuing an 8-device run at 4 and at
    2 devices (norm-rescaled momentum average — the policy that won the
    r5 A/B, scripts/elastic_momentum_ab.py / ELASTIC_AB_r05.json) keeps
    every subsequent round's loss within 15% / 40% of the uninterrupted
    8-device run (measured: <=10% at 4 dev, <=31% at 2 dev across 3
    seeds — the band documented at ParallelTrainer.adapt_state) and
    still descending; a same-topology pass through adapt_state is exact
    to float noise."""
    import jax
    from sparknet_tpu import CompiledNet, net_from_prototxt
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh
    from sparknet_tpu.parallel.mesh import fetch_global
    from sparknet_tpu.utils import checkpoint as ck
    from test_parallel import TINY_MLP

    net = CompiledNet.compile(net_from_prototxt(TINY_MLP))
    scfg = SolverConfig(base_lr=0.05, momentum=0.9, weight_decay=0.001,
                        lr_policy="fixed")
    tau, b = 3, 8

    def batches(seed, n_dev):
        r = np.random.default_rng(seed)
        data = r.standard_normal((tau, 8 * b, 6)).astype(np.float32)
        label = (data.sum(-1, keepdims=True) > 0).astype(np.int32) + \
            (data[..., :1] > 0.5).astype(np.int32)
        return {"data": data[:, :n_dev * b], "label": label[:, :n_dev * b]}

    def run(trainer, state, rounds, n_dev, start=0):
        losses = []
        for r in range(start, start + rounds):
            state, loss = trainer.train_round(
                state, batches(r, n_dev), jax.random.PRNGKey(1000 + r))
            losses.append(float(loss))
        return state, losses

    t8 = ParallelTrainer(net, scfg, make_mesh(8), tau=tau)
    s, _ = run(t8, t8.init_state(jax.random.PRNGKey(0)), 4, 8)
    d = str(tmp_path / "ck")
    ck.save(d, fetch_global(s), step=4, extra={"n_devices": 8, "tp": 1})
    flat, _, _ = ck.restore_flat(d)
    _, base = run(t8, s, 8, 8, start=4)  # uninterrupted continuation

    # same topology through adapt_state: per-worker momentum rows are
    # restored as written (no reconstruction policy) — exact to float
    # noise of the save/restore round-trip
    t8b = ParallelTrainer(net, scfg, make_mesh(8), tau=tau)
    _, same = run(t8b, t8b.adapt_state(flat), 8, 8, start=4)
    assert max(abs(a - c) / c for a, c in zip(same, base)) < 1e-5

    for nd, band in ((4, 0.15), (2, 0.40)):
        t = ParallelTrainer(net, scfg, make_mesh(nd), tau=tau)
        _, losses = run(t, t.adapt_state(flat), 8, nd, start=4)
        rel = [abs(a - c) / c for a, c in zip(losses, base)]
        assert max(rel) < band, (nd, losses, base)
        # and the continued run still LEARNS (not just stays close)
        assert np.mean(losses[-3:]) < losses[0], (nd, losses)


def test_log_every_batches_metric_fetches(tmp_path):
    """cfg.log_every=K amortizes the loop's per-round loss fetch (the only
    host sync; ~one full round trip on high-latency links) K-fold; the
    logged content must be IDENTICAL to log_every=1, rounds in order."""
    import json
    import re
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.zoo import lenet
    from sparknet_tpu.data.dataset import ArrayDataset

    r = np.random.default_rng(0)
    ds = ArrayDataset({"data": r.standard_normal(
        (256, 1, 28, 28)).astype(np.float32),
        "label": r.integers(0, 10, (256, 1)).astype(np.int32)})

    def run(log_every, tag):
        jsonl = str(tmp_path / f"m{tag}.jsonl")
        cfg = RunConfig(model="lenet", tau=2, local_batch=2, max_rounds=7,
                        eval_every=3, eval_batch=64, seed=0,
                        workdir=str(tmp_path), log_every=log_every)
        train(cfg, lenet(batch=2), ds, ds,
              logger=Logger(str(tmp_path / f"l{tag}.txt"), echo=False,
                            jsonl_path=jsonl))
        rows = [json.loads(ln) for ln in open(jsonl)]
        text = open(str(tmp_path / f"l{tag}.txt")).read()
        return rows, text

    base_rows, base_text = run(1, "a")
    k_rows, k_text = run(3, "b")

    def semantic(rows):  # drop wall-clock fields ('t', throughput)
        return [{k: r[k] for k in ("step", "loss", "test_accuracy")
                 if k in r} for r in rows]

    assert semantic(k_rows) == semantic(base_rows)  # same metrics, order
    # round-ordered loss lines in the text log too
    rounds = [int(m.group(1)) for m in
              re.finditer(r"round loss: [\d.]+.*iteration = (\d+)", k_text)]
    assert rounds == sorted(rounds) == list(range(7))
