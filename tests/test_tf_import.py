"""TF GraphDef (.pb) importer tests.

The crown-jewel parity check: the reference's OWN frozen graphs
(`models/tensorflow/mnist/mnist_graph.pb`, `alexnet/alexnet_graph.pb`)
import through our zero-dependency wire parser and execute under GraphNet;
where TensorFlow is installed, forward results are cross-checked against a
real TF session fed identical weights through the same
`//update_placeholder`/`//assign` protocol the reference used
(`libs/TensorFlowNet.scala:110-121`).
"""
import os

import numpy as np
import pytest

from sparknet_tpu.backend.graph_net import GraphNet
from sparknet_tpu.backend.tf_import import (import_tf_graphdef_file,
                                            parse_tf_graphdef, parse_wire)

MNIST_PB = "/root/reference/models/tensorflow/mnist/mnist_graph.pb"
ALEXNET_PB = "/root/reference/models/tensorflow/alexnet/alexnet_graph.pb"

needs_pb = pytest.mark.skipif(not os.path.exists(MNIST_PB),
                              reason="reference mount absent")


def test_wire_parser_roundtrip_basics():
    # field 1 varint 150; field 2 string "abc"
    buf = b"\x08\x96\x01\x12\x03abc"
    f = parse_wire(buf)
    assert f[1][0][1] == 150
    assert f[2][0][1] == b"abc"


@needs_pb
def test_parse_reference_mnist_pb():
    nodes = parse_tf_graphdef(open(MNIST_PB, "rb").read())
    assert len(nodes) == 354
    by_name = {n["name"]: n for n in nodes}
    assert by_name["data"]["op"] == "Placeholder"
    assert by_name["data"]["attrs"]["shape"] == [64, 28, 28, 1]
    assert by_name["Conv2D"]["attrs"]["padding"] == "SAME"


@needs_pb
def test_mnist_pb_executes():
    net = GraphNet(import_tf_graphdef_file(MNIST_PB))
    assert set(net.input_names) == {"data", "label"}
    assert len(net.variable_names) == 17  # 8 model + 1 batch + 8 momentum
    r = np.random.default_rng(0)
    batch = {"data": r.standard_normal((7, 28, 28, 1)).astype(np.float32),
             "label": r.integers(0, 10, (7,)).astype(np.int64)}
    out = net.forward(batch, ["accuracy", "loss"])
    assert 0.0 <= out["accuracy"] <= 1.0
    assert np.isfinite(out["loss"])


@needs_pb
def test_alexnet_pb_executes():
    net = GraphNet(import_tf_graphdef_file(ALEXNET_PB))
    assert set(net.input_names) == {"data", "label"}
    r = np.random.default_rng(1)
    # seed model variables so conv outputs are nonzero
    for v in net.variable_names:
        shape = tuple(net.variables[v].shape)
        net.variables[v] = 0.01 * r.standard_normal(shape).astype(np.float32)
    batch = {"data": r.standard_normal((2, 224, 224, 3)).astype(np.float32),
             "label": r.integers(0, 1000, (2,)).astype(np.int64)}
    out = net.forward(batch, ["accuracy", "loss"])
    assert np.isfinite(out["loss"])


@needs_pb
def test_cross_check_against_real_tensorflow():
    tf = pytest.importorskip("tensorflow")
    net = GraphNet(import_tf_graphdef_file(MNIST_PB))
    r = np.random.default_rng(3)
    # give every variable a defined value on our side
    weights = {}
    for v in net.variable_names:
        shape = tuple(net.variables[v].shape)
        w = (0.05 * r.standard_normal(shape)).astype(np.float32)
        net.variables[v] = w
        weights[v] = w
    batch = {"data": r.standard_normal((64, 28, 28, 1)).astype(np.float32),
             "label": r.integers(0, 10, (64,)).astype(np.int64)}
    ours = net.forward(batch, ["loss", "accuracy"])

    g = tf.compat.v1.GraphDef()
    g.ParseFromString(open(MNIST_PB, "rb").read())
    with tf.compat.v1.Session(graph=tf.Graph()) as sess:
        tf.import_graph_def(g, name="")
        # the reference's set_weights protocol, verbatim
        for v, w in weights.items():
            sess.run(f"{v}//assign",
                     feed_dict={f"{v}//update_placeholder:0": w})
        tf_loss, tf_acc = sess.run(
            ["loss:0", "accuracy:0"],
            feed_dict={"data:0": batch["data"], "label:0": batch["label"]})
    np.testing.assert_allclose(ours["loss"], tf_loss, rtol=2e-4)
    np.testing.assert_allclose(ours["accuracy"], tf_acc, rtol=1e-5)


@needs_pb
def test_imported_graph_default_fetches_work():
    """output_names must exclude gradient machinery/opaque ops so default
    forward() succeeds on an imported graph (regression)."""
    net = GraphNet(import_tf_graphdef_file(MNIST_PB))
    outs = net.output_names()
    assert all(not o.startswith("gradients/") for o in outs)
    assert "accuracy" in outs
    r = np.random.default_rng(0)
    batch = {"data": r.standard_normal((4, 28, 28, 1)).astype(np.float32),
             "label": r.integers(0, 10, (4,)).astype(np.int64)}
    out = net.forward(batch)  # default fetches — used to KeyError
    assert "accuracy" in out


@needs_pb
def test_step_on_imported_graph_uses_in_graph_optimizer():
    """step() trains the imported graph through its OWN optimizer subgraph:
    ApplyMomentum hyperparameters, the ExponentialDecay lr schedule, and the
    train//step counter bump all come from the graph (reference: the
    optimizer lives inside the TF graph, `TensorFlowNet.scala:86-90`)."""
    net = GraphNet(import_tf_graphdef_file(MNIST_PB))
    opt = net.discover_optimizer()
    assert len(opt.trainable) == 8
    assert opt.momentum == pytest.approx(0.9)
    assert opt.counter == "Variable_7" and opt.counter_inc == 1
    # lr schedule = tf.train.exponential_decay(0.01, it*64, 60000, 0.95,
    # staircase=True), evaluated from the graph's own subgraph
    import jax.numpy as jnp
    variables = dict(net.variables)
    assert float(opt.lr_fn(variables, None)) == pytest.approx(0.01)
    variables["Variable_7"] = jnp.asarray(60000 // 64 + 1, jnp.int32)
    assert float(opt.lr_fn(variables, None)) == pytest.approx(0.01 * 0.95)

    r = np.random.default_rng(0)
    batch = {"data": r.standard_normal((8, 28, 28, 1)).astype(np.float32),
             "label": r.integers(0, 10, (8,)).astype(np.int64)}
    losses = [net.step(batch) for _ in range(5)]  # no loss_name needed:
    assert losses[-1] < losses[0]                 # 'loss' convention node
    assert int(net.variables["Variable_7"]) == 5  # counter bumped per step
    # momentum slots accumulated INSIDE variables (they are graph variables)
    assert float(jnp.abs(net.variables["conv1/Momentum"]).sum()) > 0


def test_step_refuses_graph_without_optimizer_or_loss():
    from sparknet_tpu.backend import GraphBuilder
    g = GraphBuilder("noopt")
    g.placeholder("x", (2, 3))
    g.variable("w", np.ones((3, 2), np.float32))
    g.matmul("y", "x", "w")
    net = GraphNet(g.finalize())  # no loss -> no Train node
    with pytest.raises(ValueError, match="loss"):
        net.step({"x": np.zeros((2, 3), np.float32)})


def test_maxpool_same_nonsquare():
    """SAME padding computed per spatial dim (regression: width was padded
    with the height's total)."""
    import torch
    import torch.nn.functional  # noqa: F401
    from sparknet_tpu.backend.graphdef import NodeDef, _op_max_pool
    import jax.numpy as jnp
    x = np.random.default_rng(0).standard_normal((1, 8, 5, 3)).astype(
        np.float32)
    n = NodeDef(name="p", op="MaxPool", inputs=["x"],
                attrs={"ksize": 2, "strides": 2, "padding": "SAME"})
    got = np.asarray(_op_max_pool(n, [jnp.asarray(x)]))
    assert got.shape == (1, 4, 3, 3)  # ceil(5/2) == 3
