"""The SLO ledger (obs/history.py + obs/slo.py): ring-cascade fidelity
against exact recomputation, histogram-ring quantiles against the exact
order statistic (error bounded by the bucket ladder), burn-rate alert
EDGE semantics (firing AND resolved, zero-traffic burns nothing), the
FleetController's page-escalation fast lever (audited, still clamped by
batch relief), the /timeseries + /slo/status routes over real HTTP, the
shard round-trip, and the `sparknet-slo --selfcheck` end-to-end gate."""
import json
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

# obs first: importing fleet before obs trips the utils.metrics <->
# obs.reqtrace import cycle (obs/__init__ orders reqtrace last)
from sparknet_tpu.obs import MetricsRegistry, StatusServer
from sparknet_tpu.fleet import FleetConfig, FleetController, FleetPolicy
from sparknet_tpu.obs.history import (HistoryConfig, MetricsHistory,
                                      merge_slots, quantile_from_buckets,
                                      read_history_shards)
from sparknet_tpu.obs.slo import (LATENCY_METRIC, REQUESTS_METRIC,
                                  BurnRateAlerter, SloSpec, build_report)
from sparknet_tpu.obs.summary import summarize
from sparknet_tpu.utils.logger import Logger
from sparknet_tpu.utils.metrics import LatencyStats


def _spec(**over):
    kw = dict(model="m", latency_ms=50.0, availability=0.99,
              window_s=120.0, fast_burn=8.0, fast_window_s=10.0,
              fast_confirm_s=2.0, slow_burn=2.0, slow_window_s=60.0,
              slow_confirm_s=10.0)
    kw.update(over)
    return SloSpec(**kw)


# -- ring fidelity: the cascade must agree with exact recomputation ----------

def test_ring_downsampling_matches_exact_recompute():
    """Counter deltas and gauge envelopes read from the COARSE ring must
    equal an exact recompute over the raw per-second sample stream —
    slots merge losslessly (deltas add, envelopes widen), so
    downsampling is a fold, not an approximation."""
    reg = MetricsRegistry()
    c = reg.counter("sparknet_test_total")
    g = reg.gauge("sparknet_test_depth")
    hist = MetricsHistory(reg, HistoryConfig(
        sample_interval_s=1.0, rings=((1.0, 60), (10.0, 60))))
    c.inc(0)  # materialize the series BEFORE the baseline sample
    g.set(0.0)
    t0 = time.time()
    hist.sample_now(now=t0)  # first sight: baseline, no delta
    rng = np.random.default_rng(7)
    incs = rng.integers(0, 9, 120)
    gvals = rng.uniform(-5.0, 5.0, 120)
    for i in range(120):
        c.inc(int(incs[i]))
        g.set(float(gvals[i]))
        hist.sample_now(now=t0 + 1 + i)
    now = t0 + 121
    # the full span only fits the 10 s ring: its folded delta must be
    # the exact sum of every per-second increment
    w = hist.window("sparknet_test_total", 600.0, now=now)
    assert w["sparknet_test_total"]["delta"] == int(incs.sum())
    # the fine ring answers short windows exactly too
    w30 = hist.window("sparknet_test_total", 30.0, now=now)
    assert w30["sparknet_test_total"]["delta"] == int(incs[-30:].sum())
    # gauge envelope over the coarse ring: exact min/max/last
    wg = hist.window("sparknet_test_depth", 600.0, now=now)
    env = wg["sparknet_test_depth"]
    assert env["last"] == pytest.approx(float(gvals[-1]))
    assert env["min"] == pytest.approx(float(gvals.min()))
    assert env["max"] == pytest.approx(float(gvals.max()))


def test_histogram_ring_quantile_bounded_by_bucket_ladder():
    """The ring-windowed p99 is interpolated from fixed buckets; against
    the exact order statistic (LatencyStats over the SAME observations)
    the error must stay inside one bucket-ladder rung (adjacent default
    edges are <= 2.5x apart)."""
    reg = MetricsRegistry()
    stats = LatencyStats(window=4096, registry=reg, name=LATENCY_METRIC,
                         model="m")
    hist = MetricsHistory(reg, HistoryConfig(
        sample_interval_s=1.0, rings=((1.0, 600),)))
    stats.add(0.02)  # materialize the series before the baseline
    t0 = time.time()
    hist.sample_now(now=t0)
    rng = np.random.default_rng(3)
    draws = np.exp(rng.normal(np.log(0.02), 0.6, 2000))  # ~5..80 ms
    for i in range(10):
        for v in draws[i * 200:(i + 1) * 200]:
            stats.add(float(v))
        hist.sample_now(now=t0 + 1 + i)
    for q in (0.5, 0.9, 0.99):
        exact = stats.windowed_quantile(q, 300.0)
        est = hist.windowed_quantile(LATENCY_METRIC, q, 300.0,
                                     labels={"model": "m"}, now=t0 + 11)
        assert exact is not None and est is not None
        assert 1 / 2.6 < est / exact < 2.6, \
            f"q={q}: ring {est} vs exact {exact}"


def test_quantile_from_buckets_interpolation_and_inf_clamp():
    le = [0.1, 1.0]  # finite edges only (snapshot convention); the
    # overflow is count - sum(counts)
    # 10 obs <= 0.1, 10 in (0.1, 1], none above: p50 sits mid-ladder
    assert quantile_from_buckets(le, [10, 10], 20, 0.5) == \
        pytest.approx(0.1)
    assert quantile_from_buckets(le, [10, 10], 20, 0.75) == \
        pytest.approx(0.55)
    # all mass in the +Inf overflow clamps to the top finite edge
    assert quantile_from_buckets(le, [0, 0], 10, 0.99) == \
        pytest.approx(1.0)
    assert quantile_from_buckets(le, [10, 10], 0, 0.5) is None


# -- shard persistence -------------------------------------------------------

def test_history_shards_roundtrip(tmp_path):
    reg = MetricsRegistry()
    c = reg.counter(REQUESTS_METRIC, labels=("model", "outcome"))
    lat = reg.histogram(LATENCY_METRIC, labels=("model",))
    hist = MetricsHistory(reg, HistoryConfig(
        sample_interval_s=1.0, rings=((1.0, 600),),
        persist_dir=str(tmp_path)))
    c.inc(0, model="m", outcome="ok")  # pre-baseline registration
    lat.observe(0.01, model="m")       # (one obs rides the baseline)
    t0 = time.time()
    hist.sample_now(now=t0)
    for i in range(20):
        c.inc(3, model="m", outcome="ok")
        lat.observe(0.01, model="m")
        hist.sample_now(now=t0 + 1 + i)
    families, slots = read_history_shards(str(tmp_path))
    # the meta row self-describes the families — including the bucket
    # ladder the offline report's quantiles need
    assert families[LATENCY_METRIC]["kind"] == "histogram"
    assert families[LATENCY_METRIC]["le"][-1] == 10.0  # finite edges
    merged = merge_slots(slots)
    key = f'{REQUESTS_METRIC}{{model=m,outcome=ok}}'
    assert merged.c[key] == 60
    hkey = f'{LATENCY_METRIC}{{model=m}}'
    assert merged.h[hkey][2] == 20  # n
    assert sum(merged.h[hkey][0]) == 20  # per-bucket deltas


# -- burn-rate alerting: edges, not levels ------------------------------------

def _drive(alerter, hist, lat, req, t0, start, n, latency_s, outcome):
    for i in range(start, start + n):
        for _ in range(20):
            lat.observe(latency_s, model="m")
            req.inc(model="m", outcome=outcome)
        hist.sample_now(now=t0 + i)
        alerter.evaluate(now=t0 + i)


def test_burn_edges_fire_and_resolve():
    reg = MetricsRegistry()
    lat = reg.histogram(LATENCY_METRIC, labels=("model",))
    req = reg.counter(REQUESTS_METRIC, labels=("model", "outcome"))
    hist = MetricsHistory(reg, HistoryConfig(
        sample_interval_s=1.0, rings=((1.0, 600),)))
    alerter = BurnRateAlerter(hist, [_spec()], registry=reg)
    t0 = time.time()
    _drive(alerter, hist, lat, req, t0, 0, 30, 0.005, "ok")
    assert alerter.alerts_fired == 0  # quiet traffic must not page
    assert alerter.firing_pages() == []
    _drive(alerter, hist, lat, req, t0, 30, 20, 0.2, "failed")
    assert "m" in alerter.firing_pages()
    fired = alerter.alerts_fired
    assert fired > 0
    _drive(alerter, hist, lat, req, t0, 50, 40, 0.005, "ok")
    assert alerter.firing_pages() == []  # short window lets it resolve
    assert alerter.alerts_fired == fired  # resolve is not a new firing
    edges = {(r["severity"], r["edge"]) for r in alerter.audit}
    assert ("page", "firing") in edges and ("page", "resolved") in edges
    # attainment rides every edge row (the sparknet-metrics hook)
    assert all(0.0 <= r["attainment"] <= 1.0 for r in alerter.audit)


def test_zero_traffic_burns_nothing():
    reg = MetricsRegistry()
    reg.histogram(LATENCY_METRIC, labels=("model",))
    reg.counter(REQUESTS_METRIC, labels=("model", "outcome"))
    hist = MetricsHistory(reg, HistoryConfig(
        sample_interval_s=1.0, rings=((1.0, 600),)))
    alerter = BurnRateAlerter(hist, [_spec()], registry=reg)
    t0 = time.time()
    for i in range(30):
        hist.sample_now(now=t0 + i)
        alerter.evaluate(now=t0 + i)
    assert alerter.alerts_fired == 0
    assert alerter.firing_pages() == []
    g = reg.gauge("sparknet_slo_error_budget_remaining",
                  labels=("model",))
    assert g.value(model="m") == 1.0  # no traffic, no budget burned


def test_spec_validation_fails_at_construction():
    with pytest.raises(ValueError):
        _spec(latency_ms=None, availability=None)  # no objective at all
    with pytest.raises(ValueError):
        _spec(availability=1.5)
    with pytest.raises(ValueError):
        _spec(fast_window_s=5.0, fast_confirm_s=10.0)  # confirm > long
    with pytest.raises(ValueError):
        BurnRateAlerter(
            MetricsHistory(MetricsRegistry(), HistoryConfig()),
            [_spec(), _spec()])  # one spec per model


# -- the fleet controller's fast lever ----------------------------------------

class _StubRouter:
    """The minimal router surface the controller's fast lever reads."""

    def __init__(self):
        self.registry = MetricsRegistry()
        self.cfg = types.SimpleNamespace(workers=1)
        self.lanes = {}
        self.replicas = {"m": []}
        self.latency = {}

    def attach_fleet(self, controller):
        pass

    def _replica_routable(self, rep):
        return True


class _StubAlerter:
    def __init__(self):
        self.pages = []

    def firing_pages(self):
        return list(self.pages)


class _StubAdmission:
    def __init__(self, starvation=0.0):
        self.starvation = starvation
        self.pressures = []

    def set_pressure(self, p):
        self.pressures.append(p)

    def starvation_s(self):
        return self.starvation


def test_controller_page_escalation_edge_audited():
    router = _StubRouter()
    alerter = _StubAlerter()
    fc = FleetController(router, cfg=FleetConfig(
        interval_s=0.05, page_pressure=0.9,
        policy=FleetPolicy(up_ticks=2, min_window_n=8)))
    fc.attach_alerter(alerter)
    fc.tick()
    assert fc.pressure == 0.0  # quiet: no page, no pressure
    alerter.pages = ["m"]
    fc.tick()
    assert fc.pressure == 0.9  # floored at page_pressure immediately
    ev = fc.audit[-1]
    assert (ev["model"], ev["direction"], ev["reason"]) == \
        ("_slo", "pressure", "slo_page")
    assert ev["models"] == "m"
    n_audit = len(fc.audit)
    fc.tick()
    assert len(fc.audit) == n_audit  # edge, not level: no repeat rows
    alerter.pages = []
    fc.tick()
    assert fc.pressure == 0.0  # page cleared -> lever releases


def test_batch_relief_still_clamps_page_escalation():
    """The scavenger-starvation clamp outranks the page floor: a firing
    page must not weld the door shut on the low class forever."""
    router = _StubRouter()
    alerter = _StubAlerter()
    alerter.pages = ["m"]
    admission = _StubAdmission(starvation=120.0)
    policy = FleetPolicy(up_ticks=2, min_window_n=8,
                         batch_max_starvation_s=60.0)
    fc = FleetController(router, admission=admission, cfg=FleetConfig(
        interval_s=0.05, page_pressure=0.9, policy=policy))
    fc.attach_alerter(alerter)
    fc.tick()
    assert fc.pressure == policy.batch_relief_pressure
    assert admission.pressures[-1] == policy.batch_relief_pressure
    kinds = {(e["direction"], e["reason"]) for e in fc.audit}
    assert ("pressure", "slo_page") in kinds
    assert ("relief", "batch_starvation") in kinds


# -- the HTTP surface ---------------------------------------------------------

def _get(srv, path):
    host, port = srv.address
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=5) as r:
        return json.loads(r.read())


def test_timeseries_and_slo_status_over_http():
    reg = MetricsRegistry()
    lat = reg.histogram(LATENCY_METRIC, labels=("model",))
    req = reg.counter(REQUESTS_METRIC, labels=("model", "outcome"))
    hist = MetricsHistory(reg, HistoryConfig(
        sample_interval_s=1.0, rings=((1.0, 600),)))
    alerter = BurnRateAlerter(hist, [_spec()], registry=reg)
    t0 = time.time()
    _drive(alerter, hist, lat, req, t0, 0, 10, 0.2, "failed")
    srv = StatusServer(0, reg)
    hist.attach_http(srv)
    alerter.attach_http(srv)
    try:
        disco = _get(srv, "/timeseries")
        assert LATENCY_METRIC in disco["families"]
        assert disco["rings"][0]["res_s"] == 1.0
        body = _get(srv, f"/timeseries?name={LATENCY_METRIC}"
                         f"&window=600&q=0.99&model=m")
        qv = body["quantile"]
        assert qv["q"] == 0.99 and qv["value"] > 0.05  # a 200 ms tail
        rate = _get(srv, f"/timeseries?name={REQUESTS_METRIC}"
                         f"&window=600&outcome=failed")
        key = f"{REQUESTS_METRIC}{{model=m,outcome=failed}}"
        assert rate["agg"][key]["delta"] == 180  # post-baseline incs
        slo = _get(srv, "/slo/status")
        assert slo["specs"][0]["model"] == "m"
        assert any(a["firing"] for a in slo["alerts"])
        assert slo["audit"][-1]["edge"] == "firing"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv, "/timeseries?name=nope_total")
        assert ei.value.code == 400  # unknown series: typed, not a 500
    finally:
        srv.stop()


# -- the summarizer's SLO view ------------------------------------------------

def test_summary_slo_view_from_alert_rows(tmp_path):
    jsonl = tmp_path / "m.jsonl"
    log = Logger(echo=False, jsonl_path=str(jsonl))
    reg = MetricsRegistry()
    lat = reg.histogram(LATENCY_METRIC, labels=("model",))
    req = reg.counter(REQUESTS_METRIC, labels=("model", "outcome"))
    hist = MetricsHistory(reg, HistoryConfig(
        sample_interval_s=1.0, rings=((1.0, 600),)))
    alerter = BurnRateAlerter(hist, [_spec()], registry=reg, logger=log)
    t0 = time.time()
    _drive(alerter, hist, lat, req, t0, 0, 15, 0.2, "failed")
    _drive(alerter, hist, lat, req, t0, 15, 30, 0.005, "ok")
    log.close()
    recs = [json.loads(ln) for ln in
            jsonl.read_text().splitlines() if ln]
    s = summarize(recs)
    view = s["slo"]
    assert view["alert_edges"] >= 2
    assert view["firing_at_end"] == []  # recovery resolved everything
    m = view["models"]["m"]
    assert m["pages"] >= 1
    assert 0.0 < m["attainment"]["latency"] < 1.0


# -- offline report + the end-to-end selfcheck --------------------------------

def test_build_report_from_shards_and_journal(tmp_path):
    hist_dir = tmp_path / "history"
    jsonl = tmp_path / "journal.jsonl"
    log = Logger(echo=False, jsonl_path=str(jsonl))
    reg = MetricsRegistry()
    lat = reg.histogram(LATENCY_METRIC, labels=("model",))
    req = reg.counter(REQUESTS_METRIC, labels=("model", "outcome"))
    hist = MetricsHistory(reg, HistoryConfig(
        sample_interval_s=1.0, rings=((1.0, 600),),
        persist_dir=str(hist_dir)))
    alerter = BurnRateAlerter(hist, [_spec()], registry=reg, logger=log)
    t0 = time.time()
    _drive(alerter, hist, lat, req, t0, 0, 20, 0.005, "ok")
    _drive(alerter, hist, lat, req, t0, 20, 20, 0.2, "failed")
    log.close()
    rep = build_report(str(hist_dir), [str(jsonl)], [_spec()],
                       report_window_s=10)
    m = rep["models"]["m"]
    # 800 sent minus 2 first-sight baselines (each outcome series'
    # first sample establishes a baseline, not a delta)
    assert m["requests"] == 760
    assert 0.0 < m["availability"] < 1.0
    latency = m["slo"]["latency"]
    assert latency["attainment"] < 1.0  # the burn shows up
    assert latency["worst_windows"][0]["err_frac"] > 0.5
    assert any(a["edge"] == "firing" for a in rep["alerts"])


def test_sparknet_slo_selfcheck_end_to_end(tmp_path):
    from sparknet_tpu.obs.slo import main as slo_main
    assert slo_main(["--selfcheck", "--keep", str(tmp_path)]) == 0
