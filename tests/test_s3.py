"""Native s3:// ingest against a local fake-S3 server — full parity with
the reference's actual data plane (it streamed ImageNet from S3 per task,
`loaders/ImageNetLoader.scala:62-63`). The fake server VERIFIES the AWS
Signature Version 4 on every request (recomputing it server-side from the
shared secret), so the stdlib SigV4 implementation is tested end to end,
not just exercised."""
import os

import numpy as np
import pytest

from sparknet_tpu.data import imagenet

ACCESS, SECRET = "AKTEST", "testsecret"

#: the LIVE handler class of the current fixture's server (the SigV4-
#: verifying FakeS3Handler now lives in fake_stores so bench/chaos can
#: serve s3:// outside pytest; state is per-server, the fixture rebinds
#: this module global)
_FakeS3 = None


@pytest.fixture
def s3(tmp_path, monkeypatch):
    global _FakeS3
    from fake_stores import serve_s3, stop_serving
    root = str(tmp_path / "local")
    imagenet.write_synthetic_shards(root, n_shards=3, per_shard=6, size=48)
    objects = {}
    for f in sorted(os.listdir(root)):
        with open(os.path.join(root, f), "rb") as fh:
            objects[f"bkt/imagenet/{f}"] = fh.read()
    srv, endpoint = serve_s3(objects, secret=SECRET)
    _FakeS3 = srv.handler
    monkeypatch.setenv("AWS_ENDPOINT_URL", endpoint)
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", ACCESS)
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", SECRET)
    monkeypatch.setenv("AWS_REGION", "us-east-1")
    monkeypatch.setenv("no_proxy", "*")
    from sparknet_tpu.data import gcs as gcs_mod, s3 as s3_mod
    monkeypatch.setattr(gcs_mod, "BACKOFF_S", 0.01)
    s3_mod._CLIENTS.clear()
    s3_mod._SIZE_CACHE.clear()
    s3_mod._STAT_CACHE.clear()
    yield "s3://bkt/imagenet", root
    stop_serving(srv)
    _FakeS3 = None


def test_s3_list_and_labels_signed(s3):
    """Listing + label fetch work, and the server ACCEPTED the SigV4 it
    verified — a wrong signature is rejected (negative control)."""
    url, root = s3
    remote = imagenet.list_shards(url, prefix="train.")
    local = imagenet.list_shards(root, prefix="train.")
    assert [os.path.basename(p) for p in remote] == \
        [os.path.basename(p) for p in local]
    assert len(remote) == 3  # > page_size: pagination exercised
    assert imagenet.load_label_map(f"{url}/train.txt") == \
        imagenet.load_label_map(os.path.join(root, "train.txt"))


def test_s3_bad_secret_rejected(s3, monkeypatch):
    from sparknet_tpu.data import s3 as s3_mod
    import urllib.error
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "wrong")
    s3_mod._CLIENTS.clear()
    with pytest.raises(urllib.error.HTTPError):
        imagenet.list_shards(s3[0])


def test_s3_loader_bit_identical_to_local(s3):
    url, root = s3
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    s = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    l = imagenet.ShardedTarLoader(imagenet.list_shards(root), labels,
                                  height=32, width=32)
    si, sl = s.load_all()
    li, ll = l.load_all()
    np.testing.assert_array_equal(si, li)
    np.testing.assert_array_equal(sl, ll)


def test_s3_stream_resumes_after_disconnect(s3):
    """Truncated body mid-tar -> signed ranged reconnect -> identical
    data (the reference's S3 streams had no such resilience)."""
    url, root = s3
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    _FakeS3.fail_once = {"imagenet/train.0000.tar"}
    s = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    l = imagenet.ShardedTarLoader(imagenet.list_shards(root), labels,
                                  height=32, width=32)
    np.testing.assert_array_equal(s.load_all()[0], l.load_all()[0])


def test_s3_mid_shard_seek_and_size(s3):
    url, root = s3
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    all_pos = [(lbl, pos) for _, lbl, pos in imagenet.ShardedTarLoader(
        imagenet.list_shards(root), labels, 32, 32).iter_with_pos()]
    mid = all_pos[7][1]
    cont = [(lbl, pos) for _, lbl, pos in imagenet.ShardedTarLoader(
        imagenet.list_shards(url), labels, 32, 32).iter_with_pos(mid)]
    assert cont == all_pos[8:]
    for g, l in zip(imagenet.list_shards(url), imagenet.list_shards(root)):
        assert imagenet.path_size(g) == os.path.getsize(l)
    # cold-cache size: ranged HEAD-equivalent (Content-Range total)
    from sparknet_tpu.data import s3 as s3_mod
    s3_mod._SIZE_CACHE.clear()
    g0, l0 = imagenet.list_shards(url)[0], imagenet.list_shards(root)[0]
    assert imagenet.path_size(g0) == os.path.getsize(l0)


def test_s3_upload_roundtrip_and_sharder_push(s3, tmp_path):
    """s3_write PUTs with a signed payload hash (server verifies both the
    signature AND that the hash matches the body); the sharder's --upload
    path pushes a whole shard dir and the loader reads it back
    bit-identically — the reference's put_imagenet_on_s3 story end to
    end."""
    import sys
    url, root = s3
    from sparknet_tpu.data.s3 import s3_read, s3_write
    s3_write("s3://bkt/up/x.bin", b"hello-shards")
    assert s3_read("s3://bkt/up/x.bin") == b"hello-shards"

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import shard_imagenet
    n = shard_imagenet.upload_dir(root, "s3://bkt2/imagenet")
    assert n == 4  # 3 shards + train.txt
    labels = imagenet.load_label_map("s3://bkt2/imagenet/train.txt")
    up = imagenet.ShardedTarLoader(
        imagenet.list_shards("s3://bkt2/imagenet"), labels, 32, 32)
    local = imagenet.ShardedTarLoader(
        imagenet.list_shards(root), labels, 32, 32)
    np.testing.assert_array_equal(up.load_all()[0], local.load_all()[0])
    with pytest.raises(SystemExit, match="gs:// or s3://"):
        shard_imagenet.upload_dir(root, "/local/path")


def test_s3_equal_size_replace_invalidated_by_etag(s3):
    """The s3 twin of the gs generation test: an EQUAL-size replacement
    changes the ETag (it rides the same `bytes=0-0` probe the size check
    already made), so the warm member index is dropped and the shard is
    re-walked instead of carved at stale offsets (ADVICE r5 #3)."""
    url, root = s3
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    s = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    s.load_all()
    assert len(s._bucket_indices) == 3
    name = sorted(k for k in _FakeS3.objects if k.endswith(".tar"))[0]
    obj_url = f"s3://{name}"
    stat_before = imagenet.path_stat(obj_url, fresh=True)
    # equal-size replacement: flip one byte INSIDE the first member's
    # data (offset 600: past the 512-byte tar header, inside the JPEG) —
    # size unchanged, ETag (md5 of the object) changes
    raw = bytearray(_FakeS3.objects[name])
    raw[600] ^= 0x01
    _FakeS3.objects[name] = bytes(raw)
    stat_after = imagenet.path_stat(obj_url, fresh=True)
    assert stat_after[0] == stat_before[0]  # equal size
    assert stat_after[1] != stat_before[1]  # different ETag
    # next epoch must NOT carve at the stale index: the freshness check
    # drops it and the tarfile walk re-captures with the NEW stat (the
    # flipped member may fail decode — counted in `skipped`, never
    # silently mis-carved)
    s.load_all()
    assert s._bucket_indices[obj_url][1] == stat_after


def test_s3_second_epoch_carve_bit_identical(s3):
    """The r5 bucket member-carve path (see test_gcs) over the SigV4
    transport: epoch 2 slices members by the captured index, bytes
    identical to the tarfile epoch."""
    url, root = s3
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    s = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    e1 = s.load_all()
    assert s._bucket_indices  # index captured on the full first epoch
    e2 = s.load_all()
    np.testing.assert_array_equal(e1[0], e2[0])
    np.testing.assert_array_equal(e1[1], e2[1])
    assert s.skipped == 0


# -- retry parity with the GCS client (full jitter + Retry-After) -----------

def test_s3_503_slowdown_retried_with_fresh_signature(s3):
    """AWS throttles with `503 SlowDown` (+ Retry-After), not 429: the
    signed S3 path must ride the shared full-jitter backoff and present a
    FRESH SigV4 signature on the retry (the fake server VERIFIES every
    signature server-side, so a stale or missing re-sign would 403 and
    403 is not retried)."""
    from sparknet_tpu.data import s3 as s3_mod

    url = s3_mod.s3_list_shards("s3://bkt/imagenet")[0]
    _, key = s3_mod.parse_s3_url(url)
    _FakeS3.slowdown_once.add(key)
    data = s3_mod.s3_read(url)  # succeeds THROUGH the throttle
    assert data[:4] and len(data) > 0
    assert not _FakeS3.slowdown_once  # the 503 was actually served
    # the throttled attempt was itself signed (x-amz-date present), and
    # the signature-verified retry delivered the bytes
    assert _FakeS3.slowdown_log and _FakeS3.slowdown_log[-1]


def test_s3_multipart_part_put_retries_through_503(s3, monkeypatch):
    """Multipart uploads (the checkpoint writer's path — exactly what a
    preempted worker rejoining through a flaky bucket exercises) retry a
    throttled part PUT instead of failing the whole upload."""
    from sparknet_tpu.data import s3 as s3_mod

    calls = {"n": 0}
    orig = s3_mod._gcs.http_get_with_retry

    def counting(url, headers=None, timeout=60.0, method="GET", data=None,
                 headers_fn=None):
        if method == "PUT" and "partNumber=" in url:
            calls["n"] += 1
        return orig(url, headers, timeout, method=method, data=data,
                    headers_fn=headers_fn)

    monkeypatch.setattr(s3_mod._gcs, "http_get_with_retry", counting)
    monkeypatch.setattr(s3_mod, "S3_UPLOAD_PART", 1 << 10)
    # one of the part PUTs gets a 503 SlowDown: the retry must happen
    # INSIDE the transport (calls stay at one per part) and re-sign
    _FakeS3.slowdown_once.add("imagenet/big.bin")
    payload = bytes(range(256)) * 16  # 4 KiB -> 4 parts
    s3_mod.s3_write_large("s3://bkt/imagenet/big.bin", payload,
                          parallel=2, part_bytes=1 << 10)
    assert _FakeS3.objects["bkt/imagenet/big.bin"] == payload
    assert calls["n"] == 4  # the 503 retried inside http_get_with_retry
    assert not _FakeS3.slowdown_once  # the throttle was actually served
    # the throttled attempt itself carried a (verified) SigV4 signature
    assert _FakeS3.slowdown_log and _FakeS3.slowdown_log[-1]


def test_retry_delay_honors_retry_after_on_503():
    """S3's SlowDown is a 503: its Retry-After must floor the jittered
    delay exactly like a 429's (PR 1 only honored 429)."""
    import io
    import urllib.error
    from email.message import Message

    from sparknet_tpu.data.gcs import retry_delay

    for code in (429, 503):
        hdrs = Message()
        hdrs["Retry-After"] = "7"
        err = urllib.error.HTTPError("http://x", code, "slow", hdrs,
                                     io.BytesIO(b""))
        assert retry_delay(0, err) >= 7.0, code
    # 500 carries no Retry-After contract: delay stays jittered-small
    hdrs = Message()
    hdrs["Retry-After"] = "7"
    err = urllib.error.HTTPError("http://x", 500, "boom", hdrs,
                                 io.BytesIO(b""))
    assert retry_delay(0, err) < 7.0


def test_http_retry_headers_fn_called_per_attempt(s3):
    """`headers_fn` is the per-attempt re-sign hook: it must be invoked
    once per ATTEMPT (fresh x-amz-date per retry), not once per call."""
    from sparknet_tpu.data import gcs as gcs_mod
    from sparknet_tpu.data import s3 as s3_mod

    url = s3_mod.s3_list_shards("s3://bkt/imagenet")[0]
    bucket, key = s3_mod.parse_s3_url(url)
    _FakeS3.slowdown_once.add(key)
    client = s3_mod._shared_client()
    base, host, path = client._url_parts(bucket, key)
    calls = {"n": 0}

    def signing():
        calls["n"] += 1
        return client._sign("GET", host, path, "", {})

    import urllib.parse
    with gcs_mod.http_get_with_retry(
            base + urllib.parse.quote(path, safe="/-_.~"), None,
            headers_fn=signing) as r:
        r.read()
    assert calls["n"] == 2  # one throttled attempt + one success
