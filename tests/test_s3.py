"""Native s3:// ingest against a local fake-S3 server — full parity with
the reference's actual data plane (it streamed ImageNet from S3 per task,
`loaders/ImageNetLoader.scala:62-63`). The fake server VERIFIES the AWS
Signature Version 4 on every request (recomputing it server-side from the
shared secret), so the stdlib SigV4 implementation is tested end to end,
not just exercised."""
import datetime
import hashlib
import hmac
import http.server
import os
import threading
import urllib.parse

import numpy as np
import pytest

from sparknet_tpu.data import imagenet

ACCESS, SECRET = "AKTEST", "testsecret"


def _expected_sig(method, path, query, headers_lower, signed, region,
                  payload_hash=None):
    """Server-side SigV4 recomputation (mirrors the spec, written against
    the AWS docs independently of the client). `headers_lower` is the
    received header map lowercased; `signed` the SignedHeaders list."""
    amz_date = headers_lower["x-amz-date"]
    datestamp = amz_date[:8]
    canon_headers = "".join(
        f"{k}:{headers_lower[k].strip()}\n" for k in signed.split(";"))
    canonical = "\n".join([
        method, urllib.parse.quote(path, safe="/-_.~"), query,
        canon_headers, signed,
        payload_hash or hashlib.sha256(b"").hexdigest()])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(canonical.encode()).hexdigest()])

    def h(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()
    key = h(h(h(h(("AWS4" + SECRET).encode(), datestamp),
              region), "s3"), "aws4_request")
    return hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()


class _FakeS3(http.server.BaseHTTPRequestHandler):
    objects = {}       # "bucket/key" -> bytes
    fail_once = set()
    region = "us-east-1"
    verify_auth = True
    page_size = 2

    def log_message(self, *a):
        pass

    def _check_sig(self, path, query, method="GET", payload_hash=None):
        if not self.verify_auth:
            return True
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            self.send_error(403, "missing SigV4")
            return False
        hdrs = {k.lower(): v for k, v in self.headers.items()}
        signed = auth.split("SignedHeaders=")[1].split(",")[0].strip()
        want = auth.split("Signature=")[1].strip()
        got = _expected_sig(method, path, query, hdrs, signed, self.region,
                            payload_hash)
        if want != got:
            self.send_error(403, "bad signature")
            return False
        return True

    def do_PUT(self):
        parsed = urllib.parse.urlparse(self.path)
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        # the signed payload hash must MATCH the body (tamper detection)
        claimed = self.headers.get("x-amz-content-sha256", "")
        if claimed != hashlib.sha256(body).hexdigest():
            self.send_error(400, "payload hash mismatch")
            return
        if not self._check_sig(parsed.path, parsed.query, method="PUT",
                               payload_hash=claimed):
            return
        parts = parsed.path.lstrip("/").split("/", 1)
        if len(parts) != 2:
            self.send_error(400)
            return
        self.objects[f"{parts[0]}/{parts[1]}"] = body
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        if not self._check_sig(parsed.path, parsed.query):
            return
        parts = parsed.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = parts[1] if len(parts) > 1 else ""
        if not key:  # ListObjectsV2
            prefix = qs.get("prefix", [""])[0]
            names = sorted(k.split("/", 1)[1] for k in self.objects
                           if k.startswith(bucket + "/"))
            names = [n for n in names if n.startswith(prefix)]
            start = int(qs.get("continuation-token", ["0"])[0])
            page = names[start:start + self.page_size]
            trunc = start + self.page_size < len(names)
            items = "".join(
                f"<Contents><Key>{n}</Key><Size>"
                f"{len(self.objects[f'{bucket}/{n}'])}</Size></Contents>"
                for n in page)
            nxt = (f"<NextContinuationToken>{start + self.page_size}"
                   f"</NextContinuationToken>" if trunc else "")
            body = (f'<?xml version="1.0"?><ListBucketResult>'
                    f"<IsTruncated>{'true' if trunc else 'false'}"
                    f"</IsTruncated>{items}{nxt}</ListBucketResult>"
                    ).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        obj = self.objects.get(f"{bucket}/{key}")
        if obj is None:
            self.send_error(404)
            return
        start = 0
        rng = self.headers.get("Range")
        if rng:
            lo, _, hi = rng.split("=")[1].partition("-")
            start = int(lo)
            self.send_response(206)
            end = int(hi) if hi else len(obj) - 1
            body = obj[start:end + 1]
            self.send_header("Content-Range",
                             f"bytes {start}-{end}/{len(obj)}")
        else:
            self.send_response(200)
            body = obj
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if key in self.fail_once:
            self.fail_once.discard(key)
            self.wfile.write(body[: max(1, len(body) // 2)])
            self.wfile.flush()
            self.connection.close()
            return
        self.wfile.write(body)


@pytest.fixture
def s3(tmp_path, monkeypatch):
    root = str(tmp_path / "local")
    imagenet.write_synthetic_shards(root, n_shards=3, per_shard=6, size=48)
    objects = {}
    for f in sorted(os.listdir(root)):
        with open(os.path.join(root, f), "rb") as fh:
            objects[f"bkt/imagenet/{f}"] = fh.read()
    _FakeS3.objects = objects
    _FakeS3.fail_once = set()
    _FakeS3.verify_auth = True
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _FakeS3)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    monkeypatch.setenv("AWS_ENDPOINT_URL",
                       f"http://127.0.0.1:{srv.server_address[1]}")
    monkeypatch.setenv("AWS_ACCESS_KEY_ID", ACCESS)
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", SECRET)
    monkeypatch.setenv("AWS_REGION", "us-east-1")
    monkeypatch.setenv("no_proxy", "*")
    from sparknet_tpu.data import gcs as gcs_mod, s3 as s3_mod
    monkeypatch.setattr(gcs_mod, "BACKOFF_S", 0.01)
    s3_mod._CLIENTS.clear()
    s3_mod._SIZE_CACHE.clear()
    yield "s3://bkt/imagenet", root
    srv.shutdown()


def test_s3_list_and_labels_signed(s3):
    """Listing + label fetch work, and the server ACCEPTED the SigV4 it
    verified — a wrong signature is rejected (negative control)."""
    url, root = s3
    remote = imagenet.list_shards(url, prefix="train.")
    local = imagenet.list_shards(root, prefix="train.")
    assert [os.path.basename(p) for p in remote] == \
        [os.path.basename(p) for p in local]
    assert len(remote) == 3  # > page_size: pagination exercised
    assert imagenet.load_label_map(f"{url}/train.txt") == \
        imagenet.load_label_map(os.path.join(root, "train.txt"))


def test_s3_bad_secret_rejected(s3, monkeypatch):
    from sparknet_tpu.data import s3 as s3_mod
    import urllib.error
    monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "wrong")
    s3_mod._CLIENTS.clear()
    with pytest.raises(urllib.error.HTTPError):
        imagenet.list_shards(s3[0])


def test_s3_loader_bit_identical_to_local(s3):
    url, root = s3
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    s = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    l = imagenet.ShardedTarLoader(imagenet.list_shards(root), labels,
                                  height=32, width=32)
    si, sl = s.load_all()
    li, ll = l.load_all()
    np.testing.assert_array_equal(si, li)
    np.testing.assert_array_equal(sl, ll)


def test_s3_stream_resumes_after_disconnect(s3):
    """Truncated body mid-tar -> signed ranged reconnect -> identical
    data (the reference's S3 streams had no such resilience)."""
    url, root = s3
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    _FakeS3.fail_once = {"imagenet/train.0000.tar"}
    s = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    l = imagenet.ShardedTarLoader(imagenet.list_shards(root), labels,
                                  height=32, width=32)
    np.testing.assert_array_equal(s.load_all()[0], l.load_all()[0])


def test_s3_mid_shard_seek_and_size(s3):
    url, root = s3
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    all_pos = [(lbl, pos) for _, lbl, pos in imagenet.ShardedTarLoader(
        imagenet.list_shards(root), labels, 32, 32).iter_with_pos()]
    mid = all_pos[7][1]
    cont = [(lbl, pos) for _, lbl, pos in imagenet.ShardedTarLoader(
        imagenet.list_shards(url), labels, 32, 32).iter_with_pos(mid)]
    assert cont == all_pos[8:]
    for g, l in zip(imagenet.list_shards(url), imagenet.list_shards(root)):
        assert imagenet.path_size(g) == os.path.getsize(l)
    # cold-cache size: ranged HEAD-equivalent (Content-Range total)
    from sparknet_tpu.data import s3 as s3_mod
    s3_mod._SIZE_CACHE.clear()
    g0, l0 = imagenet.list_shards(url)[0], imagenet.list_shards(root)[0]
    assert imagenet.path_size(g0) == os.path.getsize(l0)


def test_s3_upload_roundtrip_and_sharder_push(s3, tmp_path):
    """s3_write PUTs with a signed payload hash (server verifies both the
    signature AND that the hash matches the body); the sharder's --upload
    path pushes a whole shard dir and the loader reads it back
    bit-identically — the reference's put_imagenet_on_s3 story end to
    end."""
    import sys
    url, root = s3
    from sparknet_tpu.data.s3 import s3_read, s3_write
    s3_write("s3://bkt/up/x.bin", b"hello-shards")
    assert s3_read("s3://bkt/up/x.bin") == b"hello-shards"

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    import shard_imagenet
    n = shard_imagenet.upload_dir(root, "s3://bkt2/imagenet")
    assert n == 4  # 3 shards + train.txt
    labels = imagenet.load_label_map("s3://bkt2/imagenet/train.txt")
    up = imagenet.ShardedTarLoader(
        imagenet.list_shards("s3://bkt2/imagenet"), labels, 32, 32)
    local = imagenet.ShardedTarLoader(
        imagenet.list_shards(root), labels, 32, 32)
    np.testing.assert_array_equal(up.load_all()[0], local.load_all()[0])
    with pytest.raises(SystemExit, match="gs:// or s3://"):
        shard_imagenet.upload_dir(root, "/local/path")


def test_s3_second_epoch_carve_bit_identical(s3):
    """The r5 bucket member-carve path (see test_gcs) over the SigV4
    transport: epoch 2 slices members by the captured index, bytes
    identical to the tarfile epoch."""
    url, root = s3
    labels = imagenet.load_label_map(os.path.join(root, "train.txt"))
    s = imagenet.ShardedTarLoader(imagenet.list_shards(url), labels,
                                  height=32, width=32)
    e1 = s.load_all()
    assert s._bucket_indices  # index captured on the full first epoch
    e2 = s.load_all()
    np.testing.assert_array_equal(e1[0], e2[0])
    np.testing.assert_array_equal(e1[1], e2[1])
    assert s.skipped == 0
