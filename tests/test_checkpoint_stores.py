"""The checkpoint test matrix over ALL THREE store kinds — local dir,
gs:// (fake GCS with resumable/compose uploads), s3:// (SigV4-verifying
fake with multipart uploads). Every semantic PR 1 established (per-array
digests, verify, corrupt-latest fallback, anomalous tagging, retention
protecting the newest verified snapshot, uncommitted-save invisibility)
must hold identically against bucket URIs: `restore_newest_verified` is
the health supervisor's rollback selector and pod runs point
checkpoint_dir at a bucket. Plus the AsyncCheckpointWriter unit contract
(single flight, backpressure, loud failure) and the async train-loop
round trip against a bucket."""
import io
import json
import os
import threading
import time

import numpy as np
import pytest

from sparknet_tpu.utils import checkpoint as ckpt

pytestmark = pytest.mark.filterwarnings("ignore::RuntimeWarning")


@pytest.fixture(params=["local", "gs", "s3"])
def store(request, tmp_path, monkeypatch):
    """(checkpoint directory, mutate_state_fn, drop_meta_fn). The mutators
    corrupt / decommit a given step the way that store kind gets torn:
    byte flips in state.npz, meta.json removed (a writer killed before the
    commit marker landed)."""
    from fake_stores import corrupt_npz_bytes as _silently_corrupt
    kind = request.param

    if kind == "local":
        d = str(tmp_path / "ck")

        def mutate(step):
            p = os.path.join(d, f"step-{step}", "state.npz")
            with open(p, "rb") as f:
                raw = f.read()
            with open(p, "wb") as f:
                f.write(_silently_corrupt(raw))

        def drop_meta(step):
            os.remove(os.path.join(d, f"step-{step}", "meta.json"))

        yield d, mutate, drop_meta
        return
    import contextlib

    from fake_stores import bucket_store
    from sparknet_tpu.data import gcs as gcs_mod, s3 as s3_mod
    # small chunks/parts so modest test states exercise the PARALLEL
    # upload paths (multiple resumable sessions + compose / multipart)
    monkeypatch.setattr(gcs_mod, "GS_UPLOAD_CHUNK", 256 << 10)
    monkeypatch.setattr(s3_mod, "S3_UPLOAD_PART", 256 << 10)
    with contextlib.ExitStack() as stack:
        # bucket_store is the shared bootstrap (env, caches, backoff) the
        # bench uses too — one place, no drift
        root, srv = stack.enter_context(bucket_store(kind))
        d = f"{root}/ck"
        # fake-GCS object keys carry no bucket; fake-S3 keys do
        key = (lambda s, f: f"ck/step-{s}/{f}") if kind == "gs" else \
            (lambda s, f: f"bkt/ck/step-{s}/{f}")
        handler = srv.handler

        def mutate(step):
            handler.objects[key(step, "state.npz")] = _silently_corrupt(
                handler.objects[key(step, "state.npz")])

        def drop_meta(step):
            handler.objects.pop(key(step, "meta.json"), None)

        yield d, mutate, drop_meta


def _tree(seed, with_bf16=True):
    r = np.random.default_rng(seed)
    t = {"a": {"w": r.standard_normal((64, 33)).astype(np.float32),
               "b": r.standard_normal((33,)).astype(np.float32)},
         "it": np.asarray([seed] * 4, np.int32)}
    if with_bf16:
        import ml_dtypes
        t["a"]["v"] = r.standard_normal((16,)).astype(ml_dtypes.bfloat16)
    return t


def _assert_tree_equal(flat, tree):
    np.testing.assert_array_equal(flat["a/w"], tree["a"]["w"])
    np.testing.assert_array_equal(flat["it"], tree["it"])
    if "a/v" in flat:
        assert flat["a/v"].dtype == tree["a"]["v"].dtype
        np.testing.assert_array_equal(
            flat["a/v"].view(np.uint16), tree["a"]["v"].view(np.uint16))


def test_roundtrip_latest_verify(store):
    d, _, _ = store
    trees = {s: _tree(s) for s in (1, 2, 3)}
    for s, t in trees.items():
        path = ckpt.save(d, t, step=s, extra={"n_devices": 4})
        assert ckpt.verify(path)
    assert ckpt.latest_step(d) == 3
    flat, step, extra = ckpt.restore_flat(d)
    assert step == 3 and extra["n_devices"] == 4
    _assert_tree_equal(flat, trees[3])
    flat1, s1, _ = ckpt.restore_flat(d, step=1)
    assert s1 == 1
    _assert_tree_equal(flat1, trees[1])


def test_digests_byte_identical_across_stores(store, tmp_path):
    """The bucket writer must persist the SAME bytes the local store does:
    the per-array sha256 digests in meta.json are computed pre-store, so
    equal digests == byte-identical state payload."""
    d, _, _ = store
    tree = _tree(7)
    path = ckpt.save(d, tree, step=1)
    local = ckpt.save(str(tmp_path / "ref"), tree, step=1)
    if ckpt.is_bucket_path(path):
        meta = json.loads(ckpt._bucket_ops(path).read(f"{path}/meta.json"))
    else:
        meta = json.load(open(os.path.join(path, "meta.json")))
    ref = json.load(open(os.path.join(local, "meta.json")))
    assert meta["digests"] == ref["digests"]
    assert meta["keys"] == ref["keys"]


def test_corrupt_latest_falls_back(store):
    d, mutate, _ = store
    trees = {s: _tree(s) for s in (1, 2, 3)}
    for s, t in trees.items():
        ckpt.save(d, t, step=s)
    mutate(3)
    assert not ckpt.verify(ckpt._join(d, "step-3"))
    assert ckpt.verify(ckpt._join(d, "step-2"))
    flat, step, _ = ckpt.restore_flat(d)
    assert step == 2
    _assert_tree_equal(flat, trees[2])
    with pytest.raises(ckpt.CheckpointCorruptError, match="digest"):
        ckpt.restore_flat(d, step=3)
    assert ckpt.newest_verified_step(d) == 2


def test_uncommitted_save_is_invisible(store):
    """A writer killed between the state upload and the meta.json commit
    marker leaves not-a-checkpoint: latest/restore skip it, and the next
    save sweeps the orphan."""
    d, _, drop_meta = store
    ckpt.save(d, _tree(1), step=1)
    ckpt.save(d, _tree(2), step=2)
    drop_meta(2)
    assert ckpt.latest_step(d) == 1
    flat, step, _ = ckpt.restore_flat(d)
    assert step == 1
    ckpt.save(d, _tree(3), step=3)  # sweeps the step-2 orphan
    assert ckpt._list_steps(d) in ([1, 3], [1, 2, 3])
    if ckpt.is_bucket_path(d):  # orphan state object actually deleted
        assert ckpt._list_steps(d) == [1, 3]


def test_anomalous_skipped_by_rollback_selector(store):
    d, _, _ = store
    ckpt.save(d, _tree(1), step=1)
    ckpt.save(d, _tree(2), step=2, extra={"anomalous": True})
    assert ckpt.newest_verified_step(d) == 2
    assert ckpt.newest_verified_step(d, skip_anomalous=True) == 1
    found = ckpt.restore_newest_verified(d, skip_anomalous=True)
    assert found is not None and found[1] == 1


def test_retain_protects_newest_verified(store):
    d, mutate, _ = store
    for s in range(1, 6):
        ckpt.save(d, _tree(s), step=s)
    mutate(4)
    mutate(5)
    # the mutation simulates SILENT at-rest corruption. For our own last
    # write, retain's written-and-verified cache legitimately trusts the
    # write-time digests while the store fingerprint is unchanged (the
    # documented trade — fake-GCS generations don't bump on an in-place
    # mutate, exactly like real at-rest rot); dropping the process-local
    # record models the realistic observer: a DIFFERENT process running
    # retention after the rot, which must do the full read-back
    ckpt.invalidate_written_cache()
    ckpt.retain(d, keep=2)
    # keep-window is {4, 5}, but 3 is the newest VERIFIED one: kept
    assert ckpt._list_steps(d) == [3, 4, 5]
    assert ckpt.newest_verified_step(d) == 3


def test_retain_plain(store):
    d, _, _ = store
    for s in range(1, 6):
        ckpt.save(d, _tree(s), step=s)
    ckpt.retain(d, keep=2)
    assert ckpt._list_steps(d) == [4, 5]
    assert ckpt.latest_step(d) == 5


def test_retain_skips_readback_for_own_last_write(store, monkeypatch):
    """The protect scan must NOT re-download + re-hash the newest snapshot
    when THIS process wrote it and the store fingerprint is unchanged —
    the per-save ~244 MB ranged-GET the cache exists to kill. A cleared
    cache (another process's retention) restores the full read-back."""
    d, _, _ = store
    for s in range(1, 4):
        ckpt.save(d, _tree(s), step=s)
    calls = []
    real_verify = ckpt.verify
    monkeypatch.setattr(ckpt, "verify",
                        lambda p: calls.append(p) or real_verify(p))
    ckpt.retain(d, keep=2)
    assert calls == [], "retain re-verified our own just-written step"
    assert ckpt._list_steps(d) == [2, 3]
    ckpt.invalidate_written_cache(d)
    ckpt.retain(d, keep=2)
    assert len(calls) == 1 and calls[0].endswith("step-3")


def test_retain_cache_invalidated_by_foreign_rewrite(store):
    """A step REWRITTEN after our save (another writer, different bytes
    -> different size) changes the fingerprint: retain falls back to the
    real verify and still catches that the rewrite is valid/invalid."""
    d, mutate, drop_meta = store
    for s in range(1, 4):
        ckpt.save(d, _tree(s), step=s)
    # simulate: OUR record of step 3 holds the fingerprint of the bytes
    # WE wrote, but the store now carries someone else's rewrite (any
    # fingerprint drift -> miss; drift is pinned directly here because
    # the fake stores' rewrite tokens vary by kind)
    fp_key = ckpt._cache_key(d)
    ckpt._written_verified[fp_key] = (3, ("stale-token", 0, 0))
    assert not ckpt._written_verified_hit(d, 3)
    ckpt.retain(d, keep=2)  # full verify path, nothing breaks
    assert ckpt._list_steps(d) == [2, 3]


def test_overwrite_same_step(store):
    """Re-saving an existing step replaces it atomically (the loop does
    this on a retried window after rollback)."""
    d, _, _ = store
    ckpt.save(d, _tree(1), step=1)
    t2 = _tree(9)
    ckpt.save(d, t2, step=1)
    flat, step, _ = ckpt.restore_flat(d)
    assert step == 1
    _assert_tree_equal(flat, t2)


def test_large_blob_parallel_upload_roundtrip(store):
    """A state large enough to take the chunked-parallel path (multiple
    GCS resumable sessions + compose / multiple S3 multipart parts) must
    round-trip bit-exactly through the ranged-GET restore."""
    d, _, _ = store
    r = np.random.default_rng(3)
    # ~2 MB >> the fixture's 256 KiB chunk: 4+ parallel parts
    tree = {"big": r.standard_normal((512, 1024)).astype(np.float32)}
    path = ckpt.save(d, tree, step=1)
    assert ckpt.verify(path)
    flat, step, _ = ckpt.restore_flat(d)
    np.testing.assert_array_equal(flat["big"], tree["big"])
    if ckpt.is_bucket_path(d):  # no stray .part- components left behind
        ops = ckpt._bucket_ops(d)
        assert not [u for u in ops.list_urls(d) if ".part-" in u]


# -- AsyncCheckpointWriter unit contract ------------------------------------


def test_async_writer_single_flight_and_backpressure():
    order = []
    gate = threading.Event()

    def slow():
        gate.wait(5)
        order.append("write1")

    w = ckpt.AsyncCheckpointWriter()
    try:
        w.submit(slow)
        assert w.in_flight
        t0 = time.perf_counter()
        gate.set()
        w.submit(lambda: order.append("write2"))  # waits out write1
        assert time.perf_counter() - t0 < 5
        assert order[0] == "write1"
        w.wait()
        assert order == ["write1", "write2"]
        assert not w.in_flight
    finally:
        w.close()


def test_async_writer_reraises_failure():
    w = ckpt.AsyncCheckpointWriter()
    try:
        w.submit(lambda: (_ for _ in ()).throw(IOError("store died")))
        with pytest.raises(IOError, match="store died"):
            w.submit(lambda: None)  # the NEXT save is where it surfaces
        w.wait()  # the queued lambda (if it ran) is clean
    finally:
        w.close()


def test_async_writer_close_waits():
    done = []
    w = ckpt.AsyncCheckpointWriter()
    w.submit(lambda: (time.sleep(0.1), done.append(1)))
    w.close(wait=True)
    assert done == [1]


# -- the async two-stage pipeline through the REAL train loop ---------------


def _mnist_run(tmp_path, ckdir, max_rounds, resume, async_=True):
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data import mnist
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.solver import SolverConfig
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    d = str(tmp_path / "mnist")
    if not os.path.isdir(d):
        mnist.write_synthetic(d, n_train=128, n_test=32)
    tr = mnist.MnistLoader(d).train_batch_dict()
    cfg = RunConfig(
        solver=SolverConfig(base_lr=0.01, momentum=0.9, lr_policy="fixed"),
        tau=2, local_batch=4, eval_every=0, max_rounds=max_rounds,
        workdir=str(tmp_path), seed=0, checkpoint_dir=ckdir,
        checkpoint_every=2, checkpoint_async=async_, resume=resume)
    return train(cfg, lenet(batch=cfg.local_batch), ArrayDataset(tr),
                 logger=Logger(echo=False))


@pytest.mark.parametrize("kind", ["gs", "local_sync"])
def test_train_loop_async_bucket_resume_exact(tmp_path, monkeypatch, kind):
    """The composed story: the loop's async two-stage saves land in a
    BUCKET (or a local dir with async off — the control), an interrupted
    run resumes from them, and the final params match an uninterrupted
    run bit-for-bit (same invariant the local resume test asserts)."""
    if kind == "gs":
        from fake_stores import serve_gcs, stop_serving
        srv, endpoint = serve_gcs()
        monkeypatch.setenv("STORAGE_EMULATOR_HOST", endpoint)
        monkeypatch.setenv("no_proxy", "*")
        ck_part, ck_full = "gs://bkt/ck_part", "gs://bkt/ck_full"
        async_ = True
    else:
        srv = None
        ck_part = str(tmp_path / "ck_part")
        ck_full = str(tmp_path / "ck_full")
        async_ = False
    try:
        full = _mnist_run(tmp_path, ck_full, 4, resume=False, async_=async_)
        _mnist_run(tmp_path, ck_part, 2, resume=False, async_=async_)
        assert ckpt.latest_step(ck_part) == 2
        resumed = _mnist_run(tmp_path, ck_part, 4, resume=True,
                             async_=async_)
        for lname in full.params:
            for pname in full.params[lname]:
                np.testing.assert_array_equal(
                    np.asarray(resumed.params[lname][pname]),
                    np.asarray(full.params[lname][pname]),
                    err_msg=f"{lname}/{pname}")
    finally:
        if srv is not None:
            stop_serving(srv)


# -- sharded layout (r8): per-shard files + manifest commit marker ----------


def _placed_state(n_dev=4, seed=0):
    """A small NamedSharding-placed state with every piece-plan shape:
    fully replicated leaves (chunked across shard files), data-sharded
    leaves (one piece per owner device), a bf16 extension-dtype leaf,
    and a replicated scalar."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from sparknet_tpu.parallel.mesh import make_mesh
    mesh = make_mesh(n_dev)
    r = np.random.default_rng(seed)

    def put(a, spec):
        return jax.device_put(a, NamedSharding(mesh, spec))

    tree = {
        "params": {"l1": {
            "w": put(r.standard_normal((12, 6)).astype(np.float32), P()),
            "b": put(r.standard_normal((6,)).astype(np.float32), P())}},
        "momentum": {"l1": {
            "w": put(jnp.asarray(r.standard_normal(
                (n_dev, 12, 6)), jnp.bfloat16), P("data")),
            "b": put(r.standard_normal(
                (n_dev, 6)).astype(np.float32), P("data"))}},
        "it": put(np.int32(5), P()),
    }
    return tree, mesh


def _shard_urls(d, step):
    if ckpt.is_bucket_path(d):
        return sorted(u for u in ckpt._bucket_ops(d).list_urls(
            f"{d.rstrip('/')}/step-{step}") if "/shard-" in u)
    sd = os.path.join(d, f"step-{step}")
    return sorted(os.path.join(sd, f) for f in os.listdir(sd)
                  if f.startswith("shard-"))


def _rewrite(d, url, mutate_fn):
    if ckpt.is_bucket_path(d):
        ops = ckpt._bucket_ops(d)
        ops.write(url, mutate_fn(ops.read(url)))
    else:
        with open(url, "rb") as f:
            raw = f.read()
        with open(url, "wb") as f:
            f.write(mutate_fn(raw))


def test_sharded_roundtrip_bitwise_matches_monolithic(store):
    """The sharded layout is a STORAGE format: restore_flat over a
    sharded save must return the exact flat map a monolithic save of the
    same state returns — keys, dtypes, bytes — and the logical bytes
    written are identical (no replicated leaf persisted twice)."""
    from sparknet_tpu.parallel.mesh import fetch_global, fetch_state_shards
    d, _, _ = store
    tree, mesh = _placed_state()
    snap = fetch_state_shards(tree, mesh)
    ckpt.save_sharded(d, snap, step=1, extra={"layout": "logical"})
    ckpt.save(d, fetch_global(tree), step=2, extra={"layout": "logical"})
    f_sh, s_sh, e_sh = ckpt.restore_flat(d, step=1)
    f_mono, _, _ = ckpt.restore_flat(d, step=2)
    # commit_ts is stamped per save (wall clock at manifest commit), so it
    # is present and differs between the two saves — strip it before the
    # caller-extra equality check.
    assert isinstance(e_sh.pop("commit_ts"), float)
    assert e_sh == {"layout": "logical"}
    assert sorted(f_sh) == sorted(f_mono)
    for k in f_mono:
        assert f_sh[k].dtype == f_mono[k].dtype, k
        np.testing.assert_array_equal(f_sh[k], f_mono[k], err_msg=k)
    assert ckpt.sharded_nbytes(snap) == sum(
        a.nbytes for a in f_mono.values())
    assert ckpt.verify(ckpt._join(d, "step-1"))
    # files: one per mesh device + the manifest commit marker
    assert len(_shard_urls(d, 1)) == 4


def test_sharded_corrupt_shard_detected_and_falls_back(store):
    """A flipped byte in ONE shard file is a digest mismatch: verify
    fails, explicit-step restore raises, auto-latest falls back to the
    previous step bit-exactly — the monolithic integrity story, per
    shard."""
    from fake_stores import corrupt_npz_bytes
    from sparknet_tpu.parallel.mesh import fetch_state_shards
    d, _, _ = store
    tree, mesh = _placed_state(seed=1)
    ckpt.save_sharded(d, fetch_state_shards(tree, mesh), step=1)
    ref, _, _ = ckpt.restore_flat(d, step=1)
    tree2, _ = _placed_state(seed=2)
    ckpt.save_sharded(d, fetch_state_shards(tree2, mesh), step=2)
    _rewrite(d, _shard_urls(d, 2)[1], corrupt_npz_bytes)
    assert not ckpt.verify(ckpt._join(d, "step-2"))
    with pytest.raises(ckpt.CheckpointCorruptError, match="digest"):
        ckpt.restore_flat(d, step=2)
    with pytest.warns(RuntimeWarning, match="digest mismatch"):
        flat, step, _ = ckpt.restore_flat(d)
    assert step == 1
    for k in ref:
        np.testing.assert_array_equal(flat[k], ref[k], err_msg=k)


def test_sharded_uncommitted_save_invisible_and_swept(store):
    """Orphan shard files (a writer killed before the manifest landed)
    are not-a-checkpoint, and the NEXT save's sweep removes them — the
    stale-.tmp rule taught about per-shard files."""
    from sparknet_tpu.parallel.mesh import fetch_state_shards
    d, _, drop_meta = store
    tree, mesh = _placed_state(seed=3)
    snap = fetch_state_shards(tree, mesh)
    ckpt.save_sharded(d, snap, step=1)
    ckpt.save_sharded(d, snap, step=2)
    drop_meta(2)  # the kill -9 shape: shards landed, commit marker gone
    assert ckpt.latest_step(d) == 1
    with pytest.warns(RuntimeWarning):
        _, step, _ = ckpt.restore_flat(d)
    assert step == 1
    ckpt.save_sharded(d, snap, step=3)  # sweep runs here
    if ckpt.is_bucket_path(d):
        assert _shard_urls(d, 2) == []
    else:
        assert not os.path.isdir(os.path.join(d, "step-2"))
    assert ckpt.latest_step(d) == 3


def test_sharded_overwrite_clears_stale_shards(store):
    """Overwriting a step with a NARROWER sharded save (fewer devices ->
    fewer files) must not leave the old save's extra shard files behind
    to pair with the new manifest."""
    from sparknet_tpu.parallel.mesh import fetch_state_shards
    d, _, _ = store
    tree4, mesh4 = _placed_state(n_dev=4, seed=4)
    ckpt.save_sharded(d, fetch_state_shards(tree4, mesh4), step=1)
    assert len(_shard_urls(d, 1)) == 4
    tree2, mesh2 = _placed_state(n_dev=2, seed=5)
    ckpt.save_sharded(d, fetch_state_shards(tree2, mesh2), step=1)
    assert len(_shard_urls(d, 1)) == 2
    flat, _, _ = ckpt.restore_flat(d, step=1)
    from sparknet_tpu.parallel.mesh import fetch_global
    ref = ckpt._flatten(fetch_global(tree2))
    for k in ref:
        np.testing.assert_array_equal(flat[k], ref[k], err_msg=k)


def test_sharded_retain_written_cache_covers_all_shards(store,
                                                        monkeypatch):
    """retain()'s read-back-skip cache fingerprints EVERY shard file of
    a sharded save: unchanged -> no re-verify; ONE rewritten shard ->
    full read-back (which then catches a corrupt rewrite)."""
    from fake_stores import corrupt_npz_bytes
    from sparknet_tpu.parallel.mesh import fetch_state_shards
    d, _, _ = store
    tree, mesh = _placed_state(seed=6)
    snap = fetch_state_shards(tree, mesh)
    for s in (1, 2, 3):
        ckpt.save_sharded(d, snap, step=s)
    calls = []
    real_verify = ckpt.verify
    monkeypatch.setattr(ckpt, "verify",
                        lambda p: calls.append(p) or real_verify(p))
    ckpt.retain(d, keep=2)
    assert calls == [], "retain re-verified our own just-written shards"
    _rewrite(d, _shard_urls(d, 3)[0], corrupt_npz_bytes)
    ckpt.retain(d, keep=2)
    assert len(calls) >= 1, "rewritten shard did not invalidate the cache"
    # and the corrupt newest step no longer counts as verified
    assert ckpt.newest_verified_step(d) == 2


def test_sharded_multiprocess_commit_protocol(tmp_path):
    """The multi-host write path, driven in-process: two 'processes'
    each persist their own shard files + digest report; the manifest
    commits only once every report landed, and the restored map is the
    full state. (Real pods run this per process — structurally the same
    calls.)"""
    from sparknet_tpu.parallel.mesh import fetch_state_shards, fetch_global
    d = str(tmp_path / "ck")
    tree, mesh = _placed_state(n_dev=2, seed=7)
    snap = fetch_state_shards(tree, mesh)
    ref = ckpt._flatten(fetch_global(tree))

    def proc_view(p):
        view = {"n_shards": snap["n_shards"],
                "owners": {0: 0, 1: 1},  # file i owned by process i
                "process_index": p, "process_count": 2, "leaves": {}}
        for key, rec in snap["leaves"].items():
            view["leaves"][key] = {
                "shape": rec["shape"], "dtype": rec["dtype"],
                "pieces": [(f, o, s, (a if f == p else None))
                           for f, o, s, a in rec["pieces"]]}
        return view

    # a PREVIOUS incarnation's crashed save left a stale digest report
    # (and, say, a half-written shard): the stage-1 prepare — process 0
    # + barrier, before any stage-2 write — must clear it so the commit
    # poll can never stamp dead digests into the new manifest
    os.makedirs(os.path.join(d, "step-1"))
    with open(os.path.join(d, "step-1", "commit-1.json"), "w") as f:
        json.dump({ckpt.shard_file_name(1, 2): "deadbeef" * 8}, f)
    ckpt.prepare_sharded_step(d, 1)
    assert not os.path.exists(os.path.join(d, "step-1", "commit-1.json"))

    # process 1 writes first (its shards + report); step stays invisible
    ckpt.save_sharded(d, proc_view(1), step=1)
    assert ckpt.latest_step(d) is None
    # process 0 writes its shards, collects the reports, commits meta
    ckpt.save_sharded(d, proc_view(0), step=1)
    assert ckpt.latest_step(d) == 1
    flat, _, _ = ckpt.restore_flat(d, step=1)
    for k in ref:
        np.testing.assert_array_equal(flat[k], ref[k], err_msg=k)
    # commit reports were cleaned up after the manifest landed
    left = os.listdir(os.path.join(d, "step-1"))
    assert not [f for f in left if f.startswith("commit-")], left


def test_sharded_writer_metrics_scope_labels(tmp_path):
    """The AsyncCheckpointWriter families carry scope labels: the whole
    stage-2 closure as scope='snapshot', each shard file write as
    scope='shard', the manifest commit as scope='meta' — podview's
    slow-shard attribution input."""
    from sparknet_tpu.obs import MetricsRegistry
    from sparknet_tpu.parallel.mesh import fetch_state_shards
    d = str(tmp_path / "ck")
    tree, mesh = _placed_state(seed=8)
    snap = fetch_state_shards(tree, mesh)
    reg = MetricsRegistry()
    w = ckpt.AsyncCheckpointWriter(registry=reg)
    try:
        w.submit(lambda: ckpt.save_sharded(d, snap, step=1,
                                           metrics=w.note_write))
        w.wait()
    finally:
        w.close()
    text = reg.render_prometheus()
    writes = [ln for ln in text.splitlines()
              if ln.startswith("sparknet_checkpoint_writes_total{")]
    for scope in ("snapshot", "shard", "meta"):
        assert any(f'scope="{scope}"' in ln and 'outcome="ok"' in ln
                   for ln in writes), (scope, writes)
    # the shard counter saw one inc per shard file
    shard_line = next(ln for ln in writes if 'scope="shard"' in ln)
    assert float(shard_line.rsplit(" ", 1)[1]) == 4.0, shard_line
    assert 'sparknet_checkpoint_write_seconds' in text
