"""RSS leak gates for the composed streaming system (r5, VERDICT weak #1).

The r4 soak attributed the TPU run's RSS growth to the dev tunnel because
a CPU-backend control held flat — but nothing FAILED if a future change
made the CPU path's slope nonzero. These are the tripwires. Two gates,
because on the CPU backend a full-size train round runs ~20x slower than
the same math un-shard_mapped (CPU-backend artifact, irrelevant on TPU),
so one test cannot have both big bytes and the full loop inside a CI
budget:

  1. BIG BYTES, no trainer: 150 rounds of ~4.7 MB preprocessed batches
     through the production ingest path (parallel shard readers -> C++
     decode -> ring -> ImagePreprocessor via the loop's own
     prepare_round_batches). This is where the byte-sized buffers live;
     a retained-batch leak accrues ~700 MB over the window.
  2. FULL LOOP, small shapes: 60 train() rounds (lenet) with per-round
     checkpoints and logging — the loop glue (metrics, hooks, checkpoint
     writer, loss pipeline) at CI speed.

The size-matched full-loop evidence at the r4 TPU soak's exact shapes is
the slower companion artifact: `scripts/soak_stream.py --cpu-control`
-> SOAK_CONTROL_r05.json (300 rounds, 4.31 MB/round, RSS 830 -> 802 MB:
flat).
"""
import json
import os

import numpy as np
import pytest


def _rss_mb() -> float:
    with open("/proc/self/status") as f:
        for ln in f:
            if ln.startswith("VmRSS:"):
                return int(ln.split()[1]) / 1024.0
    return -1.0


@pytest.mark.slow
def test_ingest_pipeline_rss_flat(tmp_path):
    """Gate 1: production ingest at soak byte size, RSS flat."""
    from sparknet_tpu import precision
    from sparknet_tpu.apps.train_loop import prepare_round_batches
    from sparknet_tpu.data import imagenet
    from sparknet_tpu.data.preprocess import ImagePreprocessor
    from sparknet_tpu.data.streaming import make_parallel_source
    from sparknet_tpu.schema import Field, Schema

    size, crop, b, tau, rounds = 72, 67, 32, 5, 150
    root = str(tmp_path / "shards")
    label_path = imagenet.write_synthetic_shards(
        root, n_shards=8, per_shard=256, n_classes=16, size=size)
    labels = imagenet.load_label_map(label_path)
    schema = Schema(Field("data", "float32", (crop, crop, 3)),
                    Field("label", "int32", (1,)))
    pp = ImagePreprocessor(schema, mean_image=None, crop=crop, seed=0,
                           out_dtype="bfloat16")
    cdt = precision.compute_dtype()
    src = make_parallel_source(imagenet.list_shards(root), labels, 1, b,
                               tau, 4, height=size, width=size)
    samples = {}
    with src:
        for rnd in range(rounds):
            batches = prepare_round_batches(src, rnd, tau, 0, pp, cdt)
            assert batches["data"].shape[1] == b
            samples[rnd] = _rss_mb()
    assert src.skipped == 0
    baseline = max(v for r, v in samples.items() if 15 <= r <= 40)
    steady = float(np.median([v for r, v in samples.items()
                              if r >= rounds - 15]))
    growth = steady - baseline
    # one retained round is ~4.7 MB f32 (or 2.4 MB bf16): a leak accrues
    # ~260-500 MB over the asserted ~110 rounds
    assert growth < 40.0, (
        f"RSS grew {growth:.1f} MB from post-warmup peak {baseline:.1f} "
        f"to steady {steady:.1f} over ~{rounds - 40} ingest rounds of "
        f"~4.7 MB each — the ingest pipeline is retaining memory "
        f"(samples: {sorted(samples.items())[::15]})")


@pytest.mark.slow
def test_train_loop_rss_flat(tmp_path):
    """Gate 2: the full train() loop (checkpoints, metrics, loss
    pipeline, round hooks) holds RSS flat at CI shapes."""
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data import imagenet
    from sparknet_tpu.data.streaming import make_parallel_source
    from sparknet_tpu.utils.config import RunConfig
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import lenet

    size, b, tau, rounds = 28, 8, 2, 60
    root = str(tmp_path / "shards")
    label_path = imagenet.write_synthetic_shards(
        root, n_shards=4, per_shard=64, n_classes=10, size=size)
    labels = imagenet.load_label_map(label_path)
    src = make_parallel_source(imagenet.list_shards(root), labels, 1, b,
                               tau, 2, height=size, width=size)

    class GrayTo28:
        def convert_batch(self, batch, train=True, rng=None):
            x = batch["data"].astype(np.float32).mean(axis=1)
            return {"data": x[..., None], "label": batch["label"]}

    cfg = RunConfig(model="lenet", n_classes=10, n_devices=1,
                    local_batch=b, tau=tau, max_rounds=rounds,
                    eval_every=0, precision="float32",
                    workdir=str(tmp_path / "wk"),
                    checkpoint_dir=str(tmp_path / "ck"),
                    checkpoint_every=10, log_every=4, seed=0)
    samples = {}

    def hook(rnd, state):
        samples[rnd] = _rss_mb()

    jsonl = str(tmp_path / "m.jsonl")
    train(cfg, lenet(batch=b), src, None,
          logger=Logger(str(tmp_path / "log.txt"), echo=False,
                        jsonl_path=jsonl),
          batch_transform=GrayTo28(), round_hook=hook)
    losses = [json.loads(ln)["loss"] for ln in open(jsonl) if "loss" in ln]
    assert len(losses) == rounds and np.isfinite(losses).all()
    baseline = max(v for r, v in samples.items() if 10 <= r <= 25)
    steady = float(np.median([v for r, v in samples.items()
                              if r >= rounds - 8]))
    growth = steady - baseline
    assert growth < 25.0, (
        f"RSS grew {growth:.1f} MB from post-warmup peak {baseline:.1f} "
        f"to steady {steady:.1f} over the train() loop "
        f"(samples: {sorted(samples.items())[::6]})")
