"""Local fake object-store servers shared by the gs://|s3:// tests, the
chaos tests' bucket variants, `bench.py --e2e --store gs` and
`bench.py --checkpoint-stall` (the bucket checkpoint measurements). Moved
out of test_gcs.py in r5 so non-pytest callers (bench, chaos subprocesses)
can serve a bucket without importing a test module's fixtures.

Handler STATE IS PER SERVER (r6, ADVICE r5 #2): `make_gcs_handler()` /
`make_s3_handler()` mint a fresh subclass holding its own `objects` /
`fail_once` / `range_log` / session dicts, so two fake servers coexist in
one process and `stop_serving` can drop a served corpus from RSS. The
module-level `FakeGcsHandler` base keeps its (empty) class attrs so legacy
imports still resolve; servers returned by the helpers expose the live
class as `srv.handler`.

The GCS fake speaks the write-side subset the checkpoint store needs:
simple media upload, RESUMABLE upload sessions (initiate -> chunk PUTs
with Content-Range -> 308/200, object visible only on finalize), compose,
object DELETE, and per-object `generation` metadata (bumped on every
write — the member-index freshness token). The S3 fake verifies AWS
SigV4 on every request and additionally speaks multipart upload
(initiate/part/complete/abort), ETag metadata, and DELETE.
"""
from __future__ import annotations

import contextlib
import hashlib
import hmac
import http.server
import json
import os
import threading
import time
import urllib.parse

#: range_log entries are capped so a long in-process soak (which measures
#: its OWN RSS) doesn't accumulate instrumentation forever; tests clear
#: the log before asserting and never approach the cap
RANGE_LOG_CAP = 10_000


class FakeGcsHandler(http.server.BaseHTTPRequestHandler):
    """JSON-API subset: paginated listing, alt=media with Range,
    ?fields= metadata, media + resumable uploads, compose, delete.
    Knobs (class attrs on the per-server subclass):
      fail_once    — object names whose next media GET truncates mid-body
                     (Content-Length lies), exercising reconnect-resume
      ignore_range — serve 200-from-zero despite a Range header (a broken
                     middlebox); the client must fail loudly, not corrupt
      upload_delay_s — sleep per resumable-chunk PUT (widens the
                     mid-upload window the kill -9 chaos test aims at)
    """
    objects = {}
    generations = {}
    sessions = {}       # resumable sid -> {name, data, total}
    fail_once = set()
    ignore_range = False
    page_size = 2
    range_log = []
    upload_delay_s = 0.0

    def log_message(self, *a):
        pass

    def _bump(self, name):
        cls = type(self)
        cls.generations[name] = cls.generations.get(name, 0) + 1

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        parts = parsed.path.split("/")
        # /storage/v1/b/<bucket>/o[/<name>]
        if len(parts) < 6 or parts[1:4] != ["storage", "v1", "b"] or \
                parts[5] != "o":
            self.send_error(404)
            return
        if len(parts) == 6:  # listing
            prefix = qs.get("prefix", [""])[0]
            names = sorted(n for n in self.objects if n.startswith(prefix))
            start = int(qs.get("pageToken", ["0"])[0])
            page = names[start:start + self.page_size]
            d = {"items": [{"name": n, "size": str(len(self.objects[n])),
                            "generation": str(self.generations.get(n, 1))}
                           for n in page]}
            if start + self.page_size < len(names):
                d["nextPageToken"] = str(start + self.page_size)
            self._json(d)
            return
        name = urllib.parse.unquote(parts[6])
        if name not in self.objects:
            self.send_error(404)
            return
        data = self.objects[name]
        if qs.get("alt") == ["media"]:
            start = 0
            rng = self.headers.get("Range")
            if rng and len(type(self).range_log) < RANGE_LOG_CAP:
                type(self).range_log.append((name, rng))
            if rng and not self.ignore_range:
                start = int(rng.split("=")[1].split("-")[0])
                self.send_response(206)
            else:
                self.send_response(200)
            body = data[start:]
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if name in self.fail_once:  # truncate: client must resume
                self.fail_once.discard(name)
                self.wfile.write(body[: max(1, len(body) // 2)])
                self.wfile.flush()
                self.connection.close()
                return
            self.wfile.write(body)
            return
        self._json({"size": str(len(data)),  # metadata
                    "generation": str(self.generations.get(name, 1))})

    def _json(self, d, code=200, extra_headers=()):
        body = json.dumps(d).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        for k, v in extra_headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        parts = parsed.path.split("/")
        # compose: /storage/v1/b/<bucket>/o/<name>/compose
        if len(parts) == 8 and parts[1:4] == ["storage", "v1", "b"] and \
                parts[5] == "o" and parts[7] == "compose":
            name = urllib.parse.unquote(parts[6])
            body = self.rfile.read(int(self.headers.get("Content-Length",
                                                        0)))
            srcs = [s["name"] for s in
                    json.loads(body).get("sourceObjects", [])]
            if any(s not in self.objects for s in srcs):
                self.send_error(404, "compose source missing")
                return
            type(self).objects[name] = b"".join(self.objects[s]
                                                for s in srcs)
            self._bump(name)
            self._json({"name": name,
                        "size": str(len(self.objects[name]))})
            return
        # uploads: /upload/storage/v1/b/<bucket>/o
        if len(parts) < 7 or parts[1] != "upload":
            self.send_error(400)
            return
        if qs.get("uploadType") == ["media"] and "name" in qs:
            body = self.rfile.read(int(self.headers.get("Content-Length",
                                                        0)))
            name = qs["name"][0]
            type(self).objects[name] = body
            self._bump(name)
            self._json({"name": name, "size": str(len(body))})
            return
        if qs.get("uploadType") == ["resumable"] and "name" in qs:
            sid = os.urandom(8).hex()
            total = self.headers.get("x-upload-content-length")
            type(self).sessions[sid] = {
                "name": qs["name"][0], "data": bytearray(),
                "total": int(total) if total is not None else None}
            host = self.headers.get("Host", "127.0.0.1")
            self._json({}, extra_headers=(
                ("Location", f"http://{host}/upload/session/{sid}"),))
            return
        self.send_error(400)

    def do_PUT(self):
        # resumable chunk: /upload/session/<sid>
        parts = urllib.parse.urlparse(self.path).path.split("/")
        if len(parts) != 4 or parts[1:3] != ["upload", "session"]:
            self.send_error(404)
            return
        sess = self.sessions.get(parts[3])
        if sess is None:
            self.send_error(404, "no such upload session")
            return
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        if self.upload_delay_s:
            time.sleep(self.upload_delay_s)
        cr = self.headers.get("Content-Range", "")
        # "bytes a-b/total" or "bytes */total" (zero-byte finalize)
        rng, _, total_s = cr.partition("bytes ")[2].partition("/")
        total = int(total_s)
        if rng != "*":
            start = int(rng.split("-")[0])
            sess["data"][start:start + len(body)] = body
        if len(sess["data"]) >= total:
            name = sess["name"]
            type(self).objects[name] = bytes(sess["data"])
            self._bump(name)
            del type(self).sessions[parts[3]]
            self._json({"name": name, "size": str(total)})
            return
        self.send_response(308)
        self.send_header("Range", f"bytes=0-{len(sess['data']) - 1}")
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        parts = urllib.parse.urlparse(self.path).path.split("/")
        if len(parts) != 7 or parts[1:4] != ["storage", "v1", "b"] or \
                parts[5] != "o":
            self.send_error(404)
            return
        name = urllib.parse.unquote(parts[6])
        if name not in self.objects:
            self.send_error(404)
            return
        del type(self).objects[name]
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()


def make_gcs_handler():
    """A fresh FakeGcsHandler subclass with its OWN state dicts — one per
    server, so servers coexist and shutdown releases the corpus."""
    return type("FakeGcsHandlerInstance", (FakeGcsHandler,), dict(
        objects={}, generations={}, sessions={}, fail_once=set(),
        ignore_range=False, range_log=[], upload_delay_s=0.0))


# -- fake S3 (SigV4-verifying; moved from test_s3.py so bench/chaos can
#    serve s3:// buckets outside pytest) ------------------------------------

def expected_sigv4(method, path, query, headers_lower, signed, region,
                   secret, payload_hash=None):
    """Server-side SigV4 recomputation (mirrors the spec, written against
    the AWS docs independently of the client). `headers_lower` is the
    received header map lowercased; `signed` the SignedHeaders list."""
    amz_date = headers_lower["x-amz-date"]
    datestamp = amz_date[:8]
    canon_headers = "".join(
        f"{k}:{headers_lower[k].strip()}\n" for k in signed.split(";"))
    canonical = "\n".join([
        method, urllib.parse.quote(path, safe="/-_.~"), query,
        canon_headers, signed,
        payload_hash or hashlib.sha256(b"").hexdigest()])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(canonical.encode()).hexdigest()])

    def h(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()
    key = h(h(h(h(("AWS4" + secret).encode(), datestamp),
              region), "s3"), "aws4_request")
    return hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()


class FakeS3Handler(http.server.BaseHTTPRequestHandler):
    """Path-style S3 subset: ListObjectsV2, ranged GET, signed PUT,
    multipart upload (initiate/part/complete/abort), DELETE. Verifies the
    AWS Signature Version 4 on every request (recomputing it server-side
    from the shared secret) unless `verify_auth` is off."""
    objects = {}       # "bucket/key" -> bytes
    uploads = {}       # uploadId -> {"key": "bucket/key", "parts": {n: b}}
    fail_once = set()
    slowdown_once = set()  # keys whose next GET/PUT answers 503 SlowDown
    slowdown_log = []      # x-amz-date header of each throttled request
    region = "us-east-1"
    secret = "testsecret"
    verify_auth = True
    page_size = 2

    def log_message(self, *a):
        pass

    def _check_sig(self, path, query, method="GET", payload_hash=None):
        if not self.verify_auth:
            return True
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            self.send_error(403, "missing SigV4")
            return False
        hdrs = {k.lower(): v for k, v in self.headers.items()}
        signed = auth.split("SignedHeaders=")[1].split(",")[0].strip()
        want = auth.split("Signature=")[1].strip()
        got = expected_sigv4(method, path, query, hdrs, signed,
                             self.region, self.secret, payload_hash)
        if want != got:
            self.send_error(403, "bad signature")
            return False
        return True

    def _bucket_key(self, path):
        parts = path.lstrip("/").split("/", 1)
        return (parts[0], parts[1]) if len(parts) == 2 else (parts[0], "")

    def _etag(self, data):
        return hashlib.md5(data).hexdigest()

    def do_PUT(self):
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        # the signed payload hash must MATCH the body (tamper detection)
        claimed = self.headers.get("x-amz-content-sha256", "")
        if self.verify_auth and \
                claimed != hashlib.sha256(body).hexdigest():
            self.send_error(400, "payload hash mismatch")
            return
        if not self._check_sig(parsed.path, parsed.query, method="PUT",
                               payload_hash=claimed or None):
            return
        bucket, key = self._bucket_key(parsed.path)
        if not key:
            self.send_error(400)
            return
        if key in self.slowdown_once:
            # same `503 SlowDown` injection as do_GET: a throttled part
            # PUT must be retried by the transport with a FRESH
            # per-attempt SigV4 signature, never fail the whole upload
            self.slowdown_once.discard(key)
            self.slowdown_log.append(self.headers.get("x-amz-date"))
            err = (b'<?xml version="1.0"?><Error><Code>SlowDown</Code>'
                   b"</Error>")
            self.send_response(503)
            self.send_header("Retry-After", "0")
            self.send_header("Content-Length", str(len(err)))
            self.end_headers()
            self.wfile.write(err)
            return
        if "partNumber" in qs and "uploadId" in qs:  # UploadPart
            up = self.uploads.get(qs["uploadId"][0])
            if up is None or up["key"] != f"{bucket}/{key}":
                self.send_error(404, "no such upload")
                return
            up["parts"][int(qs["partNumber"][0])] = body
        else:
            type(self).objects[f"{bucket}/{key}"] = body
        self.send_response(200)
        self.send_header("ETag", f'"{self._etag(body)}"')
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_POST(self):
        parsed = urllib.parse.urlparse(self.path)
        # keep_blank_values: "?uploads=" (CreateMultipartUpload) must
        # survive parsing
        qs = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        claimed = self.headers.get("x-amz-content-sha256", "")
        if self.verify_auth and body and \
                claimed != hashlib.sha256(body).hexdigest():
            self.send_error(400, "payload hash mismatch")
            return
        if not self._check_sig(parsed.path, parsed.query, method="POST",
                               payload_hash=claimed or None):
            return
        bucket, key = self._bucket_key(parsed.path)
        if "uploads" in qs:  # CreateMultipartUpload
            uid = os.urandom(8).hex()
            type(self).uploads[uid] = {"key": f"{bucket}/{key}",
                                       "parts": {}}
            xml = (f'<?xml version="1.0"?><InitiateMultipartUploadResult>'
                   f"<Bucket>{bucket}</Bucket><Key>{key}</Key>"
                   f"<UploadId>{uid}</UploadId>"
                   f"</InitiateMultipartUploadResult>").encode()
            self._xml(xml)
            return
        if "uploadId" in qs:  # CompleteMultipartUpload
            up = self.uploads.get(qs["uploadId"][0])
            if up is None or up["key"] != f"{bucket}/{key}":
                self.send_error(404, "no such upload")
                return
            data = b"".join(up["parts"][n] for n in sorted(up["parts"]))
            type(self).objects[f"{bucket}/{key}"] = data
            del type(self).uploads[qs["uploadId"][0]]
            xml = (f'<?xml version="1.0"?><CompleteMultipartUploadResult>'
                   f'<ETag>"{self._etag(data)}"</ETag>'
                   f"</CompleteMultipartUploadResult>").encode()
            self._xml(xml)
            return
        self.send_error(400)

    def do_DELETE(self):
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        if not self._check_sig(parsed.path, parsed.query,
                               method="DELETE"):
            return
        bucket, key = self._bucket_key(parsed.path)
        if "uploadId" in qs:  # AbortMultipartUpload
            self.uploads.pop(qs["uploadId"][0], None)
        elif f"{bucket}/{key}" in self.objects:
            del type(self).objects[f"{bucket}/{key}"]
        else:
            self.send_error(404)
            return
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _xml(self, body):
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        if not self._check_sig(parsed.path, parsed.query):
            return
        bucket, key = self._bucket_key(parsed.path)
        if not key:  # ListObjectsV2
            prefix = qs.get("prefix", [""])[0]
            names = sorted(k.split("/", 1)[1] for k in self.objects
                           if k.startswith(bucket + "/"))
            names = [n for n in names if n.startswith(prefix)]
            start = int(qs.get("continuation-token", ["0"])[0])
            page = names[start:start + self.page_size]
            trunc = start + self.page_size < len(names)
            items = "".join(
                f"<Contents><Key>{n}</Key><Size>"
                f"{len(self.objects[f'{bucket}/{n}'])}</Size>"
                f'<ETag>"{self._etag(self.objects[f"{bucket}/{n}"])}"'
                f"</ETag></Contents>"
                for n in page)
            nxt = (f"<NextContinuationToken>{start + self.page_size}"
                   f"</NextContinuationToken>" if trunc else "")
            self._xml((f'<?xml version="1.0"?><ListBucketResult>'
                       f"<IsTruncated>{'true' if trunc else 'false'}"
                       f"</IsTruncated>{items}{nxt}</ListBucketResult>"
                       ).encode())
            return
        if key in self.slowdown_once:
            # AWS throttles with `503 SlowDown` (not 429), usually naming
            # its price in Retry-After — the client must back off and
            # retry with a FRESH SigV4 signature
            self.slowdown_once.discard(key)
            self.slowdown_log.append(self.headers.get("x-amz-date"))
            body = (b'<?xml version="1.0"?><Error><Code>SlowDown</Code>'
                    b"</Error>")
            self.send_response(503)
            self.send_header("Retry-After", "0")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        obj = self.objects.get(f"{bucket}/{key}")
        if obj is None:
            self.send_error(404)
            return
        start = 0
        rng = self.headers.get("Range")
        if rng:
            lo, _, hi = rng.split("=")[1].partition("-")
            start = int(lo)
            if start >= len(obj) and len(obj) == 0:
                self.send_response(416)
                self.send_header("Content-Range", f"bytes */{len(obj)}")
                self.send_header("ETag", f'"{self._etag(obj)}"')
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            self.send_response(206)
            end = int(hi) if hi else len(obj) - 1
            body = obj[start:end + 1]
            self.send_header("Content-Range",
                             f"bytes {start}-{end}/{len(obj)}")
        else:
            self.send_response(200)
            body = obj
        self.send_header("ETag", f'"{self._etag(obj)}"')
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if key in self.fail_once:
            self.fail_once.discard(key)
            self.wfile.write(body[: max(1, len(body) // 2)])
            self.wfile.flush()
            self.connection.close()
            return
        self.wfile.write(body)


def make_s3_handler(secret="testsecret", region="us-east-1",
                    verify_auth=True):
    """A fresh FakeS3Handler subclass with its OWN state (one per server)."""
    return type("FakeS3HandlerInstance", (FakeS3Handler,), dict(
        objects={}, uploads={}, fail_once=set(), slowdown_once=set(),
        slowdown_log=[], secret=secret, region=region,
        verify_auth=verify_auth))


# -- servers ----------------------------------------------------------------

def _serve(handler):
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    srv.handler = handler  # the per-server state lives on this class
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"


def serve_gcs(objects=None):
    """Fresh fake-GCS server (empty bucket unless `objects` given: a
    {name: bytes} map). Returns (server, endpoint_url); caller points
    STORAGE_EMULATOR_HOST at endpoint_url and calls stop_serving(server)."""
    handler = make_gcs_handler()
    if objects:
        handler.objects.update(objects)
        handler.generations.update({n: 1 for n in objects})
    return _serve(handler)


def serve_s3(objects=None, secret="testsecret", region="us-east-1",
             verify_auth=True):
    """Fresh fake-S3 server ({'bucket/key': bytes} corpus). Returns
    (server, endpoint_url) for AWS_ENDPOINT_URL."""
    handler = make_s3_handler(secret=secret, region=region,
                              verify_auth=verify_auth)
    if objects:
        handler.objects.update(objects)
    return _serve(handler)


def corrupt_npz_bytes(raw: bytes) -> bytes:
    """Flip one value inside an npz archive but rewrite a VALID archive
    (zip CRCs match): the silent at-rest corruption only the checkpoint
    store's recorded sha256 digests can catch. Bytes in, bytes out — the
    one canonical implementation for both the local-path and
    bucket-object corruption tests (a byte flip in the raw zip would tear
    the archive and exercise the WRONG failure path)."""
    import io

    import numpy as np
    with np.load(io.BytesIO(raw)) as z:
        arrs = {k: z[k].copy() for k in z.files}
    k = sorted(arrs)[0]
    flat = arrs[k].reshape(-1).view(np.uint8)
    flat[0] ^= 0x01
    buf = io.BytesIO()
    np.savez(buf, **arrs)
    return buf.getvalue()


@contextlib.contextmanager
def bucket_store(kind: str, objects=None, secret: str = "testsecret"):
    """Serve a fake bucket AND wire THIS process to it: sets the
    endpoint/credential env vars (prior values restored on exit), clears
    the gcs/s3 client + size/stat caches on entry AND exit (so a bench or
    script leaves no warm cache entries behind for later callers of the
    same bucket/prefix), and shortens the retry backoff so one flaky
    response can't sleep 0.5*2^n seconds inside a timed section. Yields
    (bucket_root_url, server). The non-pytest twin of the store fixtures
    in test_checkpoint_stores.py — bench `--checkpoint-stall` and scripts
    go through here so the three bootstraps can't drift."""
    from sparknet_tpu.data import gcs as gcs_mod, s3 as s3_mod
    keys = ("STORAGE_EMULATOR_HOST", "no_proxy", "AWS_ENDPOINT_URL",
            "AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY", "AWS_REGION")
    saved = {k: os.environ.get(k) for k in keys}
    saved_backoff = gcs_mod.BACKOFF_S

    def clear_caches():
        for m in (gcs_mod, s3_mod):
            m._CLIENTS.clear()
            m._SIZE_CACHE.clear()
            m._STAT_CACHE.clear()

    if kind == "gs":
        srv, endpoint = serve_gcs(objects)
        os.environ["STORAGE_EMULATOR_HOST"] = endpoint
    elif kind == "s3":
        srv, endpoint = serve_s3(objects, secret=secret)
        os.environ.update(AWS_ENDPOINT_URL=endpoint,
                          AWS_ACCESS_KEY_ID="AKFAKE",
                          AWS_SECRET_ACCESS_KEY=secret,
                          AWS_REGION="us-east-1")
    else:
        raise ValueError(f"bucket_store kind {kind!r}: gs or s3")
    os.environ["no_proxy"] = "*"
    gcs_mod.BACKOFF_S = 0.01
    clear_caches()
    try:
        yield f"{kind}://bkt", srv
    finally:
        stop_serving(srv)
        gcs_mod.BACKOFF_S = saved_backoff
        clear_caches()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _dir_objects(root: str, prefix: str):
    out = {}
    for f in sorted(os.listdir(root)):
        p = os.path.join(root, f)
        if os.path.isfile(p):
            with open(p, "rb") as fh:
                out[f"{prefix}/{f}"] = fh.read()
    return out


def serve_dir_as_gcs(root: str, prefix: str = "imagenet"):
    """Load every file under `root` into a fresh fake bucket as
    `<prefix>/<name>` and start a threaded server on 127.0.0.1:<free
    port>. Returns (server, endpoint_url); caller sets
    STORAGE_EMULATOR_HOST=endpoint_url and shuts the server down."""
    return serve_gcs(_dir_objects(root, prefix))


def serve_dir_for_ingest(root: str, prefix: str = "imagenet"):
    """serve_dir_as_gcs + the env wiring ingest callers need
    (STORAGE_EMULATOR_HOST, no_proxy). Returns (server, gs_url_root);
    call `stop_serving(server)` when done — shared by `bench.py --store
    gs` and `scripts/soak_stream.py --store gs` so the setup/cleanup
    can't drift between them. The PRIOR env values are remembered on the
    server and restored by stop_serving (the mutation must not outlive
    the fake server, ADVICE r5 #1)."""
    srv, endpoint = serve_dir_as_gcs(root, prefix)
    srv.saved_env = {k: os.environ.get(k)
                     for k in ("STORAGE_EMULATOR_HOST", "no_proxy")}
    os.environ["STORAGE_EMULATOR_HOST"] = endpoint
    os.environ["no_proxy"] = "*"
    return srv, f"gs://bkt/{prefix}"


def stop_serving(server) -> None:
    """Shut the server down, restore any env vars serve_dir_for_ingest
    saved, and drop the served corpus so it doesn't stay pinned in RSS."""
    server.shutdown()
    for k, v in getattr(server, "saved_env", {}).items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    handler = getattr(server, "handler", None)
    if handler is not None:
        handler.objects.clear()
        for attr in ("sessions", "uploads", "generations"):
            getattr(handler, attr, {}).clear()
