"""Local fake object-store servers shared by the gs:// tests, the chaos
test's bucket variant, and `bench.py --e2e --store gs` (the bucket-path
ingest measurement). Moved out of test_gcs.py in r5 so non-pytest callers
(bench, chaos subprocesses) can serve a bucket without importing a test
module's fixtures.
"""
from __future__ import annotations

import http.server
import json
import os
import threading
import urllib.parse


class FakeGcsHandler(http.server.BaseHTTPRequestHandler):
    """JSON-API subset: paginated listing, alt=media with Range, ?fields=size.
    Knobs (class attrs set by the caller):
      fail_once    — object names whose next media GET truncates mid-body
                     (Content-Length lies), exercising reconnect-resume
      ignore_range — serve 200-from-zero despite a Range header (a broken
                     middlebox); the client must fail loudly, not corrupt
    """
    objects = {}
    fail_once = set()
    ignore_range = False
    page_size = 2
    range_log = []

    def log_message(self, *a):
        pass

    def do_GET(self):
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        parts = parsed.path.split("/")
        # /storage/v1/b/<bucket>/o[/<name>]
        if len(parts) < 6 or parts[1:4] != ["storage", "v1", "b"] or \
                parts[5] != "o":
            self.send_error(404)
            return
        if len(parts) == 6:  # listing
            prefix = qs.get("prefix", [""])[0]
            names = sorted(n for n in self.objects if n.startswith(prefix))
            start = int(qs.get("pageToken", ["0"])[0])
            page = names[start:start + self.page_size]
            d = {"items": [{"name": n, "size": str(len(self.objects[n]))}
                           for n in page]}
            if start + self.page_size < len(names):
                d["nextPageToken"] = str(start + self.page_size)
            self._json(d)
            return
        name = urllib.parse.unquote(parts[6])
        if name not in self.objects:
            self.send_error(404)
            return
        data = self.objects[name]
        if qs.get("alt") == ["media"]:
            start = 0
            rng = self.headers.get("Range")
            if rng and len(type(self).range_log) < RANGE_LOG_CAP:
                type(self).range_log.append((name, rng))
            if rng and not self.ignore_range:
                start = int(rng.split("=")[1].split("-")[0])
                self.send_response(206)
            else:
                self.send_response(200)
            body = data[start:]
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if name in self.fail_once:  # truncate: client must resume
                self.fail_once.discard(name)
                self.wfile.write(body[: max(1, len(body) // 2)])
                self.wfile.flush()
                self.connection.close()
                return
            self.wfile.write(body)
            return
        self._json({"size": str(len(data))})  # metadata

    def _json(self, d):
        body = json.dumps(d).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # simple media upload
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        parts = parsed.path.split("/")
        # /upload/storage/v1/b/<bucket>/o?uploadType=media&name=...
        if len(parts) < 7 or parts[1] != "upload" or \
                qs.get("uploadType") != ["media"] or "name" not in qs:
            self.send_error(400)
            return
        body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
        self.objects[qs["name"][0]] = body
        self._json({"name": qs["name"][0], "size": str(len(body))})


#: range_log entries are capped so a long in-process soak (which measures
#: its OWN RSS) doesn't accumulate instrumentation forever; tests clear
#: the log before asserting and never approach the cap
RANGE_LOG_CAP = 10_000


def serve_dir_for_ingest(root: str, prefix: str = "imagenet"):
    """serve_dir_as_gcs + the env wiring ingest callers need
    (STORAGE_EMULATOR_HOST, no_proxy). Returns (server, gs_url_root);
    call `stop_serving(server)` when done — shared by `bench.py --store
    gs` and `scripts/soak_stream.py --store gs` so the setup/cleanup
    can't drift between them."""
    srv, endpoint = serve_dir_as_gcs(root, prefix)
    os.environ["STORAGE_EMULATOR_HOST"] = endpoint
    os.environ["no_proxy"] = "*"
    return srv, f"gs://bkt/{prefix}"


def stop_serving(server) -> None:
    server.shutdown()
    os.environ.pop("STORAGE_EMULATOR_HOST", None)


def serve_dir_as_gcs(root: str, prefix: str = "imagenet"):
    """Load every file under `root` into the fake bucket as
    `<prefix>/<name>` and start a threaded server on 127.0.0.1:<free
    port>. Returns (server, endpoint_url); caller sets
    STORAGE_EMULATOR_HOST=endpoint_url and shuts the server down."""
    objects = {}
    for f in sorted(os.listdir(root)):
        p = os.path.join(root, f)
        if os.path.isfile(p):
            with open(p, "rb") as fh:
                objects[f"{prefix}/{f}"] = fh.read()
    FakeGcsHandler.objects = objects
    FakeGcsHandler.fail_once = set()
    FakeGcsHandler.ignore_range = False
    FakeGcsHandler.range_log = []
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeGcsHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, f"http://127.0.0.1:{srv.server_address[1]}"
