"""Sequence-parallel attention vs exact single-device math, on the 8-device
CPU mesh (SURVEY §5.7: capability absent from the reference, first-class
here)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from sparknet_tpu.ops.attention import attention
from sparknet_tpu.parallel.ring_attention import make_ring_attention

B, L, H, D = 2, 64, 8, 16
N_DEV = 8


@pytest.fixture(scope="module")
def qkv(rng):
    mk = lambda: rng.standard_normal((B, L, H, D)).astype(np.float32)
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def seq_mesh():
    return Mesh(np.array(jax.devices()[:N_DEV]), ("seq",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_exact(qkv, seq_mesh, causal):
    q, k, v = qkv
    want = np.asarray(attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), causal=causal))
    ring = make_ring_attention(seq_mesh, causal=causal, impl="ring")
    got = np.asarray(ring(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_exact(qkv, seq_mesh, causal):
    q, k, v = qkv
    want = np.asarray(attention(jnp.asarray(q), jnp.asarray(k),
                                jnp.asarray(v), causal=causal))
    a2a = make_ring_attention(seq_mesh, causal=causal, impl="ulysses")
    got = np.asarray(a2a(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_ring_gradients_flow(qkv, seq_mesh):
    """Differentiable end-to-end (scan + ppermute + online softmax)."""
    q, k, v = qkv
    ring = make_ring_attention(seq_mesh, causal=True, impl="ring")

    def loss(q_):
        return jnp.sum(ring(q_, k, v) ** 2)

    g = jax.grad(loss)(jnp.asarray(q))
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0
