"""ShardedTrainer parity suite (r7): the NamedSharding-founded trainer
against the shard_map replica-layout trainer.

The two trainers share their round MATH verbatim
(`ParallelTrainer._round_math` runs inside both shard_maps), so on the
f32 TINY_MLP pin the parity is BITWISE — losses, post-round params,
momentum rows, and health scalars. On cifar10_quick through the real
train() loop the trajectory is pinned bitwise too under the default f32
policy and allclose under bf16 (conv reassociation may differ there).
Cross-layout checkpoint resume is pinned exact in all four directions —
the layouts are storage formats of the same logical state, and a resume
must never show which one wrote the snapshot.

state_sharding="momentum"/"full" (ZeRO-1) change SEMANTICS by contract
(momentum is cross-worker averaged once per round), so those modes pin
the per-device at-rest byte reduction and trajectory sanity, not
bitwise equality.
"""
import dataclasses
import json
import os
import shutil

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu import CompiledNet, net_from_prototxt
from sparknet_tpu.parallel import ParallelTrainer, ShardedTrainer, make_mesh
from sparknet_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from sparknet_tpu.solver import SolverConfig
from sparknet_tpu.utils import checkpoint as ckpt

from test_parallel import TINY_MLP

N_DEV = 8
TAU = 3
LOCAL_B = 8


@pytest.fixture(scope="module")
def net():
    return CompiledNet.compile(net_from_prototxt(TINY_MLP))


@pytest.fixture(scope="module")
def solver_cfg():
    return SolverConfig(base_lr=0.05, momentum=0.9, weight_decay=0.001,
                        lr_policy="fixed")


def make_round_batches(seed, n_dev=N_DEV):
    r = np.random.default_rng(seed)
    data = r.standard_normal((TAU, n_dev * LOCAL_B, 6)).astype(np.float32)
    label = (data.sum(-1, keepdims=True) > 0).astype(np.int32) + \
        (data[..., :1] > 0.5).astype(np.int32)
    return {"data": data, "label": label}


def assert_trees_bitwise(a, b, msg=""):
    fa = jax.tree_util.tree_leaves_with_path(a)
    fb = jax.tree_util.tree_leaves_with_path(b)
    assert len(fa) == len(fb), (msg, len(fa), len(fb))
    for (ka, xa), (_, xb) in zip(fa, fb):
        assert np.array_equal(np.asarray(xa), np.asarray(xb)), (msg, ka)


from sparknet_tpu.parallel.mesh import per_device_state_bytes  # noqa: E402
# (the ONE at-rest byte ledger — shared with bench.py --sharding so the
# BENCH_r07 acceptance number and this tier-1 pin measure the same thing)


# -- the bitwise pin ---------------------------------------------------------


def test_round_parity_bitwise_tiny_mlp(net, solver_cfg):
    """Multi-round f32 pin: same seeds, same batches -> the NamedSharding
    round must equal the shard_map round BITWISE in losses, params,
    momentum worker rows, and every health scalar."""
    a = ParallelTrainer(net, solver_cfg, make_mesh(N_DEV), tau=TAU)
    b = ShardedTrainer(net, solver_cfg, make_mesh(N_DEV), tau=TAU)
    sa = a.init_state(jax.random.PRNGKey(0))
    sb = b.init_state(jax.random.PRNGKey(0))
    for rnd in range(4):
        rng = jax.random.PRNGKey(100 + rnd)
        sa, la = a.train_round(sa, make_round_batches(rnd), rng)
        sb, lb = b.train_round(sb, make_round_batches(rnd), rng)
        assert float(la) == float(lb), rnd
        for k in a.last_health:
            assert np.array_equal(np.asarray(a.last_health[k]),
                                  np.asarray(b.last_health[k])), (rnd, k)
    assert_trees_bitwise(a.averaged_params(sa), b.averaged_params(sb),
                         "params")
    # replicated-mode momentum: [n_data] worker rows in both layouts
    assert_trees_bitwise(sa.momentum, sb.momentum, "momentum")
    # eval agrees exactly too
    batch = {k: v[0] for k, v in make_round_batches(99).items()}
    assert a.evaluate(sa, batch) == b.evaluate(sb, batch)


def test_round_parity_bitwise_tp2(net, solver_cfg):
    """DPxTP hybrid pin: on a (4, 2) mesh the ShardedTrainer holds FULL
    logical weights column-sharded by spec where the replica trainer
    holds pre-split stacked shards — the round must still match bitwise,
    and averaged_params must materialize identical full weights."""
    def mk():
        return make_mesh(N_DEV, axis_names=(DATA_AXIS, MODEL_AXIS),
                         shape=(4, 2))
    a = ParallelTrainer(net, solver_cfg, mk(), tau=TAU)
    b = ShardedTrainer(net, solver_cfg, mk(), tau=TAU)
    sa = a.init_state(jax.random.PRNGKey(1))
    sb = b.init_state(jax.random.PRNGKey(1))
    for rnd in range(2):
        rng = jax.random.PRNGKey(7 + rnd)
        sa, la = a.train_round(sa, make_round_batches(rnd), rng)
        sb, lb = b.train_round(sb, make_round_batches(rnd), rng)
        assert float(la) == float(lb), rnd
    assert_trees_bitwise(a.averaged_params(sa), b.averaged_params(sb),
                         "tp2 params")
    # the logical TP layout is the serve-side contract: full weights by
    # spec, no reassembly step
    for lname, lp in sb.params.items():
        for pname, leaf in lp.items():
            assert leaf.shape == np.asarray(
                b.averaged_params(sb)[lname][pname]).shape


def test_elastic_tau_masked_round_parity(net, solver_cfg):
    """The elastic_tau traced-budget input works identically in both
    layouts (same masked scan, same [n_data] vector plumbing)."""
    a = ParallelTrainer(net, solver_cfg, make_mesh(4), tau=TAU,
                        elastic_tau=True)
    b = ShardedTrainer(net, solver_cfg, make_mesh(4), tau=TAU,
                       elastic_tau=True)
    sa = a.init_state(jax.random.PRNGKey(2))
    sb = b.init_state(jax.random.PRNGKey(2))
    budgets = (3, 1, 2, 3)
    rng = jax.random.PRNGKey(11)
    batches = make_round_batches(0, n_dev=4)
    sa, la = a.train_round(sa, dict(batches), rng, tau_by_worker=budgets)
    sb, lb = b.train_round(sb, dict(batches), rng, tau_by_worker=budgets)
    assert float(la) == float(lb)
    assert_trees_bitwise(a.averaged_params(sa), b.averaged_params(sb),
                         "elastic_tau")


# -- ZeRO-1 state sharding ---------------------------------------------------


def test_momentum_sharding_cuts_per_device_bytes(net, solver_cfg):
    """state_sharding='momentum' must cut the at-rest per-device momentum
    bytes by >= (n_data-1)/n_data of the shardable momentum bytes (leaves
    with a dim divisible by n_data; indivisible leaves legitimately stay
    whole) while leaving params replicated."""
    rep = ShardedTrainer(net, solver_cfg, make_mesh(N_DEV), tau=TAU)
    zm = ShardedTrainer(net, solver_cfg, make_mesh(N_DEV), tau=TAU,
                        state_sharding="momentum")
    s_rep = rep.init_state(jax.random.PRNGKey(0))
    s_zm = zm.init_state(jax.random.PRNGKey(0))
    b_rep = per_device_state_bytes(s_rep)
    b_zm = per_device_state_bytes(s_zm)
    assert b_zm["params"] == b_rep["params"]
    # shardable bytes: logical momentum leaves with any dim % n_data == 0
    shardable = sum(
        int(np.prod(x.shape)) * x.dtype.itemsize
        for x in jax.tree.leaves(zm.init_state(
            jax.random.PRNGKey(0)).momentum)
        if any(s % N_DEV == 0 and s > 0 for s in x.shape))
    want_cut = shardable * (N_DEV - 1) // N_DEV
    assert b_rep["momentum"] - b_zm["momentum"] >= want_cut, (
        b_rep, b_zm, shardable)


def test_full_sharding_cuts_param_bytes_too(net, solver_cfg):
    rep = ShardedTrainer(net, solver_cfg, make_mesh(N_DEV), tau=TAU)
    zf = ShardedTrainer(net, solver_cfg, make_mesh(N_DEV), tau=TAU,
                        state_sharding="full")
    b_rep = per_device_state_bytes(rep.init_state(jax.random.PRNGKey(0)))
    b_zf = per_device_state_bytes(zf.init_state(jax.random.PRNGKey(0)))
    assert b_zf["params"] < b_rep["params"]
    assert b_zf["momentum"] < b_rep["momentum"]


@pytest.mark.parametrize("mode", ["momentum", "full"])
def test_zero1_modes_train_and_stay_finite(net, solver_cfg, mode):
    """The ZeRO modes are a semantic opt-in (momentum cross-worker
    averaged once per round) — pin that they train: loss descends on the
    same easy task, params stay finite, and the jit cache holds one
    executable (the re-shard constraint must not fork variants)."""
    t = ShardedTrainer(net, solver_cfg, make_mesh(N_DEV), tau=TAU,
                       state_sharding=mode)
    state = t.init_state(jax.random.PRNGKey(0))
    losses = []
    for rnd in range(6):
        state, loss = t.train_round(state, make_round_batches(rnd % 3),
                                    jax.random.PRNGKey(200 + rnd))
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
    assert all(np.all(np.isfinite(np.asarray(x)))
               for x in jax.tree.leaves(state.params))
    assert t.compiled_variants() in (0, 1, 2)  # exe + fast-path key


def test_zero1_requires_named_and_tp1(net, solver_cfg):
    with pytest.raises(NotImplementedError):
        ShardedTrainer(net, solver_cfg,
                       make_mesh(N_DEV, axis_names=(DATA_AXIS, MODEL_AXIS),
                                 shape=(4, 2)),
                       tau=TAU, state_sharding="momentum")
    with pytest.raises(ValueError):
        ShardedTrainer(net, solver_cfg, make_mesh(N_DEV), tau=TAU,
                       state_sharding="typo")
    from sparknet_tpu.apps.train_loop import resolve_trainer_impl
    from sparknet_tpu.utils.config import RunConfig
    with pytest.raises(ValueError):
        resolve_trainer_impl(RunConfig(trainer_impl="shard_map",
                                       state_sharding="momentum"))


def test_resolve_trainer_impl_env_and_knob(monkeypatch):
    from sparknet_tpu.apps.train_loop import resolve_trainer_impl
    from sparknet_tpu.utils.config import RunConfig
    monkeypatch.delenv("SPARKNET_TRAINER_IMPL", raising=False)
    assert resolve_trainer_impl(RunConfig()) == "shard_map"
    monkeypatch.setenv("SPARKNET_TRAINER_IMPL", "named")
    assert resolve_trainer_impl(RunConfig()) == "named"
    # an explicit knob beats the env (the env is the CI matrix lever)
    assert resolve_trainer_impl(
        RunConfig(trainer_impl="shard_map")) == "shard_map"
    with pytest.raises(ValueError):
        resolve_trainer_impl(RunConfig(trainer_impl="nope"))


# -- elastic resize as re-placement -----------------------------------------


def test_resized_carries_class_and_sharding(net, solver_cfg):
    t = ShardedTrainer(net, solver_cfg, make_mesh(N_DEV), tau=TAU,
                       state_sharding="momentum")
    t2 = t.resized(4)
    assert type(t2) is ShardedTrainer
    assert t2.state_sharding == "momentum"
    assert t2.n_devices == 4


def test_adapt_live_replacement_matches_checkpoint_roundtrip(net,
                                                             solver_cfg):
    """The elastic fast path: adopting the live logical state onto a
    smaller mesh must equal writing + re-reading a checkpoint (the slow
    path both trainers share) — same params bitwise, same policy-mapped
    momentum."""
    t8 = ShardedTrainer(net, solver_cfg, make_mesh(N_DEV), tau=TAU)
    s8 = t8.init_state(jax.random.PRNGKey(3))
    for rnd in range(2):
        s8, _ = t8.train_round(s8, make_round_batches(rnd),
                               jax.random.PRNGKey(rnd))
    t4 = t8.resized(4)
    live = t4.adapt_live(s8, momentum_policy="norm_rescale")
    from sparknet_tpu.parallel.mesh import fetch_global
    flat = ckpt._flatten(fetch_global(s8))
    via_ckpt = t4.adapt_state(flat, momentum_policy="norm_rescale",
                              old_layout="logical")
    assert_trees_bitwise(live.params, via_ckpt.params, "live params")
    assert_trees_bitwise(live.momentum, via_ckpt.momentum, "live momentum")
    # and the resized trainer actually trains from it
    live2, loss = t4.train_round(live, make_round_batches(9, n_dev=4),
                                 jax.random.PRNGKey(9))
    assert np.isfinite(float(loss))


# -- cross-layout checkpoint resume (the four directions) --------------------


def _loop_cfg(tmp_path, sub, impl, max_rounds, ckdir=None,
              state_sharding="replicated", checkpoint_sharded="auto"):
    from sparknet_tpu.utils.config import RunConfig
    wd = tmp_path / sub
    wd.mkdir(exist_ok=True)
    return RunConfig(
        solver=SolverConfig(base_lr=0.01, momentum=0.9, weight_decay=0.004,
                            lr_policy="fixed"),
        tau=2, local_batch=4, eval_every=0, max_rounds=max_rounds,
        workdir=str(wd), seed=0, trainer_impl=impl,
        state_sharding=state_sharding,
        checkpoint_sharded=checkpoint_sharded,
        checkpoint_dir=str(ckdir or wd / "ck"), checkpoint_every=2,
        checkpoint_async=False)


def _run_loop(tmp_path, sub, impl, max_rounds, ckdir=None,
              state_sharding="replicated", checkpoint_sharded="auto"):
    from sparknet_tpu.apps.train_loop import train
    from sparknet_tpu.data import cifar
    from sparknet_tpu.data.dataset import ArrayDataset
    from sparknet_tpu.utils.logger import Logger
    from sparknet_tpu.zoo import cifar10_quick
    d = str(tmp_path / "cifar")
    if not os.path.isdir(d):
        cifar.write_synthetic(d, n_per_file=40)
    loader = cifar.CifarLoader(d)
    cfg = _loop_cfg(tmp_path, sub, impl, max_rounds, ckdir=ckdir,
                    state_sharding=state_sharding,
                    checkpoint_sharded=checkpoint_sharded)
    jsonl = os.path.join(cfg.workdir, "m.jsonl")
    train(cfg, cifar10_quick(batch=cfg.local_batch),
          ArrayDataset(loader.train_batch_dict()),
          logger=Logger(os.path.join(cfg.workdir, "log.txt"), echo=False,
                        jsonl_path=jsonl))
    losses = [json.loads(l)["loss"] for l in open(jsonl) if '"loss"' in l]
    return losses, cfg


def test_cifar10_quick_loop_trajectory_parity(tmp_path):
    """ISSUE 8 acceptance pin: the NamedSharding trainer reproduces the
    shard_map trainer's cifar10_quick loss trajectory through the REAL
    train() loop. Under the default f32 policy the rounds are the same
    XLA math on the same placement — pinned bitwise, which subsumes the
    allclose-under-bf16 requirement."""
    ref, _ = _run_loop(tmp_path, "ref", "shard_map", 4)
    named, _ = _run_loop(tmp_path, "named", "named", 4)
    assert len(ref) == 4
    assert named == ref


def test_cross_layout_resume_all_directions_exact(tmp_path):
    """A checkpoint is a storage format, not a commitment: each layout
    resumes the other's snapshot and continues the uninterrupted
    trajectory EXACTLY (same-topology momentum rows map 1:1; params are
    logical in both directions)."""
    ref, _ = _run_loop(tmp_path, "ref", "shard_map", 4)
    _, c_named = _run_loop(tmp_path, "seed_named", "named", 2)
    _, c_rep = _run_loop(tmp_path, "seed_rep", "shard_map", 2)
    for i, (src, impl) in enumerate(
            ((c_named, "shard_map"), (c_rep, "named"),
             (c_named, "named"), (c_rep, "shard_map"))):
        ck2 = tmp_path / f"copy{i}"
        shutil.copytree(src.checkpoint_dir, ck2)
        cont, _ = _run_loop(tmp_path, f"cont{i}", impl, 4, ckdir=ck2)
        assert cont == ref[2:], (i, impl, cont, ref)


def test_named_checkpoint_meta_stamps_layout(tmp_path):
    _, cfg = _run_loop(tmp_path, "stamp", "named", 2)
    metas = sorted((tmp_path / "stamp" / "ck").glob("step-*/meta.json"))
    assert metas
    extra = json.load(open(metas[-1]))["extra"]
    assert extra["layout"] == "logical"
    assert extra["state_sharding"] == "replicated"


def test_zero1_loop_checkpoint_roundtrip(tmp_path):
    """state_sharding='momentum' through the loop: checkpoints save the
    gathered logical momentum and a resume continues without error (the
    semantics pin is test_zero1_modes_train_and_stay_finite; here the
    storage path is under test)."""
    _, c1 = _run_loop(tmp_path, "zm", "named", 2,
                      state_sharding="momentum")
    cont, _ = _run_loop(tmp_path, "zm2", "named", 4,
                        ckdir=c1.checkpoint_dir,
                        state_sharding="momentum")
    assert len(cont) == 2 and all(np.isfinite(l) for l in cont)


# -- r8: sharded checkpoint layout, crossed with state layouts + stores ------

_FMT_REF: list = []


@pytest.mark.parametrize("kind", ["local", "gs", "s3"])
def test_cross_layout_and_format_restore_matrix(tmp_path, kind,
                                                monkeypatch):
    """The r8 storage matrix: checkpoint FORMAT (sharded <-> monolithic)
    x state LAYOUT (replica <-> logical) x STORE (local / gs:// / s3://).
    A seed run saves under one (format, layout); a continuation under the
    OTHER format and layout resumes from the same store and must
    reproduce the uninterrupted reference trajectory exactly — the
    format, like the layout, is a storage decision no resume may be able
    to observe."""
    import contextlib
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from fake_stores import bucket_store

    with contextlib.ExitStack() as stack:
        if kind == "local":
            root = None
        else:
            root, _ = stack.enter_context(bucket_store(kind))
        # the uninterrupted reference trajectory is deterministic and
        # store-independent — computed once, reused across the 3 params
        if not _FMT_REF:
            _FMT_REF.extend(_run_loop(tmp_path, "fmt_ref",
                                      "shard_map", 4)[0])
        ref = list(_FMT_REF)
        cells = (("named", "on", "shard_map", "off"),
                 ("shard_map", "off", "named", "on"))
        for i, (impl_a, fmt_a, impl_b, fmt_b) in enumerate(cells):
            ckdir = (f"{root}/fmt{i}" if root
                     else str(tmp_path / f"fmt{i}"))
            _, cfg_a = _run_loop(tmp_path, f"fmt_seed{i}", impl_a, 2,
                                 ckdir=ckdir, checkpoint_sharded=fmt_a)
            meta = ckpt._load_meta(ckpt._join(ckdir, "step-2"))
            assert ("shards" in meta) == (fmt_a == "on"), meta.keys()
            cont, _ = _run_loop(tmp_path, f"fmt_cont{i}", impl_b, 4,
                                ckdir=ckdir, checkpoint_sharded=fmt_b)
            assert cont == ref[2:], (i, kind, cont, ref)
