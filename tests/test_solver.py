"""Solver tests: lr policies vs closed form, Caffe SGD update rule vs a
hand-written numpy oracle (the reference's update lived in native Caffe —
`libs/CaffeSolver.scala:11-18` — and was never unit-tested)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu import CompiledNet, net_from_prototxt
from sparknet_tpu.solver import SgdSolver, SolverConfig, learning_rate
from tests.test_net import CIFARISH


def lr_at(cfg, it):
    return float(learning_rate(cfg, jnp.asarray(it)))


def approx(x):
    return pytest.approx(x, rel=1e-4)


def test_lr_policies():
    assert lr_at(SolverConfig(base_lr=0.001, lr_policy="fixed"), 999) == approx(0.001)
    step = SolverConfig(base_lr=0.01, lr_policy="step", gamma=0.1, stepsize=100000)
    assert lr_at(step, 0) == approx(0.01)
    assert lr_at(step, 99999) == approx(0.01)
    assert lr_at(step, 100000) == approx(0.001)
    assert lr_at(step, 250000) == approx(0.0001)
    inv = SolverConfig(base_lr=0.01, lr_policy="inv", gamma=0.0001, power=0.75)
    assert lr_at(inv, 0) == approx(0.01)
    assert lr_at(inv, 10000) == approx(0.01 * (1 + 0.0001 * 10000) ** -0.75)
    ms = SolverConfig(base_lr=0.1, lr_policy="multistep", gamma=0.5,
                      stepvalue=(10, 20))
    assert lr_at(ms, 5) == approx(0.1)
    assert lr_at(ms, 10) == approx(0.05)
    assert lr_at(ms, 25) == approx(0.025)
    poly = SolverConfig(base_lr=0.1, lr_policy="poly", power=2.0, max_iter=100)
    assert lr_at(poly, 50) == pytest.approx(0.1 * 0.25)


def test_caffe_sgd_update_rule():
    """V <- m*V + lr*lr_mult*(g + wd*decay_mult*w); W <- W - V, elementwise."""
    net = CompiledNet.compile(net_from_prototxt(CIFARISH))
    cfg = SolverConfig(base_lr=0.05, momentum=0.9, weight_decay=0.004,
                       lr_policy="fixed")
    solver = SgdSolver(net, cfg)
    params = net.init_params(jax.random.PRNGKey(0))
    state = solver.init_state(params)
    g = jax.tree.map(lambda w: jnp.ones_like(w) * 0.5, params)

    # two manual steps to exercise momentum accumulation
    w0 = np.asarray(params["conv1"]["w"])
    b0 = np.asarray(params["conv1"]["b"])
    p1, s1 = solver.update(params, state, g)
    p2, s2 = solver.update(p1, s1, g)

    # conv1 weight: lr_mult=1; bias: lr_mult=2 (from the prototxt params)
    v1 = 0.05 * (0.5 + 0.004 * w0)
    w1 = w0 - v1
    v2 = 0.9 * v1 + 0.05 * (0.5 + 0.004 * w1)
    w2 = w1 - v2
    np.testing.assert_allclose(np.asarray(p2["conv1"]["w"]), w2, rtol=1e-5)

    bv1 = 0.05 * 2 * (0.5 + 0.004 * b0)
    b1 = b0 - bv1
    bv2 = 0.9 * bv1 + 0.05 * 2 * (0.5 + 0.004 * b1)
    b2 = b1 - bv2
    np.testing.assert_allclose(np.asarray(p2["conv1"]["b"]), b2, rtol=1e-5)
    assert int(s2.it) == 2


def test_training_reduces_loss():
    net = CompiledNet.compile(net_from_prototxt(CIFARISH))
    solver = SgdSolver(net, SolverConfig(base_lr=0.01, momentum=0.9,
                                         lr_policy="fixed"))
    params = net.init_params(jax.random.PRNGKey(0))
    state = solver.init_state(params)
    batch = net.example_batch()  # fixed batch -> loss must drop
    losses = []
    for i in range(30):
        params, state, loss = solver.step(params, state, batch,
                                          jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::10]
    assert np.isfinite(losses).all()


def test_iter_size_accumulation_matches_full_batch(rng):
    """Caffe iter_size semantics: k accumulation micro-batches + one update
    == one update on the concatenated batch (loss is a batch mean, so
    grad-mean over micro-batches equals the full-batch grad)."""
    from sparknet_tpu.apps.adult_app import adult_net
    data = rng.standard_normal((8, 16)).astype(np.float32)
    label = rng.integers(0, 2, (8, 1)).astype(np.int32)

    full = CompiledNet.compile(adult_net(batch=8, n_features=16))
    p0 = full.init_params(jax.random.PRNGKey(0))
    s_full = SgdSolver(full, SolverConfig(base_lr=0.1, momentum=0.9,
                                          weight_decay=0.01, iter_size=1))
    st = s_full.init_state(p0)
    pf, stf, loss_f = s_full.step(p0, st, {"C0": data, "label": label})

    half = CompiledNet.compile(adult_net(batch=4, n_features=16))
    p1 = half.init_params(jax.random.PRNGKey(0))
    s_acc = SgdSolver(half, SolverConfig(base_lr=0.1, momentum=0.9,
                                         weight_decay=0.01, iter_size=2))
    st2 = s_acc.init_state(p1)
    pa, sta, loss_a = s_acc.step(p1, st2, {"C0": data, "label": label})

    assert float(loss_a) == pytest.approx(float(loss_f), rel=1e-5)
    assert int(sta.it) == int(stf.it) == 1  # ONE iteration per k micro-batches
    for lname in pf:
        for pname in pf[lname]:
            np.testing.assert_allclose(
                np.asarray(pa[lname][pname]), np.asarray(pf[lname][pname]),
                rtol=1e-5, atol=1e-6, err_msg=f"{lname}/{pname}")


def test_iter_size_indivisible_batch_rejected(rng):
    from sparknet_tpu.apps.adult_app import adult_net
    net = CompiledNet.compile(adult_net(batch=3, n_features=16))
    p = net.init_params(jax.random.PRNGKey(0))
    s = SgdSolver(net, SolverConfig(iter_size=2))
    with pytest.raises(ValueError, match="iter_size"):
        s.step(p, s.init_state(p),
               {"C0": np.zeros((7, 16), np.float32),
                "label": np.zeros((7, 1), np.int32)})


def test_iter_size_rejected_in_distributed_trainer():
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh
    from sparknet_tpu.apps.adult_app import adult_net
    net = CompiledNet.compile(adult_net(batch=4, n_features=16))
    with pytest.raises(ValueError, match="iter_size"):
        ParallelTrainer(net, SolverConfig(iter_size=2), make_mesh(2))


def test_bf16_velocity_opt_in():
    """velocity_dtype='bfloat16' (SolverConfig): the stored momentum
    history is bf16 but each step applies the UNROUNDED f32 velocity, so a
    short trajectory stays close to the exact rule; the default remains
    float32 (Caffe-exact, PARITY.md)."""
    net = CompiledNet.compile(net_from_prototxt(CIFARISH))
    base = dict(base_lr=0.05, momentum=0.9, weight_decay=0.004,
                lr_policy="fixed")
    exact = SgdSolver(net, SolverConfig(**base))
    fast = SgdSolver(net, SolverConfig(velocity_dtype="bfloat16", **base))
    params = net.init_params(jax.random.PRNGKey(0))
    se, sf = exact.init_state(params), fast.init_state(params)
    assert se.momentum["conv1"]["w"].dtype == jnp.float32
    assert sf.momentum["conv1"]["w"].dtype == jnp.bfloat16
    g = jax.tree.map(lambda w: jnp.ones_like(w) * 0.5, params)
    pe, pf = params, params
    for _ in range(3):
        pe, se = exact.update(pe, se, g)
        pf, sf = fast.update(pf, sf, g)
    # params stay f32 and close to the exact trajectory (bf16 has ~3
    # decimal digits; 3 steps of history rounding)
    assert pf["conv1"]["w"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(pf["conv1"]["w"]),
                               np.asarray(pe["conv1"]["w"]),
                               rtol=2e-2, atol=2e-3)
    with pytest.raises(ValueError, match="velocity_dtype"):
        SgdSolver(net, SolverConfig(velocity_dtype="float16", **base))


def test_bf16_velocity_flows_through_trainer(tmp_path):
    """ParallelTrainer must honor SolverConfig.velocity_dtype when it
    builds the distributed state (it used to zeros_like the params,
    silently pinning f32), and a round must run on the bf16 state."""
    import jax
    from sparknet_tpu.parallel import ParallelTrainer, make_mesh

    from sparknet_tpu.zoo import cifar10_quick
    net = CompiledNet.compile(cifar10_quick(batch=2))
    cfg = SolverConfig(base_lr=0.01, momentum=0.9,
                       velocity_dtype="bfloat16")
    tr = ParallelTrainer(net, cfg, make_mesh(2), tau=2)
    state = tr.init_state(jax.random.PRNGKey(0))
    assert state.momentum["conv1"]["w"].dtype == jnp.bfloat16
    assert state.params["conv1"]["w"].dtype == jnp.float32
    r = np.random.default_rng(0)
    batches = {"data": r.standard_normal((2, 4, 32, 32, 3))
               .astype(np.float32),
               "label": r.integers(0, 10, (2, 4, 1)).astype(np.int32)}
    state, loss = tr.train_round(state, batches, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    assert state.momentum["conv1"]["w"].dtype == jnp.bfloat16
