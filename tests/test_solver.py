"""Solver tests: lr policies vs closed form, Caffe SGD update rule vs a
hand-written numpy oracle (the reference's update lived in native Caffe —
`libs/CaffeSolver.scala:11-18` — and was never unit-tested)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sparknet_tpu import CompiledNet, net_from_prototxt
from sparknet_tpu.solver import SgdSolver, SolverConfig, learning_rate
from tests.test_net import CIFARISH


def lr_at(cfg, it):
    return float(learning_rate(cfg, jnp.asarray(it)))


def approx(x):
    return pytest.approx(x, rel=1e-4)


def test_lr_policies():
    assert lr_at(SolverConfig(base_lr=0.001, lr_policy="fixed"), 999) == approx(0.001)
    step = SolverConfig(base_lr=0.01, lr_policy="step", gamma=0.1, stepsize=100000)
    assert lr_at(step, 0) == approx(0.01)
    assert lr_at(step, 99999) == approx(0.01)
    assert lr_at(step, 100000) == approx(0.001)
    assert lr_at(step, 250000) == approx(0.0001)
    inv = SolverConfig(base_lr=0.01, lr_policy="inv", gamma=0.0001, power=0.75)
    assert lr_at(inv, 0) == approx(0.01)
    assert lr_at(inv, 10000) == approx(0.01 * (1 + 0.0001 * 10000) ** -0.75)
    ms = SolverConfig(base_lr=0.1, lr_policy="multistep", gamma=0.5,
                      stepvalue=(10, 20))
    assert lr_at(ms, 5) == approx(0.1)
    assert lr_at(ms, 10) == approx(0.05)
    assert lr_at(ms, 25) == approx(0.025)
    poly = SolverConfig(base_lr=0.1, lr_policy="poly", power=2.0, max_iter=100)
    assert lr_at(poly, 50) == pytest.approx(0.1 * 0.25)


def test_caffe_sgd_update_rule():
    """V <- m*V + lr*lr_mult*(g + wd*decay_mult*w); W <- W - V, elementwise."""
    net = CompiledNet.compile(net_from_prototxt(CIFARISH))
    cfg = SolverConfig(base_lr=0.05, momentum=0.9, weight_decay=0.004,
                       lr_policy="fixed")
    solver = SgdSolver(net, cfg)
    params = net.init_params(jax.random.PRNGKey(0))
    state = solver.init_state(params)
    g = jax.tree.map(lambda w: jnp.ones_like(w) * 0.5, params)

    # two manual steps to exercise momentum accumulation
    w0 = np.asarray(params["conv1"]["w"])
    b0 = np.asarray(params["conv1"]["b"])
    p1, s1 = solver.update(params, state, g)
    p2, s2 = solver.update(p1, s1, g)

    # conv1 weight: lr_mult=1; bias: lr_mult=2 (from the prototxt params)
    v1 = 0.05 * (0.5 + 0.004 * w0)
    w1 = w0 - v1
    v2 = 0.9 * v1 + 0.05 * (0.5 + 0.004 * w1)
    w2 = w1 - v2
    np.testing.assert_allclose(np.asarray(p2["conv1"]["w"]), w2, rtol=1e-5)

    bv1 = 0.05 * 2 * (0.5 + 0.004 * b0)
    b1 = b0 - bv1
    bv2 = 0.9 * bv1 + 0.05 * 2 * (0.5 + 0.004 * b1)
    b2 = b1 - bv2
    np.testing.assert_allclose(np.asarray(p2["conv1"]["b"]), b2, rtol=1e-5)
    assert int(s2.it) == 2


def test_training_reduces_loss():
    net = CompiledNet.compile(net_from_prototxt(CIFARISH))
    solver = SgdSolver(net, SolverConfig(base_lr=0.01, momentum=0.9,
                                         lr_policy="fixed"))
    params = net.init_params(jax.random.PRNGKey(0))
    state = solver.init_state(params)
    batch = net.example_batch()  # fixed batch -> loss must drop
    losses = []
    for i in range(30):
        params, state, loss = solver.step(params, state, batch,
                                          jax.random.PRNGKey(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::10]
    assert np.isfinite(losses).all()
