"""Collective-traffic pin for the distributed round.

BASELINE.md's >=90%-scaling claim rests on the round moving EXACTLY one
copy of the net's parameters per τ-round (weight pmean; momentum stays
worker-local — reference `libs/CaffeNet.scala:123-137` only ships net
blobs). PERF.md §ici-scaling-model turns that byte count into predicted
efficiency at 8/16/32 chips; this test pins the byte count itself by
inspecting the compiled round's optimized HLO, so an accidental extra
all-gather / per-step sync / momentum-on-the-wire regression fails CI
instead of silently halving the predicted scaling.

Pinned properties (on the 8-virtual-device CPU mesh, caffenet shapes):
  1. bytes all-reduced per round ≈ one per-replica copy of the params
     (+ the scalar loss pmean) — NOT ×τ, NOT params+momentum;
  2. τ-invariance: compiling at τ=2 and τ=4 moves identical bytes
     (averaging is per-round, never per-step);
  3. op-count sanity: the number of collective ops stays bounded by the
     param-leaf count + loss (XLA's combiner may merge below that).
"""
import re

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from sparknet_tpu import CompiledNet
from sparknet_tpu.parallel import ParallelTrainer, make_mesh
from sparknet_tpu.parallel.mesh import DATA_AXIS, place_global_state
from sparknet_tpu.solver import SolverConfig
from sparknet_tpu.zoo import caffenet

N_DEV = 8
LOCAL_B = 4
CROP = 67
N_CLASSES = 16

_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2,
                "u16": 2, "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8,
                "u64": 8}

# result shapes of an HLO op line: `f32[1,96,3,11,11]{4,3,2,1,0}` tokens
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _collective_lines(hlo: str):
    """(op_kind, result_bytes) for every collective in the optimized HLO.

    `-start` variants are the async halves of the same op — counting
    `-done` too would double; we take only starts + synchronous forms."""
    out = []
    for line in hlo.splitlines():
        m = re.search(r"= (.+?) (all-reduce|all-gather|reduce-scatter|"
                      r"all-to-all|collective-permute)"
                      r"(-start)?\(", line)
        if not m:
            continue
        result, kind = m.group(1), m.group(2)
        nbytes = 0
        for dt, dims in _SHAPE_RE.findall(result):
            if dt not in _DTYPE_BYTES:
                continue  # layout annotation like {4,3,2,1,0}
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES[dt]
        out.append((kind, nbytes))
    return out


def _build(tau: int):
    net = CompiledNet.compile(
        caffenet(batch=LOCAL_B, crop=CROP, n_classes=N_CLASSES))
    mesh = make_mesh(N_DEV)
    trainer = ParallelTrainer(
        net, SolverConfig(base_lr=0.01, momentum=0.9, weight_decay=5e-4,
                          lr_policy="fixed"), mesh, tau=tau)
    state = trainer.init_state(jax.random.PRNGKey(0))
    r = np.random.default_rng(0)
    batches = {
        "data": r.standard_normal(
            (tau, N_DEV * LOCAL_B, CROP, CROP, 3)).astype(np.float32),
        "label": r.integers(0, N_CLASSES,
                            (tau, N_DEV * LOCAL_B, 1)).astype(np.int32)}
    sharded = trainer._shard_batches(batches)
    rngs = place_global_state(
        jax.random.split(jax.random.PRNGKey(1), N_DEV),
        trainer.mesh, P(DATA_AXIS))
    return trainer, state, sharded, rngs


def _round_collectives(tau: int):
    trainer, state, sharded, rngs = _build(tau)
    import jax.numpy as jnp
    hlo = trainer._round.lower(state, sharded, rngs,
                               jnp.asarray(1.0, jnp.float32)
                               ).compile().as_text()
    per_replica_param_bytes = sum(
        int(np.prod(leaf.shape[1:])) * leaf.dtype.itemsize
        for lp in jax.tree.leaves(
            state.params, is_leaf=lambda x: hasattr(x, "shape"))
        for leaf in [lp])
    n_leaves = len(jax.tree.leaves(state.params))
    return _collective_lines(hlo), per_replica_param_bytes, n_leaves


@pytest.fixture(scope="module")
def tau2():
    return _round_collectives(2)


def test_round_moves_one_param_copy(tau2):
    colls, param_bytes, n_leaves = tau2
    assert colls, "no collectives found in the compiled round HLO"
    kinds = {k for k, _ in colls}
    # DP round: weight average + loss average are pmean -> all-reduce.
    # Anything else on the wire is a regression.
    assert kinds == {"all-reduce"}, f"unexpected collectives: {kinds}"
    total = sum(b for _, b in colls)
    # one param copy + three f32 scalars: the loss and the two health
    # signals (grad_norm, nonfinite count — reduced over τ BEFORE the
    # psum, so they stay scalars; combiner padding tolerance 1%)
    assert param_bytes <= total <= int(param_bytes * 1.01) + 256, (
        f"round all-reduces {total} bytes; params are {param_bytes} — "
        f"{'momentum or batch data is on the wire' if total > param_bytes * 1.5 else 'short of one param copy'}")
    assert len(colls) <= n_leaves + 3, (
        f"{len(colls)} collective ops for {n_leaves} param leaves "
        f"(+ loss + 2 health scalars)")


def test_round_collective_bytes_tau_invariant(tau2):
    colls2, param_bytes, _ = tau2
    colls4, _, _ = _round_collectives(4)
    assert sum(b for _, b in colls2) == sum(b for _, b in colls4), (
        "collective bytes grew with tau — averaging has become per-step")


def test_perf_md_documents_the_measured_bytes(tau2):
    """PERF.md's ICI model must quote the same per-round byte count this
    pin measures (so the analytic scaling numbers can't drift from the
    compiled program)."""
    _, param_bytes, _ = tau2
    # the model is written for the FULL caffenet (crop 227, 1000 classes);
    # recompute its param bytes analytically from the zoo spec
    net = CompiledNet.compile(caffenet(batch=4, crop=227, n_classes=1000))
    params = net.init_params(jax.random.PRNGKey(0))
    full_bytes = sum(l.nbytes for l in jax.tree.leaves(params))
    import pathlib
    perf = pathlib.Path(__file__).resolve().parent.parent / "PERF.md"
    text = perf.read_text()
    mb = full_bytes / 1e6
    assert f"{mb:.0f} MB" in text or f"{mb:.1f} MB" in text, (
        f"PERF.md ici-scaling section must quote the pinned param volume "
        f"({mb:.1f} MB)")


def _tp_round_collectives(tau: int = 2, dp: int = 4, tp: int = 2):
    """Compile the DP×TP hybrid round on TINY_MLP shapes and parse its
    collectives. ip1 (num_output 16) and ip2 (4) are both divisible by
    tp=2, so both are column-sharded; conv-free, so every all-gather in
    the program is the TP feature gather."""
    from test_parallel import TINY_MLP
    from sparknet_tpu import net_from_prototxt

    net = CompiledNet.compile(net_from_prototxt(TINY_MLP))
    mesh = make_mesh(dp * tp, axis_names=("data", "model"),
                     shape=(dp, tp))
    trainer = ParallelTrainer(
        net, SolverConfig(base_lr=0.01, momentum=0.9, lr_policy="fixed"),
        mesh, tau=tau)
    r = np.random.default_rng(0)
    b = 4
    batches = {
        "data": r.standard_normal((tau, dp * b, 6)).astype(np.float32),
        "label": r.integers(0, 4, (tau, dp * b, 1)).astype(np.int32)}
    sharded = trainer._shard_batches(batches)
    rngs = place_global_state(
        jax.random.split(jax.random.PRNGKey(1), dp),
        trainer.mesh, P(DATA_AXIS))
    import jax.numpy as jnp
    hlo = trainer._round.lower(
        trainer.init_state(jax.random.PRNGKey(0)), sharded,
        rngs, jnp.asarray(1.0, jnp.float32)).compile().as_text()
    params = net.init_params(jax.random.PRNGKey(0))
    per_replica_param_bytes = sum(
        l.nbytes for l in jax.tree.leaves(params))
    return _collective_lines(hlo), per_replica_param_bytes


@pytest.fixture(scope="module")
def tp_tau2():
    return _tp_round_collectives(tau=2)


def test_tp_round_collective_kinds_and_weight_bytes(tp_tau2):
    """The DP×TP hybrid round's wire traffic, pinned: the weight-average
    all-reduce stays ONE param copy per round — but a LOGICAL copy, i.e.
    column-sharded layers contribute 1/tp each per model rank (shard
    identity is preserved across the data-axis pmean; a full-size
    all-reduce here would mean shards were being summed together — the
    r3 bug class this guards). TP additionally puts all-gathers on the
    wire (the Megatron feature gather + its transpose), which the DP-only
    test asserts are ABSENT; their per-activation bytes scale with
    batch×features, pinned loosely here (presence + τ-scaling) since
    XLA may fuse them."""
    tp = 2
    colls, full_param_bytes = tp_tau2
    kinds = {k for k, _ in colls}
    assert "all-reduce" in kinds, kinds
    assert "all-gather" in kinds, (
        f"TP round emitted no all-gather — column sharding is not "
        f"actually sharded? kinds={kinds}")
    ar_bytes = sum(b for k, b in colls if k == "all-reduce")
    # sharded-layer params (here: ALL layers are TP-shardable InnerProducts)
    # cross the wire as 1/tp each; only small HEALTH/LOSS riders come
    # along — three f32 scalars (loss, grad_norm, nonfinite), each
    # psum'd over data AND vma-cleared over the model axis (2 legs), plus
    # the [n_data + 1] attribution-plus-authority vector on the same two
    # legs: 6×4 + 2×4×(n_data+1) bytes, computed exactly so the slack
    # stays tight — at these ~360-byte shapes a single layer's
    # shards-summed regression is ~130 bytes and a blanket slack would
    # mask exactly the bug class this pins.
    n_data = 4  # dp in _tp_round_collectives
    riders = 6 * 4 + 2 * 4 * (n_data + 1)
    logical = full_param_bytes / tp
    assert logical <= ar_bytes <= logical + riders + 8, (
        f"weight-average all-reduce moved {ar_bytes} bytes; expected "
        f"~{int(logical)} (one LOGICAL copy: full {full_param_bytes} / "
        f"tp {tp}) + {riders} rider bytes")


def test_tp_round_allgather_bytes_tau_scale(tp_tau2):
    """The TP feature gathers happen INSIDE every local step, so their
    bytes scale ~linearly with τ (unlike the weight all-reduce, pinned
    τ-invariant above) — τ=4 must carry ~2x the all-gather bytes of τ=2,
    and the all-reduce must not grow."""
    c2, _ = tp_tau2
    c4, _ = _tp_round_collectives(tau=4)
    ag2 = sum(b for k, b in c2 if k == "all-gather")
    ag4 = sum(b for k, b in c4 if k == "all-gather")
    assert ag2 > 0 and 1.8 * ag2 <= ag4 <= 2.2 * ag2, (ag2, ag4)
    ar2 = sum(b for k, b in c2 if k == "all-reduce")
    ar4 = sum(b for k, b in c4 if k == "all-reduce")
    assert ar2 == ar4, (ar2, ar4)
