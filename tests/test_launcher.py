"""tpu_pod_launch.sh fault-tolerance tests with a stubbed gcloud: the
spot-preemption recover+rerun loop (`watch`), the one-shot `resume`, and
queued-resource creation — the reference's ec2/spark_ec2.py spot story,
exercised hermetically (no cloud, no network)."""
import os
import stat
import subprocess

import pytest

SCRIPT = os.path.join(os.path.dirname(__file__), "..", "scripts",
                      "tpu_pod_launch.sh")

GCLOUD_STUB = r"""#!/bin/sh
# gcloud stub: state machine in $STUB_DIR. Logs every call.
DIR="$STUB_DIR"
echo "$@" >> "$DIR/calls.log"
case "$*" in
  *"tpu-vm describe"*)
    if [ -f "$DIR/transient" ]; then echo "ERROR: auth expired"; exit 1; fi
    if [ -f "$DIR/warn" ]; then echo "WARNING: quota nearing limit" >&2; fi
    if [ -f "$DIR/state" ]; then cat "$DIR/state"
    else echo "ERROR: NOT_FOUND: $2"; exit 1; fi ;;
  *"tpu-vm create"*)
    if [ -f "$DIR/createfail" ]; then echo "ERROR: stockout"; exit 1; fi
    echo READY > "$DIR/state" ;;
  *"tpu-vm delete"*)
    if [ -f "$DIR/deletefail" ]; then echo "ERROR: PERMISSION_DENIED"; exit 1; fi
    rm -f "$DIR/state" ;;
  *"queued-resources create"*) echo PROVISIONING > "$DIR/qstate"
                               echo READY > "$DIR/state" ;;
  *"queued-resources describe"*)
    s=$(cat "$DIR/qstate" 2>/dev/null || echo UNKNOWN)
    echo ACTIVE > "$DIR/qstate"   # next poll sees ACTIVE
    echo "$s" ;;
  *"queued-resources delete"*) rm -f "$DIR/qstate" ;;
  *"tpu-vm scp"*) : ;;
  *"tpu-vm ssh"*)
    case "$*" in
      *"pip install"*) exit 0 ;;   # setup
      *"curl "*) cat "$DIR/podstatus" 2>/dev/null; exit 0 ;;
      *"worker-"*) cat "$DIR/podhb" 2>/dev/null; exit 0 ;;
      *"--command cat "*) cat "$DIR/heartbeat" 2>/dev/null; exit 0 ;;
    esac
    line=$(head -n 1 "$DIR/runplan" 2>/dev/null || echo ok)
    tail -n +2 "$DIR/runplan" > "$DIR/runplan.t" 2>/dev/null || true
    mv "$DIR/runplan.t" "$DIR/runplan" 2>/dev/null || true
    case "$line" in
      preempt) echo PREEMPTED > "$DIR/state"; exit 255 ;;
      vanish)  rm -f "$DIR/state"; exit 255 ;;
      fail)    exit 7 ;;
      elastic) exit 75 ;;
      *)       exit 0 ;;
    esac ;;
esac
"""


@pytest.fixture
def launcher(tmp_path):
    stub_dir = tmp_path / "stub"
    stub_dir.mkdir()
    gcloud = stub_dir / "gcloud"
    gcloud.write_text(GCLOUD_STUB)
    gcloud.chmod(gcloud.stat().st_mode | stat.S_IEXEC)

    def run(*args, env=None, plan=None):
        if plan is not None:
            (stub_dir / "runplan").write_text("\n".join(plan) + "\n")
        e = dict(os.environ)
        e["PATH"] = f"{stub_dir}:{e['PATH']}"
        e["STUB_DIR"] = str(stub_dir)
        e["TPU_POLL_SECS"] = "0"
        e.update(env or {})
        return subprocess.run(["sh", SCRIPT, *args], env=e, cwd=str(tmp_path),
                              capture_output=True, text=True, timeout=60)

    run.calls = lambda: (stub_dir / "calls.log").read_text() \
        if (stub_dir / "calls.log").exists() else ""
    run.state = lambda: (stub_dir / "state").read_text().strip() \
        if (stub_dir / "state").exists() else "MISSING"
    run.stub_dir = stub_dir
    return run


def test_status_missing_and_create(launcher):
    r = launcher("status", "pod", "z")
    assert r.returncode == 0 and r.stdout.strip() == "MISSING"
    assert launcher("create", "pod", "z", "v5e-32").returncode == 0
    assert launcher("status", "pod", "z").stdout.strip() == "READY"


def test_spot_flag(launcher):
    launcher("create", "pod", "z", "v5e-32", env={"TPU_SPOT": "1"})
    assert "--spot" in launcher.calls()


def test_watch_recovers_from_preemption(launcher):
    """First run is preempted mid-flight -> watch deletes the husk,
    recreates (create+setup), re-runs; second run completes -> exit 0."""
    launcher("create", "pod", "z", "v5e-32")
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app",
                 plan=["preempt", "ok"])
    assert r.returncode == 0, r.stderr
    assert "recovering" in r.stderr and "recreating" in r.stderr
    assert "command completed" in r.stderr
    calls = launcher.calls()
    assert calls.count("tpu-vm create") == 2  # initial + recreate
    assert launcher.state() == "READY"


def test_watch_recovers_vanished_vm(launcher):
    """The VM disappearing entirely (state MISSING) is recovered the same
    way as an explicit PREEMPTED state."""
    launcher("create", "pod", "z", "v5e-32")
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app",
                 plan=["vanish", "ok"])
    assert r.returncode == 0, r.stderr


def test_watch_stops_on_real_app_failure(launcher):
    """A non-zero exit on a READY pod that REPEATS is an app bug, not a
    preemption: watch must NOT loop — it stops and points at `resume`."""
    launcher("create", "pod", "z", "v5e-32")
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app",
                 plan=["fail", "fail", "ok"])
    assert r.returncode == 1
    assert "app error" in r.stderr
    assert launcher.calls().count("tpu-vm create") == 1  # no recreate


def test_watch_retries_transient_run_failure(launcher):
    """ONE run failure on a READY pod is retried before concluding app
    error: a transient ssh/network drop mid-run must not abort
    supervision of a healthy training job (r3 advisor)."""
    launcher("create", "pod", "z", "v5e-32")
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app",
                 plan=["fail", "ok"])
    assert r.returncode == 0, r.stderr
    assert "retrying once" in r.stderr
    assert "command completed" in r.stderr
    assert launcher.calls().count("tpu-vm create") == 1  # no recreate


def test_watch_reports_heartbeat_on_ready_failure(launcher):
    """With TPU_HEARTBEAT_FILE set, a run failure on a READY pod fetches
    the app's heartbeat JSON from worker 0 and echoes it — watch's
    "slow vs sick" answer without log parsing (the stub serves the
    fixture's heartbeat file for `--command cat` ssh calls)."""
    launcher("create", "pod", "z", "v5e-32")
    (launcher.stub_dir / "heartbeat").write_text(
        '{"t": 1.0, "step": 12, "status": "nonfinite", "rollbacks": 2}')
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app",
                 plan=["fail", "fail"],
                 env={"TPU_HEARTBEAT_FILE": "/tmp/hb.json"})
    assert r.returncode == 1  # two READY failures: app error
    assert "last heartbeat from worker 0" in r.stderr
    assert "nonfinite" in r.stderr
    # and without the knob no heartbeat ssh traffic happens at all
    assert launcher.calls().count("--command cat") == 2


def test_watch_pod_status_probe_names_sick_worker(launcher):
    """With TPU_POD_STATUS_PORT set, a READY-pod failure curls worker 0's
    pod aggregation endpoint and echoes the MERGED pod JSON — a sick or
    straggling worker != 0 is named by id, which the single worker-0
    heartbeat probe could never do."""
    launcher("create", "pod", "z", "v5e-32")
    (launcher.stub_dir / "podstatus").write_text(
        '{"n_workers": 4, "n_alive": 4, "stragglers": ["2"], '
        '"workers": [{"worker": "2", "status": "nonfinite"}]}')
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app",
                 plan=["fail", "fail"],
                 env={"TPU_POD_STATUS_PORT": "9100"})
    assert r.returncode == 1  # two READY failures: app error
    assert "pod status from worker 0" in r.stderr
    assert '"stragglers": ["2"]' in r.stderr
    assert "curl" in launcher.calls()


def test_watch_pod_file_fallback_names_sick_worker(launcher):
    """Pod endpoint unreachable -> fall back to per-worker heartbeat
    files on the shared TPU_POD_DIR prefix: every worker's beat is
    echoed with its id, so the sick worker is still named."""
    launcher("create", "pod", "z", "v5e-32")
    (launcher.stub_dir / "podhb").write_text(
        '{"t": 1.0, "worker": 0, "status": "ok"}\n'
        '{"t": 1.0, "worker": 1, "status": "nonfinite"}')
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app",
                 plan=["fail", "fail"],
                 env={"TPU_POD_STATUS_PORT": "9100",
                      "TPU_POD_DIR": "/data/pod"})
    assert r.returncode == 1
    assert "falling back" in r.stderr
    assert "per-worker heartbeats" in r.stderr
    assert '"worker": 1' in r.stderr and "nonfinite" in r.stderr


def test_watch_creates_from_nothing(launcher):
    """watch on a not-yet-created pod bootstraps it (MISSING -> recreate)."""
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app", plan=["ok"])
    assert r.returncode == 0, r.stderr
    assert "tpu-vm create" in launcher.calls()


def test_resume_one_shot(launcher):
    launcher("create", "pod", "z", "v5e-32")
    # simulate a preemption observed out-of-band
    launcher("run", "pod", "z", "x", plan=["preempt"])
    r = launcher("resume", "pod", "z", "v5e-32", "python -m app",
                 plan=["ok"])
    assert r.returncode == 0, r.stderr
    assert launcher.calls().count("tpu-vm create") == 2


def test_create_queued_waits_for_active(launcher):
    r = launcher("create-queued", "pod", "z", "v5e-32")
    assert r.returncode == 0, r.stderr
    # polled through PROVISIONING to ACTIVE
    assert "PROVISIONING" in r.stderr and "ACTIVE" in r.stderr


def test_delete_cleans_queued_wrapper(launcher):
    launcher("create-queued", "pod", "z", "v5e-32")
    launcher("delete", "pod", "z")
    assert "queued-resources delete" in launcher.calls()
    assert launcher.state() == "MISSING"


def test_describe_warning_does_not_mask_state(launcher):
    """A successful describe that ALSO prints a gcloud warning to stderr
    must still yield the bare state value — with stderr folded into the
    capture, watch would see a multi-line blob matching no case and
    degrade to an endless UNKNOWN-wait on a READY pod (r3 advisor)."""
    launcher("create", "pod", "z", "v5e-32")
    (launcher.stub_dir / "warn").write_text("")
    r = launcher("status", "pod", "z")
    assert r.stdout.strip() == "READY"
    # and watch still supervises a run to completion through the warning
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app", plan=["ok"])
    assert r.returncode == 0, r.stderr


def test_transient_describe_failure_is_not_missing(launcher):
    """A describe that fails for a non-NOT_FOUND reason (network, auth)
    must NOT be treated as a vanished VM: status says UNKNOWN and resume
    refuses to delete/recreate (r3 review: a client-side blip must not
    kill a healthy pod)."""
    launcher("create", "pod", "z", "v5e-32")
    (launcher.stub_dir / "transient").write_text("")
    r = launcher("status", "pod", "z")
    assert r.stdout.strip() == "UNKNOWN"
    r = launcher("resume", "pod", "z", "v5e-32", "python -m app")
    assert r.returncode == 1
    assert "not recoverable" in r.stderr
    assert "tpu-vm delete" not in launcher.calls()


def test_resume_surfaces_create_failure(launcher):
    """Recreate failing (spot stockout) must propagate, not silently
    'succeed' into a run against a missing VM."""
    launcher("create", "pod", "z", "v5e-32")
    launcher("run", "pod", "z", "x", plan=["preempt"])
    (launcher.stub_dir / "createfail").write_text("")
    r = launcher("resume", "pod", "z", "v5e-32", "python -m app")
    assert r.returncode == 1
    # and the run was never attempted against the missing VM
    assert "python -m app" not in launcher.calls()


def test_delete_failure_propagates(launcher):
    """delete must NOT exit 0 when gcloud failed for a real reason — a
    billed pod silently left running is the worst outcome."""
    launcher("create", "pod", "z", "v5e-32")
    (launcher.stub_dir / "deletefail").write_text("")
    r = launcher("delete", "pod", "z")
    assert r.returncode != 0
    assert "PERMISSION_DENIED" in r.stderr
    # absent resources are fine: delete of a never-created pod exits 0
    (launcher.stub_dir / "deletefail").unlink()
    launcher("delete", "pod", "z")
    assert launcher("delete", "pod", "z").returncode == 0


def test_queued_recreate_knob(launcher):
    """TPU_QUEUED=1 routes watch/resume recreates through queued
    resources (the create-queued pairing for large pods)."""
    launcher("create-queued", "pod", "z", "v5e-32")
    launcher("run", "pod", "z", "x", plan=["preempt"])
    r = launcher("resume", "pod", "z", "v5e-32", "python -m app",
                 env={"TPU_QUEUED": "1"}, plan=["ok"])
    assert r.returncode == 0, r.stderr
    assert launcher.calls().count("queued-resources create") == 2


def test_watch_recreate_resets_transient_fail_count(launcher):
    """A real recovery (recreate) between two READY-pod run failures must
    reset the consecutive-failure count: fail -> preempt+recreate -> fail
    -> ok is a healthy supervised run, not an 'app error'."""
    launcher("create", "pod", "z", "v5e-32")
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app",
                 plan=["fail", "preempt", "fail", "ok"])
    assert r.returncode == 0, r.stderr
    assert launcher.calls().count("tpu-vm create") == 2  # one recreate


def test_watch_elastic_exit75_relaunches_without_strike(launcher):
    """Exit 75 (ElasticRelaunch) is the app's "membership changed,
    checkpointed, relaunch me" signal: watch re-runs immediately — no
    strike, no recreate — and repeated 75s never trip the app-error
    stop (each relaunch is a legitimate joiner rejoining the pod)."""
    launcher("create", "pod", "z", "v5e-32")
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app",
                 plan=["elastic", "elastic", "ok"])
    assert r.returncode == 0, r.stderr
    assert r.stderr.count("elastic membership change") == 2
    assert "command completed" in r.stderr
    assert "app error" not in r.stderr
    assert launcher.calls().count("tpu-vm create") == 1  # no recreate


def test_watch_elastic_exit75_then_real_failure_still_stops(launcher):
    """A 75-relaunch resets nothing it shouldn't: two genuine failures
    after an elastic relaunch still stop with the app-error verdict."""
    launcher("create", "pod", "z", "v5e-32")
    r = launcher("watch", "pod", "z", "v5e-32", "python -m app",
                 plan=["elastic", "fail", "fail"])
    assert r.returncode == 1
    assert "elastic membership change" in r.stderr
    assert "app error" in r.stderr
