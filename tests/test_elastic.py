"""Elastic, preemption-tolerant pod training (parallel/elastic.py + the
train loop's resize path): membership declaration (stale -> full-jitter
re-probe -> evict, never a single missed beat; joiner adoption), the live
resize through the verified checkpoint store, min_workers
checkpoint-and-halt, per-worker τ masking, and the promoted
elastic-momentum A/B smoke."""
import json
import os
import random
import time

import numpy as np
import pytest

from sparknet_tpu import CompiledNet, net_from_prototxt
from sparknet_tpu.apps.train_loop import train
from sparknet_tpu.data.dataset import ArrayDataset
from sparknet_tpu.obs.pod import worker_heartbeat_path
from sparknet_tpu.parallel import ParallelTrainer, make_mesh
from sparknet_tpu.parallel.elastic import (ELASTIC_RELAUNCH_EXIT,
                                           ElasticRelaunch,
                                           MembershipController)
from sparknet_tpu.solver import SolverConfig
from sparknet_tpu.utils.config import ElasticConfig, RunConfig
from sparknet_tpu.utils.health import TrainingHealthError, liveness_classify
from sparknet_tpu.utils.heartbeat import HeartbeatWriter, read_heartbeat
from sparknet_tpu.utils.logger import Logger
from test_parallel import TINY_MLP


# -- heartbeat age + the shared dead-vs-slow rule ----------------------------

def test_read_heartbeat_returns_age(tmp_path):
    p = str(tmp_path / "hb.json")
    HeartbeatWriter(p).beat(3, status="ok")
    hb = read_heartbeat(p)
    assert hb["age_s"] is not None and hb["age_s"] < 5.0
    # a backdated beat reads as old through the SAME field
    rec = json.load(open(p))
    rec["t"] = time.time() - 1000
    json.dump(rec, open(p, "w"))
    assert read_heartbeat(p)["age_s"] > 900


def test_liveness_classify_dead_vs_slow():
    assert liveness_classify(None, 60) == "missing"
    assert liveness_classify({"status": "ok"}, 60) == "missing"  # no t
    assert liveness_classify({"status": "ok", "age_s": 1.0}, 60) == "ok"
    assert liveness_classify({"status": "ok", "age_s": 90.0}, 60) == "stale"
    assert liveness_classify({"status": "done", "age_s": 1.0}, 60) == "done"
    for s in ("spike", "nonfinite", "rollback", "degraded"):
        assert liveness_classify({"status": s, "age_s": 1.0}, 60) == "sick"
    # SLOW is not a liveness verdict: a fresh beat with a huge round_s
    # is "ok" here — only the straggler attribution may flag it
    assert liveness_classify(
        {"status": "ok", "age_s": 1.0, "round_s": 100.0}, 60) == "ok"


# -- MembershipController ----------------------------------------------------

def _beat(pod_dir, worker, status="ok", age=0.0, **kv):
    p = worker_heartbeat_path(str(pod_dir), worker)
    HeartbeatWriter(p, interval_s=0.0).beat(0, status=status, force=True,
                                            **kv)
    if age:
        rec = json.load(open(p))
        rec["t"] = time.time() - age
        json.dump(rec, open(p, "w"))


def _controller(pod_dir, n=3, **cfg_kw):
    cfg_kw.setdefault("stale_after_s", 60.0)
    cfg_kw.setdefault("reprobe_backoff_s", 0.0)  # immediate re-probes
    cfg_kw.setdefault("dead_probes", 2)
    cfg_kw.setdefault("poll_interval_s", 0.0)
    return MembershipController(
        ElasticConfig(enabled=True, **cfg_kw), str(pod_dir),
        self_worker=0, expected_workers=n, rng=random.Random(0))


def test_never_evicts_on_a_single_missed_beat(tmp_path):
    pod = tmp_path / "pod"
    for i in (1, 2):
        _beat(pod, i)
    c = _controller(pod, n=3)
    assert c.poll(0) is None  # first poll seeds membership
    assert c.members == {"0", "1", "2"}
    _beat(pod, 2, age=1000)  # worker 2 goes silent
    # sighting 1 only SUSPECTS; probes 1 and 2 must both still see it
    # stale before the eviction fires
    assert c.poll(1) is None
    assert "2" in c._suspect
    assert c.poll(2) is None          # probe 1 of 2
    ev = c.poll(3)                    # probe 2 of 2 -> dead
    assert ev is not None and ev.dead == ("2",) and ev.epoch == 1
    assert ev.reasons["2"] == "stale"
    assert c.members == {"0", "1"}
    assert c.audit[-1]["dead"] == ["2"]


def test_fresh_beat_clears_suspicion(tmp_path):
    pod = tmp_path / "pod"
    _beat(pod, 1)
    c = _controller(pod, n=2)
    c.poll(0)
    _beat(pod, 1, age=1000)
    assert c.poll(1) is None and "1" in c._suspect
    _beat(pod, 1)  # the worker comes back before the probes run out
    assert c.poll(2) is None
    assert not c._suspect and c.members == {"0", "1"}


def test_done_is_a_graceful_leave_without_probes(tmp_path):
    pod = tmp_path / "pod"
    _beat(pod, 1)
    c = _controller(pod, n=2)
    c.poll(0)
    _beat(pod, 1, status="done")
    ev = c.poll(1)
    assert ev is not None and ev.dead == ("1",)
    assert ev.reasons["1"] == "done"


def test_joiner_adopted_and_denied(tmp_path):
    pod = tmp_path / "pod"
    c = _controller(pod, n=1)
    c.poll(0)
    assert c.members == {"0"}
    _beat(pod, 5)  # a brand-new worker id offers a fresh beat
    ev = c.poll(1)
    assert ev is not None and ev.joined == ("5",) and ev.n_workers == 2
    # deny policy: the same offer is ignored (warned once)
    c2 = _controller(pod / "2", n=1, rejoin="deny")
    c2.poll(0)
    _beat(pod / "2", 7)
    with pytest.warns(RuntimeWarning, match="rejoin policy"):
        assert c2.poll(1) is None
    assert c2.members == {"0"}


def test_stale_leftover_outside_declared_range_never_joins(tmp_path):
    pod = tmp_path / "pod"
    _beat(pod, 9, age=1000)  # a previous incarnation's dead file
    c = _controller(pod, n=2)
    c.poll(0)
    assert c.members == {"0", "1"}  # declared range only
    assert c.poll(1) is None        # and it never joins while stale


def test_stale_leftover_inside_declared_range_not_seeded(tmp_path):
    """The exit-75 relaunch-bounce breaker: a relaunched pod whose
    declared range still names a permanently-lost worker must NOT seed
    it from its leftover stale heartbeat (that would re-evict it and
    relaunch forever) — but the worker rejoins through adopt the moment
    it beats fresh."""
    pod = tmp_path / "pod"
    _beat(pod, 1)              # alive peer
    _beat(pod, 2, age=1000)    # previous incarnation's dead worker
    c = _controller(pod, n=3)
    assert c.poll(0) is None
    assert c.members == {"0", "1"}  # leftover excluded at seeding
    assert c.audit[-1]["seed_leftovers"] == ["2"]
    assert c.poll(1) is None        # ...and never evicted (no bounce)
    _beat(pod, 2)                   # the worker comes back
    ev = c.poll(2)
    assert ev is not None and ev.joined == ("2",)
    assert c.members == {"0", "1", "2"}


def test_expected_but_never_beating_worker_is_evicted(tmp_path):
    pod = tmp_path / "pod"
    c = _controller(pod, n=2)  # worker 1 declared but NEVER beats
    c.poll(0)
    assert c.members == {"0", "1"}
    assert c.poll(1) is None   # suspect
    assert c.poll(2) is None   # probe 1
    ev = c.poll(3)             # probe 2 -> dead
    assert ev is not None and ev.dead == ("1",)
    assert ev.reasons["1"] == "missing"


def test_tau_by_worker_two_worker_median_budgets_the_slow_one(tmp_path):
    """The review-pinned 2-worker case: with round times {1.0, 2.0} the
    median is their MIDPOINT (utils.health._median), so the slow worker
    gets a genuinely shorter budget — an upper-middle 'median' would
    hand everyone full τ and adaptation could never engage at pod size
    2."""
    pod = tmp_path / "pod"
    _beat(pod, 0, round_s=1.0)
    _beat(pod, 1, round_s=2.0)
    c = _controller(pod, n=2, tau_adapt=True)
    c.poll(0)
    out = c.tau_by_worker(4)
    assert out == {"0": 4, "1": 3}  # round(4 * 1.5 / 2.0) == 3
    # uniform pod: every budget is full τ -> None (nothing to adapt)
    _beat(pod, 1, round_s=1.0)
    c.poll(1, force=True)
    assert c.tau_by_worker(4) is None
    # 2-worker extreme skew: the midpoint median caps the cut at ~τ/2
    # (the straggler is still half the pod's evidence)
    _beat(pod, 1, round_s=100.0)
    c.poll(2, force=True)
    assert c.tau_by_worker(4)["1"] == 2  # round(4 * 50.5 / 100)
    # 3-worker pod: a true outlier is floored at tau_min, fast workers
    # keep full τ
    _beat(pod, 2, round_s=1.0)
    ev = c.poll(3, force=True)
    assert ev is not None and ev.joined == ("2",)
    out3 = c.tau_by_worker(4)
    assert out3 == {"0": 4, "1": c.cfg.tau_min, "2": 4}


@pytest.mark.chaos
def test_tau_adapt_through_train_loop(tmp_path):
    """tau_adapt end to end on a 2-devices-per-worker pod: the per-WORKER
    budget dict expands to the per-DATA-GROUP vector (4 groups, 2
    workers), so the slow worker's BOTH device groups run the shorter
    budget — sized per membership it would crash the trainer's
    per-group assert."""
    pod = tmp_path / "pod"
    hb1 = HeartbeatWriter(worker_heartbeat_path(str(pod), 1),
                          interval_s=0.0)
    hb1.beat(0, status="ok", round_s=0.5, force=True)
    cfg = _tiny_cfg(tmp_path, 4, max_rounds=6)
    cfg.tau = 4
    cfg.elastic.expected_workers = 2  # 2 workers x 2 device groups
    cfg.elastic.tau_adapt = True

    def hook(rnd, state):
        hb1.beat(rnd, status="ok", round_s=0.5, force=True)

    log = Logger(str(tmp_path / "l.txt"), echo=False)
    # worker 0 (this loop) reports no round_s until its first flush ran;
    # after that the controller sees {0: fast, 1: 0.5s} and budgets
    st = train(cfg, net_from_prototxt(TINY_MLP), _tiny_ds(), None,
               logger=log, round_hook=hook)
    log.close()
    # layout-neutral topology probe (momentum rows == data groups)
    assert np.asarray(st.momentum[list(st.momentum)[0]]["w"]).shape[0] == 4
    # the loop ran with a 4-entry vector (or full-τ None) — no assert
    # fired, and training completed across heterogeneous budgets


# -- per-worker τ masking (elastic_tau) --------------------------------------

def _tiny(n_dev, tau=3, cls=ParallelTrainer, **kw):
    net = CompiledNet.compile(net_from_prototxt(TINY_MLP))
    scfg = SolverConfig(base_lr=0.05, momentum=0.9, lr_policy="fixed")
    return cls(net, scfg, make_mesh(n_dev), tau=tau, **kw)


def _tiny_batches(n_dev, tau=3, b=4, seed=0):
    r = np.random.default_rng(seed)
    data = r.standard_normal((tau, n_dev * b, 6)).astype(np.float32)
    label = (data.sum(-1, keepdims=True) > 0).astype(np.int32)
    return {"data": data, "label": label}


def test_elastic_tau_full_vector_matches_legacy(trainer_cls):
    import jax
    t0 = _tiny(4, cls=trainer_cls)
    t1 = _tiny(4, elastic_tau=True, cls=trainer_cls)
    b = _tiny_batches(4)
    s0, l0 = t0.train_round(t0.init_state(jax.random.PRNGKey(0)), b,
                            jax.random.PRNGKey(1))
    s1, l1 = t1.train_round(t1.init_state(jax.random.PRNGKey(0)), b,
                            jax.random.PRNGKey(1))
    assert abs(float(l0) - float(l1)) < 1e-6
    for ln in s0.params:
        for pn in s0.params[ln]:
            np.testing.assert_allclose(
                np.asarray(s0.params[ln][pn]), np.asarray(s1.params[ln][pn]),
                rtol=1e-5, atol=1e-7, err_msg=f"{ln}/{pn}")


def test_tau_by_worker_all_ones_equals_tau1_trainer(trainer_cls):
    """Masking oracle: every worker budgeted 1 step == a τ=1 trainer on
    the first slice (same per-worker rng rows by construction)."""
    import jax
    t_el = _tiny(4, elastic_tau=True, cls=trainer_cls)
    b = _tiny_batches(4)
    sA, lA = t_el.train_round(t_el.init_state(jax.random.PRNGKey(0)), b,
                              jax.random.PRNGKey(1),
                              tau_by_worker=[1, 1, 1, 1])
    t_ref = _tiny(4, tau=1, cls=trainer_cls)
    sB, lB = t_ref.train_round(t_ref.init_state(jax.random.PRNGKey(0)),
                               {k: v[:1] for k, v in b.items()},
                               jax.random.PRNGKey(1))
    assert abs(float(lA) - float(lB)) < 1e-6
    for ln in sA.params:
        for pn in sA.params[ln]:
            np.testing.assert_allclose(
                np.asarray(sA.params[ln][pn]), np.asarray(sB.params[ln][pn]),
                rtol=1e-5, atol=1e-7, err_msg=f"{ln}/{pn}")


def test_tau_by_worker_changes_are_recompile_free(trainer_cls):
    import jax
    t = _tiny(2, elastic_tau=True, cls=trainer_cls)
    b = _tiny_batches(2)
    s = t.init_state(jax.random.PRNGKey(0))
    # two priming rounds: steady state is ONE executable plus a fast-path
    # key for its own output layout (the second round's input), which the
    # two layouts reach one round apart
    s, _ = t.train_round(s, b, jax.random.PRNGKey(1))
    s, _ = t.train_round(s, b, jax.random.PRNGKey(1))
    n0 = t.compiled_variants()
    for vec in ([2, 3], [1, 1], [3, 2]):
        s, _ = t.train_round(s, b, jax.random.PRNGKey(2),
                             tau_by_worker=vec)
    assert t.compiled_variants() == n0  # a traced input, not a shape
    with pytest.raises(ValueError):
        _tiny(2).train_round(s, b, jax.random.PRNGKey(3),
                             tau_by_worker=[1, 1])
    # resized() carries the whole configuration (and the CLASS) to the
    # new mesh
    t2 = t.resized(1)
    assert type(t2) is trainer_cls
    assert (t2.n_devices, t2.tau, t2.elastic_tau) == (1, t.tau, True)


# -- the train loop's elastic resize path ------------------------------------

def _tiny_cfg(tmp_path, n_dev, max_rounds, **kw):
    kw.setdefault("elastic", ElasticConfig(
        enabled=True, expected_workers=n_dev, stale_after_s=30.0,
        reprobe_backoff_s=0.0, dead_probes=2, poll_interval_s=0.0,
        min_workers=1))
    return RunConfig(model="prototxt-inline", n_devices=n_dev,
                     local_batch=8, tau=2, max_rounds=max_rounds,
                     eval_every=0, workdir=str(tmp_path),
                     checkpoint_dir=str(tmp_path / "ck"),
                     checkpoint_every=3, pod_dir=str(tmp_path / "pod"),
                     heartbeat_every_s=0.0, **kw)


def _tiny_ds(n=512, seed=0):
    r = np.random.default_rng(seed)
    data = r.standard_normal((n, 6)).astype(np.float32)
    label = (data.sum(-1, keepdims=True) > 0).astype(np.int32)
    return ArrayDataset({"data": data, "label": label})


def _kill(pod_dir, worker):
    """Backdate the worker's beat so it reads stale immediately (the
    deterministic stand-in for 'the VM was preempted minutes ago')."""
    p = worker_heartbeat_path(str(pod_dir), worker)
    rec = json.load(open(p))
    rec["t"] = time.time() - 1e4
    json.dump(rec, open(p, "w"))


@pytest.mark.chaos
@pytest.mark.parametrize("impl", ["shard_map", "named"])
def test_elastic_evict_and_rejoin_through_train_loop(tmp_path, impl):
    """THE tentpole path: a worker's heartbeat goes stale mid-run -> the
    loop evicts it at the τ boundary (resize 2 devices -> 1; restored
    from the verified checkpoint under the replica layout, RE-PLACED
    live under the NamedSharding layout), it comes back -> rejoin
    (1 -> 2). Every eviction/rejoin lands in the JSONL audit trail and
    training keeps descending across both resizes — under BOTH trainer
    implementations."""
    pod = tmp_path / "pod"
    hb1 = HeartbeatWriter(worker_heartbeat_path(str(pod), 1),
                          interval_s=0.0)
    hb1.beat(0, status="ok", round_s=0.01, force=True)
    cfg = _tiny_cfg(tmp_path, 2, max_rounds=12, trainer_impl=impl)
    shapes, killed, rejoined = [], [False], [False]

    def hook(rnd, state):
        # layout-neutral topology probe: replicated momentum rows count
        # the data groups in BOTH layouts ([n_devices] replica rows vs
        # [n_data] logical worker rows; tp == 1 here so they coincide)
        shapes.append(
            np.asarray(state.momentum[list(state.momentum)[0]]
                       ["w"]).shape[0])
        if not killed[0] and rnd == 2:
            killed[0] = True
            _kill(pod, 1)
        elif killed[0] and not rejoined[0] and min(shapes) == 1:
            rejoined[0] = True
            hb1.beat(rnd, status="ok", round_s=0.01, force=True)
        elif not killed[0]:
            hb1.beat(rnd, status="ok", round_s=0.01, force=True)

    jsonl = str(tmp_path / "m.jsonl")
    log = Logger(str(tmp_path / "l.txt"), echo=False, jsonl_path=jsonl)
    train(cfg, net_from_prototxt(TINY_MLP), _tiny_ds(), None, logger=log,
          round_hook=hook)
    log.close()
    recs = [json.loads(l) for l in open(jsonl)]
    resizes = [r for r in recs if r.get("event") == "resize"]
    assert any(r["dead"] == ["1"] for r in resizes), resizes
    assert any(r["joined"] == ["1"] for r in resizes), resizes
    assert sorted(set(shapes)) == [1, 2]  # both topologies actually ran
    epochs = [r["epoch"] for r in resizes]
    assert epochs == sorted(epochs) and epochs[-1] == 2
    losses = [r["loss"] for r in recs if "loss" in r]
    assert losses[-1] < losses[0]  # survived BOTH resizes and kept learning
    if impl == "named":
        # the logical layout resizes by RE-PLACEMENT, not store read-back
        assert "re-placed live state" in open(str(tmp_path / "l.txt")).read()


@pytest.mark.chaos
def test_elastic_below_min_workers_checkpoints_and_halts(tmp_path):
    """Dropping below min_workers is a LOUD halt, never a hang: the loop
    writes a verified checkpoint at the boundary, then raises
    TrainingHealthError naming the dead worker."""
    from sparknet_tpu.utils import checkpoint as ck

    pod = tmp_path / "pod"
    HeartbeatWriter(worker_heartbeat_path(str(pod), 1),
                    interval_s=0.0).beat(0, status="ok", force=True)
    cfg = _tiny_cfg(tmp_path, 2, max_rounds=40)
    cfg.elastic.min_workers = 2

    def hook(rnd, state):
        if rnd == 1:
            _kill(pod, 1)

    log = Logger(str(tmp_path / "l.txt"), echo=False,
                 jsonl_path=str(tmp_path / "m.jsonl"))
    with pytest.raises(TrainingHealthError, match="min_workers"):
        train(cfg, net_from_prototxt(TINY_MLP), _tiny_ds(), None,
              logger=log, round_hook=hook)
    log.close()
    step = ck.newest_verified_step(cfg.checkpoint_dir)
    assert step is not None and step >= 1  # the boundary snapshot landed
    recs = [json.loads(l) for l in open(str(tmp_path / "m.jsonl"))]
    assert any(r.get("event") == "resize" and r["dead"] == ["1"]
               for r in recs)


@pytest.mark.chaos
def test_elastic_resume_after_halt_continues(tmp_path):
    """The checkpoint the halt left behind is a working resume point: a
    relaunch at the surviving size picks it up through the normal elastic
    resume path and finishes the run."""
    test_elastic_below_min_workers_checkpoints_and_halts(tmp_path)
    cfg = _tiny_cfg(tmp_path, 1, max_rounds=6)
    cfg.elastic.min_workers = 1
    cfg.elastic.expected_workers = 1
    log_path = str(tmp_path / "l2.txt")
    log = Logger(log_path, echo=False)
    st = train(cfg, net_from_prototxt(TINY_MLP), _tiny_ds(), None,
               logger=log)
    log.close()
    assert np.asarray(st.momentum[list(st.momentum)[0]]["w"]).shape[0] == 1
    assert "ELASTIC resume" in open(log_path).read()


def test_membership_change_without_reshardable_source_relaunches(tmp_path):
    """A source that cannot reshard in-process (streaming) turns a
    membership change into checkpoint + ElasticRelaunch (SystemExit 75)
    — the launcher's relaunch-as-joiner signal — never a hang."""
    from sparknet_tpu.apps.train_loop import run_loop
    from sparknet_tpu.data.dataset import RoundSampler
    from sparknet_tpu.utils import checkpoint as ck

    pod = tmp_path / "pod"
    HeartbeatWriter(worker_heartbeat_path(str(pod), 1),
                    interval_s=0.0).beat(0, status="ok", force=True)
    cfg = _tiny_cfg(tmp_path, 2, max_rounds=40)

    class NoReshard:  # next_round but no reshard(): streaming-shaped
        stateless_rounds = True

        def __init__(self, sampler):
            self._s = sampler

        def next_round(self, round_index=None):
            return self._s.next_round(round_index)

    trainer = _tiny(2, tau=cfg.tau)
    src = NoReshard(RoundSampler(_tiny_ds(), 2, cfg.local_batch, cfg.tau))

    def hook(rnd, state):
        if rnd == 1:
            _kill(pod, 1)

    log = Logger(str(tmp_path / "l.txt"), echo=False)
    with pytest.raises(ElasticRelaunch) as ei:
        run_loop(cfg, trainer, src, None, log, round_hook=hook,
                 trainer_factory=None)
    log.close()
    assert ei.value.code == ELASTIC_RELAUNCH_EXIT == 75
    assert ck.newest_verified_step(cfg.checkpoint_dir) is not None


# -- the promoted elastic-momentum A/B smoke (satellite) ---------------------

def test_elastic_momentum_ab_smoke(tmp_path):
    """Short-rounds run/resume smoke of scripts/elastic_momentum_ab.py:
    the A/B harness whose verdict (norm_rescale) the elastic resize
    applies must keep running end to end — every policy resumes 8->4 and
    8->2 and produces the summary/winner schema."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "elastic_momentum_ab",
        os.path.join(os.path.dirname(__file__), "..", "scripts",
                     "elastic_momentum_ab.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out_path = str(tmp_path / "ab.json")
    out = mod.main(["--seeds", "1", "--rounds-pre", "2",
                    "--rounds-post", "3", "--out", out_path])
    assert out["winner"] in mod.POLICIES
    for pol in mod.POLICIES:
        for nd in (4, 2):
            assert len(out["results"][pol][nd]) == 1
            assert "max_rel_dev" in out["results"][pol][nd][0]
    on_disk = json.load(open(out_path))
    assert on_disk["summary"].keys() == out["summary"].keys()
