"""Independent numpy reimplementation of cifar10_quick forward/backward +
Caffe SGD — the recipe-scale trajectory oracle (r4, VERDICT item 4b).

Derived from the Caffe layer definitions the reference ran natively
(conv/pool semantics per Caffe's ConvolutionLayer/PoolingLayer, SGD per
SGDSolver::ComputeUpdateValue), NOT from sparknet_tpu's jax code: gradients
come from hand-written im2col/col2im, window argmax routing, and clipped
average-pool divisors. Agreement of a 50-iteration recipe-hyperparameter
trajectory between this and the jitted framework step is evidence the
framework's net+solver are RIGHT, not merely self-consistent.

Layouts follow the framework's storage so states compare directly:
activations NHWC, conv weights HWIO, ip weights (in, out). All math f32.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np
from numpy.lib.stride_tricks import sliding_window_view


# -- primitives --------------------------------------------------------------

def _ceil_out(size: int, k: int, s: int) -> int:
    # Caffe pool output (pad=0): ceil((size - k) / s) + 1
    return int(np.ceil((size - k) / s)) + 1


def conv_fwd(x, w, b, pad):
    """x [N,H,W,C], w [k,k,C,O] (stride 1). Returns (y, cols)."""
    n, h, wd, c = x.shape
    k = w.shape[0]
    xp = np.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    win = sliding_window_view(xp, (k, k), axis=(1, 2))  # N,OH,OW,C,k,k
    cols = win.transpose(0, 1, 2, 4, 5, 3).reshape(
        n, h, wd, k * k * c)  # taps row-major, channel minor == HWIO order
    y = cols @ w.reshape(k * k * c, -1) + b
    return y.astype(np.float32), cols


def conv_bwd(dy, cols, x_shape, w, pad):
    """Returns (dx, dw [k,k,C,O], db)."""
    n, h, wd, c = x_shape
    k = w.shape[0]
    o = w.shape[-1]
    wmat = w.reshape(k * k * c, o)
    db = dy.sum(axis=(0, 1, 2))
    dwmat = cols.reshape(-1, k * k * c).T @ dy.reshape(-1, o)
    dcols = (dy.reshape(-1, o) @ wmat.T).reshape(n, h, wd, k, k, c)
    dxp = np.zeros((n, h + 2 * pad, wd + 2 * pad, c), np.float32)
    for ki in range(k):      # col2im: scatter-add each tap's contribution
        for kj in range(k):
            dxp[:, ki:ki + h, kj:kj + wd] += dcols[:, :, :, ki, kj]
    dx = dxp[:, pad:pad + h, pad:pad + wd]
    return dx, dwmat.reshape(w.shape).astype(np.float32), db.astype(np.float32)


def _pool_windows(x, k, s):
    """End-pad (value-agnostic caller pads) and window: returns padded x
    dims + window view helper shapes."""
    n, h, w, c = x.shape
    oh, ow = _ceil_out(h, k, s), _ceil_out(w, k, s)
    eh = (oh - 1) * s + k - h
    ew = (ow - 1) * s + k - w
    return oh, ow, max(eh, 0), max(ew, 0)


def maxpool_fwd(x, k, s):
    n, h, w, c = x.shape
    oh, ow, eh, ew = _pool_windows(x, k, s)
    xp = np.pad(x, ((0, 0), (0, eh), (0, ew), (0, 0)),
                constant_values=-np.inf)
    win = sliding_window_view(xp, (k, k), axis=(1, 2))[:, ::s, ::s]
    # windows row-major: argmax picks the FIRST max (Caffe's recorded argmax)
    flat = win.reshape(n, oh, ow, c, k * k)
    arg = flat.argmax(axis=-1)
    y = np.take_along_axis(flat, arg[..., None], axis=-1)[..., 0]
    return y.astype(np.float32), arg


def maxpool_bwd(dy, arg, x_shape, k, s):
    n, h, w, c = x_shape
    oh, ow = dy.shape[1:3]
    dx = np.zeros((n, h + k, w + k, c), np.float32)  # slack for edge windows
    ki, kj = np.divmod(arg, k)
    ii = np.arange(oh)[None, :, None, None] * s + ki
    jj = np.arange(ow)[None, None, :, None] * s + kj
    nn = np.arange(n)[:, None, None, None]
    cc = np.arange(c)[None, None, None, :]
    np.add.at(dx, (nn, ii, jj, cc), dy)
    return dx[:, :h, :w]


def avepool_fwd(x, k, s):
    n, h, w, c = x.shape
    oh, ow, eh, ew = _pool_windows(x, k, s)
    xp = np.pad(x, ((0, 0), (0, eh), (0, ew), (0, 0)))
    win = sliding_window_view(xp, (k, k), axis=(1, 2))[:, ::s, ::s]
    ssum = win.sum(axis=(-2, -1))  # N,OH,OW,C? (window axes last)
    # Caffe divisor: window extent clipped to the (unpadded, pad=0) image
    dh = np.minimum(np.arange(oh) * s + k, h) - np.arange(oh) * s
    dw = np.minimum(np.arange(ow) * s + k, w) - np.arange(ow) * s
    div = np.outer(dh, dw).astype(np.float32)
    return (ssum / div[None, :, :, None]).astype(np.float32), div


def avepool_bwd(dy, div, x_shape, k, s):
    n, h, w, c = x_shape
    oh, ow = dy.shape[1:3]
    g = dy / div[None, :, :, None]
    dx = np.zeros((n, h + k, w + k, c), np.float32)
    for ki in range(k):
        for kj in range(k):
            ii = np.arange(oh) * s + ki
            jj = np.arange(ow) * s + kj
            dx[:, ii[:, None], jj[None, :], :] += g
    return dx[:, :h, :w]


def softmax_loss_fwd_bwd(logits, labels):
    """Mean NLL over the batch (Caffe SoftmaxWithLoss default
    normalization); returns (loss, dlogits)."""
    z = logits - logits.max(axis=-1, keepdims=True)
    e = np.exp(z)
    p = e / e.sum(axis=-1, keepdims=True)
    n = logits.shape[0]
    nll = -np.log(p[np.arange(n), labels] + 1e-30)
    d = p.copy()
    d[np.arange(n), labels] -= 1.0
    return float(nll.mean()), (d / n).astype(np.float32)


# -- cifar10_quick network ---------------------------------------------------

# (name, kind) in execution order; relu is in-place on its input blob
ARCH = [("conv1", "conv", 2), ("pool1", "max"), ("relu1", "relu"),
        ("conv2", "conv", 2), ("relu2", "relu"), ("pool2", "ave"),
        ("conv3", "conv", 2), ("relu3", "relu"), ("pool3", "ave"),
        ("ip1", "ip"), ("ip2", "ip")]
POOL_K, POOL_S = 3, 2
# cifar10_quick param multipliers (weight, bias): lr_mult (1, 2), decay (1, 1)
LR_MULT = {"w": 1.0, "b": 2.0}
DECAY_MULT = {"w": 1.0, "b": 1.0}


def forward_backward(params: Dict[str, Dict[str, np.ndarray]],
                     images_nhwc: np.ndarray, labels: np.ndarray
                     ) -> Tuple[float, Dict[str, Dict[str, np.ndarray]]]:
    """One f32 forward+backward of cifar10_quick; returns (loss, grads)."""
    x = images_nhwc.astype(np.float32)
    acts: List = []  # (kind, saved-for-backward...)
    for entry in ARCH:
        name, kind = entry[0], entry[1]
        if kind == "conv":
            pad = entry[2]
            y, cols = conv_fwd(x, params[name]["w"], params[name]["b"], pad)
            acts.append((name, kind, cols, x.shape, pad))
            x = y
        elif kind == "max":
            y, arg = maxpool_fwd(x, POOL_K, POOL_S)
            acts.append((name, kind, arg, x.shape))
            x = y
        elif kind == "ave":
            y, div = avepool_fwd(x, POOL_K, POOL_S)
            acts.append((name, kind, div, x.shape))
            x = y
        elif kind == "relu":
            mask = x > 0
            acts.append((name, kind, mask))
            x = x * mask
        elif kind == "ip":
            shp = x.shape
            # Caffe flattens NCHW-ordered (weight rows line up with an
            # NCHW walk of the bottom blob)
            flat = (x.transpose(0, 3, 1, 2).reshape(shp[0], -1)
                    if x.ndim == 4 else x.reshape(shp[0], -1))
            y = flat @ params[name]["w"] + params[name]["b"]
            acts.append((name, kind, flat, shp))
            x = y
    loss, d = softmax_loss_fwd_bwd(x, labels)

    grads: Dict[str, Dict[str, np.ndarray]] = {}
    for entry in reversed(acts):
        name, kind = entry[0], entry[1]
        if kind == "ip":
            _, _, flat, shp = entry
            grads[name] = {"w": flat.T @ d, "b": d.sum(axis=0)}
            d = d @ params[name]["w"].T
            d = (d.reshape(shp[0], shp[3], shp[1], shp[2])
                 .transpose(0, 2, 3, 1) if len(shp) == 4
                 else d.reshape(shp))
        elif kind == "relu":
            d = d * entry[2]
        elif kind == "ave":
            _, _, div, x_shape = entry
            d = avepool_bwd(d, div, x_shape, POOL_K, POOL_S)
        elif kind == "max":
            _, _, arg, x_shape = entry
            d = maxpool_bwd(d, arg, x_shape, POOL_K, POOL_S)
        elif kind == "conv":
            _, _, cols, x_shape, pad = entry
            d, dw, db = conv_bwd(d, cols, x_shape, params[name]["w"], pad)
            grads[name] = {"w": dw, "b": db}
    return loss, grads


def sgd_update(params, velocity, grads, lr, momentum, weight_decay):
    """Caffe SGDSolver::ComputeUpdateValue: V <- m*V + local_lr*(g + wd*W);
    W <- W - V. In place on params/velocity."""
    for lname in params:
        for pname in params[lname]:
            local_lr = lr * LR_MULT[pname]
            local_wd = weight_decay * DECAY_MULT[pname]
            g = grads[lname][pname] + local_wd * params[lname][pname]
            velocity[lname][pname] = (momentum * velocity[lname][pname]
                                      + local_lr * g)
            params[lname][pname] = (params[lname][pname]
                                    - velocity[lname][pname])


def train(params, batches, lr, momentum, weight_decay) -> List[float]:
    """Run the recipe loop over [(images_nhwc, labels), ...]; mutates
    params; returns per-iteration losses."""
    velocity = {l: {p: np.zeros_like(v) for p, v in lp.items()}
                for l, lp in params.items()}
    losses = []
    for images, labels in batches:
        loss, grads = forward_backward(params, images, labels)
        sgd_update(params, velocity, grads, lr, momentum, weight_decay)
        losses.append(loss)
    return losses
