#!/bin/sh
# Build the host C++ data plane shared library.
set -e
cd "$(dirname "$0")"
g++ -O3 -march=native -shared -fPIC -fopenmp -o libjpeg_plane.so \
    jpeg_plane.cpp -ljpeg
echo "built $(pwd)/libjpeg_plane.so"
