// Host-side C++ data plane for the ImageNet ingest path.
//
// The reference's equivalent was JVM-native imaging (libjpeg via
// twelvemonkeys/ImageIO + thumbnailator, reference
// preprocessing/ScaleAndConvert.scala:16-48): JPEG decode + force-resize +
// planar CHW byte output, the host-CPU-bound hot loop at ImageNet scale.
// Here: libjpeg decode, bilinear force-resize, CHW emit — plus a fused
// crop/mean-subtract/NHWC batch kernel so Python never touches pixels.
// OpenMP parallel across a batch; plain C ABI for ctypes.
//
// Build: see native/build.sh (g++ -O3 -shared -fPIC -fopenmp -ljpeg).

#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#include <jpeglib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

// Bilinear resize HWC uint8 -> HWC uint8 (force-resize, no aspect keep —
// matching the reference's thumbnailator forceSize).
void resize_bilinear_hwc(const uint8_t* src, int sh, int sw, uint8_t* dst,
                         int dh, int dw, int ch) {
  const float ys = dh > 1 ? float(sh - 1) / float(dh - 1) : 0.f;
  const float xs = dw > 1 ? float(sw - 1) / float(dw - 1) : 0.f;
  for (int y = 0; y < dh; ++y) {
    const float fy = y * ys;
    const int y0 = int(fy);
    const int y1 = y0 + 1 < sh ? y0 + 1 : y0;
    const float wy = fy - y0;
    for (int x = 0; x < dw; ++x) {
      const float fx = x * xs;
      const int x0 = int(fx);
      const int x1 = x0 + 1 < sw ? x0 + 1 : x0;
      const float wx = fx - x0;
      for (int c = 0; c < ch; ++c) {
        const float v00 = src[(y0 * sw + x0) * ch + c];
        const float v01 = src[(y0 * sw + x1) * ch + c];
        const float v10 = src[(y1 * sw + x0) * ch + c];
        const float v11 = src[(y1 * sw + x1) * ch + c];
        const float v = (1 - wy) * ((1 - wx) * v00 + wx * v01) +
                        wy * ((1 - wx) * v10 + wx * v11);
        dst[(y * dw + x) * ch + c] = uint8_t(v + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

// Decode one JPEG and force-resize to (out_h, out_w), writing planar CHW
// uint8 (3 channels). Returns 0 on success, nonzero on decode error.
int jp_decode_resize_chw(const uint8_t* jpeg, long jpeg_len, int out_h,
                         int out_w, uint8_t* out_chw) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  // Declared BEFORE setjmp: longjmp must not jump out of a scope holding
  // live destructible objects (UB + leak); declared here they survive the
  // jump and destruct on normal function return.
  std::vector<uint8_t> hwc;
  std::vector<uint8_t> resized;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, jpeg, static_cast<unsigned long>(jpeg_len));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return 2;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const int sh = cinfo.output_height, sw = cinfo.output_width;
  const int ch = cinfo.output_components;  // 3 after JCS_RGB
  if (ch != 3 || sh <= 0 || sw <= 0) {
    jpeg_destroy_decompress(&cinfo);
    return 3;
  }
  hwc.resize(size_t(sh) * sw * ch);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = hwc.data() + size_t(cinfo.output_scanline) * sw * ch;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  // Strict mode: libjpeg silently tolerates truncated streams (gray fill,
  // warning counter bumped); treat any warning as corrupt so the skip
  // accounting matches the PIL fallback.
  const long warnings = cinfo.err->num_warnings;
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  if (warnings > 0) return 4;

  resized.resize(size_t(out_h) * out_w * ch);
  resize_bilinear_hwc(hwc.data(), sh, sw, resized.data(), out_h, out_w, ch);
  // HWC -> planar CHW
  for (int c = 0; c < ch; ++c)
    for (int y = 0; y < out_h; ++y)
      for (int x = 0; x < out_w; ++x)
        out_chw[(size_t(c) * out_h + y) * out_w + x] =
            resized[(size_t(y) * out_w + x) * ch + c];
  return 0;
}

// Batch decode: jpegs given as one concatenated buffer + offsets/lengths.
// Each output slot is 3*out_h*out_w bytes; ok[i] = 0 on success.
// OpenMP-parallel: this is the multi-core ingest loop that keeps chips fed.
void jp_decode_resize_chw_batch(const uint8_t* blob, const long* offsets,
                                const long* lengths, int n, int out_h,
                                int out_w, uint8_t* out, int* ok) {
#pragma omp parallel for schedule(dynamic)
  for (int i = 0; i < n; ++i) {
    ok[i] = jp_decode_resize_chw(blob + offsets[i], lengths[i], out_h, out_w,
                                 out + size_t(i) * 3 * out_h * out_w);
  }
}

}  // extern "C" (reopened below — the shared body is a C++ template)

// float -> bfloat16 with round-to-nearest-even (matches XLA/ml_dtypes).
static inline uint16_t jp_f32_to_bf16(float f) {
  uint32_t x;
  __builtin_memcpy(&x, &f, 4);
  if ((x & 0x7fffffffu) > 0x7f800000u) {
    // NaN: quiet it (set the top mantissa bit) — the RNE add below would
    // carry a low-payload NaN into the exponent and emit +/-Inf. Inf
    // itself survives the add (0x7f800000 + 0x7fff keeps exponent 0xff).
    return uint16_t((x >> 16) | 0x0040u);
  }
  const uint32_t lsb = (x >> 16) & 1u;
  x += 0x7fffu + lsb;
  return uint16_t(x >> 16);
}

static inline float jp_f32_id(float f) { return f; }

// Shared fused train-time preprocess body — the C++ twin of reference
// ImageNetTensorFlowPreprocessor (Preprocessor.scala:150-178): CHW uint8
// batch -> mean-subtract (full-size CHW f32 mean) -> per-image crop at
// (ys[i], xs[i]) -> NHWC, store converted by Cvt. Channel-OUTER loop
// order: reads walk each source plane sequentially (the channel planes
// sit h*w apart — pixel-inner order made every read a cache miss,
// measured 3.4x slower); writes are stride-c (6/12 bytes),
// cache-resident since the whole per-image output fits in L2.
template <typename OutT, OutT (*Cvt)(float)>
static void jp_crop_mean_nhwc_body(const uint8_t* images_chw, int n, int c,
                                   int h, int w, const float* mean_chw,
                                   const int* ys, const int* xs, int crop,
                                   OutT* out_nhwc) {
#pragma omp parallel for schedule(static)
  for (int i = 0; i < n; ++i) {
    const uint8_t* img = images_chw + size_t(i) * c * h * w;
    OutT* dst = out_nhwc + size_t(i) * crop * crop * c;
    const int y0 = ys[i], x0 = xs[i];
    for (int cc = 0; cc < c; ++cc) {
      for (int y = 0; y < crop; ++y) {
        const uint8_t* srow = img + (size_t(cc) * h + (y + y0)) * w + x0;
        const float* mrow =
            mean_chw ? mean_chw + (size_t(cc) * h + (y + y0)) * w + x0
                     : nullptr;
        OutT* drow = dst + size_t(y) * crop * c + cc;
        for (int x = 0; x < crop; ++x) {
          drow[size_t(x) * c] =
              Cvt(float(srow[x]) - (mrow ? mrow[x] : 0.f));
        }
      }
    }
  }
}

extern "C" {

void jp_crop_mean_nhwc(const uint8_t* images_chw, int n, int c, int h, int w,
                       const float* mean_chw, const int* ys, const int* xs,
                       int crop, float* out_nhwc) {
  jp_crop_mean_nhwc_body<float, jp_f32_id>(
      images_chw, n, c, h, w, mean_chw, ys, xs, crop, out_nhwc);
}

// bf16-emitting variant: saves the numpy-side float32->bfloat16 cast
// (single-threaded and ~3x slower than this loop) AND 2/3 of the output
// write traffic — the training apps feed the device bf16 batches, so the
// f32 intermediate was pure overhead.
void jp_crop_mean_nhwc_bf16(const uint8_t* images_chw, int n, int c, int h,
                            int w, const float* mean_chw, const int* ys,
                            const int* xs, int crop, uint16_t* out_nhwc) {
  jp_crop_mean_nhwc_body<uint16_t, jp_f32_to_bf16>(
      images_chw, n, c, h, w, mean_chw, ys, xs, crop, out_nhwc);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Tar member indexer — removes the Python tarfile walk (GIL-held, ~0.05
// ms/image) from the streaming ingest hot loop. Parses plain POSIX/ustar
// archives: 512-byte headers, octal sizes, data padded to 512. Returns the
// member count, writing per-member data offset, size, an is-regular-file
// flag, and the BASENAME (what the label map keys on, reference
// ImageNetLoader.scala:71) truncated to name_cap-1.
// Bails with -1 on GNU/pax extension headers (L/K/x/g) — their presence
// would desynchronize member numbering from Python's tarfile, which hides
// them; callers fall back to tarfile. Bails -2 on IO error, -3 if max_n
// is too small, -4 when EOF arrives before the zero end-of-archive block
// (an archive truncated AT a member boundary looks complete to a naive
// walk — and to Python's tarfile, which iterates the partial archive
// silently; requiring the terminator makes this the one place that
// detects it).
#include <cstdio>

extern "C" long jp_tar_index(const char* path, long max_n, long* offsets,
                             long* sizes, unsigned char* isfile, char* names,
                             long name_cap) {
  FILE* f = fopen(path, "rb");
  if (!f) return -2;
  long n = 0;
  unsigned char hdr[512];
  long pos = 0;
  bool saw_end = false;
  while (fread(hdr, 1, 512, f) == 512) {
    pos += 512;
    // end-of-archive: a zero block
    bool all_zero = true;
    for (int i = 0; i < 512 && all_zero; ++i) all_zero = hdr[i] == 0;
    if (all_zero) { saw_end = true; break; }
    char type = char(hdr[156]);
    if (type == 'L' || type == 'K' || type == 'x' || type == 'g') {
      fclose(f);
      return -1;  // extension headers: numbering would diverge
    }
    // size: octal at 124 (12 bytes); base-256 (high bit) unsupported
    if (hdr[124] & 0x80) { fclose(f); return -1; }
    long size = 0;
    for (int i = 124; i < 136; ++i) {
      unsigned char c = hdr[i];
      if (c == 0 || c == ' ') continue;
      if (c < '0' || c > '7') { fclose(f); return -2; }
      size = size * 8 + (c - '0');
    }
    if (n >= max_n) { fclose(f); return -3; }
    offsets[n] = pos;
    sizes[n] = size;
    // regular file: '0' or NUL typeflag
    isfile[n] = (type == '0' || type == 0) ? 1 : 0;
    // basename of name[0:100] (ustar prefix only affects directories we
    // don't emit; basename is unchanged by it)
    char full[101];
    for (int i = 0; i < 100; ++i) full[i] = char(hdr[i]);
    full[100] = 0;
    const char* base = full;
    for (const char* p = full; *p; ++p)
      if (*p == '/') base = p + 1;
    long j = 0;
    for (; base[j] && j < name_cap - 1; ++j) names[n * name_cap + j] = base[j];
    names[n * name_cap + j] = 0;
    ++n;
    long padded = (size + 511) & ~511L;
    if (fseek(f, padded, SEEK_CUR) != 0) { fclose(f); return -2; }
    pos += padded;
  }
  fclose(f);
  return saw_end ? n : -4;
}
