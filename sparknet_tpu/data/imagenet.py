"""ImageNet-scale sharded-tar ingest.

Parity with reference `loaders/ImageNetLoader.scala` + `ScaleAndConvert.scala`:
a dataset is a set of tar shards (each holding JPEGs) plus a
`train.txt`-style "filename label" map; workers stream their shards, decode +
force-resize each JPEG to a fixed size, and emit (CHW float32, label).

Differences by design:
  - shard assignment is by host (`host_shards`): host i of k takes shards
    i::k — the mesh-native replacement for one-Spark-partition-per-tar.
  - the reference's corrupt-image infinite loop (tar advance only on decode
    success, ImageNetLoader.scala:82-85) is fixed: every entry always
    advances; failures are counted and skipped (`skipped` counter).
  - decode backend: the native C++ data plane (`sparknet_tpu.data.jpeg_plane`)
    when built, else PIL. Both produce identical CHW uint8 arrays.
"""
from __future__ import annotations

import io
import os
import tarfile
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np


def load_label_map(path: str) -> Dict[str, int]:
    """Parse 'filename label' lines (reference getLabels, lines 44-57).
    Accepts a local path, a gs:// url, or an s3:// url (the reference read
    its label file from S3 the same way, `ImageNetLoader.scala:44-57`)."""
    from .gcs import gs_read, is_gs_path
    from .s3 import is_s3_path, s3_read
    text = (gs_read(path).decode() if is_gs_path(path)
            else s3_read(path).decode() if is_s3_path(path)
            else open(path).read())
    out: Dict[str, int] = {}
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln:
            continue
        name, _, label = ln.rpartition(" ")
        out[name] = int(label)
    return out


def list_shards(root: str, prefix: str = "") -> List[str]:
    """All .tar shard paths under root matching prefix, sorted. gs:// and
    s3:// roots list the bucket natively (HTTP, no FUSE, no SDK — the
    reference listed its S3 bucket per run, `ImageNetLoader.scala:28-41`)."""
    from .gcs import gs_list_shards, is_gs_path
    from .s3 import is_s3_path, s3_list_shards
    if is_gs_path(root):
        return gs_list_shards(root, prefix)
    if is_s3_path(root):
        return s3_list_shards(root, prefix)
    shards = sorted(
        os.path.join(root, f) for f in os.listdir(root)
        if f.startswith(prefix) and f.endswith(".tar"))
    if not shards:
        raise FileNotFoundError(f"no .tar shards under {root!r} "
                                f"matching prefix {prefix!r}")
    return shards


def path_size(path: str, fresh: bool = False) -> int:
    """Byte size of a local file or gs://|s3:// object (shard-weight
    estimates and corpus identity use sizes; bucket sizes come from the
    listing metadata, cached — no extra round trip per shard).
    `fresh=True` bypasses the bucket caches with one metadata request."""
    from .gcs import gs_size, is_gs_path
    from .s3 import is_s3_path, s3_size
    if is_gs_path(path):
        return gs_size(path, fresh=fresh)
    if is_s3_path(path):
        return s3_size(path, fresh=fresh)
    return os.path.getsize(path)


def path_stat(path: str, fresh: bool = False) -> Tuple[int, Optional[str]]:
    """(size, freshness token) — generation for gs://, ETag for s3://,
    None for local files. Both ride the SAME metadata request the
    size-only probe already made, and together they catch what size alone
    cannot: an EQUAL-size replacement of a bucket object (which would
    otherwise be carved at stale member offsets into garbage)."""
    from .gcs import gs_stat, is_gs_path
    from .s3 import is_s3_path, s3_stat
    if is_gs_path(path):
        return gs_stat(path, fresh=fresh)
    if is_s3_path(path):
        return s3_stat(path, fresh=fresh)
    return os.path.getsize(path), None


def _check_tar_terminator(path: str) -> None:
    """Raise TruncatedTarError when a LOCAL tar lacks its zero
    end-of-archive blocks — a shard truncated exactly at a member boundary
    otherwise looks complete to tarfile and trains on partial data. Best
    effort (a member whose data ends in >=1 KiB of zeros could mask a
    missing terminator), which still catches the realistic interrupted-
    copy case the silent path would swallow."""
    from .jpeg_plane import TruncatedTarError
    size = os.path.getsize(path)
    if size < 1024 or size % 512:
        raise TruncatedTarError(f"tar {path!r}: size {size} is not a "
                                f"whole number of 512-byte blocks")
    with open(path, "rb") as f:
        f.seek(size - 1024)
        if f.read(1024).strip(b"\0"):
            raise TruncatedTarError(
                f"tar {path!r} ended without the zero end-of-archive "
                f"block — truncated at a member boundary?")


def _open_tar(path: str) -> tarfile.TarFile:
    """Local shards open seekably; gs://|s3:// shards open as ONE streamed
    ranged GET (`r|` mode) with transparent reconnect-resume — the
    per-task streamed GetObject of the reference
    (`ImageNetLoader.scala:62-63`). Entry-skip on a COLD resume reads
    through the stream (tar offsets of entry N are unknown without an
    index), costing one partial shard download once per restart; once a
    full pass has captured the member index (r5,
    `ShardedTarLoader._bucket_indices`), later epochs and warm resumes
    carve members by (offset, size) and open AT the target byte."""
    from .gcs import gs_open_stream, is_gs_path
    from .s3 import is_s3_path, s3_open_stream
    if is_gs_path(path):
        return tarfile.open(fileobj=gs_open_stream(path), mode="r|*")
    if is_s3_path(path):
        return tarfile.open(fileobj=s3_open_stream(path), mode="r|*")
    return tarfile.open(path, "r")


def host_shards(shards: Sequence[str], host_id: int, host_count: int) -> List[str]:
    return list(shards[host_id::host_count])


def _decode_pil(data: bytes, height: int, width: int) -> np.ndarray:
    from PIL import Image
    img = Image.open(io.BytesIO(data)).convert("RGB")
    img = img.resize((width, height), Image.BILINEAR)  # force-resize
    return np.asarray(img, dtype=np.uint8).transpose(2, 0, 1)  # HWC->CHW


def get_decoder():
    """Prefer the native C++ plane; fall back to PIL."""
    try:
        from . import jpeg_plane
        if jpeg_plane.available():
            return jpeg_plane.decode_resize_chw
    except ImportError:
        pass
    return _decode_pil


class ShardedTarLoader:
    """Streams (image CHW uint8, label) pairs from tar shards.

    Reference call shape: `loader.apply(sc, prefix, labelFile, h, w)`
    -> RDD[(Array[Byte], Int)] (ImageNetLoader.scala:93-101).
    """

    def __init__(self, shard_paths: Sequence[str], label_map: Dict[str, int],
                 height: int = 256, width: int = 256):
        self.shard_paths = list(shard_paths)
        self.label_map = label_map
        self.height = height
        self.width = width
        self.skipped = 0  # corrupt/unlabeled entries (counted, never looped on)
        self._tar_indices: Dict[str, object] = {}  # path -> C member index
        #: bucket url -> [(offset_data, size, isfile, basename)] captured
        #: during the first full tarfile walk; epoch >= 2 carves members
        #: from the ranged stream directly (no per-member header parsing)
        self._bucket_indices: Dict[str, list] = {}
        #: cumulative seconds inside decode calls (the OpenMP-parallel
        #: stage) — wall and calling-thread CPU. Pipeline benchmarks
        #: subtract the CPU figure from the producer's CPU time to get the
        #: "serial residue" (tar read + buffer write + glue); CPU clocks
        #: stay honest under GIL/core contention where wall clocks inflate
        self.decode_s = 0.0
        self.decode_cpu_s = 0.0
        self._decode = get_decoder()
        self._decode_batch = None
        try:
            from . import jpeg_plane
            if jpeg_plane.available():
                self._decode_batch = jpeg_plane.decode_resize_chw_batch
        except ImportError:
            pass

    #: entries buffered per parallel-decode call (native OpenMP batch path)
    DECODE_CHUNK = 128

    def __iter__(self) -> Iterator[Tuple[np.ndarray, int]]:
        for img, label, _pos in self.iter_with_pos():
            yield img, label

    def iter_with_pos(self, start: Tuple[int, int] = (0, 0)
                      ) -> Iterator[Tuple[np.ndarray, int, Tuple[int, int]]]:
        """Yield (img CHW uint8, label, cursor) where cursor =
        (shard_index, tar entries consumed in that shard) AFTER the entry
        that produced the example. Seeking with `start` skips that many raw
        tar entries WITHOUT decoding — the resume path for streaming runs
        (the reference restarted its RDD from scratch; SURVEY §5.3)."""
        start_shard, start_entry = start
        chunk: List[Tuple[bytes, int, Tuple[int, int]]] = []
        for si in range(start_shard, len(self.shard_paths)):
            skip = start_entry if si == start_shard else 0
            for item in self._shard_entries(si, skip):
                chunk.append(item)
                if len(chunk) >= self.DECODE_CHUNK:
                    yield from self._decode_chunk(chunk)
                    chunk = []
        if chunk:
            yield from self._decode_chunk(chunk)

    def _shard_entries(self, si: int, skip: int
                       ) -> Iterator[Tuple[bytes, int, Tuple[int, int]]]:
        """(jpeg bytes, label, cursor) for labeled file members of shard si
        after the first `skip` members. Local shards use the C member index
        + pread (both GIL-free — the Python tarfile walk was ~0.05 ms/image
        of GIL-held serial residue per reader, PERF.md input pipeline);
        bucket streams and extension-header archives use tarfile. Member
        numbering is identical on both paths (cursor compatibility)."""
        path = self.shard_paths[si]
        idx = self._tar_index(path)
        if idx is not None:
            offsets, sizes, isfile, names = idx
            with open(path, "rb") as f:
                fd = f.fileno()
                for e in range(skip, len(offsets)):
                    if not isfile[e]:
                        continue
                    label = self.label_map.get(names[e])
                    if label is None:
                        self.skipped += 1
                        continue
                    data = os.pread(fd, sizes[e], offsets[e])
                    if len(data) != sizes[e]:
                        # shard truncated since indexing: fail loudly, a
                        # short JPEG would be miscounted as routine decode
                        # corruption and silently skipped
                        raise OSError(
                            f"{path}: short read at member {e + 1} "
                            f"({len(data)} of {sizes[e]} bytes) — shard "
                            f"truncated?")
                    yield data, label, (si, e + 1)
            return
        is_bucket = path.startswith(("gs://", "s3://"))
        if is_bucket:
            cached = self._bucket_indices.get(path)
            if cached is not None:
                bidx, stat_at_capture = cached
                # a replaced object makes the recorded offsets garbage:
                # one fresh metadata request per shard per epoch compares
                # (size, generation|ETag) — the token catches even an
                # EQUAL-size replacement, which size alone cannot — and
                # falls back to the tarfile walk (which re-captures).
                if path_stat(path, fresh=True) != stat_at_capture:
                    del self._bucket_indices[path]
                else:
                    # epoch >= 2 (or post-resume with a warm index):
                    # carve members straight out of ONE ranged stream by
                    # recorded (offset, size) — no tarfile header
                    # parsing, and the stream OPENS at the first needed
                    # byte, so a mid-shard resume skips the prefix
                    # download entirely
                    yield from self._bucket_entries_indexed(path, si,
                                                            skip, bidx)
                    return
        else:
            # tarfile iterates a boundary-truncated archive SILENTLY; the
            # C indexer catches it via the missing terminator, and this
            # closes the same hole on the fallback path (no native plane,
            # extension-header archives). Remote objects are served
            # consistently by the store, so a truncated UPLOAD is the
            # uploader's bug — each ranged read is still length-checked.
            _check_tar_terminator(path)
        # freshness token captured BEFORE the walk: if the object is
        # replaced WHILE we stream it, the index holds old-byte offsets —
        # pairing it with the post-walk stat would make every later
        # epoch's staleness compare pass and carve garbage forever;
        # pairing it with the pre-walk stat makes the next epoch's fresh
        # stat differ and forces a re-walk
        stat_at_walk = path_stat(path, fresh=True) if is_bucket else None
        index = []  # (offset_data, size, isfile, basename) per member
        with _open_tar(path) as tar:
            entry = 0
            for member in tar:  # ALWAYS advances (bug fix vs reference)
                entry += 1
                if is_bucket:
                    index.append((member.offset_data, member.size,
                                  member.isfile(),
                                  os.path.basename(member.name)))
                if entry <= skip or not member.isfile():
                    continue
                name = os.path.basename(member.name)
                label = self.label_map.get(name)
                if label is None:
                    self.skipped += 1
                    continue
                yield tar.extractfile(member).read(), label, (si, entry)
        if is_bucket:
            # cache any walk that REACHED end-of-archive (this code runs
            # only when the member loop exhausted the tar): even a skip>0
            # resume continuation iterated the stream from byte 0 and
            # recorded every member, so its index is complete too — the
            # old `skip == 0` gate made a resumed shard pay one extra
            # full header-parsing walk for nothing. The PRE-walk
            # (size, token) stat rides along for the staleness check.
            self._bucket_indices[path] = (index, stat_at_walk)

    #: forward gaps below this are read-and-discarded on the carve path;
    #: larger ones reopen the ranged stream at the target offset
    BUCKET_REOPEN_GAP = 1 << 20

    def _bucket_entries_indexed(self, path: str, si: int, skip: int, index
                                ) -> Iterator[Tuple[bytes, int,
                                                    Tuple[int, int]]]:
        """Indexed bucket read: one sequential ranged GET per epoch (like
        the tarfile path) but members sliced by recorded (offset, size) —
        the Python tar-header walk the C indexer removed for local shards
        (PERF.md input pipeline) is gone here too. Short reads fail
        loudly: a shortened member must not decode as routine corruption."""
        from .gcs import gs_open_stream, is_gs_path
        from .s3 import s3_open_stream
        opener = gs_open_stream if is_gs_path(path) else s3_open_stream
        stream, pos = None, 0
        try:
            for e in range(skip, len(index)):
                offset, size, isfile, name = index[e]
                if not isfile:
                    continue
                label = self.label_map.get(name)
                if label is None:
                    self.skipped += 1
                    continue
                if stream is None or offset - pos > self.BUCKET_REOPEN_GAP:
                    if stream is not None:
                        stream.close()
                    stream, pos = opener(path, start=offset), offset
                while pos < offset:  # discard inter-member gap
                    chunk = stream.read(min(offset - pos, 1 << 16))
                    if not chunk:
                        raise IOError(f"{path}: EOF in gap before member "
                                      f"{e + 1} at byte {pos}")
                    pos += len(chunk)
                parts = []
                need = size
                while need:
                    chunk = stream.read(need)
                    if not chunk:
                        raise IOError(
                            f"{path}: short read at member {e + 1} "
                            f"({size - need} of {size} bytes) — object "
                            f"shorter than its index?")
                    parts.append(chunk)
                    need -= len(chunk)
                pos = offset + size
                yield b"".join(parts), label, (si, e + 1)
        finally:
            if stream is not None:
                stream.close()

    def _tar_index(self, path: str):
        """Cached C member index for a LOCAL shard; None -> tarfile path
        (bucket urls, native plane unavailable, or extension headers)."""
        if path in self._tar_indices:
            return self._tar_indices[path]
        idx = None
        if not path.startswith(("gs://", "s3://")):
            try:
                from . import jpeg_plane
                if jpeg_plane.supports_tar_index():
                    idx = jpeg_plane.tar_index(path)
            except ImportError:
                idx = None
            except jpeg_plane.TruncatedTarError:
                # do NOT fall back: tarfile iterates a boundary-truncated
                # archive silently, which would train on partial data
                raise
            except OSError:
                idx = None
        self._tar_indices[path] = idx
        return idx

    def _decode_chunk(self, chunk: List[Tuple[bytes, int, Tuple[int, int]]]
                      ) -> Iterator[Tuple[np.ndarray, int, Tuple[int, int]]]:
        """Decode a buffered chunk — multi-core via the native OpenMP batch
        kernel when available, else per-image fallback."""
        import time
        if self._decode_batch is not None:
            t0, c0 = time.perf_counter(), time.thread_time()
            images, ok = self._decode_batch([c[0] for c in chunk],
                                            self.height, self.width)
            self.decode_s += time.perf_counter() - t0
            self.decode_cpu_s += time.thread_time() - c0
            for i, (_, label, pos) in enumerate(chunk):
                if ok[i]:
                    yield images[i], label, pos
                else:
                    self.skipped += 1  # corrupt image: skip, don't loop
            return
        for data, label, pos in chunk:
            try:
                t0, c0 = time.perf_counter(), time.thread_time()
                img = self._decode(data, self.height, self.width)
                self.decode_s += time.perf_counter() - t0
                self.decode_cpu_s += time.thread_time() - c0
                yield img, label, pos
            except Exception:
                self.skipped += 1


    def load_all(self, limit: Optional[int] = None
                 ) -> Tuple[np.ndarray, np.ndarray]:
        """Materialize examples (use for shard-sized chunks). `limit` stops
        DECODING at that many examples — a true RAM cap, not a post-hoc
        slice of a fully decoded corpus."""
        images, labels = [], []
        for img, label in self:
            images.append(img)
            labels.append(label)
            if limit is not None and len(images) >= limit:
                break
        if not images:
            raise ValueError(f"no decodable labeled images in "
                             f"{self.shard_paths}")
        return np.stack(images), np.asarray(labels, np.int32)

    def batches(self, batch_size: int, *, drop_last: bool = True
                ) -> Iterator[Dict[str, np.ndarray]]:
        """Streaming batch iterator: {'data': (B,C,H,W) uint8, 'label': (B,1)}."""
        buf_img: List[np.ndarray] = []
        buf_lbl: List[int] = []
        for img, label in self:
            buf_img.append(img)
            buf_lbl.append(label)
            if len(buf_img) == batch_size:
                yield {"data": np.stack(buf_img),
                       "label": np.asarray(buf_lbl, np.int32)[:, None]}
                buf_img, buf_lbl = [], []
        if buf_img and not drop_last:
            yield {"data": np.stack(buf_img),
                   "label": np.asarray(buf_lbl, np.int32)[:, None]}


def write_synthetic_shards(root: str, n_shards: int = 2, per_shard: int = 8,
                           n_classes: int = 10, size: int = 64,
                           seed: int = 0, corrupt_every: Optional[int] = None
                           ) -> str:
    """Build tiny real-JPEG tar shards + label file (for tests).
    Returns the label file path. corrupt_every=k injects a truncated JPEG at
    every k-th entry (exercising the skip path)."""
    from PIL import Image
    os.makedirs(root, exist_ok=True)
    r = np.random.default_rng(seed)
    label_lines = []
    count = 0
    for s in range(n_shards):
        tar_path = os.path.join(root, f"train.{s:04d}.tar")
        with tarfile.open(tar_path, "w") as tar:
            for i in range(per_shard):
                name = f"img_{s}_{i}.JPEG"
                arr = r.integers(0, 256, (size, size, 3), dtype=np.uint8)
                buf = io.BytesIO()
                Image.fromarray(arr).save(buf, format="JPEG")
                data = buf.getvalue()
                count += 1
                if corrupt_every and count % corrupt_every == 0:
                    data = data[: len(data) // 2]  # truncated -> decode error
                info = tarfile.TarInfo(name=name)
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
                label_lines.append(f"{name} {int(r.integers(0, n_classes))}")
    label_path = os.path.join(root, "train.txt")
    with open(label_path, "w") as f:
        f.write("\n".join(label_lines) + "\n")
    return label_path
