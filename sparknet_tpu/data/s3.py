"""Native `s3://` object-store ingest — the reference's actual data plane.

The reference streamed ImageNet straight from S3, one `AmazonS3Client.
getObject` per tar (`loaders/ImageNetLoader.scala:62-63`; upload side
`scripts/put_imagenet_on_s3.py`). This module gives the loaders the same
capability with no SDK: listing (ListObjectsV2), whole-object fetch, and
ranged streams with reconnect-resume, over plain HTTPS with AWS Signature
Version 4 computed from the stdlib (hmac/hashlib — SigV4 is just a chain
of HMAC-SHA256s).

Credentials: AWS_ACCESS_KEY_ID / AWS_SECRET_ACCESS_KEY (+ optional
AWS_SESSION_TOKEN) from the environment — the same channel the reference
used (its README.md:46-56 exported the keys). Anonymous requests (public
buckets) are made when no keys are set. Region from AWS_REGION /
AWS_DEFAULT_REGION, else us-east-1.

`AWS_ENDPOINT_URL` (the conventional S3-emulator knob) redirects all
traffic — tests run a local fake server through the full path, signature
included. Retry/resume semantics are shared with the GCS client
(`gcs.GcsRangeStream` drives the reconnects): a dropped connection mid-tar
resumes with `Range: bytes=<pos>-`; a truncated body is detected against
Content-Length and resumed, never treated as EOF.
"""
from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.parse
import xml.etree.ElementTree as ET
from typing import List, Optional, Tuple

from . import gcs as _gcs  # shared retry/range-stream machinery

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _canon_query(q: dict) -> str:
    """SigV4 canonical query string: sorted keys, %20-quoted values
    (urlencode's '+' form would sign a different string than AWS
    canonicalizes)."""
    return "&".join(
        f"{urllib.parse.quote(k, safe='')}="
        f"{urllib.parse.quote(v, safe='')}"
        for k, v in sorted(q.items()))


def parse_s3_url(url: str) -> Tuple[str, str]:
    """'s3://bucket/some/prefix' -> ('bucket', 'some/prefix')."""
    if not url.startswith("s3://"):
        raise ValueError(f"not an s3:// url: {url!r}")
    rest = url[len("s3://"):]
    bucket, _, name = rest.partition("/")
    if not bucket:
        raise ValueError(f"s3:// url missing bucket: {url!r}")
    return bucket, name


def is_s3_path(path: str) -> bool:
    return isinstance(path, str) and path.startswith("s3://")


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


class S3Client:
    """Minimal SigV4-signing S3 client over the shared urllib machinery."""

    def __init__(self, endpoint: Optional[str] = None,
                 region: Optional[str] = None, timeout: float = 60.0):
        self.endpoint = (endpoint or os.environ.get("AWS_ENDPOINT_URL")
                         or "").rstrip("/")
        self.region = (region or os.environ.get("AWS_REGION")
                       or os.environ.get("AWS_DEFAULT_REGION")
                       or "us-east-1")
        self.timeout = timeout
        self.access_key = os.environ.get("AWS_ACCESS_KEY_ID")
        self.secret_key = os.environ.get("AWS_SECRET_ACCESS_KEY")
        self.session_token = os.environ.get("AWS_SESSION_TOKEN")

    # -- SigV4 ---------------------------------------------------------------

    def _sign(self, method: str, host: str, path: str, query: str,
              headers: dict, payload_hash: str = _EMPTY_SHA256) -> dict:
        """Add Authorization (+ x-amz-*) headers. SigV4 per the AWS spec:
        canonical request -> string-to-sign -> HMAC chain (date, region,
        service, 'aws4_request'). `payload_hash` is sha256(body) for PUTs
        (the empty-body hash for GETs)."""
        if not self.access_key or not self.secret_key:
            return headers  # anonymous (public bucket)
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        headers = dict(headers)
        headers["x-amz-date"] = amz_date
        headers["x-amz-content-sha256"] = payload_hash
        if self.session_token:
            headers["x-amz-security-token"] = self.session_token
        all_h = {**headers, "host": host}
        signed = ";".join(sorted(k.lower() for k in all_h))
        canonical = "\n".join([
            method,
            urllib.parse.quote(path, safe="/-_.~"),
            query,
            "".join(f"{k}:{all_h[k2].strip()}\n" for k, k2 in
                    sorted((k.lower(), k) for k in all_h)),
            signed,
            payload_hash,
        ])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(canonical.encode()).hexdigest()])
        key = _hmac(_hmac(_hmac(_hmac(
            ("AWS4" + self.secret_key).encode(), datestamp),
            self.region), "s3"), "aws4_request")
        sig = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={sig}")
        return headers

    def _url_parts(self, bucket: str, key: str = ""
                   ) -> Tuple[str, str, str]:
        """(base_url, host, path). Custom endpoints use path-style
        addressing (emulators rarely speak virtual-hosted); AWS proper
        uses virtual-hosted-style."""
        if self.endpoint:
            host = urllib.parse.urlparse(self.endpoint).netloc
            path = f"/{bucket}" + (f"/{key}" if key else "")
            return self.endpoint, host, path
        host = f"{bucket}.s3.{self.region}.amazonaws.com"
        return f"https://{host}", host, ("/" + key if key else "/")

    def _request(self, bucket: str, key: str, query: str = "",
                 headers: Optional[dict] = None, method: str = "GET",
                 data: Optional[bytes] = None):
        base, host, path = self._url_parts(bucket, key)
        payload = (hashlib.sha256(data).hexdigest() if data is not None
                   else _EMPTY_SHA256)
        url = base + urllib.parse.quote(path, safe="/-_.~")
        if query:
            url += "?" + query
        # sign PER ATTEMPT (headers_fn): every multipart part PUT,
        # CompleteMultipartUpload POST, and ranged-GET reconnect shares
        # the transport's full-jitter backoff (Retry-After honored on
        # 429 and S3's `503 SlowDown`), and each retry carries a fresh
        # x-amz-date — a retry that slept out a long Retry-After floor
        # must not replay a signature into the SigV4 clock-skew window
        return _gcs.http_get_with_retry(
            url, None, self.timeout, method=method, data=data,
            headers_fn=lambda: self._sign(method, host, path, query,
                                          dict(headers or {}),
                                          payload_hash=payload))

    # -- API -----------------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = ""
                     ) -> List[Tuple[str, int]]:
        """[(key, size), ...] under prefix (ListObjectsV2, paginated)."""
        return [(k, s) for k, s, _ in self.list_objects_meta(bucket, prefix)]

    def list_objects_meta(self, bucket: str, prefix: str = ""
                          ) -> List[Tuple[str, int, Optional[str]]]:
        """[(key, size, etag), ...] under prefix (ListObjectsV2,
        paginated). The ETag rides the listing XML AWS already returns —
        the freshness token for warm member indexes, parallel to the GCS
        generation."""
        out: List[Tuple[str, int, Optional[str]]] = []
        token = None
        while True:
            q = {"list-type": "2", "prefix": prefix}
            if token:
                q["continuation-token"] = token
            with self._request(bucket, "", query=_canon_query(q)) as r:
                root = ET.fromstring(r.read())
            ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
            for c in root.findall(f"{ns}Contents"):
                et = c.find(f"{ns}ETag")
                out.append((c.find(f"{ns}Key").text,
                            int(c.find(f"{ns}Size").text or 0),
                            et.text.strip('"') if et is not None
                            and et.text else None))
            trunc = root.find(f"{ns}IsTruncated")
            if trunc is None or trunc.text != "true":
                break
            nxt = root.find(f"{ns}NextContinuationToken")
            token = nxt.text if nxt is not None else None
            if not token:
                break
        return sorted(out)

    def read_object(self, bucket: str, key: str) -> bytes:
        with self._request(bucket, key) as r:
            return r.read()

    def open_stream(self, bucket: str, key: str,
                    start: int = 0) -> "_S3RangeStream":
        return _S3RangeStream(self, bucket, key, start)


class _S3RangeStream(_gcs.GcsRangeStream):
    """GcsRangeStream with the connect step swapped for a signed S3 GET —
    inherits the reconnect/resume/truncation logic unchanged."""

    def __init__(self, client: S3Client, bucket: str, key: str,
                 start: int = 0):
        super().__init__(client=None, bucket=bucket, name=key, start=start)
        self._s3 = client

    def _connect(self):
        import io
        import urllib.error
        headers = {}
        if self._pos:
            headers["Range"] = f"bytes={self._pos}-"
        try:
            self._resp = self._s3._request(self._bucket, self._name,
                                           headers=headers)
        except urllib.error.HTTPError as e:
            if e.code == 416:
                self._resp = io.BytesIO(b"")
                self._eof = True
                return
            raise
        if self._pos and getattr(self._resp, "status", 206) != 206:
            raise IOError(
                f"s3: server ignored Range bytes={self._pos}- for "
                f"s3://{self._bucket}/{self._name}")
        cl = self._resp.headers.get("Content-Length")
        self._end = self._pos + int(cl) if cl is not None else None


#: s3:// url -> byte size (filled by listings, like gcs._SIZE_CACHE)
_SIZE_CACHE: dict = {}
#: s3:// url -> (size, etag) — the freshness token pair (gcs._STAT_CACHE)
_STAT_CACHE: dict = {}
_CLIENTS: dict = {}


def _shared_client() -> S3Client:
    ep = os.environ.get("AWS_ENDPOINT_URL") or "aws"
    client = _CLIENTS.get(ep)
    if client is None:
        client = _CLIENTS[ep] = S3Client()
    return client


def s3_list_shards(root: str, prefix: str = "") -> List[str]:
    """s3:// analogue of `imagenet.list_shards`."""
    bucket, base = parse_s3_url(root)
    if base and not base.endswith("/"):
        base += "/"
    out = []
    for key, size, etag in _shared_client().list_objects_meta(bucket, base):
        rel = key[len(base):]
        if "/" in rel:
            continue
        if rel.startswith(prefix) and rel.endswith(".tar"):
            url = f"s3://{bucket}/{key}"
            _SIZE_CACHE[url] = size
            _STAT_CACHE[url] = (size, etag)
            out.append(url)
    if not out:
        raise FileNotFoundError(f"no .tar shards under {root!r} "
                                f"matching prefix {prefix!r}")
    return sorted(out)


def s3_list_urls(root: str) -> List[str]:
    """ALL object urls under an s3:// prefix (recursive, sorted; empty
    list when nothing matches) — the checkpoint store's directory listing."""
    bucket, base = parse_s3_url(root)
    if base and not base.endswith("/"):
        base += "/"
    out = []
    for key, size, etag in _shared_client().list_objects_meta(bucket, base):
        url = f"s3://{bucket}/{key}"
        _SIZE_CACHE[url] = size
        _STAT_CACHE[url] = (size, etag)
        out.append(url)
    return sorted(out)


def s3_read(url: str) -> bytes:
    bucket, key = parse_s3_url(url)
    return _shared_client().read_object(bucket, key)


def s3_open_stream(url: str, start: int = 0) -> _S3RangeStream:
    bucket, key = parse_s3_url(url)
    return _shared_client().open_stream(bucket, key, start)


def s3_write(url: str, data: bytes) -> None:
    """Upload bytes to an s3:// object (SigV4-signed PUT with the payload
    hash) — the reference sharder's upload side
    (`scripts/put_imagenet_on_s3.py`). Content-Type is set (and signed)
    explicitly: urllib would otherwise inject form-urlencoded, which S3
    stores as the object's type."""
    bucket, key = parse_s3_url(url)
    with _shared_client()._request(
            bucket, key, method="PUT", data=data,
            headers={"Content-Type": "application/octet-stream"}) as r:
        r.read()
    _SIZE_CACHE[url] = len(data)
    _STAT_CACHE.pop(url, None)


def s3_size(url: str, fresh: bool = False) -> int:
    if not fresh and url in _SIZE_CACHE:
        return _SIZE_CACHE[url]
    return s3_stat(url, fresh=fresh)[0]


def s3_stat(url: str, fresh: bool = False) -> Tuple[int, Optional[str]]:
    """(size, etag) from one `bytes=0-0` ranged GET (the same request the
    size-only probe made — the ETag header rides along for free). The ETag
    is the freshness token: an equal-size replacement changes it."""
    import urllib.error
    if not fresh and url in _STAT_CACHE:
        return _STAT_CACHE[url]
    bucket, key = parse_s3_url(url)
    client = _shared_client()
    try:
        with client._request(bucket, key,
                             headers={"Range": "bytes=0-0"}) as r:
            cr = r.headers.get("Content-Range", "")
            size = (int(cr.rpartition("/")[2]) if "/" in cr
                    else int(r.headers.get("Content-Length", 0)))
            etag = (r.headers.get("ETag") or "").strip('"') or None
    except urllib.error.HTTPError as e:
        # a ZERO-byte object cannot satisfy bytes=0-0: AWS answers 416
        # with the total in Content-Range ("bytes */0")
        if e.code != 416:
            raise
        cr = e.headers.get("Content-Range", "")
        size = int(cr.rpartition("/")[2]) if "/" in cr else 0
        etag = (e.headers.get("ETag") or "").strip('"') or None
    _SIZE_CACHE[url] = size
    _STAT_CACHE[url] = (size, etag)
    return size, etag


def s3_delete(url: str, missing_ok: bool = True) -> None:
    """Signed DELETE; 404 is success when `missing_ok`."""
    import urllib.error
    bucket, key = parse_s3_url(url)
    try:
        with _shared_client()._request(bucket, key, method="DELETE") as r:
            r.read()
    except urllib.error.HTTPError as e:
        if not (missing_ok and e.code == 404):
            raise
    _SIZE_CACHE.pop(url, None)
    _STAT_CACHE.pop(url, None)


#: multipart part size — AWS requires >= 5 MiB per non-final part; 8 MiB
#: matches the GCS chunk for comparable retry re-send cost
S3_UPLOAD_PART = 8 << 20
S3_UPLOAD_PARALLEL = 4


def s3_write_large(url: str, data, *,
                   parallel: Optional[int] = None,
                   part_bytes: Optional[int] = None) -> None:
    """Bulk upload of bytes-like `data` (bytes, or a memoryview that is
    only copied one part at a time) via S3 multipart: initiate ->
    parallel signed UploadPart
    PUTs -> CompleteMultipartUpload. The object appears atomically at
    complete time — a writer killed mid-upload leaves only an invisible
    multipart session (aborted on failure when we still can), never a torn
    object. Payloads of one part or parallel=1 fall back to the plain
    signed PUT (itself atomic)."""
    if parallel is None:
        parallel = S3_UPLOAD_PARALLEL
    if part_bytes is None:
        part_bytes = S3_UPLOAD_PART  # read at call time: patchable
    if parallel <= 1 or len(data) <= part_bytes:
        s3_write(url, bytes(data) if isinstance(data, memoryview)
                 else data)
        return
    from concurrent.futures import ThreadPoolExecutor
    bucket, key = parse_s3_url(url)
    client = _shared_client()
    with client._request(bucket, key, query="uploads=",
                         method="POST") as r:
        root = ET.fromstring(r.read())
    ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
    uid_el = root.find(f"{ns}UploadId")
    if uid_el is None or not uid_el.text:
        raise IOError(f"s3: CreateMultipartUpload for {url} returned no "
                      f"UploadId")
    uid = uid_el.text

    bounds = [(i, min(i + part_bytes, len(data)))
              for i in range(0, len(data), part_bytes)]

    def put_part(n_ab):
        n, (a, b) = n_ab
        q = _canon_query({"partNumber": str(n), "uploadId": uid})
        # bytes() per part: `data` may be a zero-copy memoryview; urllib
        # needs real bytes, so copy one bounded part at a time
        with client._request(bucket, key, query=q, method="PUT",
                             data=bytes(data[a:b])) as r:
            r.read()
            return n, (r.headers.get("ETag") or "").strip('"')

    try:
        with ThreadPoolExecutor(min(parallel, len(bounds)),
                                thread_name_prefix="s3-part") as ex:
            etags = sorted(ex.map(put_part, enumerate(bounds, start=1)))
        body = ("<CompleteMultipartUpload>" + "".join(
            f"<Part><PartNumber>{n}</PartNumber>"
            f"<ETag>\"{e}\"</ETag></Part>" for n, e in etags)
            + "</CompleteMultipartUpload>").encode()
        with client._request(bucket, key,
                             query=_canon_query({"uploadId": uid}),
                             method="POST", data=body) as r:
            resp = r.read()
        # AWS can answer CompleteMultipartUpload with HTTP 200 whose BODY
        # is an <Error> document (e.g. InternalError) — a 200 status does
        # not mean the object materialized. Committing meta.json on top
        # of a failed complete would break the commit-marker invariant.
        root2 = ET.fromstring(resp) if resp.strip() else None
        if root2 is None or root2.tag.endswith("Error"):
            raise IOError(
                f"s3: CompleteMultipartUpload for {url} failed in-body: "
                f"{resp[:200]!r}")
    except BaseException:
        try:  # abort so the store reclaims the parts
            with client._request(bucket, key,
                                 query=_canon_query({"uploadId": uid}),
                                 method="DELETE") as r:
                r.read()
        except Exception:
            pass
        raise
    _SIZE_CACHE[url] = len(data)
    _STAT_CACHE.pop(url, None)
