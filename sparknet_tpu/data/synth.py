"""Deterministic synthetic CIFAR-like dataset (r4: offline accuracy proxy).

Real CIFAR-10 is unreachable in this environment (PARITY.md), so the
recipe-scale accuracy evidence runs on a synthetic stand-in with the same
tensor statistics the reference pipeline feeds the net: 3x32x32, raw
[0, 255] pixel scale, mean-image subtraction downstream (reference
`loaders/CifarLoader.scala:60-66`), 10 balanced classes. Class-conditional
and LEARNABLE but not trivial: each class is a smooth random template,
each example a randomly shifted copy + pixel noise, so cifar10_quick must
learn translation-tolerant features, not a lookup table.

Fully deterministic in (seed, index): example i is the same bytes on every
host, every run, every chunk size — the property the parity artifacts and
the numpy-oracle trajectory test rely on.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

N_CLASSES = 10
SHAPE = (3, 32, 32)
_SHIFT = 6        # max |dx|, |dy| translation
_NOISE = 75.0     # pixel noise std
_AMP = 40.0       # template amplitude around mid-gray
# calibration (r4): with shift 6 / noise 75 / amp 40, cifar10_quick reaches
# ~0.5 test accuracy at 500 iters and keeps climbing through the 4000-iter
# recipe — hard enough that the full run is informative, far above the 0.1
# chance floor (the earlier 25/60 setting saturated at 0.99 by iter 100)


def class_templates(seed: int = 0) -> np.ndarray:
    """[10, 3, 32, 32] smooth random templates: 8x8 gaussian fields
    bilinearly upsampled to 32x32, scaled to mid-gray +- _AMP."""
    r = np.random.default_rng((seed, 0xC1A55))
    low = r.standard_normal((N_CLASSES, 3, 8, 8))
    # bilinear 8 -> 32 upsample via separable linear interpolation
    xs = np.linspace(0, 7, 32)
    i0 = np.clip(np.floor(xs).astype(int), 0, 6)
    frac = xs - i0
    up = low[..., i0, :] * (1 - frac)[None, None, :, None] + \
        low[..., i0 + 1, :] * frac[None, None, :, None]
    up = up[..., i0] * (1 - frac) + up[..., i0 + 1] * frac
    return (128.0 + _AMP * up / np.abs(up).max()).astype(np.float32)


def synthetic_cifar(n: int, seed: int = 0, start: int = 0
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Examples [start, start+n): (images [n,3,32,32] float32 in [0,255],
    labels [n] int32). Label of example i is i % 10 (balanced)."""
    tmpl = class_templates(seed)
    pad = np.pad(tmpl, ((0, 0), (0, 0), (_SHIFT, _SHIFT), (_SHIFT, _SHIFT)),
                 mode="edge")
    images = np.empty((n,) + SHAPE, np.float32)
    labels = np.empty((n,), np.int32)
    for j in range(n):
        i = start + j
        r = np.random.default_rng((seed, 1, i))
        c = i % N_CLASSES
        dy, dx = r.integers(-_SHIFT, _SHIFT + 1, 2)
        base = pad[c, :, _SHIFT + dy:_SHIFT + dy + 32,
                   _SHIFT + dx:_SHIFT + dx + 32]
        # NO clipping: clip-saturated pixels create masses of repeated
        # values, whose conv outputs near-tie in max-pool windows — and a
        # near-tie's argmax flips under 1-ulp implementation differences,
        # injecting gradient-routing chaos that swamps trajectory
        # comparisons (measured: conv1 L2 drift 6.6% by iter 10 with
        # clipping, 100x less without). Float pixels are fine: the scale
        # is still CIFAR-like and the mean subtraction downstream centers
        # them either way.
        images[j] = base + _NOISE * r.standard_normal(SHAPE, np.float32)
        labels[j] = c
    return images, labels


def mean_image(seed: int = 0, n: int = 2000) -> np.ndarray:
    """Deterministic mean image over the first n examples (the CifarLoader
    computed the train-set mean; n=2000 is statistically equivalent here
    and keeps artifact generation fast)."""
    images, _ = synthetic_cifar(n, seed=seed)
    return images.mean(axis=0)


# -- ImageNet-shaped corpus (r5: CaffeNet-scale convergence evidence) --------

IMAGENET_SIZE = 256
IMAGENET_CLASSES = 64
_IN_SHIFT = 24     # max |dx|, |dy| translation (vs the 227/256 crop's 29)
_IN_NOISE = 35.0   # pixel noise std (survives JPEG q=90: smooth template
                   # carries the class signal, noise is the nuisance)
_IN_AMP = 45.0     # template amplitude around mid-gray
_IN_BRIGHT = 20.0  # per-image brightness jitter


def imagenet_templates(seed: int = 0,
                       n_classes: int = IMAGENET_CLASSES) -> np.ndarray:
    """[C, 3, 256, 256] smooth random templates: 16x16 gaussian fields
    bilinearly upsampled (same construction as the CIFAR stand-in at 4x
    the spatial detail — enough structure that conv1 11x11/4 features,
    LRN and the grouped tail all see realistic activation ranges)."""
    r = np.random.default_rng((seed, 0x1A6E7))
    low = r.standard_normal((n_classes, 3, 16, 16))
    size = IMAGENET_SIZE
    xs = np.linspace(0, 15, size)
    i0 = np.clip(np.floor(xs).astype(int), 0, 14)
    frac = xs - i0
    up = low[..., i0, :] * (1 - frac)[None, None, :, None] + \
        low[..., i0 + 1, :] * frac[None, None, :, None]
    up = up[..., i0] * (1 - frac) + up[..., i0 + 1] * frac
    return (128.0 + _IN_AMP * up / np.abs(up).max()).astype(np.float32)


def synthetic_imagenet(n: int, seed: int = 0, start: int = 0,
                       n_classes: int = IMAGENET_CLASSES,
                       noise: float = _IN_NOISE, shift: int = _IN_SHIFT):
    """Examples [start, start+n): (images [n, 256, 256, 3] uint8 HWC —
    JPEG-encodable, unlike the float CIFAR stand-in; labels [n] int32,
    balanced i % n_classes). Each example is its class template randomly
    shifted (edge-padded) + brightness jitter + pixel noise, clipped to
    uint8. Deterministic in (seed, index, noise, shift).

    The defaults give an easy corpus (CaffeNet saturates ~100% by iter
    600 — useful for breakout-timing comparisons); `noise=85, shift=48`
    matches the CIFAR stand-in's calibrated mid-difficulty ratios
    (noise/amp ~1.9, shift ~19% of the frame) for studies that need a
    non-saturating asymptote."""
    tmpl = imagenet_templates(seed, n_classes)
    s = int(shift)
    pad = np.pad(tmpl, ((0, 0), (0, 0), (s, s), (s, s)), mode="edge")
    size = IMAGENET_SIZE
    images = np.empty((n, size, size, 3), np.uint8)
    labels = np.empty((n,), np.int32)
    for j in range(n):
        i = start + j
        r = np.random.default_rng((seed, 2, i))
        c = i % n_classes
        dy, dx = r.integers(-s, s + 1, 2)
        base = pad[c, :, s + dy:s + dy + size, s + dx:s + dx + size]
        img = (base + r.uniform(-_IN_BRIGHT, _IN_BRIGHT)
               + noise * r.standard_normal((3, size, size),
                                           np.float32))
        images[j] = np.clip(img, 0, 255).astype(np.uint8).transpose(1, 2, 0)
        labels[j] = c
    return images, labels


def write_synthetic_ilsvrc_tar(path: str, n: int, seed: int = 0,
                               n_classes: int = IMAGENET_CLASSES,
                               quality: int = 90,
                               noise: float = _IN_NOISE,
                               shift: int = _IN_SHIFT) -> None:
    """Write an ILSVRC2012-layout training tar-of-tars (outer tar of
    per-synset `nXXXXXXXX.tar` members, each holding that class's JPEGs)
    from the synthetic corpus — so `scripts/shard_imagenet.py` ingests it
    through EXACTLY the path real ImageNet takes (synset discovery,
    sorted-synset labels, shuffle, re-shard). Synset c is named
    f"n{c:08d}", so sorted order == label order == template index."""
    import io
    import tarfile

    from PIL import Image

    members = {c: io.BytesIO() for c in range(n_classes)}
    inner = {c: tarfile.open(fileobj=members[c], mode="w")
             for c in range(n_classes)}
    chunk = 512
    for s0 in range(0, n, chunk):
        images, labels = synthetic_imagenet(min(chunk, n - s0), seed=seed,
                                            start=s0, n_classes=n_classes,
                                            noise=noise, shift=shift)
        for k in range(len(labels)):
            c = int(labels[k])
            buf = io.BytesIO()
            Image.fromarray(images[k]).save(buf, format="JPEG",
                                            quality=quality)
            data = buf.getvalue()
            info = tarfile.TarInfo(name=f"n{c:08d}_{s0 + k}.JPEG")
            info.size = len(data)
            inner[c].addfile(info, io.BytesIO(data))
    with tarfile.open(path, "w") as outer:
        for c in range(n_classes):
            inner[c].close()
            blob = members[c].getvalue()
            info = tarfile.TarInfo(name=f"n{c:08d}.tar")
            info.size = len(blob)
            outer.addfile(info, io.BytesIO(blob))
