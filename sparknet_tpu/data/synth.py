"""Deterministic synthetic CIFAR-like dataset (r4: offline accuracy proxy).

Real CIFAR-10 is unreachable in this environment (PARITY.md), so the
recipe-scale accuracy evidence runs on a synthetic stand-in with the same
tensor statistics the reference pipeline feeds the net: 3x32x32, raw
[0, 255] pixel scale, mean-image subtraction downstream (reference
`loaders/CifarLoader.scala:60-66`), 10 balanced classes. Class-conditional
and LEARNABLE but not trivial: each class is a smooth random template,
each example a randomly shifted copy + pixel noise, so cifar10_quick must
learn translation-tolerant features, not a lookup table.

Fully deterministic in (seed, index): example i is the same bytes on every
host, every run, every chunk size — the property the parity artifacts and
the numpy-oracle trajectory test rely on.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

N_CLASSES = 10
SHAPE = (3, 32, 32)
_SHIFT = 6        # max |dx|, |dy| translation
_NOISE = 75.0     # pixel noise std
_AMP = 40.0       # template amplitude around mid-gray
# calibration (r4): with shift 6 / noise 75 / amp 40, cifar10_quick reaches
# ~0.5 test accuracy at 500 iters and keeps climbing through the 4000-iter
# recipe — hard enough that the full run is informative, far above the 0.1
# chance floor (the earlier 25/60 setting saturated at 0.99 by iter 100)


def class_templates(seed: int = 0) -> np.ndarray:
    """[10, 3, 32, 32] smooth random templates: 8x8 gaussian fields
    bilinearly upsampled to 32x32, scaled to mid-gray +- _AMP."""
    r = np.random.default_rng((seed, 0xC1A55))
    low = r.standard_normal((N_CLASSES, 3, 8, 8))
    # bilinear 8 -> 32 upsample via separable linear interpolation
    xs = np.linspace(0, 7, 32)
    i0 = np.clip(np.floor(xs).astype(int), 0, 6)
    frac = xs - i0
    up = low[..., i0, :] * (1 - frac)[None, None, :, None] + \
        low[..., i0 + 1, :] * frac[None, None, :, None]
    up = up[..., i0] * (1 - frac) + up[..., i0 + 1] * frac
    return (128.0 + _AMP * up / np.abs(up).max()).astype(np.float32)


def synthetic_cifar(n: int, seed: int = 0, start: int = 0
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Examples [start, start+n): (images [n,3,32,32] float32 in [0,255],
    labels [n] int32). Label of example i is i % 10 (balanced)."""
    tmpl = class_templates(seed)
    pad = np.pad(tmpl, ((0, 0), (0, 0), (_SHIFT, _SHIFT), (_SHIFT, _SHIFT)),
                 mode="edge")
    images = np.empty((n,) + SHAPE, np.float32)
    labels = np.empty((n,), np.int32)
    for j in range(n):
        i = start + j
        r = np.random.default_rng((seed, 1, i))
        c = i % N_CLASSES
        dy, dx = r.integers(-_SHIFT, _SHIFT + 1, 2)
        base = pad[c, :, _SHIFT + dy:_SHIFT + dy + 32,
                   _SHIFT + dx:_SHIFT + dx + 32]
        # NO clipping: clip-saturated pixels create masses of repeated
        # values, whose conv outputs near-tie in max-pool windows — and a
        # near-tie's argmax flips under 1-ulp implementation differences,
        # injecting gradient-routing chaos that swamps trajectory
        # comparisons (measured: conv1 L2 drift 6.6% by iter 10 with
        # clipping, 100x less without). Float pixels are fine: the scale
        # is still CIFAR-like and the mean subtraction downstream centers
        # them either way.
        images[j] = base + _NOISE * r.standard_normal(SHAPE, np.float32)
        labels[j] = c
    return images, labels


def mean_image(seed: int = 0, n: int = 2000) -> np.ndarray:
    """Deterministic mean image over the first n examples (the CifarLoader
    computed the train-set mean; n=2000 is statistically equivalent here
    and keeps artifact generation fast)."""
    images, _ = synthetic_cifar(n, seed=seed)
    return images.mean(axis=0)
