"""Schema-driven preprocessing: the reference's Preprocessor framework
(`libs/Preprocessor.scala`) rebuilt batch-vectorized.

Reference impls being matched:
  - DefaultPreprocessor (lines 22-52): per-cell dtype dispatch -> here a
    schema-driven batch cast (`DefaultPreprocessor.convert_batch`).
  - ImageNetPreprocessor (54-83): mean-image subtraction + random 256->227
    crop as a strided view -> `ImagePreprocessor` (vectorized crops via
    sliding-window views, no copies until the final gather).
  - ImageNetTensorFlowPreprocessor (150-178): adds CHW->HWC transpose for the
    accelerator layout -> `to_nhwc` (TPU wants NHWC too).

Parity notes: crop offsets are uniform-random per image per epoch; the
reference used one random offset per image conversion. No flip augmentation
(the reference has none).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..schema import Schema


def to_nhwc(batch: np.ndarray) -> np.ndarray:
    """NCHW -> NHWC (device layout)."""
    assert batch.ndim == 4, batch.shape
    return np.ascontiguousarray(np.transpose(batch, (0, 2, 3, 1)))


def random_crop_nchw(images: np.ndarray, crop: int,
                     rng: np.random.Generator) -> np.ndarray:
    """Vectorized random spatial crop on an NCHW batch (view-gather, matching
    the reference's subarray-view crop at Preprocessor.scala:75-77)."""
    n, c, h, w = images.shape
    if h == crop and w == crop:
        return images
    assert h >= crop and w >= crop, (images.shape, crop)
    ys = rng.integers(0, h - crop + 1, n)
    xs = rng.integers(0, w - crop + 1, n)
    out = np.empty((n, c, crop, crop), dtype=images.dtype)
    for i in range(n):  # slice-views; copies only into the output buffer
        out[i] = images[i, :, ys[i]:ys[i] + crop, xs[i]:xs[i] + crop]
    return out


def center_crop_nchw(images: np.ndarray, crop: int) -> np.ndarray:
    n, c, h, w = images.shape
    y, x = (h - crop) // 2, (w - crop) // 2
    return images[:, :, y:y + crop, x:x + crop]


class DefaultPreprocessor:
    """Casts raw batch fields to the schema dtypes (reference lines 22-52:
    Float/Double/Int/Long/Binary -> float32 NDArray)."""

    def __init__(self, schema: Schema):
        self.schema = schema

    def convert_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out = {}
        for f in self.schema.fields:
            arr = np.asarray(batch[f.name]).astype(f.dtype, copy=False)
            out[f.name] = arr.reshape((arr.shape[0],) + f.shape)
        return out


class ImagePreprocessor(DefaultPreprocessor):
    """Mean-subtract + random/center crop (+ NHWC) for image fields.

    mean_image: CHW float32 (full pre-crop size), or None.
    train mode crops randomly (reference ImageNetPreprocessor), eval mode
    center-crops (deterministic eval — an upgrade over the reference, which
    random-cropped eval batches too; set eval_random_crop=True for strict
    behavioral parity).
    """

    def __init__(self, schema: Schema, image_field: str = "data",
                 mean_image: Optional[np.ndarray] = None,
                 crop: Optional[int] = None, seed: int = 0,
                 nhwc: bool = True, eval_random_crop: bool = False,
                 out_dtype: str = "float32"):
        super().__init__(schema)
        self.image_field = image_field
        self.mean_image = (None if mean_image is None
                           else mean_image.astype(np.float32))
        self.crop = crop
        self.nhwc = nhwc
        self.eval_random_crop = eval_random_crop
        # emit the COMPUTE dtype directly ("bfloat16"): the native plane
        # writes it from its OpenMP loop, so the training loop's host-side
        # cast becomes a no-op instead of a single-threaded ml_dtypes pass
        # over the whole round (~19% of ingest, bench.py --e2e r3)
        assert out_dtype in ("float32", "bfloat16"), out_dtype
        self.out_dtype = out_dtype
        self._rng = np.random.default_rng(seed)

    def convert_batch(self, batch: Dict[str, np.ndarray], *,
                      train: bool = True,
                      rng: Optional[np.random.Generator] = None
                      ) -> Dict[str, np.ndarray]:
        """`rng` overrides the internal stream — pass a round-keyed generator
        for checkpoint-resume-exact crop schedules."""
        rng = rng if rng is not None else self._rng
        out = dict(batch)
        raw = np.asarray(out[self.image_field])
        img = self._try_native_fused(raw, train, rng)
        if img is None:
            img = raw.astype(np.float32)
            if self.mean_image is not None:
                img = img - self.mean_image  # pre-crop, per reference (line 70)
            if self.crop is not None:
                if train or self.eval_random_crop:
                    img = random_crop_nchw(img, self.crop, rng)
                else:
                    img = center_crop_nchw(img, self.crop)
            if self.nhwc:
                img = to_nhwc(img)
            if self.out_dtype != "float32":
                import ml_dtypes
                img = img.astype(ml_dtypes.bfloat16)
        out[self.image_field] = img
        for f in self.schema.fields:
            if f.name != self.image_field and f.name in out:
                arr = np.asarray(out[f.name]).astype(f.dtype, copy=False)
                # apply the schema's per-example shape, like the base class:
                # e.g. label Field shape (1,) -> (B,1), () -> (B,) flat
                out[f.name] = arr.reshape((arr.shape[0],) + f.shape)
        return out

    def _try_native_fused(self, raw: np.ndarray, train: bool,
                          rng: np.random.Generator) -> Optional[np.ndarray]:
        """Fused C++ mean-subtract+crop+NHWC for uint8 CHW batches
        (native/jpeg_plane.cpp jp_crop_mean_nhwc). None -> numpy fallback."""
        if not (self.nhwc and self.crop is not None and raw.ndim == 4
                and raw.dtype == np.uint8):
            return None
        try:
            from . import jpeg_plane
            if not jpeg_plane.available():
                return None
        except ImportError:
            return None
        n, _, h, w = raw.shape
        if train or self.eval_random_crop:
            ys = rng.integers(0, h - self.crop + 1, n).astype(np.int32)
            xs = rng.integers(0, w - self.crop + 1, n).astype(np.int32)
        else:
            ys = np.full(n, (h - self.crop) // 2, np.int32)
            xs = np.full(n, (w - self.crop) // 2, np.int32)
        dt = self.out_dtype
        if dt == "bfloat16" and not jpeg_plane.supports_bf16_out():
            dt = "float32"  # stale .so: fall back, cast later in the loop
        return jpeg_plane.crop_mean_nhwc(raw, self.mean_image, ys, xs,
                                         self.crop, out_dtype=dt)


def compute_mean_image(images_chw: np.ndarray) -> np.ndarray:
    """Mean image over the dataset (reference ImageNetApp.scala:66-69 did this
    as a distributed long-sum reduce; single vectorized pass here)."""
    return images_chw.astype(np.float64).mean(axis=0).astype(np.float32)
