"""ctypes binding for the native C++ data plane (native/jpeg_plane.cpp).

Covers the reference's native-imaging role (JVM libjpeg via twelvemonkeys,
reference `preprocessing/ScaleAndConvert.scala`): JPEG decode + force-resize
+ planar CHW, plus a fused crop/mean-subtract/NHWC batch kernel. Auto-builds
with g++ on first use (cached .so); `available()` gates all callers, with
PIL/numpy fallbacks elsewhere.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SO_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libjpeg_plane.so"))

_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    if not os.path.exists(_SO_PATH):
        script = os.path.join(_NATIVE_DIR, "build.sh")
        if not os.path.exists(script):
            _build_failed = True
            return None
        try:
            subprocess.run(["sh", script], check=True, capture_output=True,
                           timeout=120)
        except (subprocess.SubprocessError, OSError):
            _build_failed = True
            return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        _build_failed = True
        return None
    lib.jp_decode_resize_chw.restype = ctypes.c_int
    lib.jp_decode_resize_chw.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8)]
    lib.jp_decode_resize_chw_batch.restype = None
    lib.jp_decode_resize_chw_batch.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_long),
        ctypes.POINTER(ctypes.c_long), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int)]
    lib.jp_crop_mean_nhwc.restype = None
    lib.jp_crop_mean_nhwc.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.c_int, ctypes.POINTER(ctypes.c_float)]
    try:
        lib.jp_crop_mean_nhwc_bf16.restype = None
        lib.jp_crop_mean_nhwc_bf16.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
            ctypes.c_int, ctypes.POINTER(ctypes.c_uint16)]
    except AttributeError:
        lib.jp_crop_mean_nhwc_bf16 = None  # pre-bf16 .so build
    try:
        lib.jp_tar_index.restype = ctypes.c_long
        lib.jp_tar_index.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_char_p, ctypes.c_long]
    except AttributeError:
        lib.jp_tar_index = None  # pre-index .so build
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def decode_resize_chw(data: bytes, height: int, width: int) -> np.ndarray:
    """One JPEG -> CHW uint8 at (height, width). Raises ValueError on corrupt
    input (same contract as the PIL fallback)."""
    lib = _load()
    assert lib is not None, "native plane unavailable"
    out = np.empty((3, height, width), dtype=np.uint8)
    rc = lib.jp_decode_resize_chw(
        data, len(data), height, width,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if rc != 0:
        raise ValueError(f"jpeg decode failed (rc={rc})")
    return out


def decode_resize_chw_batch(jpegs: list, height: int, width: int
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """Parallel batch decode. Returns (images (N,3,H,W) uint8, ok (N,) bool);
    corrupt entries have ok=False and undefined pixels."""
    lib = _load()
    assert lib is not None, "native plane unavailable"
    n = len(jpegs)
    blob = b"".join(jpegs)
    offsets = np.zeros(n, dtype=np.int64)
    lengths = np.array([len(j) for j in jpegs], dtype=np.int64)
    np.cumsum(lengths[:-1], out=offsets[1:])
    out = np.empty((n, 3, height, width), dtype=np.uint8)
    ok = np.zeros(n, dtype=np.int32)
    lib.jp_decode_resize_chw_batch(
        blob, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_long)), n, height,
        width, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ok.ctypes.data_as(ctypes.POINTER(ctypes.c_int)))
    return out, ok == 0


def supports_bf16_out() -> bool:
    lib = _load()
    return lib is not None and \
        getattr(lib, "jp_crop_mean_nhwc_bf16", None) is not None


def crop_mean_nhwc(images_chw_u8: np.ndarray,
                   mean_chw: Optional[np.ndarray],
                   ys: np.ndarray, xs: np.ndarray, crop: int,
                   out_dtype: str = "float32") -> np.ndarray:
    """Fused mean-subtract + crop + NHWC for a CHW uint8 batch.
    out_dtype 'bfloat16' writes device-ready bf16 straight from the
    OpenMP loop (round-to-nearest-even, bit-identical to ml_dtypes'
    cast) — the training apps feed bf16, so emitting f32 then casting
    on the single-threaded prefetch path was ~19% of the whole ingest
    pipeline (bench.py --e2e, r3)."""
    lib = _load()
    assert lib is not None, "native plane unavailable"
    images_chw_u8 = np.ascontiguousarray(images_chw_u8, dtype=np.uint8)
    n, c, h, w = images_chw_u8.shape
    ys = np.ascontiguousarray(ys, dtype=np.int32)
    xs = np.ascontiguousarray(xs, dtype=np.int32)
    mean_ptr = None
    if mean_chw is not None:
        mean_chw = np.ascontiguousarray(mean_chw, dtype=np.float32)
        assert mean_chw.shape == (c, h, w), (mean_chw.shape, (c, h, w))
        mean_ptr = mean_chw.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
    args = (images_chw_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            n, c, h, w, mean_ptr,
            ys.ctypes.data_as(ctypes.POINTER(ctypes.c_int)),
            xs.ctypes.data_as(ctypes.POINTER(ctypes.c_int)), crop)
    if out_dtype == "bfloat16":
        assert supports_bf16_out(), \
            "libjpeg_plane.so predates bf16 output — rerun native/build.sh"
        import ml_dtypes
        out = np.empty((n, crop, crop, c), dtype=ml_dtypes.bfloat16)
        lib.jp_crop_mean_nhwc_bf16(
            *args, out.view(np.uint16).ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint16)))
        return out
    assert out_dtype == "float32", out_dtype
    out = np.empty((n, crop, crop, c), dtype=np.float32)
    lib.jp_crop_mean_nhwc(
        *args, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    return out


class TruncatedTarError(OSError):
    """A shard is missing data (truncated mid-member or missing the tar
    end-of-archive terminator). Distinct from plain OSError so callers
    that fall back to tarfile on INDEXING problems still surface this
    loudly — Python's tarfile iterates a boundary-truncated archive
    silently, so falling back would train on partial data."""


def supports_tar_index() -> bool:
    lib = _load()
    return lib is not None and \
        getattr(lib, "jp_tar_index", None) is not None


def tar_index(path: str, name_cap: int = 128):
    """Parse a local tar's member table in C (no GIL-held Python walk):
    returns (data_offsets int64[n], sizes int64[n], isfile bool[n],
    basenames list[str]) with member numbering identical to Python
    tarfile iteration, or None when the archive uses extension headers
    (GNU long names / pax) — callers fall back to tarfile."""
    lib = _load()
    assert lib is not None, "native plane unavailable"
    if getattr(lib, "jp_tar_index", None) is None:
        return None  # pre-index .so build
    max_n = max(64, os.path.getsize(path) // 512 // 2 + 2)
    offsets = np.zeros(max_n, dtype=np.int64)
    sizes = np.zeros(max_n, dtype=np.int64)
    isfile = np.zeros(max_n, dtype=np.uint8)
    names = np.zeros(max_n * name_cap, dtype=np.uint8)
    n = lib.jp_tar_index(
        path.encode(), max_n,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_long)),
        isfile.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        names.ctypes.data_as(ctypes.c_char_p), name_cap)
    if n == -1:
        return None  # extension headers: numbering would diverge
    if n == -4:
        raise TruncatedTarError(
            f"tar {path!r} ended without the zero end-of-archive block — "
            f"truncated at a member boundary?")
    if n < 0:
        raise OSError(f"tar index of {path!r} failed (rc={n})")
    if n and int(offsets[n - 1] + sizes[n - 1]) > os.path.getsize(path):
        # truncated archive: fseek past EOF "succeeds", so the C walk can
        # index members whose data is missing
        raise TruncatedTarError(
            f"tar {path!r} is truncated (last member extends past EOF)")
    name_list = [bytes(names[i * name_cap:(i + 1) * name_cap]
                       ).split(b"\0", 1)[0].decode("utf-8", "replace")
                 for i in range(n)]
    return offsets[:n], sizes[:n], isfile[:n].astype(bool), name_list
