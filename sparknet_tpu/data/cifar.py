"""CIFAR-10 binary-format loader.

Parity with reference `loaders/CifarLoader.scala`: reads the 6 binary batch
files (data_batch_{1..5}.bin, test_batch.bin; 1 label byte + 3072 CHW image
bytes per record), validates file presence, shuffles the train set with a
seeded permutation, and computes the train mean image. Vectorized with numpy
instead of the reference's per-byte loops.
"""
from __future__ import annotations

import os
from typing import Dict, List, Tuple

import numpy as np

from ..schema import Field, Schema

TRAIN_FILES = [f"data_batch_{i}.bin" for i in range(1, 6)]
TEST_FILES = ["test_batch.bin"]
RECORD_BYTES = 1 + 3072
IMAGE_SHAPE = (3, 32, 32)  # CHW, as stored

SCHEMA = Schema(Field("data", "float32", (3, 32, 32)),
                Field("label", "int32", (1,)))


def _read_batch_file(path: str) -> Tuple[np.ndarray, np.ndarray]:
    raw = np.fromfile(path, dtype=np.uint8)
    if raw.size % RECORD_BYTES != 0:
        raise ValueError(f"{path}: size {raw.size} not a multiple of "
                         f"{RECORD_BYTES}-byte records")
    records = raw.reshape(-1, RECORD_BYTES)
    labels = records[:, 0].astype(np.int32)
    images = records[:, 1:].reshape(-1, *IMAGE_SHAPE).astype(np.float32)
    return images, labels


class CifarLoader:
    """Loads CIFAR-10 from `path` (dir containing the .bin files).

    Attributes (reference parity): train_images/train_labels (shuffled),
    test_images/test_labels, mean_image (train mean, CHW float32).
    """

    def __init__(self, path: str, seed: int = 0):
        for f in TRAIN_FILES + TEST_FILES:
            fp = os.path.join(path, f)
            if not os.path.exists(fp):
                raise FileNotFoundError(
                    f"CIFAR-10 file missing: {fp} (download with "
                    f"scripts/get_cifar10.sh)")
        train = [_read_batch_file(os.path.join(path, f)) for f in TRAIN_FILES]
        test = [_read_batch_file(os.path.join(path, f)) for f in TEST_FILES]
        images = np.concatenate([t[0] for t in train])
        labels = np.concatenate([t[1] for t in train])
        # seeded shuffle (reference: random permutation at CifarLoader.scala:31-35)
        perm = np.random.default_rng(seed).permutation(len(images))
        self.train_images = images[perm]
        self.train_labels = labels[perm]
        self.test_images = np.concatenate([t[0] for t in test])
        self.test_labels = np.concatenate([t[1] for t in test])
        self.mean_image = self.train_images.mean(axis=0)

    def train_batch_dict(self, subtract_mean: bool = True) -> Dict[str, np.ndarray]:
        data = self.train_images
        if subtract_mean:
            data = data - self.mean_image
        return {"data": data, "label": self.train_labels[:, None]}

    def test_batch_dict(self, subtract_mean: bool = True) -> Dict[str, np.ndarray]:
        data = self.test_images
        if subtract_mean:
            data = data - self.mean_image
        return {"data": data, "label": self.test_labels[:, None]}


def write_synthetic(path: str, n_per_file: int = 100, seed: int = 0) -> None:
    """Write tiny synthetic files in the exact binary format (for tests)."""
    os.makedirs(path, exist_ok=True)
    r = np.random.default_rng(seed)
    for f in TRAIN_FILES + TEST_FILES:
        labels = r.integers(0, 10, (n_per_file, 1), dtype=np.uint8)
        images = r.integers(0, 256, (n_per_file, 3072), dtype=np.uint8)
        np.concatenate([labels, images], axis=1).tofile(os.path.join(path, f))
