"""MNIST IDX-format loader.

Parity with reference `loaders/MnistLoader.scala`: parses the IDX format with
magic-number / count / shape validation (reference lines 18-29, 45-50),
normalizes pixels to [-0.5, 0.5] (line 35), labels as ints (line 54).
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Dict, Tuple

import numpy as np

from ..schema import Field, Schema

IMAGES_MAGIC = 2051
LABELS_MAGIC = 2049

SCHEMA = Schema(Field("data", "float32", (1, 28, 28)),
                Field("label", "int32", (1,)))


def _open(path: str):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def read_idx_images(path: str) -> np.ndarray:
    with _open(path) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != IMAGES_MAGIC:
            raise ValueError(f"{path}: bad magic {magic}, expected "
                             f"{IMAGES_MAGIC} (IDX image file)")
        data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
    if data.size != n * rows * cols:
        raise ValueError(f"{path}: truncated ({data.size} pixels, header "
                         f"promised {n}x{rows}x{cols})")
    return data.reshape(n, rows, cols)


def read_idx_labels(path: str) -> np.ndarray:
    with _open(path) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != LABELS_MAGIC:
            raise ValueError(f"{path}: bad magic {magic}, expected "
                             f"{LABELS_MAGIC} (IDX label file)")
        data = np.frombuffer(f.read(n), dtype=np.uint8)
    if data.size != n:
        raise ValueError(f"{path}: truncated labels")
    return data.astype(np.int32)


class MnistLoader:
    """Loads train/test splits from a directory with the standard filenames
    (train-images-idx3-ubyte[.gz], etc.)."""

    FILES = {
        "train_images": "train-images-idx3-ubyte",
        "train_labels": "train-labels-idx1-ubyte",
        "test_images": "t10k-images-idx3-ubyte",
        "test_labels": "t10k-labels-idx1-ubyte",
    }

    def __init__(self, path: str):
        resolved = {}
        for key, base in self.FILES.items():
            for cand in (os.path.join(path, base), os.path.join(path, base + ".gz")):
                if os.path.exists(cand):
                    resolved[key] = cand
                    break
            else:
                raise FileNotFoundError(f"MNIST file missing: {path}/{base}[.gz]")
        self.train_images = self._norm(read_idx_images(resolved["train_images"]))
        self.train_labels = read_idx_labels(resolved["train_labels"])
        self.test_images = self._norm(read_idx_images(resolved["test_images"]))
        self.test_labels = read_idx_labels(resolved["test_labels"])
        if len(self.train_images) != len(self.train_labels):
            raise ValueError("train images/labels count mismatch")

    @staticmethod
    def _norm(images: np.ndarray) -> np.ndarray:
        # [-0.5, 0.5] normalization (reference MnistLoader.scala:35)
        return (images.astype(np.float32) / 255.0 - 0.5)[:, None, :, :]

    def train_batch_dict(self) -> Dict[str, np.ndarray]:
        return {"data": self.train_images, "label": self.train_labels[:, None]}

    def test_batch_dict(self) -> Dict[str, np.ndarray]:
        return {"data": self.test_images, "label": self.test_labels[:, None]}


def write_synthetic(path: str, n_train: int = 256, n_test: int = 64,
                    seed: int = 0) -> None:
    """Write tiny synthetic IDX files (exact format, for tests)."""
    os.makedirs(path, exist_ok=True)
    r = np.random.default_rng(seed)

    def w_images(name, n):
        with open(os.path.join(path, name), "wb") as f:
            f.write(struct.pack(">IIII", IMAGES_MAGIC, n, 28, 28))
            f.write(r.integers(0, 256, n * 28 * 28, dtype=np.uint8).tobytes())

    def w_labels(name, n):
        with open(os.path.join(path, name), "wb") as f:
            f.write(struct.pack(">II", LABELS_MAGIC, n))
            f.write(r.integers(0, 10, n, dtype=np.uint8).tobytes())

    w_images("train-images-idx3-ubyte", n_train)
    w_labels("train-labels-idx1-ubyte", n_train)
    w_images("t10k-images-idx3-ubyte", n_test)
    w_labels("t10k-labels-idx1-ubyte", n_test)
