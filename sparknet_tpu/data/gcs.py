"""Native `gs://` object-store ingest — no SDK, no FUSE.

The reference's data plane read straight from the object store per task
(`loaders/ImageNetLoader.scala:62-63`: one `AmazonS3Client.getObject` per
tar). The r3 build delegated cloud storage to a GCS-FUSE mount, inheriting
its failure modes; this module is the direct equivalent of the reference's
approach for GCS: plain HTTPS against the JSON API
(`storage.googleapis.com`) with

  - object LISTING with pagination (the shard discovery pass),
  - whole-object fetch (label files),
  - STREAMED ranged reads with transparent resume — a dropped connection
    mid-tar reconnects with `Range: bytes=<pos>-` and continues, so a
    multi-hour streaming epoch survives the network blips a FUSE mount
    turns into EIO.

Auth (in order): an emulator endpoint needs none; `GOOGLE_OAUTH_ACCESS_TOKEN`
if set; the GCE/TPU-VM metadata server (the standard production path — TPU
VMs carry a service account); `gcloud auth print-access-token`; anonymous
(public buckets). Tokens are cached until ~expiry.

`STORAGE_EMULATOR_HOST` (the conventional GCS-emulator knob) redirects all
traffic — tests run a local fake server and exercise the full path,
including mid-stream disconnects.
"""
from __future__ import annotations

import http.client
import io
import json
import os
import random
import subprocess
import sys
import time
import urllib.error
import urllib.parse
import warnings
import urllib.request
from typing import List, Optional, Tuple

_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")

#: (attempts, base backoff seconds) for ranged-read reconnects and
#: retryable HTTP errors (429/5xx)
RETRIES = 5
BACKOFF_S = 0.5


def retry_delay(attempt: int, err: Optional[BaseException] = None) -> float:
    """Seconds to sleep before retry `attempt` + 1: FULL-JITTER exponential
    backoff — uniform in [0, BACKOFF_S * 2^attempt]. The previous
    deterministic `BACKOFF_S * 2^attempt` synchronized every reader into a
    thundering herd: after a shared 429 all `ingest_sources` readers (and
    all hosts of a pod) slept the exact same time and re-arrived together,
    earning the next 429. A 429/503's `Retry-After` header (seconds form),
    when present, is honored as a FLOOR under the jittered delay — the
    server knows when capacity returns; arriving earlier just burns an
    attempt. 503 matters for S3: AWS throttles with `503 SlowDown` (not
    429) and often names its price in Retry-After — a preempted worker
    rejoining a pod through a hot bucket prefix is exactly this path."""
    delay = random.uniform(0.0, BACKOFF_S * (2 ** attempt))
    if isinstance(err, urllib.error.HTTPError) and err.code in (429, 503):
        ra = (err.headers.get("Retry-After")
              if err.headers is not None else None)
        try:
            if ra is not None:
                delay = max(delay, float(ra))
        except ValueError:
            pass  # HTTP-date form: rare from GCS; keep the jittered delay
    return delay


def parse_gs_url(url: str) -> Tuple[str, str]:
    """'gs://bucket/some/prefix' -> ('bucket', 'some/prefix')."""
    if not url.startswith("gs://"):
        raise ValueError(f"not a gs:// url: {url!r}")
    rest = url[len("gs://"):]
    bucket, _, name = rest.partition("/")
    if not bucket:
        raise ValueError(f"gs:// url missing bucket: {url!r}")
    return bucket, name


def is_gs_path(path: str) -> bool:
    return isinstance(path, str) and path.startswith("gs://")


def http_get_with_retry(url: str, headers: Optional[dict] = None,
                        timeout: float = 60.0, method: str = "GET",
                        data: Optional[bytes] = None,
                        headers_fn=None):
    """HTTP request with retry on 429/5xx and connection errors; returns
    the open response (caller reads/closes). 4xx other than 429 propagates
    immediately — retrying a 403/404 only hides it. Shared by the GCS and
    S3 clients (auth differs per caller; the transport does not). Bodies
    (`data`) are bytes held in memory, so retrying a PUT/POST re-sends the
    identical payload.

    `headers_fn` (mutually additive with `headers`) is called PER ATTEMPT
    to (re)build the request headers: SigV4 signatures embed `x-amz-date`,
    and a retry that slept out a long Retry-After floor must present a
    FRESH signature, not replay a stale one into AWS's 15-minute clock-
    skew window (the S3 client signs per attempt through this hook)."""
    last: Optional[BaseException] = None
    for attempt in range(RETRIES):
        h = dict(headers or {})
        if headers_fn is not None:
            h.update(headers_fn())
        req = urllib.request.Request(url, headers=h, data=data,
                                     method=method)
        try:
            return urllib.request.urlopen(req, timeout=timeout)
        except urllib.error.HTTPError as e:
            if e.code not in (429, 500, 502, 503, 504):
                raise
            last = e
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            last = e
        if attempt < RETRIES - 1:  # no dead-time sleep before the raise
            time.sleep(retry_delay(attempt, last))
    raise ConnectionError(f"{method} {url} failed after {RETRIES} attempts"
                          ) from last


class GcsClient:
    """Minimal GCS JSON-API client over urllib (stdlib only)."""

    def __init__(self, endpoint: Optional[str] = None,
                 timeout: float = 60.0):
        self.endpoint = (endpoint or os.environ.get("STORAGE_EMULATOR_HOST")
                         or "https://storage.googleapis.com").rstrip("/")
        if "://" not in self.endpoint:
            self.endpoint = "http://" + self.endpoint
        self._emulated = "storage.googleapis.com" not in self.endpoint
        self.timeout = timeout
        self._token: Optional[str] = None
        self._token_expiry = 0.0

    # -- auth ----------------------------------------------------------------

    def _auth_header(self) -> dict:
        if self._emulated:
            return {}
        tok = self._get_token()
        return {"Authorization": f"Bearer {tok}"} if tok else {}

    def _get_token(self) -> Optional[str]:
        if self._token is not None and time.time() < self._token_expiry:
            return self._token
        tok, ttl = self._fetch_token()
        self._token = tok
        self._token_expiry = time.time() + ttl
        return tok

    def _fetch_token(self) -> Tuple[Optional[str], float]:
        env = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
        if env:
            return env, 300.0
        try:  # GCE/TPU-VM metadata server: THE production path
            req = urllib.request.Request(
                _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
            with urllib.request.urlopen(req, timeout=2.0) as r:
                d = json.loads(r.read())
            return d["access_token"], max(60.0, d.get("expires_in", 300) - 60)
        except Exception:
            pass
        try:  # workstation fallback
            tok = subprocess.run(
                ["gcloud", "auth", "print-access-token"],
                capture_output=True, text=True, timeout=20).stdout.strip()
            if tok:
                return tok, 300.0
        except Exception:
            pass
        print("gcs: no credentials found (metadata server, "
              "GOOGLE_OAUTH_ACCESS_TOKEN, gcloud all unavailable) — "
              "proceeding anonymously", file=sys.stderr)
        return None, 300.0

    # -- requests with retry -------------------------------------------------

    def _open(self, url: str, headers: Optional[dict] = None):
        """GET with auth + the shared retry loop."""
        return http_get_with_retry(
            url, {**self._auth_header(), **(headers or {})}, self.timeout)

    # -- API -----------------------------------------------------------------

    def list_objects(self, bucket: str, prefix: str = ""
                     ) -> List[Tuple[str, int]]:
        """[(name, size), ...] under prefix, paginated, name-sorted."""
        return [(n, s) for n, s, _ in self.list_objects_meta(bucket, prefix)]

    def list_objects_meta(self, bucket: str, prefix: str = ""
                          ) -> List[Tuple[str, int, Optional[str]]]:
        """[(name, size, generation), ...] under prefix, paginated,
        name-sorted. Generation rides the same listing request (one extra
        field) so freshness tokens cost no additional round trips; servers
        that omit it (older emulators) yield None."""
        out: List[Tuple[str, int, Optional[str]]] = []
        token = None
        while True:
            q = {"prefix": prefix,
                 "fields": "items(name,size,generation),nextPageToken"}
            if token:
                q["pageToken"] = token
            url = (f"{self.endpoint}/storage/v1/b/"
                   f"{urllib.parse.quote(bucket, safe='')}/o?"
                   + urllib.parse.urlencode(q))
            with self._open(url) as r:
                d = json.loads(r.read())
            out.extend((it["name"], int(it.get("size", 0)),
                        it.get("generation"))
                       for it in d.get("items", []))
            token = d.get("nextPageToken")
            if not token:
                break
        return sorted(out)

    def _media_url(self, bucket: str, name: str) -> str:
        return (f"{self.endpoint}/storage/v1/b/"
                f"{urllib.parse.quote(bucket, safe='')}/o/"
                f"{urllib.parse.quote(name, safe='')}?alt=media")

    def read_object(self, bucket: str, name: str) -> bytes:
        with self._open(self._media_url(bucket, name)) as r:
            return r.read()

    def open_stream(self, bucket: str, name: str,
                    start: int = 0) -> "GcsRangeStream":
        """Byte stream from `start` with transparent reconnect-and-resume
        (the per-tar streamed GetObject of the reference's ingest)."""
        return GcsRangeStream(self, bucket, name, start)


class GcsRangeStream(io.RawIOBase):
    """Read-only streamed object body. A mid-read connection failure
    reopens the request with `Range: bytes=<current position>-` — the
    stream position never goes backwards and nothing is re-yielded."""

    def __init__(self, client: GcsClient, bucket: str, name: str,
                 start: int = 0):
        self._client = client
        self._bucket = bucket
        self._name = name
        self._pos = int(start)
        self._resp = None
        self._eof = False
        self._end: Optional[int] = None  # pos + remaining Content-Length

    def _connect(self):
        headers = {}
        if self._pos:
            headers["Range"] = f"bytes={self._pos}-"
        try:
            self._resp = self._client._open(
                self._client._media_url(self._bucket, self._name),
                headers=headers)
        except urllib.error.HTTPError as e:
            if e.code == 416:  # start is at/past EOF: empty stream
                self._resp = io.BytesIO(b"")
                self._eof = True
                return
            raise
        # a server ignoring Range would silently re-serve from byte 0 and
        # corrupt the tar stream mid-resume — fail loudly instead
        if self._pos and getattr(self._resp, "status", 206) != 206:
            raise IOError(
                f"gcs: server ignored Range bytes={self._pos}- for "
                f"gs://{self._bucket}/{self._name}")
        # http.client returns b"" (not an error) when a length-delimited
        # body is truncated by a dropped connection — remember where the
        # body SHOULD end so a short b"" is treated as a disconnect, not
        # EOF (a silently shortened tar would drop examples)
        cl = self._resp.headers.get("Content-Length")
        self._end = self._pos + int(cl) if cl is not None else None
        if self._end is None:
            # chunked-transfer proxy/emulator: a dropped connection then
            # looks exactly like EOF — truncation detection is OFF. Say
            # so once rather than silently degrade.
            warnings.warn(
                f"gcs: no Content-Length for gs://{self._bucket}/"
                f"{self._name} — truncated-body detection disabled for "
                f"this stream", RuntimeWarning, stacklevel=2)

    def readable(self) -> bool:
        return True

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            chunks = []
            while True:
                c = self.read(1 << 20)
                if not c:
                    return b"".join(chunks)
                chunks.append(c)
        if self._eof:
            return b""
        last: Optional[BaseException] = None
        for attempt in range(RETRIES):
            if self._resp is None:
                self._connect()
                if self._eof:
                    return b""
            try:
                data = self._resp.read(n)
            except (ConnectionError, TimeoutError, OSError,
                    urllib.error.URLError,
                    http.client.HTTPException) as e:  # e.g. IncompleteRead
                last = e
                try:
                    self._resp.close()
                except Exception:
                    pass
                self._resp = None  # reconnect from self._pos
                if attempt < RETRIES - 1:
                    time.sleep(retry_delay(attempt, last))
                continue
            if data:
                self._pos += len(data)
                return data
            if self._end is not None and self._pos < self._end:
                # truncated body: reconnect and resume from _pos
                last = ConnectionError(
                    f"body ended at {self._pos}, expected {self._end}")
                try:
                    self._resp.close()
                except Exception:
                    pass
                self._resp = None
                if attempt < RETRIES - 1:
                    time.sleep(retry_delay(attempt, last))
                continue
            self._eof = True
            return data
        raise ConnectionError(
            f"gcs: read of gs://{self._bucket}/{self._name} at byte "
            f"{self._pos} failed after {RETRIES} reconnects") from last

    def tell(self) -> int:
        return self._pos

    def close(self) -> None:
        if self._resp is not None:
            try:
                self._resp.close()
            except Exception:
                pass
            self._resp = None
        super().close()


#: gs:// url -> byte size, filled by listings so per-shard size lookups
#: (corpus identity, host weight estimates) cost no extra round trips
_SIZE_CACHE: dict = {}

#: gs:// url -> (size, generation), filled alongside _SIZE_CACHE — the
#: freshness token pair the member-index staleness check compares (size
#: alone misses an equal-size replacement; generation cannot)
_STAT_CACHE: dict = {}

#: endpoint -> shared GcsClient: the token cache lives on the client, and
#: the ingest hot path opens one stream per tar per epoch — a fresh client
#: per call would re-fetch credentials (a metadata-server round trip, or
#: worse a `gcloud` subprocess) on every shard open. Keyed by endpoint so
#: tests that repoint STORAGE_EMULATOR_HOST get a matching client.
_CLIENTS: dict = {}


def _shared_client() -> "GcsClient":
    ep = (os.environ.get("STORAGE_EMULATOR_HOST")
          or "https://storage.googleapis.com")
    client = _CLIENTS.get(ep)
    if client is None:
        client = _CLIENTS[ep] = GcsClient()
    return client


def gs_list_shards(root: str, prefix: str = "") -> List[str]:
    """gs:// analogue of `imagenet.list_shards`: all .tar objects under
    root whose basename starts with prefix, as gs:// urls, sorted."""
    bucket, base = parse_gs_url(root)
    if base and not base.endswith("/"):
        base += "/"
    client = _shared_client()
    out = []
    for name, size, gen in client.list_objects_meta(bucket, base):
        rel = name[len(base):]
        if "/" in rel:  # direct children only, like os.listdir
            continue
        if rel.startswith(prefix) and rel.endswith(".tar"):
            url = f"gs://{bucket}/{name}"
            _SIZE_CACHE[url] = size
            _STAT_CACHE[url] = (size, gen)
            out.append(url)
    if not out:
        raise FileNotFoundError(f"no .tar shards under {root!r} "
                                f"matching prefix {prefix!r}")
    return sorted(out)


def gs_list_urls(root: str) -> List[str]:
    """ALL object urls under a gs:// prefix (recursive, sorted; empty list
    when nothing matches — unlike gs_list_shards this is not tar-specific
    and a bare prefix is not an error: the checkpoint store lists a
    possibly-empty directory)."""
    bucket, base = parse_gs_url(root)
    if base and not base.endswith("/"):
        base += "/"
    out = []
    for name, size, gen in _shared_client().list_objects_meta(bucket, base):
        url = f"gs://{bucket}/{name}"
        _SIZE_CACHE[url] = size
        _STAT_CACHE[url] = (size, gen)
        out.append(url)
    return sorted(out)


def gs_size(url: str, fresh: bool = False) -> int:
    """Object byte size: listing cache first, else one metadata GET.
    `fresh=True` bypasses the cache (one metadata GET) — used to detect
    an object replaced under a warm member index."""
    if not fresh and url in _SIZE_CACHE:
        return _SIZE_CACHE[url]
    return gs_stat(url, fresh=fresh)[0]


def gs_stat(url: str, fresh: bool = False
            ) -> Tuple[int, Optional[str]]:
    """(size, generation) from one metadata GET (`?fields=size,generation`
    — the same request the size-only check used, one extra field). The
    generation is the freshness token the member-index staleness check
    needs: an EQUAL-size replacement changes generation even though size
    alone cannot see it."""
    if not fresh and url in _STAT_CACHE:
        return _STAT_CACHE[url]
    bucket, name = parse_gs_url(url)
    client = _shared_client()
    u = (f"{client.endpoint}/storage/v1/b/"
         f"{urllib.parse.quote(bucket, safe='')}/o/"
         f"{urllib.parse.quote(name, safe='')}?fields=size,generation")
    with client._open(u) as r:
        d = json.loads(r.read())
    stat = (int(d.get("size", 0)), d.get("generation"))
    _SIZE_CACHE[url] = stat[0]
    _STAT_CACHE[url] = stat
    return stat


def gs_read(url: str) -> bytes:
    bucket, name = parse_gs_url(url)
    return _shared_client().read_object(bucket, name)


def gs_open_stream(url: str, start: int = 0) -> GcsRangeStream:
    bucket, name = parse_gs_url(url)
    return _shared_client().open_stream(bucket, name, start)


def gs_write(url: str, data: bytes) -> None:
    """Upload bytes to a gs:// object (simple media upload) — the push
    side of the ingest tooling (the reference's sharder uploaded its
    chunks to the object store, `scripts/put_imagenet_on_s3.py`)."""
    bucket, name = parse_gs_url(url)
    client = _shared_client()
    u = (f"{client.endpoint}/upload/storage/v1/b/"
         f"{urllib.parse.quote(bucket, safe='')}/o?uploadType=media&name="
         f"{urllib.parse.quote(name, safe='')}")
    with http_get_with_retry(
            u, {**client._auth_header(),
                "Content-Type": "application/octet-stream"},
            client.timeout, method="POST", data=data) as r:
        r.read()
    _SIZE_CACHE[url] = len(data)
    _STAT_CACHE.pop(url, None)


def gs_delete(url: str, missing_ok: bool = True) -> None:
    """DELETE an object; 404 is success when `missing_ok` (retention and
    part cleanup race nothing — only one writer per checkpoint dir)."""
    bucket, name = parse_gs_url(url)
    client = _shared_client()
    u = (f"{client.endpoint}/storage/v1/b/"
         f"{urllib.parse.quote(bucket, safe='')}/o/"
         f"{urllib.parse.quote(name, safe='')}")
    try:
        with http_get_with_retry(u, client._auth_header(), client.timeout,
                                 method="DELETE") as r:
            r.read()
    except urllib.error.HTTPError as e:
        if not (missing_ok and e.code == 404):
            raise
    _SIZE_CACHE.pop(url, None)
    _STAT_CACHE.pop(url, None)


# -- resumable / composite upload (the checkpoint writer's push side) --------

#: resumable-upload chunk granularity — the GCS protocol requires every
#: non-final chunk be a multiple of 256 KiB; 8 MiB balances per-chunk HTTP
#: overhead against retry re-send cost
GS_UPLOAD_CHUNK = 8 << 20

#: component count for parallel composite uploads of large blobs (the
#: ~244 MB checkpoint state.npz): each part is its own resumable session
#: on its own thread, then one compose call finalizes the object
GS_UPLOAD_PARALLEL = 4


def gs_write_resumable(url: str, data,
                       chunk_bytes: Optional[int] = None) -> None:
    """Upload bytes-like `data` (bytes or a zero-copy memoryview) via ONE
    resumable-upload session: initiate (POST
    `uploadType=resumable` -> session URL), then sequential chunk PUTs with
    `Content-Range`. The object becomes visible only when the FINAL chunk
    lands — a killed writer leaves no partial object, which is the
    atomicity the checkpoint store's upload-then-finalize protocol needs.
    Intermediate chunks answer 308 (Resume Incomplete); the final one 200."""
    if chunk_bytes is None:
        chunk_bytes = GS_UPLOAD_CHUNK  # read at call time: patchable
    if chunk_bytes % (256 << 10):
        raise ValueError(f"chunk_bytes {chunk_bytes} is not a multiple of "
                         f"256 KiB (GCS resumable-upload granularity)")
    bucket, name = parse_gs_url(url)
    client = _shared_client()
    u = (f"{client.endpoint}/upload/storage/v1/b/"
         f"{urllib.parse.quote(bucket, safe='')}/o?uploadType=resumable"
         f"&name={urllib.parse.quote(name, safe='')}")
    with http_get_with_retry(
            u, {**client._auth_header(),
                "x-upload-content-length": str(len(data)),
                "Content-Type": "application/octet-stream"},
            client.timeout, method="POST") as r:
        r.read()
        session = r.headers.get("Location")
    if not session:
        raise IOError(f"gcs: resumable-upload initiate for {url} returned "
                      f"no session Location")
    total = len(data)
    sent = 0
    while True:
        # bytes() per chunk: `data` may be a zero-copy memoryview of the
        # serialized state (checkpoint writer); urllib needs real bytes,
        # so copy only one chunk at a time, never the whole blob
        chunk = bytes(data[sent:sent + chunk_bytes])
        end = sent + len(chunk) - 1
        rng = (f"bytes {sent}-{end}/{total}" if chunk
               else f"bytes */{total}")  # zero-byte object: one finalize PUT
        try:
            with http_get_with_retry(
                    session, {"Content-Range": rng}, client.timeout,
                    method="PUT", data=chunk) as r:
                r.read()
        except urllib.error.HTTPError as e:
            if e.code != 308:  # 308 = chunk accepted, session continues
                raise
        sent += len(chunk)
        if sent >= total:
            break
    _SIZE_CACHE[url] = total
    _STAT_CACHE.pop(url, None)


def gs_compose(dest_url: str, part_urls: List[str]) -> None:
    """Server-side compose of up to 32 source objects into `dest_url` (the
    finalize step of a parallel composite upload): the destination appears
    atomically, or not at all."""
    bucket, name = parse_gs_url(dest_url)
    parts = []
    for p in part_urls:
        b, n = parse_gs_url(p)
        if b != bucket:
            raise ValueError(f"compose source {p} not in bucket {bucket}")
        parts.append(n)
    client = _shared_client()
    u = (f"{client.endpoint}/storage/v1/b/"
         f"{urllib.parse.quote(bucket, safe='')}/o/"
         f"{urllib.parse.quote(name, safe='')}/compose")
    body = json.dumps({"sourceObjects": [{"name": n} for n in parts]}
                      ).encode()
    with http_get_with_retry(
            u, {**client._auth_header(),
                "Content-Type": "application/json"},
            client.timeout, method="POST", data=body) as r:
        r.read()
    _SIZE_CACHE.pop(dest_url, None)
    _STAT_CACHE.pop(dest_url, None)


def gs_write_large(url: str, data, *,
                   parallel: Optional[int] = None,
                   chunk_bytes: Optional[int] = None) -> None:
    """Bulk upload of bytes-like `data` (bytes, or a memoryview that is
    never copied whole) for multi-hundred-MB blobs (checkpoint state.npz):
    split into `parallel` component objects uploaded CONCURRENTLY (each its
    own resumable session — gsutil's parallel composite upload shape), then
    one compose finalizes the destination and the parts are deleted. Small
    payloads (one chunk or parallel=1) take a single resumable session.
    Either way the destination object appears atomically: a writer killed
    mid-upload leaves at most invisible sessions / stray `.part-` objects,
    never a torn destination."""
    if parallel is None:
        parallel = GS_UPLOAD_PARALLEL
    if chunk_bytes is None:
        chunk_bytes = GS_UPLOAD_CHUNK
    if parallel <= 1 or len(data) <= chunk_bytes:
        gs_write_resumable(url, data, chunk_bytes)
        return
    from concurrent.futures import ThreadPoolExecutor
    n = min(parallel, -(-len(data) // chunk_bytes))
    # part boundaries on chunk granularity (non-final resumable chunks
    # must be 256 KiB-aligned; aligning parts keeps every chunk aligned)
    per = -(-len(data) // n)
    per = -(-per // chunk_bytes) * chunk_bytes
    bounds = [(i, min(i + per, len(data)))
              for i in range(0, len(data), per)]
    nonce = os.urandom(6).hex()
    part_urls = [f"{url}.part-{nonce}-{k:04d}" for k in range(len(bounds))]
    try:
        with ThreadPoolExecutor(len(bounds),
                                thread_name_prefix="gs-part") as ex:
            list(ex.map(lambda ab: gs_write_resumable(
                ab[0], data[ab[1][0]:ab[1][1]], chunk_bytes),
                zip(part_urls, bounds)))
        gs_compose(url, part_urls)
    finally:
        for p in part_urls:  # success or abort: parts must not linger
            try:
                gs_delete(p)
            except Exception:
                pass
    _SIZE_CACHE[url] = len(data)
    _STAT_CACHE.pop(url, None)
