"""In-memory dataset partitioning + τ-round batch sampling.

Reproduces the reference's data motion semantics on a mesh:
  - `repartition(numWorkers).cache()` (reference `apps/CifarApp.scala:65-66`)
    -> `ArrayDataset.partitions(n_workers)`: contiguous equal splits.
  - per-round random window per worker (`apps/CifarApp.scala:131-133`:
    startIdx = Random.nextInt(len - τ·batch); it.drop(startIdx)) ->
    `RoundSampler.next_round()` draws an independent random window per worker
    and lays out [tau, n_workers*local_b, ...] arrays whose batch axis is
    blocked by worker — exactly the trainer's P(None, 'data') sharding, so
    each device reads its own partition's window.
"""
from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np


class ArrayDataset:
    """Dict of aligned numpy arrays (leading dim = examples)."""

    def __init__(self, arrays: Dict[str, np.ndarray]):
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"misaligned fields: {sizes}")
        self.arrays = arrays
        self.size = next(iter(sizes.values()))

    def __len__(self) -> int:
        return self.size

    def shuffled(self, seed: int) -> "ArrayDataset":
        perm = np.random.default_rng(seed).permutation(self.size)
        return ArrayDataset({k: v[perm] for k, v in self.arrays.items()})

    def partition_bounds(self, n_workers: int):
        per = self.size // n_workers
        if per == 0:
            raise ValueError(f"{self.size} examples < {n_workers} workers")
        return [(w * per, (w + 1) * per) for w in range(n_workers)]

    def host_shard(self, host_id: int, host_count: int) -> "ArrayDataset":
        """This host's contiguous slice of an (identically loaded) dataset —
        the multi-host analogue of the reference's
        `repartition(numWorkers)` + per-executor caching
        (`apps/CifarApp.scala:65-66`): each host then trains only on its own
        disjoint examples. No-op for a single-host world."""
        if host_count == 1:
            return self
        if not (0 <= host_id < host_count):
            raise ValueError(f"host_id {host_id} not in [0, {host_count})")
        lo, hi = self.partition_bounds(host_count)[host_id]
        return ArrayDataset({k: v[lo:hi] for k, v in self.arrays.items()})


class RoundSampler:
    """Per-round τ-window sampler over worker partitions."""

    def __init__(self, dataset: ArrayDataset, n_workers: int, local_batch: int,
                 tau: int, seed: int = 0):
        self.ds = dataset
        self.n_workers = n_workers
        self.local_batch = local_batch
        self.tau = tau
        self.bounds = dataset.partition_bounds(n_workers)
        window = tau * local_batch
        part = self.bounds[0][1] - self.bounds[0][0]
        if window > part:
            raise ValueError(
                f"τ·batch = {window} exceeds partition size {part} "
                f"({dataset.size} examples / {n_workers} workers)")
        self.seed = seed
        self._rng = np.random.default_rng(seed)

    def reshard(self, n_workers: int) -> "RoundSampler":
        """A NEW sampler over the same dataset/seed with the partitions
        re-cut for `n_workers` — the elastic-resize data path: survivors
        (and joiners) re-partition the corpus instead of training on the
        dead worker's orphaned shard forever. Round-keyed draws stay
        deterministic in (seed, round_index) for the new layout."""
        return RoundSampler(self.ds, n_workers, self.local_batch, self.tau,
                            seed=self.seed)

    def next_round(self, round_index: Optional[int] = None
                   ) -> Dict[str, np.ndarray]:
        """[tau, n_workers*local_b, ...] arrays, batch axis blocked by worker.

        Pass round_index for a round-keyed rng: sampling then depends only on
        (seed, round_index), making checkpoint-resume draw identical windows.
        """
        rng = (np.random.default_rng((self.seed, round_index))
               if round_index is not None else self._rng)
        window = self.tau * self.local_batch
        idx = np.empty((self.tau, self.n_workers * self.local_batch), np.int64)
        for w, (lo, hi) in enumerate(self.bounds):
            start = lo + rng.integers(0, hi - lo - window + 1)
            span = np.arange(start, start + window).reshape(
                self.tau, self.local_batch)
            idx[:, w * self.local_batch:(w + 1) * self.local_batch] = span
        flat = idx.reshape(-1)
        return {
            k: v[flat].reshape((self.tau, idx.shape[1]) + v.shape[1:])
            for k, v in self.ds.arrays.items()}

    def eval_batches(self, batch: int) -> Iterator[Dict[str, np.ndarray]]:
        """Sequential full-coverage eval batches (global batch size)."""
        n = (self.ds.size // batch) * batch
        for i in range(0, n, batch):
            yield {k: v[i:i + batch] for k, v in self.ds.arrays.items()}
