"""UCI Adult/Census CSV loader (tabular).

Parity with the reference's adult path (`src/test/scala/apps/LoadAdultDataSpec.scala`
+ `models/adult/adult.prototxt`): CSV rows -> numeric feature columns C0..Cn
plus a binary label from the income field. Categorical columns are
dictionary-encoded to float indices (the reference fed spark-csv columns
straight to the net; numeric semantics preserved here).
"""
from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Standard UCI adult.data column order.
COLUMNS = ["age", "workclass", "fnlwgt", "education", "education_num",
           "marital_status", "occupation", "relationship", "race", "sex",
           "capital_gain", "capital_loss", "hours_per_week", "native_country",
           "income"]
NUMERIC = {"age", "fnlwgt", "education_num", "capital_gain", "capital_loss",
           "hours_per_week"}


class AdultLoader:
    def __init__(self, path: str, feature_columns: Optional[Sequence[str]] = None,
                 normalize: bool = True):
        if not os.path.exists(path):
            raise FileNotFoundError(f"adult CSV missing: {path}")
        self.feature_columns = list(feature_columns or
                                    [c for c in COLUMNS if c != "income"])
        rows: List[List[str]] = []
        with open(path, newline="") as f:
            for row in csv.reader(f):
                if len(row) != len(COLUMNS):
                    continue  # blank/short lines in the raw UCI file
                rows.append([c.strip() for c in row])
        if not rows:
            raise ValueError(f"{path}: no parseable rows")
        self.vocab: Dict[str, Dict[str, int]] = {}
        feats = np.zeros((len(rows), len(self.feature_columns)), np.float32)
        labels = np.zeros((len(rows),), np.int32)
        for j, col in enumerate(self.feature_columns):
            ci = COLUMNS.index(col)
            if col in NUMERIC:
                feats[:, j] = [float(r[ci]) for r in rows]
            else:
                vocab = self.vocab.setdefault(col, {})
                for i, r in enumerate(rows):
                    feats[i, j] = vocab.setdefault(r[ci], len(vocab))
        for i, r in enumerate(rows):
            labels[i] = 1 if r[-1].startswith(">50K") else 0
        if normalize:
            mu, sd = feats.mean(0), feats.std(0)
            sd[sd == 0] = 1.0
            feats = (feats - mu) / sd
        self.features = feats
        self.labels = labels

    def batch_dict(self) -> Dict[str, np.ndarray]:
        """Net inputs: 'C0' = feature matrix (N, n_features), 'label'."""
        return {"C0": self.features, "label": self.labels[:, None]}


def write_synthetic(path: str, n: int = 200, seed: int = 0) -> None:
    """Tiny synthetic adult.data in the exact CSV shape (for tests)."""
    r = np.random.default_rng(seed)
    workclasses = ["Private", "Self-emp", "Federal-gov"]
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        for _ in range(n):
            age = int(r.integers(17, 90))
            row = [str(age), workclasses[int(r.integers(0, 3))], "77516",
                   "Bachelors", "13", "Never-married", "Adm-clerical",
                   "Not-in-family", "White", "Male",
                   str(int(r.integers(0, 5000))), "0",
                   str(int(r.integers(1, 99))), "United-States",
                   ">50K" if r.random() < 0.25 else "<=50K"]
            w.writerow(row)
