"""Streaming round source: train from tar shards larger than host RAM.

The reference never materialized ImageNet — each Spark task streamed its tar
and trained on what it read (`loaders/ImageNetLoader.scala:59-91`, one
partition per tar). This is that data motion, mesh-native: a background
thread streams + decodes this HOST's shards (via `ShardedTarLoader`, which
already fans decode out over OpenMP) and assembles τ-round batch arrays into
a bounded queue, so round R+1's window is decoded while round R trains on
device. Host RAM holds only `prefetch_rounds + 1` rounds of decoded pixels,
never the corpus.

Semantics vs the in-RAM `RoundSampler`:
  - windows are consecutive stream positions, not random offsets into a
    cached partition — exactly the reference's behavior for its streamed
    (non-cached) datasets; shards cycle forever (epoch boundaries are
    invisible, like the reference's `.repeat()`-style requeue).
  - `round_index` is accepted for API compatibility but does not key the
    sampling: a resumed run re-streams from shard 0 rather than seeking to
    the interrupted stream position (the reference had no resume at all).
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from .imagenet import ShardedTarLoader


def streaming_sum_count(loader: ShardedTarLoader
                        ) -> Tuple[np.ndarray, int]:
    """One streaming pass over the shards -> (per-pixel float64 sum CHW,
    count). The mean-image reduce (`ImageNetApp.scala:66-69`) without ever
    materializing the corpus; hosts combine (sum, count) pairs for the
    global mean."""
    total: Optional[np.ndarray] = None
    count = 0
    for img, _ in loader:
        if total is None:
            total = np.zeros(img.shape, np.float64)
        total += img
        count += 1
    if count == 0:
        raise ValueError(f"no decodable labeled images in "
                         f"{loader.shard_paths}")
    return total, count


class StreamingRoundSource:
    """Bounded-prefetch producer of τ-round batches from tar shards.

    `next_round()` returns the same layout `RoundSampler.next_round` does —
    {field: [tau, n_workers*local_batch, ...]} with the batch axis blocked by
    worker, each worker's block a consecutive run of tau*local_batch stream
    examples (its "window"). Raw uint8 CHW + int32 labels; per-round
    preprocessing (mean/crop/NHWC) stays in the training loop.
    """

    def __init__(self, loader: ShardedTarLoader, n_workers: int,
                 local_batch: int, tau: int, prefetch_rounds: int = 2):
        self.loader = loader
        self.n_workers = n_workers
        self.local_batch = local_batch
        self.tau = tau
        self.round_examples = n_workers * local_batch * tau
        self.epochs = 0  # completed passes over the shard set
        self._q: queue.Queue = queue.Queue(maxsize=max(1, prefetch_rounds))
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._produce, name="stream-decode", daemon=True)
        self._thread.start()

    # -- producer (background thread) ---------------------------------------

    def _produce(self) -> None:
        try:
            imgs, lbls = [], []
            while not self._stop.is_set():
                n_before = 0
                for img, label in self.loader:
                    n_before += 1
                    imgs.append(img)
                    lbls.append(label)
                    if len(imgs) == self.round_examples:
                        if not self._put(self._assemble(imgs, lbls)):
                            return
                        imgs, lbls = [], []
                    if self._stop.is_set():
                        return
                if n_before == 0:
                    raise ValueError(
                        f"no decodable labeled images in "
                        f"{self.loader.shard_paths}")
                self.epochs += 1  # wrap: stream the shards again
        except BaseException as e:  # surface in the consumer
            self._err = e
            self._stop.set()

    def _assemble(self, imgs, lbls) -> Dict[str, np.ndarray]:
        # consecutive tau*B run per worker -> [W, tau, B, ...] -> [tau, W*B, ...]
        w, b, t = self.n_workers, self.local_batch, self.tau
        data = np.stack(imgs).reshape((w, t, b) + imgs[0].shape)
        labels = np.asarray(lbls, np.int32).reshape(w, t, b)
        return {
            "data": np.ascontiguousarray(
                data.transpose((1, 0, 2) + tuple(range(3, data.ndim)))
                .reshape((t, w * b) + imgs[0].shape)),
            "label": labels.transpose(1, 0, 2).reshape(t, w * b, 1),
        }

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer ------------------------------------------------------------

    def next_round(self, round_index: Optional[int] = None
                   ) -> Dict[str, np.ndarray]:
        while True:
            if self._err is not None:
                raise RuntimeError("streaming decode thread failed") \
                    from self._err
            try:
                return self._q.get(timeout=0.1)
            except queue.Empty:
                if self._stop.is_set() and self._err is None:
                    raise RuntimeError("streaming source closed")

    @property
    def skipped(self) -> int:
        return self.loader.skipped

    def close(self) -> None:
        self._stop.set()
        # drain so a blocked producer put() sees the stop promptly
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "StreamingRoundSource":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
