"""Streaming round source: train from tar shards larger than host RAM.

The reference never materialized ImageNet — each Spark task streamed its tar
and trained on what it read (`loaders/ImageNetLoader.scala:59-91`, one
partition per tar). This is that data motion, mesh-native: a background
thread streams + decodes this HOST's shards (via `ShardedTarLoader`, which
already fans decode out over OpenMP) and assembles τ-round batch arrays into
a bounded queue, so round R+1's window is decoded while round R trains on
device. Host RAM holds only `prefetch_rounds + 1` rounds of decoded pixels,
never the corpus.

Semantics vs the in-RAM `RoundSampler`:
  - windows are consecutive stream positions, not random offsets into a
    cached partition — exactly the reference's behavior for its streamed
    (non-cached) datasets; shards cycle forever (epoch boundaries are
    invisible, like the reference's `.repeat()`-style requeue).
  - `round_index` is accepted for API compatibility but does not key the
    sampling: position is a STREAM CURSOR. The source reports the cursor
    after each consumed round (`cursor`/`epochs`, updated by `next_round`),
    the training loop persists it in the checkpoint, and a resumed source
    (`start_cursor=`/`start_epochs=`) seeks — skipping raw tar entries
    without decoding — instead of re-streaming from shard 0 (the reference
    had no resume at all; SURVEY §5.3).
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from .imagenet import ShardedTarLoader


def streaming_sum_count(loader: ShardedTarLoader
                        ) -> Tuple[np.ndarray, int]:
    """One streaming pass over the shards -> (per-pixel float64 sum CHW,
    count). The mean-image reduce (`ImageNetApp.scala:66-69`) without ever
    materializing the corpus; hosts combine (sum, count) pairs for the
    global mean."""
    total: Optional[np.ndarray] = None
    count = 0
    for img, _ in loader:
        if total is None:
            total = np.zeros(img.shape, np.float64)
        total += img
        count += 1
    if count == 0:
        raise ValueError(f"no decodable labeled images in "
                         f"{loader.shard_paths}")
    return total, count


class StreamingRoundSource:
    """Bounded-prefetch producer of τ-round batches from tar shards.

    `next_round()` returns the same layout `RoundSampler.next_round` does —
    {field: [tau, n_workers*local_batch, ...]} with the batch axis blocked by
    worker, each worker's block a consecutive run of tau*local_batch stream
    examples (its "window"). Raw uint8 CHW + int32 labels; per-round
    preprocessing (mean/crop/NHWC) stays in the training loop.

    The producer thread starts lazily on the first `next_round()`, so a
    source can be constructed, then positioned from a checkpoint
    (`start_cursor`/`start_epochs` at construction) before any decode work
    happens. After each `next_round()`, `cursor` is the (shard_index,
    entries_consumed_in_shard) position after that round's last example and
    `epochs` the completed shard-set passes — exactly what a checkpoint
    taken now must record to resume the stream.
    """

    def __init__(self, loader: ShardedTarLoader, n_workers: int,
                 local_batch: int, tau: int, prefetch_rounds: int = 2,
                 start_cursor: Tuple[int, int] = (0, 0),
                 start_epochs: int = 0):
        self.loader = loader
        self.n_workers = n_workers
        self.local_batch = local_batch
        self.tau = tau
        self.round_examples = n_workers * local_batch * tau
        #: position after the last round handed to the consumer
        self.cursor: Tuple[int, int] = tuple(start_cursor)
        #: completed passes over the shard set at that position
        self.epochs = int(start_epochs)
        self._q: queue.Queue = queue.Queue(maxsize=max(1, prefetch_rounds))
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._round_cursors: Dict[int, Tuple[Tuple[int, int], int]] = {}

    def _ensure_started(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._produce, name="stream-decode", daemon=True)
            self._thread.start()

    # -- producer (background thread) ---------------------------------------

    def _produce(self) -> None:
        try:
            w, b, t = self.n_workers, self.local_batch, self.tau
            data = label = None
            count = 0
            cursor = self.cursor
            epochs = self.epochs
            seeked = cursor != (0, 0)
            while not self._stop.is_set():
                n_before = 0
                for img, lbl, pos in self.loader.iter_with_pos(cursor):
                    n_before += 1
                    if data is None:
                        # round layout: [tau, W*B, ...] with the batch axis
                        # blocked by worker, each worker's block a
                        # consecutive tau*b stream run. Write each image
                        # straight into its slot — ONE copy per image
                        # (stack+transpose+contiguous cost 3x the bytes)
                        data = np.empty((t, w * b) + img.shape, img.dtype)
                        label = np.empty((t, w * b, 1), np.int32)
                    wk, rem = divmod(count, t * b)
                    tt, j = divmod(rem, b)
                    data[tt, wk * b + j] = img
                    label[tt, wk * b + j, 0] = lbl
                    count += 1
                    if count == self.round_examples:
                        item = ({"data": data, "label": label}, pos, epochs)
                        if not self._put(item):
                            return
                        data = label = None  # handed off; fresh buffers
                        count = 0
                    if self._stop.is_set():
                        return
                if n_before == 0 and not seeked:
                    # a full from-the-start pass produced nothing: the
                    # shards are empty/corrupt. (A seeked first pass may
                    # legitimately be empty — cursor at the stream's end.)
                    raise ValueError(
                        f"no decodable labeled images in "
                        f"{self.loader.shard_paths}")
                cursor = (0, 0)  # wrap: stream the shards again
                seeked = False
                epochs += 1
        except BaseException as e:  # surface in the consumer
            self._err = e
            self._stop.set()

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer ------------------------------------------------------------

    def seek(self, cursor: Tuple[int, int], epochs: int = 0) -> None:
        """Position the stream from a checkpoint. Only valid before the
        first `next_round()` (the producer starts lazily)."""
        if self._thread is not None:
            raise RuntimeError("seek() after streaming started — construct "
                               "a fresh source or seek before next_round()")
        self.cursor = (int(cursor[0]), int(cursor[1]))
        self.epochs = int(epochs)

    def next_round(self, round_index: Optional[int] = None
                   ) -> Dict[str, np.ndarray]:
        self._ensure_started()
        while True:
            if self._err is not None:
                raise RuntimeError("streaming decode thread failed") \
                    from self._err
            try:
                batches, self.cursor, self.epochs = self._q.get(timeout=0.1)
                if round_index is not None:
                    # cursor keyed by the round it feeds: the training
                    # loop's one-deep prefetch fetches round R+1 while R
                    # trains, so "the source's current cursor" at
                    # checkpoint time is one round AHEAD of the trained
                    # state — checkpoints ask for cursor_at(trained round)
                    self._round_cursors[round_index] = (self.cursor,
                                                        self.epochs)
                    for k in [k for k in self._round_cursors
                              if k < round_index - 4]:
                        del self._round_cursors[k]
                return batches
            except queue.Empty:
                if self._stop.is_set() and self._err is None:
                    raise RuntimeError("streaming source closed")

    def cursor_at(self, round_index: int
                  ) -> Optional[Tuple[Tuple[int, int], int]]:
        """((shard, entry), epochs) after the round that carried this
        index, if still retained — what a checkpoint taken after training
        that round must record."""
        return self._round_cursors.get(round_index)

    @property
    def skipped(self) -> int:
        return self.loader.skipped

    def close(self) -> None:
        self._stop.set()
        # drain so a blocked producer put() sees the stop promptly
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "StreamingRoundSource":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
