"""Streaming round source: train from tar shards larger than host RAM.

The reference never materialized ImageNet — each Spark task streamed its tar
and trained on what it read (`loaders/ImageNetLoader.scala:59-91`, one
partition per tar). This is that data motion, mesh-native: a background
thread streams + decodes this HOST's shards (via `ShardedTarLoader`, which
already fans decode out over OpenMP) and assembles τ-round batch arrays into
a bounded queue, so round R+1's window is decoded while round R trains on
device. Host RAM holds only `prefetch_rounds + 1` rounds of decoded pixels,
never the corpus.

Semantics vs the in-RAM `RoundSampler`:
  - windows are consecutive stream positions, not random offsets into a
    cached partition — exactly the reference's behavior for its streamed
    (non-cached) datasets; shards cycle forever (epoch boundaries are
    invisible, like the reference's `.repeat()`-style requeue).
  - `round_index` is accepted for API compatibility but does not key the
    sampling: position is a STREAM CURSOR. The source reports the cursor
    after each consumed round (`cursor`/`epochs`, updated by `next_round`),
    the training loop persists it in the checkpoint, and a resumed source
    (`start_cursor=`/`start_epochs=`) seeks — skipping raw tar entries
    without decoding — instead of re-streaming from shard 0 (the reference
    had no resume at all; SURVEY §5.3).
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from .imagenet import ShardedTarLoader


def _split_loaders(shard_paths, label_map, n_sources: int, height: int,
                   width: int, cls=ShardedTarLoader) -> list:
    """THE reader fan-out invariant, in one place: N clamped to the shard
    count, reader j takes shards j::N (the same i::k mechanism
    `imagenet.host_shards` uses across hosts). Shared by the parallel
    round source and the parallel mean pass so the split cannot drift."""
    n = max(1, min(int(n_sources), len(shard_paths)))
    return [cls(list(shard_paths[j::n]), label_map,
                height=height, width=width) for j in range(n)]


def streaming_sum_count(loader: ShardedTarLoader, workers: int = 1
                        ) -> Tuple[np.ndarray, int]:
    """One streaming pass over the shards -> (per-pixel float64 sum CHW,
    count). The mean-image reduce (`ImageNetApp.scala:66-69`) without ever
    materializing the corpus; hosts combine (sum, count) pairs for the
    global mean.

    `workers` > 1 fans the pass out over shard subsets j::N in threads
    (decode and pread release the GIL): on real ImageNet this one-time
    pass decodes the host's whole corpus, which at a single reader's rate
    is tens of minutes a 40-core host spends 97% idle. Partial sums are
    float64, and the per-subset partials are reduced in a fixed (subset-
    index) order, so the result is deterministic for a given worker
    count; it equals the serial pass up to float64 summation order (~1
    ulp on uint8-sourced pixels), not bit-for-bit, since grouping
    additions by subset reorders them."""

    def one(sub: ShardedTarLoader) -> Tuple[Optional[np.ndarray], int]:
        total: Optional[np.ndarray] = None
        count = 0
        for img, _ in sub:
            if total is None:
                total = np.zeros(img.shape, np.float64)
            total += img
            count += 1
        return total, count

    subs = _split_loaders(loader.shard_paths, loader.label_map, workers,
                          loader.height, loader.width, cls=type(loader))
    n = len(subs)
    if n == 1:
        total, count = one(loader)
    else:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(n, thread_name_prefix="mean-pass") as pool:
            parts = list(pool.map(one, subs))
        for sub in subs:
            loader.skipped += sub.skipped
            # keep the shared loader's C member-index cache warm: the
            # training stream reuses this loader (ingest_sources=1) and
            # would otherwise re-walk every tar's headers
            loader._tar_indices.update(sub._tar_indices)
        total, count = None, 0
        for t, c in parts:
            if t is not None:
                total = t if total is None else total + t
                count += c
    if count == 0:
        raise ValueError(f"no decodable labeled images in "
                         f"{loader.shard_paths}")
    return total, count


class StreamingRoundSource:
    """Bounded-prefetch producer of τ-round batches from tar shards.

    `next_round()` returns the same layout `RoundSampler.next_round` does —
    {field: [tau, n_workers*local_batch, ...]} with the batch axis blocked by
    worker, each worker's block a consecutive run of tau*local_batch stream
    examples (its "window"). Raw uint8 CHW + int32 labels; per-round
    preprocessing (mean/crop/NHWC) stays in the training loop.

    The producer thread starts lazily on the first `next_round()`, so a
    source can be constructed, then positioned from a checkpoint
    (`start_cursor`/`start_epochs` at construction) before any decode work
    happens. After each `next_round()`, `cursor` is the (shard_index,
    entries_consumed_in_shard) position after that round's last example and
    `epochs` the completed shard-set passes — exactly what a checkpoint
    taken now must record to resume the stream.
    """

    def __init__(self, loader: ShardedTarLoader, n_workers: int,
                 local_batch: int, tau: int, prefetch_rounds: int = 2,
                 start_cursor: Tuple[int, int] = (0, 0),
                 start_epochs: int = 0):
        self.loader = loader
        self.n_workers = n_workers
        self.local_batch = local_batch
        self.tau = tau
        self.round_examples = n_workers * local_batch * tau
        #: position after the last round handed to the consumer
        self.cursor: Tuple[int, int] = tuple(start_cursor)
        #: completed passes over the shard set at that position
        self.epochs = int(start_epochs)
        self._q: queue.Queue = queue.Queue(maxsize=max(1, prefetch_rounds))
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._round_cursors: Dict[int, Tuple[Tuple[int, int], int]] = {}

    def _ensure_started(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._produce, name="stream-decode", daemon=True)
            self._thread.start()

    # -- producer (background thread) ---------------------------------------

    def _produce(self) -> None:
        try:
            w, b, t = self.n_workers, self.local_batch, self.tau
            data = label = None
            count = 0
            cursor = self.cursor
            epochs = self.epochs
            seeked = cursor != (0, 0)
            while not self._stop.is_set():
                n_before = 0
                for img, lbl, pos in self.loader.iter_with_pos(cursor):
                    n_before += 1
                    if data is None:
                        # round layout: [tau, W*B, ...] with the batch axis
                        # blocked by worker, each worker's block a
                        # consecutive tau*b stream run. Write each image
                        # straight into its slot — ONE copy per image
                        # (stack+transpose+contiguous cost 3x the bytes)
                        data = np.empty((t, w * b) + img.shape, img.dtype)
                        label = np.empty((t, w * b, 1), np.int32)
                    wk, rem = divmod(count, t * b)
                    tt, j = divmod(rem, b)
                    data[tt, wk * b + j] = img
                    label[tt, wk * b + j, 0] = lbl
                    count += 1
                    if count == self.round_examples:
                        item = ({"data": data, "label": label}, pos, epochs)
                        if not self._put(item):
                            return
                        data = label = None  # handed off; fresh buffers
                        count = 0
                    if self._stop.is_set():
                        return
                if n_before == 0 and not seeked:
                    # a full from-the-start pass produced nothing: the
                    # shards are empty/corrupt. (A seeked first pass may
                    # legitimately be empty — cursor at the stream's end.)
                    raise ValueError(
                        f"no decodable labeled images in "
                        f"{self.loader.shard_paths}")
                cursor = (0, 0)  # wrap: stream the shards again
                seeked = False
                epochs += 1
        except BaseException as e:  # surface in the consumer
            self._err = e
            self._stop.set()

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer ------------------------------------------------------------

    def seek(self, cursor: Tuple[int, int], epochs: int = 0) -> None:
        """Position the stream from a checkpoint. Only valid before the
        first `next_round()` (the producer starts lazily)."""
        if self._thread is not None:
            raise RuntimeError("seek() after streaming started — construct "
                               "a fresh source or seek before next_round()")
        self.cursor = (int(cursor[0]), int(cursor[1]))
        self.epochs = int(epochs)

    def seek_rows(self, rows) -> bool:
        """Uniform resume protocol shared with ParallelStreamingSource:
        `rows` is [[shard, entry, epochs], ...], one row per reader. A
        single-reader source can only honor a single-reader checkpoint —
        a source-count change reassigned the shards, so old cursors are
        meaningless and the caller restarts the stream (returns False)."""
        if len(rows) != 1:
            return False
        self.seek((rows[0][0], rows[0][1]), rows[0][2])
        return True

    def next_round(self, round_index: Optional[int] = None
                   ) -> Dict[str, np.ndarray]:
        self._ensure_started()
        while True:
            if self._err is not None:
                raise RuntimeError("streaming decode thread failed") \
                    from self._err
            try:
                batches, self.cursor, self.epochs = self._q.get(timeout=0.1)
                if round_index is not None:
                    # cursor keyed by the round it feeds: the training
                    # loop's one-deep prefetch fetches round R+1 while R
                    # trains, so "the source's current cursor" at
                    # checkpoint time is one round AHEAD of the trained
                    # state — checkpoints ask for cursor_at(trained round)
                    self._round_cursors[round_index] = (self.cursor,
                                                        self.epochs)
                    for k in [k for k in self._round_cursors
                              if k < round_index - 4]:
                        del self._round_cursors[k]
                return batches
            except queue.Empty:
                if self._stop.is_set() and self._err is None:
                    raise RuntimeError("streaming source closed")

    def cursor_at(self, round_index: int
                  ) -> Optional[Tuple[Tuple[int, int], int]]:
        """((shard, entry), epochs) after the round that carried this
        index, if still retained — what a checkpoint taken after training
        that round must record."""
        return self._round_cursors.get(round_index)

    @property
    def skipped(self) -> int:
        return self.loader.skipped

    def close(self) -> None:
        self._stop.set()
        # drain so a blocked producer put() sees the stop promptly
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "StreamingRoundSource":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _RingSlot:
    """One in-flight round buffer: producers write disjoint blocks, the
    consumer takes it when all producers have finished theirs."""

    __slots__ = ("round", "done", "ready", "data", "label", "cursors")

    def __init__(self, round_index: int, n_sources: int):
        self.round = round_index
        self.done = 0
        self.ready = False
        self.data = None
        self.label = None
        self.cursors = [None] * n_sources


class ParallelStreamingSource:
    """N concurrent shard readers feeding one round stream — the per-source
    throughput ceiling killer (r3 review item 1).

    One `StreamingRoundSource` runs a single producer thread: decode fans
    out over OpenMP, but the tar read + round-buffer write residue is
    serial, capping any single source at ~1/residue img/s no matter how
    many cores the host has (PERF.md input-pipeline scaling model). The
    reference had no such ceiling — it ran one Spark task per tar chunk
    (`loaders/ImageNetLoader.scala:28-41`), so the whole corpus decoded in
    parallel across every executor core. This class is that corpus-wide
    parallelism per host: reader j streams loaders[j] (the host's shards
    j::N via `imagenet.host_shards`-style splitting) and writes its block
    of each round DIRECTLY into a shared ring of round buffers — no
    assembly copy, no global serial stage; the per-round serial work on
    any one thread divides by N.

    Round layout is identical to `StreamingRoundSource.next_round`:
    {field: [tau, n_workers*local_batch, ...]}, batch axis blocked by
    worker. The round's linear example index c maps to slot
    (c//(tau*b), c%(tau*b)); reader j owns c in [j*block, (j+1)*block)
    with block = round_examples/N — contiguous stream runs per reader, and
    when N == n_workers each worker's window is exactly one reader's
    stream (the reference's partition-per-worker shape).

    Resume: each reader has an independent (shard, entry) cursor + epoch
    counter over ITS shard subset; `cursor_at(round)` returns all N
    (cursor, epochs) pairs and `seek_rows` repositions all N — the
    checkpoint carries one row per reader per host. A checkpoint taken
    with a different reader count cannot be honored (the shard assignment
    itself changed): seek_rows returns False and the caller restarts the
    stream, same policy as a host-count change.
    """

    def __init__(self, loaders, n_workers: int, local_batch: int, tau: int,
                 prefetch_rounds: int = 2):
        if not loaders:
            raise ValueError("need at least one loader")
        for i, ld in enumerate(loaders):
            if not ld.shard_paths:
                raise ValueError(
                    f"reader {i} of {len(loaders)} has no shards — use "
                    f"fewer sources than shards (shards split j::N)")
        self.loaders = list(loaders)
        self.n_sources = len(loaders)
        self.n_workers = n_workers
        self.local_batch = local_batch
        self.tau = tau
        self.round_examples = n_workers * local_batch * tau
        if self.round_examples % self.n_sources:
            raise ValueError(
                f"round examples {self.round_examples} "
                f"(= {n_workers} workers x {local_batch} batch x {tau} tau) "
                f"not divisible by {self.n_sources} sources")
        self.block = self.round_examples // self.n_sources
        self._K = max(2, prefetch_rounds + 1)
        self._ring = [_RingSlot(i, self.n_sources) for i in range(self._K)]
        self._next_out = 0  # next round index the consumer takes
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._err: Optional[BaseException] = None
        self._threads: Optional[list] = None
        self._start = [((0, 0), 0)] * self.n_sources
        #: per-reader cursors after the last consumed round
        self.cursors = list(self._start)
        self._round_cursors: Dict[int, list] = {}
        #: per-reader {'busy_cpu_s','wait_s','images'}; see source_stats()
        self.stats = [{"busy_cpu_s": 0.0, "wait_s": 0.0, "images": 0}
                      for _ in range(self.n_sources)]

    def source_stats(self) -> list:
        """Per-reader stage accounting: busy_cpu_s (the reader thread's CPU
        time outside ring waits), decode_cpu_s (its CPU share of decode —
        the OpenMP-parallel stage), serial_s = busy_cpu - decode_cpu (tar
        read + buffer write + glue — the per-reader SERIAL residue whose
        division by N is this class's whole point), wait_s (ring
        backpressure, wall), images. CPU clocks, not wall: a thread
        descheduled behind the GIL or a busy core accrues none, so the
        accounting holds on any core count (a wall clock on a contended
        host charges every reader for its neighbors' work)."""
        out = []
        for j, st in enumerate(self.stats):
            d = dict(st)
            d["decode_cpu_s"] = self.loaders[j].decode_cpu_s
            d["serial_s"] = max(0.0, d["busy_cpu_s"] - d["decode_cpu_s"])
            out.append(d)
        return out

    # -- producers (one thread per reader) -----------------------------------

    def _ensure_started(self) -> None:
        if self._threads is None:
            self._threads = [
                threading.Thread(target=self._produce, args=(j,),
                                 name=f"stream-decode-{j}", daemon=True)
                for j in range(self.n_sources)]
            for t in self._threads:
                t.start()

    def _produce(self, j: int) -> None:
        import time
        try:
            b, t = self.local_batch, self.tau
            st = self.stats[j]
            cursor, epochs = self._start[j]
            seeked = cursor != (0, 0)
            e = 0  # examples this reader produced (monotonic)
            slot = None
            while not self._stop.is_set():
                n_before = 0
                t0 = time.thread_time()  # CPU clock: see source_stats()
                for img, lbl, pos in self.loaders[j].iter_with_pos(cursor):
                    st["busy_cpu_s"] += time.thread_time() - t0
                    n_before += 1
                    r, within = divmod(e, self.block)
                    if within == 0:
                        tw = time.perf_counter()
                        slot = self._acquire(r, img.shape, img.dtype)
                        st["wait_s"] += time.perf_counter() - tw
                        if slot is None:
                            return  # stopped while waiting
                    t0 = time.thread_time()
                    c = j * self.block + within
                    wk, rem = divmod(c, t * b)
                    tt, jj = divmod(rem, b)
                    slot.data[tt, wk * b + jj] = img
                    slot.label[tt, wk * b + jj, 0] = lbl
                    e += 1
                    st["images"] += 1
                    if within == self.block - 1:
                        self._finish(slot, j, (pos, epochs))
                        slot = None
                    if self._stop.is_set():
                        return
                st["busy_cpu_s"] += time.thread_time() - t0
                if n_before == 0 and not seeked:
                    raise ValueError(
                        f"no decodable labeled images in reader {j}'s "
                        f"shards {self.loaders[j].shard_paths}")
                cursor = (0, 0)  # wrap this reader's shard subset
                seeked = False
                epochs += 1
        except BaseException as exc:  # surface in the consumer
            with self._cond:
                self._err = exc
                self._stop.set()
                self._cond.notify_all()

    def _acquire(self, r: int, shape, dtype) -> Optional[_RingSlot]:
        """Block until ring slot r%K is writable for round r; allocate its
        buffers on first touch. Returns None if the source is stopping."""
        slot = self._ring[r % self._K]
        with self._cond:
            while not self._stop.is_set() and slot.round != r:
                self._cond.wait(0.1)
            if self._stop.is_set():
                return None
            if slot.data is None:
                w, b, t = self.n_workers, self.local_batch, self.tau
                slot.data = np.empty((t, w * b) + tuple(shape), dtype)
                slot.label = np.empty((t, w * b, 1), np.int32)
        return slot

    def _finish(self, slot: _RingSlot, j: int, cursor) -> None:
        with self._cond:
            slot.cursors[j] = cursor
            slot.done += 1
            if slot.done == self.n_sources:
                slot.ready = True
                self._cond.notify_all()

    # -- consumer ------------------------------------------------------------

    def seek_rows(self, rows) -> bool:
        """Reposition all N readers from checkpoint rows
        [[shard, entry, epochs], ...]. Only before the first next_round().
        False when the row count doesn't match this reader count (shard
        assignment changed — caller restarts the stream from zero)."""
        if self._threads is not None:
            raise RuntimeError("seek_rows() after streaming started")
        if len(rows) != self.n_sources:
            return False
        self._start = [((int(r[0]), int(r[1])), int(r[2])) for r in rows]
        self.cursors = list(self._start)
        return True

    def next_round(self, round_index: Optional[int] = None
                   ) -> Dict[str, np.ndarray]:
        self._ensure_started()
        with self._cond:
            slot = self._ring[self._next_out % self._K]
            while True:
                if self._err is not None:
                    raise RuntimeError(
                        "streaming decode thread failed") from self._err
                if slot.round == self._next_out and slot.ready:
                    break
                if self._stop.is_set():
                    raise RuntimeError("streaming source closed")
                self._cond.wait(0.1)
            batches = {"data": slot.data, "label": slot.label}
            self.cursors = list(slot.cursors)
            # recycle the slot for round (current + K)
            slot.round += self._K
            slot.ready = False
            slot.done = 0
            slot.data = slot.label = None
            slot.cursors = [None] * self.n_sources
            self._next_out += 1
            self._cond.notify_all()
        if round_index is not None:
            # same one-round-behind protocol as StreamingRoundSource:
            # checkpoints ask for cursor_at(trained round)
            self._round_cursors[round_index] = list(self.cursors)
            for k in [k for k in self._round_cursors
                      if k < round_index - 4]:
                del self._round_cursors[k]
        return batches

    def cursor_at(self, round_index: int) -> Optional[list]:
        """[((shard, entry), epochs), ...] per reader after the round that
        carried this index, if still retained."""
        return self._round_cursors.get(round_index)

    @property
    def skipped(self) -> int:
        return sum(ld.skipped for ld in self.loaders)

    def close(self) -> None:
        with self._cond:
            self._stop.set()
            self._cond.notify_all()
        if self._threads is not None:
            for t in self._threads:
                t.join(timeout=5.0)

    def __enter__(self) -> "ParallelStreamingSource":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def make_parallel_source(shard_paths, label_map, n_workers: int,
                         local_batch: int, tau: int, n_sources: int,
                         height: int = 256, width: int = 256,
                         prefetch_rounds: int = 2) -> ParallelStreamingSource:
    """Split a host's shards j::N across N readers (the same i::k mechanism
    `imagenet.host_shards` uses across hosts) and build the parallel
    source. N is clamped to the shard count — more readers than shards
    would leave empty readers."""
    loaders = _split_loaders(shard_paths, label_map, n_sources,
                             height, width)
    return ParallelStreamingSource(loaders, n_workers, local_batch, tau,
                                   prefetch_rounds=prefetch_rounds)
