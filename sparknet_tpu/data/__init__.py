from .cifar import CifarLoader  # noqa: F401
from .mnist import MnistLoader  # noqa: F401
from .adult import AdultLoader  # noqa: F401
from .imagenet import ShardedTarLoader, load_label_map, list_shards  # noqa: F401
from .dataset import ArrayDataset, RoundSampler  # noqa: F401
from .preprocess import (DefaultPreprocessor, ImagePreprocessor,  # noqa: F401
                         compute_mean_image, to_nhwc)
