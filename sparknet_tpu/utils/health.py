"""Training health supervision: anomaly classification + recovery policy.

The reference's loop was `while(true)` with `task.maxFailures=1` (SURVEY
§5.3): a diverging or numerically-poisoned run had no answer — a NaN loss
sailed through the round, silently corrupted every replica via the
τ-averaging pmean (one bad worker poisons all after one sync), and was
checkpointed over the last good state until retention had deleted every
clean snapshot. Large-scale practice (PaLM's restart-and-skip response to
loss spikes; the local-SGD robustness line descending from the SparkNet
τ-averaging scheme) treats anomaly detection + rollback as a first-class
subsystem. This module is the host-side half:

  - `HealthConfig`   — the knobs (rolling window, MAD threshold, rollback
                       budget, LR backoff, deterministic fault injection).
  - `HealthMonitor`  — rolling ROBUST loss statistics (median + MAD over a
                       window of healthy rounds only), classifying each
                       round as ok / spike / nonfinite and deciding
                       skip-and-continue vs rollback.
  - `TrainingHealthError` — the loud hard-fail after `max_rollbacks`.

The device-side half lives in the trainers: `_round_impl` additionally
returns a global gradient norm and an any-nonfinite count, psum'd over the
data axis INSIDE the already-compiled round — so the signals cost no extra
host round-trip and stay on device until the loop's normal `log_every`
flush fetches them alongside the deferred losses.
"""
from __future__ import annotations

import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

OK = "ok"
SPIKE = "spike"
NONFINITE = "nonfinite"


class TrainingHealthError(RuntimeError):
    """Unrecoverable training-health failure (rollback budget exhausted, or
    recovery impossible — no verified checkpoint to roll back to)."""


@dataclass
class HealthConfig:
    """Knobs for the training health supervisor (RunConfig.health).

    Classification: a round is `nonfinite` when the on-device flag tripped
    (NaN/Inf in the loss, gradients, or post-round params anywhere on the
    mesh) and `spike` when its loss exceeds the rolling median by
    `spike_mad` robust sigmas (MAD * 1.4826) over a window of the last
    `window` HEALTHY rounds (spikes/nonfinites never enter the window, so
    one outlier cannot inflate the scale estimate and mask the next).

    Recovery (driven by the train loop): an isolated spike is skipped —
    logged, excluded from the statistics, training continues. `nonfinite`,
    or `spike_patience` consecutive spikes, triggers a rollback to the
    newest VERIFIED non-anomalous checkpoint with the learning rate scaled
    by `lr_backoff` and the retried rounds' data order advanced (round-keyed
    rngs make the retried window deterministic-but-different). After
    `max_rollbacks` rollbacks the run hard-fails loudly.
    """

    enabled: bool = True
    # rolling robust statistics
    window: int = 32            # healthy-loss window for median/MAD
    min_history: int = 8        # rounds of history before spikes classify
    spike_mad: float = 10.0     # spike threshold, in robust sigmas
    # recovery policy
    spike_patience: int = 3     # consecutive spikes that force a rollback
    max_rollbacks: int = 3      # hard-fail budget
    lr_backoff: float = 0.5     # lr multiplier applied per rollback (1.0 =
    #                             off; only trainers with supports_lr_scale)
    # deterministic fault injection (chaos tests): on the FIRST pass over
    # these rounds (rounds above the loop's high-water mark of executed
    # rounds) the prepared batch is poisoned — float inputs forced to NaN
    # (inject_nan_rounds) or scaled by inject_spike_scale
    # (inject_spike_rounds). Retried passes after a rollback are clean
    # while LATER configured rounds still fire, so the detect -> rollback
    # -> recover path is exercised without flakiness. Inert when
    # `enabled` is False.
    inject_nan_rounds: Tuple[int, ...] = ()
    inject_spike_rounds: Tuple[int, ...] = ()
    inject_spike_scale: float = 1e3

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "HealthConfig":
        import dataclasses
        known = {f.name for f in dataclasses.fields(HealthConfig)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown health config keys: {sorted(unknown)}")
        kw = dict(d)
        for k in ("inject_nan_rounds", "inject_spike_rounds"):
            if k in kw:
                kw[k] = tuple(kw[k])
        return HealthConfig(**kw)


def _is_finite(x: Optional[float]) -> bool:
    return x is None or math.isfinite(x)


class HealthMonitor:
    """Classifies flushed round metrics and drives the recovery decision.

    Purely host-side and deterministic: feed it the (round, loss,
    grad_norm, nonfinite_count) tuples in round order via `observe`; it
    returns the classification and latches `rollback_needed` when the
    policy demands one (consumed by the loop via `consume_rollback`).
    Multi-host safe by construction: the inputs are mesh-reduced scalars
    (identical on every process), so every process reaches the same
    decision without extra communication.
    """

    def __init__(self, cfg: HealthConfig, registry=None):
        self.cfg = cfg
        self._window: deque = deque(maxlen=max(2, cfg.window))
        self._consecutive_spikes = 0
        self._rollback_needed: Optional[str] = None  # reason, when latched
        self.last_anomaly_round: Optional[int] = None
        self.rollbacks = 0
        self.counts = {OK: 0, SPIKE: 0, NONFINITE: 0}
        # shared-schema telemetry (obs.MetricsRegistry): classification
        # counts and the rollback budget as scrapeable counters/gauges
        self._c_rounds = self._c_rollbacks = self._g_gnorm = None
        if registry is not None:
            self._c_rounds = registry.counter(
                "sparknet_health_rounds_total",
                "rounds by health classification", labels=("cls",))
            self._c_rollbacks = registry.counter(
                "sparknet_health_rollbacks_total",
                "recoveries consumed from the rollback budget")
            self._g_gnorm = registry.gauge(
                "sparknet_health_grad_norm",
                "last flushed global gradient norm")

    # -- rolling robust statistics -------------------------------------------

    def stats(self) -> Tuple[Optional[float], Optional[float]]:
        """(median, robust sigma = MAD * 1.4826) of the healthy window, or
        (None, None) with insufficient history."""
        n = len(self._window)
        if n < max(2, self.cfg.min_history):
            return None, None
        xs = sorted(self._window)
        med = _median(xs)
        mad = _median(sorted(abs(x - med) for x in xs))
        return med, 1.4826 * mad

    # -- classification + policy ---------------------------------------------

    def observe(self, rnd: int, loss: float,
                grad_norm: Optional[float] = None,
                nonfinite_count: float = 0.0) -> str:
        """Classify round `rnd` and update policy state. Returns
        'ok' | 'spike' | 'nonfinite'."""
        cls = OK
        if (nonfinite_count and nonfinite_count > 0) or not _is_finite(loss):
            cls = NONFINITE
        elif not _is_finite(grad_norm):
            # loss/params finite but the grad-norm scalar is not: either a
            # f32 overflow in the squared-norm accumulation (violent-but-
            # finite divergence) or a transient Inf gradient the update
            # absorbed. Not numerically poisoned state — classify as a
            # spike so the skip/patience policy applies, not as nonfinite
            # (the device flag over losses+params is the authority there).
            cls = SPIKE
        else:
            med, sigma = self.stats()
            # sigma floor at 1e-3 of the loss scale: a plateaued window
            # (many bit-identical losses -> MAD = 0) must not turn every
            # ordinary fluctuation above the median into a spike
            if med is not None and loss > med + self.cfg.spike_mad * max(
                    sigma, 1e-3 * max(abs(med), 1.0)):
                cls = SPIKE
        self.counts[cls] += 1
        if self._c_rounds is not None:
            self._c_rounds.inc(cls=cls)
            if grad_norm is not None and _is_finite(grad_norm):
                self._g_gnorm.set(grad_norm)
        if cls == OK:
            self._window.append(float(loss))
            self._consecutive_spikes = 0
        else:
            self.last_anomaly_round = rnd
            if cls == NONFINITE:
                self._rollback_needed = NONFINITE
            else:
                self._consecutive_spikes += 1
                if self._consecutive_spikes >= max(1, self.cfg.spike_patience):
                    self._rollback_needed = "repeated spikes"
        return cls

    @property
    def rollback_needed(self) -> Optional[str]:
        """Reason string when the policy wants a rollback, else None."""
        return self._rollback_needed

    def consume_rollback(self) -> str:
        """Acknowledge the latched rollback (the loop is about to perform
        it): counts it against the budget, resets the spike streak, and
        raises TrainingHealthError once the budget is exhausted."""
        reason = self._rollback_needed or "unknown"
        self._rollback_needed = None
        self._consecutive_spikes = 0
        # the restored state predates the anomaly: don't tag post-recovery
        # checkpoints anomalous for an incident that was rolled away
        self.last_anomaly_round = None
        self.rollbacks += 1
        if self._c_rollbacks is not None:
            self._c_rollbacks.inc()
        if self.rollbacks > max(0, self.cfg.max_rollbacks):
            raise TrainingHealthError(
                f"training health: rollback budget exhausted "
                f"({self.cfg.max_rollbacks} rollbacks) — last trigger: "
                f"{reason}; anomalies: {self.counts[SPIKE]} spikes, "
                f"{self.counts[NONFINITE]} nonfinite rounds. The run is "
                f"not recovering; inspect the data/lr before relaunching.")
        return reason

    def recently_anomalous(self, rnd: int) -> bool:
        """True when an anomaly was classified within the last `window`
        rounds — checkpoints taken here are tagged `anomalous` so rollback
        skips them (the state may embed the spike)."""
        return (self.last_anomaly_round is not None
                and rnd - self.last_anomaly_round < max(1, self.cfg.window))


def _median(xs) -> float:
    n = len(xs)
    m = n // 2
    return float(xs[m]) if n % 2 else 0.5 * (xs[m - 1] + xs[m])


def mad_classify(values, thresh_sigma: float = 5.0,
                 rel_floor: float = 0.25):
    """Median+MAD outlier flags over one cross-sectional sample — the same
    robust-sigma rule `HealthMonitor.observe` applies to its rolling loss
    window, packaged for the pod aggregator's per-worker round times and
    the summary tool's per-round skew audit.

    Returns (median, robust_sigma, [flag per value]): value i is flagged
    when it exceeds median + thresh_sigma * sigma, with sigma =
    MAD * 1.4826 floored at rel_floor * |median| — a degenerate MAD
    (identical values, the healthy-pod common case) must not turn
    measurement noise into straggler flags, and a zero median must not
    zero the floor (the max(|med|, tiny) guard). Fewer than 3 values
    returns all-False: with n == 2 both deviations EQUAL the MAD, so the
    rule mathematically cannot fire — callers wanting a 2-sample verdict
    need a ratio rule (see obs/pod.py) instead of a fake sigma.
    """
    xs = [float(v) for v in values]
    if len(xs) < 3:
        med = _median(sorted(xs)) if xs else 0.0
        return med, 0.0, [False] * len(xs)
    s = sorted(xs)
    med = _median(s)
    mad = _median(sorted(abs(x - med) for x in s))
    sigma = max(1.4826 * mad, rel_floor * max(abs(med), 1e-12))
    return med, sigma, [x > med + thresh_sigma * sigma for x in xs]


def liveness_classify(hb: Optional[Dict[str, Any]],
                      stale_after_s: float) -> str:
    """THE dead-vs-slow rule, shared by straggler naming (obs/pod.py), the
    elastic MembershipController, and anything probing a heartbeat dict
    (utils/heartbeat.read_heartbeat output — `age_s` is stamped at read
    time). One threshold, one vocabulary:

      "missing"  no readable heartbeat at all (file/object gone, torn,
                 or carrying no timestamp) — a candidate-dead worker
      "done"     the worker said goodbye (status "done"): a graceful
                 leave, not a failure
      "stale"    a beat exists but is older than `stale_after_s` — the
                 writer stopped writing: candidate-dead, subject to the
                 controller's re-probe policy (never evict on one look)
      "sick"     fresh beat, anomalous status (spike/nonfinite/rollback/
                 degraded): alive but unhealthy — a health-supervisor
                 problem, NOT a membership problem
      "ok"       fresh beat, healthy status — mere slowness shows up in
                 round_s/straggler attribution, never here

    A slow worker is "ok" here by construction: slowness is the straggler
    attributor's verdict (median+MAD over round_s), deadness is this
    one's, and conflating them is how pods evict their stragglers."""
    if hb is None:
        return "missing"
    status = str(hb.get("status", "ok"))
    if status == "done":
        return "done"
    age = hb.get("age_s")
    if age is None:
        try:
            age = max(0.0, time.time() - float(hb["t"]))
        except (KeyError, TypeError, ValueError):
            return "missing"
    if float(age) > float(stale_after_s):
        return "stale"
    if status in (SPIKE, NONFINITE, "rollback", "degraded"):
        return "sick"
    return "ok"


def poison_batch(batches: Dict[str, Any], mode: str,
                 scale: float = 1e3) -> Dict[str, Any]:
    """Deterministically poison one round's prepared batch (fault-injection
    hook): float arrays get NaN ('nan') or a *scale blowup ('spike');
    integer arrays (labels) are left intact. Returns a new dict — the
    original arrays are not mutated."""
    import numpy as np

    out = {}
    for k, v in batches.items():
        a = np.asarray(v)
        if np.issubdtype(a.dtype, np.floating):
            out[k] = (np.full_like(a, np.nan) if mode == "nan"
                      else a * a.dtype.type(scale))
        else:
            out[k] = v
    return out
