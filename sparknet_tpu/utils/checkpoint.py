"""Checkpoint / resume: params + optimizer state + loop counter.

The reference had save/load of net weights only, never wired into training
(`libs/CaffeNet.scala:152-165`; SURVEY §5.4 flags this as a genuine gap).
Here checkpoints are first-class: the FULL TrainState (per-device params AND
worker-local momentum AND iteration counter) plus the round index round-trips
exactly, so a resumed run continues bit-identically.

Format: a directory with
  - state.npz   — flattened pytree leaves, keys are /-joined paths
  - meta.json   — {"round": N, "tree": <pytree structure descriptor>}
Atomic via write-to-temp + rename. `latest`/`step-N` naming with retention.
"""
from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, Optional, Tuple

import jax
import ml_dtypes
import numpy as np

# np.savez silently degrades extension dtypes (bfloat16 & friends from
# ml_dtypes) to void ('V2') — the restored leaf is unusable. Such leaves are
# stored as same-width uint views with the real dtype name recorded in
# meta.json, and re-viewed on restore.
_UINT_OF_SIZE = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def _is_extension_dtype(dt: np.dtype) -> bool:
    # bfloat16/float8_e4m3fn report kind 'V', but float8_e5m2 reports kind
    # 'f' (and still breaks savez) — match on the registering module too,
    # excluding structured dtypes (which have .names)
    return dt.names is None and (
        dt.kind == "V" or dt.type.__module__ == "ml_dtypes")


def _flatten(tree: Any) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(_path_str(p) for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _path_str(p) -> str:
    if hasattr(p, "key"):
        return str(p.key)
    if hasattr(p, "idx"):
        return str(p.idx)
    if hasattr(p, "name"):
        return str(p.name)
    return str(p)


def save(directory: str, tree: Any, *, step: int,
         extra: Optional[Dict[str, Any]] = None) -> str:
    """Atomically write checkpoint `step-N` under directory; returns path."""
    os.makedirs(directory, exist_ok=True)
    flat = _flatten(tree)
    ext_dtypes = {}
    for key, arr in flat.items():
        if _is_extension_dtype(arr.dtype):
            ext_dtypes[key] = arr.dtype.name
            flat[key] = arr.view(_UINT_OF_SIZE[arr.dtype.itemsize])
    tmp = tempfile.mkdtemp(dir=directory, prefix=".tmp-")
    try:
        np.savez(os.path.join(tmp, "state.npz"), **flat)
        meta = {"step": int(step), "keys": sorted(flat.keys())}
        if ext_dtypes:
            meta["ext_dtypes"] = ext_dtypes
        if extra:
            meta["extra"] = extra
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        final = os.path.join(directory, f"step-{int(step)}")
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    steps = [int(d.split("-", 1)[1]) for d in os.listdir(directory)
             if d.startswith("step-") and d.split("-", 1)[1].isdigit()]
    return max(steps) if steps else None


def unflatten_like(template: Any, flat: Dict[str, np.ndarray]) -> Any:
    """Rebuild `template`'s structure from a flat {path-key: array} map.
    Shape mismatches fail loudly with the leaf path."""
    leaves_t, _ = jax.tree_util.tree_flatten_with_path(template)
    new_leaves = []
    for pth, leaf in leaves_t:
        key = "/".join(_path_str(p) for p in pth)
        if key not in flat:
            raise KeyError(f"checkpoint missing leaf {key!r}")
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"leaf {key!r}: checkpoint shape {arr.shape} != template "
                f"{np.shape(leaf)} (device-count change? re-tile first)")
        new_leaves.append(arr)
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template), new_leaves)


def restore(directory: str, template: Any, *, step: Optional[int] = None
            ) -> Tuple[Any, int, Dict[str, Any]]:
    """Restore into the structure of `template` (a pytree with correctly-
    shaped leaves, e.g. a freshly-built TrainState). Returns
    (tree, step, extra). Shape mismatches fail loudly with the leaf path."""
    flat, step, extra = restore_flat(directory, step)
    return unflatten_like(template, flat), step, extra


def restore_flat(directory: str, step: Optional[int] = None
                 ) -> Tuple[Dict[str, np.ndarray], int, Dict[str, Any]]:
    """Restore the raw flat {path-key: array} mapping without a template —
    for ELASTIC resume, where the saved leading device axis differs from
    the current topology and a structural template cannot match
    (ParallelTrainer.adapt_state re-tiles from this)."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory!r}")
    path = os.path.join(directory, f"step-{int(step)}")
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    with np.load(os.path.join(path, "state.npz")) as z:
        flat = {k: z[k] for k in z.files}
    for key, name in meta.get("ext_dtypes", {}).items():
        flat[key] = flat[key].view(np.dtype(name))
    return flat, int(meta["step"]), meta.get("extra", {})


def retain(directory: str, keep: int = 3) -> None:
    """Delete all but the newest `keep` checkpoints."""
    if not os.path.isdir(directory):
        return
    steps = sorted((int(d.split("-", 1)[1]) for d in os.listdir(directory)
                    if d.startswith("step-") and d.split("-", 1)[1].isdigit()))
    for s in steps[:-keep] if keep else steps:
        shutil.rmtree(os.path.join(directory, f"step-{s}"), ignore_errors=True)
